"""Grid-search the flagship BSCgs1 config on the D4IC analog — with the
framework's own grid engine.

Round-4 found the transcribed non-Smooth BSCgs1 config worst-in-roster on the
D4IC analog (optF1 0.178 vs the reference's 0.30-0.34 notebook band). The
transcription is ONE point of what the reference actually ran: a grid-search
across its gs-script series, selected by the eval_gs flow
(/root/reference/train/REDCLIFF_S_CMLP_d4IC_BSCgs1.py:66-108 is one driver of
the series; the Smooth gs4 sibling differs in ADJ_L1 1.0->0.1 etc., and the
eval_gs_* scripts rank the runs). This experiment runs that selection HERE,
with the axes the reference's own configs span:

1. curate the D4IC-analog HSNR fold 0 (same generator as
   accuracy_parity_d4ic.py);
2. train a gen_lr x ADJ_L1_REG_COEFF x FACTOR_COS_SIM_COEFF grid of the
   BSCgs1 architecture — ALL points at once through RedcliffGridRunner, each
   point carrying its own rescaled coefficients and mirrored stopping
   coefficients exactly as the per-job driver would set them (ref :98-105);
3. score EVERY point's best model with the off-diag optimal-F1 battery
   (selection-vs-science curve in the artifact);
4. select by the reference's criterion (min stopping criteria, the per-run
   quantity eval_gs ranks) and re-train the winner config through the REAL
   array-task driver at all three SNR tiers x 3 folds — the exact setup of
   the round-4 ACCURACY_D4IC tables — so the winner's row is directly
   comparable.

Writes experiments/D4IC_GRID_SEARCH.json (--arch bscgs1, default) or
experiments/D4IC_GRID_SEARCH_SMOOTH.json (--arch smooth — the same
coefficient axes on the Smooth gs4 architecture; shapes cannot share one
vmapped program, so each architecture runs as its own grid, the
group_configs_by_shape contract).

Run:  python experiments/d4ic_grid_search.py <workdir> [--smoke]
      [--max-iter N] [--folds N] [--arch bscgs1|smooth]
"""
import argparse
import json
import os
import pickle
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from accuracy_parity_d4ic import (  # noqa: E402
    NUM_NETWORKS, NUM_NODES, REDCLIFF_ARGS, SMOOTH_ARGS, curate_network)
from redcliff_tpu.data.curation import (  # noqa: E402
    save_cached_args_file_for_data)
from redcliff_tpu.data.dream4 import make_d4ic_fold  # noqa: E402
from redcliff_tpu.eval.cross_alg import evaluate_algorithm_on_fold  # noqa: E402
from redcliff_tpu.train.driver import (  # noqa: E402
    rescale_dataset_dependent_coefficients, run_coefficient_grid,
    set_up_and_run_experiments)
from redcliff_tpu.train.orchestration import (  # noqa: E402
    create_model_instance, get_data_for_model_training)
from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig  # noqa: E402
from redcliff_tpu.utils.config import (  # noqa: E402
    load_true_gc_factors, read_in_data_args, read_in_model_args)

# the axes the reference's own d4IC gs points span: BSCgs1 sits at
# (5e-4, 1.0, 1.0); the Smooth gs4 sibling moved ADJ_L1 to 0.1; lr and
# cos-sim bracket the published settings one decade each way
GEN_LR_AXIS = (0.0002, 0.0005, 0.002)
ADJ_L1_AXIS = (1.0, 0.1, 0.01)
COS_SIM_AXIS = (10.0, 1.0, 0.1)
OFFDIAG = "key_stats_estGC_normOffDiag_vs_trueGC_normOffDiag"
TIERS = ("HSNR", "MSNR", "LSNR")


def curate_tier_fold(base, snr, fold, n_train, n_val):
    """D4IC-analog mixture fold for one SNR tier (accuracy_parity_d4ic's
    curation flow, shared network pool)."""
    nets_root = os.path.join(base, "networks")
    graphs = [curate_network(nets_root, n, fold, n_train, n_val)
              for n in range(NUM_NETWORKS)]
    fold_dir = os.path.join(base, "data", f"d4ic_{snr}", f"fold_{fold}")
    if not os.path.isfile(os.path.join(
            fold_dir, f"data_fold{fold}_cached_args.txt")):
        make_d4ic_fold(nets_root, fold_dir, fold_id=fold,
                       num_factors=NUM_NETWORKS, snr_tier=snr,
                       shuffle_rng=np.random.default_rng(fold))
        save_cached_args_file_for_data(
            fold_dir, NUM_NODES, graphs, f"data_fold{fold}_cached_args.txt")
    return os.path.join(fold_dir, f"data_fold{fold}_cached_args.txt")


def pooled_offdiag(stats_by_fold):
    """Mean +/- SEM over per-factor optF1 values pooled across folds (the
    ACCURACY_D4IC tables' across-factors-then-folds statistic)."""
    vals = []
    aucs = []
    for stats in stats_by_fold:
        s = stats[OFFDIAG]
        vals.extend(s["f1_vals_across_factors"])
        aucs.extend(s.get("roc_auc_vals_across_factors", []))
    vals = np.asarray(vals, dtype=np.float64)
    out = {"offdiag_optimal_f1_mean": float(vals.mean()),
           "offdiag_optimal_f1_sem": float(vals.std(ddof=1)
                                           / np.sqrt(len(vals)))
           if len(vals) > 1 else 0.0}
    if aucs:
        aucs = np.asarray(aucs, dtype=np.float64)
        out["offdiag_roc_auc_mean"] = float(aucs.mean())
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("workdir")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--max-iter", type=int, default=None,
                    help="cap the selection grid's epochs (default: the "
                         "reference max_iter=1000; the all-inactive early "
                         "exit usually stops far earlier)")
    ap.add_argument("--folds", type=int, default=3)
    ap.add_argument("--arch", default="bscgs1", choices=["bscgs1", "smooth"],
                    help="architecture to search: the non-Smooth BSCgs1 "
                         "shape or the Smooth gs4 shape (the reference's "
                         "gs1 -> gs4 progression searched across BOTH; "
                         "different shapes cannot share one vmapped program, "
                         "so each runs as its own grid — the "
                         "group_configs_by_shape contract)")
    args = ap.parse_args()
    # curation is fully seeded and architecture-independent: share the
    # workdir across archs and isolate only the run roots / args files
    base = os.path.abspath(args.workdir) + ("_smoke" if args.smoke else "")
    os.makedirs(base, exist_ok=True)
    arch_tag = "_smooth" if args.arch == "smooth" else ""
    n_train, n_val = (24, 8) if args.smoke else (120, 30)

    model_type = ("REDCLIFF_S_CMLP_Smooth" if args.arch == "smooth"
                  else "REDCLIFF_S_CMLP")
    margs = dict(SMOOTH_ARGS if args.arch == "smooth" else REDCLIFF_ARGS)
    if args.smoke:
        margs.update(max_iter="12", num_pretrain_epochs="4",
                     num_acclimation_epochs="4", check_every="2")

    gen_axis = GEN_LR_AXIS if not args.smoke else GEN_LR_AXIS[:2]
    adj_axis = ADJ_L1_AXIS if not args.smoke else ADJ_L1_AXIS[:2]
    cos_axis = COS_SIM_AXIS if not args.smoke else COS_SIM_AXIS[1:2]
    points_raw = [{"gen_lr": lr, "ADJ_L1_REG_COEFF": adj,
                   "FACTOR_COS_SIM_COEFF": cs}
                  for lr in gen_axis for adj in adj_axis for cs in cos_axis]

    # ------------------------------------------------- selection data (fold 0)
    t0 = time.time()
    dargs_file = curate_tier_fold(base, "HSNR", 0, n_train, n_val)
    true_gcs = load_true_gc_factors(dargs_file)
    print(f"[curate] HSNR fold 0: {time.time()-t0:.1f}s", flush=True)

    # args/coefficients through the driver's own read/rescale path, so the
    # grid's base config matches what a per-job run would build (the grid
    # points then override the searched axes per point)
    margs_file = os.path.join(base, f"{model_type}_gs_cached_args.txt")
    with open(margs_file, "w") as f:
        json.dump(margs, f)
    args_dict = {"save_root_path": os.path.join(base, f"runs_grid{arch_tag}"),
                 "model_type": model_type,
                 "model_cached_args_file": margs_file,
                 "data_set_name": "data_fold0",
                 "data_cached_args_file": dargs_file}
    read_in_model_args(args_dict)
    read_in_data_args(args_dict)
    rescale_dataset_dependent_coefficients(args_dict)
    model = create_model_instance(
        args_dict, employ_version_with_smoothing_loss="Smooth" in model_type)
    # grid_search=False: the winner re-runs train through the driver on the
    # full fold, so selection must see the same data (the default True keeps
    # only a quarter — the reference's cheap-search subsampling)
    train_ds, val_ds = get_data_for_model_training(args_dict,
                                                   grid_search=False)

    tc = RedcliffTrainConfig(
        embed_lr=args_dict["embed_lr"], embed_eps=args_dict["embed_eps"],
        embed_weight_decay=args_dict["embed_weight_decay"],
        gen_lr=args_dict["gen_lr"], gen_eps=args_dict["gen_eps"],
        gen_weight_decay=args_dict["gen_weight_decay"],
        max_iter=args_dict["max_iter"], lookback=args_dict["lookback"],
        check_every=args_dict["check_every"],
        batch_size=args_dict["batch_size"],
        stopping_criteria_forecast_coeff=args_dict[
            "stopping_criteria_forecast_coeff"],
        stopping_criteria_factor_coeff=args_dict[
            "stopping_criteria_factor_coeff"],
        stopping_criteria_cosSim_coeff=args_dict[
            "stopping_criteria_cosSim_coeff"])

    def rescaled(key, raw):
        d = {"coeff_dict": {key: raw},
             "num_factors": args_dict["num_factors"],
             "num_channels": args_dict["num_channels"]}
        rescale_dataset_dependent_coefficients(d)
        return d["coeff_dict"][key]

    # per-point engine axes: searched coefficients rescaled by the driver's
    # own helper, stopping cos-sim coefficient mirroring the loss coefficient
    # per point exactly as the reference driver overwrites it (ref :102-105)
    grid_points = []
    for pt in points_raw:
        cs = rescaled("FACTOR_COS_SIM_COEFF", pt["FACTOR_COS_SIM_COEFF"])
        grid_points.append({
            "gen_lr": pt["gen_lr"],
            "adj_l1_reg_coeff": rescaled("ADJ_L1_REG_COEFF",
                                         pt["ADJ_L1_REG_COEFF"]),
            "factor_cos_sim_coeff": cs,
            "stopping_criteria_cosSim_coeff": cs,
        })

    G = len(grid_points)
    print(f"[grid] training {G} points at once "
          f"(axes {len(gen_axis)}x{len(adj_axis)}x{len(cos_axis)})",
          flush=True)
    t_grid = time.time()
    res = run_coefficient_grid(model, tc, grid_points, train_ds, val_ds,
                               key=jax.random.PRNGKey(0),
                               max_iter=args.max_iter,
                               init_point_params=model.init(
                                   jax.random.PRNGKey(0)))
    grid_wall = time.time() - t_grid
    criteria = np.asarray(res.best_criteria, dtype=np.float64)
    print(f"[grid] done in {grid_wall:.0f}s "
          f"({res.val_history.shape[0]} epochs run)", flush=True)

    # --------------------------------------- score EVERY point on fold 0
    per_point = []
    for i, (raw, gp) in enumerate(zip(points_raw, grid_points)):
        run_dir = os.path.join(base, f"runs_grid{arch_tag}",
                               f"grid_point{i}")
        os.makedirs(run_dir, exist_ok=True)
        pt_params = jax.tree.map(lambda x: np.asarray(x)[i], res.best_params)
        with open(os.path.join(run_dir, "final_best_model.bin"), "wb") as f:
            pickle.dump({"model_class": "RedcliffSCMLP",
                         "config": model.config, "params": pt_params}, f)
        stats = evaluate_algorithm_on_fold(run_dir, "REDCLIFF_S_CMLP",
                                           true_gcs)
        s = stats[OFFDIAG]
        per_point.append({
            "raw": raw, "engine_point": gp,
            "best_criteria": float(criteria[i]),
            "best_epoch": int(res.best_epoch[i]),
            "optf1_fold0": s["f1_mean_across_factors"],
            "optf1_fold0_sem": s["f1_mean_std_err_across_factors"],
        })
        print(f"[score] {raw}: criteria={criteria[i]:.4f} "
              f"optF1={s['f1_mean_across_factors']:.3f}", flush=True)

    sel = int(np.argmin(criteria))
    oracle = int(np.argmax([p["optf1_fold0"] for p in per_point]))
    print(f"[select] criteria winner: {points_raw[sel]} "
          f"(optF1 {per_point[sel]['optf1_fold0']:.3f}); "
          f"oracle best: {points_raw[oracle]} "
          f"(optF1 {per_point[oracle]['optf1_fold0']:.3f})", flush=True)

    # ------------------------- winner re-run: real driver, 3 tiers x N folds
    winner_raw = points_raw[sel]
    wm = dict(margs,
              gen_lr=repr(winner_raw["gen_lr"]),
              ADJ_L1_REG_COEFF=repr(winner_raw["ADJ_L1_REG_COEFF"]),
              FACTOR_COS_SIM_COEFF=repr(winner_raw["FACTOR_COS_SIM_COEFF"]))
    wm_file = os.path.join(base, f"{model_type}_winner_cached_args.txt")
    with open(wm_file, "w") as f:
        json.dump(wm, f)

    tiers = TIERS if not args.smoke else ("HSNR",)
    winner_rows = {}
    for snr in tiers:
        stats_by_fold = []
        for fold in range(args.folds):
            dargs = curate_tier_fold(base, snr, fold, n_train, n_val)
            # winner-config-encoded save root: run_folder_name encodes only
            # RESCALED coefficients (and not gen_lr at all, the reference
            # layout's limitation), so a re-invocation selecting a different
            # winner must land in its own tree rather than resume this one's
            wtag = "_".join(f"{k[:3]}{v}" for k, v in sorted(
                winner_raw.items())).replace(".", "-")
            save_root = os.path.join(
                base, f"runs_winner{arch_tag}_{snr}_{wtag}")
            os.makedirs(save_root, exist_ok=True)
            t0 = time.time()
            set_up_and_run_experiments(
                {"save_root_path": save_root}, [wm_file], [dargs],
                possible_model_types=[model_type],
                possible_data_sets=[f"data_fold{fold}"], task_id=1)
            print(f"[winner] {snr} fold {fold}: {time.time()-t0:.1f}s",
                  flush=True)
            matches = [os.path.join(save_root, d)
                       for d in sorted(os.listdir(save_root))
                       if f"data_fold{fold}" in d]
            assert len(matches) == 1, (save_root, fold, matches)
            run_dir = matches[0]
            stats_by_fold.append(evaluate_algorithm_on_fold(
                run_dir, "REDCLIFF_S_CMLP",
                load_true_gc_factors(dargs)))
        winner_rows[snr] = pooled_offdiag(stats_by_fold)
        print(f"[winner] {snr}: optF1 "
              f"{winner_rows[snr]['offdiag_optimal_f1_mean']:.3f} ± "
              f"{winner_rows[snr]['offdiag_optimal_f1_sem']:.3f}", flush=True)

    out = {
        "dataset": "synthetic-source D4IC analog (accuracy_parity_d4ic "
                   "curation), selection on HSNR fold 0",
        "architecture": args.arch,
        "smoke": bool(args.smoke),
        "axes_raw": {"gen_lr": list(gen_axis),
                     "ADJ_L1_REG_COEFF": list(adj_axis),
                     "FACTOR_COS_SIM_COEFF": list(cos_axis)},
        "grid_size": G,
        "grid_wall_clock_s": round(grid_wall, 1),
        "grid_epochs_run": int(res.val_history.shape[0]),
        "per_point": per_point,
        "selected_by_criteria": winner_raw,
        "selected_optf1_fold0": per_point[sel]["optf1_fold0"],
        "oracle_point": points_raw[oracle],
        "oracle_optf1_fold0": per_point[oracle]["optf1_fold0"],
        "transcribed_round4_baseline": (
            {"HSNR": 0.315, "MSNR": 0.319, "LSNR": 0.211,
             "note": "round-4 ACCURACY_D4IC tables, the un-searched Smooth "
                     "gs4 transcription"}
            if args.arch == "smooth" else
            {"HSNR": 0.178, "MSNR": 0.177, "LSNR": 0.178,
             "note": "round-4 ACCURACY_D4IC tables, the un-searched BSCgs1 "
                     "transcription (gen_lr 5e-4, ADJ_L1 1.0, COS_SIM 1.0)"}),
        "winner_rows": winner_rows,
    }
    tag = "_SMOOTH" if args.arch == "smooth" else ""
    dest = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"D4IC_GRID_SEARCH{tag}.json" if not args.smoke
                        else f"D4IC_GRID_SEARCH{tag}_smoke.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[done] wrote {dest}", flush=True)


if __name__ == "__main__":
    main()
