"""Reference-scale accuracy parity: the canonical synSys experiment.

Reproduces the reference's synSysInnovGauss1030 benchmark flow at the
hyperparameter scale of
/root/reference/train/REDCLIFF_S_CMLP_synSysInnovGauss1030_BSCgsSmooth3Parsim_cached_args.txt
(num_factors overwritten per dataset and coefficients rescaled exactly as the
reference driver does, ref train/...Parsim.py:98-105):

1. curate the numF2_numSF2_numN6_numE2 synthetic system across folds at the
   reference's sample counts (1040 train / 240 val recordings per class label,
   T=100, gaussian innovations, OneHot labels — ref currate_...py:24),
2. train REDCLIFF-S (DGCNN embedder, 300-epoch schedule with 100 pretrain +
   100 acclimation) plus the cMLP, NAVAR-cMLP and DYNOTEARS baselines through
   the real array-task driver,
3. score every run's GC estimates against the fold's true factor graphs with
   the cross-algorithm optimal-F1 battery (eval/cross_alg.py),
4. optionally (--dynamic) score the dynamic readouts — embedder state-score
   tracking and conditional-GC edge dynamics vs the oracle activations
   (eval/dynamic_readout.py), and
5. write mean±SEM off-diag optimal-F1 / ROC-AUC per algorithm to
   ACCURACY_SYNSYS_<N>_<E>_<F>.json for BASELINE.md.

The --system flag generalizes the study to any N-E-F (nodes-edges-factors)
configuration of the reference's synSysIG1030 complexity sweep;
experiments/run_banded_sweep.sh drives the multi-system banded study and
experiments/banded_condense.py condenses it into BANDED_SYNSYS.json.

Run:  python experiments/accuracy_parity_synsys.py <workdir> [--folds N]
      [--smoke]   (reduced samples/epochs for a plumbing check)
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # accuracy study; CPU is deterministic

import numpy as np  # noqa: E402

from redcliff_tpu.data.curation import curate_synthetic_fold  # noqa: E402
from redcliff_tpu.eval.cross_alg import (  # noqa: E402
    run_cross_algorithm_comparison)
from redcliff_tpu.train.driver import set_up_and_run_experiments  # noqa: E402
from redcliff_tpu.utils.config import load_true_gc_factors  # noqa: E402

# reference cached-args, transcribed (stringly-typed like the originals)
REDCLIFF_ARGS = {
    "output_length": "1", "batch_size": "128", "max_iter": "300",
    "lookback": "1", "check_every": "10", "verbose": "0", "num_sims": "1",
    "num_factors": "2", "num_supervised_factors": "2",
    "wavelet_level": "None", "gen_hidden": "[25]", "gen_lr": "0.0005",
    "gen_eps": "0.0001", "gen_weight_decay": "0.0001",
    "gen_lag_and_input_len": "4", "FORECAST_COEFF": "10.0",
    "FACTOR_SCORE_COEFF": "100.0", "FACTOR_COS_SIM_COEFF": "1.0",
    "FACTOR_WEIGHT_L1_COEFF": "0.001",
    "FACTOR_WEIGHT_SMOOTHING_PENALTY_COEFF": "0.0",
    "ADJ_L1_REG_COEFF": "0.1", "DAGNESS_REG_COEFF": "0.0",
    "DAGNESS_LAG_COEFF": "0.0", "DAGNESS_NODE_COEFF": "0.0",
    "primary_gc_est_mode": "conditional_factor_fixed_embedder",
    "forward_pass_mode": "apply_factor_weights_after_sim_completion",
    "training_mode": "pretrain_embedder_then_acclimate_factors_then_combined",
    "num_pretrain_epochs": "100", "num_acclimation_epochs": "100",
    "factor_score_embedder_type": "DGCNN", "embed_hidden_sizes": "[0]",
    "embed_num_hidden_nodes": "100", "embed_num_graph_conv_layers": "3",
    "embed_lr": "0.0005", "embed_eps": "0.0001",
    "embed_weight_decay": "0.0001", "embed_lag": "16",
    "use_sigmoid_restriction": "0", "sigmoid_eccentricity_coeff": "10.0",
    "prior_factors_path": "None", "cost_criteria": "CosineSimilarity",
    "unsupervised_start_index": "0", "max_factor_prior_batches": "10",
    "stopping_criteria_forecast_coeff": "10.",
    "stopping_criteria_factor_coeff": "100.",
    "stopping_criteria_cosSim_coeff": "1.", "deltaConEps": "0.1",
    "in_degree_coeff": "1.", "out_degree_coeff": "1.",
}
# ref train/cMLP_synSysInnovGauss1030_BLgs2Parsim_mi300_cached_args.txt
CMLP_ARGS = {
    "output_length": "1", "num_sims": "1", "embed_hidden_sizes": "[10]",
    "batch_size": "128", "gen_eps": "0.0001", "gen_weight_decay": "0.0001",
    "max_iter": "300", "lookback": "1", "check_every": "10", "verbose": "0",
    "num_factors": "1", "num_supervised_factors": "0",
    "wavelet_level": "None", "gen_hidden": "[25]", "gen_lr": "0.0001",
    "gen_lag_and_input_len": "2", "FORECAST_COEFF": "1.0",
    "FACTOR_SCORE_COEFF": "0.0", "ADJ_L1_REG_COEFF": "1.0",
    "DAGNESS_REG_COEFF": "0.0", "DAGNESS_LAG_COEFF": "0.0",
    "DAGNESS_NODE_COEFF": "0.0",
}
# ref train/NAVAR_CMLP_d4IC_BCTVgs1Parsim_cached_args.txt, nodes adjusted
NAVAR_ARGS = {
    "num_nodes": "6", "num_hidden": "256", "maxlags": "20",
    "hidden_layers": "2", "dropout": "0", "val_proportion": "0.0",
    "epochs": "1000", "batch_size": "128", "check_every": "100",
    "learning_rate": "0.0001", "weight_decay": "0",
    "split_timeseries": "0", "signal_format": "original", "lambda1": "0.0",
}
# ref train/DYNOTEARS_Vanilla_d4IC_BCNIBCHVgs1Parsim_cached_args.txt
DYNOTEARS_ARGS = {
    "lambda_w": "0.9", "lambda_a": "0.1", "max_iter": "100",
    "h_tol": "0.00000001", "w_threshold": "0.0", "tabu_edges": "None",
    "tabu_parent_nodes": "None", "tabu_child_nodes": "None",
    "lag_size": "1", "signal_format": "original",
}
# ref train/cLSTM_synSysInnovGauss1030_BLgs2_mi300_cached_args.txt
CLSTM_ARGS = {
    "output_length": "1", "num_sims": "1", "embed_hidden_sizes": "[10]",
    "batch_size": "128", "gen_eps": "0.0001", "gen_weight_decay": "0.0001",
    "max_iter": "300", "lookback": "3", "check_every": "5", "verbose": "0",
    "num_factors": "1", "num_supervised_factors": "0",
    "wavelet_level": "None", "gen_hidden": "25", "gen_lr": "0.0001",
    "context": "2", "max_input_length": "4", "FORECAST_COEFF": "1.0",
    "FACTOR_SCORE_COEFF": "0.0", "ADJ_L1_REG_COEFF": "1.0",
    "DAGNESS_REG_COEFF": "0.0", "DAGNESS_LAG_COEFF": "0.0",
    "DAGNESS_NODE_COEFF": "0.0",
}
# ref train/DGCNN_synSysInnovGauss1030_BLgs2_mi300_cached_args.txt
# (num_channels/num_classes follow the 6-node 2-factor dataset, as the
# reference's per-dataset overwrite would set them)
DGCNN_ARGS = {
    "batch_size": "128", "gen_eps": "0.0001", "gen_weight_decay": "0.0001",
    "max_iter": "300", "lookback": "1", "check_every": "10", "verbose": "0",
    "num_channels": "6", "wavelet_level": "None",
    "num_wavelets_per_chan": "1", "num_features_per_node": "2",
    "num_graph_conv_layers": "3", "num_hidden_nodes": "250",
    "num_classes": "2", "signal_format": "original flattened",
    "gen_lr": "0.0001",
}
# ref train/DCSFANMF_synSysInnovGauss1030_BOBPgs2Parsim_cached_args.txt
# (n_components/n_sup_networks follow the 2-factor dataset)
DCSFA_ARGS = {
    "batch_size": "128", "num_high_level_node_features": "13",
    "best_model_name": "dCSFA-NMF-best-model.pt", "num_node_features": "50",
    "n_components": "2", "n_sup_networks": "2",
    "signal_format": "original flattened directed_spectrum vanilla",
    "h": "256", "momentum": "0.9", "lr": "0.0005", "recon_weight": "2.0",
    "sup_weight": "1.0", "sup_recon_weight": "1.0",
    "sup_smoothness_weight": "1.0", "n_epochs": "250",
    "n_pre_epochs": "50", "nmf_max_iter": "10",
}

# the reference's synSys experiment matrix is REDCLIFF-S vs
# {cMLP, cLSTM, DGCNN, DCSFA-NMF} (train/*_synSysInnovGauss1030_*); NAVAR and
# DYNOTEARS are its d4IC-only baselines, included here as extended baselines
MODELS = (
    ("REDCLIFF_S_CMLP", REDCLIFF_ARGS, "REDCLIFF_S_CMLP"),
    ("cMLP", CMLP_ARGS, "CMLP"),
    ("cLSTM", CLSTM_ARGS, "CLSTM"),
    ("DGCNN", DGCNN_ARGS, "DGCNN"),
    ("DCSFANMF", DCSFA_ARGS, "DCSFA"),
    ("NAVAR_CMLP", NAVAR_ARGS, "NAVAR_CMLP"),
    ("DYNOTEARS_Vanilla", DYNOTEARS_ARGS, "DYNOTEARS_Vanilla"),
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("workdir")
    ap.add_argument("--folds", type=int, default=3)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--only-fold", type=int, default=None,
                    help="curate+train just this fold (for cross-process "
                         "fold parallelism), skip evaluation")
    ap.add_argument("--eval-only", action="store_true",
                    help="skip training (runs must exist) and just evaluate")
    ap.add_argument("--system", default="6-2-2",
                    help="synthetic system (nodes-edges-factors shorthand "
                         "N-E-F as in the paper, e.g. 6-2-2, 12-11-2, 3-1-2, "
                         "6-4-2, 6-2-3 — any of the reference synSysIG1030 "
                         "complexity-sweep configurations)")
    ap.add_argument("--dynamic", action="store_true",
                    help="additionally score the DYNAMIC readouts (embedder "
                         "state-score tracking + conditional-GC edge dynamics "
                         "vs the oracle activations) for every algorithm")
    ap.add_argument("--algs", default="all", choices=["all", "ref"],
                    help="'ref' = the reference's synSys baseline set only "
                         "(REDCLIFF, cMLP, cLSTM, DGCNN, DCSFA)")
    args = ap.parse_args()
    base = args.workdir
    os.makedirs(base, exist_ok=True)
    num_nodes, num_edges, num_factors = (int(v)
                                         for v in args.system.split("-"))
    sys_folder = f"synSys{num_nodes}_{num_edges}_{num_factors}"
    models = MODELS
    if args.algs == "ref":
        models = tuple(m for m in MODELS
                       if m[0] not in ("NAVAR_CMLP", "DYNOTEARS_Vanilla"))

    # the reference curates 1040/240 recordings per class label (x(S+1)
    # labels = 3120/720); this environment has ONE cpu core, so we keep the
    # per-class budget as the TOTAL (1040/240) — hyperparameters, schedule,
    # and coefficient rescaling stay exactly at reference scale
    n_train = 1040 if not args.smoke else 240
    n_val = 240 if not args.smoke else 96
    model_args = {name: dict(a) for name, a, _ in models}
    if num_nodes != 6:
        # NAVAR's num_nodes comes from its model cached-args; every other
        # family's channel count is overwritten from the DATA cached-args by
        # read_in_data_args
        for key in ("NAVAR_CMLP",):
            if key in model_args:
                model_args[key]["num_nodes"] = str(num_nodes)
    if num_factors != 2:
        # the reference's per-dataset factor-count overwrite (its drivers set
        # num_factors from the data cached-args, ref train/...Parsim.py:96)
        model_args["REDCLIFF_S_CMLP"].update(
            num_factors=str(num_factors),
            num_supervised_factors=str(num_factors))
        if "DGCNN" in model_args:
            model_args["DGCNN"]["num_classes"] = str(num_factors)
        if "DCSFANMF" in model_args:
            model_args["DCSFANMF"].update(
                n_components=str(num_factors),
                n_sup_networks=str(num_factors))
    # deviation from the reference's d4IC NAVAR epochs=1000: the synSys
    # dataset is ~13x larger per fold and this study runs on CPU; NAVAR
    # plateaus well before 250 epochs here (loss history in the run dir)
    if "NAVAR_CMLP" in model_args:
        model_args["NAVAR_CMLP"].update(epochs="250", check_every="50")
    if args.smoke:
        model_args["REDCLIFF_S_CMLP"].update(
            max_iter="12", num_pretrain_epochs="4",
            num_acclimation_epochs="4", check_every="2")
        model_args["cMLP"].update(max_iter="10", check_every="2")
        model_args["cLSTM"].update(max_iter="10", check_every="2")
        model_args["DGCNN"].update(max_iter="10", check_every="2")
        model_args["DCSFANMF"].update(n_epochs="10", n_pre_epochs="4")
        if "NAVAR_CMLP" in model_args:
            model_args["NAVAR_CMLP"].update(epochs="40", check_every="20")

    folds_to_run = (range(args.folds) if args.only_fold is None
                    else [args.only_fold])
    data_args_by_fold = {}
    true_by_fold = {}
    for fold in folds_to_run:
        t0 = time.time()
        fold_dir, _ = curate_synthetic_fold(
            os.path.join(base, "data"), fold_id=fold, num_nodes=num_nodes,
            num_lags=2, num_factors=num_factors,
            num_supervised_factors=num_factors,
            num_edges_per_graph=num_edges, num_samples_in_train_set=n_train,
            num_samples_in_val_set=n_val, sample_recording_len=100,
            burnin_period=50, label_type_setting="OneHot",
            noise_type="gaussian", noise_level=1.0,
            folder_name=sys_folder)
        data_args_by_fold[fold] = os.path.join(
            fold_dir, f"data_fold{fold}_cached_args.txt")
        true_by_fold[fold] = load_true_gc_factors(data_args_by_fold[fold])
        print(f"[curate] fold {fold}: {time.time()-t0:.1f}s -> {fold_dir}",
              flush=True)

    roots = {}
    for model_type, _, alias in models:
        margs_file = os.path.join(base, f"{model_type}_synSys_cached_args.txt")
        with open(margs_file, "w") as f:
            json.dump(model_args[model_type], f)
        save_root = os.path.join(base, "runs", f"{alias}_models")
        os.makedirs(save_root, exist_ok=True)
        roots[alias] = save_root
        if args.eval_only:
            continue
        for fold in folds_to_run:
            t0 = time.time()
            set_up_and_run_experiments(
                {"save_root_path": save_root}, [margs_file],
                [data_args_by_fold[fold]],
                possible_model_types=[model_type],
                possible_data_sets=[f"data_fold{fold}"], task_id=1)
            print(f"[train] {model_type} fold {fold}: {time.time()-t0:.1f}s",
                  flush=True)

    if args.only_fold is not None:
        print(f"[done] fold {args.only_fold} trained; run --eval-only "
              "after all folds finish", flush=True)
        return

    # eval windows for data-dependent GC readouts (NAVAR contribution stats),
    # z-scored exactly as the training loaders normalized them — the models
    # never saw raw-amplitude signals
    eval_inputs = {"data": {}}
    from redcliff_tpu.data.shards import load_normalized_samples
    for fold in range(args.folds):
        if fold not in data_args_by_fold:
            fd = os.path.join(base, "data", sys_folder, f"fold_{fold}")
            data_args_by_fold[fold] = os.path.join(
                fd, f"data_fold{fold}_cached_args.txt")
            true_by_fold[fold] = load_true_gc_factors(data_args_by_fold[fold])
        val_dir = os.path.join(os.path.dirname(data_args_by_fold[fold]),
                               "validation")
        eval_inputs["data"][fold] = np.asarray(
            load_normalized_samples(val_dir).X[:128])

    system_key = (f"numF{num_factors}_numSF{num_factors}_"
                  f"numN{num_nodes}_numE{num_edges}_{sys_folder}")
    full = run_cross_algorithm_comparison(
        list(roots.values()), {"data": true_by_fold},
        os.path.join(base, "evals", system_key),
        num_folds=args.folds, plot=not args.smoke,
        algorithms=[alias for _, _, alias in models],
        eval_inputs=eval_inputs)

    paradigm = "key_stats_estGC_normOffDiag_vs_trueGC_normOffDiag"
    out = {"dataset": f"{system_key} (OneHot, gaussian innovations)",
           "system": args.system,
           "folds": args.folds, "smoke": bool(args.smoke),
           "train_samples_per_fold": n_train, "algorithms": {}}
    for alg, stats in full["data"][paradigm].items():
        out["algorithms"][alg] = {
            "offdiag_optimal_f1_mean": stats["f1_mean_across_factors"],
            "offdiag_optimal_f1_sem": stats["f1_mean_std_err_across_factors"],
            "offdiag_roc_auc_mean": stats.get("roc_auc_mean_across_factors"),
            "offdiag_roc_auc_sem": stats.get(
                "roc_auc_mean_std_err_across_factors"),
        }
        print(f"[result] {alg}: optF1 "
              f"{out['algorithms'][alg]['offdiag_optimal_f1_mean']:.3f} ± "
              f"{out['algorithms'][alg]['offdiag_optimal_f1_sem']:.3f}  "
              f"ROC-AUC {out['algorithms'][alg]['offdiag_roc_auc_mean']}",
              flush=True)

    if args.dynamic:
        from redcliff_tpu.eval.dynamic_readout import (
            run_dynamic_readout_evaluation)
        dyn = run_dynamic_readout_evaluation(
            roots=roots, data_args_by_fold=data_args_by_fold,
            true_by_fold=true_by_fold, num_folds=args.folds,
            num_supervised_factors=num_factors,
            save_root=os.path.join(base, "evals", system_key, "dynamic"))
        out["dynamic_readouts"] = dyn

    tag = "_" + args.system.replace("-", "_")
    dest = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"ACCURACY_SYNSYS{tag}.json" if not args.smoke
                        else "ACCURACY_SYNSYS_smoke.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[done] wrote {dest}", flush=True)


if __name__ == "__main__":
    main()
