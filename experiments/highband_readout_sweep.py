"""High-band dynamic-readout sweep: why per-edge tracking r ~ 0.03, and what
convention fixes or explains it.

Round-4's banded study scored the High band's dynamic readouts barely above
zero (per-edge tracking r 0.030, BASELINE.md:107-108) — in the band where the
paper's claim is strongest. Two confounds were identified (VERDICT r4 weak
#5, ADVICE r4 #4):

1. REDCLIFF was scored with history=embed_lag (16) while static baselines
   used history=max(L,2)=2 — different window counts and label offsets of the
   same recordings (ADVICE: score all algorithms on a common window grid);
2. the window's label anchor was its LAST step, but High-band systems switch
   states quickly: a 16-step window's content reflects its interior, so
   anchoring truth at the trailing edge misaligns estimate and truth near
   every transition.

This experiment retrains the High-band factor-sweep systems (6-2-4 / 6-2-5 /
6-2-6 — the banded-study configurations, same generator/seeds/budgets) with
REDCLIFF-S and the two strongest static baselines, then scores the dynamic
readouts under a convention sweep:

* common window grid (ADVICE fix) x label_align in {last, center, majority};
* the round-4 convention (per-algorithm windows, last-step anchor) re-scored
  for continuity with BANDED_SYNSYS.json.

Writes experiments/HIGHBAND_READOUT_SWEEP.json.

Run:  python experiments/highband_readout_sweep.py <workdir> [--smoke]
      [--systems 6-2-4,6-2-5,6-2-6] [--folds N]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from accuracy_parity_synsys import (  # noqa: E402
    CMLP_ARGS, DGCNN_ARGS, REDCLIFF_ARGS)
from redcliff_tpu.data.curation import curate_synthetic_fold  # noqa: E402
from redcliff_tpu.eval.dynamic_readout import (  # noqa: E402
    run_dynamic_readout_evaluation)
from redcliff_tpu.train.driver import set_up_and_run_experiments  # noqa: E402
from redcliff_tpu.utils.config import load_true_gc_factors  # noqa: E402

CONDITIONS = (
    {"name": "round4_convention", "common_window_grid": False,
     "label_align": "last"},
    {"name": "common_grid_last", "common_window_grid": True,
     "label_align": "last"},
    {"name": "common_grid_center", "common_window_grid": True,
     "label_align": "center"},
    {"name": "common_grid_majority", "common_window_grid": True,
     "label_align": "majority"},
)


def run_system(base, system, folds, smoke):
    num_nodes, num_edges, num_factors = (int(v) for v in system.split("-"))
    n_train, n_val = (240, 96) if smoke else (1040, 240)
    sys_folder = f"synSys{num_nodes}_{num_edges}_{num_factors}"

    model_args = {
        "REDCLIFF_S_CMLP": dict(REDCLIFF_ARGS,
                                num_factors=str(num_factors),
                                num_supervised_factors=str(num_factors)),
        "cMLP": dict(CMLP_ARGS),
        "DGCNN": dict(DGCNN_ARGS, num_classes=str(num_factors)),
    }
    if smoke:
        model_args["REDCLIFF_S_CMLP"].update(
            max_iter="12", num_pretrain_epochs="4",
            num_acclimation_epochs="4", check_every="2")
        model_args["cMLP"].update(max_iter="8", check_every="2")
        model_args["DGCNN"].update(max_iter="8", check_every="2")

    data_args_by_fold = {}
    true_by_fold = {}
    for fold in range(folds):
        fold_dir, _ = curate_synthetic_fold(
            os.path.join(base, "data"), fold_id=fold, num_nodes=num_nodes,
            num_lags=2, num_factors=num_factors,
            num_supervised_factors=num_factors,
            num_edges_per_graph=num_edges, num_samples_in_train_set=n_train,
            num_samples_in_val_set=n_val, sample_recording_len=100,
            burnin_period=50, label_type_setting="OneHot",
            noise_type="gaussian", noise_level=1.0, folder_name=sys_folder)
        data_args_by_fold[fold] = os.path.join(
            fold_dir, f"data_fold{fold}_cached_args.txt")
        true_by_fold[fold] = load_true_gc_factors(data_args_by_fold[fold])

    roots = {}
    for model_type, margs in model_args.items():
        margs_file = os.path.join(base, f"{model_type}_cached_args.txt")
        with open(margs_file, "w") as f:
            json.dump(margs, f)
        alias = {"REDCLIFF_S_CMLP": "REDCLIFF_S_CMLP", "cMLP": "CMLP",
                 "DGCNN": "DGCNN"}[model_type]
        save_root = os.path.join(base, "runs", f"{alias}_models")
        os.makedirs(save_root, exist_ok=True)
        roots[alias] = save_root
        for fold in range(folds):
            t0 = time.time()
            set_up_and_run_experiments(
                {"save_root_path": save_root}, [margs_file],
                [data_args_by_fold[fold]],
                possible_model_types=[model_type],
                possible_data_sets=[f"data_fold{fold}"], task_id=1)
            print(f"[{system} train] {alias} fold {fold}: "
                  f"{time.time()-t0:.1f}s", flush=True)

    results = {}
    for cond in CONDITIONS:
        dyn = run_dynamic_readout_evaluation(
            roots=roots, data_args_by_fold=data_args_by_fold,
            true_by_fold=true_by_fold, num_folds=folds,
            num_supervised_factors=num_factors,
            save_root=os.path.join(base, "evals", "dynamic", cond["name"]),
            common_window_grid=cond["common_window_grid"],
            label_align=cond["label_align"])
        results[cond["name"]] = dyn
        r = dyn.get("REDCLIFF_S_CMLP", {})
        print(f"[{system} {cond['name']}] REDCLIFF edge_tracking_r="
              f"{(r.get('edge_tracking_r') or {}).get('mean')} "
              f"dyn_optF1={(r.get('dynamic_optimal_f1') or {}).get('mean')} "
              f"state_r={(r.get('state_score_r') or {}).get('mean')}",
              flush=True)
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("workdir")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--systems", default="6-2-4,6-2-5,6-2-6")
    ap.add_argument("--folds", type=int, default=3)
    args = ap.parse_args()
    out = {"folds": args.folds, "smoke": bool(args.smoke),
           "conditions": [c["name"] for c in CONDITIONS], "systems": {}}
    for system in args.systems.split(","):
        base = (os.path.abspath(args.workdir) + f"_{system}"
                + ("_smoke" if args.smoke else ""))
        os.makedirs(base, exist_ok=True)
        out["systems"][system] = run_system(base, system, args.folds,
                                            args.smoke)

    # cross-system aggregate per condition (mean of per-system means)
    agg = {}
    for cond in CONDITIONS:
        per_metric = {}
        for system, res in out["systems"].items():
            r = res[cond["name"]].get("REDCLIFF_S_CMLP", {})
            for metric in ("edge_tracking_r", "dynamic_optimal_f1",
                           "state_score_r", "dominant_state_acc"):
                st = r.get(metric)
                if isinstance(st, dict) and st.get("mean") is not None:
                    per_metric.setdefault(metric, []).append(st["mean"])
        agg[cond["name"]] = {
            m: {"mean": float(np.mean(v)), "n_systems": len(v)}
            for m, v in per_metric.items()}
    out["redcliff_aggregate_by_condition"] = agg

    dest = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "HIGHBAND_READOUT_SWEEP.json" if not args.smoke
                        else "HIGHBAND_READOUT_SWEEP_smoke.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[done] wrote {dest}", flush=True)


if __name__ == "__main__":
    main()
