"""D4IC-pattern accuracy study: the reference's OTHER headline benchmark flow.

The reference's D4IC benchmark superimposes five DREAM4 InSilico-Size10 gene
networks' signals per sample — one dominant, four background — with the
(num_factors, 1) coefficient vector as the label, at three SNR tiers
(ref data/dream4_insilicoCombo.py:83-151,156-198), and compares the full
algorithm roster incl. the d4IC-only baselines NAVAR and DYNOTEARS
(ref evaluate/eval_sysOptF1_crossAlg_d4IC_HSNR_...py). The original DREAM4
TSV source data does NOT ship with the reference repository, so exact D4IC
replication is impossible here; this experiment runs the SAME flow end to end
on a synthetic-source analog:

1. five 10-node single-state sVAR "networks", each with its own ground-truth
   lagged graph (the DREAM4 gold-standard stand-ins), per-network recordings
   curated into the per-network fold/split shard layout;
2. `data.dream4.make_d4ic_fold` builds the actual D4IC mixture at a named
   SNR tier (dominant/background coefficients, label = coefficient vector —
   the exact reference mixing code path, exercising the (S, 1) label-shape
   branch every model's loss dispatches on);
3. every algorithm of the reference's d4IC roster trains through the real
   array-task driver at the reference's own d4IC cached-args
   (REDCLIFF_S_CMLP_d4IC_BSCgs1 plus the Smooth "Parsim" variant
   REDCLIFF_S_CMLP_Smooth_d4IC_BSCgs4ParsimSmo0 — the reference's headline
   D4IC model — cMLP/cLSTM_d4IC_BLgs1Parsim, DGCNN_d4IC_BLgs1Parsim,
   DCSFANMF_d4IC_OBPgs1, NAVAR_CMLP/DYNOTEARS d4IC Parsim — transcribed
   below, driver coefficient rescaling applied);
4. the cross-algorithm optimal-F1 battery scores each run against the five
   network graphs; results land in ACCURACY_D4IC_<tier>.json.

Deviations from the reference data geometry, both documented and forced by
the environment: recordings are 48 steps (DREAM4 perturbation rounds are 21;
48 keeps the directed-spectrum features DCSFA consumes well-conditioned) and
the per-network sample budget is 120 train / 30 val per fold (single CPU
core). Dynamic readouts are NOT scored here: a D4IC recording's state is
constant by construction (one dominant network per sample), so there are no
within-recording dynamics to track.

Run:  python experiments/accuracy_parity_d4ic.py <workdir> [--folds N]
      [--snr HSNR|MSNR|LSNR] [--smoke]
"""
import argparse
import json
import os
import pickle
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from redcliff_tpu.data import synthetic as S  # noqa: E402
from redcliff_tpu.data.curation import (  # noqa: E402
    save_cached_args_file_for_data)
from redcliff_tpu.data.dream4 import make_d4ic_fold  # noqa: E402
from redcliff_tpu.data.shards import load_normalized_samples  # noqa: E402
from redcliff_tpu.eval.cross_alg import (  # noqa: E402
    run_cross_algorithm_comparison)
from redcliff_tpu.train.driver import set_up_and_run_experiments  # noqa: E402
from redcliff_tpu.utils.config import load_true_gc_factors  # noqa: E402

NUM_NETWORKS = 5
NUM_NODES = 10
RECORDING_LEN = 48

# ref train/REDCLIFF_S_CMLP_d4IC_BSCgs1_cached_args.txt (transcribed)
REDCLIFF_ARGS = {
    "output_length": "1", "batch_size": "128", "max_iter": "1000",
    "lookback": "1", "check_every": "10", "verbose": "0", "num_sims": "1",
    "num_factors": "5", "num_supervised_factors": "5",
    "wavelet_level": "None", "gen_hidden": "[25]", "gen_lr": "0.0005",
    "gen_eps": "0.0001", "gen_weight_decay": "0.0001",
    "gen_lag_and_input_len": "4", "FORECAST_COEFF": "10.0",
    "FACTOR_SCORE_COEFF": "100.0", "FACTOR_COS_SIM_COEFF": "1.0",
    "FACTOR_WEIGHT_L1_COEFF": "0.001", "ADJ_L1_REG_COEFF": "1.0",
    "DAGNESS_REG_COEFF": "0.0", "DAGNESS_LAG_COEFF": "0.0",
    "DAGNESS_NODE_COEFF": "0.0",
    "primary_gc_est_mode": "conditional_factor_fixed_embedder",
    "forward_pass_mode": "apply_factor_weights_after_sim_completion",
    "training_mode": "pretrain_embedder_then_acclimate_factors_then_combined",
    "num_pretrain_epochs": "50", "num_acclimation_epochs": "15",
    "factor_score_embedder_type": "DGCNN", "embed_hidden_sizes": "[0]",
    "embed_num_hidden_nodes": "100", "embed_num_graph_conv_layers": "3",
    "embed_lr": "0.0002", "embed_eps": "0.0001",
    "embed_weight_decay": "0.0001", "embed_lag": "16",
    "use_sigmoid_restriction": "0", "sigmoid_eccentricity_coeff": "10.0",
    "prior_factors_path": "None", "cost_criteria": "CosineSimilarity",
    "unsupervised_start_index": "0", "max_factor_prior_batches": "10",
    "stopping_criteria_forecast_coeff": "10.",
    "stopping_criteria_factor_coeff": "100.",
    "stopping_criteria_cosSim_coeff": "1.", "deltaConEps": "0.1",
    "in_degree_coeff": "1.", "out_degree_coeff": "1.",
}
# ref train/cMLP_d4IC_BLgs1Parsim_cached_args.txt
CMLP_ARGS = {
    "output_length": "1", "num_sims": "1", "embed_hidden_sizes": "[60]",
    "batch_size": "128", "gen_eps": "0.0001", "gen_weight_decay": "0.0001",
    "max_iter": "1000", "lookback": "1", "check_every": "10", "verbose": "0",
    "num_factors": "1", "num_supervised_factors": "0",
    "wavelet_level": "None", "gen_hidden": "[50]", "gen_lr": "0.0005",
    "gen_lag_and_input_len": "2", "FORECAST_COEFF": "1.0",
    "FACTOR_SCORE_COEFF": "0.0", "ADJ_L1_REG_COEFF": "1.0",
    "DAGNESS_REG_COEFF": "0.0", "DAGNESS_LAG_COEFF": "0.0",
    "DAGNESS_NODE_COEFF": "0.0",
}
# ref train/cLSTM_d4IC_BLgs1Parsim_cached_args.txt
CLSTM_ARGS = {
    "output_length": "1", "num_sims": "1", "embed_hidden_sizes": "[10]",
    "batch_size": "128", "gen_eps": "0.0001", "gen_weight_decay": "0.0001",
    "max_iter": "1000", "lookback": "3", "check_every": "5", "verbose": "0",
    "num_factors": "1", "num_supervised_factors": "0",
    "wavelet_level": "None", "gen_hidden": "25", "gen_lr": "0.0005",
    "context": "2", "max_input_length": "4", "FORECAST_COEFF": "1.0",
    "FACTOR_SCORE_COEFF": "0.0", "ADJ_L1_REG_COEFF": "10.0",
    "DAGNESS_REG_COEFF": "0.0", "DAGNESS_LAG_COEFF": "0.0",
    "DAGNESS_NODE_COEFF": "0.0",
}
# ref train/DGCNN_d4IC_BLgs1Parsim_cached_args.txt
DGCNN_ARGS = {
    "batch_size": "128", "gen_eps": "0.0001", "gen_weight_decay": "0.0001",
    "max_iter": "1000", "lookback": "1", "check_every": "10", "verbose": "0",
    "num_channels": "10", "wavelet_level": "None",
    "num_wavelets_per_chan": "1", "num_features_per_node": "2",
    "num_graph_conv_layers": "1", "num_hidden_nodes": "100",
    "num_classes": "5", "signal_format": "original flattened",
    "gen_lr": "0.0001",
}
# ref train/DCSFANMF_d4IC_OBPgs1_cached_args.txt
DCSFA_ARGS = {
    "batch_size": "128", "num_high_level_node_features": "5",
    "best_model_name": "dCSFA-NMF-best-model.pt", "num_node_features": "20",
    "n_components": "5", "n_sup_networks": "5",
    "signal_format": "original flattened directed_spectrum vanilla",
    "h": "256", "momentum": "0.5", "lr": "0.001", "recon_weight": "1.0",
    "sup_weight": "2.0", "sup_recon_weight": "1.0",
    "sup_smoothness_weight": "2.0", "n_epochs": "1000",
    "n_pre_epochs": "50", "nmf_max_iter": "20",
}
# NAVAR/DYNOTEARS are the reference's d4IC-only baselines; their transcribed
# cached-args live in the synSys module (which borrows them from d4IC) — one
# transcription, shared. NAVAR's num_nodes follows this dataset.
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from accuracy_parity_synsys import DYNOTEARS_ARGS  # noqa: E402
from accuracy_parity_synsys import NAVAR_ARGS as _NAVAR_SYNSYS  # noqa: E402

# deviation from the reference's d4IC epochs=1000, as in the synSys study:
# single-CPU-core budget; NAVAR's loss plateaus well before 250 epochs here
NAVAR_ARGS = dict(_NAVAR_SYNSYS, num_nodes=str(NUM_NODES), epochs="250",
                  check_every="50")

# ref train/REDCLIFF_S_CMLP_Smooth_d4IC_BSCgs4ParsimSmo0_cached_args.txt —
# the state-smoothing class at its d4IC "Parsim" configuration, expressed as
# the overlay on BSCgs1 so the actual differences are visible: wider factor
# networks, smaller 2-layer DGCNN embedder, longer embed_lag, 10x smaller
# ADJ_L1, plus the (zero-valued, with num_sims=1 structurally inert)
# smoothing coefficient the Smooth class requires
SMOOTH_ARGS = dict(
    REDCLIFF_ARGS,
    gen_hidden="[100]",
    ADJ_L1_REG_COEFF="0.1",
    FACTOR_WEIGHT_SMOOTHING_PENALTY_COEFF="0.0",
    embed_num_hidden_nodes="30",
    embed_num_graph_conv_layers="2",
    embed_lag="20",
)

MODELS = (
    ("REDCLIFF_S_CMLP", REDCLIFF_ARGS, "REDCLIFF_S_CMLP"),
    # alias avoids substring collision with the non-smooth root in
    # select_algorithm_root while keeping the REDCLIFF GC dispatch
    ("REDCLIFF_S_CMLP_Smooth", SMOOTH_ARGS, "REDCLIFF_Smooth"),
    ("cMLP", CMLP_ARGS, "CMLP"),
    ("cLSTM", CLSTM_ARGS, "CLSTM"),
    ("DGCNN", DGCNN_ARGS, "DGCNN"),
    ("DCSFANMF", DCSFA_ARGS, "DCSFA"),
    ("NAVAR_CMLP", NAVAR_ARGS, "NAVAR_CMLP"),
    ("DYNOTEARS_Vanilla", DYNOTEARS_ARGS, "DYNOTEARS_Vanilla"),
)


def curate_network(nets_root, net_id, fold, n_train, n_val):
    """One synthetic 'gene network': a single-state 10-node sVAR with its own
    lagged graph; per-network recordings in the per-network shard layout the
    D4IC builder consumes. Returns the network's (C, C, L) graph.

    The five network GRAPHS are fixed across folds (seeded by net_id only),
    matching the D4IC design where folds are CV resamplings of the same five
    DREAM4 networks; only the recordings are redrawn per fold."""
    p = S.reference_curation_params(NUM_NODES)
    graph_seed = 17 * net_id + 1
    graphs, acts, _ = S.generate_lagged_adjacency_graphs_for_factor_model(
        num_nodes=NUM_NODES, num_lags=2, num_factors=1,
        make_factors_orthogonal=False,
        make_factors_singular_components=False, rand_seed=graph_seed,
        off_diag_edge_strengths=p["off_diag_edge_strengths"],
        diag_receiving_node_forgetting_coeffs=
            p["diag_receiving_node_forgetting_coeffs"],
        diag_sending_node_forgetting_coeffs=
            p["diag_sending_node_forgetting_coeffs"],
        num_edges_per_graph=13)
    X, Y = S.generate_synthetic_dataset(
        jax.random.PRNGKey(fold * 1000 + net_id), graphs, acts,
        p["base_freqs"], p["noise_mu"], p["noise_var"], p["innovation_amp"],
        num_samples=n_train + n_val, recording_length=RECORDING_LEN,
        burnin_period=50, num_labeled_sys_states=1, label_type="Oracle",
        noise_type="gaussian")
    X = np.asarray(X)
    for split, sl in (("train", slice(0, n_train)),
                      ("validation", slice(n_train, None))):
        d = os.path.join(nets_root, f"net{net_id}", f"fold_{fold}", split)
        os.makedirs(d, exist_ok=True)
        samples = [[X[i], np.zeros((1,))] for i in range(len(X))[sl]]
        with open(os.path.join(d, "subset_0.pkl"), "wb") as f:
            pickle.dump(samples, f)
    return np.asarray(graphs[0])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("workdir")
    ap.add_argument("--folds", type=int, default=3)
    ap.add_argument("--snr", default="HSNR", choices=["HSNR", "MSNR", "LSNR"])
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--algs", default="all", choices=["all", "core"],
                    help="'core' drops NAVAR/DYNOTEARS")
    args = ap.parse_args()
    base = os.path.abspath(args.workdir) + ("_smoke" if args.smoke else "")
    os.makedirs(base, exist_ok=True)
    n_train, n_val = (24, 8) if args.smoke else (120, 30)
    models = MODELS if args.algs == "all" else tuple(
        m for m in MODELS if m[0] not in ("NAVAR_CMLP", "DYNOTEARS_Vanilla"))

    model_args = {name: dict(a) for name, a, _ in models}
    if args.smoke:
        for key in ("REDCLIFF_S_CMLP", "REDCLIFF_S_CMLP_Smooth"):
            model_args[key].update(
                max_iter="12", num_pretrain_epochs="4",
                num_acclimation_epochs="4", check_every="2")
        for key in ("cMLP", "cLSTM", "DGCNN"):
            model_args[key].update(max_iter="10", check_every="2")
        model_args["DCSFANMF"].update(n_epochs="10", n_pre_epochs="4")
        if "NAVAR_CMLP" in model_args:
            model_args["NAVAR_CMLP"].update(epochs="40", check_every="20")

    # ------------------------------------------------------------- curation
    data_args_by_fold = {}
    true_by_fold = {}
    nets_root = os.path.join(base, "networks")
    for fold in range(args.folds):
        t0 = time.time()
        graphs = [curate_network(nets_root, n, fold, n_train, n_val)
                  for n in range(NUM_NETWORKS)]
        fold_dir = os.path.join(base, "data", f"d4ic_{args.snr}",
                                f"fold_{fold}")
        make_d4ic_fold(nets_root, fold_dir, fold_id=fold,
                       num_factors=NUM_NETWORKS, snr_tier=args.snr,
                       shuffle_rng=np.random.default_rng(fold))
        save_cached_args_file_for_data(
            fold_dir, NUM_NODES, graphs, f"data_fold{fold}_cached_args.txt")
        data_args_by_fold[fold] = os.path.join(
            fold_dir, f"data_fold{fold}_cached_args.txt")
        true_by_fold[fold] = load_true_gc_factors(data_args_by_fold[fold])
        print(f"[curate] fold {fold}: {time.time()-t0:.1f}s -> {fold_dir}",
              flush=True)

    # ------------------------------------------------------------- training
    roots = {}
    for model_type, _, alias in models:
        margs_file = os.path.join(base, f"{model_type}_d4ic_cached_args.txt")
        with open(margs_file, "w") as f:
            json.dump(model_args[model_type], f)
        # tier-namespaced: run folder names do not encode the SNR tier, so a
        # shared runs/ dir would let a second tier resume the first's models
        save_root = os.path.join(base, f"runs_{args.snr}", f"{alias}_models")
        os.makedirs(save_root, exist_ok=True)
        roots[alias] = save_root
        for fold in range(args.folds):
            t0 = time.time()
            set_up_and_run_experiments(
                {"save_root_path": save_root}, [margs_file],
                [data_args_by_fold[fold]],
                possible_model_types=[model_type],
                possible_data_sets=[f"data_fold{fold}"], task_id=1)
            print(f"[train] {model_type} fold {fold}: {time.time()-t0:.1f}s",
                  flush=True)

    # ----------------------------------------------------------------- eval
    eval_inputs = {"data": {}}
    for fold in range(args.folds):
        val_dir = os.path.join(os.path.dirname(data_args_by_fold[fold]),
                               "validation")
        eval_inputs["data"][fold] = np.asarray(
            load_normalized_samples(val_dir).X[:128])

    full = run_cross_algorithm_comparison(
        list(roots.values()), {"data": true_by_fold},
        os.path.join(base, "evals", f"d4ic_{args.snr}"),
        num_folds=args.folds, plot=not args.smoke,
        algorithms=[alias for _, _, alias in models],
        eval_inputs=eval_inputs)

    paradigm = "key_stats_estGC_normOffDiag_vs_trueGC_normOffDiag"
    out = {"dataset": f"synthetic-source D4IC analog, {args.snr} "
                      f"({NUM_NETWORKS} x {NUM_NODES}-node networks, "
                      f"T={RECORDING_LEN}, dominant/background mixing)",
           "snr_tier": args.snr, "folds": args.folds,
           "smoke": bool(args.smoke),
           "train_samples_per_fold": n_train * NUM_NETWORKS,
           "algorithms": {}}
    for alg, stats in full["data"][paradigm].items():
        out["algorithms"][alg] = {
            "offdiag_optimal_f1_mean": stats["f1_mean_across_factors"],
            "offdiag_optimal_f1_sem": stats["f1_mean_std_err_across_factors"],
            "offdiag_roc_auc_mean": stats.get("roc_auc_mean_across_factors"),
            "offdiag_roc_auc_sem": stats.get(
                "roc_auc_mean_std_err_across_factors"),
        }
        print(f"[result] {alg}: optF1 "
              f"{out['algorithms'][alg]['offdiag_optimal_f1_mean']:.3f} ± "
              f"{out['algorithms'][alg]['offdiag_optimal_f1_sem']:.3f}  "
              f"ROC-AUC {out['algorithms'][alg]['offdiag_roc_auc_mean']}",
              flush=True)

    dest = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"ACCURACY_D4IC_{args.snr}.json" if not args.smoke
                        else f"ACCURACY_D4IC_{args.snr}_smoke.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[done] wrote {dest}", flush=True)


if __name__ == "__main__":
    main()
