"""Sharded-grid scaling curve on a virtual device mesh.

Real multi-chip hardware is not reachable from this environment (one TPU chip
behind an intermittent tunnel), so the multi-chip story is validated two ways:
correctness of the sharded grid step on an 8-device CPU mesh
(tests/test_parallel_grid.py::test_grid_runner_sharded_over_mesh, plus the
driver's dryrun_multichip), and — here — the SHAPE of the scaling behavior:
steps/s of the same G-point grid step with its grid axis sharded over
1/2/4/8 virtual devices.

Honest framing: the virtual devices share ONE physical CPU core, so total
FLOP throughput cannot scale — what this measures is that sharding the grid
axis adds no super-linear overhead (collective/dispatch cost stays flat as
device count rises while per-device compute shrinks proportionally). On real
chips the same program gives each shard its own MXU; the per-device work
division measured here is the quantity that turns into speedup there.

Each device count runs in a fresh subprocess (the XLA device count is fixed
at backend init). Writes experiments/SHARDED_GRID_SCALING.json.

Run:  python experiments/sharded_grid_scaling.py [--grid 16] [--steps 8]
"""
import argparse
import json
import os
import subprocess
import sys
import time

CHILD = r"""
import json, os, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")

from redcliff_tpu.models.redcliff import RedcliffSCMLP, RedcliffSCMLPConfig
from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
from redcliff_tpu.parallel.mesh import grid_mesh
from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig

G, B, STEPS = {G}, {B}, {STEPS}
n_dev = len(jax.devices())
model = RedcliffSCMLP(RedcliffSCMLPConfig(
    num_chans=10, gen_lag=4, gen_hidden=(32,), embed_lag=16,
    embed_hidden_sizes=(0,), num_factors=5, num_supervised_factors=5,
    factor_score_coeff=2.0, factor_cos_sim_coeff=0.05,
    factor_weight_l1_coeff=0.01, adj_l1_reg_coeff=0.001,
    factor_score_embedder_type="DGCNN", dgcnn_num_graph_conv_layers=3,
    dgcnn_num_hidden_nodes=100,
    primary_gc_est_mode="conditional_factor_fixed_embedder",
    num_sims=2, training_mode="combined"))
mesh = grid_mesh(n_dev) if n_dev > 1 else None
spec = GridSpec(points=[
    {{"gen_lr": 1e-3 * (1 + (i % 4)), "adj_l1_reg_coeff": 1e-3 * (i % 2)}}
    for i in range(G)])
runner = RedcliffGridRunner(model, RedcliffTrainConfig(batch_size=B), spec,
                            mesh=mesh)
rng = np.random.default_rng(0)
cfg = model.config
T = cfg.max_lag + cfg.num_sims
X = jax.device_put(rng.normal(size=(B, T, cfg.num_chans)).astype(np.float32))
Y = jax.device_put(rng.uniform(
    size=(B, cfg.num_supervised_factors, 1)).astype(np.float32))
params, optA, optB = runner.init_grid(jax.random.PRNGKey(0))
coeffs = runner.coeffs
active = jax.numpy.ones((G,), dtype=bool)
from redcliff_tpu.runtime.numerics import init_numerics_state
ns = init_numerics_state(lanes=G)
step = runner._steps["combined"]
p, a, b, ns, _ = step(params, optA, optB, ns, coeffs, active, X, Y)  # compile+warm
jax.block_until_ready(p)
t0 = time.perf_counter()
for _ in range(STEPS):
    p, a, b, ns, _ = step(p, a, b, ns, coeffs, active, X, Y)
jax.block_until_ready(p)
dt = time.perf_counter() - t0
# fingerprint for cross-device-count equivalence of the program's output
fp = float(jax.numpy.mean(jax.numpy.abs(p["factors"][0]["w"])))
print(json.dumps({{"n_devices": n_dev, "step_s": dt / STEPS,
                   "steps_per_s": STEPS / dt, "fingerprint": fp}}))
"""


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--grid", type=int, default=16)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=32)
    args = ap.parse_args()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = CHILD.format(repo=repo, G=args.grid, B=args.batch,
                       STEPS=args.steps)
    rows = []
    for n_dev in (1, 2, 4, 8):
        env = dict(os.environ,
                   XLA_FLAGS=(os.environ.get("XLA_FLAGS", "") +
                              f" --xla_force_host_platform_device_count={n_dev}"),
                   JAX_PLATFORMS="cpu")
        t0 = time.time()
        r = subprocess.run([sys.executable, "-c", src], env=env,
                           capture_output=True, text=True, timeout=1800)
        if r.returncode != 0:
            print(r.stderr[-2000:], file=sys.stderr)
            raise SystemExit(f"child with {n_dev} devices failed")
        row = json.loads(r.stdout.strip().splitlines()[-1])
        row["wall_s"] = round(time.time() - t0, 1)
        rows.append(row)
        print(f"[scaling] {n_dev} devices: {row['steps_per_s']:.2f} steps/s "
              f"(step {row['step_s']*1e3:.1f} ms)", flush=True)

    # the sharded program must compute the same result on every mesh size
    fps = [r["fingerprint"] for r in rows]
    spread = max(fps) - min(fps)
    assert spread < 1e-5 * max(abs(f) for f in fps), fps

    base = rows[0]["step_s"]
    out = {
        "config": {"grid_points": args.grid, "batch_size": args.batch,
                   "steps": args.steps,
                   "note": "virtual CPU mesh on a single physical core: "
                           "measures sharding overhead shape, not speedup"},
        "rows": [{**r, "step_time_vs_1dev": round(r["step_s"] / base, 3)}
                 for r in rows],
        "output_fingerprint_spread": spread,
    }
    dest = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "SHARDED_GRID_SCALING.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[done] wrote {dest}", flush=True)


if __name__ == "__main__":
    main()
