"""Smooth architecture + grid-searched coefficients: improving the
reference's own headline D4IC model.

The round-5 grid search (experiments/d4ic_grid_search.py) selected
(gen_lr 2e-3, ADJ_L1 0.1, COS_SIM 0.1) for the non-Smooth BSCgs1
architecture, lifting it 0.178 -> 0.285 HSNR. The reference's actual
headline D4IC model is the Smooth "Parsim" variant (BSCgs4ParsimSmo0,
0.315 +/- 0.061 on the analog), whose architecture the coefficient grid
cannot reach (different gen_hidden/embedder shapes cannot share one vmapped
program). This experiment applies the searched coefficients to the Smooth
architecture — the composition the reference's own gs1 -> gs4 progression
suggests — and scores it in the ACCURACY_D4IC setup (3 SNR tiers x 3 folds
through the real driver).

Writes experiments/D4IC_SMOOTH_SEARCHED.json.

Run:  python experiments/d4ic_smooth_searched.py <workdir> [--smoke]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

jax.config.update("jax_platforms", "cpu")

from accuracy_parity_d4ic import SMOOTH_ARGS  # noqa: E402
from d4ic_grid_search import (  # noqa: E402
    TIERS, curate_tier_fold, pooled_offdiag)
from redcliff_tpu.eval.cross_alg import (  # noqa: E402
    evaluate_algorithm_on_fold, find_run_directory)
from redcliff_tpu.train.driver import set_up_and_run_experiments  # noqa: E402
from redcliff_tpu.utils.config import load_true_gc_factors  # noqa: E402

# the round-5 searched coefficients (D4IC_GRID_SEARCH.json selected point),
# applied to the Smooth architecture: gen_lr 5e-4 -> 2e-3 and COS_SIM
# 1.0 -> 0.1 (ADJ_L1 was already 0.1 in the Smooth config)
SEARCHED = dict(SMOOTH_ARGS, gen_lr="0.002", FACTOR_COS_SIM_COEFF="0.1",
                ADJ_L1_REG_COEFF="0.1")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("workdir")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--folds", type=int, default=3)
    args = ap.parse_args()
    base = os.path.abspath(args.workdir) + ("_smoke" if args.smoke else "")
    os.makedirs(base, exist_ok=True)
    n_train, n_val = (24, 8) if args.smoke else (120, 30)

    margs = dict(SEARCHED)
    if args.smoke:
        margs.update(max_iter="12", num_pretrain_epochs="4",
                     num_acclimation_epochs="4", check_every="2")
    margs_file = os.path.join(
        base, "REDCLIFF_S_CMLP_Smooth_searched_cached_args.txt")
    with open(margs_file, "w") as f:
        json.dump(margs, f)

    tiers = TIERS if not args.smoke else ("HSNR",)
    rows = {}
    for snr in tiers:
        stats_by_fold = []
        for fold in range(args.folds):
            dargs = curate_tier_fold(base, snr, fold, n_train, n_val)
            save_root = os.path.join(base, f"runs_{snr}")
            os.makedirs(save_root, exist_ok=True)
            t0 = time.time()
            set_up_and_run_experiments(
                {"save_root_path": save_root}, [margs_file], [dargs],
                possible_model_types=["REDCLIFF_S_CMLP_Smooth_searched"],
                possible_data_sets=[f"data_fold{fold}"], task_id=1)
            print(f"[{snr}] fold {fold}: {time.time()-t0:.1f}s", flush=True)
            run_dir = find_run_directory(save_root, "data", fold)
            stats_by_fold.append(evaluate_algorithm_on_fold(
                run_dir, "REDCLIFF_S_CMLP", load_true_gc_factors(dargs)))
        rows[snr] = pooled_offdiag(stats_by_fold)
        print(f"[{snr}] optF1 {rows[snr]['offdiag_optimal_f1_mean']:.3f} ± "
              f"{rows[snr]['offdiag_optimal_f1_sem']:.3f}", flush=True)

    out = {
        "description": "Smooth (BSCgs4ParsimSmo0) architecture with the "
                       "round-5 grid-searched coefficients, ACCURACY_D4IC "
                       "setup",
        "coefficients": {"gen_lr": 0.002, "ADJ_L1_REG_COEFF": 0.1,
                         "FACTOR_COS_SIM_COEFF": 0.1},
        "folds": args.folds, "smoke": bool(args.smoke),
        "rows": rows,
        "round4_smooth_transcribed": {"HSNR": 0.315, "MSNR": 0.319,
                                      "LSNR": 0.211},
        "round5_nonsmooth_searched": {"HSNR": 0.285, "MSNR": 0.280,
                                      "LSNR": 0.229},
    }
    dest = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "D4IC_SMOOTH_SEARCHED.json" if not args.smoke
                        else "D4IC_SMOOTH_SEARCHED_smoke.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[done] wrote {dest}", flush=True)


if __name__ == "__main__":
    main()
