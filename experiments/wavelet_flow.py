"""The wavelet-decomposed training flow end to end — the TST gsSmooth1 shape.

The reference's TST/LFP headline family is configured by
/root/reference/train/REDCLIFF_S_CMLP_tst100hzRerun1024AvgReg_gsSmooth1.py:
the Smooth REDCLIFF variant, DGCNN embedder, 9 factors with 3 supervised
(the TST task's 3 behavioral states), 300-epoch schedule with 100 pretrain +
100 acclimation — and the repo's wavelet pathway (stationary wavelet
decomposition stored per sample, signal_format "wavelet_decomp", the
4-band-per-channel ranking mask and channel condensation of
ref models/cmlp.py:62-82,169-199) exists for exactly this family, though no
shipped cached-args file enables it. No experiment in THIS build had ever
exercised the wavelet flow either (VERDICT r4 missing #2); this one runs it:

1. curate a synthetic LFP-analog with the TST structure: 3 labeled states
   (num_factors axis of the generator), recording length 128 (divisible by
   2**3 as swt requires; the real TST windows are 1024 steps);
2. train through the REAL array-task driver, wavelet_level=3 (the reference's
   4-wavelets-per-channel configuration, the only one its ranking mask
   defines): REDCLIFF-S Smooth on wavelet_decomp input, the cMLP baseline on
   wavelet_decomp input, and a non-wavelet REDCLIFF-S Smooth control on the
   same folds;
3. score through the eval battery (combine_wavelet_representations=True
   condensed readout — the system-level convention) plus the wavelet-RANKED
   readout variant per run.

Writes experiments/ACCURACY_WAVELET_6_2_3.json.

Run:  python experiments/wavelet_flow.py <workdir> [--smoke] [--folds N]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from accuracy_parity_synsys import CMLP_ARGS, REDCLIFF_ARGS  # noqa: E402
from redcliff_tpu.data.curation import curate_synthetic_fold  # noqa: E402
from redcliff_tpu.eval.cross_alg import (  # noqa: E402
    evaluate_algorithm_on_fold, find_run_directory)
from redcliff_tpu.eval.model_io import load_model_for_eval  # noqa: E402
from redcliff_tpu.eval.stats import three_view_optimal_f1_stats  # noqa: E402
from redcliff_tpu.train.driver import set_up_and_run_experiments  # noqa: E402
from redcliff_tpu.utils.config import load_true_gc_factors  # noqa: E402

OFFDIAG = "key_stats_estGC_normOffDiag_vs_trueGC_normOffDiag"
WAVELET_LEVEL = 3          # 4 bands/channel — the reference mask's domain
RECORDING_LEN = 128        # divisible by 2**3; TST real windows are 1024
NUM_NODES, NUM_EDGES, NUM_STATES = 6, 2, 3

# the TST gsSmooth1 configuration (transcribed), adapted to the analog's
# size: num_factors 9 / 3 supervised exactly as the reference sets for TST's
# 3 behavioral states (ref ..._gsSmooth1_cached_args.txt)
SMOOTH_WAVELET_ARGS = dict(
    REDCLIFF_ARGS,
    num_factors="9", num_supervised_factors="3",
    wavelet_level=str(WAVELET_LEVEL),
    FACTOR_WEIGHT_SMOOTHING_PENALTY_COEFF="25.0",
    ADJ_L1_REG_COEFF="0.1",
)
CMLP_WAVELET_ARGS = dict(CMLP_ARGS, wavelet_level=str(WAVELET_LEVEL))
SMOOTH_CONTROL_ARGS = dict(SMOOTH_WAVELET_ARGS, wavelet_level="None")

MODELS = (
    ("REDCLIFF_S_CMLP_Smooth", SMOOTH_WAVELET_ARGS, "REDCLIFF_Smooth_wav"),
    ("cMLP", CMLP_WAVELET_ARGS, "CMLP_wav"),
    ("REDCLIFF_S_CMLP_SmoothCtl", SMOOTH_CONTROL_ARGS, "REDCLIFF_Smooth_raw"),
)


def ranked_readout_offdiag(run_dir, alg, true_gcs):
    """The wavelet-RANKED condensed readout (rank_wavelets=True), scored with
    the same off-diag statistic; None for non-wavelet runs."""
    model, params = load_model_for_eval(run_dir)[:2]
    cfg = getattr(model, "config", None)
    if getattr(cfg, "wavelet_level", None) is None:
        return None
    if "REDCLIFF" in alg:
        # the battery's list-of-factors readout (eval/gc_estimates.py), with
        # the ranking mask applied
        ests_by_sample = model.gc_as_lists(
            params, gc_est_mode="fixed_factor_exclusive", threshold=False,
            ignore_lag=False, combine_wavelet_representations=True,
            rank_wavelets=True)
        est = np.stack([np.asarray(e, np.float64)
                        for e in ests_by_sample[0]])
    else:
        # generic families return a list of per-factor estimates (length 1)
        est = np.stack([np.asarray(g, np.float64) for g in model.gc(
            params, threshold=False, ignore_lag=False,
            combine_wavelet_representations=True, rank_wavelets=True)])
    f1s = []
    for k, true in enumerate(true_gcs):
        e = est[min(k, est.shape[0] - 1)]
        f1s.append(three_view_optimal_f1_stats(
            np.asarray(e, np.float64), true)[OFFDIAG]["f1"])
    return f1s


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("workdir")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--folds", type=int, default=2)
    args = ap.parse_args()
    base = os.path.abspath(args.workdir) + ("_smoke" if args.smoke else "")
    os.makedirs(base, exist_ok=True)
    n_train, n_val = (24, 8) if args.smoke else (1040, 240)

    model_args = {name: dict(a) for name, a, _ in MODELS}
    if args.smoke:
        for key in ("REDCLIFF_S_CMLP_Smooth", "REDCLIFF_S_CMLP_SmoothCtl"):
            model_args[key].update(max_iter="10", num_pretrain_epochs="3",
                                   num_acclimation_epochs="3",
                                   check_every="2")
        model_args["cMLP"].update(max_iter="8", check_every="2")

    data_args_by_fold = {}
    true_by_fold = {}
    for fold in range(args.folds):
        t0 = time.time()
        fold_dir, _ = curate_synthetic_fold(
            os.path.join(base, "data"), fold_id=fold, num_nodes=NUM_NODES,
            num_lags=2, num_factors=NUM_STATES,
            num_supervised_factors=NUM_STATES,
            num_edges_per_graph=NUM_EDGES,
            num_samples_in_train_set=n_train, num_samples_in_val_set=n_val,
            sample_recording_len=RECORDING_LEN, burnin_period=50,
            label_type_setting="OneHot", noise_type="gaussian",
            noise_level=1.0, folder_name="lfpAnalog6_2_3")
        data_args_by_fold[fold] = os.path.join(
            fold_dir, f"data_fold{fold}_cached_args.txt")
        true_by_fold[fold] = load_true_gc_factors(data_args_by_fold[fold])
        print(f"[curate] fold {fold}: {time.time()-t0:.1f}s", flush=True)

    out = {"dataset": f"synthetic LFP-analog {NUM_NODES}-{NUM_EDGES}-"
                      f"{NUM_STATES}, T={RECORDING_LEN}, OneHot",
           "wavelet_level": WAVELET_LEVEL, "folds": args.folds,
           "smoke": bool(args.smoke), "algorithms": {}}
    for model_type, _, alias in MODELS:
        margs_file = os.path.join(base, f"{model_type}_cached_args.txt")
        with open(margs_file, "w") as f:
            json.dump(model_args[model_type], f)
        save_root = os.path.join(base, "runs", f"{alias}_models")
        os.makedirs(save_root, exist_ok=True)
        pooled, pooled_ranked = [], []
        for fold in range(args.folds):
            t0 = time.time()
            set_up_and_run_experiments(
                {"save_root_path": save_root}, [margs_file],
                [data_args_by_fold[fold]],
                possible_model_types=[model_type],
                possible_data_sets=[f"data_fold{fold}"], task_id=1)
            print(f"[train] {alias} fold {fold}: {time.time()-t0:.1f}s",
                  flush=True)
            run_dir = find_run_directory(save_root, "data", fold)
            # alg dispatch: the Smooth control shares the REDCLIFF readout
            alg = "REDCLIFF_S_CMLP" if "REDCLIFF" in model_type else "CMLP"
            stats = evaluate_algorithm_on_fold(run_dir, alg,
                                               true_by_fold[fold])
            pooled.extend(stats[OFFDIAG]["f1_vals_across_factors"])
            ranked = ranked_readout_offdiag(run_dir, alg,
                                            true_by_fold[fold])
            if ranked is not None:
                pooled_ranked.extend(ranked)
        f1 = np.asarray(pooled, dtype=np.float64)
        row = {"offdiag_optimal_f1_mean": float(f1.mean()),
               "offdiag_optimal_f1_sem": float(
                   f1.std(ddof=1) / np.sqrt(len(f1))) if len(f1) > 1 else 0.0}
        if pooled_ranked:
            r = np.asarray(pooled_ranked, dtype=np.float64)
            row["ranked_offdiag_optimal_f1_mean"] = float(r.mean())
            row["ranked_offdiag_optimal_f1_sem"] = float(
                r.std(ddof=1) / np.sqrt(len(r))) if len(r) > 1 else 0.0
        out["algorithms"][alias] = row
        print(f"[result] {alias}: {row}", flush=True)

    dest = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "ACCURACY_WAVELET_6_2_3.json" if not args.smoke
                        else "ACCURACY_WAVELET_smoke.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[done] wrote {dest}", flush=True)


if __name__ == "__main__":
    main()
