#!/bin/bash
# Multi-system complexity-banded synSys study (the paper's separating result).
#
# Runs the reference-scale accuracy study over systems spanning the paper's
# complexity bands (complexity = (C^2-C)/E; Low <=7 < Moderate <=13 < High),
# drawn from the reference's synSysIG1030 sweep matrix
# (/root/reference/evaluate/plotCrossExpSummaries_...synSysIG1030...py:67-115):
#   6-2-2   High     (15.0)
#   12-11-2 Moderate (12.0)
#   3-1-2   Low      (6.0)
#   6-2-3   High     (15.0, 3 factors)
#   6-4-2   Moderate (7.5)
#   6-6-2   Low      (5.0)
# ordered so every band is covered as early as possible. Each system gets its
# own workdir (run-dir discovery is per-system); eval trees are assembled into
# one root for the banded condenser as systems complete.
#
# Usage: experiments/run_banded_sweep.sh [BASE=/tmp/banded] [FOLDS=3]
set -u
BASE="${1:-/tmp/banded}"
FOLDS="${2:-3}"
REPO="$(cd "$(dirname "$0")/.." && pwd)"
mkdir -p "$BASE" "$BASE/all/evals"

for sys in 6-2-2 12-11-2 3-1-2 6-2-3 6-4-2 6-6-2; do
    echo "[sweep] $(date -u +%H:%M:%S) starting system $sys" | tee -a "$BASE/sweep.log"
    python "$REPO/experiments/accuracy_parity_synsys.py" "$BASE/sys_$sys" \
        --folds "$FOLDS" --algs ref --system "$sys" --dynamic \
        > "$BASE/log_$sys.txt" 2>&1
    rc=$?
    echo "[sweep] $(date -u +%H:%M:%S) system $sys rc=$rc" | tee -a "$BASE/sweep.log"
    # assemble what exists so far and re-condense (partial results stay usable)
    cp -r "$BASE/sys_$sys/evals/." "$BASE/all/evals/" 2>/dev/null
    python "$REPO/experiments/banded_condense.py" "$BASE/all" \
        >> "$BASE/sweep.log" 2>&1
done
echo "[sweep] $(date -u +%H:%M:%S) done" | tee -a "$BASE/sweep.log"
