"""Condense the multi-system synSys study into the paper's separating result.

Walks the shared eval root produced by repeated runs of
``accuracy_parity_synsys.py --system N-E-F`` (one eval tree per system),
then:

1. runs the complexity-banded cross-experiment analysis
   (eval/analysis.run_cross_experiment_analysis — the rebuild of the
   reference's plotCrossExpSummaries_...synSysIG1030... driver): per-band
   per-algorithm absolute optimal-F1 and the pairwise per-factor improvement
   of REDCLIFF-S over every baseline;
2. aggregates the per-system dynamic-readout summaries (state-score tracking
   + conditional-GC dynamics, eval/dynamic_readout.py) into one table;
3. writes experiments/BANDED_SYNSYS.json with the banded improvement table,
   the dynamic-readout table, and per-system detail — the artifact behind
   BASELINE.md's separating-result section.

Run:  python experiments/banded_condense.py <workdir> [--out FILE]
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from redcliff_tpu.eval.analysis import (  # noqa: E402
    run_cross_experiment_analysis)
from redcliff_tpu.eval.stats import summarize_values  # noqa: E402

BASELINE_ALG = "REDCLIFF_S_CMLP"


def _mean_sem(vals):
    s = summarize_values(vals)
    return s["mean"], s["mean_std_err"]


def band_improvement_table(condensed, by_category):
    """{band: {alg: {mean, sem, n_systems, per_system}}} — the mean across
    systems in the band of the per-system mean per-factor improvement of
    REDCLIFF-S over each algorithm (ref plotCross...py:160-262 semantics:
    improvements are baseline_vals - alg_vals per factor)."""
    out = {}
    for band, sys_keys in by_category.items():
        alg_accum = {}
        for key in sys_keys:
            imps = condensed[key]["improvements"] or {}
            for alg, st in imps.items():
                if alg == BASELINE_ALG:
                    continue
                alg_accum.setdefault(alg, {})[key] = st["mean"]
        out[band] = {}
        for alg, per_sys in alg_accum.items():
            vals = [v for v in per_sys.values()
                    if v is not None and np.isfinite(v)]
            if not vals:
                continue
            mean, sem = _mean_sem(vals)
            out[band][alg] = {
                "mean_improvement": mean,
                "sem": sem,
                "n_systems": len(vals),
                "per_system": {k: float(v) for k, v in per_sys.items()},
            }
    return out


def collect_dynamic_summaries(eval_root):
    """{system_key: {alg: {metric: {mean, sem, n}}}} from the per-system
    dynamic_readout_summary.json files."""
    out = {}
    for sys_key in sorted(os.listdir(eval_root)):
        p = os.path.join(eval_root, sys_key, "dynamic",
                         "dynamic_readout_summary.json")
        if os.path.isfile(p):
            with open(p) as f:
                out[sys_key] = json.load(f)
    return out


def aggregate_dynamic(dyn_by_system, systems=None):
    """{alg: {metric: {mean, sem, n_systems}}} across systems (mean of the
    per-system means; SEM across systems). ``systems`` restricts the
    aggregation (e.g. to one complexity band)."""
    accum = {}
    for sys_key, stats in dyn_by_system.items():
        if systems is not None and sys_key not in systems:
            continue
        for alg, metrics in stats.items():
            if alg.startswith("_") or not isinstance(metrics, dict):
                continue  # summary metadata (_conventions), not an algorithm
            for metric, st in metrics.items():
                # scalar convention fields (scoring_window) ride alongside
                # the {mean, sem, n} metric dicts
                if not isinstance(st, dict) or st.get("mean") is None:
                    continue
                accum.setdefault(alg, {}).setdefault(metric, []).append(
                    st["mean"])
    out = {}
    for alg, metrics in accum.items():
        out[alg] = {}
        for metric, vals in metrics.items():
            mean, sem = _mean_sem(vals)
            out[alg][metric] = {"mean": mean, "sem": sem,
                                "n_systems": len(vals)}
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("workdir")
    ap.add_argument("--out", default=None)
    ap.add_argument("--plot", action="store_true")
    args = ap.parse_args()
    eval_root = os.path.join(args.workdir, "evals")
    save_root = os.path.join(args.workdir, "banded_analysis")

    res = run_cross_experiment_analysis(
        eval_root, save_root, baseline_alg=BASELINE_ALG, plot=args.plot)
    bands = band_improvement_table(res["condensed"], res["by_category"])
    dyn_by_system = collect_dynamic_summaries(eval_root)

    per_system = {}
    for key, entry in res["condensed"].items():
        per_system[key] = {
            "complexity": entry["complexity"],
            "band": res["system_details"][key]["complexity_category"],
            "alg_optf1": {a: {"mean": st["mean"], "sem": st["sem"]}
                          for a, st in entry["alg_stats"].items()},
            "improvements_of_redcliff": {
                a: st for a, st in (entry["improvements"] or {}).items()
                if a != BASELINE_ALG},
        }

    out = {
        "baseline_alg": BASELINE_ALG,
        "paradigm": "key_stats_estGC_normOffDiag_vs_trueGC_normOffDiag / f1",
        "banded_improvement": bands,
        "dynamic_readouts_by_system": dyn_by_system,
        "dynamic_readouts_aggregate": aggregate_dynamic(dyn_by_system),
        "dynamic_readouts_by_band": {
            band: aggregate_dynamic(dyn_by_system, systems=set(keys))
            for band, keys in res["by_category"].items() if keys},
        "per_system": per_system,
        "by_category": res["by_category"],
    }
    dest = args.out or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BANDED_SYNSYS.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[done] wrote {dest}", flush=True)
    for band in ("High", "Moderate", "Low"):
        for alg, st in bands.get(band, {}).items():
            print(f"[band {band}] REDCLIFF-S vs {alg}: "
                  f"{st['mean_improvement']:+.3f} ± {st['sem']:.3f} "
                  f"({st['n_systems']} systems)", flush=True)


if __name__ == "__main__":
    main()
