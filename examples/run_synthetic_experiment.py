"""End-to-end example: the reference's whole experiment flow in one script.

Curates a small synthetic sVAR benchmark (the test strategy's ground-truth
oracle), trains a REDCLIFF-S model and a cMLP baseline through the
array-task driver (the SLURM-compatible entry point), evaluates everything
through the filesystem contract (cross-algorithm comparison, grid
selection), and regenerates the analysis report — the same layers a full
D4IC/TST experiment uses, at toy scale.

Run on CPU (about a minute):

    python examples/run_synthetic_experiment.py /tmp/redcliff_demo

On a TPU chip, drop the platform override below; coefficient grids can then
train dozens of hyperparameter points concurrently via
``redcliff_tpu.train.run_coefficient_grid`` (see README "Multi-host").
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("REDCLIFF_EXAMPLE_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")  # the example is CPU-sized

from redcliff_tpu.data.curation import curate_synthetic_fold  # noqa: E402
from redcliff_tpu.eval.analysis import generate_analysis_report  # noqa: E402
from redcliff_tpu.eval.cross_alg import (  # noqa: E402
    run_cross_algorithm_comparison)
from redcliff_tpu.eval.grid_selection import select_best_models  # noqa: E402
from redcliff_tpu.train.driver import set_up_and_run_experiments  # noqa: E402
from redcliff_tpu.utils.config import load_true_gc_factors  # noqa: E402

# toy-scale hyperparameters shared by both model families
_SHARED_ARGS = {
    "num_sims": "1", "embed_hidden_sizes": "[8]", "batch_size": "8",
    "gen_eps": "0.0001", "gen_weight_decay": "0.0", "max_iter": "8",
    "lookback": "3", "check_every": "1", "verbose": "0",
    "output_length": "1", "wavelet_level": "None", "gen_hidden": "[12]",
    "gen_lr": "0.005", "gen_lag_and_input_len": "3",
    "FORECAST_COEFF": "1.0", "ADJ_L1_REG_COEFF": "0.001",
    "DAGNESS_REG_COEFF": "0.0", "DAGNESS_LAG_COEFF": "0.0",
    "DAGNESS_NODE_COEFF": "0.0",
}
REDCLIFF_ARGS = {
    **_SHARED_ARGS,
    "embed_lag": "4", "num_factors": "2", "num_supervised_factors": "2",
    "use_sigmoid_restriction": "1",
    "factor_score_embedder_type": "Vanilla_Embedder",
    "primary_gc_est_mode": "fixed_factor_exclusive",
    "forward_pass_mode": "apply_factor_weights_at_each_sim_step",
    "FACTOR_SCORE_COEFF": "10.0",
    "FACTOR_WEIGHT_L1_COEFF": "0.01", "FACTOR_COS_SIM_COEFF": "0.01",
    "training_mode": "combined", "embed_lr": "0.005",
    "embed_eps": "0.0001", "embed_weight_decay": "0.0",
    "num_pretrain_epochs": "0", "num_acclimation_epochs": "0",
    "prior_factors_path": "None", "cost_criteria": "combo",
    "unsupervised_start_index": "0", "max_factor_prior_batches": "5",
    "stopping_criteria_forecast_coeff": "1.0",
    "stopping_criteria_factor_coeff": "1.0",
    "stopping_criteria_cosSim_coeff": "1.0", "deltaConEps": "0.1",
    "in_degree_coeff": "1.0", "out_degree_coeff": "1.0",
}
CMLP_ARGS = dict(_SHARED_ARGS)


def main(base):
    os.makedirs(base, exist_ok=True)

    # 1. curate: shards + cached-args with stringified true graphs --------
    print("[1/5] curating the synthetic benchmark fold")
    fold_dir, _ = curate_synthetic_fold(
        os.path.join(base, "data"), fold_id=0, num_nodes=5, num_factors=2,
        num_supervised_factors=2, num_samples_in_train_set=48,
        num_samples_in_val_set=16, sample_recording_len=30,
        folder_name="demoSys")
    data_args = os.path.join(fold_dir, "data_fold0_cached_args.txt")

    # 2. train both model families via the array-task driver --------------
    roots = {}
    for model_type, args, fname, alias in (
            ("REDCLIFF_S_CMLP", REDCLIFF_ARGS,
             "REDCLIFF_S_CMLP_demo_cached_args.txt", "REDCLIFF_S_CMLP"),
            ("cMLP", CMLP_ARGS, "cMLP_demo_cached_args.txt", "CMLP")):
        print(f"[2/5] training {model_type}")
        margs = os.path.join(base, fname)
        with open(margs, "w") as f:
            json.dump(args, f)
        save_root = os.path.join(base, "runs", f"{alias}_models")
        os.makedirs(save_root, exist_ok=True)
        set_up_and_run_experiments(
            {"save_root_path": save_root}, [margs], [data_args],
            possible_model_types=[model_type],
            possible_data_sets=["data_fold0"], task_id=1)
        roots[alias] = save_root

    # 3. cross-algorithm evaluation through the filesystem contract -------
    print("[3/5] cross-algorithm evaluation")
    true_gcs = load_true_gc_factors(data_args)
    eval_root = os.path.join(base, "evals")
    # algorithms passed explicitly: root discovery matches names against
    # full paths, so a base dir containing a model name would otherwise
    # make every root ambiguous
    full = run_cross_algorithm_comparison(
        list(roots.values()), {"data_fold0": {0: true_gcs}},
        os.path.join(eval_root, "numF2_numSF2_numN5_demo_data"),
        num_folds=1, plot=True,
        algorithms=["REDCLIFF_S_CMLP", "CMLP"])
    paradigm = "key_stats_estGC_normOffDiag_vs_trueGC_normOffDiag"
    for alg, stats in full["data_fold0"][paradigm].items():
        print(f"    {alg}: off-diag optimal F1 = "
              f"{stats['f1_mean_across_factors']:.3f} "
              f"± {stats['f1_mean_std_err_across_factors']:.3f}")

    # 4. grid-search selection over the run metadata ----------------------
    print("[4/5] grid selection")
    best = select_best_models(roots["REDCLIFF_S_CMLP"],
                              selection_criteria=("forecasting_loss",
                                                  "factor_loss"))
    print("    best run by forecasting loss:",
          best["forecasting_loss"]["best_run"])

    # 5. one-command analysis report --------------------------------------
    print("[5/5] analysis report")
    report = generate_analysis_report(eval_root,
                                      os.path.join(base, "report"))
    print("    artifacts:", sorted(os.listdir(os.path.join(base, "report")))[:5],
          "...")
    print(f"done — everything under {base}")
    return report


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "/tmp/redcliff_demo")
