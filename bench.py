"""Benchmark: REDCLIFF-S grid-training throughput on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

value        — training-window throughput (windows/sec/chip) of the vmapped
               hyperparameter-grid REDCLIFF-S train step at the headline grid
               size (G grid points trained simultaneously — this framework's
               execution model).
vs_baseline  — speedup over the reference's execution pattern on the SAME chip:
               one jit'd train step per grid point, stepped sequentially
               (the SLURM-array one-process-per-point pattern of
               ref train/REDCLIFF_S_CMLP_d4IC_BSCgs1.py:66-108, with each
               point's compute already tensorized — i.e. this understates the
               true advantage over the reference's per-factor Python loops).

Extra context fields (so "fast" is judgeable against hardware capability):
  flops_per_step — XLA cost-analysis FLOPs of one compiled grid step
  mfu_pct        — implied chip utilization vs the device's dense peak
  g_scaling      — {G: windows/s} curve over grid sizes
  device / error — backend actually used; error is non-null if the TPU was
                   unavailable and the bench fell back to CPU

The reference repository publishes no benchmark numbers (BASELINE.md), so the
sequential-vs-grid ratio on identical hardware is the honest comparable.

Hardened: backend init failure is caught and retried; the JSON line is ALWAYS
emitted (with an "error" field when measurement was impossible).
"""
import json
import sys
import time
import traceback

import numpy as np

# dense peak FLOPs/s per chip, bf16/fp-dense (public TPU specs); fp32 runs at
# a lower peak on MXU — mfu_pct is therefore a conservative lower bound
PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def _emit(payload):
    print(json.dumps(payload))
    sys.stdout.flush()


def _probe_accelerator(timeout_s=240.0):
    """Check in a KILLABLE subprocess whether the accelerator backend can
    initialize: a hung tunnel (observed with the axon TPU backend) would
    otherwise block this process in a C call forever."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); print(d[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s)
        if r.returncode == 0:
            return True, r.stdout.strip()
        return False, f"probe rc={r.returncode}: {r.stderr.strip()[-300:]}"
    except subprocess.TimeoutExpired:
        return False, f"accelerator backend init hung > {timeout_s:.0f}s"
    except Exception as e:
        return False, f"probe failed: {e!r}"


def _init_backend():
    """Initialize a jax backend; probe the accelerator in a subprocess first
    (retry once), then fall back to CPU. Returns (jax, devices, error_or_None)."""
    ok, info = _probe_accelerator()
    if not ok:
        print(f"bench: accelerator probe failed ({info}); retrying",
              file=sys.stderr, flush=True)
        time.sleep(5.0)
        ok, info = _probe_accelerator()

    import jax

    if not ok:
        err = f"accelerator backend unavailable ({info}); ran on cpu"
        try:
            jax.config.update("jax_platforms", "cpu")
            return jax, jax.devices(), err
        except Exception as e:  # pragma: no cover - no backend at all
            return None, None, f"no jax backend available: {info!r} / {e!r}"
    try:
        return jax, jax.devices(), None
    except RuntimeError as e:
        # probe succeeded but in-process init failed; last resort: cpu
        try:
            jax.config.update("jax_platforms", "cpu")
            return jax, jax.devices(), f"backend init failed ({e}); ran on cpu"
        except Exception as e2:
            return None, None, f"no jax backend available: {e!r} / {e2!r}"


def _flops_of(jax, compiled):
    """XLA cost-analysis FLOPs of a compiled computation (None if unavailable)."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        f = ca.get("flops")
        return float(f) if f and f > 0 else None
    except Exception:
        return None


def _model_config():
    from redcliff_tpu.models.redcliff import RedcliffSCMLPConfig

    # D4IC-like shapes: 10 channels, gen_lag 4, embed_lag 16 (ref cached args)
    return RedcliffSCMLPConfig(
        num_chans=10, gen_lag=4, gen_hidden=(32,), embed_lag=16,
        embed_hidden_sizes=(0,), num_factors=5, num_supervised_factors=5,
        factor_score_coeff=2.0, factor_cos_sim_coeff=0.05,
        factor_weight_l1_coeff=0.01, adj_l1_reg_coeff=0.001,
        factor_score_embedder_type="DGCNN", dgcnn_num_graph_conv_layers=3,
        dgcnn_num_hidden_nodes=100,
        primary_gc_est_mode="conditional_factor_fixed_embedder",
        num_sims=2, training_mode="combined",
    )


def _bench_grid(jax, model, G, B, steps):
    """Throughput (windows/s) + FLOPs/step of the G-point vmapped grid step."""
    from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig

    cfg = model.config
    spec = GridSpec(points=[
        {"gen_lr": 1e-3 * (1 + (i % 4)), "adj_l1_reg_coeff": 1e-3 * (i % 2),
         "factor_cos_sim_coeff": 0.05 * (i % 3)}
        for i in range(G)
    ])
    runner = RedcliffGridRunner(model, RedcliffTrainConfig(batch_size=B), spec,
                                mesh=None)
    rng = np.random.default_rng(0)
    T = cfg.max_lag + cfg.num_sims
    X = jax.device_put(rng.normal(size=(B, T, cfg.num_chans)).astype(np.float32))
    Y = jax.device_put(
        rng.uniform(size=(B, cfg.num_supervised_factors, 1)).astype(np.float32))

    params, optA, optB = runner.init_grid(jax.random.PRNGKey(0))
    coeffs = runner.coeffs
    active = jax.numpy.ones((G,), dtype=bool)
    step = runner._steps["combined"]

    # AOT-compile ONCE and time through the compiled object (calling the jit
    # wrapper after .lower().compile() would compile a second time — the jit
    # executable cache is not populated by AOT compilation)
    compiled = step.lower(params, optA, optB, coeffs, active, X, Y).compile()
    flops = _flops_of(jax, compiled)

    p, a, b, _ = compiled(params, optA, optB, coeffs, active, X, Y)  # warm dispatch
    jax.block_until_ready(p)
    t0 = time.perf_counter()
    for _ in range(steps):
        p, a, b, _ = compiled(p, a, b, coeffs, active, X, Y)
    jax.block_until_ready(p)
    dt = time.perf_counter() - t0
    return G * B * steps / dt, flops, dt / steps, runner, (p, a, b, coeffs, X, Y)


def _bench_sequential(jax, model, runner, grid_state, G, B, steps):
    """Reference execution pattern: one jit'd step per point, run sequentially."""
    import optax

    params, optA, optB, coeffs, X, Y = grid_state
    point_params = jax.tree.map(lambda x: x[0], params)
    point_optA = jax.tree.map(
        lambda x: x[0] if hasattr(x, "ndim") and x.ndim > 0 else x, optA)
    point_optB = jax.tree.map(
        lambda x: x[0] if hasattr(x, "ndim") and x.ndim > 0 else x, optB)
    point_coeffs = {k: v[0] for k, v in coeffs.items()}

    def single_step(params, a_state, b_state, coeffs, X, Y):
        def loss_fn(pp):
            return model.loss_for_phase(pp, X, Y, "combined", coeffs=coeffs,
                                        need_gc=True, need_gc_lagged=True)
        (_, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updA, a_state = runner.optA.update(grads["embedder"], a_state)
        updB, b_state = runner.optB.update(grads["factors"], b_state)
        params = dict(
            params,
            embedder=optax.apply_updates(
                params["embedder"],
                jax.tree.map(lambda u: -coeffs["embed_lr"] * u, updA)),
            factors=optax.apply_updates(
                params["factors"],
                jax.tree.map(lambda u: -coeffs["gen_lr"] * u, updB)),
        )
        return params, a_state, b_state

    sstep = jax.jit(single_step, donate_argnums=(0, 1, 2))
    pp, aa, bb = sstep(point_params, point_optA, point_optB, point_coeffs, X, Y)
    jax.block_until_ready(pp)
    t0 = time.perf_counter()
    for _ in range(steps):
        for _ in range(G):  # one sequential step per grid point, like a job array
            pp, aa, bb = sstep(pp, aa, bb, point_coeffs, X, Y)
    jax.block_until_ready(pp)
    dt = time.perf_counter() - t0
    return G * B * steps / dt


def main():
    jax, devices, err = _init_backend()
    if jax is None:
        _emit({"metric": "redcliff_s_grid_train_windows_per_sec_per_chip",
               "value": None, "unit": "windows/s/chip", "vs_baseline": None,
               "error": err})
        return

    from redcliff_tpu.models.redcliff import RedcliffSCMLP

    dev_kind = devices[0].device_kind
    platform = devices[0].platform
    on_cpu = platform == "cpu"
    model = RedcliffSCMLP(_model_config())
    B = 64
    # headline = the largest grid the bench sweeps: the framework's execution
    # model is "batch as many grid points as fit", and G=64 still fits this
    # model in a fraction of HBM (G-scaling below shows near-linear gains)
    G_HEAD = 16 if on_cpu else 64
    steps = 8 if on_cpu else 30

    # --- G-scaling curve + headline measurement ---------------------------
    # headline first so a wall-clock-budget bailout still yields the number
    t_start = time.perf_counter()
    budget_s = 300.0
    g_scaling = {}
    headline = None
    # each extra G costs one compile (~40s on TPU); keep the sweep small
    # enough that the whole bench stays well under the driver's time budget
    extra_g = (1, 4) if on_cpu else (1, 4, 256)
    for G in (G_HEAD,) + extra_g:
        if G != G_HEAD and time.perf_counter() - t_start > budget_s:
            print(f"bench: skipping G={G} (wall-clock budget)", file=sys.stderr)
            continue
        print(f"bench: measuring G={G}", file=sys.stderr, flush=True)
        wps, flops, step_s, runner, state = _bench_grid(jax, model, G, B, steps)
        g_scaling[str(G)] = round(wps, 1)
        if G == G_HEAD:
            headline = (wps, flops, step_s, runner, state)

    grid_wps, flops_per_step, step_seconds, runner, grid_state = headline
    seq_steps = max(steps // 3, 3)
    seq_wps = _bench_sequential(jax, model, runner, grid_state, G_HEAD, B, seq_steps)

    peak = PEAK_FLOPS.get(dev_kind)
    mfu = (100.0 * flops_per_step / step_seconds / peak
           if (flops_per_step and peak and not on_cpu) else None)

    _emit({
        "metric": "redcliff_s_grid_train_windows_per_sec_per_chip",
        "value": round(grid_wps, 1),
        "unit": "windows/s/chip",
        "vs_baseline": round(grid_wps / seq_wps, 2),
        "device": dev_kind,
        "platform": platform,
        "grid_points": G_HEAD,
        "batch_size": B,
        "flops_per_step": flops_per_step,
        "mfu_pct": round(mfu, 2) if mfu is not None else None,
        "g_scaling": g_scaling,
        "error": err,
    })


if __name__ == "__main__":
    try:
        main()
    except Exception as e:
        traceback.print_exc(file=sys.stderr)
        _emit({"metric": "redcliff_s_grid_train_windows_per_sec_per_chip",
               "value": None, "unit": "windows/s/chip", "vs_baseline": None,
               "error": f"{type(e).__name__}: {e}"})
        sys.exit(0)
