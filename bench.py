"""Benchmark: REDCLIFF-S grid-training throughput on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

value        — training-window throughput (windows/sec/chip) of the vmapped
               hyperparameter-grid REDCLIFF-S train step (G grid points trained
               simultaneously — this framework's execution model).
vs_baseline  — speedup over the reference's execution pattern on the SAME chip:
               one jit'd train step per grid point, stepped sequentially
               (the SLURM-array one-process-per-point pattern of
               ref train/REDCLIFF_S_CMLP_d4IC_BSCgs1.py:66-108, with each
               point's compute already tensorized — i.e. this understates the
               true advantage over the reference's per-factor Python loops).

The reference repository publishes no benchmark numbers (BASELINE.md), so the
sequential-vs-grid ratio on identical hardware is the honest comparable.
"""
import json
import time

import numpy as np


def main():
    import jax

    from redcliff_tpu.models.redcliff import RedcliffSCMLP, RedcliffSCMLPConfig
    from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig

    # D4IC-like shapes: 10 channels, gen_lag 4, embed_lag 16 (ref cached args)
    cfg = RedcliffSCMLPConfig(
        num_chans=10, gen_lag=4, gen_hidden=(32,), embed_lag=16,
        embed_hidden_sizes=(0,), num_factors=5, num_supervised_factors=5,
        factor_score_coeff=2.0, factor_cos_sim_coeff=0.05,
        factor_weight_l1_coeff=0.01, adj_l1_reg_coeff=0.001,
        factor_score_embedder_type="DGCNN", dgcnn_num_graph_conv_layers=3,
        dgcnn_num_hidden_nodes=100,
        primary_gc_est_mode="conditional_factor_fixed_embedder",
        num_sims=2, training_mode="combined",
    )
    model = RedcliffSCMLP(cfg)
    G = 16
    B = 64
    steps = 30
    spec = GridSpec(points=[
        {"gen_lr": 1e-3 * (1 + (i % 4)), "adj_l1_reg_coeff": 1e-3 * (i % 2),
         "factor_cos_sim_coeff": 0.05 * (i % 3)}
        for i in range(G)
    ])
    tc = RedcliffTrainConfig(batch_size=B)
    runner = RedcliffGridRunner(model, tc, spec, mesh=None)

    rng = np.random.default_rng(0)
    T = cfg.max_lag + cfg.num_sims
    X = rng.normal(size=(B, T, cfg.num_chans)).astype(np.float32)
    Y = rng.uniform(size=(B, cfg.num_supervised_factors, 1)).astype(np.float32)
    Xd, Yd = jax.device_put(X), jax.device_put(Y)

    params, optA, optB = runner.init_grid(jax.random.PRNGKey(0))
    coeffs = runner.coeffs
    step = runner._steps["combined"]

    # --- grid-vmapped path ------------------------------------------------
    p, a, b, _ = step(params, optA, optB, coeffs, Xd, Yd)  # compile
    jax.block_until_ready(p)
    t0 = time.perf_counter()
    for _ in range(steps):
        p, a, b, _ = step(p, a, b, coeffs, Xd, Yd)
    jax.block_until_ready(p)
    grid_time = time.perf_counter() - t0
    grid_wps = G * B * steps / grid_time

    # --- sequential per-point path (reference execution pattern) ----------
    point_params = jax.tree.map(lambda x: x[0], params)
    point_optA = jax.tree.map(lambda x: x[0] if hasattr(x, "ndim") and x.ndim > 0 else x, optA)
    point_optB = jax.tree.map(lambda x: x[0] if hasattr(x, "ndim") and x.ndim > 0 else x, optB)
    point_coeffs = {k: v[0] for k, v in coeffs.items()}

    import optax

    def single_step(params, a_state, b_state, coeffs, X, Y):
        def loss_fn(pp):
            return model.loss_for_phase(pp, X, Y, "combined", coeffs=coeffs,
                                        need_gc=True, need_gc_lagged=True)
        (_, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updA, a_state = runner.optA.update(grads["embedder"], a_state)
        updB, b_state = runner.optB.update(grads["factors"], b_state)
        params = dict(
            params,
            embedder=optax.apply_updates(
                params["embedder"],
                jax.tree.map(lambda u: -coeffs["embed_lr"] * u, updA)),
            factors=optax.apply_updates(
                params["factors"],
                jax.tree.map(lambda u: -coeffs["gen_lr"] * u, updB)),
        )
        return params, a_state, b_state

    sstep = jax.jit(single_step)
    pp, aa, bb = sstep(point_params, point_optA, point_optB, point_coeffs, Xd, Yd)
    jax.block_until_ready(pp)
    seq_steps = max(steps // 3, 5)
    t0 = time.perf_counter()
    for _ in range(seq_steps):
        for _ in range(G):  # one sequential step per grid point, like a job array
            pp, aa, bb = sstep(pp, aa, bb, point_coeffs, Xd, Yd)
    jax.block_until_ready(pp)
    seq_time = time.perf_counter() - t0
    seq_wps = G * B * seq_steps / seq_time

    print(json.dumps({
        "metric": "redcliff_s_grid_train_windows_per_sec_per_chip",
        "value": round(grid_wps, 1),
        "unit": "windows/s/chip",
        "vs_baseline": round(grid_wps / seq_wps, 2),
    }))


if __name__ == "__main__":
    main()
