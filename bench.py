"""Benchmark: REDCLIFF-S grid-training throughput on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

value        — training-window throughput (windows/sec/chip) of the vmapped
               hyperparameter-grid REDCLIFF-S train step at the headline grid
               size, driven through the lax.scan k-batch dispatch (one host
               dispatch per k batches — the framework's production execution
               mode; parallel/grid.py scan_batches).
vs_baseline  — speedup over the reference's execution pattern on the SAME chip:
               one jit'd train step per grid point, stepped sequentially
               (the SLURM-array one-process-per-point pattern of
               ref train/REDCLIFF_S_CMLP_d4IC_BSCgs1.py:66-108, with each
               point's compute already tensorized — i.e. this understates the
               true advantage over the reference's per-factor Python loops).

Extra context fields (so "fast" is judgeable against hardware capability):
  flops_per_step  — XLA cost-analysis FLOPs of one compiled per-batch grid step
  mfu_pct         — chip utilization vs dense peak, from the SCANNED dispatch
                    (dispatch overhead amortized over k batches — honest MFU)
  g_scaling       — {G: {wps, wps_scan, epoch_scan, mfu_pct}} over grid sizes
                    (epoch_scan = the single-dispatch epoch engine,
                    parallel/grid.py auto mode)
  epoch_scan_wps  — headline-G throughput of the epoch engine dispatch
  dispatches_per_epoch — the dispatch-count contract per mode for a nominal
                    32-batch epoch (data/pipeline.py dispatch_budget — the
                    same helper the tier-1 tripwire test asserts against)
  ckpt_stall_ms   — measured main-thread checkpoint cost on the headline
                    grid state: async hand-off (what the train loop now
                    stalls) vs the synchronous gather+write it replaced
  mixed_precision — smallest g_scaling point re-measured under the
                    PRODUCTION precision_mode="mixed" path (bf16 MXU
                    contractions, f32 master params/reductions, numerics
                    sentinel armed): wps_ratio_vs_f32 vs the same point's
                    f32 scan + the sentinel skip count (precision-cliff
                    evidence) — measured on EVERY backend (CPU emulates
                    bf16, slower but never null). `bf16` stays as the
                    legacy alias for trajectory continuity
  autotune        — one fresh GL-prox tiling search (ops/autotune.py):
                    search_ms, winner tile, measured speedup vs the default
                    tile, and the zero-re-search persistence contract
                    (winner_persisted: the second resolve loads the store's
                    winner with 0 search steps)
  dead_lane_flops_saved_pct / compaction — elastic grid scheduler win
                    (parallel/compaction.py): on a seeded early-stopping
                    grid, the share of lane-epochs the live-lane compaction
                    did not have to compute vs a fixed-width run
  compile_cache   — persistent XLA compilation-cache win
                    (runtime/compileobs.py): cold compile_ms of the headline
                    scanned program (cache miss + write) vs warm compile_ms
                    (in-memory caches cleared, identical program re-lowered
                    -> disk-cache retrieval). cold_cache_hits > 0 flags a
                    round whose "cold" sample itself warm-started from a
                    previous run's cache — the cross-run win, reported
                    rather than hidden
  obs_overhead_pct — telemetry-spine cost (redcliff_tpu/obs): tracing-on vs
                    tracing-off throughput of the compiled grid step through
                    the engine's dispatch chokepoint (per-dispatch span +
                    flight ring). Contract: <= 2% on, ~0 off
  regressions     — the cross-round regression sentinel's findings
                    (redcliff_tpu/obs/regress.py, run at the end of EVERY
                    round against the prior BENCH_r*.json trajectory with
                    per-family noise bands; empty list = clean), plus a
                    regression_sentinel summary (rounds compared, families
                    judged, improvements) — the BENCH trajectory audits
                    itself instead of waiting for a human to eyeball it
  probe_log       — every accelerator probe attempt (the axon TPU tunnel hangs
                    intermittently for minutes; attempts spread with backoff)
  probe_retry     — fixed-schema outcome of the shared probe retry policy
                    (redcliff_tpu/runtime/retry.py: policy knobs, per-attempt
                    backoff actually waited, deadline_hit), so artifacts
                    distinguish "tunnel dead" from "policy too impatient"
  device / error  — backend actually used; error non-null if the TPU was
                    unavailable and the bench fell back to CPU
  cached / measured_at / live_fallback — when live TPU probes fail but a cached
                    TPU measurement exists (experiments/TPU_BENCH_CACHE.json,
                    written by tpu_watch.py during any live tunnel window or by
                    a previous live bench run), the emitted headline is that
                    real-TPU measurement marked cached:true with its timestamp
                    and source; the live CPU fallback run rides along under
                    live_fallback so the current run stays diagnosable

Architecture: the parent process NEVER initializes a jax backend. It probes the
accelerator in killable subprocesses on a backoff schedule and runs the actual
measurement in a child process (`bench.py --measure tpu|cpu`), so a tunnel that
hangs mid-run is killed and retried instead of wedging the bench. The reference
repository publishes no benchmark numbers (BASELINE.md), so the
sequential-vs-grid ratio on identical hardware is the honest comparable.
"""
import dataclasses
import datetime
import glob
import json
import os
import random
import subprocess
import sys
import time
import traceback

import numpy as np

# stdlib-only module (never initializes a jax backend — safe in this parent
# process, which must stay killable): the shared probe retry/backoff policy
# all accelerator-probing entry points (bench.py, tpu_watch.py, the DCN dry
# run) now route through, replacing the hand-rolled PROBE_WAITS spread
from redcliff_tpu.runtime.retry import PROBE_RETRY_POLICY, GiveUp, retry

# newest successful TPU measurement, written here by this script on a live TPU
# run and by tpu_watch.py's opportunistic background measurements; embedded in
# the emitted JSON (marked cached, with provenance) when live probes fail
TPU_CACHE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "experiments", "TPU_BENCH_CACHE.json")
# tracked seed: the dated 2026-07-29 live-TPU measurement (BASELINE.md measured
# table), used when no runtime cache exists (the runtime cache is gitignored
# and overwritten by any fresher live-window measurement)
TPU_CACHE_SEED_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   "experiments", "TPU_BENCH_CACHE_SEED.json")
# a cached measurement older than this is flagged stale (age_hours is always
# reported; old-but-real TPU evidence is surfaced with provenance, not dropped)
TPU_CACHE_STALE_AFTER_S = 48 * 3600.0
# cooperative lock so tpu_watch.py and a live bench.py run never measure on the
# same chip (and the same 1-core host) concurrently; flock is released by the
# kernel when the holder dies, so there is no stale-lock state to break
TPU_MEASURE_LOCK = TPU_CACHE_PATH + ".lock"

# dense peak FLOPs/s per chip, bf16/fp-dense (public TPU specs); fp32 runs at
# a lower peak on MXU — mfu_pct is therefore a conservative lower bound
PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

METRIC = "redcliff_s_grid_train_windows_per_sec_per_chip"

PROBE_TIMEOUT_S = 75.0
MEASURE_TIMEOUT_S = 1500.0


def _emit(payload):
    print(json.dumps(payload))
    sys.stdout.flush()


def _attach_regressions(payload):
    """Run the cross-round regression sentinel (obs/regress.py) on the
    final payload and embed its machine-readable block — every emitted
    round records whether it regressed the trajectory. Never fails the
    bench: a sentinel error is recorded, not raised."""
    try:
        from redcliff_tpu.obs import regress

        block = regress.run_sentinel(
            payload, bench_dir=os.path.dirname(os.path.abspath(__file__)))
        payload["regressions"] = block["regressions"]
        payload["regression_sentinel"] = {
            k: block[k] for k in ("rounds_compared", "families_checked",
                                  "improvements", "skipped", "notes",
                                  "tpu_cache")}
    except Exception as e:  # noqa: BLE001 — the sentinel must never
        payload["regressions"] = None  # cost a measured round its artifact
        payload["regression_sentinel"] = {
            "error": f"{type(e).__name__}: {e}"}
    return payload


def _utcnow_iso():
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


def _git_head():
    try:
        r = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                           capture_output=True, text=True, timeout=10,
                           cwd=os.path.dirname(os.path.abspath(__file__)))
        return r.stdout.strip() or None
    except Exception:
        return None


def _load_tpu_cache():
    """Newest cached TPU measurement ({measured_at, result, ...}) or None.

    Staleness is REPORTED, never used to discard: a dated real-TPU
    measurement with provenance beats a CPU fallback with none, and the
    consumer can discount it from the attached ``age_hours`` /
    ``cache_stale`` / ``cache_commit_mismatch`` fields. The recorded
    git_commit rides along as provenance (doc-only commits happen constantly,
    so a commit mismatch is a flag, not a rejection criterion).

    Falls back to the tracked seed file when the runtime cache is absent or
    malformed, so the dated real-TPU evidence survives a wiped workdir."""
    for path in (TPU_CACHE_PATH, TPU_CACHE_SEED_PATH):
        cache = _load_tpu_cache_file(path)
        if cache is not None:
            return cache
    return None


def _load_tpu_cache_file(path):
    try:
        with open(path) as f:
            cache = json.load(f)
        if not (isinstance(cache, dict)
                and isinstance(cache.get("result"), dict)
                and cache["result"].get("value")
                and cache["result"].get("platform") == "tpu"):
            return None
        measured = datetime.datetime.strptime(
            cache["measured_at"], "%Y-%m-%dT%H:%M:%SZ").replace(
            tzinfo=datetime.timezone.utc)
        age = (datetime.datetime.now(datetime.timezone.utc)
               - measured).total_seconds()
        cache["age_hours"] = round(age / 3600.0, 1)
        cache["stale"] = age > TPU_CACHE_STALE_AFTER_S
        if cache["stale"]:
            print(f"bench: TPU cache is {age/3600:.1f}h old; reporting with "
                  f"staleness flags rather than discarding", file=sys.stderr)
        return cache
    except (OSError, json.JSONDecodeError, KeyError, ValueError):
        return None


def _write_tpu_cache(payload, source="bench.py live run", extras=None):
    """Persist a successful TPU measurement for future runs' fallback.

    Shared by bench.py (live runs) and tpu_watch.py (opportunistic windows) so
    there is exactly one writer implementation for the schema
    _load_tpu_cache validates. Unique tmp per pid keeps concurrent writers'
    os.replace promotions atomic.

    bench.py records the fixed-schema probe/retry outcome
    (runtime.retry.RetryOutcome.log(): policy knobs, per-attempt backoff and
    result, deadline_hit) via ``extras={"probe_retry": ...}`` so future BENCH
    artifacts can distinguish "tunnel dead" from "policy too impatient"."""
    try:
        cache = {
            "measured_at": _utcnow_iso(),
            "source": source,
            "git_commit": _git_head(),
            "result": {k: v for k, v in payload.items()
                       if k not in ("probe_log", "probe_retry")},
        }
        if extras:
            cache.update(extras)
        tmp = f"{TPU_CACHE_PATH}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(cache, f, indent=1)
        os.replace(tmp, TPU_CACHE_PATH)
    except OSError as e:
        print(f"bench: could not write TPU cache: {e}", file=sys.stderr)


_lock_fd = None


def _acquire_measure_lock(wait_s=0.0, poll_s=15.0):
    """Cooperative TPU-measurement lock via fcntl.flock — mutual exclusion
    with kernel-side release if the holder dies (no stale-lock breaking, no
    TOCTOU). Returns True if acquired; waits up to wait_s for a holder."""
    global _lock_fd
    import errno
    import fcntl

    try:
        fd = os.open(TPU_MEASURE_LOCK, os.O_CREAT | os.O_WRONLY)
    except OSError:
        return True  # lockfile unusable (e.g. RO fs): don't deadlock bench
    deadline = time.monotonic() + wait_s
    while True:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            _lock_fd = fd
            try:  # advisory pid note; failure must not drop the held lock
                os.truncate(fd, 0)
                os.write(fd, f"{os.getpid()} {_utcnow_iso()}".encode())
            except OSError:
                pass
            return True
        except OSError as e:
            if e.errno not in (errno.EWOULDBLOCK, errno.EAGAIN, errno.EACCES):
                # not contention — flock unsupported (e.g. some network
                # mounts): operate locklessly rather than treating every
                # window as contended / blocking the full wait
                os.close(fd)
                return True
            if time.monotonic() >= deadline:
                os.close(fd)
                return False
            time.sleep(min(poll_s, max(deadline - time.monotonic(), 0.1)))


def _release_measure_lock():
    global _lock_fd
    if _lock_fd is not None:
        try:
            os.close(_lock_fd)  # closing the fd drops the flock
        except OSError:
            pass
        _lock_fd = None


# ---------------------------------------------------------------------------
# parent: probe + orchestrate
# ---------------------------------------------------------------------------
def _probe_accelerator(timeout_s=PROBE_TIMEOUT_S):
    """Check in a KILLABLE subprocess whether the accelerator backend can
    initialize: a hung tunnel (observed with the axon TPU backend) would
    otherwise block this process in a C call forever."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); print(d[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s)
        if r.returncode == 0 and r.stdout.strip() not in ("", "cpu"):
            return True, r.stdout.strip()
        if r.returncode == 0:
            return False, f"no accelerator: backend is {r.stdout.strip()!r}"
        return False, f"probe rc={r.returncode}: {r.stderr.strip()[-300:]}"
    except subprocess.TimeoutExpired:
        return False, f"accelerator backend init hung > {timeout_s:.0f}s"
    except Exception as e:
        return False, f"probe failed: {e!r}"


def _run_measure_child(platform, timeout_s=MEASURE_TIMEOUT_S):
    """Run the measurement in a child process; return (payload | None, info)."""
    try:
        r = subprocess.run(
            [sys.executable, __file__, "--measure", platform],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None, f"measurement on {platform} hung > {timeout_s:.0f}s"
    sys.stderr.write(r.stderr[-4000:])
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            payload = json.loads(line)
            if isinstance(payload, dict) and payload.get("metric") == METRIC:
                return payload, "ok"
        except json.JSONDecodeError:
            continue
    return None, (f"measurement child on {platform} rc={r.returncode} "
                  f"emitted no result JSON: {r.stderr.strip()[-300:]}")


MAX_MEASURE_ATTEMPTS = 2


def _orchestrate():
    t0 = time.monotonic()
    probe_log = []
    state = {"measure_attempts": 0}

    def probe_round(attempt):
        """One probe attempt; on a live tunnel, one measurement attempt.
        Returns the measured payload (success) or None (back off + retry)."""
        ok, info = _probe_accelerator()
        probe_log.append({"attempt": attempt,
                          "t_offset_s": round(time.monotonic() - t0, 1),
                          "ok": ok, "info": info})
        print(f"bench: probe {attempt} at +{probe_log[-1]['t_offset_s']}s "
              f"-> {info}", file=sys.stderr, flush=True)
        if not ok:
            return None
        if state["measure_attempts"] >= MAX_MEASURE_ATTEMPTS:
            # a tunnel that probes OK but hangs mid-measure must not keep
            # burning 25-minute measurement timeouts; bound the total
            raise GiveUp("measurement attempt budget exhausted")
        state["measure_attempts"] += 1
        # if tpu_watch.py is mid-measurement on the chip, wait for it (its
        # result lands in the cache); proceed regardless after the wait so a
        # wedged-but-not-yet-stale lock can't deadlock the round's bench run
        got_lock = _acquire_measure_lock(wait_s=1800.0)
        try:
            payload, minfo = _run_measure_child("tpu")
        finally:
            if got_lock:
                _release_measure_lock()
        if payload is not None and payload.get("value"):
            return payload
        # tunnel dropped mid-measurement: log and keep probing
        probe_log.append({"attempt": attempt,
                          "t_offset_s": round(time.monotonic() - t0, 1),
                          "ok": False, "info": f"measure: {minfo}"})
        print(f"bench: TPU measurement failed ({minfo}); continuing probes",
              file=sys.stderr, flush=True)
        return None

    # PROBE_RETRY_POLICY's 15-min deadline budgets pure probing; here each
    # attempt may embed a full measurement (MEASURE_TIMEOUT_S) plus a wait on
    # tpu_watch's measure lock, so widen the deadline to cover the
    # MAX_MEASURE_ATTEMPTS budget — otherwise one hung measurement would
    # consume the whole loop and the second attempt could never run. The
    # jittered rng spreads fleet-synchronized bench runs apart.
    policy = PROBE_RETRY_POLICY
    if policy.deadline_s is not None:
        policy = dataclasses.replace(
            policy, deadline_s=(policy.deadline_s + MAX_MEASURE_ATTEMPTS
                                * (MEASURE_TIMEOUT_S + 300.0)))
    outcome = retry(probe_round, policy,
                    is_success=lambda p: p is not None,
                    info_of=lambda p: ("measured" if p is not None
                                       else "no measurement this attempt"),
                    rng=random.Random())
    retry_log = outcome.log()
    if outcome.ok:
        payload = outcome.value
        payload["probe_log"] = probe_log
        payload["probe_retry"] = retry_log
        _write_tpu_cache(payload, extras={"probe_retry": retry_log})
        _emit(_attach_regressions(payload))
        return

    if state["measure_attempts"] > 0:
        err = (f"accelerator probed OK but {state['measure_attempts']} "
               f"measurement attempt(s) failed/hung (see probe_log); "
               f"ran on cpu")
    else:
        err = (f"accelerator unavailable across {len(outcome.attempts)} "
               f"backoff probe attempts over {round(time.monotonic() - t0)}s"
               f"{' (probe deadline hit)' if outcome.deadline_hit else ''}; "
               f"ran on cpu")
    payload, minfo = _run_measure_child("cpu", timeout_s=900.0)
    if payload is not None:
        # append (never replace) any error the CPU child itself reported, so a
        # fallback-path crash stays diagnosable from the published JSON
        child_err = payload.get("error")
        payload["error"] = f"{err}; child: {child_err}" if child_err else err
    else:
        payload = {"metric": METRIC, "value": None, "unit": "windows/s/chip",
                   "vs_baseline": None, "error": f"{err}; then {minfo}"}

    cached = _load_tpu_cache()
    if cached is not None:
        # headline the newest real-TPU measurement (opportunistically captured
        # during a live tunnel window by tpu_watch.py or a previous bench run),
        # clearly marked as cached with provenance; the live CPU fallback rides
        # along so the current run stays fully diagnosable
        out = dict(cached["result"])
        out["cached"] = True
        out["measured_at"] = cached.get("measured_at")
        out["age_hours"] = cached.get("age_hours")
        out["cache_stale"] = cached.get("stale", False)
        out["cache_source"] = cached.get("source", "tpu_watch.py")
        out["cache_git_commit"] = cached.get("git_commit")
        # perf-relevant commits may have landed since the cached run; flag the
        # mismatch so consumers can discount stale-code measurements without
        # manual cross-checking (doc-only commits make this a flag, not a veto)
        head = _git_head()
        out["cache_commit_mismatch"] = bool(
            head and cached.get("git_commit") and head != cached["git_commit"])
        for marker in ("pre_scan_dispatch", "backfilled", "backfill_note",
                       "pallas_prox_check"):
            if cached.get(marker) is not None:
                out[marker] = cached[marker]
        # error contract: non-null whenever the TPU was unavailable for THIS
        # run — the value is a real-TPU number, but from an earlier window
        out["error"] = err
        out["live_fallback"] = {k: v for k, v in payload.items()
                                if k != "probe_log"}
        out["probe_log"] = probe_log
        out["probe_retry"] = retry_log
        _emit(_attach_regressions(out))
        return

    payload["probe_log"] = probe_log
    payload["probe_retry"] = retry_log
    _emit(_attach_regressions(payload))


# ---------------------------------------------------------------------------
# child: the actual measurement
# ---------------------------------------------------------------------------
def _flops_of(compiled):
    """XLA cost-analysis FLOPs of a compiled computation (None if unavailable)."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        f = ca.get("flops")
        return float(f) if f and f > 0 else None
    except Exception:
        return None


def _model_config():
    from redcliff_tpu.models.redcliff import RedcliffSCMLPConfig

    # D4IC-like shapes: 10 channels, gen_lag 4, embed_lag 16 (ref cached args)
    return RedcliffSCMLPConfig(
        num_chans=10, gen_lag=4, gen_hidden=(32,), embed_lag=16,
        embed_hidden_sizes=(0,), num_factors=5, num_supervised_factors=5,
        factor_score_coeff=2.0, factor_cos_sim_coeff=0.05,
        factor_weight_l1_coeff=0.01, adj_l1_reg_coeff=0.001,
        factor_score_embedder_type="DGCNN", dgcnn_num_graph_conv_layers=3,
        dgcnn_num_hidden_nodes=100,
        primary_gc_est_mode="conditional_factor_fixed_embedder",
        num_sims=2, training_mode="combined",
    )


def _make_runner(jax, model, G, B, matmul_precision=None,
                 precision_mode="f32"):
    from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig

    spec = GridSpec(points=[
        {"gen_lr": 1e-3 * (1 + (i % 4)), "adj_l1_reg_coeff": 1e-3 * (i % 2),
         "factor_cos_sim_coeff": 0.05 * (i % 3)}
        for i in range(G)
    ])
    return RedcliffGridRunner(
        model, RedcliffTrainConfig(batch_size=B,
                                   matmul_precision=matmul_precision,
                                   precision_mode=precision_mode),
        spec, mesh=None)


def _mfu_pct(scan_flops, scan_dispatch_s, peak):
    """Cost-analysis FLOPs / measured scanned-dispatch time vs chip peak."""
    if not (scan_flops and peak):
        return None
    return round(100.0 * scan_flops / scan_dispatch_s / peak, 2)


def _bench_grid(jax, model, G, B, steps, scan_k, matmul_precision=None,
                precision_mode="f32", scan_only=False):
    """Per-batch and scanned throughput (+FLOPs) of the G-point grid step.

    scan_only skips the per-batch compile + timing (the scanned dispatch is
    the production execution mode and the headline number) — used by the
    mixed-precision variant so it costs one compile, not two."""
    cfg = model.config
    runner = _make_runner(jax, model, G, B, matmul_precision=matmul_precision,
                          precision_mode=precision_mode)
    rng = np.random.default_rng(0)
    T = cfg.max_lag + cfg.num_sims
    X = jax.device_put(rng.normal(size=(B, T, cfg.num_chans)).astype(np.float32))
    Y = jax.device_put(
        rng.uniform(size=(B, cfg.num_supervised_factors, 1)).astype(np.float32))

    from redcliff_tpu.runtime.numerics import init_numerics_state

    params, optA, optB = runner.init_grid(jax.random.PRNGKey(0))
    coeffs = runner.coeffs
    active = jax.numpy.ones((G,), dtype=bool)
    ns = init_numerics_state(lanes=G)

    wps = flops = dt = None
    epoch_wps = None
    p, a, b = params, optA, optB
    if not scan_only:
        step = runner._steps["combined"]
        # AOT-compile ONCE and time through the compiled object (calling the
        # jit wrapper after .lower().compile() would compile a second time —
        # the jit executable cache is not populated by AOT compilation)
        compiled = step.lower(params, optA, optB, ns, coeffs, active, X,
                              Y).compile()
        flops = _flops_of(compiled)
        p, a, b, ns, _ = compiled(params, optA, optB, ns, coeffs, active,
                                  X, Y)
        jax.block_until_ready(p)
        t0 = time.perf_counter()
        for _ in range(steps):
            p, a, b, ns, _ = compiled(p, a, b, ns, coeffs, active, X, Y)
        jax.block_until_ready(p)
        dt = time.perf_counter() - t0
        wps = G * B * steps / dt

    # scanned k-batch dispatch: same update semantics (grid scan test pins
    # bit-parity), one host dispatch per k batches. The compile of this
    # program is the warm-vs-cold compile-cache probe's COLD sample
    # (runtime/compileobs.py counters)
    from redcliff_tpu.runtime import compileobs

    Xs = jax.numpy.stack([X] * scan_k)
    Ys = jax.numpy.stack([Y] * scan_k)
    sstep = runner._scan_steps["combined"]
    # abstract avals: the cache probe re-lowers this exact program later,
    # after the concrete buffers have been donated away
    compile_args = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
        (p, a, b, ns, coeffs, active, Xs, Ys))
    c0 = compileobs.snapshot()
    scompiled = sstep.lower(p, a, b, ns, coeffs, active, Xs, Ys).compile()
    scan_compile = compileobs.delta(c0)
    sflops = _flops_of(scompiled)
    p, a, b, ns, _ = scompiled(p, a, b, ns, coeffs, active, Xs, Ys)  # warm
    jax.block_until_ready(p)
    sdispatches = max(2, steps // scan_k)
    t0 = time.perf_counter()
    for _ in range(sdispatches):
        p, a, b, ns, _ = scompiled(p, a, b, ns, coeffs, active, Xs, Ys)
    jax.block_until_ready(p)
    sdt = time.perf_counter() - t0
    scan_wps = G * B * scan_k * sdispatches / sdt
    scan_dispatch_s = sdt / sdispatches

    if not scan_only:
        # epoch engine (parallel/grid.py _epoch_steps): one dispatch gathers
        # + scans an epoch chunk from the HBM-resident dataset by index —
        # the auto-mode production path; timed over the same scan_k batches
        # so wps_epoch is directly comparable to wps_scan
        Xfull = jax.device_put(np.concatenate([np.asarray(X)] * scan_k))
        Yfull = jax.device_put(np.concatenate([np.asarray(Y)] * scan_k))
        idx = jax.device_put(
            np.arange(B * scan_k, dtype=np.int32).reshape(scan_k, B))
        estep = runner._epoch_steps["combined"]
        ecompiled = estep.lower(p, a, b, ns, coeffs, active, Xfull, Yfull,
                                idx).compile()
        p, a, b, ns, _ = ecompiled(p, a, b, ns, coeffs, active, Xfull,
                                   Yfull, idx)  # warm
        jax.block_until_ready(p)
        t0 = time.perf_counter()
        for _ in range(sdispatches):
            p, a, b, ns, _ = ecompiled(p, a, b, ns, coeffs, active, Xfull,
                                       Yfull, idx)
        jax.block_until_ready(p)
        edt = time.perf_counter() - t0
        epoch_wps = G * B * scan_k * sdispatches / edt

    return {
        "wps": wps, "flops": flops,
        "step_s": dt / steps if dt is not None else None,
        "scan_wps": scan_wps, "scan_flops": sflops,
        "scan_dispatch_s": scan_dispatch_s,
        "scan_compile": scan_compile,
        "compile_args": compile_args,
        "epoch_wps": epoch_wps,
        # final sentinel counters after the timed dispatches (the
        # mixed-precision probe reports guarded skips from these)
        "nstate": ns,
        "runner": runner, "state": (p, a, b, coeffs, X, Y),
    }


def _bench_sequential(jax, model, runner, grid_state, G, B, steps):
    """Reference execution pattern: one jit'd step per point, run sequentially."""
    import optax

    params, optA, optB, coeffs, X, Y = grid_state
    point_params = jax.tree.map(lambda x: x[0], params)
    point_optA = jax.tree.map(
        lambda x: x[0] if hasattr(x, "ndim") and x.ndim > 0 else x, optA)
    point_optB = jax.tree.map(
        lambda x: x[0] if hasattr(x, "ndim") and x.ndim > 0 else x, optB)
    point_coeffs = {k: v[0] for k, v in coeffs.items()}

    def single_step(params, a_state, b_state, coeffs, X, Y):
        def loss_fn(pp):
            return model.loss_for_phase(pp, X, Y, "combined", coeffs=coeffs,
                                        need_gc=True, need_gc_lagged=True)
        (_, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updA, a_state = runner.optA.update(grads["embedder"], a_state)
        updB, b_state = runner.optB.update(grads["factors"], b_state)
        params = dict(
            params,
            embedder=optax.apply_updates(
                params["embedder"],
                jax.tree.map(lambda u: -coeffs["embed_lr"] * u, updA)),
            factors=optax.apply_updates(
                params["factors"],
                jax.tree.map(lambda u: -coeffs["gen_lr"] * u, updB)),
        )
        return params, a_state, b_state

    sstep = jax.jit(single_step, donate_argnums=(0, 1, 2))
    pp, aa, bb = sstep(point_params, point_optA, point_optB, point_coeffs, X, Y)
    jax.block_until_ready(pp)
    t0 = time.perf_counter()
    for _ in range(steps):
        for _ in range(G):  # one sequential step per grid point, like a job array
            pp, aa, bb = sstep(pp, aa, bb, point_coeffs, X, Y)
    jax.block_until_ready(pp)
    dt = time.perf_counter() - t0
    return G * B * steps / dt


def _bench_dead_lanes(jax):
    """dead_lane_flops_saved_pct on an early-stopping grid: a seeded 8-point
    fit where most lanes stop improving fast, compaction ON (the default) —
    the gap between lanes actually computed and what a fixed-width run pays
    is the dead-lane waste the elastic scheduler recovers
    (parallel/compaction.py). Tiny model shapes: this measures scheduling,
    not FLOPs, so it must not eat the measurement budget."""
    import jax.numpy  # noqa: F401 — backend live

    from redcliff_tpu.data.datasets import ArrayDataset
    from redcliff_tpu.models.redcliff import RedcliffSCMLP, RedcliffSCMLPConfig
    from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig

    model = RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=4, gen_lag=2, gen_hidden=(8,), embed_lag=4,
        embed_hidden_sizes=(8,), num_factors=2, num_supervised_factors=2,
        factor_weight_l1_coeff=0.01, adj_l1_reg_coeff=0.001,
        factor_cos_sim_coeff=0.01, factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive", num_sims=1,
        training_mode="combined"))
    # 2 live lanes + 6 frozen (zero-lr) lanes: the frozen ones early-stop at
    # the first patience check and the grid compacts 8 -> 2
    points = ([{"gen_lr": 1e-3}, {"gen_lr": 3e-3}]
              + [{"gen_lr": 0.0, "embed_lr": 0.0}] * 6)
    tc = RedcliffTrainConfig(max_iter=8, batch_size=16, lookback=1,
                             check_every=1)
    runner = RedcliffGridRunner(model, tc, GridSpec(points=points))
    rng = np.random.default_rng(0)
    cfg = model.config
    T = cfg.max_lag + cfg.num_sims
    ds = ArrayDataset(rng.normal(size=(48, T, cfg.num_chans)).astype(np.float32),
                      rng.uniform(size=(48, 3, 1)).astype(np.float32))
    import jax as _jax

    runner.fit(_jax.random.PRNGKey(0), ds, ds)
    s = runner.dispatch_stats
    saved_pct = (100.0 * (1.0 - s["lane_epochs"] / s["lane_epochs_nominal"])
                 if s["lane_epochs_nominal"] else 0.0)
    return {
        "grid_points": len(points),
        "epochs": s["epochs"],
        "compactions": s["compactions"],
        "final_width": s["grid_width"],
        "lane_epochs": s["lane_epochs"],
        "lane_epochs_nominal": s["lane_epochs_nominal"],
        "dead_lane_flops_saved_pct": round(saved_pct, 1),
    }


def _bench_remesh():
    """Degraded-mesh re-shard planning latency (parallel/remesh.py): the
    host-only cost a host-loss resume adds BEFORE the first dispatch —
    planning which lanes of a checkpointed sweep ride the bucket ladder
    onto the survivors. Measured at sweep-service scale (G=4096, half the
    lanes already retired) onto a non-power-of-two 6-device survivor set
    (the worst case: every lane migrates and the width re-buckets)."""
    import numpy as np

    from redcliff_tpu.parallel import remesh

    G = 4096
    rng = np.random.default_rng(0)
    active = rng.random(G) < 0.5
    ids = np.arange(G, dtype=np.int32)
    t0 = time.perf_counter()
    plan = remesh.plan_resharding(active, ids, [], n_devices=6)
    plan_ms = (time.perf_counter() - t0) * 1e3
    return {"grid_points": G, "lanes_live": int(active.sum()),
            "to_devices": 6,
            "new_width": plan.new_width if plan is not None else None,
            "lanes_retired": (int(plan.retire_rows.size)
                              if plan is not None else 0),
            "plan_ms": round(plan_ms, 3)}


def _bench_compile_cache(jax, runner, compile_args):
    """Warm-vs-cold compile cost of the headline scanned program with the
    persistent XLA compilation cache (runtime/compileobs.py). The cold number
    was captured when the program first compiled (cache miss -> full XLA
    compile + cache write); clearing jax's in-memory executable caches and
    re-compiling the identical program then measures the warm path — a
    persistent-cache retrieval, which is what every restart / supervisor
    re-attempt / resumed preemption pays instead of a full compile."""
    from redcliff_tpu.runtime import compileobs

    before = compileobs.snapshot()
    jax.clear_caches()
    sstep = runner._scan_steps["combined"]
    sstep.lower(*compile_args).compile()
    d = compileobs.delta(before)
    return {
        "dir": jax.config.jax_compilation_cache_dir,
        "warm_compile_ms": d["compile_ms"],
        "warm_cache_hits": d["cache_hits"],
        "warm_cache_misses": d["cache_misses"],
    }


def _bench_autotune(jax):
    """autotune probe (ISSUE 14, ops/autotune.py): one fresh iterative
    GL-prox tiling search at the bench model's first-layer group shape —
    search cost, the winner tile, its measured speedup over the default
    tile — then the zero-re-search contract: the winner must load from the
    persisted store with zero search steps on a second resolve. A throwaway
    store dir per round keeps search_ms a *measured* family instead of a
    cache hit."""
    import shutil
    import tempfile

    from redcliff_tpu.ops import autotune

    cfg = _model_config()
    rows = cfg.num_factors * cfg.num_chans * cfg.num_chans
    cols = cfg.gen_hidden[0] * cfg.gen_lag
    tmp = tempfile.mkdtemp(prefix="bench_autotune_")
    try:
        autotune.clear_memo()
        br, rec = autotune.tune_gl_prox(rows, cols, base_dir=tmp, reps=3,
                                        force=True)
        autotune.clear_memo()  # drop the memo: reuse must come from DISK
        br2, rec2 = autotune.tune_gl_prox(rows, cols, base_dir=tmp)
        autotune.drain_records()
        return {
            "kernel": "gl_prox", "rows": rows, "cols": cols,
            "winner_block_rows": br,
            "candidates": rec.get("candidates"),
            "search_ms": rec.get("search_ms"),
            "speedup_vs_default": rec.get("speedup_vs_default"),
            "second_run_search_steps": rec2.get("search_steps"),
            "winner_persisted": (br2 == br
                                 and rec2.get("search_steps") == 0),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
        autotune.clear_memo()  # no throwaway-store winners outlive the probe


def _bench_obs_overhead(jax, runner, grid_state, steps=30, calls=4000):
    """obs_overhead_pct: the telemetry spine's cost on the hot path.

    Two measurements through the engine's dispatch chokepoint
    (``_call_cold`` -> per-dispatch span -> flight ring):

    1. the spine's PER-DISPATCH cost in isolation — ``_call_cold`` around a
       no-op callable, tracing on vs off (``redcliff_tpu.obs.set_enabled``),
       averaged over ``calls`` iterations. Differencing two full-dispatch
       throughput legs instead would report this container's run-to-run
       step noise (measured at +-25%), orders of magnitude above the
       spine's µs-level cost;
    2. the real compiled grid step's time (one short run, tracing off).

    ``pct`` = span cost / step time. The spine's contract is <= 2% with
    tracing on and ~0 off (ISSUE 7 acceptance; docs/ARCHITECTURE.md
    "Telemetry spine") — this probe pins it in every BENCH_r* round."""
    import jax.numpy as jnp

    from redcliff_tpu import obs
    from redcliff_tpu.runtime.numerics import init_numerics_state

    noop = lambda: None
    key_noop = ("obs_probe_noop",)

    def per_call_us(n):
        t0 = time.perf_counter()
        for _ in range(n):
            runner._call_cold(key_noop, noop)
        return (time.perf_counter() - t0) / n * 1e6

    p0, a0, b0, coeffs, X, Y = grid_state
    G = int(jax.tree.leaves(coeffs)[0].shape[0])
    # the donated-buffer step consumes its inputs; probe on private copies
    p = jax.tree.map(jnp.copy, p0)
    a = jax.tree.map(jnp.copy, a0)
    b = jax.tree.map(jnp.copy, b0)
    ns = init_numerics_state(lanes=G)
    active = jnp.ones((G,), dtype=bool)
    step = runner._steps["combined"]
    key = ("obs_probe", "combined", G)

    was = obs.enabled()
    try:
        obs.set_enabled(True)
        per_call_us(100)  # warm the cold path + span machinery
        on_us = per_call_us(calls)
        obs.set_enabled(False)
        per_call_us(100)
        off_us = per_call_us(calls)
        # real step time, tracing off (the denominator)
        p, a, b, ns = runner._call_cold(key, step, p, a, b, ns, coeffs,
                                        active, X, Y)[:4]  # warm compile
        jax.block_until_ready(p)
        t0 = time.perf_counter()
        for _ in range(steps):
            p, a, b, ns = runner._call_cold(key, step, p, a, b, ns,
                                            coeffs, active, X, Y)[:4]
        jax.block_until_ready(p)
        step_us = (time.perf_counter() - t0) / steps * 1e6
    finally:
        obs.set_enabled(was)
    span_us = max(on_us - off_us, 0.0)
    return {"pct": round(100.0 * span_us / step_us, 4),
            "span_cost_us": round(span_us, 3),
            "disabled_cost_us": round(off_us, 3),
            "step_us": round(step_us, 1), "steps": steps, "calls": calls}


def _bench_ckpt_stall(jax, grid_state):
    """Main-thread checkpoint cost, async hand-off vs synchronous write, on
    the headline grid state: async_ms is what the train loop actually stalls
    (snapshot + submit), sync_ms is the full gather+pickle+CRC+fsync the
    old path paid in-line. Written to a throwaway dir."""
    import shutil
    import tempfile

    import jax.numpy as jnp

    from redcliff_tpu.runtime.checkpoint import (AsyncCheckpointWriter,
                                                 write_checkpoint)

    params, optA, optB = grid_state[0], grid_state[1], grid_state[2]
    state = {"params": params, "optA_state": optA, "optB_state": optB}
    tmpdir = tempfile.mkdtemp(prefix="bench_ckpt_")
    try:
        path = os.path.join(tmpdir, "bench_checkpoint.pkl")
        to_host = lambda t: jax.tree.map(np.asarray, t)
        t0 = time.perf_counter()
        write_checkpoint(path, to_host(state))
        sync_ms = (time.perf_counter() - t0) * 1e3
        # same hand-off the grid engine performs: one fused snapshot
        # dispatch + async D2H kickoff + thread submit
        snapshot = jax.jit(lambda t: jax.tree.map(jnp.copy, t))
        snapshot(state)  # compile outside the timed region
        with AsyncCheckpointWriter() as w:
            t0 = time.perf_counter()
            snap = snapshot(state)
            for leaf in jax.tree.leaves(snap):
                if hasattr(leaf, "copy_to_host_async"):
                    leaf.copy_to_host_async()
            w.submit(lambda: write_checkpoint(path, to_host(snap)))
            async_ms = (time.perf_counter() - t0) * 1e3
        return {"async_ms": round(async_ms, 2), "sync_ms": round(sync_ms, 2)}
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def _bench_mem_model(jax, model, grid_state, G, B):
    """mem_model_err_pct: the analytical HBM footprint model
    (obs/memory.py) vs the device allocator, on the probe grid.

    The measurement is a LIVE-BYTES DELTA: poll ``bytes_in_use``, allocate
    one fresh copy of the probe's grid state (params + Adam moments +
    coeffs — a known, analytically-sized allocation on the default
    device), poll again, free the copy. Comparing against the allocator's
    lifetime ``peak_bytes_in_use`` instead would fold in every earlier
    bench stage's transients (the G-scaling sweep compiles up to G=256
    here) and flag the model for the allocator's history — the delta
    isolates exactly the bytes the model claims to predict.
    ``model_bytes`` (the abstract-shape `grid_footprint` prediction for
    this (shape, G)) rides along for context. On backends without
    ``memory_stats()`` — this container's CPU — the error is null WITH a
    reason, never a fabricated number."""
    import jax.numpy as jnp

    from redcliff_tpu.obs import memory as obsmem

    p, a, b, coeffs, X, Y = grid_state
    state = (p, a, b, coeffs)
    analytical = obsmem.tree_bytes(state)
    model_bytes = obsmem.grid_footprint(model, None, G)["total_bytes"]
    out = {"grid_points": G, "analytical_bytes": int(analytical),
           "model_bytes": int(model_bytes)}
    wm0 = obsmem.poll_watermark()
    if wm0 is None or wm0.get("bytes_in_use") is None:
        out.update(abs_err_pct=None,
                   reason=f"memory_stats unsupported on "
                          f"{jax.default_backend()}")
        return out
    copy = jax.tree.map(jnp.copy, state)
    jax.block_until_ready(copy)
    wm1 = obsmem.poll_watermark()
    measured = wm1["bytes_in_use"] - wm0["bytes_in_use"]
    del copy
    if measured <= 0:
        out.update(abs_err_pct=None,
                   reason="allocator live-bytes delta not observable")
        return out
    err = 100.0 * (analytical - measured) / measured
    out.update(abs_err_pct=round(abs(err), 1), err_pct=round(err, 1),
               measured_delta_bytes=int(measured),
               measured_peak_bytes=wm1.get("peak_bytes"),
               bytes_limit=wm1.get("bytes_limit"),
               n_devices=wm1.get("n_devices"))
    return out


def _bench_fleet(n_devices=8, budget_bytes=8 << 30):
    """fleet probe: the admission planner (redcliff_tpu/fleet/planner.py)
    on a synthetic heterogeneous request mix — mesh-slot utilization of
    cost/memory-aware packing vs the naive FIFO one-request-per-fit
    baseline (what the repo did before the fleet service), plus planning
    latency. Deterministic input, host-only: the numbers track the
    planner, not a fit."""
    from redcliff_tpu.fleet import planner

    # 3 shapes x small tenant requests (1-6 points each, mixed priorities/
    # deadlines): the real service mix — many requests far below one
    # bucket, which FIFO pads to the mesh one fit at a time
    shapes = [
        {"num_chans": 4, "num_factors": 2, "gen_lag": 2},
        {"num_chans": 8, "num_factors": 4, "gen_lag": 3},
        {"num_chans": 16, "num_factors": 4, "gen_lag": 5},
    ]
    reqs = []
    for i in range(18):
        shape = shapes[i % len(shapes)]
        reqs.append({
            "request_id": f"req-{i:03d}",
            "tenant": f"tenant-{i % 5}",
            "submitted_at": float(i),
            "priority": (1 if i % 7 == 0 else 0),
            "deadline_s": (600.0 if i % 5 == 0 else None),
            "shape": shape,
            "points": [{"gen_lr": 1e-3 * (j + 1)}
                       for j in range(1 + (i * 3) % 6)],
            "epochs": 50,
            "per_lane_bytes": 64 << 20,
            "fixed_bytes": 256 << 20,
            "spec": {"model_config": shape, "epochs": 50},
        })
    t0 = time.perf_counter()
    packed = planner.plan(reqs, n_devices=n_devices,
                          budget_bytes=budget_bytes)
    plan_ms = (time.perf_counter() - t0) * 1e3
    fifo = planner.fifo_plan(reqs, n_devices=n_devices,
                             budget_bytes=budget_bytes)
    pu = packed["utilization"]["utilization_pct"]
    fu = fifo["utilization"]["utilization_pct"]
    over = [b for b in packed["batches"]
            if b["predicted_bytes"] is not None
            and b["predicted_bytes"] > budget_bytes]
    return {
        "requests": len(reqs),
        "n_devices": n_devices,
        "budget_bytes": budget_bytes,
        "batches": len(packed["batches"]),
        "fifo_batches": len(fifo["batches"]),
        "unschedulable": len(packed["unschedulable"]),
        "packed_utilization_pct": pu,
        "fifo_utilization_pct": fu,
        "utilization_gain": (round(pu / fu, 3) if pu and fu else None),
        "headroom_violations": len(over),  # contract: always 0
        "plan_ms": round(plan_ms, 3),
    }


def _bench_fleet_containment():
    """fleet_containment probe (ISSUE 11): healthy-sibling completion
    latency WITH vs WITHOUT a poison co-tenant, end-to-end through two real
    fleet drains. Both legs run at the same bucket width (3 healthy 1-point
    requests -> width 4; +1 attributable nan-poison -> still width 4), so
    the ratio isolates the containment machinery — attribution, dead-letter
    routing, attempt accounting — not a program-family change. The
    ``contained`` flag is the correctness contract: 3 done, 1 dead-lettered,
    0 failed."""
    import shutil
    import tempfile

    from redcliff_tpu.fleet.__main__ import TINY_SPEC
    from redcliff_tpu.fleet.chaos import poison_point
    from redcliff_tpu.fleet.queue import FleetQueue
    from redcliff_tpu.fleet.worker import work
    from redcliff_tpu.runtime.retry import RetryPolicy
    from redcliff_tpu.runtime.supervisor import SupervisorPolicy

    env = dict(os.environ)
    env.pop("REDCLIFF_FAULT_INJECT", None)
    env.pop("REDCLIFF_FAULT_MARKER", None)

    def drain(root, poison):
        q = FleetQueue(root)
        spec = json.loads(json.dumps(TINY_SPEC))
        spec["epochs"] = 1
        for i in range(3):
            q.submit(f"bench-h{i}", [{"gen_lr": 1e-3 * (i + 1)}], spec=spec)
        if poison is not None:
            q.submit("bench-poison", [poison], spec=spec)
        policy = SupervisorPolicy(
            max_restarts=1,
            backoff=RetryPolicy(max_attempts=10, base_delay_s=0.05,
                                multiplier=1.0, max_delay_s=0.05))
        t0 = time.perf_counter()
        work(str(root), drain=True, poll_s=0.1, lease_s=60.0,
             supervisor_policy=policy, env=env, max_attempts=2)
        return time.perf_counter() - t0, q.status()["counts"]

    tmp = tempfile.mkdtemp(prefix="bench_fleet_containment_")
    try:
        healthy_wall, hc = drain(os.path.join(tmp, "healthy"), None)
        poisoned_wall, pc = drain(os.path.join(tmp, "poisoned"),
                                  poison_point("nan"))
        # a broken BASELINE leg (e.g. requests dead-lettered by a
        # durability bug) would make latency_ratio garbage, so the
        # correctness flag covers both legs
        baseline_ok = (hc["done"] == 3 and hc["failed"] == 0
                       and hc["deadletter"] == 0)
        return {
            "healthy_wall_s": round(healthy_wall, 3),
            "poisoned_wall_s": round(poisoned_wall, 3),
            "latency_ratio": (round(poisoned_wall / healthy_wall, 3)
                              if healthy_wall and baseline_ok else None),
            "healthy_done": pc["done"],
            "deadlettered": pc["deadletter"],
            "contained": (baseline_ok and pc["done"] == 3
                          and pc["deadletter"] == 1
                          and pc["failed"] == 0),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_mesh_packing():
    """mesh_packing probe (ISSUE 18): two heterogeneous tiny batches
    drained end-to-end through one worker, serially vs spatially packed
    onto disjoint sub-mesh slots of a simulated 4-device host pool
    (``--xla_force_host_platform_device_count=4`` in the supervised
    children). ``makespan_ratio`` is the packed/serial wall-clock — the
    number the whole subsystem exists to push below 1.0.
    ``utilization_pct`` integrates busy device-seconds from the
    slot_claim/slot_free event pairs over the packed leg's wall.
    ``headroom_violations`` sums the priced plans' violation counters (0
    by construction: the planner's per-lane HBM gate admits each
    co-tenant against the REMAINING headroom). The ``packed_ok`` flag is
    the correctness contract: both legs fully done, zero violations, and
    the packed leg actually overlapped two slots in time."""
    import shutil
    import tempfile

    from redcliff_tpu.fleet.__main__ import TINY_SPEC
    from redcliff_tpu.fleet.queue import FleetQueue
    from redcliff_tpu.fleet.worker import work
    from redcliff_tpu.obs.logging import read_jsonl
    from redcliff_tpu.runtime.retry import RetryPolicy
    from redcliff_tpu.runtime.supervisor import SupervisorPolicy

    env = dict(os.environ)
    env.pop("REDCLIFF_FAULT_INJECT", None)
    env.pop("REDCLIFF_FAULT_MARKER", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=4"
                        ).strip()

    def drain(root, mode):
        q = FleetQueue(root)
        for i in range(2):
            # distinct data seeds -> distinct merge keys -> two batches
            spec = json.loads(json.dumps(TINY_SPEC))
            spec["epochs"] = 1
            spec["mesh"] = "auto"
            spec["data"]["seed"] = i
            q.submit(f"bench-pack{i}", [{"gen_lr": 1e-3 * (i + 1)}],
                     spec=spec)
        policy = SupervisorPolicy(
            max_restarts=1,
            backoff=RetryPolicy(max_attempts=10, base_delay_s=0.05,
                                multiplier=1.0, max_delay_s=0.05))
        t0 = time.perf_counter()
        work(str(root), drain=True, poll_s=0.1, lease_s=120.0,
             n_devices=4, supervisor_policy=policy, env=env,
             max_attempts=2, packing=mode)
        return time.perf_counter() - t0, q.status()["counts"]

    tmp = tempfile.mkdtemp(prefix="bench_mesh_packing_")
    try:
        serial_wall, sc = drain(os.path.join(tmp, "serial"), "off")
        packed_wall, pc = drain(os.path.join(tmp, "packed"), "force")
        claims, frees = {}, {}
        violations = partial_rows = 0
        for rec in read_jsonl(os.path.join(tmp, "packed")):
            if rec.get("event") == "packing":
                kind = rec.get("kind")
                if kind == "slot_claim":
                    claims[rec.get("batch_id")] = rec
                elif kind == "slot_free":
                    frees[rec.get("batch_id")] = rec
                elif kind == "plan":
                    violations += int(rec.get("headroom_violations") or 0)
        # partial_result rows stream into the per-batch RUN-DIR chains and
        # results/<id>.partial.jsonl files, not the root chain
        for path in glob.glob(os.path.join(
                tmp, "packed", "work", "*", "results", "*.partial.jsonl")):
            with open(path, encoding="utf-8") as fh:
                partial_rows += sum(1 for _ in fh)
        busy_dev_s = 0.0
        spans = []
        for bid, c in claims.items():
            f = frees.get(bid)
            if f is None:
                continue
            t0_, t1_ = c.get("wall_time"), f.get("wall_time")
            if not (isinstance(t0_, (int, float))
                    and isinstance(t1_, (int, float)) and t1_ > t0_):
                continue
            width = int((c.get("slot") or {}).get("width") or 1)
            busy_dev_s += width * (t1_ - t0_)
            spans.append((t0_, t1_))
        overlapped = any(a0 < b1 and b0 < a1
                         for i, (a0, a1) in enumerate(spans)
                         for (b0, b1) in spans[i + 1:])
        util = (round(100.0 * busy_dev_s / (4 * packed_wall), 1)
                if packed_wall else None)
        both_done = (sc["done"] == 2 and sc["failed"] == 0
                     and pc["done"] == 2 and pc["failed"] == 0)
        return {
            "serial_wall_s": round(serial_wall, 3),
            "packed_wall_s": round(packed_wall, 3),
            "makespan_ratio": (round(packed_wall / serial_wall, 3)
                               if serial_wall and both_done else None),
            "utilization_pct": util,
            "headroom_violations": violations,
            "partial_rows": partial_rows,
            "packed_ok": bool(both_done and violations == 0 and overlapped),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_autoscale(n_requests=4, max_workers=2):
    """autoscale probe (ISSUE 16, fleet/autoscale.py): a seeded submit
    storm drained by the SLO-driven control loop, end-to-end through real
    worker processes.

    ``breach_to_recovery_s`` is the wall time from the FIRST windowed
    queue-wait breach the autoscaler detects to the queue fully drained —
    the breach-absorption latency the subsystem exists to bound.
    ``reject_eta_err_pct`` is the backpressure gate's reject-with-ETA
    accuracy: once the autoscaler has published its pool state, one extra
    submit is attempted under a deliberately tiny queue-wait SLO; the
    structured reject's predicted wait is compared against the OBSERVED
    remaining drain wall. The ``recovered`` flag is the correctness
    contract: every stormed request done, zero dead-letters, pool actually
    grew past one worker."""
    import shutil
    import tempfile

    from redcliff_tpu.fleet import autoscale as _autoscale
    from redcliff_tpu.fleet.__main__ import TINY_SPEC
    from redcliff_tpu.fleet.chaos import submit_storm
    from redcliff_tpu.fleet.queue import BackpressureReject, FleetQueue

    env = dict(os.environ)
    env.pop("REDCLIFF_FAULT_INJECT", None)
    env.pop("REDCLIFF_FAULT_MARKER", None)
    env.pop("REDCLIFF_SLO_QUEUE_P99_S", None)

    tmp = tempfile.mkdtemp(prefix="bench_autoscale_")
    root = os.path.join(tmp, "fleet")
    try:
        spec = json.loads(json.dumps(TINY_SPEC))
        spec["epochs"] = 1
        storm = submit_storm(root, n_requests, tenant="bench-storm",
                             seed=0, spec=spec)
        q = FleetQueue(root)
        policy = _autoscale.AutoscalePolicy(
            max_workers=max_workers, min_workers=0,
            # a target far below one tiny fit's wall forces immediate
            # growth to the cap — the storm IS the breach scenario
            target_drain_s=1.0, hysteresis_s=0.5, window_s=600.0,
            default_eta_s=30.0)
        scaler = _autoscale.Autoscaler(
            root, policy=policy, lease_s=60.0, poll_s=0.1, max_attempts=2,
            max_restarts=1, env=env,
            worker_args=["--max-restarts", "1",
                         "--base-delay-s", "0.05", "--max-delay-s", "0.05"],
            thresholds={"queue_p99_s": 0.05})
        max_seen = 0
        reject = None
        t_reject = None
        try:
            deadline = time.time() + 600.0
            while time.time() < deadline:
                scaler.tick()
                max_seen = max(max_seen, len(scaler.workers))
                if reject is None and q.pending():
                    # pool state is published: probe the admission gate
                    # under a deliberately tiny queue-wait SLO
                    os.environ["REDCLIFF_SLO_QUEUE_P99_S"] = "0.05"
                    try:
                        q.submit("bench-reject", [{"gen_lr": 1e-3}],
                                 spec=spec)
                    except BackpressureReject as rej:
                        reject = {"eta_s": rej.eta_s,
                                  "workers": rej.workers}
                        t_reject = time.perf_counter()
                    finally:
                        os.environ.pop("REDCLIFF_SLO_QUEUE_P99_S", None)
                if scaler.settled() and not any(
                        w["proc"].poll() is None
                        for w in scaler.workers.values()):
                    break
                time.sleep(0.2)
        finally:
            scaler.close()
        t_drained = time.perf_counter()
        t_drained_wall = time.time()
        counts = q.status()["counts"]
        breach_to_recovery = None
        if scaler.first_breach_wall is not None:
            breach_to_recovery = round(
                t_drained_wall - scaler.first_breach_wall, 3)
        eta_err_pct = None
        if reject is not None and t_reject is not None \
                and t_drained > t_reject:
            observed = t_drained - t_reject
            eta_err_pct = round(
                100.0 * abs(reject["eta_s"] - observed) / observed, 1)
        return {
            "stormed": len(storm["submitted"]),
            "max_workers_seen": max_seen,
            "done": counts["done"],
            "deadlettered": counts["deadletter"],
            "failed": counts["failed"],
            "breach_to_recovery_s": breach_to_recovery,
            "reject_eta_s": (reject or {}).get("eta_s"),
            "reject_eta_err_pct": eta_err_pct,
            "recovered": (counts["done"] == len(storm["submitted"])
                          and counts["deadletter"] == 0
                          and counts["failed"] == 0 and max_seen > 1),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_predictive_policy(n_devices=8, check_every=5, gather_ms=250.0):
    """predictive_policy probe (ISSUE 15, parallel/policy.py): heuristic
    bucket ladder vs the predictive scheduling policy on a SIMULATED
    mixed-shape early-stopping sweep.

    Ground truth is a synthetic per-(shape, width) cost table (epoch cost =
    per-lane ms x width + fixed; one compile cost per program family) from
    which a cost-model store is trained — the policy sees exactly what a
    converged store would hold, so the probe isolates the DECISIONS, not
    prediction error (MAPE health is the cost_model events' job). Both legs
    replay the same deterministic lane-retirement schedules through the
    same simulator: epochs are charged at the width the policy chose, and
    each FIRST-TOUCH (shape, width) program pays its compile once — the
    persistent-cache discipline, with the same warm-start set (the rungs
    the store has compile evidence for) on both legs.

    ``makespan_ratio`` < 1.0 is the acceptance claim (registered as an
    ``obs regress`` family with contract_max=1.0): the predictive policy
    wins by HOLDING compactions whose recompile costs more than the
    surviving epochs save, and by starting grids at WARM adjacent rungs
    instead of cold heuristic ones. ``empty_store_identical`` pins the
    fallback contract: with no store, both policies must produce
    bit-identical decision streams and makespans."""
    import numpy as np

    from redcliff_tpu.obs import costmodel
    from redcliff_tpu.parallel import compaction
    from redcliff_tpu.parallel.policy import (GridSchedulingPolicy,
                                              PredictiveSchedulingPolicy)

    shapes = {
        "A": {"per_lane_ms": 6.0, "fixed_ms": 30.0, "compile_ms": 9000.0},
        "B": {"per_lane_ms": 12.0, "fixed_ms": 50.0, "compile_ms": 15000.0},
        "C": {"per_lane_ms": 3.0, "fixed_ms": 20.0, "compile_ms": 6000.0},
    }
    # program families with compile evidence in the store == the warm
    # persistent-cache start set (both legs)
    warm_history = {"A": (32,), "B": (16,), "C": (8,)}
    # the mixed-shape queue: (shape, G_real, epochs)
    sweep = [("A", 24, 60), ("B", 12, 40), ("A", 9, 50), ("C", 30, 80),
             ("B", 20, 30), ("C", 7, 40), ("A", 18, 30), ("B", 5, 50)]

    def epoch_ms(sk, width):
        t = shapes[sk]
        return t["per_lane_ms"] * width + t["fixed_ms"]

    def live_at(g, epochs, e):
        # deterministic early stopping: ~linear decay to one survivor by
        # 60% of the horizon (the shape of a real criteria sweep)
        return max(1, int(round(g * (1.0 - 0.9 * min(
            e / max(epochs * 0.6, 1.0), 1.0)))))

    def trained_model():
        store = costmodel._empty_store()
        rows = []
        for sk in shapes:
            widths = {1, 2, 4}
            w = compaction.bucket_width(1, n_devices)
            while w <= 64:
                widths.add(w)
                w = compaction.bucket_width(w + 1, n_devices)
            for w in sorted(widths):
                rows.append({"shape": sk, "g_bucket": w, "epochs": 50,
                             "epoch_ms": epoch_ms(sk, w) * 50,
                             "compiles": (1 if w in warm_history[sk]
                                          else 0),
                             "compile_ms": (shapes[sk]["compile_ms"]
                                            if w in warm_history[sk]
                                            else 0.0)})
        costmodel._merge_rows(store, rows, "sim", now=1.0)
        return costmodel.CostModel(store)

    def simulate(make_policy):
        warm = {(sk, w) for sk, ws in warm_history.items() for w in ws}
        total_ms = 0.0
        compiles = holds = widens = 0
        decisions = []
        for sk, g, epochs in sweep:
            pol = make_policy(sk, epochs)
            w = pol.initial_width(g, n_devices)
            if hasattr(pol, "take_decision"):
                d = pol.take_decision()
                widens += bool(d and d.get("action") == "widen")
            decisions.append(("init", sk, g, w))
            orig = np.concatenate(
                [np.arange(g, dtype=np.int32),
                 np.full((w - g,), -1, np.int32)])
            active = np.zeros((w,), bool)
            active[:g] = True
            retired = set()
            if (sk, w) not in warm:
                total_ms += shapes[sk]["compile_ms"]
                compiles += 1
                warm.add((sk, w))
            for e in range(epochs):
                lanes = np.flatnonzero(active)
                live = live_at(g, epochs, e)
                if live < lanes.size:
                    active[lanes[live:]] = False
                total_ms += epoch_ms(sk, active.size)
                if e % check_every == 0:
                    plan = pol.compaction_plan(
                        active, orig, retired, n_devices,
                        epochs_remaining=epochs - e - 1)
                    if hasattr(pol, "take_decision"):
                        d = pol.take_decision()
                        holds += bool(d and d.get("action") == "hold")
                    if plan is not None:
                        decisions.append(("compact", sk, int(orig.size),
                                          plan.new_width, e))
                        retired.update(int(i) for i in plan.retire_ids)
                        orig = plan.orig_ids
                        active = plan.active.copy()
                        if (sk, plan.new_width) not in warm:
                            total_ms += shapes[sk]["compile_ms"]
                            compiles += 1
                            warm.add((sk, plan.new_width))
                        total_ms += gather_ms
        return total_ms, decisions, compiles, holds, widens

    def heuristic(sk, epochs):
        return GridSchedulingPolicy()

    model = trained_model()

    def predictive(sk, epochs):
        return PredictiveSchedulingPolicy(cost_model=model, shape_key=sk,
                                          platform="sim", epochs=epochs,
                                          gather_ms=gather_ms)

    heur_ms, heur_dec, heur_compiles, _, _ = simulate(heuristic)
    t0 = time.perf_counter()
    pred_ms, pred_dec, pred_compiles, holds, widens = simulate(predictive)
    decide_ms = (time.perf_counter() - t0) * 1e3

    def empty_predictive(sk, epochs):
        return PredictiveSchedulingPolicy(
            cost_model=costmodel.CostModel(costmodel._empty_store()),
            shape_key=sk, platform="sim", epochs=epochs,
            gather_ms=gather_ms)

    empty_ms, empty_dec, _, _, _ = simulate(empty_predictive)
    return {
        "fits": len(sweep),
        "n_devices": n_devices,
        "heuristic_makespan_s": round(heur_ms / 1e3, 3),
        "predictive_makespan_s": round(pred_ms / 1e3, 3),
        "makespan_ratio": (round(pred_ms / heur_ms, 4) if heur_ms
                           else None),
        "heuristic_compiles": heur_compiles,
        "predictive_compiles": pred_compiles,
        "holds": holds,
        "widens": widens,
        # fallback contract: an empty store must reproduce the heuristic
        # decision stream bit-for-bit (and therefore its makespan)
        "empty_store_identical": (empty_dec == heur_dec
                                  and empty_ms == heur_ms),
        "decide_ms": round(decide_ms, 3),
    }


def _bench_trace_export(n_records=2000):
    """trace_export probe: span -> Perfetto round-trip cost
    (obs/trace_export.py) on a synthetic but schema-shaped run dir —
    ``n_records`` span/epoch records written through the real MetricLogger,
    then one timed build+validate+serialize pass. Deterministic input, so
    the timing tracks the exporter, not a fit."""
    import shutil
    import tempfile

    from redcliff_tpu.obs.logging import MetricLogger
    from redcliff_tpu.obs.trace_export import build_trace, validate_trace

    run = tempfile.mkdtemp(prefix="bench_trace_")
    try:
        with MetricLogger(run) as log:
            log.log("fit_start", model="bench_probe", grid_size=8,
                    grid_width=8, shape={"num_chans": 4})
            for i in range(n_records):
                if i % 4 == 0:
                    log.log("epoch", epoch=i // 4, lanes_live=8,
                            grid_width=8, epoch_ms=1.0)
                else:
                    log.log("span", name="grid.dispatch", dur_ms=0.5,
                            span_id=i + 1, t_wall=time.time())
            log.log("fit_end")
        t0 = time.perf_counter()
        trace = build_trace(run)
        errors = validate_trace(trace)
        blob = json.dumps(trace, allow_nan=False)
        export_ms = (time.perf_counter() - t0) * 1e3
        return {"export_ms": round(export_ms, 2),
                "records": n_records + 2,
                "events": len(trace["traceEvents"]),
                "bytes": len(blob),
                "valid": not errors,
                "validate_errors": errors[:3]}
    finally:
        shutil.rmtree(run, ignore_errors=True)


def _bench_quality(jax):
    """quality probe (ISSUE 13, obs/quality.py): a small DETERMINISTIC
    synthetic sVAR grid fit with ground-truth graphs in hand — the live
    model-quality observatory's graph-recovery score (final AUROC/AUPR vs
    the true graphs), its convergence readout (plateaued-at epoch, top-k
    edge-set stability), and the per-check-window readout cost.

    ``overhead_pct`` follows the obs_overhead_pct discipline: the ISOLATED
    per-window summary cost (jit'd readout + gather, median of warm calls)
    against the fit's own measured steady-state epoch time, amortized at
    the production ``check_every=50`` cadence (the probe fit itself runs
    check_every=1 so every epoch exercises the path). Contract: <= 2 %,
    enforced by the ``quality.overhead_pct`` regress family; the AUROC
    floor rides ``quality.synthetic_auroc`` (contract_min)."""
    import numpy as np

    from redcliff_tpu.data import synthetic as S
    from redcliff_tpu.data.datasets import train_val_split
    from redcliff_tpu.models.redcliff import (RedcliffSCMLP,
                                              RedcliffSCMLPConfig)
    from redcliff_tpu.obs import quality as _q
    from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig

    D, K, G = 5, 2, 4
    p = S.reference_curation_params(D)
    graphs, acts, _ = S.generate_lagged_adjacency_graphs_for_factor_model(
        num_nodes=D, num_lags=2, num_factors=K,
        make_factors_orthogonal=True,
        make_factors_singular_components=False, rand_seed=7,
        off_diag_edge_strengths=p["off_diag_edge_strengths"],
        diag_receiving_node_forgetting_coeffs=p[
            "diag_receiving_node_forgetting_coeffs"],
        diag_sending_node_forgetting_coeffs=p[
            "diag_sending_node_forgetting_coeffs"],
        num_edges_per_graph=6)
    X, Y = S.generate_synthetic_dataset(
        jax.random.PRNGKey(9), graphs, acts, p["base_freqs"], p["noise_mu"],
        p["noise_var"], p["innovation_amp"], num_samples=96,
        recording_length=24, burnin_period=10, num_labeled_sys_states=K,
        noise_type="gaussian", noise_amp=0.0)
    train_ds, val_ds = train_val_split(X, Y, val_fraction=0.25,
                                       rng=np.random.default_rng(0))
    model = RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=D, gen_lag=2, gen_hidden=(12,), embed_lag=4,
        embed_hidden_sizes=(8,), num_factors=K, num_supervised_factors=K,
        forecast_coeff=1.0, factor_score_coeff=10.0,
        factor_weight_l1_coeff=0.01, adj_l1_reg_coeff=0.001,
        factor_cos_sim_coeff=0.01,
        factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive", num_sims=1,
        training_mode="combined"))
    tc = RedcliffTrainConfig(max_iter=20, batch_size=16, check_every=1,
                             gen_lr=5e-3, embed_lr=5e-3)
    spec = GridSpec(points=[{"gen_lr": 5e-3 * (i + 1)} for i in range(G)])
    prev = os.environ.get(_q.ENV_ENABLE)
    os.environ[_q.ENV_ENABLE] = "1"
    try:
        runner = RedcliffGridRunner(model, tc, spec)
        runner.fit(jax.random.PRNGKey(0), train_ds, val_ds,
                   true_gc=list(graphs))
        qstats = (runner.dispatch_stats or {}).get("quality") or {}

        # isolated per-window readout cost: the vmapped jit'd summary +
        # its host gather on a grid-width params stack (warm; median)
        qual_fn = jax.jit(jax.vmap(_q.make_summary_fn(model),
                                   in_axes=(0, None)))
        params = runner.init_grid(jax.random.PRNGKey(0))[0]
        first = next(iter(val_ds.batches(tc.batch_size)))
        import jax.numpy as jnp

        Xw = jnp.asarray(np.asarray(first[0])[
            : tc.max_samples_for_gc_tracking, : model.config.max_lag, :])
        gather = lambda out: {k: np.asarray(v) for k, v in out.items()}
        gather(qual_fn(params, Xw))  # warm the program
        times = []
        for _ in range(15):
            t0 = time.perf_counter()
            gather(qual_fn(params, Xw))
            times.append((time.perf_counter() - t0) * 1e3)
        per_window_ms = sorted(times)[len(times) // 2]

        # steady-state epoch cost from the fit's own accounting (the
        # width's first epoch carries compile skew — excluded)
        ds_stats = runner.dispatch_stats
        wkey = max(ds_stats["epoch_ms_by_width"],
                   key=lambda w: ds_stats["epochs_by_width"][w])
        n_w = ds_stats["epochs_by_width"][wkey]
        tot = ds_stats["epoch_ms_by_width"][wkey]
        first_ms = ds_stats["first_epoch_ms_by_width"].get(wkey, 0.0)
        steady_epoch_ms = ((tot - first_ms) / (n_w - 1) if n_w > 1
                           else tot / max(n_w, 1))
        cadence = RedcliffTrainConfig().check_every
        overhead_pct = (100.0 * per_window_ms
                        / (steady_epoch_ms * cadence)
                        if steady_epoch_ms else None)
        return {
            "grid_points": G,
            "epochs": ds_stats["epochs"],
            "windows": qstats.get("windows"),
            "final_auroc": qstats.get("mean_auroc"),
            "final_aupr": qstats.get("mean_aupr"),
            "edge_stability": qstats.get("mean_edge_stability"),
            "convergence_epoch": qstats.get("converged_at_epoch"),
            "plateaued": qstats.get("plateaued_count"),
            "per_window_ms": round(per_window_ms, 3),
            "steady_epoch_ms": round(steady_epoch_ms, 3),
            "check_every_amortized": cadence,
            "overhead_pct": (round(overhead_pct, 3)
                             if overhead_pct is not None else None),
        }
    finally:
        if prev is None:
            os.environ.pop(_q.ENV_ENABLE, None)
        else:
            os.environ[_q.ENV_ENABLE] = prev


def _bench_serve(jax, capacity=8, ticks=96):
    """serve probe (ISSUE 17, redcliff_tpu/serve): the streaming inference
    service on a fully leased slot table — per-sample ingest->answer p99
    through the shared vmapped dispatch, sustained samples/s at that
    stream count, and the churn-isolation pin (co-resident lanes
    byte-identical with vs without a chaos storm of connect/disconnect/
    NaN/abandoned neighbors; 1.0 means the pin holds).

    The latency run uses the real clock (that IS the metric); the
    isolation check rides :func:`redcliff_tpu.serve.chaos
    .churn_isolation_report`'s virtual clock so its verdict is pure math.
    Warmup (ring fill + jit compile of the dispatch) is excluded from the
    timed window.

    Elastic-data-plane legs (ISSUE 20): a 25%-occupancy leg under the
    forced occupancy ladder (the saturated run above can never show a
    dead-lane saving — every slot is leased) reporting the structural
    ``dead_lane_flops_saved_pct`` of riding the min rung; a backlogged
    fusion leg (``fuse=8``) reporting ``fused_samples_per_s`` through the
    single-scan drain; and a ``mixed_ratio_vs_f32`` leg re-running a short
    saturated window under ``precision_mode="mixed"`` (bf16 contraction
    emulation on CPU — the ratio is evidence the path works everywhere,
    the TPU speedup shows only on MXU hardware)."""
    from redcliff_tpu.models.redcliff import (RedcliffSCMLP,
                                              RedcliffSCMLPConfig)
    from redcliff_tpu.obs import slo as _slo
    from redcliff_tpu.serve import chaos as _chaos
    from redcliff_tpu.serve.service import ServeService

    D, K = 6, 2
    model = RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=D, gen_lag=2, gen_hidden=(12,), embed_lag=4,
        embed_hidden_sizes=(12,), num_factors=K, num_supervised_factors=K,
        factor_weight_l1_coeff=0.01, adj_l1_reg_coeff=0.001,
        factor_cos_sim_coeff=0.01,
        factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive", num_sims=1,
        training_mode="combined"))
    params = model.init(jax.random.PRNGKey(0))

    svc = ServeService(model, params, root=None, capacity=capacity,
                       resume=False)
    try:
        feeds = {f"s{i}": _chaos.stream_samples(i, ticks, D)
                 for i in range(capacity)}
        for sid in feeds:
            svc.connect(sid=sid, now=time.perf_counter())
        warm_ticks = model.config.embed_lag + 2
        lats, answered = [], 0
        t0 = time.perf_counter()
        for t in range(ticks):
            if t == warm_ticks:
                lats, answered = [], 0
                t0 = time.perf_counter()
            t_ing = {}
            for sid, arr in feeds.items():
                t_ing[sid] = time.perf_counter()
                svc.ingest(sid, arr[t], now=t_ing[sid])
            svc.pump(now=time.perf_counter())
            for sid in feeds:
                t_done = time.perf_counter()
                for _rec in svc.poll(sid, now=t_done):
                    answered += 1
                    # end-to-end ingest->poll (the service's own
                    # latency_ms shares a clock base with time.time(),
                    # not perf_counter — measure externally)
                    lats.append((t_done - t_ing[sid]) * 1e3)
        wall_s = time.perf_counter() - t0
    finally:
        svc.stop()

    iso = _chaos.churn_isolation_report(
        lambda: ServeService(model, params, root=None, capacity=capacity,
                             resume=False),
        chans=D, n_victims=2, n_samples=24, seed=0)

    def _timed_run(n_streams, seed0, fuse=1, burst=1, ladder="off",
                   precision_mode="f32", n_ticks=None, widths_out=None):
        """One timed serve window: ``n_streams`` feeds, ``burst`` samples
        ingested per stream per pump (backlog depth for the fusion path),
        warmup excluded. Returns (answered, wall_s)."""
        n_ticks = n_ticks if n_ticks is not None else ticks
        svc = ServeService(model, params, root=None, capacity=capacity,
                           resume=False, ladder=ladder, fuse=fuse,
                           precision_mode=precision_mode)
        try:
            fd = {f"x{i}": _chaos.stream_samples(seed0 + i,
                                                 n_ticks * burst, D)
                  for i in range(n_streams)}
            for sid in fd:
                svc.connect(sid=sid, now=time.perf_counter())
            warm = model.config.embed_lag + 2
            n_ans = 0
            t0 = time.perf_counter()
            for t in range(n_ticks):
                if t == warm:
                    n_ans, t0 = 0, time.perf_counter()
                for sid, arr in fd.items():
                    for j in range(burst):
                        svc.ingest(sid, arr[t * burst + j],
                                   now=time.perf_counter())
                svc.pump(now=time.perf_counter())
                if widths_out is not None and t >= warm:
                    widths_out.append(svc.engine.width)
                for sid in fd:
                    n_ans += len(svc.poll(sid, now=time.perf_counter()))
            return n_ans, time.perf_counter() - t0
        finally:
            svc.stop()

    # 25%-occupancy ladder leg: capacity//4 streams, forced ladder with
    # tight hysteresis so the shrink lands inside the window
    low_n = max(1, capacity // 4)
    old_hold = os.environ.get("REDCLIFF_SERVE_LADDER_HOLD")
    os.environ["REDCLIFF_SERVE_LADDER_HOLD"] = "2"
    try:
        widths = []
        low_ans, low_wall = _timed_run(low_n, 200, ladder="force",
                                       widths_out=widths)
    finally:
        if old_hold is None:
            os.environ.pop("REDCLIFF_SERVE_LADDER_HOLD", None)
        else:
            os.environ["REDCLIFF_SERVE_LADDER_HOLD"] = old_hold
    mean_width = (sum(widths) / len(widths)) if widths else capacity
    dead_saved = round(100.0 * (1.0 - mean_width / capacity), 1)

    # backlogged fusion leg: each pump drains an 8-deep backlog in one scan
    fuse_ans, fuse_wall = _timed_run(low_n, 300, fuse=8, burst=8,
                                     n_ticks=max(12, ticks // 8))

    # mixed-precision leg: short saturated window, mixed vs f32 throughput
    mix_ticks = max(16, ticks // 3)
    f32_ans, f32_wall = _timed_run(capacity, 400, n_ticks=mix_ticks)
    mix_ans, mix_wall = _timed_run(capacity, 400, n_ticks=mix_ticks,
                                   precision_mode="mixed")
    mixed_ratio = None
    if f32_ans and f32_wall > 0 and mix_wall > 0:
        mixed_ratio = round((mix_ans / mix_wall) / (f32_ans / f32_wall), 3)

    return {
        "streams_per_chip": capacity,
        "ticks_timed": ticks - warm_ticks,
        "answered": answered,
        "p50_ms": (round(_slo.percentile(lats, 50.0), 3) if lats else None),
        "p99_ms": (round(_slo.percentile(lats, 99.0), 3) if lats else None),
        "samples_per_s": (round(answered / wall_s, 1) if wall_s > 0
                          else None),
        "isolation_ok": 1.0 if iso["identical"] else 0.0,
        "isolation_compared": iso["compared"],
        "isolation_rejects": iso["rejects"],
        "low_occupancy_streams": low_n,
        "low_occupancy_mean_rung": round(mean_width, 2),
        "dead_lane_flops_saved_pct": dead_saved,
        "low_occupancy_samples_per_s": (round(low_ans / low_wall, 1)
                                        if low_wall > 0 else None),
        "fused_samples_per_s": (round(fuse_ans / fuse_wall, 1)
                                if fuse_wall > 0 else None),
        "mixed_ratio_vs_f32": mixed_ratio,
    }


def _bench_fleet_trace(n_requests=50):
    """fleet_trace probe (ISSUE 12): the whole-fleet Perfetto join cost
    (obs/trace_export.py ``--fleet``) on a synthetic ``n_requests``-request
    lifecycle history — every request submitted / claimed / attempted /
    settled with deterministic timings, then one timed
    build+validate+serialize pass. Deterministic input, so the timing
    tracks the ledger join, not a fit."""
    import shutil
    import tempfile

    from redcliff_tpu.fleet import history as fleet_history
    from redcliff_tpu.obs.slo import compute_slo
    from redcliff_tpu.obs.trace_export import (build_fleet_trace,
                                               validate_trace)

    root = tempfile.mkdtemp(prefix="bench_fleet_trace_")
    try:
        t = 1_000_000_000.0
        for i in range(n_requests):
            rid, tr = f"req-{i:04d}", f"tr-{i:032x}"
            tenant = f"tenant-{i % 5}"
            fleet_history.append_event(root, "submitted", request_id=rid,
                                       trace_id=tr, tenant=tenant,
                                       now=t + i, submitted_at=t + i,
                                       deadline_s=600.0)
            fleet_history.append_event(root, "claimed", request_id=rid,
                                       trace_id=tr, tenant=tenant,
                                       batch_id=f"b-{i % 8}",
                                       now=t + i + 2, worker="w-bench")
            fleet_history.append_event(root, "attempt", request_id=rid,
                                       trace_id=tr, tenant=tenant,
                                       batch_id=f"b-{i % 8}",
                                       now=t + i + 5, started_at=t + i + 3,
                                       attempts=1, classification="clean")
            fleet_history.append_event(root, "settled", request_id=rid,
                                       trace_id=tr, now=t + i + 30,
                                       state="done")
        t0 = time.perf_counter()
        trace = build_fleet_trace(root)
        errors = validate_trace(trace)
        blob = json.dumps(trace, allow_nan=False)
        export_ms = (time.perf_counter() - t0) * 1e3
        slo = compute_slo(fleet_history.read_history(root), thresholds={})
        return {"export_ms": round(export_ms, 2),
                "requests": n_requests,
                "history_records": 4 * n_requests,
                "events": len(trace["traceEvents"]),
                "bytes": len(blob),
                "valid": not errors and slo["settled"] == n_requests,
                "validate_errors": errors[:3]}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _measure(platform):
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()
    # persistent XLA compilation cache (versioned subdir per toolchain +
    # backend): cold compiles below land in it, the warm-vs-cold probe reads
    # it back, and future bench runs / grid fits on this machine warm-start.
    # REDCLIFF_COMPILE_CACHE overrides the default tmp location
    from redcliff_tpu.runtime import compileobs

    import tempfile

    compile_cache_dir = compileobs.enable_cache(
        os.environ.get(compileobs.ENV_CACHE_DIR)
        or os.path.join(tempfile.gettempdir(), "redcliff_xla_cache"))
    if platform == "tpu" and devices[0].platform == "cpu":
        # the tunnel dropped between the parent's probe and this child's
        # init and jax fell back to CPU — exit non-zero so the parent keeps
        # probing instead of publishing a CPU number as the TPU result
        print("measure child: requested accelerator but backend is cpu",
              file=sys.stderr, flush=True)
        raise SystemExit(3)

    from redcliff_tpu.models.redcliff import RedcliffSCMLP

    dev_kind = devices[0].device_kind
    on_cpu = devices[0].platform == "cpu"
    model = RedcliffSCMLP(_model_config())
    B = 64
    # headline = the largest grid the bench sweeps: the framework's execution
    # model is "batch as many grid points as fit", and G=64 still fits this
    # model in a fraction of HBM (G-scaling below shows near-linear gains)
    G_HEAD = 16 if on_cpu else 64
    steps = 8 if on_cpu else 30
    scan_k = 4 if on_cpu else 8
    peak = PEAK_FLOPS.get(dev_kind)

    t_start = time.perf_counter()
    budget_s = 180.0 if on_cpu else 420.0
    g_scaling = {}
    headline = None
    bf16 = None
    # each extra G costs two compiles (~40s each on TPU); keep the sweep small
    # enough that the whole bench stays under the measurement timeout
    extra_g = (1, 4) if on_cpu else (1, 4, 128, 256)
    for G in (G_HEAD,) + extra_g:
        if G != G_HEAD and time.perf_counter() - t_start > budget_s:
            print(f"bench: skipping G={G} (wall-clock budget)", file=sys.stderr)
            continue
        print(f"bench: measuring G={G}", file=sys.stderr, flush=True)
        r = _bench_grid(jax, model, G, B, steps, scan_k)
        g_scaling[str(G)] = {
            "wps": round(r["wps"], 1),
            "wps_scan": round(r["scan_wps"], 1),
            # the epoch-scan engine entry: same k batches, one dispatch
            # gathering+scanning them from device-resident data by index
            "epoch_scan": (round(r["epoch_wps"], 1)
                           if r["epoch_wps"] is not None else None),
            "mfu_pct": _mfu_pct(r["scan_flops"], r["scan_dispatch_s"], peak)
            if not on_cpu else None,
        }
        if G == G_HEAD:
            headline = r

    # mixed-precision probe (the promoted bf16 field, ISSUE 14): the
    # SMALLEST measured g_scaling point re-run under the PRODUCTION
    # precision_mode="mixed" path (bf16 MXU contractions, f32 master
    # params/reductions, numerics sentinel armed), every backend (the CPU
    # fallback emulates bf16 matmuls, slower but measured — never null).
    # Scan dispatch only (one compile); wps_ratio_vs_f32 vs the same
    # point's f32 wps_scan is the acceptance comparable, and the sentinel
    # skip count is the precision-cliff evidence (0 = no cliff at this
    # shape). `bf16` stays as the legacy alias so the BENCH_r* trajectory
    # keeps comparing
    G_small = min(int(g) for g in g_scaling)
    print(f"bench: measuring mixed precision G={G_small}", file=sys.stderr,
          flush=True)
    try:
        from redcliff_tpu.runtime.numerics import numerics_summary

        rb = _bench_grid(jax, model, G_small, B, steps, scan_k,
                         precision_mode="mixed", scan_only=True)
        f32_wps = g_scaling[str(G_small)]["wps_scan"]
        skips = numerics_summary(rb["nstate"])["skipped"]
        mixed_precision = {
            "grid_points": G_small,
            "wps_scan": round(rb["scan_wps"], 1),
            "wps_ratio_vs_f32": (round(rb["scan_wps"] / f32_wps, 3)
                                 if f32_wps else None),
            "sentinel_skips": int(np.sum(skips)),
            "mfu_pct": (_mfu_pct(rb["scan_flops"], rb["scan_dispatch_s"],
                                 peak) if not on_cpu else None)}
        bf16 = {"grid_points": G_small,
                "wps_scan": mixed_precision["wps_scan"],
                "ratio_vs_f32": mixed_precision["wps_ratio_vs_f32"],
                "mfu_pct": mixed_precision["mfu_pct"]}
    except Exception as e:  # never fail the bench over the precision probe
        mixed_precision = {"error": f"{type(e).__name__}: {e}",
                           "wps_ratio_vs_f32": None}
        bf16 = {"error": f"{type(e).__name__}: {e}"}

    seq_steps = max(steps // 3, 3)
    seq_wps = _bench_sequential(jax, model, headline["runner"],
                                headline["state"], G_HEAD, B, seq_steps)

    # dispatch-count contract per single-phase epoch (shared helper with the
    # tier-1 tripwire test) for a nominal 32-full-batch epoch, plus the
    # measured main-thread checkpoint stall (async hand-off vs sync write)
    from redcliff_tpu.data.pipeline import dispatch_budget

    nominal_nb = 32
    dispatches_per_epoch = {
        "num_full_batches": nominal_nb,
        "per_batch": dispatch_budget(nominal_nb, mode="per_batch"),
        "kscan": dispatch_budget(nominal_nb, scan_batches=scan_k,
                                 mode="kscan"),
        "epoch_scan": dispatch_budget(nominal_nb, mode="epoch"),
    }
    try:
        ckpt_stall_ms = _bench_ckpt_stall(jax, headline["state"])
    except Exception as e:  # never fail the bench over the stall probe
        ckpt_stall_ms = {"error": f"{type(e).__name__}: {e}"}

    # elastic-scheduler win: dead-lane FLOPs recovered by compaction on an
    # early-stopping grid (parallel/compaction.py)
    try:
        compaction_probe = _bench_dead_lanes(jax)
    except Exception as e:
        compaction_probe = {"error": f"{type(e).__name__}: {e}"}

    # elastic re-meshing: host-side re-shard plan latency at sweep-service
    # scale (what a degraded-mesh resume pays before its first dispatch)
    try:
        remesh_probe = _bench_remesh()
    except Exception as e:
        remesh_probe = {"error": f"{type(e).__name__}: {e}"}

    # persistent-cache win: cold (captured at the headline scan compile,
    # cache miss) vs warm (in-memory caches cleared, identical program
    # re-lowered -> persistent-cache retrieval)
    try:
        cc = _bench_compile_cache(jax, headline["runner"],
                                  headline["compile_args"])
        cold_ms = headline["scan_compile"]["compile_ms"]
        cc.update({
            "cold_compile_ms": cold_ms,
            "cold_cache_hits": headline["scan_compile"]["cache_hits"],
            "warm_vs_cold_speedup": (
                round(cold_ms / cc["warm_compile_ms"], 2)
                if cc["warm_compile_ms"] else None),
        })
        compile_cache = cc
    except Exception as e:
        compile_cache = {"error": f"{type(e).__name__}: {e}",
                         "dir": compile_cache_dir}

    # kernel-tiling autotune (ops/autotune.py): search cost + winner vs
    # default-tile speedup + the zero-re-search persistence contract
    try:
        autotune_probe = _bench_autotune(jax)
    except Exception as e:  # never fail the bench over the autotune probe
        autotune_probe = {"error": f"{type(e).__name__}: {e}",
                          "speedup_vs_default": None}

    # telemetry-spine overhead (redcliff_tpu/obs): tracing-on vs tracing-off
    # throughput through the engine's dispatch chokepoint, every round
    try:
        obs_overhead = _bench_obs_overhead(jax, headline["runner"],
                                           headline["state"])
    except Exception as e:  # never fail the bench over the obs probe
        obs_overhead = {"error": f"{type(e).__name__}: {e}"}

    # device-memory observatory (obs/memory.py): analytical footprint vs
    # the measured allocator watermark (null-with-reason on CPU)
    try:
        mem_model = _bench_mem_model(jax, model, headline["state"], G_HEAD,
                                     B)
    except Exception as e:  # never fail the bench over the memory probe
        mem_model = {"error": f"{type(e).__name__}: {e}",
                     "abs_err_pct": None}

    # span -> Perfetto round-trip cost (obs/trace_export.py)
    try:
        trace_export = _bench_trace_export()
    except Exception as e:  # never fail the bench over the export probe
        trace_export = {"error": f"{type(e).__name__}: {e}"}

    # fleet admission planner: packed vs FIFO mesh-slot utilization + plan
    # latency on the synthetic heterogeneous request mix
    try:
        fleet_probe = _bench_fleet()
    except Exception as e:  # never fail the bench over the fleet probe
        fleet_probe = {"error": f"{type(e).__name__}: {e}"}

    # fleet failure containment: healthy-sibling latency with vs without a
    # poison co-tenant (two real drains, same bucket width)
    try:
        fleet_containment = _bench_fleet_containment()
    except Exception as e:  # never fail the bench over the containment probe
        fleet_containment = {"error": f"{type(e).__name__}: {e}",
                             "latency_ratio": None}

    # fleet trace export: the whole-fleet Perfetto join on a synthetic
    # 50-request lifecycle history (obs/trace_export.py --fleet)
    try:
        fleet_trace = _bench_fleet_trace()
    except Exception as e:  # never fail the bench over the trace probe
        fleet_trace = {"error": f"{type(e).__name__}: {e}"}

    # predictive scheduling policy (ISSUE 15): simulated mixed-shape sweep
    # makespan — predictive vs heuristic ladder, with the empty-store
    # bit-identity contract
    try:
        predictive_policy = _bench_predictive_policy()
    except Exception as e:  # never fail the bench over the policy probe
        predictive_policy = {"error": f"{type(e).__name__}: {e}",
                             "makespan_ratio": None}

    # spatial mesh packing (ISSUE 18): two heterogeneous batches drained
    # serially vs co-resident on disjoint sub-mesh slots of a simulated
    # 4-device pool — packed/serial makespan + pool utilization
    try:
        packing_probe = _bench_mesh_packing()
    except Exception as e:  # never fail the bench over the packing probe
        packing_probe = {"error": f"{type(e).__name__}: {e}",
                         "makespan_ratio": None, "utilization_pct": None}

    # SLO-driven autoscaling (ISSUE 16): seeded submit storm drained by the
    # control loop through real workers — breach-absorption latency + the
    # backpressure gate's reject-with-ETA accuracy
    try:
        autoscale_probe = _bench_autoscale()
    except Exception as e:  # never fail the bench over the autoscale probe
        autoscale_probe = {"error": f"{type(e).__name__}: {e}",
                           "breach_to_recovery_s": None,
                           "reject_eta_err_pct": None}

    # model-quality observatory (obs/quality.py): graph recovery + readout
    # overhead on a deterministic synthetic sVAR grid fit with ground truth
    try:
        quality_probe = _bench_quality(jax)
    except Exception as e:  # never fail the bench over the quality probe
        quality_probe = {"error": f"{type(e).__name__}: {e}",
                         "final_auroc": None, "overhead_pct": None}

    # streaming inference service (ISSUE 17, redcliff_tpu/serve): saturated
    # slot-table dispatch latency + the churn-isolation contract
    try:
        serve_probe = _bench_serve(jax)
    except Exception as e:  # never fail the bench over the serve probe
        serve_probe = {"error": f"{type(e).__name__}: {e}",
                       "p99_ms": None, "isolation_ok": None}

    mfu_head = (_mfu_pct(headline["scan_flops"], headline["scan_dispatch_s"],
                         peak) if not on_cpu else None)
    _emit({
        "metric": METRIC,
        "value": round(headline["scan_wps"], 1),
        "unit": "windows/s/chip",
        "vs_baseline": round(headline["scan_wps"] / seq_wps, 2),
        "device": dev_kind,
        "platform": devices[0].platform,
        "grid_points": G_HEAD,
        "batch_size": B,
        "scan_batches": scan_k,
        "per_step_wps": round(headline["wps"], 1),
        "epoch_scan_wps": (round(headline["epoch_wps"], 1)
                           if headline["epoch_wps"] is not None else None),
        "flops_per_step": headline["flops"],
        "mfu_pct": mfu_head,
        "g_scaling": g_scaling,
        "dispatches_per_epoch": dispatches_per_epoch,
        "ckpt_stall_ms": ckpt_stall_ms,
        "bf16": bf16,
        "mixed_precision": mixed_precision,
        "autotune": autotune_probe,
        "dead_lane_flops_saved_pct": compaction_probe.get(
            "dead_lane_flops_saved_pct"),
        "compaction": compaction_probe,
        "remesh": remesh_probe,
        "compile_cache": compile_cache,
        "obs_overhead_pct": obs_overhead.get("pct"),
        "obs_overhead": obs_overhead,
        "mem_model_err_pct": mem_model.get("abs_err_pct"),
        "mem_model": mem_model,
        "trace_export": trace_export,
        "fleet": fleet_probe,
        "fleet_containment": fleet_containment,
        "fleet_trace": fleet_trace,
        "predictive_policy": predictive_policy,
        "packing": packing_probe,
        "autoscale": autoscale_probe,
        "quality": quality_probe,
        "serve": serve_probe,
        "error": None,
    })


def main():
    if "--measure" in sys.argv:
        _measure(sys.argv[sys.argv.index("--measure") + 1])
        return
    _orchestrate()


if __name__ == "__main__":
    try:
        main()
    except Exception as e:
        traceback.print_exc(file=sys.stderr)
        _emit({"metric": METRIC, "value": None, "unit": "windows/s/chip",
               "vs_baseline": None, "error": f"{type(e).__name__}: {e}"})
        sys.exit(0)
