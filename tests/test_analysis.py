"""L6 analysis/reporting layer tests: complexity scoring, cross-experiment
condensation, ablation summaries, model visualization, and the one-command
report (notebook + summ_/plotCrossExpSummaries capability)."""
import os
import pickle

import numpy as np
import pytest

from redcliff_tpu.eval.analysis import (
    ALG_ALIASES,
    collect_summary_figures,
    complexity_category,
    condense_cross_experiment,
    factor_selection_table,
    generate_analysis_report,
    network_complexity,
    parse_system_name,
    run_cross_experiment_analysis,
    short_system_name,
    summarize_ablations,
    visualize_factors_across_folds,
    visualize_trained_model_factors,
)
from redcliff_tpu.eval.summaries import OFFDIAG_PARADIGM


def test_network_complexity_and_banding():
    # (ne / (nc^2 - nc))^-1: the paper's inverse-sparsity score
    assert network_complexity(12, 11) == pytest.approx(132 / 11)  # 12.0
    assert network_complexity(3, 1) == pytest.approx(6.0)
    assert network_complexity(6, 2) == pytest.approx(15.0)
    assert complexity_category(6.0) == "Low"
    assert complexity_category(12.0) == "Moderate"
    assert complexity_category(15.0) == "High"
    # bounds are (lower, upper]: <=7 Low, >13 High (ref plotCross...py:144-149)
    assert complexity_category(7.0) == "Low"
    assert complexity_category(13.0) == "Moderate"


def test_parse_system_name_both_forms():
    long = ("numF2_numSF2_numN12_numE11_edgesNonlinear_labelsOneHot_"
            "noiT-gaussian_noiL-1-0_oFscF_data")
    d = parse_system_name(long)
    assert d["num_factors"] == 2
    assert d["num_supervised_factors"] == 2
    assert d["num_nodes"] == 12
    assert d["num_edges"] == 11
    assert short_system_name(long) == "nN12_nE11_nF2"
    d2 = parse_system_name("nN6_nE4_nF3")
    assert (d2["num_nodes"], d2["num_edges"], d2["num_factors"]) == (6, 4, 3)


def _fake_summary(alg_vals):
    """A full_comparrisson_summary dict in the cross_alg driver's layout."""
    by_alg = {}
    for alg, vals in alg_vals.items():
        vals = np.asarray(vals, dtype=np.float64)
        by_alg[alg] = {
            "f1_vals_across_factors": vals.tolist(),
            "f1_mean_across_factors": float(vals.mean()),
            "f1_median_across_factors": float(np.median(vals)),
            "f1_std_dev_across_factors": float(vals.std()),
            "f1_mean_std_err_across_factors": float(
                vals.std() / np.sqrt(len(vals))),
        }
    return {"cv_main": {OFFDIAG_PARADIGM: by_alg}}


def _write_eval_tree(root):
    systems = {
        # complexity (12^2-12)/11 = 12.0 -> Moderate
        "numF2_numSF2_numN12_numE11_data": {
            "REDCLIFF_S_CMLP_WithSmoothing": [0.9, 0.8],
            "CMLP": [0.6, 0.5],
        },
        # complexity 6 -> Low
        "numF2_numSF2_numN3_numE1_data": {
            "REDCLIFF_S_CMLP_WithSmoothing": [0.7, 0.75],
            "CMLP": [0.72, 0.6],
        },
    }
    for sys_key, alg_vals in systems.items():
        d = os.path.join(root, sys_key)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "full_comparrisson_summary.pkl"),
                  "wb") as f:
            pickle.dump(_fake_summary(alg_vals), f)
    return systems


def test_condense_cross_experiment_with_improvements(tmp_path):
    _write_eval_tree(str(tmp_path))
    out = condense_cross_experiment(
        str(tmp_path), baseline_alg="REDCLIFF_S_CMLP_WithSmoothing")
    assert len(out) == 2
    entry = out["numF2_numSF2_numN12_numE11_data"]
    assert entry["complexity"] == pytest.approx(12.0)
    assert entry["alg_stats"]["CMLP"]["mean"] == pytest.approx(0.55)
    # improvement vs baseline: per-factor diffs [0.3, 0.3] -> mean 0.3, sem 0
    imp = entry["improvements"]["CMLP"]
    assert imp["mean"] == pytest.approx(0.3)
    assert imp["sem"] == pytest.approx(0.0)
    # the baseline's improvement over itself is zero
    assert entry["improvements"]["REDCLIFF_S_CMLP_WithSmoothing"][
        "mean"] == pytest.approx(0.0)


def test_run_cross_experiment_analysis_writes_figures(tmp_path):
    eval_root = tmp_path / "evals"
    save_root = tmp_path / "report"
    _write_eval_tree(str(eval_root))
    out = run_cross_experiment_analysis(str(eval_root), str(save_root))
    assert out["by_category"]["Moderate"] == [
        "numF2_numSF2_numN12_numE11_data"]
    assert out["by_category"]["Low"] == ["numF2_numSF2_numN3_numE1_data"]
    names = os.listdir(save_root)
    assert "system_details.pkl" in names
    assert any(n.startswith("Moderate_complexity_cross_synth") for n in names)
    assert any("REDCImprovement" in n for n in names)
    with open(save_root / "system_details.pkl", "rb") as f:
        details = pickle.load(f)
    assert details["numF2_numSF2_numN12_numE11_data"][
        "dataset_name"] == "nN12_nE11_nF2"


def test_summarize_ablations_golden():
    summaries = {
        "full": _fake_summary({"REDCLIFF_S_CMLP": [0.9, 0.8]}),
        "no_cos_sim": _fake_summary({"REDCLIFF_S_CMLP": [0.7, 0.6]}),
    }
    table = summarize_ablations(summaries, "full")
    assert table["full"]["mean"] == pytest.approx(0.85)
    assert table["full"]["full_minus_variant_mean"] == pytest.approx(0.0)
    assert table["no_cos_sim"]["mean"] == pytest.approx(0.65)
    assert table["no_cos_sim"]["full_minus_variant_mean"] == pytest.approx(0.2)
    assert table["no_cos_sim"]["full_minus_variant_sem"] == pytest.approx(0.0)


def test_factor_selection_table(tmp_path):
    runs = {}
    for nf, losses in ((2, [3.0, 2.0, 1.5]), (3, [3.0, 1.0, 1.2])):
        fold_dirs = []
        for fold in range(2):
            d = tmp_path / f"nf{nf}_fold{fold}"
            os.makedirs(d)
            meta = {"avg_forecasting_loss": [x + 0.1 * fold for x in losses],
                    "avg_factor_loss": [x * 0.5 for x in losses]}
            with open(d / "training_meta_data_and_hyper_parameters.pkl",
                      "wb") as f:
                pickle.dump(meta, f)
            fold_dirs.append(str(d))
        runs[nf] = fold_dirs
    table = factor_selection_table(runs)
    # best (min) forecasting loss per fold: nf=2 -> [1.5, 1.6], nf=3 -> [1.0, 1.1]
    assert table[2]["avg_forecasting_loss_mean"] == pytest.approx(1.55)
    assert table[3]["avg_forecasting_loss_mean"] == pytest.approx(1.05)
    assert table[3]["avg_factor_loss_mean"] == pytest.approx(0.5)


def test_collect_summary_figures(tmp_path):
    eval_root = tmp_path / "evals"
    sub = eval_root / "sysA" / "cv_main"
    os.makedirs(sub)
    fig = sub / f"factor_level_{OFFDIAG_PARADIGM}_f1_vals_by_algorithm.png"
    fig.write_bytes(b"png")
    out = collect_summary_figures(str(eval_root), str(tmp_path / "report"))
    assert len(out) == 1
    assert os.path.basename(out[0]).startswith("sysA_factor_level_")


def test_generate_analysis_report_end_to_end(tmp_path):
    eval_root = tmp_path / "evals"
    save_root = tmp_path / "report"
    _write_eval_tree(str(eval_root))
    report = generate_analysis_report(str(eval_root), str(save_root))
    assert "off_diag_f1" in report["tables"]
    mean_table = report["tables"]["off_diag_f1"]["mean"]
    assert mean_table["numF2_numSF2_numN12_numE11_data"][
        "CMLP"] == pytest.approx(0.55)
    assert (save_root / "analysis_report.pkl").exists()
    assert (save_root / "system_details.pkl").exists()
    # headline CSV written by the summaries condenser
    csvs = [n for n in os.listdir(save_root) if n.endswith(".csv")]
    assert csvs


def test_visualize_trained_model_factors(tmp_path):
    """Model visualization path on a loadable artifact (the notebook's
    per-fold GC visualization cells)."""
    from redcliff_tpu.models.dynotears import DynotearsConfig

    rng = np.random.default_rng(0)
    true_g = (rng.uniform(size=(4, 4, 1)) > 0.5).astype(float)
    runs = []
    for fold in range(2):
        run = tmp_path / f"dset_fold{fold}_run"
        os.makedirs(run)
        with open(run / "final_best_model.bin", "wb") as f:
            pickle.dump({"model_class": "DynotearsVanillaModel",
                         "config": DynotearsConfig(lag_size=1),
                         "a_est": true_g[:, :, 0] + 0.01 * fold}, f)
        runs.append(str(run))

    save = tmp_path / "vis"
    ests = visualize_trained_model_factors(
        runs[0], "DYNOTEARS_Vanilla", 1, str(save), true_gcs=[true_g])
    assert len(ests) == 1
    assert (save / "factor_0_gc_est.png").exists()
    assert (save / "all_factors_gc_est.png").exists()

    avg = visualize_factors_across_folds(
        runs, "DYNOTEARS_Vanilla", 1, str(tmp_path / "vis_folds"),
        true_gcs=[true_g])
    assert len(avg) == 1
    assert (tmp_path / "vis_folds" / "avg_across_folds_gc_est.png").exists()
    assert (tmp_path / "vis_folds" / "fold_0" / "factor_0_gc_est.png").exists()
    # normalized averaging keeps estimates on [0, 1]
    assert np.max(avg[0]) <= 1.0 + 1e-9
