"""Fleet sweep service tests (redcliff_tpu/fleet, ISSUE 10).

Queue durability units (spool/claim/lease/terminal protocol), admission
planner units (same-shape batching, headroom gate, ordering, packed-vs-FIFO
utilization), worker end-to-end (submit -> plan -> supervise -> complete,
with tenant-stamped telemetry the watch/report CLIs join), and the
crash-safety ACCEPTANCE: SIGKILL the worker mid-fit -> lease expires -> a
second worker reclaims the recorded batch and resumes from the grid
checkpoint -> final per-request results bit-identical to an uninterrupted
run, no request lost, none run twice.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from redcliff_tpu.fleet import planner
from redcliff_tpu.fleet.queue import FleetQueue, LeaseLost
from redcliff_tpu.fleet.__main__ import TINY_POINTS, TINY_SPEC
from redcliff_tpu.obs import schema as obs_schema
from redcliff_tpu.obs.logging import read_jsonl


def _submit_tiny(q, tenant, epochs=2, points=None, **kw):
    spec = json.loads(json.dumps(TINY_SPEC))
    spec["epochs"] = epochs
    return q.submit(tenant, points or list(TINY_POINTS), spec=spec, **kw)


# ---------------------------------------------------------------------------
# queue durability units
# ---------------------------------------------------------------------------
def test_submit_pending_claim_complete_roundtrip(tmp_path):
    q = FleetQueue(tmp_path / "fleet")
    rid = _submit_tiny(q, "alice", priority=3)
    assert [r["request_id"] for r in q.pending()] == [rid]
    rec = q.pending()[0]
    assert rec["tenant"] == "alice" and rec["priority"] == 3
    assert rec["spec"]["model_config"]["num_chans"] == 4

    lease = q.claim(rid, "w1", lease_s=30.0)
    assert lease is not None
    # live lease: not pending, not claimable by another worker
    assert q.pending() == []
    assert q.claim(rid, "w2", lease_s=30.0) is None

    assert q.complete(rid, result={"ok": True}) is True
    assert q.is_terminal(rid)
    assert q.result(rid)["result"] == {"ok": True}
    # never run twice: the done record is first-writer-wins and the request
    # is no longer claimable
    assert q.complete(rid, result={"ok": False}) is False
    assert q.result(rid)["result"] == {"ok": True}
    assert q.claim(rid, "w3", lease_s=30.0) is None
    assert q.status()["counts"]["done"] == 1


def test_release_requeues(tmp_path):
    q = FleetQueue(tmp_path)
    rid = _submit_tiny(q, "t")
    lease = q.claim(rid, "w1", lease_s=30.0)
    lease.release()
    assert [r["request_id"] for r in q.pending()] == [rid]
    assert q.claim(rid, "w2", lease_s=30.0) is not None


def test_lease_expiry_reclaim_inherits_batch(tmp_path):
    q = FleetQueue(tmp_path)
    rid = _submit_tiny(q, "t")
    lease = q.claim(rid, "w1", lease_s=60.0, batch_id="batch-abc",
                    batch_request_ids=[rid])
    assert lease is not None
    # live: a second claim loses
    assert q.claim(rid, "w2", lease_s=30.0) is None
    # deterministic expiry (no load-sensitive sleep): renew with a zero
    # lease, so the claim is expired at the very next clock read
    lease.renew(0.0)
    assert q.expired_claims().get("batch-abc")
    re = q.claim(rid, "w2", lease_s=30.0)
    assert re is not None
    # the reclaim inherits the dead worker's batch composition so the new
    # worker resumes the SAME run dir/checkpoint
    assert re.data["batch_id"] == "batch-abc"
    assert re.data["batch_request_ids"] == [rid]
    assert re.data["reclaimed_from"]["worker"] == "w1"
    # the original holder's renew/release must not clobber the new owner
    with pytest.raises(LeaseLost):
        lease.renew(30.0)
    lease.release()
    assert q.lease_of(rid)["worker"] == "w2"


def test_renew_extends_expiry(tmp_path):
    q = FleetQueue(tmp_path)
    rid = _submit_tiny(q, "t")
    lease = q.claim(rid, "w1", lease_s=0.2)
    e0 = lease.data["expires_at"]
    lease.renew(30.0)
    assert q.lease_of(rid)["expires_at"] > e0
    assert q.lease_of(rid)["renewals"] == 1


def test_fail_is_terminal(tmp_path):
    q = FleetQueue(tmp_path)
    rid = _submit_tiny(q, "t")
    assert q.fail(rid, "numerics_abort")
    assert q.pending() == []
    assert q.claim(rid, "w", lease_s=5.0) is None
    assert q.status()["counts"]["failed"] == 1


def test_torn_spool_line_skipped(tmp_path):
    q = FleetQueue(tmp_path)
    a = _submit_tiny(q, "a")
    # a submitter SIGKILLed mid-append leaves a torn tail; readers skip it
    with open(q.spool_path, "a") as f:
        f.write('{"request_id": "req-torn", "tenant"')
    b = _submit_tiny(q, "b")
    ids = [r["request_id"] for r in q.pending()]
    assert ids == [a, b]
    st = q.status()
    assert st["torn_spool_lines"] == 1
    assert st["counts"]["submitted"] == 2


# ---------------------------------------------------------------------------
# admission planner units
# ---------------------------------------------------------------------------
def _req(i, shape, n_points, tenant="t", priority=0, deadline_s=None,
         per_lane=None, fixed=0, epochs=10):
    return {
        "request_id": f"req-{i:03d}", "tenant": tenant,
        "submitted_at": float(i), "priority": priority,
        "deadline_s": deadline_s, "shape": shape,
        "points": [{"gen_lr": 1e-3 * (j + 1)} for j in range(n_points)],
        "epochs": epochs, "per_lane_bytes": per_lane, "fixed_bytes": fixed,
        "spec": {"model_config": shape, "epochs": epochs},
    }


SHAPE_A = {"num_chans": 4, "num_factors": 2}
SHAPE_B = {"num_chans": 8, "num_factors": 4}


def test_same_shape_requests_merge_into_one_batch():
    reqs = [_req(0, SHAPE_A, 2), _req(1, SHAPE_A, 3), _req(2, SHAPE_B, 2)]
    pl = planner.plan(reqs, n_devices=1)
    assert len(pl["batches"]) == 2
    merged = next(b for b in pl["batches"] if b["n_points"] == 5)
    assert merged["requests"] == ["req-000", "req-001"]
    assert merged["g_bucket"] == 8  # bucket ladder, not exact width
    assert pl["unschedulable"] == []


def test_spec_mismatch_never_merges():
    # same shape key but different horizons: one merged GridSpec would not
    # mean the same math for both tenants
    a = _req(0, SHAPE_A, 2, epochs=10)
    b = _req(1, SHAPE_A, 2, epochs=50)
    pl = planner.plan([a, b], n_devices=1)
    assert len(pl["batches"]) == 2


def test_headroom_gate_never_admits_over_budget():
    per_lane = 1 << 30  # 1 GiB per lane
    budget = 9 << 30    # fits an 8-bucket, not a 16-bucket
    reqs = [_req(i, SHAPE_A, 3, per_lane=per_lane) for i in range(6)]
    pl = planner.plan(reqs, n_devices=1, budget_bytes=budget)
    assert pl["batches"], "planner dropped everything"
    for b in pl["batches"]:
        assert b["predicted_bytes"] is not None
        assert b["predicted_bytes"] <= budget  # the acceptance contract
    # all 18 points admitted across multiple batches
    assert sum(b["n_points"] for b in pl["batches"]) == 18


def test_oversized_single_request_unschedulable_not_admitted():
    r = _req(0, SHAPE_A, 4, per_lane=4 << 30)  # 16 GiB at its own bucket
    pl = planner.plan([r], n_devices=1, budget_bytes=8 << 30)
    assert pl["batches"] == []
    assert pl["unschedulable"][0]["request_id"] == "req-000"
    assert pl["unschedulable"][0]["reason"] == "exceeds_headroom"


def test_no_memory_hints_degrade_to_ungated():
    pl = planner.plan([_req(0, SHAPE_A, 2)], n_devices=1,
                      budget_bytes=1024)
    assert len(pl["batches"]) == 1
    assert pl["batches"][0]["predicted_bytes"] is None


def test_priority_then_deadline_orders_batches():
    lo = _req(0, SHAPE_A, 2, priority=0)
    hi = _req(1, SHAPE_B, 2, priority=5)
    dl = _req(2, {"num_chans": 16}, 2, priority=0, deadline_s=60.0)
    pl = planner.plan([lo, hi, dl], n_devices=1)
    order = [b["requests"][0] for b in pl["batches"]]
    assert order == ["req-001", "req-002", "req-000"]


def test_plan_deterministic_and_batch_id_stable():
    reqs = [_req(i, SHAPE_A, 2) for i in range(4)]
    p1 = planner.plan(list(reversed(reqs)), n_devices=2)
    p2 = planner.plan(reqs, n_devices=2)
    assert [b["batch_id"] for b in p1["batches"]] \
        == [b["batch_id"] for b in p2["batches"]]
    assert planner.batch_id_for(["a", "b"]) != planner.batch_id_for(["b", "a"])


def test_packed_beats_fifo_mesh_slot_utilization():
    # the bench probe's claim, pinned: heterogeneous small requests on an
    # 8-device mesh — FIFO pads every micro-fit to the mesh, packing fills
    # buckets
    reqs = [_req(i, (SHAPE_A, SHAPE_B)[i % 2], 1 + (i * 3) % 5,
                 per_lane=64 << 20) for i in range(12)]
    packed = planner.plan(reqs, n_devices=8, budget_bytes=8 << 30)
    fifo = planner.fifo_plan(reqs, n_devices=8, budget_bytes=8 << 30)
    pu = packed["utilization"]["utilization_pct"]
    fu = fifo["utilization"]["utilization_pct"]
    assert pu > fu
    assert len(packed["batches"]) < len(fifo["batches"])


def test_fleet_sources_pass_schema_check():
    # fleet control modules are under the no-host-sync discipline (no jax);
    # fleet event/span literals must be registered
    assert obs_schema.check_sources() == []


# ---------------------------------------------------------------------------
# worker end-to-end (supervised jax child; warm-starts from the suite cache)
# ---------------------------------------------------------------------------
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clean_fault_env():
    env = dict(os.environ)
    env.pop("REDCLIFF_FAULT_INJECT", None)
    env.pop("REDCLIFF_FAULT_MARKER", None)
    return env


def _drain(root, **kw):
    from redcliff_tpu.runtime.retry import RetryPolicy
    from redcliff_tpu.runtime.supervisor import SupervisorPolicy

    from redcliff_tpu.fleet.worker import work

    policy = SupervisorPolicy(
        max_restarts=2,
        backoff=RetryPolicy(max_attempts=100, base_delay_s=0.05,
                            multiplier=1.0, max_delay_s=0.05))
    return work(str(root), drain=True, poll_s=0.2, lease_s=20.0,
                supervisor_policy=policy, env=_clean_fault_env(), **kw)


def test_worker_drains_multi_tenant_queue(tmp_path):
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    ra = _submit_tiny(q, "alice")
    rb = _submit_tiny(q, "bob")
    n = _drain(root)
    assert n == 1, "same-spec requests should merge into ONE batch"
    st = q.status()
    assert st["counts"]["done"] == 2 and st["counts"]["failed"] == 0
    for rid in (ra, rb):
        res = q.result(rid)["result"]
        assert res["n_points"] == 2
        assert len(res["best_criteria"]) == 2
        assert all(np.isfinite(v) for v in res["best_criteria"])

    # fleet-root events are schema-valid and carry the lifecycle
    recs = read_jsonl(str(root))
    assert obs_schema.validate_records(recs) == []
    kinds = {r.get("kind") for r in recs if r.get("event") == "fleet"}
    assert {"plan", "claim", "batch_start", "batch_end",
            "complete"} <= kinds

    # watch fleet mode: schema-valid snapshot with queue/tenant state
    from redcliff_tpu.obs.watch import build_snapshot

    snap = build_snapshot(str(root))
    assert obs_schema.validate_record(snap) == []
    assert snap["fleet"]["counts"]["done"] == 2
    assert snap["fleet"]["by_tenant"]["alice"]["done"] == 1
    assert snap["fleet"]["last_plan"]["batches"] == 1

    # per-tenant report section from the batch run dir's tenant manifest
    from redcliff_tpu.obs.report import build_report

    batch_id = next(r["batch_id"] for r in recs
                    if r.get("event") == "fleet"
                    and r.get("kind") == "batch_end")
    report = build_report(q.batch_dir(batch_id))
    assert set(report["tenants"]) == {"alice", "bob"}
    assert report["tenants"]["alice"]["points"] == 2
    assert report["read_audit"]["schema_errors"] == []
    assert report["read_audit"]["ledger_schema_errors"] == []


def test_drain_exits_on_unschedulable_only_queue(tmp_path):
    # a queue holding only requests the planner can never admit must not
    # wedge --drain forever: nothing claimable + nothing in flight = done
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    rid = _submit_tiny(q, "big", per_lane_bytes=1 << 40)
    t0 = time.time()
    n = _drain(root, budget_bytes=1 << 30)
    assert n == 0
    assert time.time() - t0 < 30.0
    assert q.status()["counts"]["queued"] == 1  # still queued, never lost


def test_watch_fleet_root_is_read_only(tmp_path):
    # a watcher must not mkdir under (or crash on) the observed root
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    _submit_tiny(q, "t")
    for d in ("leases", "done", "failed", "work"):
        os.rmdir(root / d)
    from redcliff_tpu.obs.watch import build_snapshot

    snap = build_snapshot(str(root))
    assert snap["fleet"]["counts"]["queued"] == 1
    for d in ("leases", "done", "failed", "work"):
        assert not os.path.exists(root / d), f"watch created {d}/"


def test_worker_fleet_status_cli(tmp_path):
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    _submit_tiny(q, "cli")
    out = subprocess.run(
        [sys.executable, "-m", "redcliff_tpu.fleet", "status", "--root",
         str(root), "--json"], capture_output=True, text=True,
        env=_clean_fault_env(), cwd=REPO_ROOT)
    assert out.returncode == 0, out.stderr
    st = json.loads(out.stdout)
    assert st["counts"]["queued"] == 1


def test_sigkill_worker_lease_reclaim_resume_bit_identical(tmp_path):
    """The crash-safety acceptance (ISSUE 10): SIGKILL the worker (and its
    supervised fit) mid-batch -> the lease expires -> a second worker
    reclaims the RECORDED batch and resumes from the grid checkpoint ->
    final per-request results bit-identical to an uninterrupted run; the
    request is neither lost nor executed twice."""
    root_kill = tmp_path / "fleet_kill"
    root_ref = tmp_path / "fleet_ref"
    qk = FleetQueue(root_kill)
    qr = FleetQueue(root_ref)
    rid_kill = _submit_tiny(qk, "crash", epochs=4)
    rid_ref = _submit_tiny(qr, "crash", epochs=4)

    # worker 1: its own process group (so the supervised run_batch child
    # dies with it), fault-armed to drop a marker at the end of epoch 1 —
    # by then the epoch-1 checkpoint is durable
    marker = str(tmp_path / "epoch1.marker")
    env = _clean_fault_env()
    env["REDCLIFF_FAULT_INJECT"] = "marker_after_epoch:1"
    env["REDCLIFF_FAULT_MARKER"] = marker
    w1 = subprocess.Popen(
        [sys.executable, "-m", "redcliff_tpu.fleet", "work", "--root",
         str(root_kill), "--max-batches", "1", "--lease-s", "2",
         "--poll-s", "0.2"],
        env=env, start_new_session=True, cwd=REPO_ROOT,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 240
        while not os.path.exists(marker):
            assert time.time() < deadline, "fit never reached epoch 1"
            assert w1.poll() is None, "worker 1 exited before the marker"
            time.sleep(0.05)
        os.killpg(w1.pid, signal.SIGKILL)
    finally:
        if w1.poll() is None:
            os.killpg(w1.pid, signal.SIGKILL)
        w1.wait()

    # the claim is still on disk; once its 2 s lease expires the request is
    # reclaimable — never lost
    lease = qk.lease_of(rid_kill)
    assert lease is not None and lease["batch_id"]
    while time.time() < float(lease["expires_at"]):
        time.sleep(0.05)
    assert qk.status()["counts"]["queued"] == 1

    # worker 2 (clean env): reclaims the recorded batch, resumes, completes
    n = _drain(root_kill)
    assert n == 1
    assert qk.status()["counts"]["done"] == 1

    # reference leg: uninterrupted run of the identical spec
    assert _drain(root_ref) == 1
    res_kill = qk.result(rid_kill)["result"]
    res_ref = qr.result(rid_ref)["result"]
    for key in ("best_criteria", "best_epoch", "val_history", "active",
                "failures"):
        assert res_kill[key] == res_ref[key], f"{key} diverged after resume"

    # resumed, not re-run: the killed batch's run dir shows exactly one
    # fresh fit_start and at least one resumed attempt, and only one done
    # record exists (never run twice)
    batch_id = lease["batch_id"]
    recs = read_jsonl(qk.batch_dir(batch_id))
    starts = [r for r in recs if r.get("event") == "fit_start"]
    fresh = [r for r in starts if r.get("resumed_from_epoch") is None]
    resumed = [r for r in starts if r.get("resumed_from_epoch") is not None]
    assert len(fresh) == 1 and len(resumed) >= 1
    done_dir = os.path.join(str(root_kill), "done")
    assert os.listdir(done_dir) == [f"{rid_kill}.json"]
    # the reclaim is audited in the fleet events
    froot = read_jsonl(str(root_kill))
    assert any(r.get("event") == "fleet" and r.get("kind") == "reclaim"
               for r in froot)
