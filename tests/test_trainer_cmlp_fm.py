"""End-to-end slice: generate synthetic sVAR data, train the cMLP_FM baseline with
the generic trainer, and score the learned GC estimate against the oracle graph —
the reference's train/CMLP_* capability (SURVEY.md §7 Phase 1)."""
import os

import jax
import numpy as np
import pytest

from redcliff_tpu.data import synthetic as S
from redcliff_tpu.data.datasets import ArrayDataset, train_val_split
from redcliff_tpu.models.cmlp_fm import CMLPFM, CMLPFMConfig
from redcliff_tpu.train.trainer import TrainConfig, Trainer, load_model
from redcliff_tpu.utils.metrics import compute_optimal_f1, roc_auc


@pytest.fixture(scope="module")
def single_factor_data():
    D = 5
    p = S.reference_curation_params(D)
    graphs, acts, _ = S.generate_lagged_adjacency_graphs_for_factor_model(
        num_nodes=D, num_lags=2, num_factors=1, make_factors_orthogonal=False,
        make_factors_singular_components=False, rand_seed=11,
        off_diag_edge_strengths=p["off_diag_edge_strengths"],
        diag_receiving_node_forgetting_coeffs=p["diag_receiving_node_forgetting_coeffs"],
        diag_sending_node_forgetting_coeffs=p["diag_sending_node_forgetting_coeffs"],
        num_edges_per_graph=6,
    )
    X, Y = S.generate_synthetic_dataset(
        jax.random.PRNGKey(5), graphs, acts, p["base_freqs"], p["noise_mu"],
        p["noise_var"], p["innovation_amp"], num_samples=256,
        recording_length=40, burnin_period=10, num_labeled_sys_states=1,
        noise_type="gaussian", noise_amp=0.0,
    )
    return graphs, X, Y


def test_cmlp_fm_end_to_end_recovers_structure(single_factor_data, tmp_path):
    graphs, X, Y = single_factor_data
    D = X.shape[2]
    train_ds, val_ds = train_val_split(X, Y, val_fraction=0.2,
                                       rng=np.random.default_rng(0))
    cfg = CMLPFMConfig(num_chans=D, gen_lag=2, gen_hidden=(16,), input_length=8,
                       forecast_coeff=1.0, adj_l1_coeff=1e-3)
    model = CMLPFM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    trainer = Trainer(model, TrainConfig(learning_rate=5e-3, max_iter=30,
                                         batch_size=64, check_every=10, lookback=5))
    res = trainer.fit(params, train_ds, val_ds,
                      true_GC=[graphs[0]], save_dir=str(tmp_path / "run"))

    # forecasting loss decreased
    fl = res.histories["avg_forecasting_loss"]
    assert fl[-1] < fl[0]

    # learned GC separates true edges from non-edges clearly better than chance
    est = np.asarray(model.gc(res.params, ignore_lag=True)[0])
    truth = (graphs[0].sum(axis=2) > 0).astype(int)
    auc = roc_auc(truth.ravel(), est.ravel())
    _, f1 = compute_optimal_f1(truth.ravel(), est.ravel())
    assert auc > 0.75, f"ROC-AUC {auc} too close to chance"
    assert f1 > 0.6

    # tracker histories populated per epoch
    assert res.tracker is not None
    assert len(res.tracker.f1score_histories[0.0][0]) == len(fl)

    # artifact layout matches the reference contract
    run_dir = tmp_path / "run"
    assert (run_dir / "final_best_model.bin").exists()
    assert (run_dir / "training_meta_data_and_hyper_parameters.pkl").exists()
    payload = load_model(str(run_dir))
    assert payload["model_class"] == "CMLPFM"
    assert payload["config"].num_chans == D


def test_trainer_resume_roundtrip(single_factor_data, tmp_path):
    graphs, X, Y = single_factor_data
    D = X.shape[2]
    train_ds, val_ds = train_val_split(X, Y, val_fraction=0.2,
                                       rng=np.random.default_rng(1))
    cfg = CMLPFMConfig(num_chans=D, gen_lag=2, gen_hidden=(8,), input_length=8)
    model = CMLPFM(cfg)
    params = model.init(jax.random.PRNGKey(2))
    run = str(tmp_path / "resume_run")

    t1 = Trainer(model, TrainConfig(learning_rate=1e-3, max_iter=4, batch_size=64,
                                    check_every=1))
    r1 = t1.fit(params, train_ds, val_ds, save_dir=run)

    # resume continues from saved epoch with optimizer state intact
    t2 = Trainer(model, TrainConfig(learning_rate=1e-3, max_iter=8, batch_size=64,
                                    check_every=1))
    r2 = t2.fit(params, train_ds, val_ds, save_dir=run, resume=True)
    assert len(r2.histories["avg_combo_loss"]) == 8
    assert r2.histories["avg_combo_loss"][:4] == r1.histories["avg_combo_loss"]


def test_prox_in_training_sparsifies(single_factor_data):
    graphs, X, Y = single_factor_data
    D = X.shape[2]
    train_ds, val_ds = train_val_split(X, Y, val_fraction=0.2,
                                       rng=np.random.default_rng(2))
    cfg = CMLPFMConfig(num_chans=D, gen_lag=2, gen_hidden=(8,), input_length=8)
    model = CMLPFM(cfg)
    params = model.init(jax.random.PRNGKey(3))
    dense = Trainer(model, TrainConfig(learning_rate=2e-3, max_iter=6, batch_size=64,
                                       check_every=100))
    sparse = Trainer(model, TrainConfig(learning_rate=2e-3, max_iter=6, batch_size=64,
                                        check_every=100, prox_penalty="GL",
                                        prox_lam=20.0))
    r_dense = dense.fit(params, train_ds, val_ds)
    r_sparse = sparse.fit(params, train_ds, val_ds)
    gc_dense = np.asarray(model.gc(r_dense.params)[0])
    gc_sparse = np.asarray(model.gc(r_sparse.params)[0])
    # Adam's momentum re-grows groups between prox applications, so assert strong
    # shrinkage rather than exact zeros (exact zeroing of the prox op itself is
    # unit-tested in test_cmlp.py)
    assert gc_sparse.mean() < 0.25 * gc_dense.mean()
