"""Tests for PCMCI (ParCorr) and the TS-transformer."""
import jax
import numpy as np
import pytest

from redcliff_tpu.models.pcmci import (
    parcorr_test,
    pcmci,
    pcmci_val_graph,
    rpcmci_by_regime,
)
from redcliff_tpu.models.ts_transformer import (
    TSTransformerConfig,
    TSTransformerEncoder,
    TSTransformerEncoderClassiregressor,
)


# ------------------------------------------------------------- ParCorr

def test_parcorr_direct_dependence():
    rng = np.random.default_rng(0)
    x = rng.normal(size=400)
    y = 0.8 * x + 0.3 * rng.normal(size=400)
    r, p = parcorr_test(x, y)
    assert r > 0.8 and p < 1e-6


def test_parcorr_conditioning_removes_confounder():
    rng = np.random.default_rng(1)
    z = rng.normal(size=500)
    x = z + 0.3 * rng.normal(size=500)
    y = z + 0.3 * rng.normal(size=500)
    r_raw, p_raw = parcorr_test(x, y)
    assert p_raw < 1e-6  # confounded: strongly correlated
    r_cond, p_cond = parcorr_test(x, y, z)
    assert abs(r_cond) < 0.2 and p_cond > 0.01


def test_parcorr_matches_scipy_pearson_when_unconditioned():
    from scipy.stats import pearsonr

    rng = np.random.default_rng(2)
    x = rng.normal(size=120)
    y = 0.5 * x + rng.normal(size=120)
    r, p = parcorr_test(x, y)
    r_ref, p_ref = pearsonr(x, y)
    assert r == pytest.approx(r_ref, rel=1e-6)
    assert p == pytest.approx(p_ref, rel=1e-3)


# ------------------------------------------------------------- PCMCI

def _var_system(rng, T=800, noise=0.3):
    """3-var linear VAR(1): 0 -> 1, 1 -> 2, plus self-memory."""
    X = np.zeros((T, 3))
    for t in range(1, T):
        X[t, 0] = 0.5 * X[t - 1, 0] + rng.normal(scale=noise)
        X[t, 1] = 0.5 * X[t - 1, 1] + 0.6 * X[t - 1, 0] \
            + rng.normal(scale=noise)
        X[t, 2] = 0.5 * X[t - 1, 2] + 0.6 * X[t - 1, 1] \
            + rng.normal(scale=noise)
    return X


def test_pcmci_recovers_var_structure():
    rng = np.random.default_rng(3)
    X = _var_system(rng)
    res = pcmci(X, tau_max=2, pc_alpha=0.2, alpha_level=0.01)
    g = pcmci_val_graph(res, alpha_level=0.01)
    # true cross links present...
    assert g[0, 1] > 0.3
    assert g[1, 2] > 0.3
    # ...and the spurious two-hop 0 -> 2 link screened off by conditioning
    assert g[0, 2] < g[0, 1] / 2
    # no reverse causation
    assert g[1, 0] < 0.15 and g[2, 1] < 0.15
    # self links dominated by memory
    assert g[0, 0] > 0.3


def test_pcmci_multiple_recordings_no_boundary_leak():
    rng = np.random.default_rng(4)
    recs = [_var_system(rng, T=150) for _ in range(5)]
    res = pcmci(recs, tau_max=1, alpha_level=0.01)
    g = pcmci_val_graph(res, alpha_level=0.01)
    assert g[0, 1] > 0.3 and g[1, 2] > 0.3


def test_pcmci_output_shapes():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(100, 4))
    res = pcmci(X, tau_max=3)
    assert res["val_matrix"].shape == (4, 4, 4)
    assert res["p_matrix"].shape == (4, 4, 4)
    # tau=0 slice kept for tigramite shape parity
    assert np.all(res["p_matrix"][:, :, 0] == 1.0)
    assert set(res["parents"]) == {0, 1, 2, 3}


def test_rpcmci_by_regime_separates_structures():
    rng = np.random.default_rng(6)

    def system(driver):
        X = np.zeros((300, 3))
        for t in range(1, 300):
            for c in range(3):
                X[t, c] = 0.4 * X[t - 1, c] + rng.normal(scale=0.3)
            X[t, (driver + 1) % 3] += 0.7 * X[t - 1, driver]
        return X

    recs = [system(0), system(0), system(1), system(1)]
    out = rpcmci_by_regime(recs, [0, 0, 1, 1], num_regimes=2, tau_max=1,
                           alpha_level=0.01)
    g0 = pcmci_val_graph(out[0], alpha_level=0.01)
    g1 = pcmci_val_graph(out[1], alpha_level=0.01)
    assert g0[0, 1] > 0.3 and g0[1, 2] < 0.2
    assert g1[1, 2] > 0.3 and g1[0, 1] < 0.2


# ------------------------------------------------- TS transformer

def test_ts_transformer_encoder_shapes():
    cfg = TSTransformerConfig(feat_dim=5, max_len=12, d_model=16, n_heads=4,
                              num_layers=2, dim_feedforward=32)
    model = TSTransformerEncoder(cfg)
    params = model.init(jax.random.PRNGKey(0))
    X = jax.random.normal(jax.random.PRNGKey(1), (3, 12, 5))
    out = model.forward(params, X)
    assert out.shape == (3, 12, 5)
    loss, aux = model.loss(params, X)
    assert np.isfinite(float(loss))


def test_ts_transformer_padding_mask():
    cfg = TSTransformerConfig(feat_dim=4, max_len=10, d_model=8, n_heads=2,
                              num_layers=1, dim_feedforward=16,
                              norm="LayerNorm")
    model = TSTransformerEncoder(cfg)
    params = model.init(jax.random.PRNGKey(0))
    X = jax.random.normal(jax.random.PRNGKey(1), (2, 10, 4))
    mask = np.ones((2, 10), dtype=bool)
    mask[1, 6:] = False
    out = model.forward(params, X, jax.numpy.asarray(mask))
    assert np.isfinite(np.asarray(out)).all()
    # padded-position content must not affect kept positions of that sample
    X2 = np.asarray(X).copy()
    X2[1, 6:] = 99.0
    out2 = model.forward(params, jax.numpy.asarray(X2),
                         jax.numpy.asarray(mask))
    np.testing.assert_allclose(np.asarray(out[1, :6]),
                               np.asarray(out2[1, :6]), atol=2e-4)


def test_ts_transformer_classifier_learns():
    cfg = TSTransformerConfig(feat_dim=3, max_len=8, d_model=16, n_heads=4,
                              num_layers=1, dim_feedforward=32,
                              num_classes=2, norm="LayerNorm")
    model = TSTransformerEncoderClassiregressor(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(7)
    # class 0: rising ramp; class 1: falling ramp
    n = 64
    y = rng.integers(0, 2, size=n)
    ramp = np.linspace(-1, 1, 8)
    X = np.stack([np.stack([(1 - 2 * yi) * ramp] * 3, axis=1)
                  for yi in y]) + 0.1 * rng.normal(size=(n, 8, 3))
    X = jax.numpy.asarray(X.astype(np.float32))
    Y = jax.numpy.asarray(y)

    import optax

    tx = optax.adam(1e-2)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, X, Y):
        (loss, _), grads = jax.value_and_grad(model.loss, has_aux=True)(
            params, X, Y)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    for _ in range(60):
        params, opt_state, loss = step(params, opt_state, X, Y)
    preds = np.argmax(np.asarray(model.forward(params, X)), axis=1)
    assert (preds == y).mean() > 0.9
