"""Integration tests: cached-args -> factory -> trainer dispatch (L4)."""
import os

import numpy as np
import pytest

from redcliff_tpu.data.curation import curate_synthetic_fold
from redcliff_tpu.train.orchestration import (
    call_model_fit_method,
    create_model_instance,
    get_data_for_model_training,
)
from redcliff_tpu.utils.config import read_in_data_args, read_in_model_args

REF_TRAIN = "/root/reference/train"


def _parsed_redcliff_args():
    path = os.path.join(REF_TRAIN,
                        "REDCLIFF_S_CMLP_d4IC_BSCgs1_cached_args.txt")
    if not os.path.isfile(path):
        pytest.skip("reference cached-args absent")
    args = {"model_type": "REDCLIFF_S_CMLP", "model_cached_args_file": path}
    return read_in_model_args(args)


def test_factory_builds_redcliff_from_reference_args():
    args = _parsed_redcliff_args()
    args["num_channels"] = 6
    model = create_model_instance(args)
    cfg = model.config
    assert cfg.num_factors == 5
    assert cfg.gen_lag == 4
    assert cfg.factor_score_embedder_type == "DGCNN"
    assert cfg.forecast_coeff == 10.0
    assert cfg.factor_score_coeff == 100.0
    # smoothing disabled unless the Smooth variant is requested
    assert cfg.factor_weight_smoothing_penalty_coeff == 0.0


def test_factory_smoothing_variant():
    path = os.path.join(
        REF_TRAIN,
        "REDCLIFF_S_CMLP_Smooth_d4IC_BSCgs4ParsimSmo0_cached_args.txt")
    if not os.path.isfile(path):
        pytest.skip("reference cached-args absent")
    args = {"model_type": "REDCLIFF_S_CMLP_WithSmoothing",
            "model_cached_args_file": path}
    read_in_model_args(args)
    args["num_channels"] = 6
    model = create_model_instance(args,
                                  employ_version_with_smoothing_loss=True)
    assert model.config.factor_weight_smoothing_penalty_coeff == \
        args["coeff_dict"]["FACTOR_WEIGHT_SMOOTHING_PENALTY_COEFF"]


def test_factory_declared_but_absent_variants():
    # REDCLIFF_S_CLSTM is now implemented (cLSTM factor networks); only the
    # DGCNN-factor variant remains absent, as in the reference
    with pytest.raises(NotImplementedError):
        create_model_instance({"model_type": "REDCLIFF_S_DGCNN"})


def test_factory_unknown_type():
    with pytest.raises(ValueError):
        create_model_instance({"model_type": "MYSTERY"})


def test_end_to_end_cached_args_to_short_fit(tmp_path):
    """Full L5-equivalent wiring: curate a fold, read its cached-args, build
    a cMLP_FM from a synthesized model cached-args file, fit briefly."""
    import json

    fold_dir, graphs = curate_synthetic_fold(
        str(tmp_path), fold_id=0, num_nodes=5, num_factors=2,
        num_samples_in_train_set=8, num_samples_in_val_set=4,
        sample_recording_len=40, burnin_period=5)
    model_args = {
        "num_sims": "1", "embed_hidden_sizes": "[8]", "batch_size": "4",
        "gen_eps": "0.0001", "gen_weight_decay": "0.0", "max_iter": "3",
        "lookback": "2", "check_every": "2", "verbose": "0",
        "output_length": "1", "wavelet_level": "None", "gen_hidden": "[8]",
        "gen_lr": "0.01", "gen_lag_and_input_len": "3",
        "FORECAST_COEFF": "1.0", "ADJ_L1_REG_COEFF": "0.01",
        "DAGNESS_REG_COEFF": "0.0", "DAGNESS_LAG_COEFF": "0.0",
        "DAGNESS_NODE_COEFF": "0.0",
    }
    margs_path = tmp_path / "cmlp_cached_args.txt"
    with open(margs_path, "w") as f:
        json.dump(model_args, f)

    args = {"model_type": "cMLP",
            "model_cached_args_file": str(margs_path)}
    read_in_model_args(args)
    args["data_cached_args_file"] = os.path.join(
        fold_dir, "data_fold0_cached_args.txt")
    read_in_data_args(args)
    # the reference feeds input_length windows; widen to the recording so the
    # generic trainer sees (B, T, C) windows directly
    args["input_length"] = 10

    model = create_model_instance(args)
    train_ds, val_ds = get_data_for_model_training(args, grid_search=False)
    assert train_ds.X.shape == (8, 40, 5)

    save_dir = str(tmp_path / "run")
    params, result = call_model_fit_method(model, args, train_ds, val_ds,
                                           save_dir=save_dir)
    assert os.path.isfile(os.path.join(save_dir, "final_best_model.bin"))
    gc = model.gc(params)
    assert len(gc) == 1 and np.asarray(gc[0]).shape[:2] == (5, 5)


def test_redcliff_short_fit_via_dispatch(tmp_path):
    """REDCLIFF-S end-to-end through the orchestration layer on tiny data."""
    fold_dir, graphs = curate_synthetic_fold(
        str(tmp_path), fold_id=0, num_nodes=4, num_factors=2,
        num_samples_in_train_set=6, num_samples_in_val_set=3,
        sample_recording_len=30, burnin_period=5)
    args = {
        "model_type": "REDCLIFF_S_CMLP",
        "num_channels": 4,
        "gen_lag": 2, "gen_hidden": [6], "embed_lag": 4,
        "embed_hidden_sizes": [6], "input_length": 2, "output_length": 1,
        "num_factors": 2, "num_supervised_factors": 2,
        "coeff_dict": {"FORECAST_COEFF": 1.0, "FACTOR_SCORE_COEFF": 1.0,
                       "FACTOR_COS_SIM_COEFF": 0.1,
                       "FACTOR_WEIGHT_L1_COEFF": 0.01,
                       "ADJ_L1_REG_COEFF": 0.01},
        "use_sigmoid_restriction": True,
        "factor_score_embedder_type": "Vanilla_Embedder",
        "factor_score_embedder_args": [],
        "primary_gc_est_mode": "fixed_factor_exclusive",
        "forward_pass_mode": "apply_factor_weights_at_each_sim_step",
        "num_sims": 1, "wavelet_level": None,
        "training_mode": "combined", "num_pretrain_epochs": 0,
        "num_acclimation_epochs": 0,
        "embed_lr": 1e-3, "embed_eps": 1e-8, "embed_weight_decay": 0.0,
        "gen_lr": 1e-3, "gen_eps": 1e-8, "gen_weight_decay": 0.0,
        "max_iter": 2, "lookback": 2, "check_every": 2, "batch_size": 3,
        "data_cached_args_file": os.path.join(
            fold_dir, "data_fold0_cached_args.txt"),
    }
    read_in_data_args(args)
    model = create_model_instance(args)
    train_ds, val_ds = get_data_for_model_training(args, grid_search=False)
    params, result = call_model_fit_method(
        model, args, train_ds, val_ds, save_dir=str(tmp_path / "run"))
    ests = model.gc_as_lists(params)
    assert len(ests) == 1 and len(ests[0]) == 2
