"""Integration tests: cached-args -> factory -> trainer dispatch (L4)."""
import os

import numpy as np
import pytest

from redcliff_tpu.data.curation import curate_synthetic_fold
from redcliff_tpu.train.orchestration import (
    call_model_eval_method,
    call_model_fit_method,
    create_model_instance,
    get_data_for_model_training,
)
from redcliff_tpu.utils.config import read_in_data_args, read_in_model_args

REF_TRAIN = "/root/reference/train"


def _parsed_redcliff_args():
    path = os.path.join(REF_TRAIN,
                        "REDCLIFF_S_CMLP_d4IC_BSCgs1_cached_args.txt")
    if not os.path.isfile(path):
        pytest.skip("reference cached-args absent")
    args = {"model_type": "REDCLIFF_S_CMLP", "model_cached_args_file": path}
    return read_in_model_args(args)


def test_factory_builds_redcliff_from_reference_args():
    args = _parsed_redcliff_args()
    args["num_channels"] = 6
    model = create_model_instance(args)
    cfg = model.config
    assert cfg.num_factors == 5
    assert cfg.gen_lag == 4
    assert cfg.factor_score_embedder_type == "DGCNN"
    assert cfg.forecast_coeff == 10.0
    assert cfg.factor_score_coeff == 100.0
    # smoothing disabled unless the Smooth variant is requested
    assert cfg.factor_weight_smoothing_penalty_coeff == 0.0


def test_factory_smoothing_variant():
    path = os.path.join(
        REF_TRAIN,
        "REDCLIFF_S_CMLP_Smooth_d4IC_BSCgs4ParsimSmo0_cached_args.txt")
    if not os.path.isfile(path):
        pytest.skip("reference cached-args absent")
    args = {"model_type": "REDCLIFF_S_CMLP_WithSmoothing",
            "model_cached_args_file": path}
    read_in_model_args(args)
    args["num_channels"] = 6
    model = create_model_instance(args,
                                  employ_version_with_smoothing_loss=True)
    assert model.config.factor_weight_smoothing_penalty_coeff == \
        args["coeff_dict"]["FACTOR_WEIGHT_SMOOTHING_PENALTY_COEFF"]


def test_factory_declared_but_absent_variants():
    # REDCLIFF_S_CLSTM is now implemented (cLSTM factor networks); only the
    # DGCNN-factor variant remains absent, as in the reference
    with pytest.raises(NotImplementedError):
        create_model_instance({"model_type": "REDCLIFF_S_DGCNN"})


def test_factory_unknown_type():
    with pytest.raises(ValueError):
        create_model_instance({"model_type": "MYSTERY"})


def test_end_to_end_cached_args_to_short_fit(tmp_path):
    """Full L5-equivalent wiring: curate a fold, read its cached-args, build
    a cMLP_FM from a synthesized model cached-args file, fit briefly."""
    import json

    fold_dir, graphs = curate_synthetic_fold(
        str(tmp_path), fold_id=0, num_nodes=5, num_factors=2,
        num_samples_in_train_set=8, num_samples_in_val_set=4,
        sample_recording_len=40, burnin_period=5)
    model_args = {
        "num_sims": "1", "embed_hidden_sizes": "[8]", "batch_size": "4",
        "gen_eps": "0.0001", "gen_weight_decay": "0.0", "max_iter": "3",
        "lookback": "2", "check_every": "2", "verbose": "0",
        "output_length": "1", "wavelet_level": "None", "gen_hidden": "[8]",
        "gen_lr": "0.01", "gen_lag_and_input_len": "3",
        "FORECAST_COEFF": "1.0", "ADJ_L1_REG_COEFF": "0.01",
        "DAGNESS_REG_COEFF": "0.0", "DAGNESS_LAG_COEFF": "0.0",
        "DAGNESS_NODE_COEFF": "0.0",
    }
    margs_path = tmp_path / "cmlp_cached_args.txt"
    with open(margs_path, "w") as f:
        json.dump(model_args, f)

    args = {"model_type": "cMLP",
            "model_cached_args_file": str(margs_path)}
    read_in_model_args(args)
    args["data_cached_args_file"] = os.path.join(
        fold_dir, "data_fold0_cached_args.txt")
    read_in_data_args(args)
    # the reference feeds input_length windows; widen to the recording so the
    # generic trainer sees (B, T, C) windows directly
    args["input_length"] = 10

    model = create_model_instance(args)
    train_ds, val_ds = get_data_for_model_training(args, grid_search=False)
    assert train_ds.X.shape == (8, 40, 5)

    save_dir = str(tmp_path / "run")
    params, result = call_model_fit_method(model, args, train_ds, val_ds,
                                           save_dir=save_dir)
    assert os.path.isfile(os.path.join(save_dir, "final_best_model.bin"))
    gc = model.gc(params)
    assert len(gc) == 1 and np.asarray(gc[0]).shape[:2] == (5, 5)


def test_redcliff_short_fit_via_dispatch(tmp_path):
    """REDCLIFF-S end-to-end through the orchestration layer on tiny data."""
    fold_dir, graphs = curate_synthetic_fold(
        str(tmp_path), fold_id=0, num_nodes=4, num_factors=2,
        num_samples_in_train_set=6, num_samples_in_val_set=3,
        sample_recording_len=30, burnin_period=5)
    args = {
        "model_type": "REDCLIFF_S_CMLP",
        "num_channels": 4,
        "gen_lag": 2, "gen_hidden": [6], "embed_lag": 4,
        "embed_hidden_sizes": [6], "input_length": 2, "output_length": 1,
        "num_factors": 2, "num_supervised_factors": 2,
        "coeff_dict": {"FORECAST_COEFF": 1.0, "FACTOR_SCORE_COEFF": 1.0,
                       "FACTOR_COS_SIM_COEFF": 0.1,
                       "FACTOR_WEIGHT_L1_COEFF": 0.01,
                       "ADJ_L1_REG_COEFF": 0.01},
        "use_sigmoid_restriction": True,
        "factor_score_embedder_type": "Vanilla_Embedder",
        "factor_score_embedder_args": [],
        "primary_gc_est_mode": "fixed_factor_exclusive",
        "forward_pass_mode": "apply_factor_weights_at_each_sim_step",
        "num_sims": 1, "wavelet_level": None,
        "training_mode": "combined", "num_pretrain_epochs": 0,
        "num_acclimation_epochs": 0,
        "embed_lr": 1e-3, "embed_eps": 1e-8, "embed_weight_decay": 0.0,
        "gen_lr": 1e-3, "gen_eps": 1e-8, "gen_weight_decay": 0.0,
        "max_iter": 2, "lookback": 2, "check_every": 2, "batch_size": 3,
        "data_cached_args_file": os.path.join(
            fold_dir, "data_fold0_cached_args.txt"),
    }
    read_in_data_args(args)
    model = create_model_instance(args)
    train_ds, val_ds = get_data_for_model_training(args, grid_search=False)
    params, result = call_model_fit_method(
        model, args, train_ds, val_ds, save_dir=str(tmp_path / "run"))
    ests = model.gc_as_lists(params)
    assert len(ests) == 1 and len(ests[0]) == 2

    # uniform eval dispatch on the trained model (ref model_utils.py:1100-1156)
    out = call_model_eval_method(model, params, args, val_ds)
    assert len(out["components"]) == 9  # REDCLIFF cMLP-variant order
    assert out["combo_loss"] == out["components"][-1]
    assert np.isfinite(out["combo_loss"])


def test_eval_dispatch_cmlp_duplication_quirk(tmp_path):
    """cMLP family: the reference doubles the component list before appending
    the normalized-GC L1 (ref model_utils.py:1098)."""
    import jax

    from redcliff_tpu.data.datasets import ArrayDataset
    from redcliff_tpu.models.cmlp_fm import CMLPFM, CMLPFMConfig

    model = CMLPFM(CMLPFMConfig(
        num_chans=4, gen_lag=2, gen_hidden=(8,), input_length=6, num_sims=1,
        forecast_coeff=1.0, adj_l1_coeff=0.01))
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    # T >= input_length + total_output_length = 6 + (6 - 2 + 1) = 11
    X = rng.normal(size=(8, 12, 4)).astype(np.float32)
    Y = rng.uniform(size=(8, 2, 1)).astype(np.float32)
    ds = ArrayDataset(X, Y)
    out = call_model_eval_method(model, params, {"batch_size": 4}, ds)
    assert len(out["components"]) == 13  # 6 components doubled + l1
    assert out["components"][:6] == out["components"][6:12]
    assert out["components"][12] == out["normalized_gc_l1"]
    assert np.isfinite(out["normalized_gc_l1"])


def test_eval_dispatch_dgcnn_and_dynotears(tmp_path):
    import jax

    from redcliff_tpu.data.datasets import ArrayDataset
    from redcliff_tpu.models.dgcnn import DGCNNConfig, DGCNNModel
    from redcliff_tpu.models.dynotears import DynotearsConfig, DynotearsModel

    rng = np.random.default_rng(1)
    # DGCNN: classifier loss + rescaled-GC L1 (ref :1310-1330)
    model = DGCNNModel(DGCNNConfig(
        num_channels=4, num_wavelets_per_chan=1, num_features_per_node=3,
        num_graph_conv_layers=2, num_hidden_nodes=8, num_classes=2))
    params = model.init(jax.random.PRNGKey(1))
    # (B, T, C) windows; the loss takes the first F=3 time rows as node features
    X = rng.normal(size=(6, 5, 4)).astype(np.float32)
    Y = rng.uniform(size=(6, 2)).astype(np.float32)
    out = call_model_eval_method(model, params, {"batch_size": 3},
                                 ArrayDataset(X, Y, normalize=False))
    assert len(out["components"]) == 2
    assert out["scaled_gc_l1"] >= 0

    # DYNOTEARS: mean validation objective (ref :1332-1338)
    dyn = DynotearsModel(DynotearsConfig(
        lambda_w=0.05, lambda_a=0.05, max_iter=5, h_tol=1e-6,
        w_threshold=0.0, lag_size=1))
    Xd = rng.normal(size=(4, 12, 3)).astype(np.float64)
    Yd = rng.uniform(size=(4, 2, 1)).astype(np.float32)
    ds = ArrayDataset(Xd, Yd, normalize=False)
    dyn.fit(ds, ds, max_data_iter=1, batch_size=2)
    out = call_model_eval_method(dyn, None, {"batch_size": 2}, ds)
    assert len(out["components"]) == 1
    assert np.isfinite(out["avg_val_loss"])

    # vanilla variant: averaged lagged graph scored in the solver's
    # (plus, minus)-split vector layout
    from redcliff_tpu.models.dynotears import DynotearsVanillaModel
    van = DynotearsVanillaModel(DynotearsConfig(
        lambda_w=0.05, lambda_a=0.05, max_iter=5, h_tol=1e-6,
        w_threshold=0.0, lag_size=1))
    van.fit(Xd, max_samples=2)
    out_v = call_model_eval_method(van, None, {"batch_size": 2}, ds)
    assert np.isfinite(out_v["avg_val_loss"])


def test_generate_signal_from_sequential_factor_model():
    """Rollout helper (ref model_utils.py:316-336): one-step predictions
    chained by window sliding, identical to the explicit Python loop."""
    import jax
    import jax.numpy as jnp

    from redcliff_tpu.models.cmlp_fm import CMLPFM, CMLPFMConfig
    from redcliff_tpu.train.orchestration import (
        generate_signal_from_sequential_factor_model)

    model = CMLPFM(CMLPFMConfig(num_chans=3, gen_lag=2, gen_hidden=(8,),
                                input_length=4, num_sims=1))
    params = model.init(jax.random.PRNGKey(0))
    x0 = jnp.asarray(np.random.default_rng(0).normal(
        size=(2, 4, 3)).astype(np.float32))
    sim = generate_signal_from_sequential_factor_model(model, params, x0, 5)
    assert sim.shape == (2, 5, 3)
    assert np.all(np.isfinite(np.asarray(sim)))

    window = x0
    for t in range(5):
        out = model.forward(params, window)
        sims = out[0] if isinstance(out, tuple) else out
        pred = sims[:, 0, :]
        np.testing.assert_allclose(np.asarray(sim[:, t]), np.asarray(pred),
                                   rtol=1e-5, atol=1e-6)
        window = jnp.concatenate([window[:, 1:], pred[:, None]], axis=1)
