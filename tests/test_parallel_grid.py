"""Grid runner + mesh sharding + pallas prox tests on the virtual 8-device mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from redcliff_tpu.data.datasets import ArrayDataset
from redcliff_tpu.models.redcliff import RedcliffSCMLP, RedcliffSCMLPConfig
from redcliff_tpu.ops.pallas_prox import gl_prox_pallas
from redcliff_tpu.ops.prox import prox_update
from redcliff_tpu.parallel.grid import (GridSpec, RedcliffGridRunner,
                                        group_configs_by_shape)
from redcliff_tpu.parallel.mesh import grid_mesh
from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig


def _model(num_chans=4, num_factors=2):
    return RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=num_chans, gen_lag=2, gen_hidden=(8,), embed_lag=4,
        embed_hidden_sizes=(8,), num_factors=num_factors,
        num_supervised_factors=2, factor_weight_l1_coeff=0.01,
        adj_l1_reg_coeff=0.001, factor_cos_sim_coeff=0.01,
        factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive", num_sims=1,
        training_mode="combined"))


def _data(model, n=64):
    cfg = model.config
    rng = np.random.default_rng(0)
    T = cfg.max_lag + cfg.num_sims
    X = rng.normal(size=(n, T, cfg.num_chans)).astype(np.float32)
    Y = rng.uniform(size=(n, cfg.num_supervised_factors + 1, 1)).astype(np.float32)
    return ArrayDataset(X, Y)


def test_grid_runner_trains_all_points():
    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 5e-3},
                            {"adj_l1_reg_coeff": 0.01}, {"factor_cos_sim_coeff": 0.1}])
    tc = RedcliffTrainConfig(max_iter=3, batch_size=32)
    runner = RedcliffGridRunner(model, tc, spec)
    ds = _data(model)
    res = runner.fit(jax.random.PRNGKey(0), ds, ds)
    assert res.val_history.shape == (3, 4)
    assert np.all(np.isfinite(res.val_history))
    # later-epoch validation improves vs first for at least some points
    assert (res.val_history[-1] < res.val_history[0]).any()
    # per-point best params have a leading G axis
    leaf = jax.tree.leaves(res.best_params)[0]
    assert leaf.shape[0] == 4


def test_grid_points_diverge_with_different_hyperparams():
    # parity is not the point here (just "different lrs -> different
    # weights"), so the fixture matches the smaller batch-16 shape family
    # other tests in this file compile anyway
    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-4}, {"gen_lr": 1e-2}])
    tc = RedcliffTrainConfig(max_iter=2, batch_size=16)
    runner = RedcliffGridRunner(model, tc, spec)
    ds = _data(model, n=32)
    res = runner.fit(jax.random.PRNGKey(1), ds, ds)
    w0 = np.asarray(jax.tree.leaves(res.best_params)[0])
    # different lrs must produce different trained weights
    assert not np.allclose(w0[0], w0[1])


def test_grid_runner_sharded_over_mesh():
    mesh = grid_mesh(8)
    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3 * (i + 1)} for i in range(8)])
    tc = RedcliffTrainConfig(max_iter=2, batch_size=32)
    runner = RedcliffGridRunner(model, tc, spec, mesh=mesh)
    ds = _data(model)
    res = runner.fit(jax.random.PRNGKey(2), ds, ds)
    assert res.val_history.shape == (2, 8)
    assert np.all(np.isfinite(res.val_history))


def test_grid_matches_single_point_training():
    """A 1-point grid must reproduce a plain single-model training trajectory."""
    model = _model()
    spec = GridSpec(points=[{}])
    tc = RedcliffTrainConfig(max_iter=2, batch_size=32, seed=3)
    runner = RedcliffGridRunner(model, tc, spec)
    ds = _data(model)
    res = runner.fit(jax.random.PRNGKey(3), ds, ds)
    assert np.all(np.isfinite(res.val_history))
    assert res.best_criteria.shape == (1,)


def test_group_configs_by_shape():
    cfgs = [{"gen_hidden": (8,), "lr": 1e-3}, {"gen_hidden": (8,), "lr": 1e-2},
            {"gen_hidden": (16,), "lr": 1e-3}]
    groups = group_configs_by_shape(cfgs, ["gen_hidden"])
    assert groups[((8,),)] == [0, 1]
    assert groups[((16,),)] == [2]


def test_group_configs_by_shape_heterogeneous_and_stable():
    """Multi-key heterogeneous partitioning with the documented ordering
    contract: groups in first-appearance order, ascending indices within
    each group, missing keys grouped under None."""
    cfgs = [
        {"gen_hidden": (16,), "embed_lag": 4},
        {"gen_hidden": (8,), "embed_lag": 4},
        {"gen_hidden": (16,), "embed_lag": 8},
        {"gen_hidden": (8,), "embed_lag": 4, "lr": 9.0},  # lr is not a shape
        {"gen_hidden": (16,), "embed_lag": 4},
        {"embed_lag": 4},  # missing shape key -> None slot
    ]
    groups = group_configs_by_shape(cfgs, ["gen_hidden", "embed_lag"])
    assert list(groups) == [((16,), 4), ((8,), 4), ((16,), 8), (None, 4)]
    assert groups[((16,), 4)] == [0, 4]
    assert groups[((8,), 4)] == [1, 3]
    assert groups[((16,), 8)] == [2]
    assert groups[(None, 4)] == [5]
    # identical input -> identical grouping (the resume fingerprint pins
    # the per-group point list)
    assert groups == group_configs_by_shape(cfgs, ["gen_hidden", "embed_lag"])


def test_shape_group_bucket_padding_never_leaks_filler():
    """The heterogeneous-sweep flow end to end: a 3-point shape group runs
    at a width-4 bucket (g_bucket padding, parallel/compaction.py) and its
    GridResult stays 3-wide everywhere — filler lanes never surface."""
    cfgs = [{"gen_lr": 1e-3}, {"gen_lr": 2e-3}, {"gen_lr": 5e-3},
            {"gen_lr": 1e-3}, {"gen_lr": 4e-3}]
    # simulate a heterogeneous sweep: indices partition into shape groups
    groups = group_configs_by_shape(
        [{"h": (8,)}, {"h": (8,)}, {"h": (8,)}, {"h": (16,)}, {"h": (16,)}],
        ["h"])
    idxs = groups[((8,),)]
    assert idxs == [0, 1, 2]
    model = _model()
    spec = GridSpec(points=[cfgs[i] for i in idxs])
    runner = RedcliffGridRunner(model, RedcliffTrainConfig(
        max_iter=2, batch_size=32), spec)
    assert runner._g_exec0 == 4  # padded up the pow2 ladder
    ds = _data(model)
    res = runner.fit(jax.random.PRNGKey(3), ds, ds)
    assert res.val_history.shape == (2, 3)
    assert res.best_criteria.shape == (3,)
    assert res.active.shape == (3,)
    assert all(v.shape == (3,) for v in res.coeffs.values())
    assert jax.tree.leaves(res.best_params)[0].shape[0] == 3
    assert res.failures == []


def test_pallas_gl_prox_matches_jnp():
    rng = np.random.default_rng(0)
    W = jnp.asarray(rng.normal(size=(3, 5, 6, 5, 2)).astype(np.float32))
    lam, lr = 0.8, 0.1
    expected = prox_update(W, lam, lr, penalty="GL")
    got = gl_prox_pallas(W, lam, lr)  # interpret mode on CPU
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-6)


def test_pallas_gl_prox_row_padding():
    # G not divisible by block_rows exercises the padding path
    rng = np.random.default_rng(1)
    W = jnp.asarray(rng.normal(size=(1, 3, 4, 7, 2)).astype(np.float32))
    expected = prox_update(W, 0.5, 0.2, penalty="GL")
    got = gl_prox_pallas(W, 0.5, 0.2, block_rows=4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-6)


def test_grid_alignment_with_pretrain_mode():
    """Grid runner applies per-point Hungarian alignment at the pretrain->train
    transition (parity with RedcliffTrainer.align_factors_with_labels)."""
    model = RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=4, gen_lag=2, gen_hidden=(8,), embed_lag=4,
        embed_hidden_sizes=(8,), num_factors=2, num_supervised_factors=2,
        factor_weight_l1_coeff=0.01, factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive", num_sims=1,
        training_mode="pretrain_embedder_and_pretrain_factor_then_combined",
        num_pretrain_epochs=1))
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 2e-3}])
    tc = RedcliffTrainConfig(max_iter=3, batch_size=32)
    runner = RedcliffGridRunner(model, tc, spec)
    ds = _data(model)
    res = runner.fit(jax.random.PRNGKey(4), ds, ds)
    assert np.all(np.isfinite(res.val_history))


def _freeze_model(mode, **over):
    kw = dict(
        num_chans=4, gen_lag=2, gen_hidden=(8,), embed_lag=4,
        embed_hidden_sizes=(8,), num_factors=2, num_supervised_factors=2,
        factor_weight_l1_coeff=0.01, adj_l1_reg_coeff=0.001,
        factor_cos_sim_coeff=0.01, factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive", num_sims=1,
        training_mode=mode, num_pretrain_epochs=1)
    kw.update(over)
    return RedcliffSCMLP(RedcliffSCMLPConfig(**kw))


@pytest.mark.slow  # dual grid + G independent trainer fits: ~26s of compile
@pytest.mark.parametrize("mode", [
    "pretrain_embedder_then_post_train_factor_withL1FreezeByBatch",
    "pretrain_embedder_then_post_train_factor_withComboCosSimL1FreezeByEpoch",
])
def test_grid_freeze_matches_independent_trainers(mode):
    """A G-point Freeze-mode grid run reproduces G independent RedcliffTrainer
    runs (the accept/revert choreography of ref redcliff_s_cmlp.py:866-885,
    1469-1515 under the grid engine)."""
    import dataclasses

    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainer

    model = _freeze_model(mode)
    points = [{"gen_lr": 1e-3}, {"gen_lr": 5e-3}]
    spec = GridSpec(points=points)
    tc = RedcliffTrainConfig(max_iter=3, batch_size=32, seed=7)
    runner = RedcliffGridRunner(model, tc, spec)
    ds = _data(model)
    key = jax.random.PRNGKey(11)
    res = runner.fit(key, ds, ds)

    init_params, _, _ = runner.init_grid(key)  # same key -> same init as fit
    for g, point in enumerate(points):
        tc_g = dataclasses.replace(tc, **{k: v for k, v in point.items()
                                          if k in ("gen_lr", "embed_lr")})
        trainer = RedcliffTrainer(model, tc_g)
        params_g = jax.tree.map(lambda x: x[g], init_params)
        out = trainer.fit(params_g, ds, ds)
        for got, want in zip(jax.tree.leaves(res.best_params),
                             jax.tree.leaves(out.params)):
            np.testing.assert_allclose(np.asarray(got)[g], np.asarray(want),
                                       rtol=2e-3, atol=2e-5)


@pytest.mark.parametrize("with_truth", [True, False])
def test_grid_selection_criteria_matches_trainer(with_truth):
    """Grid best_epoch/best_criteria equal the per-point trainer's
    best_it/best_loss on the same data — per-point stopping coefficients
    applied to coefficient-normalized val means plus the supervised
    pairwise-cosine term (num_supervised_factors=2), exactly as
    redcliff_trainer.py:336-346 / ref :1466-1538. Parity must hold on BOTH
    the labeled path (true_GC passed, the reference-shaped flow) and the
    unlabeled path (no true_GC): the cosine stopping term compares the
    model's own factor estimates to each other, so the trainer tracks it
    unconditionally, like the reference's fit and the grid."""
    import dataclasses

    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainer

    model = _model()  # S=2 -> cosSim term participates in the criteria
    points = [
        {"gen_lr": 1e-3, "stopping_criteria_cosSim_coeff": 0.5},
        {"gen_lr": 5e-3, "stopping_criteria_forecast_coeff": 2.0,
         "stopping_criteria_factor_coeff": 0.5},
    ]
    spec = GridSpec(points=points)
    tc = RedcliffTrainConfig(max_iter=4, batch_size=32, seed=7)
    runner = RedcliffGridRunner(model, tc, spec)
    ds = _data(model)
    key = jax.random.PRNGKey(21)
    res = runner.fit(key, ds, ds)

    cfg = model.config
    # any truth works on the labeled path: the cosine stopping term compares
    # estimates to each other, not to the truth
    true_GC = ([np.eye(cfg.num_chans)
                for _ in range(cfg.num_supervised_factors)]
               if with_truth else None)
    init_params, _, _ = runner.init_grid(key)  # same key -> same init as fit
    stop_keys = ("gen_lr", "embed_lr", "stopping_criteria_forecast_coeff",
                 "stopping_criteria_factor_coeff",
                 "stopping_criteria_cosSim_coeff")
    for g, point in enumerate(points):
        tc_g = dataclasses.replace(tc, **{k: v for k, v in point.items()
                                          if k in stop_keys})
        trainer = RedcliffTrainer(model, tc_g)
        params_g = jax.tree.map(lambda x: x[g], init_params)
        out = trainer.fit(params_g, ds, ds, true_GC=true_GC)
        assert int(res.best_epoch[g]) == out.best_it, (g, point)
        np.testing.assert_allclose(res.best_criteria[g], out.best_loss,
                                   rtol=2e-3)


def test_init_grid_from_replicates_point_params():
    """init_grid_from stacks ONE parameter set across the grid axis (the
    SLURM-array pattern where every per-point process seeds identically) and
    builds per-point optimizer state over it."""
    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 5e-3},
                            {"gen_lr": 2e-3}])
    runner = RedcliffGridRunner(model, RedcliffTrainConfig(batch_size=16),
                                spec)
    p0 = model.init(jax.random.PRNGKey(3))
    params, optA, optB = runner.init_grid_from(p0)
    for leaf0, stacked in zip(jax.tree.leaves(p0), jax.tree.leaves(params)):
        assert stacked.shape == (3,) + np.shape(leaf0)
        for g in range(3):
            np.testing.assert_array_equal(np.asarray(stacked[g]),
                                          np.asarray(leaf0))
    # optimizer state carries the grid axis too
    assert all(l.shape[:1] == (3,) for l in jax.tree.leaves(optA)
               if hasattr(l, "shape") and l.ndim > 0)
    # and fit accepts the pre-stacked state
    ds = _data(model, n=32)
    res = runner.fit(jax.random.PRNGKey(0), ds, ds, max_iter=1,
                     init_params=(params, optA, optB))
    assert res.best_criteria.shape == (3,)


@pytest.mark.slow  # two full fits (scan + per-batch) just to compare: ~18s
def test_grid_scan_batches_matches_per_batch():
    """The lax.scan k-batch step reproduces the one-dispatch-per-batch path
    bit-for-bit on the same data/seed (dispatch amortization must not change
    training semantics), including a non-divisible epoch remainder."""
    import dataclasses

    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 5e-3}])
    key = jax.random.PRNGKey(9)
    # n=80: 5 full batches -> one scan group of 4 + per-batch remainder of 1;
    # n=56: 3 full + 1 SHORT batch (8 rows) that must flush the group to the
    # per-batch step instead of breaking jnp.stack (regression)
    for n in (80, 56):
        ds = _data(model, n=n)
        tc = RedcliffTrainConfig(max_iter=2, batch_size=16, seed=5)
        res_plain = RedcliffGridRunner(model, tc, spec).fit(key, ds, ds)
        tc_scan = dataclasses.replace(tc, scan_batches=4)
        res_scan = RedcliffGridRunner(model, tc_scan, spec).fit(key, ds, ds)
        np.testing.assert_allclose(res_scan.val_history, res_plain.val_history,
                                   rtol=1e-6, atol=1e-7)
        for a, b in zip(jax.tree.leaves(res_scan.best_params),
                        jax.tree.leaves(res_plain.best_params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)


def test_grid_freeze_points_early_stop():
    """Freeze-mode grid points early-stop too: a zero-lr point's criteria
    never improves, so its lane goes inactive after lookback*check_every
    epochs (regression: the freeze branch never updated the active mask)."""
    model = _freeze_model(
        "pretrain_embedder_then_post_train_factor_withL1FreezeByEpoch")
    spec = GridSpec(points=[{"gen_lr": 1e-3},
                            {"gen_lr": 0.0, "embed_lr": 0.0}])
    tc = RedcliffTrainConfig(max_iter=5, batch_size=32, lookback=1,
                             check_every=1)
    runner = RedcliffGridRunner(model, tc, spec)
    ds = _data(model)
    res = runner.fit(jax.random.PRNGKey(13), ds, ds)
    assert not res.active[1]


def test_grid_early_stop_lane_masking():
    """A point whose criteria stops improving goes inactive and its parameters
    freeze (per-point analog of RedcliffTrainer's early-stop break)."""
    model = _model()
    # point 1 has zero learning rates -> its criteria never improves -> it
    # early-stops after stop_after=lookback*check_every=1 non-improving epoch
    spec = GridSpec(points=[{"gen_lr": 1e-3},
                            {"gen_lr": 0.0, "embed_lr": 0.0}])
    tc = RedcliffTrainConfig(max_iter=4, batch_size=32, lookback=1, check_every=1)
    runner = RedcliffGridRunner(model, tc, spec)
    ds = _data(model)
    res = runner.fit(jax.random.PRNGKey(5), ds, ds)
    assert res.active[0]
    assert not res.active[1]
    # the inactive lane's validation loss is frozen after it stopped
    assert np.allclose(res.val_history[1:, 1], res.val_history[1, 1])


def test_grid_trainer_cosine_parity_nonpositive():
    """The all-non-positive-estimate regime (possible for conditional GC
    modes with sign-free embedder weightings): the grid's in-jit cosine
    stopping term and the trainer tracker's host-side cosine must agree —
    both finite, both unscaled-pass-through — so criteria-based selection
    cannot swap between engines on this regime (VERDICT r4 weak #6)."""
    from redcliff_tpu.train.tracking import GCProgressTracker

    model = _model()
    cfg = model.config
    rng = np.random.default_rng(11)
    # fixed all-NEGATIVE per-factor estimates, identical for every sample
    est = -np.abs(rng.normal(size=(cfg.num_factors, cfg.num_chans,
                                   cfg.num_chans))).astype(np.float32) - 0.1

    def fake_gc(params, mode, X=None, threshold=True, ignore_lag=True,
                **kw):
        # shape contract of RedcliffSCMLP.gc: (B, K, C, C, L) with L=1 when
        # ignore_lag (point_cos slices the lag axis with [..., 0])
        B = X.shape[0]
        return jnp.asarray(est)[None].repeat(B, axis=0)[..., None]

    model.gc = fake_gc
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 2e-3}])
    tc = RedcliffTrainConfig(batch_size=8)
    runner = RedcliffGridRunner(model, tc, spec)
    params, _, _ = runner.init_grid(jax.random.PRNGKey(0))
    X = rng.normal(size=(8, cfg.max_lag, cfg.num_chans)).astype(np.float32)
    grid_cos = np.asarray(runner._cos(params, jnp.asarray(X)))
    assert np.all(np.isfinite(grid_cos))

    tracker = GCProgressTracker(num_supervised_factors=cfg.num_supervised_factors,
                                num_chans=cfg.num_chans,
                                num_factors=cfg.num_factors)
    est_by_sample = [[est[k] for k in range(cfg.num_factors)]
                     for _ in range(X.shape[0])]
    tracker.update(true_GC=None, est_by_sample=est_by_sample,
                   est_by_sample_lagsummed=est_by_sample)
    trainer_cos = tracker.latest_mean_supervised_cosine()
    assert np.isfinite(trainer_cos)
    # same semantics -> same number (both lanes see identical estimates)
    np.testing.assert_allclose(grid_cos, trainer_cos, rtol=1e-5, atol=1e-6)


def test_grid_all_inactive_early_exit():
    """Once EVERY lane has hit its patience the fit loop exits instead of
    burning max_iter epochs of masked compute (the per-point trainer would
    have broken out of each run long before)."""
    model = _model()
    # both points frozen at lr 0 -> criteria never improve -> all lanes
    # inactive after stop_after=1 epoch -> exit at the next check
    spec = GridSpec(points=[{"gen_lr": 0.0, "embed_lr": 0.0},
                            {"gen_lr": 0.0, "embed_lr": 0.0}])
    tc = RedcliffTrainConfig(max_iter=50, batch_size=32, lookback=1,
                             check_every=1)
    runner = RedcliffGridRunner(model, tc, spec)
    ds = _data(model)
    res = runner.fit(jax.random.PRNGKey(5), ds, ds)
    assert not res.active.any()
    assert res.val_history.shape[0] < 50


def test_grid_step_lane_mask_freezes_point():
    """Direct check: active=False lanes keep params and opt state bit-identical."""
    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 1e-3}])
    tc = RedcliffTrainConfig(batch_size=8)
    runner = RedcliffGridRunner(model, tc, spec)
    params, optA, optB = runner.init_grid(jax.random.PRNGKey(6))
    before = jax.tree.map(jnp.copy, params)
    cfg = model.config
    rng = np.random.default_rng(0)
    X = rng.normal(size=(8, cfg.max_lag + cfg.num_sims, cfg.num_chans)).astype(np.float32)
    Y = rng.uniform(size=(8, 3, 1)).astype(np.float32)
    active = jnp.asarray([True, False])
    from redcliff_tpu.runtime.numerics import init_numerics_state
    new, _, _, _, _ = runner._steps["combined"](
        params, optA, optB, init_numerics_state(lanes=2), runner.coeffs,
        active, X, Y)
    for b, n in zip(jax.tree.leaves(before), jax.tree.leaves(new)):
        np.testing.assert_array_equal(np.asarray(b)[1], np.asarray(n)[1])
        assert not np.allclose(np.asarray(b)[0], np.asarray(n)[0])


def test_grid_mesh_divisibility_validated():
    """g_bucket (default) absorbs a non-divisible grid by padding the
    execution width up the power-of-two ladder (masked filler lanes, sub-mesh
    when the bucket is smaller than the device count); with g_bucket=False
    the historical loud rejection is preserved."""
    model = _model()
    spec = GridSpec(points=[{} for _ in range(3)])
    with pytest.raises(ValueError, match="multiple of the mesh"):
        RedcliffGridRunner(model, RedcliffTrainConfig(g_bucket=False), spec,
                           mesh=grid_mesh(8))
    # default: G=3 pads to a width-4 bucket on a 4-device sub-mesh
    runner = RedcliffGridRunner(model, RedcliffTrainConfig(), spec,
                                mesh=grid_mesh(8))
    assert runner._g_exec0 == 4
    assert runner.mesh.devices.size == 4


def test_factor_axis_sharding_matches_unsharded():
    """Expert-style factor parallelism: K factor networks sharded across the
    8-device mesh train to the same result as the unsharded trainer."""
    from redcliff_tpu.parallel.mesh import shard_factor_axis
    from redcliff_tpu.train.redcliff_trainer import (RedcliffTrainConfig,
                                                     RedcliffTrainer)

    model = _model(num_chans=4, num_factors=8)
    tc = RedcliffTrainConfig(max_iter=2, batch_size=16, seed=3)
    ds = _data(model, n=32)
    init = model.init(jax.random.PRNGKey(7))

    plain = RedcliffTrainer(model, tc).fit(init, ds, ds)
    sharded = RedcliffTrainer(model, tc).fit(init, ds, ds,
                                             factor_mesh=grid_mesh(8, "factor"))
    for a, b in zip(jax.tree.leaves(plain.params),
                    jax.tree.leaves(sharded.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)

    # the sharded run's factor leaves actually spanned the mesh
    p = shard_factor_axis(init, grid_mesh(8, "factor"))
    leaf = jax.tree.leaves(p["factors"])[0]
    assert len(leaf.sharding.device_set) == 8

    # divisibility is validated
    bad = _model(num_chans=4, num_factors=3)
    with pytest.raises(AssertionError, match="must divide"):
        RedcliffTrainer(bad, tc).fit(bad.init(jax.random.PRNGKey(0)), ds, ds,
                                     factor_mesh=grid_mesh(8, "factor"))


def test_factor_sharding_survives_resume(tmp_path):
    """Resuming a factor-sharded run re-applies the sharding to the loaded
    params and optimizer state (checkpoints store plain numpy)."""
    from redcliff_tpu.train.redcliff_trainer import (RedcliffTrainConfig,
                                                     RedcliffTrainer)

    model = _model(num_chans=4, num_factors=8)
    ds = _data(model, n=32)
    init = model.init(jax.random.PRNGKey(8))
    run = str(tmp_path / "fac_run")
    mesh = grid_mesh(8)  # default axis name: sharding derives it from mesh

    tc1 = RedcliffTrainConfig(max_iter=2, batch_size=16, check_every=1)
    RedcliffTrainer(model, tc1).fit(init, ds, ds, save_dir=run,
                                    factor_mesh=mesh)
    tc2 = RedcliffTrainConfig(max_iter=4, batch_size=16, check_every=1)
    res = RedcliffTrainer(model, tc2).fit(init, ds, ds, save_dir=run,
                                          resume=True, factor_mesh=mesh)
    assert len(res.histories["avg_combo_loss"]) == 4
    # resumed result leaves actually span the mesh
    leaf = jax.tree.leaves(res.params["factors"])[0]
    assert len(leaf.sharding.device_set) == 8


def test_matmul_precision_option_runs():
    """matmul_precision="bfloat16" (the MXU speed/accuracy trade) traces and
    trains; results stay finite and close to the default-precision run on
    the CPU backend."""
    model = _model()
    ds = _data(model, n=32)
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 2e-3}])
    tc32 = RedcliffTrainConfig(max_iter=2, batch_size=16)
    tcbf = RedcliffTrainConfig(max_iter=2, batch_size=16,
                               matmul_precision="bfloat16")
    r32 = RedcliffGridRunner(model, tc32, spec).fit(jax.random.PRNGKey(0),
                                                    ds, ds)
    rbf = RedcliffGridRunner(model, tcbf, spec).fit(jax.random.PRNGKey(0),
                                                    ds, ds)
    assert np.all(np.isfinite(rbf.val_history))
    np.testing.assert_allclose(rbf.val_history, r32.val_history,
                               rtol=0.05, atol=0.05)

    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainer
    res = RedcliffTrainer(model, tcbf).fit(model.init(jax.random.PRNGKey(1)),
                                           ds, ds)
    assert np.isfinite(res.final_val_loss)


def test_grid_checkpoint_resume_bit_identical(tmp_path):
    """A grid fit interrupted mid-run and resumed from its checkpoint
    produces BIT-IDENTICAL results to an uninterrupted fit: params, best
    criteria/epochs, lane masks, and the batch-shuffle rng state are all
    restored (the grid analog of the per-point trainer's resume)."""
    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 3e-3}])
    tc = RedcliffTrainConfig(max_iter=6, batch_size=32, check_every=1)
    ds = _data(model)

    # uninterrupted reference run
    runner = RedcliffGridRunner(model, tc, spec)
    full = runner.fit(jax.random.PRNGKey(2), ds, ds)

    # interrupted run: 3 epochs with checkpointing, then resume to 6
    ck = str(tmp_path / "ck")
    runner2 = RedcliffGridRunner(model, tc, spec)
    part = runner2.fit(jax.random.PRNGKey(2), ds, ds, max_iter=3,
                       checkpoint_dir=ck, checkpoint_every=1)
    assert part.val_history.shape[0] == 3
    runner3 = RedcliffGridRunner(model, tc, spec)
    resumed = runner3.fit(jax.random.PRNGKey(2), ds, ds, max_iter=6,
                          checkpoint_dir=ck, checkpoint_every=1)

    np.testing.assert_array_equal(resumed.val_history, full.val_history)
    np.testing.assert_array_equal(resumed.best_criteria, full.best_criteria)
    np.testing.assert_array_equal(resumed.best_epoch, full.best_epoch)
    np.testing.assert_array_equal(resumed.active, full.active)
    for a, b in zip(jax.tree.leaves(resumed.best_params),
                    jax.tree.leaves(full.best_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_grid_checkpoint_rejects_mismatched_fit(tmp_path):
    """A checkpoint only resumes the fit that wrote it: a changed grid spec
    fails loudly instead of silently restoring stale state."""
    model = _model()
    ck = str(tmp_path / "ck")
    tc = RedcliffTrainConfig(max_iter=2, batch_size=32, check_every=1)
    ds = _data(model)
    runner = RedcliffGridRunner(
        model, tc, GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 3e-3}]))
    runner.fit(jax.random.PRNGKey(0), ds, ds, checkpoint_dir=ck,
               checkpoint_every=1)
    other = RedcliffGridRunner(
        model, tc, GridSpec(points=[{"gen_lr": 2e-3}, {"gen_lr": 3e-3}]))
    with pytest.raises(ValueError, match="different fit"):
        other.fit(jax.random.PRNGKey(0), ds, ds, checkpoint_dir=ck,
                  checkpoint_every=1)
