"""Epoch-engine pipeline tests: stream plans, prefetch, dispatch budgets,
bit-identity across stream modes, and async checkpointing.

Acceptance battery for the single-dispatch epoch engine (data/pipeline.py +
parallel/grid.py): the epoch-scan path must be BIT-identical to the per-batch
path for the same seed/config; a CPU micro-bench must show >=5x fewer
dispatches per epoch at G=16 with k=4 and throughput no worse than the k-scan
path; checkpoint saves must stop stalling the train loop while producing the
same durable artifact as a synchronous save; and a dispatch/host-sync
tripwire must fail tier-1 if the hot epoch loop regresses.
"""
import dataclasses
import inspect
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from redcliff_tpu.data import pipeline
from redcliff_tpu.data.datasets import ArrayDataset
from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
from redcliff_tpu.runtime import checkpoint as rck
from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig
from test_parallel_grid import _data, _model


# ---------------------------------------------------------------------------
# epoch batch plan: the rng-consumption contract behind cross-mode
# bit-identity
# ---------------------------------------------------------------------------
def test_epoch_batch_plan_matches_batches_order():
    ds = ArrayDataset(np.random.default_rng(0).normal(
        size=(53, 4, 3)).astype(np.float32))
    rng_plan = np.random.default_rng(7)
    rng_loop = np.random.default_rng(7)
    full, rem = pipeline.epoch_batch_plan(len(ds), 16, rng=rng_plan)
    got = [ds.X[sel] for sel in full] + ([ds.X[rem]] if len(rem) else [])
    want = [X for X, _ in ds.batches(16, rng=rng_loop)]
    assert len(got) == len(want)
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)
    # identical rng consumption: the bit-generator states must agree after
    # one epoch regardless of which code drew the shuffle
    assert rng_plan.bit_generator.state == rng_loop.bit_generator.state


def test_choose_stream_mode_eligibility():
    ds = ArrayDataset(np.zeros((64, 4, 3), np.float32),
                      np.zeros((64, 2), np.float32))
    kw = dict(scan_batches=0, batch_size=16)
    assert pipeline.choose_stream_mode("auto", ds, **kw) == "epoch"
    assert pipeline.choose_stream_mode("per_batch", ds, **kw) == "per_batch"
    # freeze-by-batch / multi-phase epochs cannot scan
    assert pipeline.choose_stream_mode("auto", ds, freeze_by_batch=True,
                                       **kw) == "per_batch"
    assert pipeline.choose_stream_mode("auto", ds, single_phase=False,
                                       **kw) == "per_batch"
    # label-less dataset: the grid step signature needs Y
    ds_nolabel = ArrayDataset(np.zeros((64, 4, 3), np.float32))
    assert pipeline.choose_stream_mode("auto", ds_nolabel,
                                       **kw) == "per_batch"
    # dataset over the HBM-residency cap degrades to kscan, then per_batch
    assert pipeline.choose_stream_mode(
        "auto", ds, scan_batches=4, batch_size=16,
        max_device_bytes=10) == "kscan"
    assert pipeline.choose_stream_mode(
        "auto", ds, scan_batches=0, batch_size=16,
        max_device_bytes=10) == "per_batch"
    # fewer samples than one batch: nothing to scan
    assert pipeline.choose_stream_mode("auto", ds, scan_batches=0,
                                       batch_size=100) == "per_batch"
    with pytest.raises(ValueError, match="stream_mode"):
        pipeline.choose_stream_mode("warp", ds, **kw)


def test_dispatch_budget():
    assert pipeline.dispatch_budget(20, mode="per_batch") == 20
    assert pipeline.dispatch_budget(20, scan_batches=4, mode="kscan") == 5
    assert pipeline.dispatch_budget(21, 1, scan_batches=4, mode="kscan") == 7
    assert pipeline.dispatch_budget(20, mode="epoch") == 1
    assert pipeline.dispatch_budget(20, 1, mode="epoch") == 2
    assert pipeline.dispatch_budget(0, 1, mode="epoch") == 1


# ---------------------------------------------------------------------------
# prefetcher: order, device placement, exception transparency, cancellation
# ---------------------------------------------------------------------------
def test_prefetch_preserves_order_and_applies_put():
    items = [(np.full((2,), i, np.float32), None) for i in range(20)]
    got = list(pipeline.prefetch_batches(iter(items), depth=2,
                                         put=jax.device_put))
    assert len(got) == 20
    for i, (X, Y) in enumerate(got):
        assert isinstance(X, jax.Array)
        assert Y is None
        np.testing.assert_array_equal(np.asarray(X), items[i][0])


def test_prefetch_propagates_source_exception():
    def bad_source():
        yield np.zeros(2), None
        raise RuntimeError("shard unreadable")

    it = pipeline.prefetch_batches(bad_source(), depth=2)
    next(it)
    with pytest.raises(RuntimeError, match="shard unreadable"):
        list(it)


def test_prefetch_abandonment_does_not_hang():
    def source():
        for i in range(10_000):
            yield np.zeros(2), None

    it = pipeline.prefetch_batches(source(), depth=2)
    next(it)
    t0 = time.monotonic()
    it.close()  # consumer walks away mid-stream
    assert time.monotonic() - t0 < 5.0


# ---------------------------------------------------------------------------
# THE acceptance property: per-batch / k-scan / epoch-scan are bit-identical
# ---------------------------------------------------------------------------
def test_update_order_bit_identity_across_all_three_paths():
    """Same seed/config -> per-batch, k-scan, and epoch-scan fits produce
    BIT-identical val histories, best params, criteria, and epochs — the
    epoch engine changes the dispatch structure, never the math. n=80
    exercises a clean 5-batch epoch; n=56 a short epoch remainder that must
    flush to the per-batch step in order."""
    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 5e-3}])
    key = jax.random.PRNGKey(9)
    for n in (80, 56):
        ds = _data(model, n=n)
        tc = RedcliffTrainConfig(max_iter=2, batch_size=16, seed=5,
                                 stream_mode="per_batch")
        res_pb = RedcliffGridRunner(model, tc, spec).fit(key, ds, ds)
        res_ks = RedcliffGridRunner(
            model, dataclasses.replace(tc, stream_mode="kscan",
                                       scan_batches=4), spec).fit(key, ds, ds)
        res_ep = RedcliffGridRunner(
            model, dataclasses.replace(tc, stream_mode="epoch"),
            spec).fit(key, ds, ds)
        for res in (res_ks, res_ep):
            np.testing.assert_array_equal(res.val_history,
                                          res_pb.val_history)
            np.testing.assert_array_equal(res.best_criteria,
                                          res_pb.best_criteria)
            np.testing.assert_array_equal(res.best_epoch, res_pb.best_epoch)
            for a, b in zip(jax.tree.leaves(res.best_params),
                            jax.tree.leaves(res_pb.best_params)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_auto_mode_resolves_to_epoch_and_is_default():
    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 5e-3}])
    ds = _data(model, n=64)
    tc = RedcliffTrainConfig(max_iter=1, batch_size=16)
    assert tc.stream_mode == "auto"
    runner = RedcliffGridRunner(model, tc, spec)
    runner.fit(jax.random.PRNGKey(0), ds, ds)
    assert runner.dispatch_stats["mode"] == "epoch"


# ---------------------------------------------------------------------------
# CPU micro-bench: >=5x fewer dispatches at G=16/k=4, throughput no worse
# ---------------------------------------------------------------------------
def test_epoch_engine_dispatch_count_and_throughput_g16():
    """G=16, k=4, 20-batch epochs: the epoch engine must issue >=5x fewer
    dispatches per epoch than the k-scan path (counted, not estimated) with
    windows/s no worse. Timing compares the same compiled update math, so
    the margin only absorbs scheduler noise."""
    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3 * (1 + i % 4)}
                            for i in range(16)])
    ds = _data(model, n=320)  # 20 full batches of 16
    key = jax.random.PRNGKey(3)
    tc_ks = RedcliffTrainConfig(max_iter=2, batch_size=16, seed=1,
                                stream_mode="kscan", scan_batches=4)
    tc_ep = RedcliffTrainConfig(max_iter=2, batch_size=16, seed=1,
                                stream_mode="epoch")
    r_ks = RedcliffGridRunner(model, tc_ks, spec)
    r_ks.fit(key, ds, ds)
    r_ep = RedcliffGridRunner(model, tc_ep, spec)
    r_ep.fit(key, ds, ds)
    ks, ep = r_ks.dispatch_stats, r_ep.dispatch_stats
    assert ks["mode"] == "kscan" and ep["mode"] == "epoch"
    # counted dispatches match the shared budget helper exactly
    assert ep["train_dispatches"] == ep["epochs"] * pipeline.dispatch_budget(
        20, mode="epoch")
    assert ks["train_dispatches"] == ks["epochs"] * pipeline.dispatch_budget(
        20, scan_batches=4, mode="kscan")
    ks_total = ks["train_dispatches"] + ks["val_dispatches"]
    ep_total = ep["train_dispatches"] + ep["val_dispatches"]
    assert ks_total >= 5 * ep_total, (ks_total, ep_total)

    # throughput: drive the already-compiled steps directly (no compile in
    # the timed region); min-of-3 absorbs CI scheduler noise
    from redcliff_tpu.runtime.numerics import init_numerics_state

    Xd, Yd = ds.device_arrays(None)
    idx = np.arange(320, dtype=np.int32).reshape(20, 16)
    params, optA, optB = r_ep.init_grid(key)
    ns = init_numerics_state(lanes=16)
    active = jnp.ones((16,), bool)
    coeffs = r_ep.coeffs
    st = (params, optA, optB, ns)

    def time_epoch_engine(st):
        t0 = time.perf_counter()
        st = r_ep._epoch_steps["combined"](*st, coeffs, active, Xd, Yd,
                                           jnp.asarray(idx))[:4]
        jax.block_until_ready(st[0])
        return time.perf_counter() - t0, st

    def time_kscan(st):
        t0 = time.perf_counter()
        for g in range(5):
            Xs = jnp.stack([Xd[i] for i in idx[g * 4 : (g + 1) * 4]])
            Ys = jnp.stack([Yd[i] for i in idx[g * 4 : (g + 1) * 4]])
            st = r_ks._scan_steps["combined"](*st, coeffs, active, Xs,
                                              Ys)[:4]
        jax.block_until_ready(st[0])
        return time.perf_counter() - t0, st

    _, st = time_epoch_engine(st)  # warm both compiled paths
    _, st = time_kscan(st)
    ep_times, ks_times = [], []
    for _ in range(3):
        dt, st = time_epoch_engine(st)
        ep_times.append(dt)
        dt, st = time_kscan(st)
        ks_times.append(dt)
    # identical math, strictly less dispatch + stack overhead: the epoch
    # engine must not be slower (1.25 tolerates timer noise)
    assert min(ep_times) <= 1.25 * min(ks_times), (ep_times, ks_times)


# ---------------------------------------------------------------------------
# tripwire: dispatch budget + no per-batch host syncs in the hot loop
# ---------------------------------------------------------------------------
def test_dispatch_budget_tripwire_default_config():
    """Default (auto) config on an eligible dataset must stay within the
    epoch budget: 1 train dispatch + 1 val dispatch per epoch (no
    remainder). A regression that silently reintroduces per-batch
    dispatches fails here."""
    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 2e-3}])
    ds = _data(model, n=64)
    runner = RedcliffGridRunner(
        model, RedcliffTrainConfig(max_iter=3, batch_size=16), spec)
    runner.fit(jax.random.PRNGKey(1), ds, ds)
    s = runner.dispatch_stats
    assert s["epochs"] == 3
    assert s["train_dispatches"] <= s["epochs"] * pipeline.dispatch_budget(
        4, mode="epoch")
    assert s["val_dispatches"] <= 2 * s["epochs"]


def test_no_per_batch_host_sync_in_hot_loop_source_scan():
    """The per-batch inner loops of the grid fit (train + val) must contain
    no device->host syncs: np.asarray / .item() / float() / gather_to_host
    on device values would serialize the device stream once per batch. The
    hoisted cos window must also stay hoisted (no first_val_X slicing in
    the epoch loop)."""
    src = inspect.getsource(RedcliffGridRunner._fit)
    # strip comments: the contract is about code, not prose
    lines = [l.split("#", 1)[0].rstrip() for l in src.splitlines()]
    code = "\n".join(lines)
    assert "first_val_X" not in code, \
        "per-epoch cos-window slice crept back into the fit loop"
    # scan the indented bodies of every per-batch loop in the epoch loop
    heads = [i for i, l in enumerate(lines)
             if "for X, Y in train_batch_iter()" in l
             or "for X, Y in val_ds.batches" in l]
    assert heads, "expected per-batch loops in _fit"
    banned = ("np.asarray", ".item()", "float(", "gather_to_host",
              "np.array(")
    for h in heads:
        indent = len(lines[h]) - len(lines[h].lstrip())
        for l in lines[h + 1 :]:
            if l.strip() and (len(l) - len(l.lstrip())) <= indent:
                break
            for pat in banned:
                assert pat not in l, (
                    f"per-batch host sync {pat!r} in the hot loop: {l.strip()}")


# ---------------------------------------------------------------------------
# async checkpointing
# ---------------------------------------------------------------------------
def test_async_writer_submit_returns_before_write_completes(tmp_path):
    done = []

    def slow_write():
        time.sleep(0.4)
        rck.write_checkpoint(str(tmp_path / "ck.pkl"), {"x": 1})
        done.append(True)

    w = rck.AsyncCheckpointWriter()
    t0 = time.monotonic()
    w.submit(slow_write)
    submit_s = time.monotonic() - t0
    assert submit_s < 0.2, "submit must be a hand-off, not the write"
    assert not done
    w.wait()
    assert done and rck.read_checkpoint(str(tmp_path / "ck.pkl")) == {"x": 1}


def test_async_writer_barrier_orders_writes_and_raises_failures(tmp_path):
    order = []
    w = rck.AsyncCheckpointWriter()
    w.submit(lambda: (time.sleep(0.2), order.append(1)))
    w.submit(lambda: order.append(2))  # must wait for the first
    w.wait()
    assert order == [1, 2]

    def boom():
        raise OSError("disk full")

    w.submit(boom)
    with pytest.raises(RuntimeError, match="background checkpoint write"):
        w.wait()


def test_overlapping_async_save_same_artifact_as_sync(tmp_path):
    """A save overlapping the next training epoch (async, the default) must
    produce the same durable artifact as a synchronous save — byte-level
    state equality of the final checkpoint generation."""
    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 3e-3}])
    ds = _data(model)
    key = jax.random.PRNGKey(2)
    cks, payloads = {}, {}
    for label, async_ckpt in (("async", True), ("sync", False)):
        ck = str(tmp_path / label)
        tc = RedcliffTrainConfig(max_iter=3, batch_size=32, check_every=1,
                                 async_checkpointing=async_ckpt)
        RedcliffGridRunner(model, tc, spec).fit(
            key, ds, ds, checkpoint_dir=ck, checkpoint_every=1)
        payloads[label] = rck.read_checkpoint(
            os.path.join(ck, "grid_checkpoint.pkl"))
        cks[label] = ck

    def assert_tree_equal(a, b, path=""):
        assert type(a) is type(b), (path, type(a), type(b))
        if isinstance(a, dict):
            assert set(a) == set(b), path
            for k in a:
                assert_tree_equal(a[k], b[k], f"{path}.{k}")
        elif isinstance(a, (list, tuple)):
            assert len(a) == len(b), path
            for i, (x, y) in enumerate(zip(a, b)):
                assert_tree_equal(x, y, f"{path}[{i}]")
        elif isinstance(a, np.ndarray):
            np.testing.assert_array_equal(a, b, err_msg=path)
        else:
            assert a == b, (path, a, b)

    got_a, got_s = payloads["async"], payloads["sync"]
    # the dispatch_stats telemetry snapshot (redcliff_tpu/obs report input)
    # is wall-clock measurements — ckpt_stall_ms/train_time_ms legitimately
    # differ between async and sync runs. It is audit payload, not fit
    # state: both modes must carry it, and EVERYTHING ELSE must be equal
    ds_a = got_a.pop("dispatch_stats")
    ds_s = got_s.pop("dispatch_stats")
    assert ds_a["train_dispatches"] == ds_s["train_dispatches"]
    assert ds_a["mode"] == ds_s["mode"]
    # the async meta fingerprints async_checkpointing-independent knobs only
    assert_tree_equal(got_a, got_s)


def test_grid_records_ckpt_stall_and_async_does_not_block(tmp_path,
                                                          monkeypatch):
    """With a deliberately slow durable write, the async fit's main-thread
    checkpoint stall stays bounded by the hand-off while the sync fit pays
    the full write in-line — the 'checkpoint save no longer blocks the
    train loop' acceptance, measured."""
    real_write = rck.write_checkpoint
    delay = 0.35

    def slow_write(path, obj):
        time.sleep(delay)
        real_write(path, obj)

    monkeypatch.setattr(rck, "write_checkpoint", slow_write)
    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 3e-3}])
    ds = _data(model)
    key = jax.random.PRNGKey(4)
    stalls = {}
    # exactly ONE mid-fit save (epoch 1 of 2): the async barrier lands at
    # fit end, outside the loop, so the loop-stall metric isolates the
    # hand-off itself. (With saves every epoch and writes slower than an
    # epoch, the next save's completion barrier would — by design — absorb
    # the previous write's tail.)
    for label, async_ckpt in (("async", True), ("sync", False)):
        tc = RedcliffTrainConfig(max_iter=2, batch_size=32, check_every=1,
                                 async_checkpointing=async_ckpt)
        runner = RedcliffGridRunner(model, tc, spec)
        runner.fit(key, ds, ds, checkpoint_dir=str(tmp_path / label),
                   checkpoint_every=2)
        stalls[label] = runner.dispatch_stats["ckpt_stall_ms"]
    # sync pays the (slowed) gather+write in the loop; the async hand-off
    # must be bounded well below the write time
    assert stalls["sync"] >= delay * 1e3 * 0.9, stalls
    assert stalls["async"] < delay * 1e3 * 0.5, stalls
    assert stalls["async"] < stalls["sync"] / 2, stalls


def test_resume_rejects_changed_stream_knobs(tmp_path):
    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 3e-3}])
    ds = _data(model)
    ck = str(tmp_path / "ck")
    tc = RedcliffTrainConfig(max_iter=2, batch_size=32, check_every=1,
                             stream_mode="per_batch")
    RedcliffGridRunner(model, tc, spec).fit(jax.random.PRNGKey(0), ds, ds,
                                            checkpoint_dir=ck,
                                            checkpoint_every=1)
    tc2 = dataclasses.replace(tc, stream_mode="epoch")
    with pytest.raises(ValueError, match="stream_mode"):
        RedcliffGridRunner(model, tc2, spec).fit(
            jax.random.PRNGKey(0), ds, ds, checkpoint_dir=ck,
            checkpoint_every=1)


def test_resume_accepts_pre_pipeline_checkpoint_under_defaults(tmp_path):
    """A checkpoint written before the stream knobs existed resumes under
    the DEFAULT knobs (all modes replay the same batch sequence); the meta
    surgery below reproduces the old on-disk format."""
    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 3e-3}])
    ds = _data(model)
    ck = str(tmp_path / "ck")
    tc = RedcliffTrainConfig(max_iter=4, batch_size=32, check_every=1)
    full = RedcliffGridRunner(model, tc, spec).fit(jax.random.PRNGKey(0),
                                                   ds, ds)
    RedcliffGridRunner(model, tc, spec).fit(
        jax.random.PRNGKey(0), ds, ds, max_iter=2, checkpoint_dir=ck,
        checkpoint_every=1)
    path = os.path.join(ck, "grid_checkpoint.pkl")
    obj = rck.read_checkpoint(path)
    for k in ("stream_mode", "prefetch_batches"):
        obj["meta"].pop(k)
    rck.write_checkpoint(path, obj)
    resumed = RedcliffGridRunner(model, tc, spec).fit(
        jax.random.PRNGKey(0), ds, ds, checkpoint_dir=ck,
        checkpoint_every=1)
    np.testing.assert_array_equal(resumed.val_history, full.val_history)


# ---------------------------------------------------------------------------
# sharded streaming dataset -> prefetched host path
# ---------------------------------------------------------------------------
def _write_shards(tmp_path, n_per_shard=(20, 17), T=4, C=3, seed=0):
    import pickle

    rng = np.random.default_rng(seed)
    split = tmp_path / "train"
    os.makedirs(split)
    all_samples = []
    for i, n in enumerate(n_per_shard):
        samples = [[rng.normal(size=(T, C)).astype(np.float32),
                    rng.uniform(size=(2,)).astype(np.float32)]
                   for _ in range(n)]
        all_samples.extend(samples)
        with open(split / f"subset_{i}.pkl", "wb") as f:
            pickle.dump(samples, f)
    return str(split), all_samples


def test_sharded_batch_dataset_matches_arraydataset(tmp_path):
    from redcliff_tpu.data.shards import ShardedBatchDataset, samples_to_arrays

    split, samples = _write_shards(tmp_path)
    sds = ShardedBatchDataset(split)
    assert len(sds) == 37
    assert not sds.supports_device_batches
    X, Y = samples_to_arrays(samples)
    ref = ArrayDataset(X, Y, normalize=True)
    # streaming f64 stats vs in-memory f32 stats: same numbers to fp noise
    np.testing.assert_allclose(sds.stats[0], ref.stats[0], rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(sds.stats[1], ref.stats[1], rtol=1e-5,
                               atol=1e-6)
    got = list(sds.batches(16))
    want = list(ref.batches(16))
    assert len(got) == len(want) == 3
    for (gX, gY), (wX, wY) in zip(got, want):
        np.testing.assert_allclose(gX, wX, rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(gY, wY)


def test_sharded_batch_dataset_quarantines_nonfinite(tmp_path):
    import pickle

    split, _ = _write_shards(tmp_path, n_per_shard=(8,))
    bad = [[np.full((4, 3), np.nan, np.float32), np.zeros(2, np.float32)]]
    with open(os.path.join(split, "subset_9.pkl"), "wb") as f:
        pickle.dump(bad, f)
    from redcliff_tpu.data.shards import ShardedBatchDataset

    with pytest.warns(RuntimeWarning, match="quarantined"):
        sds = ShardedBatchDataset(split)
    assert sds.quarantined_samples == 1
    assert len(sds) == 8


def test_sharded_dataset_quarantines_torn_file_midstream(tmp_path):
    """A shard file truncated AFTER construction (torn write between the
    stats pass and epoch N) is quarantined per file — report fires, warning
    raised — and the stream continues over the surviving shards, matching
    the PR-2 degrade-don't-crash contract."""
    import warnings

    from redcliff_tpu.data.shards import ShardedBatchDataset

    split, samples = _write_shards(tmp_path, n_per_shard=(16, 16, 16))
    sds = ShardedBatchDataset(split)
    assert len(sds) == 48 and sds.quarantined_files == {}
    torn = os.path.join(split, "subset_1.pkl")
    with open(torn, "r+b") as f:
        f.truncate(os.path.getsize(torn) // 2)
    with pytest.warns(RuntimeWarning, match="torn shard"):
        batches = list(sds.batches(8))
    # the stream continued: both healthy shards' samples arrived, in order
    assert sum(len(b[0]) for b in batches) == 32
    assert "subset_1.pkl" in sds.quarantined_files
    assert "truncated" in sds.quarantined_files["subset_1.pkl"]
    # the warning fires once per file, not once per epoch
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert sum(len(b[0]) for b in sds.batches(8)) == 32


def test_sharded_dataset_quarantines_torn_file_at_construction(tmp_path):
    from redcliff_tpu.data.shards import ShardedBatchDataset

    split, _ = _write_shards(tmp_path, n_per_shard=(16, 16))
    with open(os.path.join(split, "subset_0.pkl"), "wb") as f:
        f.write(b"\x80\x04 not a pickle")
    with pytest.warns(RuntimeWarning, match="torn shard"):
        sds = ShardedBatchDataset(split)
    # stats came from the surviving shard only; the stream works
    assert len(sds) == 16
    assert "subset_0.pkl" in sds.quarantined_files
    assert sum(len(b[0]) for b in sds.batches(8)) == 16
    # every shard torn -> loud failure, not an empty training set
    with open(os.path.join(split, "subset_1.pkl"), "wb") as f:
        f.write(b"junk")
    with pytest.raises(ValueError, match="torn"):
        with pytest.warns(RuntimeWarning):
            ShardedBatchDataset(split)


def test_grid_fit_on_sharded_stream_uses_prefetched_host_path(tmp_path):
    """A dataset without device-batch support routes through per_batch +
    prefetcher and still trains to finite losses (the too-big-for-HBM
    story, end to end)."""
    model = _model(num_chans=3)
    cfg = model.config
    T = cfg.max_lag + cfg.num_sims
    split, _ = _write_shards(tmp_path, n_per_shard=(24, 24), T=T, C=3,
                             seed=3)
    from redcliff_tpu.data.shards import ShardedBatchDataset

    sds = ShardedBatchDataset(split)
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 2e-3}])
    tc = RedcliffTrainConfig(max_iter=2, batch_size=16)
    runner = RedcliffGridRunner(model, tc, spec)
    res = runner.fit(jax.random.PRNGKey(5), sds, sds)
    assert runner.dispatch_stats["mode"] == "per_batch"
    assert np.all(np.isfinite(res.val_history))
