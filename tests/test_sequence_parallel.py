"""Ring-attention sequence parallelism on the virtual 8-device mesh: exact
agreement with dense attention (the sharded path must be a pure execution
strategy, not an approximation)."""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from redcliff_tpu.models.ts_transformer import (
    TSTransformerConfig, TSTransformerEncoder, ts_transformer_encode)
from redcliff_tpu.parallel.sequence import (ring_attention, seq_mesh,
                                            sequence_sharded)


def _dense_attention(q, k, v, causal=False):
    B, T, H, D = q.shape
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(D)
    if causal:
        keep = jnp.tril(jnp.ones((T, T), bool))
        logits = jnp.where(keep[None, None], logits, -1e30)
    attn = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", attn, v)


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    B, T, H, D = 2, 64, 4, 8
    mk = lambda: jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(qkv, causal):
    q, k, v = qkv
    mesh = seq_mesh(8)
    got = ring_attention(q, k, v, mesh, causal=causal)
    want = _dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)
    # output is genuinely sharded along time over all 8 devices
    assert len(got.sharding.device_set) == 8


def test_ring_attention_mesh_subset(qkv):
    """Works on a mesh smaller than all devices (T divisible by mesh size)."""
    q, k, v = qkv
    mesh = seq_mesh(4)
    got = ring_attention(q, k, v, mesh)
    want = _dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_ring_attention_rejects_indivisible_T(qkv):
    q, k, v = qkv
    with pytest.raises(AssertionError, match="not divisible"):
        ring_attention(q[:, :60], k[:, :60], v[:, :60], seq_mesh(8))


@pytest.mark.parametrize("norm", ["LayerNorm", "BatchNorm"])
def test_sequence_parallel_encoder_matches_dense(norm):
    """The full TS-transformer encoder under sequence parallelism (ring
    attention + XLA-partitioned FFN/norms) reproduces the dense encoder,
    including the mvts BatchNorm whose batch-time statistics psum over the
    mesh."""
    cfg = TSTransformerConfig(feat_dim=3, max_len=64, d_model=16, n_heads=4,
                              num_layers=2, dim_feedforward=32, norm=norm)
    model = TSTransformerEncoder(cfg)
    params = model.init(jax.random.PRNGKey(0))
    X = jnp.asarray(np.random.default_rng(1).normal(
        size=(2, 64, 3)).astype(np.float32))

    dense = model.forward(params, X)
    sp = model.forward(params, X, seq_mesh=seq_mesh(8))
    np.testing.assert_allclose(np.asarray(sp), np.asarray(dense),
                               rtol=5e-5, atol=5e-6)


def test_sequence_sharded_constraint():
    mesh = seq_mesh(8)
    x = jnp.ones((2, 32, 5))
    y = jax.jit(lambda a: sequence_sharded(a, mesh) * 2)(x)
    np.testing.assert_array_equal(np.asarray(y), 2 * np.ones((2, 32, 5)))


def test_long_sequence_memory_scaling():
    """The point of ring attention: a sequence long enough that dense
    attention logits would be T^2-sized still encodes with per-device blocks
    of T/8 — exercised by running a length-1024 input through the sharded
    path and spot-checking against dense on a slice-invariant statistic."""
    cfg = TSTransformerConfig(feat_dim=2, max_len=1024, d_model=8, n_heads=2,
                              num_layers=1, dim_feedforward=16,
                              norm="LayerNorm")
    model = TSTransformerEncoder(cfg)
    params = model.init(jax.random.PRNGKey(2))
    X = jnp.asarray(np.random.default_rng(3).normal(
        size=(1, 1024, 2)).astype(np.float32))
    sp = model.forward(params, X, seq_mesh=seq_mesh(8))
    dense = model.forward(params, X)
    np.testing.assert_allclose(np.asarray(sp), np.asarray(dense),
                               rtol=5e-5, atol=5e-6)
