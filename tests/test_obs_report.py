"""Telemetry-spine acceptance suite (redcliff_tpu/obs + ISSUE 7):

* the tier-1 SCHEMA TRIPWIRE — a small supervised grid fit with numerical
  faults injected must emit only registry-valid events (undocumented event/
  field drift fails here, not in a 3am post-mortem);
* the run-analytics report: ``obs report <run_dir>`` joins metrics.jsonl +
  run_ledger.jsonl + the checkpointed dispatch_stats into a time breakdown
  and a non-empty per-(shape, G-bucket) cost table;
* flight recorder on escalation: a watchdog hang incident dumps
  ``flight_record.json`` containing the stalled component's last spans;
* tracing neutrality: spans on vs off is bit-identical (the spine observes,
  never participates).
"""
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from redcliff_tpu import obs
from redcliff_tpu.obs import build_report, flight, read_jsonl, schema
from redcliff_tpu.obs.logging import MetricLogger
from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
from redcliff_tpu.runtime import checkpoint as rck
from redcliff_tpu.runtime.watchdog import (HeartbeatRegistry, Watchdog,
                                           WatchdogPolicy)
from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig
from test_parallel_grid import _data, _model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def faulted_run(tmp_path_factory):
    """One supervised grid fit with nan-batch faults injected from step 2 on:
    every lane quarantines via the in-graph guard (cause nonfinite_grad),
    exercising fit_start/epoch/span/compile/fit_end + failure machinery.
    Ledger lines are appended the way the supervisor writes them, so the
    report join has both spines to read."""
    run = str(tmp_path_factory.mktemp("obs_run"))
    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 5e-3},
                            {"gen_lr": 2e-3}])
    tc = RedcliffTrainConfig(max_iter=4, batch_size=32, check_every=1,
                             stream_mode="per_batch")
    runner = RedcliffGridRunner(model, tc, spec)
    ds = _data(model)
    old = os.environ.get("REDCLIFF_FAULT_INJECT")
    os.environ["REDCLIFF_FAULT_INJECT"] = "nan_batch:2-50"
    try:
        runner.fit(jax.random.PRNGKey(0), ds, ds, log_dir=run,
                   checkpoint_dir=run, checkpoint_every=1)
    finally:
        if old is None:
            os.environ.pop("REDCLIFF_FAULT_INJECT", None)
        else:
            os.environ["REDCLIFF_FAULT_INJECT"] = old
    # the supervisor's ledger schema, verbatim (runtime/supervisor.py)
    with open(os.path.join(run, "run_ledger.jsonl"), "a") as f:
        f.write(json.dumps({
            "event": "attempt", "attempt": 0, "cmd": ["fit"], "rc": 0,
            "classification": "clean", "action": "stop", "backoff_s": 0.0,
            "started_at": 1.0, "duration_s": 2.0}) + "\n")
        f.write(json.dumps({"event": "final", "classification": "clean",
                            "rc": 0, "attempts": 1}) + "\n")
    return run, runner


def test_schema_tripwire_faulted_grid_fit(faulted_run):
    """EVERY event a faulted supervised grid fit emits validates against the
    versioned registry — new fields/events cannot drift undocumented."""
    run, _runner = faulted_run
    stats = {}
    recs = read_jsonl(run, stats=stats)
    assert stats["torn_lines"] == 0
    events = {r["event"] for r in recs}
    # the fit actually exercised the interesting emitters
    assert {"fit_start", "epoch", "span", "fit_end"} <= events
    bad = schema.validate_records(recs)
    assert not bad, f"schema drift: {bad[:5]}"
    ledger = read_jsonl(os.path.join(run, "run_ledger.jsonl"))
    assert not schema.validate_records(ledger, kind="ledger")
    # identity triple on every record; seq strictly increasing in the file
    seqs = [r["seq"] for r in recs]
    assert all(isinstance(s, int) for s in seqs)
    assert seqs == sorted(seqs)


def test_faults_surface_in_telemetry(faulted_run):
    run, runner = faulted_run
    recs = read_jsonl(run)
    end = [r for r in recs if r["event"] == "fit_end"][-1]
    causes = {f["cause"] for f in end["failures"]}
    assert causes == {"nonfinite_grad"} and len(end["failures"]) == 3
    epochs = [r for r in recs if r["event"] == "epoch"]
    assert any(r["guarded_steps_skipped"] > 0 for r in epochs)
    # per-epoch step-cost samples rode along
    assert all(r["epoch_ms"] > 0 for r in epochs)
    ds = end["dispatch_stats"]
    assert ds["train_dispatches"] > 0 and ds["train_time_ms"] > 0
    assert ds["epochs_by_width"]


def test_report_joins_metrics_ledger_and_checkpoint(faulted_run):
    run, _ = faulted_run
    rep = build_report(run)
    json.dumps(rep, allow_nan=False)  # machine-readable, strict
    tb = rep["time_breakdown_ms"]
    assert tb["train_dispatch"] > 0 and tb["val_dispatch"] > 0
    assert rep["dispatches"]["train"] > 0
    # non-empty per-(shape, G-bucket) cost table with real samples
    assert rep["cost_table"], "cost table must not be empty"
    row = rep["cost_table"][0]
    assert row["g_bucket"] == 4 and row["epochs"] > 0
    assert row["mean_epoch_ms"] > 0
    assert "num_chans=4" in row["shape"]
    # joined inputs: ledger attempts + the checkpointed dispatch_stats
    assert rep["attempts"]["n"] == 1
    assert rep["attempts"]["final"] == "clean"
    cds = rep["checkpoint_dispatch_stats"]
    assert cds is not None and cds["train_dispatches"] > 0
    assert rep["numerics"]["quarantined_lanes"] == 3
    assert not rep["read_audit"]["schema_errors"]
    assert not rep["read_audit"]["ledger_schema_errors"]


def test_report_cli_text_and_json(faulted_run, capsys):
    from redcliff_tpu.obs.report import main, render_text

    run, _ = faulted_run
    assert main(["report", run]) == 0
    text = capsys.readouterr().out
    assert "cost table" in text and "time breakdown" in text
    out_json = os.path.join(run, "report.json")
    assert main(["report", run, "--json", "-o", out_json]) == 0
    printed = json.loads(capsys.readouterr().out)
    with open(out_json) as f:
        written = json.load(f)
    assert printed["cost_table"] == written["cost_table"]
    assert render_text(printed)


def test_report_cli_module_entry(faulted_run):
    """``python -m redcliff_tpu.obs report <dir>`` — the documented entry
    point; jax-free (the report reads artifacts, it does not need a
    backend)."""
    run, _ = faulted_run
    r = subprocess.run(
        [sys.executable, "-m", "redcliff_tpu.obs", "report", run, "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=240,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stderr[-800:]
    rep = json.loads(r.stdout)
    assert rep["cost_table"] and rep["attempts"]["n"] == 1


def test_cost_table_prefers_exact_dispatch_stats_over_sampled(tmp_path):
    """A grid with check_every=50 emits ~epochs/50 `epoch` events; the cost
    table must use fit_end's exact per-width accumulators, not the sampled
    event count (which would be ~50x low), and fall back to sampled only
    when the fit died before fit_end."""
    with MetricLogger(str(tmp_path)) as log:
        log.log("fit_start", model="RedcliffGridRunner",
                shape={"num_chans": 4}, grid_width=8)
        # 100 epochs ran; only 2 were check-window-logged
        for e in (49, 99):
            log.log("epoch", epoch=e, grid_width=8, epoch_ms=100.0)
        log.log("fit_end", dispatch_stats={
            "epochs": 100, "train_dispatches": 100, "val_dispatches": 100,
            "epochs_by_width": {"8": 100},
            "epoch_ms_by_width": {"8": 10_000.0}})
    rep = build_report(str(tmp_path))
    [row] = rep["cost_table"]
    assert row["epochs"] == 100 and not row["sampled"]
    assert row["mean_epoch_ms"] == 100.0
    assert rep["lane_epochs"]["by_bucket"] == {"8": 100}

    # crashed-before-fit_end fallback: sampled counts, marked as such
    crashed = tmp_path / "crashed"
    with MetricLogger(str(crashed)) as log:
        log.log("fit_start", model="RedcliffGridRunner",
                shape={"num_chans": 4}, grid_width=8)
        log.log("epoch", epoch=49, grid_width=8, epoch_ms=100.0)
    rep2 = build_report(str(crashed))
    [row2] = rep2["cost_table"]
    assert row2["epochs"] == 1 and row2["sampled"]


def test_report_on_empty_dir(tmp_path):
    rep = build_report(str(tmp_path))
    assert rep["cost_table"] == [] and rep["attempts"]["n"] == 0
    json.dumps(rep, allow_nan=False)


# ---------------------------------------------------------------------------
# flight recorder on watchdog escalation
# ---------------------------------------------------------------------------
def test_hang_incident_dumps_flight_record_with_last_spans(tmp_path):
    """A watchdog hang incident writes flight_record.json next to
    metrics.jsonl containing the stalled component's last spans — the ISSUE 7
    acceptance artifact."""
    flight.clear()
    # the stalled component did some traced work before wedging
    for i in range(3):
        with obs.span("prefetch.fill", component="prefetch", batch=i):
            pass
    reg = HeartbeatRegistry(default_budget_s=0.02)
    reg.stamp("prefetch")
    events = []

    logger = MetricLogger(str(tmp_path))
    wd = Watchdog(policy=WatchdogPolicy(poll_s=0.01, grace_s=60.0,
                                        hard_exit=False,
                                        latch_preempt=False),
                  registry=reg, logger=logger,
                  on_hang=events.append)
    import time as _time

    with wd:
        t0 = _time.monotonic()
        while wd.incidents == 0 and _time.monotonic() - t0 < 10.0:
            _time.sleep(0.01)
    logger.close()
    assert wd.incidents >= 1
    fr_path = tmp_path / "flight_record.json"
    assert fr_path.exists()
    with open(fr_path) as f:
        fr = json.load(f)
    assert fr["reason"] == "hang"
    names = [r["name"] for r in fr["components"]["prefetch"]]
    assert names.count("prefetch.fill") == 3
    assert "prefetch" in fr["extra"]["components"]
    # the hang event itself landed in metrics.jsonl and validates
    hang = read_jsonl(str(tmp_path), event="hang")
    assert hang and not schema.validate_records(hang)
    # the report surfaces the incident + artifact
    rep = build_report(str(tmp_path))
    assert rep["hang_incidents"] and \
        rep["flight_records"] == ["flight_record.json"]


# ---------------------------------------------------------------------------
# tracing neutrality: the spine observes, never participates
# ---------------------------------------------------------------------------
def test_tracing_on_off_bit_identical(tmp_path):
    model = _model()
    ds = _data(model, n=32)
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 5e-3}])
    tc = RedcliffTrainConfig(max_iter=2, batch_size=16)
    was = obs.enabled()
    try:
        obs.set_enabled(True)
        r_on = RedcliffGridRunner(model, tc, spec).fit(
            jax.random.PRNGKey(0), ds, ds)
        obs.set_enabled(False)
        r_off = RedcliffGridRunner(model, tc, spec).fit(
            jax.random.PRNGKey(0), ds, ds)
    finally:
        obs.set_enabled(was)
    np.testing.assert_array_equal(r_on.val_history, r_off.val_history)
    for a, b in zip(jax.tree.leaves(r_on.best_params),
                    jax.tree.leaves(r_off.best_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_payload_carries_dispatch_stats(faulted_run):
    run, _ = faulted_run
    ckpt, _src = rck.load_checkpoint(
        os.path.join(run, "grid_checkpoint.pkl"))
    assert ckpt is not None
    ds = ckpt["dispatch_stats"]
    assert ds["mode"] == "per_batch" and ds["train_dispatches"] > 0
    # audit payload, NOT fingerprint: the meta dict is untouched by it
    assert "dispatch_stats" not in ckpt["meta"]
