"""Telemetry-spine acceptance suite (redcliff_tpu/obs + ISSUE 7):

* the tier-1 SCHEMA TRIPWIRE — a small supervised grid fit with numerical
  faults injected must emit only registry-valid events (undocumented event/
  field drift fails here, not in a 3am post-mortem);
* the run-analytics report: ``obs report <run_dir>`` joins metrics.jsonl +
  run_ledger.jsonl + the checkpointed dispatch_stats into a time breakdown
  and a non-empty per-(shape, G-bucket) cost table;
* flight recorder on escalation: a watchdog hang incident dumps
  ``flight_record.json`` containing the stalled component's last spans;
* tracing neutrality: spans on vs off is bit-identical (the spine observes,
  never participates).
"""
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from redcliff_tpu import obs
from redcliff_tpu.obs import build_report, flight, read_jsonl, schema
from redcliff_tpu.obs.logging import MetricLogger
from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
from redcliff_tpu.runtime import checkpoint as rck
from redcliff_tpu.runtime.watchdog import (HeartbeatRegistry, Watchdog,
                                           WatchdogPolicy)
from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig
from test_parallel_grid import _data, _model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def faulted_run(tmp_path_factory):
    """One supervised grid fit with nan-batch faults injected from step 2 on:
    every lane quarantines via the in-graph guard (cause nonfinite_grad),
    exercising fit_start/epoch/span/compile/fit_end + failure machinery.
    Ledger lines are appended the way the supervisor writes them, so the
    report join has both spines to read."""
    run = str(tmp_path_factory.mktemp("obs_run"))
    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 5e-3},
                            {"gen_lr": 2e-3}])
    tc = RedcliffTrainConfig(max_iter=4, batch_size=32, check_every=1,
                             stream_mode="per_batch")
    runner = RedcliffGridRunner(model, tc, spec)
    ds = _data(model)
    old = os.environ.get("REDCLIFF_FAULT_INJECT")
    os.environ["REDCLIFF_FAULT_INJECT"] = "nan_batch:2-50"
    try:
        runner.fit(jax.random.PRNGKey(0), ds, ds, log_dir=run,
                   checkpoint_dir=run, checkpoint_every=1)
    finally:
        if old is None:
            os.environ.pop("REDCLIFF_FAULT_INJECT", None)
        else:
            os.environ["REDCLIFF_FAULT_INJECT"] = old
    # the supervisor's ledger schema, verbatim (runtime/supervisor.py)
    with open(os.path.join(run, "run_ledger.jsonl"), "a") as f:
        f.write(json.dumps({
            "event": "attempt", "attempt": 0, "cmd": ["fit"], "rc": 0,
            "classification": "clean", "action": "stop", "backoff_s": 0.0,
            "started_at": 1.0, "duration_s": 2.0}) + "\n")
        f.write(json.dumps({"event": "final", "classification": "clean",
                            "rc": 0, "attempts": 1}) + "\n")
    return run, runner


def test_schema_tripwire_faulted_grid_fit(faulted_run):
    """EVERY event a faulted supervised grid fit emits validates against the
    versioned registry — new fields/events cannot drift undocumented."""
    run, _runner = faulted_run
    stats = {}
    recs = read_jsonl(run, stats=stats)
    assert stats["torn_lines"] == 0
    events = {r["event"] for r in recs}
    # the fit actually exercised the interesting emitters (memory: the
    # ISSUE 9 device-memory axis rides every grid fit)
    assert {"fit_start", "epoch", "span", "memory", "fit_end"} <= events
    bad = schema.validate_records(recs)
    assert not bad, f"schema drift: {bad[:5]}"
    ledger = read_jsonl(os.path.join(run, "run_ledger.jsonl"))
    assert not schema.validate_records(ledger, kind="ledger")
    # identity triple on every record; seq strictly increasing in the file
    seqs = [r["seq"] for r in recs]
    assert all(isinstance(s, int) for s in seqs)
    assert seqs == sorted(seqs)


def test_faults_surface_in_telemetry(faulted_run):
    run, runner = faulted_run
    recs = read_jsonl(run)
    end = [r for r in recs if r["event"] == "fit_end"][-1]
    causes = {f["cause"] for f in end["failures"]}
    assert causes == {"nonfinite_grad"} and len(end["failures"]) == 3
    epochs = [r for r in recs if r["event"] == "epoch"]
    assert any(r["guarded_steps_skipped"] > 0 for r in epochs)
    # per-epoch step-cost samples rode along
    assert all(r["epoch_ms"] > 0 for r in epochs)
    ds = end["dispatch_stats"]
    assert ds["train_dispatches"] > 0 and ds["train_time_ms"] > 0
    assert ds["epochs_by_width"]


def test_report_joins_metrics_ledger_and_checkpoint(faulted_run):
    run, _ = faulted_run
    rep = build_report(run)
    json.dumps(rep, allow_nan=False)  # machine-readable, strict
    tb = rep["time_breakdown_ms"]
    assert tb["train_dispatch"] > 0 and tb["val_dispatch"] > 0
    assert rep["dispatches"]["train"] > 0
    # non-empty per-(shape, G-bucket) cost table with real samples
    assert rep["cost_table"], "cost table must not be empty"
    row = rep["cost_table"][0]
    assert row["g_bucket"] == 4 and row["epochs"] > 0
    assert row["mean_epoch_ms"] > 0
    assert "num_chans=4" in row["shape"]
    # joined inputs: ledger attempts + the checkpointed dispatch_stats
    assert rep["attempts"]["n"] == 1
    assert rep["attempts"]["final"] == "clean"
    cds = rep["checkpoint_dispatch_stats"]
    assert cds is not None and cds["train_dispatches"] > 0
    assert rep["numerics"]["quarantined_lanes"] == 3
    assert not rep["read_audit"]["schema_errors"]
    assert not rep["read_audit"]["ledger_schema_errors"]


def test_report_cli_text_and_json(faulted_run, capsys):
    from redcliff_tpu.obs.report import main, render_text

    run, _ = faulted_run
    assert main(["report", run]) == 0
    text = capsys.readouterr().out
    assert "cost table" in text and "time breakdown" in text
    out_json = os.path.join(run, "report.json")
    assert main(["report", run, "--json", "-o", out_json]) == 0
    printed = json.loads(capsys.readouterr().out)
    with open(out_json) as f:
        written = json.load(f)
    assert printed["cost_table"] == written["cost_table"]
    assert render_text(printed)


def test_report_cli_module_entry(faulted_run):
    """``python -m redcliff_tpu.obs report <dir>`` — the documented entry
    point; jax-free (the report reads artifacts, it does not need a
    backend)."""
    run, _ = faulted_run
    r = subprocess.run(
        [sys.executable, "-m", "redcliff_tpu.obs", "report", run, "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=240,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stderr[-800:]
    rep = json.loads(r.stdout)
    assert rep["cost_table"] and rep["attempts"]["n"] == 1


def test_cost_table_prefers_exact_dispatch_stats_over_sampled(tmp_path):
    """A grid with check_every=50 emits ~epochs/50 `epoch` events; the cost
    table must use fit_end's exact per-width accumulators, not the sampled
    event count (which would be ~50x low), and fall back to sampled only
    when the fit died before fit_end."""
    with MetricLogger(str(tmp_path)) as log:
        log.log("fit_start", model="RedcliffGridRunner",
                shape={"num_chans": 4}, grid_width=8)
        # 100 epochs ran; only 2 were check-window-logged
        for e in (49, 99):
            log.log("epoch", epoch=e, grid_width=8, epoch_ms=100.0)
        log.log("fit_end", dispatch_stats={
            "epochs": 100, "train_dispatches": 100, "val_dispatches": 100,
            "epochs_by_width": {"8": 100},
            "epoch_ms_by_width": {"8": 10_000.0}})
    rep = build_report(str(tmp_path))
    [row] = rep["cost_table"]
    assert row["epochs"] == 100 and not row["sampled"]
    assert row["mean_epoch_ms"] == 100.0
    assert rep["lane_epochs"]["by_bucket"] == {"8": 100}

    # crashed-before-fit_end fallback: sampled counts, marked as such
    crashed = tmp_path / "crashed"
    with MetricLogger(str(crashed)) as log:
        log.log("fit_start", model="RedcliffGridRunner",
                shape={"num_chans": 4}, grid_width=8)
        log.log("epoch", epoch=49, grid_width=8, epoch_ms=100.0)
    rep2 = build_report(str(crashed))
    [row2] = rep2["cost_table"]
    assert row2["epochs"] == 1 and row2["sampled"]


def test_report_on_empty_dir(tmp_path):
    rep = build_report(str(tmp_path))
    assert rep["cost_table"] == [] and rep["attempts"]["n"] == 0
    json.dumps(rep, allow_nan=False)


# ---------------------------------------------------------------------------
# flight recorder on watchdog escalation
# ---------------------------------------------------------------------------
def test_hang_incident_dumps_flight_record_with_last_spans(tmp_path):
    """A watchdog hang incident writes flight_record.json next to
    metrics.jsonl containing the stalled component's last spans — the ISSUE 7
    acceptance artifact."""
    flight.clear()
    # the stalled component did some traced work before wedging
    for i in range(3):
        with obs.span("prefetch.fill", component="prefetch", batch=i):
            pass
    reg = HeartbeatRegistry(default_budget_s=0.02)
    reg.stamp("prefetch")
    events = []

    logger = MetricLogger(str(tmp_path))
    wd = Watchdog(policy=WatchdogPolicy(poll_s=0.01, grace_s=60.0,
                                        hard_exit=False,
                                        latch_preempt=False),
                  registry=reg, logger=logger,
                  on_hang=events.append)
    import time as _time

    with wd:
        t0 = _time.monotonic()
        while wd.incidents == 0 and _time.monotonic() - t0 < 10.0:
            _time.sleep(0.01)
    logger.close()
    assert wd.incidents >= 1
    fr_path = tmp_path / "flight_record.json"
    assert fr_path.exists()
    with open(fr_path) as f:
        fr = json.load(f)
    assert fr["reason"] == "hang"
    names = [r["name"] for r in fr["components"]["prefetch"]]
    assert names.count("prefetch.fill") == 3
    assert "prefetch" in fr["extra"]["components"]
    # the hang event itself landed in metrics.jsonl and validates
    hang = read_jsonl(str(tmp_path), event="hang")
    assert hang and not schema.validate_records(hang)
    # the report surfaces the incident + artifact
    rep = build_report(str(tmp_path))
    assert rep["hang_incidents"] and \
        rep["flight_records"] == ["flight_record.json"]


# ---------------------------------------------------------------------------
# tracing neutrality: the spine observes, never participates
# ---------------------------------------------------------------------------
def test_tracing_on_off_bit_identical(tmp_path):
    model = _model()
    ds = _data(model, n=32)
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 5e-3}])
    tc = RedcliffTrainConfig(max_iter=2, batch_size=16)
    was = obs.enabled()
    try:
        obs.set_enabled(True)
        r_on = RedcliffGridRunner(model, tc, spec).fit(
            jax.random.PRNGKey(0), ds, ds)
        obs.set_enabled(False)
        r_off = RedcliffGridRunner(model, tc, spec).fit(
            jax.random.PRNGKey(0), ds, ds)
    finally:
        obs.set_enabled(was)
    np.testing.assert_array_equal(r_on.val_history, r_off.val_history)
    for a, b in zip(jax.tree.leaves(r_on.best_params),
                    jax.tree.leaves(r_off.best_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_payload_carries_dispatch_stats(faulted_run):
    run, _ = faulted_run
    ckpt, _src = rck.load_checkpoint(
        os.path.join(run, "grid_checkpoint.pkl"))
    assert ckpt is not None
    ds = ckpt["dispatch_stats"]
    assert ds["mode"] == "per_batch" and ds["train_dispatches"] > 0
    # audit payload, NOT fingerprint: the meta dict is untouched by it
    assert "dispatch_stats" not in ckpt["meta"]


# ---------------------------------------------------------------------------
# learned cost model: residual events, report accuracy table, fit ETA
# (obs/costmodel.py, ISSUE 8)
# ---------------------------------------------------------------------------
def test_grid_emits_cost_model_residual_events(faulted_run):
    """Every check window past the first scores prediction-vs-actual as a
    schema-registered cost_model event, and dispatch_stats carries the
    remaining-fit ETA."""
    run, runner = faulted_run
    recs = read_jsonl(run)
    cms = [r for r in recs if r["event"] == "cost_model"]
    assert len(cms) >= 2  # check_every=1, max_iter=4: epochs 1..3
    for r in cms:
        assert r["predicted_epoch_ms"] > 0 and r["actual_epoch_ms"] > 0
        assert r["source"] in ("store", "observed")
        assert r["eta_s"] >= 0 and r["epochs_remaining"] >= 0
    # the all-lanes-quarantined fit exits early, so the last scored window
    # may still predict remaining work — but never more than the horizon
    assert cms[-1]["epochs_remaining"] <= 3
    start = [r for r in recs if r["event"] == "fit_start"][-1]
    assert start["max_iter"] == 4
    ds = [r for r in recs if r["event"] == "fit_end"][-1]["dispatch_stats"]
    assert ds["eta"]["epochs_remaining"] == cms[-1]["epochs_remaining"]
    assert ds["cost_model"]["samples"] == len(cms)
    assert ds["cost_model"]["mape_pct"] >= 0


def test_report_shows_cost_model_accuracy_table(faulted_run):
    run, _ = faulted_run
    rep = build_report(run)
    acc = rep["cost_model"]["accuracy"]
    assert acc, "cost-model accuracy table must be populated"
    [row] = acc
    assert row["g_bucket"] == 4 and row["samples"] >= 2
    assert row["mape_pct"] is not None and row["mape_pct"] >= 0
    assert "num_chans=4" in row["shape"]
    assert row["last_eta_s"] is not None
    # store state rides along (the suite-wide compile cache configures one)
    assert rep["cost_model"]["store"]["configured"]
    # cached real-TPU provenance is surfaced, not invisible
    tc = rep["tpu_bench_cache"]
    assert tc and tc["platform"] == "tpu" and tc["measured_at"]
    assert tc["pallas_prox_max_abs_err"] == 5e-07
    text = render_text_of(rep)
    assert "cost model accuracy" in text
    assert "cached real-TPU evidence" in text


def render_text_of(rep):
    from redcliff_tpu.obs.report import render_text

    return render_text(rep)


def test_predict_fit_eta_within_2x_of_measured_wall(faulted_run):
    """ISSUE 8 acceptance: a model fit from this run's cost table predicts
    the fit's own epoch wall time within a generous-but-asserted 2x."""
    from redcliff_tpu.obs import costmodel

    run, _ = faulted_run
    rep = build_report(run)
    [row] = rep["cost_table"]
    model = costmodel.fit_from_report(rep, platform="cpu")
    eta_s = model.predict_fit_eta(row["shape"], row["g_bucket"],
                                  epochs=row["epochs"], platform="cpu")
    measured_s = row["total_epoch_ms"] / 1e3
    assert eta_s is not None and measured_s > 0
    assert 0.5 <= eta_s / measured_s <= 2.0
    # also within 2x of the engine's own dispatch wall accounting
    ds = rep["checkpoint_dispatch_stats"]
    engine_s = (ds["train_time_ms"] + ds["val_time_ms"]) / 1e3
    assert 0.5 <= eta_s / engine_s <= 2.0


# ---------------------------------------------------------------------------
# obs watch (obs/watch.py, ISSUE 8)
# ---------------------------------------------------------------------------
def _strip_fit_end(src_run, dst):
    """Copy a finished run dir into the shape of a LIVE one: fit_end
    dropped (the fit is still running as far as readers can tell),
    checkpoint kept (the mid-run stall source)."""
    import shutil

    os.makedirs(dst, exist_ok=True)
    with open(os.path.join(src_run, "metrics.jsonl")) as f, \
            open(os.path.join(dst, "metrics.jsonl"), "w") as out:
        for line in f:
            if '"fit_end"' not in line:
                out.write(line)
    for name in ("grid_checkpoint.pkl", "run_ledger.jsonl"):
        p = os.path.join(src_run, name)
        if os.path.exists(p):
            shutil.copy(p, os.path.join(dst, name))
    return dst


def test_watch_snapshot_live_mid_write_run(faulted_run, tmp_path):
    """ISSUE 8 acceptance: `obs watch --once --json` on a live (mid-write)
    run dir returns schema-valid output including per-fit ETA."""
    import io

    from redcliff_tpu.obs.watch import build_snapshot, render_text, run_watch

    run, _ = faulted_run
    live = _strip_fit_end(run, str(tmp_path / "live"))
    # a writer is mid-append: unterminated torn tail on disk RIGHT NOW
    with open(os.path.join(live, "metrics.jsonl"), "a") as f:
        f.write('{"event": "epoch", "epoch": 99, "wall_ti')
        f.flush()
        snap = build_snapshot(live)
    assert not schema.validate_record(snap), \
        schema.validate_record(snap)
    json.dumps(snap, allow_nan=False)
    [fit] = snap["fits"]
    assert not fit["done"]
    assert fit["grid_width"] == 4 and fit["lanes_live"] is not None
    assert fit["epoch_rate_per_min"] > 0
    # per-fit ETA from the newest cost_model event
    assert fit["eta"] is not None
    assert fit["eta"]["source"].startswith("cost_model:")
    assert fit["eta"]["eta_s"] >= 0
    assert snap["grid_eta_s"] is not None
    assert snap["read_audit"]["torn_lines"] == 1
    # stall breakdown from the checkpointed dispatch_stats
    assert snap["stalls"]["source"] == "grid_checkpoint.pkl"
    assert snap["stalls"]["ckpt_stall_ms"] >= 0
    # numerics skip counters surfaced
    assert snap["numerics"]["guarded_steps_skipped"] > 0
    # heartbeat ages present and sane
    assert snap["heartbeats"]["metrics_file_age_s"] >= 0
    assert "grid" in snap["heartbeats"]["span_age_s"]
    # the CLI body agrees with the builder and renders
    out = io.StringIO()
    assert run_watch(live, once=True, as_json=True, out=out) == 0
    cli_snap = json.loads(out.getvalue())
    assert cli_snap["fits"][0]["eta"] is not None
    assert render_text(snap)


def test_watch_cli_subcommand_json(faulted_run, capsys):
    from redcliff_tpu.obs.report import main

    run, _ = faulted_run
    assert main(["watch", run, "--once", "--json"]) == 0
    snap = json.loads(capsys.readouterr().out)
    assert snap["event"] == "watch" and snap["fits"]
    assert not schema.validate_record(snap)
    # finished fits report no ETA (nothing left to predict)
    assert snap["fits"][0]["done"] and snap["fits"][0]["eta"] is None


def test_watch_follows_rotation_boundary_while_writer_appends(tmp_path):
    """Satellite: tail-follow across a metrics.jsonl rotation boundary with
    a writer appending — the SIGKILL-mid-append harness, plus a byte cap
    small enough that the chain rotates mid-run. The snapshot must see
    every whole record across the chain and count the torn tail."""
    from redcliff_tpu.obs.watch import build_snapshot

    child = (
        "import os, signal, json\n"
        "from redcliff_tpu.obs import MetricLogger\n"
        f"log = MetricLogger({str(tmp_path)!r}, max_bytes=400,\n"
        "                   max_backups=20)\n"
        "log.log('fit_start', model='RedcliffGridRunner',\n"
        "        shape={'num_chans': 4}, grid_size=8, grid_width=8,\n"
        "        max_iter=50)\n"
        "for e in range(12):\n"
        "    log.log('epoch', epoch=e, grid_width=8, epoch_ms=100.0,\n"
        "            lanes_live=8)\n"
        "log.log('cost_model', epoch=11, predicted_epoch_ms=100.0,\n"
        "        actual_epoch_ms=101.0, residual_pct=1.0, source='store',\n"
        "        eta_s=3.8, epochs_remaining=38)\n"
        "log._fh.write('{\"event\": \"epoch\", \"epoch\": 12, \"wall')\n"
        "log._fh.flush()\n"
        "os.kill(os.getpid(), signal.SIGKILL)\n")
    r = subprocess.run([sys.executable, "-c", child], cwd=REPO, timeout=120)
    assert r.returncode == -9
    names = os.listdir(tmp_path)
    assert "metrics.jsonl.1" in names, "no rotation happened: cap too big"
    snap = build_snapshot(str(tmp_path))
    assert not schema.validate_record(snap)
    [fit] = snap["fits"]
    # every whole record across the rotation chain was followed
    assert fit["last_epoch"] == 11 and fit["epochs_seen"] == 12
    assert fit["eta"]["eta_s"] <= 3.8  # discounted by event age
    assert fit["eta"]["epochs_remaining"] == 38
    assert snap["read_audit"]["torn_lines"] == 1
    assert len(snap["read_audit"]["files"]) > 1


def test_watch_supersedes_dead_attempts(tmp_path):
    """A fit_start with no fit_end followed by another fit_start (a
    supervisor re-attempt) is a DEAD attempt, not a live fit: it must not
    contribute a phantom ETA to grid_eta_s forever."""
    from redcliff_tpu.obs.watch import build_snapshot, render_text

    with MetricLogger(str(tmp_path)) as log:
        log.log("fit_start", model="RedcliffGridRunner",
                shape={"num_chans": 4}, grid_size=8, grid_width=8,
                max_iter=50)
        for e in (0, 2):
            log.log("epoch", epoch=e, grid_width=8, epoch_ms=100.0,
                    lanes_live=8, guarded_steps_skipped=50)
        # crash: no fit_end. The supervisor restarts -> second fit_start
        log.log("fit_start", model="RedcliffGridRunner",
                shape={"num_chans": 4}, grid_size=8, grid_width=8,
                max_iter=50, resumed_from_epoch=2)
        log.log("epoch", epoch=3, grid_width=8, epoch_ms=100.0,
                lanes_live=8)
        log.log("cost_model", epoch=3, grid_width=8,
                predicted_epoch_ms=100.0, actual_epoch_ms=100.0,
                residual_pct=0.0, source="store", eta_s=4.6,
                epochs_remaining=46)
    snap = build_snapshot(str(tmp_path))
    assert not schema.validate_record(snap)
    dead, live = snap["fits"]
    assert dead["superseded"] and not dead["done"] and dead["eta"] is None
    assert not live["superseded"] and live["eta"]["eta_s"] <= 4.6
    # only the live attempt's eta counts toward the whole-run number
    assert snap["grid_eta_s"] == live["eta"]["eta_s"]
    assert "[dead]" in render_text(snap) and "[LIVE]" in render_text(snap)
    # the dead attempt's stale skip counter (50) must not shadow the live
    # attempt's state (0 skipped so far)
    assert snap["numerics"]["guarded_steps_skipped"] == 0


def test_watch_checkpoint_stalls_cached_by_file_signature(
        faulted_run, monkeypatch):
    """Follow mode must not unpickle the (params-heavy) grid checkpoint
    every tick: the stall extract is cached on (mtime, size)."""
    from redcliff_tpu.obs import report as report_mod
    from redcliff_tpu.obs import watch as watch_mod

    run, _ = faulted_run
    calls = {"n": 0}
    real = report_mod._checkpoint_stats

    def counting(run_dir):
        calls["n"] += 1
        return real(run_dir)

    monkeypatch.setattr(report_mod, "_checkpoint_stats", counting)
    watch_mod._ckpt_stall_cache.clear()
    first = watch_mod._checkpoint_stalls(run)
    second = watch_mod._checkpoint_stalls(run)
    assert first == second and first["ckpt_stall_ms"] is not None
    assert calls["n"] == 1
    # touching the file invalidates the cache
    os.utime(os.path.join(run, "grid_checkpoint.pkl"))
    watch_mod._checkpoint_stalls(run)
    assert calls["n"] == 2


def test_watch_follow_mode_reticks(faulted_run):
    import io

    from redcliff_tpu.obs.watch import run_watch

    run, _ = faulted_run
    out = io.StringIO()
    assert run_watch(run, once=False, interval=0.01, max_ticks=2,
                     out=out) == 0
    assert out.getvalue().count("watch: ") == 2


# ---------------------------------------------------------------------------
# satellite: missing/empty run dirs exit 2 with a one-line diagnosis
# ---------------------------------------------------------------------------
def test_report_and_watch_exit_2_on_missing_or_empty_dir(tmp_path, capsys):
    from redcliff_tpu.obs.report import main

    missing = str(tmp_path / "nope")
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    for args in (["report", missing], ["watch", missing, "--once"],
                 ["report", empty], ["watch", empty, "--once", "--json"]):
        assert main(args) == 2, args
        err = capsys.readouterr().err
        assert err.count("\n") == 1, err  # one-line diagnosis
        assert "obs " in err and "traceback" not in err.lower()


def test_report_and_watch_exit_2_module_entry(tmp_path):
    """The documented CLI shape: `python -m redcliff_tpu.obs {report,watch}`
    on a missing dir exits 2 without a traceback."""
    missing = str(tmp_path / "gone")
    for args in (["report", missing], ["watch", missing, "--once"]):
        r = subprocess.run(
            [sys.executable, "-m", "redcliff_tpu.obs"] + args,
            cwd=REPO, capture_output=True, text=True, timeout=240,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert r.returncode == 2, (args, r.stderr[-500:])
        assert "Traceback" not in r.stderr
        assert "does not exist" in r.stderr
