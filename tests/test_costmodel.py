"""Learned cost model (redcliff_tpu/obs/costmodel.py, ISSUE 8):

* golden fit on a synthetic cost table: exact-bucket means, nearest-width
  scaling fallback, fit-ETA arithmetic;
* the persistent store: versioned file name/format, cross-update
  accumulation, platform separation, corrupt-store tolerance, bucket cap;
* the supervisor's per-attempt ETA tail-read of ``cost_model`` events.

Pure host-side (no jax backend work) — runs in milliseconds.
"""
import json
import os

import pytest

from redcliff_tpu.obs import costmodel
from redcliff_tpu.runtime.supervisor import latest_cost_model_eta

SHAPE = "gen_lag=2,num_chans=4"


def _rows(epoch_ms_mean=100.0, epochs=10, width=8, compile_ms=500.0,
          compiles=2, shape=SHAPE):
    return [{"shape": shape, "g_bucket": width, "epochs": epochs,
             "epoch_ms": epoch_ms_mean * epochs, "compiles": compiles,
             "compile_ms": compile_ms, "cache_hits": 1, "cache_misses": 1}]


def test_store_golden_fit_and_predictions(tmp_path):
    base = str(tmp_path / "cache")
    path = costmodel.update_store(base, _rows(), platform="cpu")
    assert path == os.path.join(base, f"cost_model_v"
                                      f"{costmodel.STORE_VERSION}.json")
    with open(path) as f:
        store = json.load(f)
    assert store["version"] == costmodel.STORE_VERSION
    assert store["runs"] == 1
    [bucket] = store["buckets"].values()
    assert bucket == {
        "platform": "cpu", "shape": SHAPE, "g_bucket": 8,
        "precision": "f32", "epochs": 10,
        "epoch_ms_total": 1000.0, "compiles": 2, "compile_ms_total": 500.0,
        "cache_hits": 1, "cache_misses": 1, "runs": 1,
        "updated_at": bucket["updated_at"]}
    # precisionless rows default to the f32 bucket key (ISSUE 14)
    [key] = store["buckets"]
    assert key == costmodel.bucket_key("cpu", SHAPE, 8, "f32")

    model = costmodel.load(base)
    # exact bucket: the observed mean
    assert model.predict_epoch_ms(SHAPE, 8, platform="cpu") == 100.0
    # nearest-width fallback scales linearly by the width ratio
    assert model.predict_epoch_ms(SHAPE, 16, platform="cpu") == 200.0
    assert model.predict_epoch_ms(SHAPE, 4, platform="cpu") == 50.0
    # compile prediction: per-program mean, width-insensitive
    assert model.predict_compile_ms(SHAPE, 8) == 250.0
    assert model.predict_compile_ms(SHAPE, 16) == 250.0
    # no evidence for the shape at all -> None, never a guess
    assert model.predict_epoch_ms("other=1", 8) is None
    assert model.predict_fit_eta("other=1", 8, 10) is None
    # ETA: epochs x epoch mean (+ cold compiles)
    assert model.predict_fit_eta(SHAPE, 8, 20) == pytest.approx(2.0)
    assert model.predict_fit_eta(SHAPE, 8, 20, cold_programs=2) == \
        pytest.approx(2.5)
    assert model.staleness_s() is not None and model.staleness_s() >= 0


def test_nearest_width_fallback_clamped_to_adjacent_rung(tmp_path):
    """ISSUE 15 satellite: linear width scaling is evidence one rung away
    and extrapolation beyond — bucket 4 evidence must never price bucket
    256 (previously a confident 64x-scaled guess), and vice versa at the
    other extreme of the ladder."""
    base = str(tmp_path / "cache")
    costmodel.update_store(base, _rows(width=4), platform="cpu")
    model = costmodel.load(base)
    # exact + adjacent rungs still predict
    assert model.predict_epoch_ms(SHAPE, 4) == 100.0
    assert model.predict_epoch_ms(SHAPE, 8) == 200.0
    assert model.predict_epoch_ms(SHAPE, 2) == 50.0
    # beyond the adjacent rung: None, never a wild guess
    assert model.predict_epoch_ms(SHAPE, 16) is None
    assert model.predict_epoch_ms(SHAPE, 256) is None
    assert model.predict_fit_eta(SHAPE, 256, 10) is None
    # the other boundary: a widest-rung store never prices the bottom
    base2 = str(tmp_path / "cache2")
    costmodel.update_store(base2, _rows(width=256), platform="cpu")
    model2 = costmodel.load(base2)
    assert model2.predict_epoch_ms(SHAPE, 128) == 50.0
    assert model2.predict_epoch_ms(SHAPE, 4) is None
    assert model2.predict_epoch_ms(SHAPE, 64) is None
    # the clamp prefers the nearer rung when two are adjacent
    costmodel.update_store(base, _rows(width=8, epoch_ms_mean=300.0),
                           platform="cpu")
    model3 = costmodel.load(base)
    assert model3.predict_epoch_ms(SHAPE, 16) == 600.0  # from 8, not 4


def test_compile_warm_is_exact_bucket_evidence(tmp_path):
    base = str(tmp_path / "cache")
    costmodel.update_store(base, _rows(width=8), platform="cpu")
    model = costmodel.load(base)
    assert model.compile_warm(SHAPE, 8)
    assert model.compile_warm(SHAPE, 8, platform="cpu")
    # warmth never transfers across widths, platforms, or precisions: a
    # different bucket is a different executable
    assert not model.compile_warm(SHAPE, 4)
    assert not model.compile_warm(SHAPE, 8, platform="tpu")
    assert not model.compile_warm(SHAPE, 8, precision="mixed")
    assert not model.compile_warm("other=1", 8)


def test_store_accumulates_across_updates_and_platforms(tmp_path):
    base = str(tmp_path)
    costmodel.update_store(base, _rows(100.0, epochs=10), platform="cpu")
    costmodel.update_store(base, _rows(200.0, epochs=30), platform="cpu")
    costmodel.update_store(base, _rows(1.0, epochs=50), platform="tpu")
    model = costmodel.load(base)
    assert model.runs == 3
    # cpu bucket: (1000 + 6000) / 40 epochs
    assert model.predict_epoch_ms(SHAPE, 8, platform="cpu") == \
        pytest.approx(175.0)
    # platforms never mix
    assert model.predict_epoch_ms(SHAPE, 8, platform="tpu") == \
        pytest.approx(1.0)
    # platform=None picks the best-sampled bucket (tpu: 40 epochs)
    assert model.predict_epoch_ms(SHAPE, 8) == pytest.approx(1.0)


def test_corrupt_store_tolerated_and_rewritten(tmp_path):
    base = str(tmp_path)
    path = costmodel.store_path(base)
    with open(path, "w") as f:
        f.write('{"version": 1, "buckets": [truncated')
    assert costmodel.load(base) is None  # advisory: no model, no raise
    costmodel.update_store(base, _rows(), platform="cpu")
    model = costmodel.load(base)
    assert model is not None and model.predict_epoch_ms(SHAPE, 8) == 100.0


def test_store_path_resolution(tmp_path, monkeypatch):
    monkeypatch.delenv(costmodel.ENV_STORE_DIR, raising=False)
    monkeypatch.delenv(costmodel.ENV_CACHE_DIR, raising=False)
    assert costmodel.store_path() is None
    assert costmodel.load() is None
    monkeypatch.setenv(costmodel.ENV_CACHE_DIR, str(tmp_path / "cc"))
    assert costmodel.store_path() == str(
        tmp_path / "cc" / costmodel.STORE_NAME)
    monkeypatch.setenv(costmodel.ENV_STORE_DIR, str(tmp_path / "ov"))
    assert costmodel.store_path() == str(
        tmp_path / "ov" / costmodel.STORE_NAME)


def test_store_bucket_cap_evicts_oldest(tmp_path, monkeypatch):
    monkeypatch.setattr(costmodel, "MAX_BUCKETS", 4)
    base = str(tmp_path)
    for i in range(6):
        costmodel.update_store(base, _rows(shape=f"num_chans={i}"),
                               platform="cpu", now=float(i))
    model = costmodel.load(base)
    assert len(model.buckets) == 4
    # the oldest-updated buckets were evicted
    assert model.predict_epoch_ms("num_chans=0", 8) is None
    assert model.predict_epoch_ms("num_chans=5", 8) == 100.0


def test_rows_from_dispatch_stats_attaches_compile_to_widest():
    stats = {"epochs_by_width": {"8": 5, "4": 3},
             "epoch_ms_by_width": {"8": 500.0, "4": 150.0},
             "compiles": 6, "compile_ms": 900.0,
             "cache_hits": 2, "cache_misses": 4}
    rows = costmodel.rows_from_dispatch_stats(SHAPE, stats)
    assert [r["g_bucket"] for r in rows] == [8, 4]
    assert rows[0]["compiles"] == 6 and rows[0]["compile_ms"] == 900.0
    assert rows[1]["compiles"] == 0 and rows[1]["compile_ms"] == 0.0


def test_rows_exclude_compile_skewed_first_epoch():
    """The store learns STEADY-STATE epoch cost: each width's first epoch
    (compile/cache-priming skew) is dropped when later epochs exist."""
    stats = {"epochs_by_width": {"8": 5, "4": 1},
             "epoch_ms_by_width": {"8": 2040.0, "4": 300.0},
             # first epoch paid 2000ms of compile; steady state is 10ms
             "first_epoch_ms_by_width": {"8": 2000.0, "4": 300.0}}
    rows = costmodel.rows_from_dispatch_stats(SHAPE, stats)
    assert rows[0]["epochs"] == 4 and rows[0]["epoch_ms"] == 40.0
    # a single-epoch width keeps its one observation (better than nothing)
    assert rows[1]["epochs"] == 1 and rows[1]["epoch_ms"] == 300.0
    # pre-change stats without the accumulator fold unchanged
    legacy = {"epochs_by_width": {"8": 5},
              "epoch_ms_by_width": {"8": 500.0}}
    [row] = costmodel.rows_from_dispatch_stats(SHAPE, legacy)
    assert row["epochs"] == 5 and row["epoch_ms"] == 500.0


def test_fit_from_report_and_report_fold(tmp_path):
    report = {"cost_table": [
        {"shape": SHAPE, "g_bucket": 4, "epochs": 8,
         "total_epoch_ms": 400.0, "compiles": 1, "compile_ms": 100.0,
         "cache_hits": 0, "cache_misses": 1}]}
    model = costmodel.fit_from_report(report, platform="cpu")
    assert model.predict_epoch_ms(SHAPE, 4, platform="cpu") == 50.0
    costmodel.update_store_from_report(str(tmp_path), report,
                                       platform="cpu")
    assert costmodel.load(str(tmp_path)).predict_epoch_ms(
        SHAPE, 4, platform="cpu") == 50.0


# ---------------------------------------------------------------------------
# supervisor per-attempt ETA (runtime/supervisor.py tail-read)
# ---------------------------------------------------------------------------
def test_latest_cost_model_eta_reads_newest_event(tmp_path):
    ledger = str(tmp_path / "run_ledger.jsonl")
    metrics = tmp_path / "metrics.jsonl"
    with open(metrics, "w") as f:
        f.write(json.dumps({"event": "epoch", "wall_time": 1.0,
                            "epoch": 0}) + "\n")
        for e, eta in ((1, 30.0), (2, 20.0)):
            f.write(json.dumps({
                "event": "cost_model", "wall_time": 2.0, "epoch": e,
                "predicted_epoch_ms": 10.0, "actual_epoch_ms": 11.0,
                "eta_s": eta, "epochs_remaining": 2 - e,
                "source": "store"}) + "\n")
        f.write('{"event": "cost_model", "epoch": 3, "torn mid-app')
    eta = latest_cost_model_eta(ledger)
    assert eta == {"eta_s": 20.0, "predicted_epoch_ms": 10.0,
                   "epochs_remaining": 0, "epoch": 2, "source": "store",
                   "wall_time": 2.0}
    # since_wall bounds the scan to THIS attempt's telemetry: an event
    # stamped before the attempt started is not inherited
    assert latest_cost_model_eta(ledger, since_wall=1.5) == eta
    assert latest_cost_model_eta(ledger, since_wall=2.5) is None


def test_latest_cost_model_eta_absent_cases(tmp_path):
    assert latest_cost_model_eta(str(tmp_path / "run_ledger.jsonl")) is None
    with open(tmp_path / "metrics.jsonl", "w") as f:
        f.write(json.dumps({"event": "epoch", "wall_time": 1.0,
                            "epoch": 0}) + "\n")
    assert latest_cost_model_eta(str(tmp_path / "run_ledger.jsonl")) is None


def test_supervisor_stamps_eta_on_attempt(tmp_path):
    """A supervised run whose driver wrote cost_model telemetry DURING the
    attempt gets the remaining-work ETA on its attempt ledger record
    (schema-registered optional field); a stale event from a previous
    attempt is NOT inherited by one that died before its first window."""
    import sys

    from redcliff_tpu.obs import read_jsonl, schema
    from redcliff_tpu.runtime.supervisor import (SupervisorPolicy,
                                                 supervise)

    metrics = str(tmp_path / "metrics.jsonl")
    ledger = str(tmp_path / "run_ledger.jsonl")
    # the driver emits a cost_model event mid-attempt, then exits clean
    child = (
        "import json, time\n"
        f"open({metrics!r}, 'a').write(json.dumps({{\n"
        "    'event': 'cost_model', 'wall_time': time.time(), 'epoch': 5,\n"
        "    'predicted_epoch_ms': 100.0, 'actual_epoch_ms': 90.0,\n"
        "    'eta_s': 12.5, 'epochs_remaining': 125,\n"
        "    'source': 'observed'}) + '\\n')\n")
    out = supervise([sys.executable, "-c", child], ledger_path=ledger,
                    policy=SupervisorPolicy(max_restarts=0))
    assert out.classification == "clean"
    recs = read_jsonl(ledger)
    [att] = [r for r in recs if r["event"] == "attempt"]
    assert att["eta"]["eta_s"] == 12.5
    assert att["eta"]["epochs_remaining"] == 125
    assert not schema.validate_records(recs, kind="ledger")

    # second supervised run in the same dir, driver dies instantly: the
    # previous attempt's event predates this attempt -> NO inherited eta
    out = supervise([sys.executable, "-c", "raise SystemExit(0)"],
                    ledger_path=ledger,
                    policy=SupervisorPolicy(max_restarts=0))
    assert out.classification == "clean"
    att2 = [r for r in read_jsonl(ledger) if r["event"] == "attempt"][-1]
    assert "eta" not in att2
