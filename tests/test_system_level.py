"""System-level evaluation drivers (ref eval_utils.py:1093/:1692 capability):
per-fold key similarity battery, cross-fold aggregation, and the grid-search
variant, through the filesystem contract."""
import os
import pickle

import numpy as np
import pytest

from redcliff_tpu.data.curation import curate_synthetic_fold
from redcliff_tpu.eval.system_level import (
    evaluate_fold_system_level,
    evaluate_system_level_cv,
    evaluate_system_level_gs,
    key_similarity_stats,
    METRIC_KEYS,
)
from redcliff_tpu.models.dynotears import DynotearsConfig


def test_key_similarity_stats_perfect_match():
    A = np.array([[0.0, 1.0, 0.0], [0.0, 0.0, 1.0], [0.0, 0.0, 0.0]])
    s = key_similarity_stats(A, A)
    assert s["cos_sim"] == pytest.approx(1.0)
    assert s["mse"] == pytest.approx(0.0)
    assert s["deltaffinity"] == pytest.approx(1.0, abs=1e-9)
    assert s["roc_auc"] == pytest.approx(1.0)
    assert np.isfinite(s["dir_deltacon0"])
    assert np.isfinite(s["undir_deltacon0"])
    assert np.isfinite(s["deltacon0_wDD"])


def test_fold_system_level_views_and_options():
    rng = np.random.default_rng(0)
    true_gcs = [(rng.uniform(size=(4, 4, 2)) > 0.6).astype(float)
                for _ in range(2)]
    est_gcs = [g.sum(axis=2) + 0.05 * rng.uniform(size=(4, 4))
               for g in true_gcs]
    out = evaluate_fold_system_level(est_gcs, true_gcs)
    for view in ("normal", "transposed"):
        for k in METRIC_KEYS:
            assert len(out[view][k]) == 2
    # near-perfect estimates score near-perfect cosine on the normal view
    assert min(out["normal"]["cos_sim"]) > 0.95
    # identity baseline ignores the estimates entirely
    ident = evaluate_fold_system_level(est_gcs, true_gcs,
                                       evaluate_identity_baseline=True)
    assert max(ident["normal"]["cos_sim"]) < min(out["normal"]["cos_sim"])
    # Hungarian sorting follows the reference's convention exactly: the
    # assignment MINIMIZES cosine similarity (scipy's default, ref
    # metrics.py:274-301 — documented in utils/metrics.py), so aligned
    # estimates get anti-matched rather than kept in place
    sorted_out = evaluate_fold_system_level(est_gcs, true_gcs,
                                            sort_unsupervised_ests=True)
    assert max(sorted_out["normal"]["cos_sim"]) < 0.95
    # averaging only kicks in with MORE estimates than truths, which
    # requires exactly one truth (ref eval_utils.py:1264-1270); with equal
    # counts it is a no-op
    avg_noop = evaluate_fold_system_level(
        est_gcs, true_gcs, average_estimated_graphs_together=True)
    assert avg_noop["normal"]["mse"] == out["normal"]["mse"]
    avg = evaluate_fold_system_level(
        est_gcs + est_gcs, [true_gcs[0]],
        average_estimated_graphs_together=True)
    assert len(avg["normal"]["mse"]) == 1
    # truth preprocessing parity: the truth is never normalized or masked,
    # so a scaled truth changes MSE (est normalization is est-only)
    scaled = evaluate_fold_system_level(est_gcs,
                                        [2.0 * t for t in true_gcs])
    assert scaled["normal"]["mse"][0] != out["normal"]["mse"][0]


def _write_dyno_run(run_dir, a_est):
    os.makedirs(run_dir)
    with open(os.path.join(run_dir, "final_best_model.bin"), "wb") as f:
        pickle.dump({"model_class": "DynotearsVanillaModel",
                     "config": DynotearsConfig(lag_size=1),
                     "a_est": a_est}, f)


def test_evaluate_system_level_cv_and_gs(tmp_path):
    # real curation artifacts provide the true-graph cached-args contract
    data_args = {}
    graphs_by_fold = {}
    for fold in range(2):
        fold_dir, graphs = curate_synthetic_fold(
            str(tmp_path / "data"), fold_id=fold, num_nodes=5, num_factors=2,
            num_samples_in_train_set=4, num_samples_in_val_set=2,
            sample_recording_len=20, folder_name="toySys")
        data_args[fold] = os.path.join(fold_dir,
                                       f"data_fold{fold}_cached_args.txt")
        graphs_by_fold[fold] = graphs

    root = tmp_path / "DYNOTEARS_Vanilla_models"
    rng = np.random.default_rng(1)
    for fold in range(2):
        truth0 = np.asarray(graphs_by_fold[fold][0]).sum(axis=2)
        _write_dyno_run(str(root / f"dyno_data_fold{fold}_run"),
                        truth0 + 0.01 * rng.uniform(size=truth0.shape))

    out = evaluate_system_level_cv(
        "DYNOTEARS_Vanilla", str(root), ["data"],
        [data_args[0], data_args[1]], str(tmp_path / "eval"))
    agg = out["data"]
    for view in ("normal", "transposed"):
        for k in METRIC_KEYS:
            entry = agg[view][k]
            assert set(entry["by_fold"]) == {0, 1}
            assert len(entry["by_fold"][0]) == 2  # per-factor values
            assert entry["cross_fold_mean"] is not None
    # single-graph baselines replicate across factor slots, so factor 0's
    # estimate (near truth) scores a high cosine on the normal view
    assert agg["normal"]["cos_sim"]["by_fold"][0][0] > 0.95
    assert (tmp_path / "eval" / "data_system_level_eval_summary.pkl").exists()

    # grid-search variant: every run scored against one truth set
    gs = evaluate_system_level_gs(
        "DYNOTEARS_Vanilla", str(root),
        [np.asarray(g) for g in graphs_by_fold[0]],
        str(tmp_path / "eval_gs"))
    assert set(gs) == {"dyno_data_fold0_run", "dyno_data_fold1_run"}
    assert (tmp_path / "eval_gs" / "gs_system_level_eval_summary.pkl").exists()
    # the fold-0 run was built from fold 0's truth: it must outscore fold 1's
    assert (gs["dyno_data_fold0_run"]["normal"]["cos_sim"][0]
            >= gs["dyno_data_fold1_run"]["normal"]["cos_sim"][0])


def test_combined_gc_and_true_graph_loader(tmp_path):
    """Small eval helpers: combined system-graph view (ref :884-891) and the
    all-datasets truth loader (ref :25-42)."""
    from redcliff_tpu.eval.cross_alg import (
        read_in_true_causal_graphs_for_all_datasets)
    from redcliff_tpu.eval.gc_estimates import (
        get_combined_gc_representations_across_factors)

    ests = [np.ones((3, 3)), 2 * np.ones((3, 3))]
    trues = [np.eye(3), np.eye(3)]
    ce, ct = get_combined_gc_representations_across_factors(ests, trues)
    np.testing.assert_array_equal(ce, 3 * np.ones((3, 3)))
    np.testing.assert_array_equal(ct, 2 * np.eye(3))

    fold_dir, graphs = curate_synthetic_fold(
        str(tmp_path / "data"), fold_id=0, num_nodes=4, num_factors=2,
        num_samples_in_train_set=2, num_samples_in_val_set=2,
        sample_recording_len=15, folder_name="toySys")
    args_file = os.path.join(fold_dir, "data_fold0_cached_args.txt")
    loaded = read_in_true_causal_graphs_for_all_datasets(
        ["data_fold0"], [args_file], str(tmp_path / "vis"))
    assert len(loaded) == 1 and len(loaded[0]) == 2
    np.testing.assert_allclose(np.asarray(loaded[0][0]).sum(),
                               np.asarray(graphs[0]).sum(), rtol=1e-6)
    assert (tmp_path / "vis" / "data_fold0" / "true_gc_factors.png").exists()


def test_sort_with_more_truths_than_estimates():
    """Slot list sizes by the truth count (regression: IndexError when a
    truth index from the Hungarian assignment exceeds the estimate count)."""
    rng = np.random.default_rng(3)
    trues = [(rng.uniform(size=(4, 4, 2)) > 0.6).astype(float)
             for _ in range(3)]
    ests = [t.sum(axis=2) for t in trues[:2]]
    out = evaluate_fold_system_level(ests, trues,
                                     sort_unsupervised_ests=True)
    # unmatched truths are skipped, matched pairs are scored
    assert len(out["normal"]["cos_sim"]) == 2
    assert np.all(np.isfinite(out["normal"]["cos_sim"]))


def test_sort_pairs_estimates_with_matched_truths():
    """When the Hungarian assignment matches estimates to truths {0, 2},
    the estimate matched to truth 2 must be scored against truth 2, not
    compacted onto unmatched truth 1 (regression: silent mispairing when
    fewer estimates than truths).

    The matcher replicates the reference's scipy-minimize-over-cosine
    behavior, so the chosen pairs are the LOWEST-similarity ones: with
    e0 = t0+t1 and e1 = t1+t2 the optimal assignment is e0->t2, e1->t0
    (both cost 0), leaving t1 unmatched."""
    base = np.zeros((4, 4))
    t0 = base.copy(); t0[0, 1] = 1.0
    t1 = base.copy(); t1[1, 2] = 1.0
    t2 = base.copy(); t2[2, 3] = 1.0
    trues = [t0, t1, t2]
    ests = [t0 + t1, t1 + t2]
    out = evaluate_fold_system_level(ests, trues,
                                     sort_unsupervised_ests=True)
    assert len(out["normal"]["cos_sim"]) == 2
    # correct pairing scores (t0 vs e1) and (t2 vs e0): cosine 0, MSE 3/16.
    # the old compacting behavior scored (t1 vs e0): cosine ~0.707, MSE 1/16
    np.testing.assert_allclose(out["normal"]["cos_sim"], 0.0, atol=1e-12)
    np.testing.assert_allclose(out["normal"]["mse"], 3.0 / 16.0, atol=1e-12)


def test_cv_duplicate_fold_runs_kept(tmp_path):
    """Two run dirs with the same fold token both survive aggregation under
    disambiguated keys (regression: silent overwrite)."""
    fold_dir, graphs = curate_synthetic_fold(
        str(tmp_path / "data"), fold_id=0, num_nodes=4, num_factors=2,
        num_samples_in_train_set=2, num_samples_in_val_set=2,
        sample_recording_len=15, folder_name="toySys")
    args_file = os.path.join(fold_dir, "data_fold0_cached_args.txt")
    root = tmp_path / "DYNOTEARS_Vanilla_models"
    truth0 = np.asarray(graphs[0]).sum(axis=2)
    _write_dyno_run(str(root / "dyno_data_fold0_run"), truth0 + 0.01)
    _write_dyno_run(str(root / "dyno_data_fold0_retry"), truth0 + 0.02)
    out = evaluate_system_level_cv(
        "DYNOTEARS_Vanilla", str(root), ["data"], [args_file],
        str(tmp_path / "eval"))
    by_fold = out["data"]["normal"]["cos_sim"]["by_fold"]
    assert len(by_fold) == 2  # both runs kept
