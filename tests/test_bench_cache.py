"""Tests for bench.py's cached-TPU-measurement fallback.

The axon tunnel is alive only in rare windows (round-3 probe logs: one
~30-minute window in ~7 hours). tpu_watch.py opportunistically measures during
live windows and caches the result; bench.py must headline that cached real-TPU
measurement (with provenance) when its own live probes fail, instead of
publishing only a CPU number. These tests pin that contract without needing a
TPU: the probe/measure children are monkeypatched.
"""
import importlib.util
import json
import sys
import types

import pytest

from redcliff_tpu.runtime.retry import RetryPolicy

REPO = __file__.rsplit("/tests/", 1)[0]


@pytest.fixture()
def bench_mod(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location("bench_under_test",
                                                  f"{REPO}/bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "TPU_CACHE_PATH", str(tmp_path / "cache.json"))
    monkeypatch.setattr(mod, "TPU_CACHE_SEED_PATH",
                        str(tmp_path / "cache_seed.json"))
    monkeypatch.setattr(mod, "TPU_MEASURE_LOCK", str(tmp_path / "cache.lock"))
    # one immediate probe attempt: the production PROBE_RETRY_POLICY backs
    # off for minutes, which is exactly what these tests must not do
    monkeypatch.setattr(mod, "PROBE_RETRY_POLICY",
                        RetryPolicy(max_attempts=1, base_delay_s=0.0))
    return mod


def _capture_emits(mod, monkeypatch):
    emitted = []
    monkeypatch.setattr(mod, "_emit", emitted.append)
    return emitted


def _fake_cache(mod, value=12345.0, pallas=None, measured_at=None):
    import datetime
    if measured_at is None:
        measured_at = datetime.datetime.now(
            datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")
    cache = {
        "measured_at": measured_at,
        "source": "tpu_watch.py",
        "result": {
            "metric": mod.METRIC, "value": value, "unit": "windows/s/chip",
            "vs_baseline": 67.0, "platform": "tpu", "device": "TPU v5e",
            "g_scaling": {"64": {"wps": 1.0, "wps_scan": 2.0, "mfu_pct": 40.7},
                          "128": {"wps": 1.5, "wps_scan": 3.0, "mfu_pct": 52.0}},
            "error": None,
        },
    }
    if pallas is not None:
        cache["pallas_prox_check"] = pallas
    with open(mod.TPU_CACHE_PATH, "w") as f:
        json.dump(cache, f)
    return cache


def test_load_cache_roundtrip(bench_mod):
    assert bench_mod._load_tpu_cache() is None
    _fake_cache(bench_mod)
    cache = bench_mod._load_tpu_cache()
    assert cache["result"]["value"] == 12345.0


def test_load_cache_rejects_non_tpu_and_garbage(bench_mod):
    cache = _fake_cache(bench_mod)
    cache["result"]["platform"] = "cpu"
    with open(bench_mod.TPU_CACHE_PATH, "w") as f:
        json.dump(cache, f)
    assert bench_mod._load_tpu_cache() is None
    with open(bench_mod.TPU_CACHE_PATH, "w") as f:
        f.write("{not json")
    assert bench_mod._load_tpu_cache() is None


def test_orchestrate_headlines_cached_tpu_when_probes_fail(bench_mod,
                                                           monkeypatch):
    cache = _fake_cache(bench_mod, pallas={"ok": True, "max_abs_err": 4.2e-7})
    emitted = _capture_emits(bench_mod, monkeypatch)
    monkeypatch.setattr(bench_mod, "_probe_accelerator",
                        lambda timeout_s=1.0: (False, "tunnel hung"))

    cpu_payload = {"metric": bench_mod.METRIC, "value": 999.0,
                   "unit": "windows/s/chip", "vs_baseline": 0.8,
                   "platform": "cpu", "error": None}
    monkeypatch.setattr(
        bench_mod, "_run_measure_child",
        lambda platform, timeout_s=1.0: (dict(cpu_payload), "ok")
        if platform == "cpu" else (None, "no tpu"))

    bench_mod._orchestrate()
    assert len(emitted) == 1
    out = emitted[0]
    # headline IS the cached TPU measurement, with provenance
    assert out["value"] == 12345.0
    assert out["platform"] == "tpu"
    assert out["cached"] is True
    assert out["measured_at"] == cache["measured_at"]
    assert out["g_scaling"]["128"]["mfu_pct"] == 52.0
    assert out["pallas_prox_check"]["ok"] is True
    # the error contract stays honest: TPU was unavailable for THIS run
    assert out["error"] and "unavailable" in out["error"]
    # the live CPU run rides along, fully identified
    assert out["live_fallback"]["platform"] == "cpu"
    assert out["live_fallback"]["value"] == 999.0
    assert out["probe_log"]  # current run's probes, not the cached run's


def test_orchestrate_cpu_fallback_without_cache_unchanged(bench_mod,
                                                          monkeypatch):
    emitted = _capture_emits(bench_mod, monkeypatch)
    monkeypatch.setattr(bench_mod, "_probe_accelerator",
                        lambda timeout_s=1.0: (False, "tunnel hung"))
    cpu_payload = {"metric": bench_mod.METRIC, "value": 999.0,
                   "unit": "windows/s/chip", "vs_baseline": 0.8,
                   "platform": "cpu", "error": None}
    monkeypatch.setattr(
        bench_mod, "_run_measure_child",
        lambda platform, timeout_s=1.0: (dict(cpu_payload), "ok")
        if platform == "cpu" else (None, "no tpu"))

    bench_mod._orchestrate()
    out = emitted[0]
    assert out["platform"] == "cpu"
    assert out["value"] == 999.0
    assert "cached" not in out
    assert "unavailable" in out["error"]


def test_stale_cache_reported_not_discarded(bench_mod, monkeypatch):
    """A dated real-TPU measurement beats a CPU fallback with none: age is
    surfaced (age_hours / cache_stale), never used to drop the evidence."""
    _fake_cache(bench_mod, measured_at="2026-07-01T00:00:00Z")
    cache = bench_mod._load_tpu_cache()
    assert cache is not None
    assert cache["stale"] is True
    assert cache["age_hours"] > 48.0

    emitted = _capture_emits(bench_mod, monkeypatch)
    monkeypatch.setattr(bench_mod, "_probe_accelerator",
                        lambda timeout_s=1.0: (False, "tunnel hung"))
    cpu_payload = {"metric": bench_mod.METRIC, "value": 999.0,
                   "unit": "windows/s/chip", "vs_baseline": 0.8,
                   "platform": "cpu", "error": None}
    monkeypatch.setattr(
        bench_mod, "_run_measure_child",
        lambda platform, timeout_s=1.0: (dict(cpu_payload), "ok")
        if platform == "cpu" else (None, "no tpu"))
    bench_mod._orchestrate()
    out = emitted[0]
    assert out["platform"] == "tpu" and out["cached"] is True
    assert out["cache_stale"] is True
    assert out["age_hours"] > 48.0


def test_cache_commit_mismatch_flagged(bench_mod, monkeypatch):
    cache = _fake_cache(bench_mod)
    cache["git_commit"] = "0000000"
    cache["backfilled"] = True
    cache["pre_scan_dispatch"] = True
    with open(bench_mod.TPU_CACHE_PATH, "w") as f:
        json.dump(cache, f)
    emitted = _capture_emits(bench_mod, monkeypatch)
    monkeypatch.setattr(bench_mod, "_probe_accelerator",
                        lambda timeout_s=1.0: (False, "tunnel hung"))
    monkeypatch.setattr(bench_mod, "_git_head", lambda: "abc1234")
    cpu_payload = {"metric": bench_mod.METRIC, "value": 999.0,
                   "unit": "windows/s/chip", "vs_baseline": 0.8,
                   "platform": "cpu", "error": None}
    monkeypatch.setattr(
        bench_mod, "_run_measure_child",
        lambda platform, timeout_s=1.0: (dict(cpu_payload), "ok")
        if platform == "cpu" else (None, "no tpu"))
    bench_mod._orchestrate()
    out = emitted[0]
    assert out["cache_commit_mismatch"] is True
    # backfill provenance markers ride through to the emitted headline
    assert out["backfilled"] is True
    assert out["pre_scan_dispatch"] is True


def test_lock_falls_back_lockless_on_unsupported_flock(bench_mod, monkeypatch):
    """Non-contention flock errnos (unsupported fs) must not read as 'another
    measurement holds the lock' — that would permanently skip live windows."""
    import errno
    import fcntl

    def broken_flock(fd, op):
        raise OSError(errno.ENOLCK, "No locks available")

    monkeypatch.setattr(fcntl, "flock", broken_flock)
    assert bench_mod._acquire_measure_lock(wait_s=0.0) is True
    bench_mod._release_measure_lock()


def test_measure_lock_exclusive_and_released(bench_mod):
    assert bench_mod._acquire_measure_lock(wait_s=0.0)
    # a second open file description cannot take the flock while held
    # (flock treats separately-opened descriptors independently, so this
    # models a second process)
    import fcntl
    import os
    fd = os.open(bench_mod.TPU_MEASURE_LOCK, os.O_WRONLY)
    with pytest.raises(OSError):
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    bench_mod._release_measure_lock()
    # after release the lock is immediately acquirable again
    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    fcntl.flock(fd, fcntl.LOCK_UN)
    os.close(fd)
    bench_mod._release_measure_lock()  # idempotent


def test_seed_cache_fallback(bench_mod):
    """The tracked seed file backs the gitignored runtime cache: absent or
    malformed runtime cache falls through to the seed."""
    import shutil
    shutil.copy(f"{REPO}/experiments/TPU_BENCH_CACHE_SEED.json",
                bench_mod.TPU_CACHE_SEED_PATH)
    cache = bench_mod._load_tpu_cache()
    assert cache is not None
    assert cache["backfilled"] is True
    assert cache["result"]["platform"] == "tpu"
    # runtime cache, once present and valid, wins over the seed
    _fake_cache(bench_mod, value=777.0)
    assert bench_mod._load_tpu_cache()["result"]["value"] == 777.0
    # malformed runtime cache falls through to the seed, not to None
    with open(bench_mod.TPU_CACHE_PATH, "w") as f:
        f.write("{not json")
    assert bench_mod._load_tpu_cache()["backfilled"] is True


def test_live_tpu_success_writes_cache(bench_mod, monkeypatch):
    emitted = _capture_emits(bench_mod, monkeypatch)
    monkeypatch.setattr(bench_mod, "_probe_accelerator",
                        lambda timeout_s=1.0: (True, "tpu"))
    tpu_payload = {"metric": bench_mod.METRIC, "value": 5e7,
                   "unit": "windows/s/chip", "vs_baseline": 70.0,
                   "platform": "tpu", "device": "TPU v5e", "error": None}
    monkeypatch.setattr(
        bench_mod, "_run_measure_child",
        lambda platform, timeout_s=1.0: (dict(tpu_payload), "ok"))

    bench_mod._orchestrate()
    assert emitted[0]["platform"] == "tpu"
    assert "cached" not in emitted[0]
    cache = bench_mod._load_tpu_cache()
    assert cache["result"]["value"] == 5e7
    assert cache["source"] == "bench.py live run"
    assert "probe_log" not in cache["result"]
    assert "probe_retry" not in cache["result"]


def test_probe_retry_outcome_recorded_fixed_schema(bench_mod, monkeypatch):
    """Every orchestrate outcome carries the shared retry policy's
    fixed-schema log (policy knobs, per-attempt backoff, deadline_hit) so a
    BENCH artifact distinguishes "tunnel dead" from "policy too impatient"."""
    # failure path: probes exhausted -> probe_retry rides on the CPU payload
    emitted = _capture_emits(bench_mod, monkeypatch)
    monkeypatch.setattr(bench_mod, "_probe_accelerator",
                        lambda timeout_s=1.0: (False, "tunnel hung"))
    cpu_payload = {"metric": bench_mod.METRIC, "value": 999.0,
                   "unit": "windows/s/chip", "vs_baseline": 0.8,
                   "platform": "cpu", "error": None}
    monkeypatch.setattr(
        bench_mod, "_run_measure_child",
        lambda platform, timeout_s=1.0: (dict(cpu_payload), "ok")
        if platform == "cpu" else (None, "no tpu"))
    bench_mod._orchestrate()
    pr = emitted[0]["probe_retry"]
    assert pr["ok"] is False
    assert pr["num_attempts"] == len(pr["attempts"]) == 1
    assert set(pr["attempts"][0]) >= {"attempt", "backoff_s", "t_offset_s",
                                      "ok"}
    assert pr["policy"]["max_attempts"] == 1
    assert pr["deadline_hit"] is False

    # success path: probe_retry lands in the emitted payload AND the cache
    emitted.clear()
    monkeypatch.setattr(bench_mod, "_probe_accelerator",
                        lambda timeout_s=1.0: (True, "tpu"))
    tpu_payload = {"metric": bench_mod.METRIC, "value": 5e7,
                   "unit": "windows/s/chip", "vs_baseline": 70.0,
                   "platform": "tpu", "device": "TPU v5e", "error": None}
    monkeypatch.setattr(
        bench_mod, "_run_measure_child",
        lambda platform, timeout_s=1.0: (dict(tpu_payload), "ok"))
    bench_mod._orchestrate()
    assert emitted[0]["probe_retry"]["ok"] is True
    with open(bench_mod.TPU_CACHE_PATH) as f:
        cache = json.load(f)
    assert cache["probe_retry"]["ok"] is True
    assert cache["probe_retry"]["attempts"][0]["ok"] is True
