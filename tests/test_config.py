"""Tests for the legacy cached-args config compatibility layer."""
import json
import os

import numpy as np
import pytest

from redcliff_tpu.utils.config import (
    parse_input_list_of_ints,
    parse_input_list_of_strs,
    parse_tensor_string_representation,
    read_in_data_args,
    read_in_model_args,
    serialize_tensor_to_string,
)

REF_TRAIN = "/root/reference/train"


def test_parse_int_list():
    assert parse_input_list_of_ints("[]") == []
    assert parse_input_list_of_ints("[25]") == [25]
    assert parse_input_list_of_ints("[1,2,3]") == [1, 2, 3]


def test_parse_str_list():
    assert parse_input_list_of_strs("[]") == []
    assert parse_input_list_of_strs("[a,b]") == ["a", "b"]


def test_tensor_string_roundtrip():
    rng = np.random.default_rng(0)
    t = rng.uniform(size=(4, 4, 3))
    s = serialize_tensor_to_string(t)
    parsed = parse_tensor_string_representation(s)[:, :, ::-1]
    np.testing.assert_allclose(parsed, t, rtol=1e-12)


def test_tensor_string_single_element():
    s = "[[[0.5,],],]"
    t = parse_tensor_string_representation(s)
    assert t.shape == (1, 1, 1)
    assert t[0, 0, 0] == 0.5


def test_tensor_string_lag_major_transpose():
    # two 3x3 lag slices; parsed result must be (3, 3, 2) with slice order
    # preserved along the last axis
    sl0 = [[1.0, 2.0, 3.0], [4.0, 5.0, 6.0], [7.0, 8.0, 9.0]]
    sl1 = [[10.0, 11.0, 12.0], [13.0, 14.0, 15.0], [16.0, 17.0, 18.0]]
    s = repr([sl0, sl1])
    t = parse_tensor_string_representation(s)
    assert t.shape == (3, 3, 2)
    np.testing.assert_array_equal(t[:, :, 0], sl0)
    np.testing.assert_array_equal(t[:, :, 1], sl1)


@pytest.mark.parametrize("fname,model_type", [
    ("REDCLIFF_S_CMLP_d4IC_BSCgs1_cached_args.txt", "REDCLIFF_S_CMLP"),
    ("cMLP_d4IC_BLgs1_cached_args.txt", "cMLP"),
    ("cLSTM_d4IC_BLgs1Parsim_cached_args.txt", "cLSTM"),
    ("DGCNN_d4IC_BLgs1Parsim_cached_args.txt", "DGCNN"),
    ("DCSFANMF_d4IC_OBPgs1_cached_args.txt", "DCSFA"),
    ("DYNOTEARS_Vanilla_d4IC_BCNIBCHVgs1Parsim_cached_args.txt",
     "DYNOTEARS_Vanilla"),
    ("NAVAR_CMLP_d4IC_BCTVgs1Parsim_cached_args.txt", "NAVAR_CMLP"),
    ("REDCLIFF_S_CMLP_Smooth_d4IC_BSCgs4ParsimSmo0_cached_args.txt",
     "REDCLIFF_S_CMLP_WithSmoothing"),
])
def test_read_reference_model_cached_args(fname, model_type):
    """Parity check: every published reference cached-args file parses under
    the family schema without error and yields typed values."""
    path = os.path.join(REF_TRAIN, fname)
    if not os.path.isfile(path):
        pytest.skip(f"reference file absent: {fname}")
    args = {"model_type": model_type, "model_cached_args_file": path}
    out = read_in_model_args(args)
    assert out is args
    if model_type == "REDCLIFF_S_CMLP":
        assert out["num_factors"] == 5
        assert out["coeff_dict"]["FORECAST_COEFF"] == 10.0
        assert out["coeff_dict"]["FACTOR_SCORE_COEFF"] == 100.0
        assert out["gen_lag"] == 4
        assert out["factor_score_embedder_type"] == "DGCNN"
        assert out["primary_gc_est_mode"] == \
            "conditional_factor_fixed_embedder"
        assert isinstance(out["gen_hidden"], list)
    if model_type == "DCSFA":
        assert isinstance(out["n_components"], int)
        assert "dirspec_params" in out
    if model_type == "DYNOTEARS_Vanilla":
        assert isinstance(out["lambda_w"], float)
        assert out["X_train"] is None


def test_read_data_args_with_adjacency_tensors(tmp_path):
    rng = np.random.default_rng(1)
    g1 = (rng.uniform(size=(4, 4, 2)) > 0.5).astype(float)
    g2 = (rng.uniform(size=(4, 4, 2)) > 0.5).astype(float)
    cached = {
        "data_root_path": "/data/toy",
        "num_channels": "4",
        "net1_adjacency_tensor": serialize_tensor_to_string(g1),
        "net2_adjacency_tensor": serialize_tensor_to_string(g2),
    }
    p = tmp_path / "toy_cached_args.txt"
    with open(p, "w") as f:
        json.dump(cached, f)

    args = {"model_type": "REDCLIFF_S_CMLP", "data_cached_args_file": str(p)}
    out = read_in_data_args(args, read_in_gc_factors_for_eval=True)
    assert out["num_channels"] == 4
    assert len(out["true_GC_factors"]) == 2
    np.testing.assert_allclose(out["true_GC_factors"][0], g1)
    np.testing.assert_allclose(out["true_GC_factors"][1], g2)
    np.testing.assert_allclose(out["true_GC_tensor"][0], g1 + g2)

    # lag-collapsing families get the summed nontemporal view
    args2 = {"model_type": "DCSFA", "data_cached_args_file": str(p)}
    out2 = read_in_data_args(args2)
    np.testing.assert_allclose(out2["true_GC_tensor"][0],
                               (g1 + g2).sum(axis=2))


def test_read_reference_data_cached_args():
    """The reference repo ships dataset cached-args with stringified tensors;
    they must parse end-to-end."""
    root = "/root/reference/cached_dataset_args"
    if not os.path.isdir(root):
        pytest.skip("no reference cached_dataset_args dir")
    cands = [x for x in sorted(os.listdir(root)) if x.endswith(".txt")]
    if not cands:
        pytest.skip("no cached dataset args published")
    path = os.path.join(root, cands[0])
    with open(path) as f:
        raw = json.load(f)
    if not any("adjacency_tensor" in k for k in raw):
        pytest.skip("first cached-args file carries no adjacency tensors")
    args = {"model_type": "REDCLIFF_S_CMLP", "data_cached_args_file": path}
    out = read_in_data_args(args, read_in_gc_factors_for_eval=True)
    assert out["true_GC_factors"]
    for t in out["true_GC_factors"]:
        assert t.ndim == 3 and t.shape[0] == t.shape[1]


def test_curate_synthetic_fold_roundtrip(tmp_path):
    """Curation writes shards + cached-args; the config reader must recover
    the exact ground-truth graphs and the shard loader the samples."""
    from redcliff_tpu.data.curation import curate_synthetic_fold
    from redcliff_tpu.data.shards import load_shard_samples

    fold_dir, graphs = curate_synthetic_fold(
        str(tmp_path), fold_id=0, num_nodes=5, num_factors=2,
        num_samples_in_train_set=6, num_samples_in_val_set=2,
        sample_recording_len=50, burnin_period=5)
    train = load_shard_samples(os.path.join(fold_dir, "train"))
    assert len(train) == 6
    assert train[0][0].shape == (50, 5)

    cached = [x for x in os.listdir(fold_dir) if "cached_args" in x]
    assert len(cached) == 1
    args = {"model_type": "REDCLIFF_S_CMLP",
            "data_cached_args_file": os.path.join(fold_dir, cached[0])}
    out = read_in_data_args(args, read_in_gc_factors_for_eval=True)
    assert out["num_channels"] == 5
    assert len(out["true_GC_factors"]) == 2
    for est, true in zip(out["true_GC_factors"], graphs):
        np.testing.assert_allclose(est, true, rtol=1e-10)


def test_clean_and_aggregate(tmp_path):
    from redcliff_tpu.data.curation import (
        aggregate_synthetic_systems_datasets,
        clean_incomplete_experiment_folders,
        curate_synthetic_fold,
    )

    root = tmp_path / "curated"
    os.makedirs(root)
    curate_synthetic_fold(str(root), fold_id=0, num_nodes=5, num_factors=2,
                          num_samples_in_train_set=2, num_samples_in_val_set=1,
                          sample_recording_len=30, folder_name="sysA")
    # incomplete experiment: fold dir without cached args
    os.makedirs(root / "sysB" / "fold_0")
    kept = clean_incomplete_experiment_folders(str(root), num_folds=1)
    assert len(kept) == 1 and "sysA" in kept[0]
    assert not os.path.exists(root / "sysB")

    dest = aggregate_synthetic_systems_datasets(
        [str(root / "sysA")], str(tmp_path), "SynSys-bench")
    assert os.path.isdir(os.path.join(dest, "sysA", "fold_0"))


def test_dcsfa_dirspec_params_match_reference():
    path = os.path.join(REF_TRAIN, "DCSFANMF_d4IC_OBPgs1_cached_args.txt")
    if not os.path.isfile(path):
        pytest.skip("reference cached-args absent")
    args = {"model_type": "DCSFA", "model_cached_args_file": path}
    read_in_model_args(args)
    dp = args["dirspec_params"]
    assert dp["fs"] == 1000 and dp["max_freq"] == 250.0
    assert dp["csd_params"]["nperseg"] == args["num_node_features"]
    assert args["max_num_features_per_series"] == args["num_node_features"]


def test_include_gc_views_for_eval(tmp_path):
    rng = np.random.default_rng(2)
    g1 = (rng.uniform(size=(4, 4, 2)) > 0.5).astype(float)
    cached = {"data_root_path": "/d", "num_channels": "4",
              "net1_adjacency_tensor": serialize_tensor_to_string(g1)}
    p = tmp_path / "c.txt"
    with open(p, "w") as f:
        json.dump(cached, f)
    args = {"model_type": "DCSFA", "data_cached_args_file": str(p)}
    out = read_in_data_args(args, include_gc_views_for_eval=True)
    np.testing.assert_allclose(out["true_lagged_GC_tensor_factors"][0], g1)
    np.testing.assert_allclose(out["true_nontemporal_GC_tensor"],
                               g1.sum(axis=2))


def test_wavelet_signal_format_passthrough():
    from redcliff_tpu.data.shards import apply_signal_format

    X = np.ones((2, 8, 3), np.float32)
    out = apply_signal_format(X, "wavelet_decomp")
    np.testing.assert_array_equal(out, X)


def test_many_factor_ordering_and_gc_views(tmp_path):
    """10+ factors must keep numeric order and fill grown gc-view slots."""
    rng = np.random.default_rng(3)
    graphs = [np.full((3, 3, 1), float(i + 1)) for i in range(11)]
    cached = {"data_root_path": "/d", "num_channels": "3"}
    for i, g in enumerate(graphs):
        cached[f"net{i+1}_adjacency_tensor"] = serialize_tensor_to_string(g)
    p = tmp_path / "many.txt"
    with open(p, "w") as f:
        json.dump(cached, f)
    args = {"model_type": "REDCLIFF_S_CMLP", "data_cached_args_file": str(p)}
    out = read_in_data_args(args, read_in_gc_factors_for_eval=True,
                            include_gc_views_for_eval=True)
    assert len(out["true_GC_factors"]) == 11
    for i, t in enumerate(out["true_GC_factors"]):
        assert t[0, 0, 0] == float(i + 1), i
    assert len(out["true_lagged_GC_tensor_factors"]) == 11
    assert out["true_lagged_GC_tensor_factors"][10][0, 0, 0] == 11.0
