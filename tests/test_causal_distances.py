"""Tests for native SHD / AID causal distances (gadjid-parity module)."""
import itertools

import numpy as np
import pytest

from redcliff_tpu.eval.causal_distances import (
    _d_separated,
    _reachability,
    _to_row_to_col,
    ancestor_aid,
    oset_aid,
    parent_aid,
    shd,
)


def _dag(n, edges):
    A = np.zeros((n, n), dtype=int)
    for i, j in edges:
        A[i, j] = 1
    return A


# ---------------------------------------------------------------- SHD

def test_shd_identical_zero():
    A = _dag(4, [(0, 1), (1, 2), (2, 3)])
    assert shd(A, A) == (0.0, 0)


def test_shd_counts_reversal_once():
    A = _dag(3, [(0, 1)])
    B = _dag(3, [(1, 0)])
    norm, count = shd(A, B)
    assert count == 1
    assert norm == pytest.approx(1 / 3)


def test_shd_missing_and_extra():
    A = _dag(3, [(0, 1), (1, 2)])
    B = _dag(3, [(0, 1), (0, 2)])
    # {1,2} differs (missing), {0,2} differs (extra) -> 2 mistakes
    assert shd(A, B)[1] == 2


def test_shd_column_to_row_convention():
    A = _dag(3, [(0, 1)])
    assert shd(A.T, A.T, edge_direction="from column to row") == (0.0, 0)
    assert shd(A, A.T, edge_direction="from column to row")[1] == 1


# ------------------------------------------------------- d-separation

def _all_paths(adj_und, x, y):
    """All simple paths x..y in an undirected-representation for the oracle."""
    n = adj_und.shape[0]
    paths = []

    def extend(path):
        last = path[-1]
        if last == y:
            paths.append(list(path))
            return
        for nxt in range(n):
            if adj_und[last, nxt] and nxt not in path:
                path.append(nxt)
                extend(path)
                path.pop()

    extend([x])
    return paths


def _path_blocked(B, path, Z):
    """Classic d-separation path blocking: for each interior node decide
    collider/non-collider from edge orientations in DAG B."""
    R = _reachability(B)
    for k in range(1, len(path) - 1):
        prev, node, nxt = path[k - 1], path[k], path[k + 1]
        into_prev = B[prev, node]   # prev -> node
        into_next = B[nxt, node]    # nxt -> node
        collider = into_prev and into_next
        if collider:
            # blocked unless node or a descendant of node is in Z
            desc = R[node].copy()
            desc[node] = True
            if not np.any(desc & Z):
                return True
        else:
            if Z[node]:
                return True
    return False


def _d_separated_oracle(B, x, y, Z):
    und = B | B.T
    for path in _all_paths(und, x, y):
        if not _path_blocked(B, path, Z):
            return False
    return True


def test_d_separation_matches_bruteforce_on_random_dags():
    rng = np.random.default_rng(0)
    n = 5
    for trial in range(30):
        # random DAG via upper-triangular mask over a random permutation
        perm = rng.permutation(n)
        A = np.zeros((n, n), dtype=bool)
        for i in range(n):
            for j in range(i + 1, n):
                if rng.uniform() < 0.4:
                    A[perm[i], perm[j]] = True
        for x, y in itertools.permutations(range(n), 2):
            for zbits in range(2 ** n):
                Z = np.array([(zbits >> k) & 1 for k in range(n)], dtype=bool)
                if Z[x] or Z[y]:
                    continue
                fast = _d_separated(A, x, y, Z)
                slow = _d_separated_oracle(A, x, y, Z)
                assert fast == slow, (trial, x, y, Z.nonzero())
        if trial >= 5:  # 6 full graphs is plenty; keep runtime bounded
            break


# ------------------------------------------------------------- AID

def test_aid_identical_graphs_zero():
    A = _dag(5, [(0, 1), (1, 2), (0, 3), (3, 4), (2, 4)])
    for fn in (parent_aid, ancestor_aid, oset_aid):
        assert fn(A, A) == (0.0, 0)


def test_aid_missing_confounder_is_mistake():
    # true: z -> x, z -> y, x -> y ; guess omits z -> x
    true = _dag(3, [(2, 0), (2, 1), (0, 1)])
    guess = _dag(3, [(2, 1), (0, 1)])
    # guess proposes Pa(x)=∅ for (x=0, y=1); backdoor 0 <- 2 -> 1 is open
    norm, count = parent_aid(true, guess)
    assert count >= 1
    # with the confounder present in the guess, parent adjustment is valid
    assert parent_aid(true, true) == (0.0, 0)


def test_aid_empty_guess_counts_true_effects():
    true = _dag(4, [(0, 1), (1, 2), (2, 3)])
    guess = np.zeros((4, 4), dtype=int)
    R = _reachability(_to_row_to_col(true, "from row to column"))
    expected = int(R.sum())  # every true effect is claimed away
    for fn in (parent_aid, ancestor_aid, oset_aid):
        assert fn(true, guess)[1] == expected


def test_aid_extra_edge_claims_effect_where_none():
    true = np.zeros((3, 3), dtype=int)
    guess = _dag(3, [(0, 1)])
    # guess claims an effect 0->1 with Z=∅; in the true graph the effect is
    # zero and ∅ is a valid adjustment set (no open paths), so NOT a mistake
    assert parent_aid(true, guess) == (0.0, 0)


def test_aid_reversed_edge_mistakes():
    true = _dag(2, [(0, 1)])
    guess = _dag(2, [(1, 0)])
    # pair (0,1): guess claims no effect but truth has one -> mistake
    # pair (1,0): guess claims effect with Z=Pa(1)=∅; truth: effect of 1 on 0
    #   is zero and the path 1 <- 0 is blocked? path 1 <- 0 is non-causal,
    #   with no conditioning it is open 0 -> 1 ... x=1,y=0: path 1 <- 0 has no
    #   interior nodes, unblockable -> mistake
    norm, count = parent_aid(true, guess)
    assert count == 2
    assert norm == pytest.approx(1.0)


def test_aid_cycle_raises():
    cyc = _dag(3, [(0, 1), (1, 2), (2, 0)])
    ok = _dag(3, [(0, 1)])
    for fn in (parent_aid, ancestor_aid, oset_aid):
        with pytest.raises(ValueError):
            fn(cyc, ok)
        with pytest.raises(ValueError):
            fn(ok, cyc)


def test_aid_column_to_row_convention():
    true = _dag(3, [(2, 0), (2, 1), (0, 1)])
    guess = _dag(3, [(2, 1), (0, 1)])
    a = parent_aid(true, guess)
    b = parent_aid(true.T, guess.T, edge_direction="from column to row")
    assert a == b


def test_oset_vs_parent_on_mediator_graph():
    # x -> m -> y with confounder c: c -> x, c -> y
    true = _dag(4, [(0, 1), (1, 2), (3, 0), (3, 2)])
    # guess identical: all strategies valid
    for fn in (parent_aid, ancestor_aid, oset_aid):
        assert fn(true, true) == (0.0, 0)


def test_aid_strategies_differ_in_general():
    rng = np.random.default_rng(3)
    n = 6
    diffs = 0
    for _ in range(20):
        def rand_dag():
            A = np.zeros((n, n), dtype=int)
            perm = rng.permutation(n)
            for i in range(n):
                for j in range(i + 1, n):
                    if rng.uniform() < 0.35:
                        A[perm[i], perm[j]] = 1
            return A

        t, g = rand_dag(), rand_dag()
        res = {fn.__name__: fn(t, g)[1]
               for fn in (parent_aid, ancestor_aid, oset_aid)}
        if len(set(res.values())) > 1:
            diffs += 1
    assert diffs > 0  # the three flavors are genuinely different metrics
