"""Model-quality observatory tests (obs/quality.py, ISSUE 13).

Pins the scientific-telemetry contracts: golden parity between the live
device summary and the offline eval/gc_estimates readout, bit-identical
decision streams with the observatory on vs off, schema-valid `quality`
events with live AUROC under ground truth, the convergence diagnostics
(Jaccard stability, plateau detection, point-id keying across filler
lanes), the regression sentinel's scientific families (floors flag an
injected AUROC degradation; the real BENCH trajectory stays quiet), the
fleet per-request quality blocks, and graceful report/watch behavior on
PR-12-era (pre-quality) run dirs.
"""
import json
import os
import pickle

import jax
import numpy as np
import pytest

from redcliff_tpu.data.datasets import ArrayDataset
from redcliff_tpu.eval import gc_estimates as GE
from redcliff_tpu.models.redcliff import RedcliffSCMLP, RedcliffSCMLPConfig
from redcliff_tpu.obs import quality as Q
from redcliff_tpu.obs import read_jsonl, schema
from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
from redcliff_tpu.train.redcliff_trainer import (RedcliffTrainConfig,
                                                 RedcliffTrainer)


def _model(num_chans=4, num_factors=2):
    return RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=num_chans, gen_lag=2, gen_hidden=(8,), embed_lag=4,
        embed_hidden_sizes=(8,), num_factors=num_factors,
        num_supervised_factors=2, factor_weight_l1_coeff=0.01,
        adj_l1_reg_coeff=0.001, factor_cos_sim_coeff=0.01,
        factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive", num_sims=1,
        training_mode="combined"))


def _data(model, n=32, seed=0):
    cfg = model.config
    rng = np.random.default_rng(seed)
    T = cfg.max_lag + cfg.num_sims
    X = rng.normal(size=(n, T, cfg.num_chans)).astype(np.float32)
    Y = rng.uniform(
        size=(n, cfg.num_supervised_factors + 1, 1)).astype(np.float32)
    return ArrayDataset(X, Y)


def _true_gc(model, seed=1):
    rng = np.random.default_rng(seed)
    C = model.config.num_chans
    return [(np.abs(rng.normal(size=(C, C, 2)))
             * (rng.random((C, C, 2)) > 0.5)).astype(np.float32)
            for _ in range(model.config.num_factors)]


@pytest.fixture(scope="module")
def quality_run(tmp_path_factory):
    """One shared grid fit with the observatory on and ground truth in
    hand; reused by the parity / events / report / watch tests."""
    model = _model()
    ds = _data(model)
    truth = _true_gc(model)
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 5e-3}])
    tc = RedcliffTrainConfig(max_iter=4, batch_size=16, check_every=1)
    runner = RedcliffGridRunner(model, tc, spec)
    run_dir = str(tmp_path_factory.mktemp("quality_run"))
    result = runner.fit(jax.random.PRNGKey(0), ds, ds, log_dir=run_dir,
                        true_gc=truth)
    return {"model": model, "ds": ds, "truth": truth, "runner": runner,
            "result": result, "run_dir": run_dir}


# ---------------------------------------------------------------------------
# unit layer
# ---------------------------------------------------------------------------

def test_topk_hash_is_order_free_and_stable():
    assert Q.topk_hash([3, 1, 2]) == Q.topk_hash([2, 3, 1])
    assert Q.topk_hash([3, 1, 2]) != Q.topk_hash([3, 1, 4])
    assert len(Q.topk_hash(range(8))) == 12


def test_jaccard():
    assert Q.jaccard([1, 2, 3], [1, 2, 3]) == 1.0
    assert Q.jaccard([1, 2], [3, 4]) == 0.0
    assert Q.jaccard([1, 2, 3], [2, 3, 4]) == pytest.approx(0.5)
    assert Q.jaccard([], []) == 1.0


def test_average_precision():
    # perfect ranking -> 1.0; no positives -> None
    assert Q.average_precision([1, 1, 0, 0], [4, 3, 2, 1]) == 1.0
    assert Q.average_precision([0, 0], [1, 2]) is None
    # known value: positives at ranks 1 and 3 -> (1/1 + 2/3) / 2
    assert Q.average_precision([1, 0, 1, 0], [4, 3, 2, 1]) \
        == pytest.approx((1.0 + 2.0 / 3.0) / 2.0)


def test_topk_indices_np_matches_lax_topk_tie_order():
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    A = rng.normal(size=(5, 5)).astype(np.float32)
    A.ravel()[3] = A.ravel()[7]  # force a tie
    _, idx = jax.lax.top_k(jnp.abs(jnp.asarray(A)).ravel(), 6)
    np.testing.assert_array_equal(np.asarray(idx),
                                  Q.topk_indices_np(A, 6))


def _host_summary(energy, topk, C=4, K=2, seed=0):
    rng = np.random.default_rng(seed)
    n = len(energy)
    return {
        "gc": rng.random((n, K, C, C)).astype(np.float32),
        "col_norms": rng.random((n, K, C)).astype(np.float32),
        "edge_energy": np.asarray(energy, np.float32),
        "sparsity": np.full((n,), 0.5, np.float32),
        "topk_idx": np.asarray(topk, np.int32),
        "topk_val": rng.random((n, len(topk[0]))).astype(np.float32),
        "entropy": np.full((n,), 0.3, np.float32),
    }


def test_plateau_detection_confirms_after_window_flat_windows():
    mon = Q.QualityMonitor(window=2, tol=0.01)
    topk = [[0, 1, 2]]
    for epoch, e in enumerate([10.0, 10.0, 10.0, 10.0]):
        rec = mon.update(epoch, _host_summary([e], topk), [0])
    # windows 1..3 are flat comparisons; confirmed at the 2nd flat one
    assert mon.plateaued == {0: 2}
    assert rec["plateaued"] == [2]
    assert mon.snapshot()["converged_at_epoch"] == 2


def test_plateau_resets_on_energy_movement():
    mon = Q.QualityMonitor(window=2, tol=0.01)
    topk = [[0, 1, 2]]
    for epoch, e in enumerate([10.0, 10.0, 20.0, 20.0, 20.0]):
        mon.update(epoch, _host_summary([e], topk), [0])
    # the jump at window 2 reset the flat streak; confirmed at epoch 4
    assert mon.plateaued == {0: 4}


def test_jaccard_tracks_topk_set_changes():
    mon = Q.QualityMonitor()
    r1 = mon.update(0, _host_summary([1.0], [[0, 1, 2]]), [0])
    r2 = mon.update(1, _host_summary([1.0], [[0, 1, 2]]), [0])
    r3 = mon.update(2, _host_summary([1.0], [[0, 1, 9]]), [0])
    assert r1["jaccard"] == [None]
    assert r2["jaccard"] == [1.0]
    assert r3["jaccard"] == [pytest.approx(0.5)]
    assert r2["topk_hash"] == r1["topk_hash"]
    assert r3["topk_hash"] != r2["topk_hash"]


def test_monitor_keys_by_original_point_id_and_skips_filler():
    mon = Q.QualityMonitor(window=1, tol=0.5)
    # execution rows [filler, point 5, point 2] — filler (-1) never appears
    rec = mon.update(0, _host_summary([1.0, 2.0, 3.0],
                                      [[0, 1], [2, 3], [4, 5]]),
                     [-1, 5, 2])
    assert rec["lanes"] == [5, 2]
    mon.update(1, _host_summary([1.0, 2.0, 3.0],
                                [[0, 1], [2, 3], [4, 5]]), [-1, 5, 2])
    snap = mon.snapshot()
    assert set(snap["plateaued_at_epoch"]) == {"2", "5"}
    assert snap["plateaued_at_epoch"]["5"] == 1


def test_graph_scores_recovers_known_graph():
    truth = [np.asarray([[0.0, 1.0], [0.0, 0.0]])]
    perfect = np.asarray([[[0.1, 5.0], [0.05, 0.2]]])
    auc, ap = Q.graph_scores(truth, perfect)
    assert auc == 1.0 and ap == 1.0
    # degenerate all-positive truth -> the tracker's 0.5 convention
    auc2, _ = Q.graph_scores([np.ones((2, 2))], perfect)
    assert auc2 == 0.5


def test_summarize_host_matches_field_contract():
    mats = [np.arange(12, dtype=np.float32).reshape(2, 2, 3)]
    s = Q.summarize_host(mats, k=3)
    assert s["gc"].shape == (1, 1, 2, 2)
    np.testing.assert_allclose(s["gc"][0, 0], mats[0].sum(axis=2))
    assert s["entropy"] is None
    assert s["topk_idx"].shape == (1, 3)


# ---------------------------------------------------------------------------
# golden parity: live device summary vs the offline eval readout
# ---------------------------------------------------------------------------

def test_golden_parity_live_summary_vs_offline_readout(quality_run):
    """The in-training device-side graph summary, evaluated on the fitted
    params, must match the offline eval/gc_estimates readout: per-factor
    column norms within 1e-6 and IDENTICAL top-k edge sets."""
    model = quality_run["model"]
    res = quality_run["result"]
    K = model.config.num_factors
    fn = jax.jit(Q.make_summary_fn(model, k=6))
    first = next(iter(quality_run["ds"].batches(16)))
    Xw = np.asarray(first[0])[:8, : model.config.max_lag, :]
    for lane in range(2):
        params = jax.tree.map(lambda l, _g=lane: l[_g], res.best_params)
        live = {k: np.asarray(v)
                for k, v in fn(params, Xw).items()}
        offline = GE.get_model_gc_summary_matrices(model, params,
                                                   "REDCLIFF", K)
        # per-factor lag-summed matrices agree
        np.testing.assert_allclose(live["gc"], np.stack(offline),
                                   atol=1e-6)
        # column norms within 1e-6
        np.testing.assert_allclose(
            live["col_norms"],
            np.stack([np.linalg.norm(m, axis=0) for m in offline]),
            atol=1e-6)
        # identical top-k edge SETS on the combined graph
        combined = np.sum(offline, axis=0)
        assert (set(int(i) for i in live["topk_idx"])
                == set(int(i) for i in Q.topk_indices_np(combined, 6)))


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------

def test_grid_quality_events_schema_valid_with_gt(quality_run):
    recs = read_jsonl(quality_run["run_dir"])
    assert not schema.validate_records(recs)
    qs = [r for r in recs if r["event"] == "quality"]
    assert len(qs) == 4  # check_every=1, 4 epochs
    last = qs[-1]
    assert last["lanes"] == [0, 1]
    assert all(0.0 <= a <= 1.0 for a in last["auroc"])
    assert all(0.0 <= a <= 1.0 for a in last["aupr"])
    assert len(last["topk_hash"]) == 2
    snap = quality_run["runner"].dispatch_stats["quality"]
    assert set(snap["plateaued_at_epoch"]) == {"0", "1"}
    assert snap["windows"] == 4
    assert snap["mean_auroc"] is not None
    # the snapshot is strict-JSON-able (rides checkpoints + fleet results)
    json.dumps(snap, allow_nan=False)


def test_grid_bit_identity_and_zero_cost_off(monkeypatch, tmp_path):
    """REDCLIFF_QUALITY=1 vs =0: identical decision streams and params;
    off = no quality events, no snapshot, no summary work."""
    model = _model()
    ds = _data(model)
    spec_pts = [{"gen_lr": 1e-3}, {"gen_lr": 5e-3}]
    tc = RedcliffTrainConfig(max_iter=3, batch_size=16, check_every=1)

    def run(flag, sub):
        monkeypatch.setenv(Q.ENV_ENABLE, flag)
        runner = RedcliffGridRunner(model, tc, GridSpec(points=spec_pts))
        d = str(tmp_path / sub)
        res = runner.fit(jax.random.PRNGKey(0), ds, ds, log_dir=d,
                         true_gc=_true_gc(model))
        return runner, res, read_jsonl(d)

    r_on, res_on, recs_on = run("1", "on")
    r_off, res_off, recs_off = run("0", "off")
    # decision streams and trained params are bitwise identical
    np.testing.assert_array_equal(res_on.val_history, res_off.val_history)
    np.testing.assert_array_equal(res_on.best_criteria,
                                  res_off.best_criteria)
    for a, b in zip(jax.tree.leaves(res_on.best_params),
                    jax.tree.leaves(res_off.best_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # on: events + snapshot; off: neither
    assert any(r["event"] == "quality" for r in recs_on)
    assert not any(r["event"] == "quality" for r in recs_off)
    assert r_on.dispatch_stats["quality"] is not None
    assert r_off.dispatch_stats["quality"] is None


def test_redcliff_trainer_quality_events(tmp_path):
    model = _model()
    ds = _data(model)
    tc = RedcliffTrainConfig(max_iter=3, batch_size=16, check_every=1)
    trainer = RedcliffTrainer(model, tc)
    d = str(tmp_path / "run")
    trainer.fit(model.init(jax.random.PRNGKey(0)), ds, ds,
                true_GC=_true_gc(model), save_dir=d)
    recs = read_jsonl(d)
    assert not schema.validate_records(recs)
    qs = [r for r in recs if r["event"] == "quality"]
    assert qs and qs[-1]["lanes"] == [0]
    assert qs[-1]["auroc"] is not None
    fe = [r for r in recs if r["event"] == "fit_end"][-1]
    assert fe["quality"]["windows"] == len(qs)


def test_generic_trainer_quality_host_path(tmp_path):
    from redcliff_tpu.models.cmlp_fm import CMLPFM, CMLPFMConfig
    from redcliff_tpu.train.trainer import TrainConfig, Trainer

    rng = np.random.default_rng(0)
    model = CMLPFM(CMLPFMConfig(num_chans=4, gen_lag=2, gen_hidden=(8,),
                                input_length=8))
    ds = ArrayDataset(rng.normal(size=(32, 40, 4)).astype(np.float32))
    trainer = Trainer(model, TrainConfig(max_iter=3, check_every=1,
                                         batch_size=16))
    d = str(tmp_path / "run")
    truth = [(np.abs(rng.normal(size=(4, 4)))
              * (rng.random((4, 4)) > 0.5))]
    trainer.fit(model.init(jax.random.PRNGKey(1)), ds, ds, true_GC=truth,
                save_dir=d)
    recs = read_jsonl(d)
    assert not schema.validate_records(recs)
    qs = [r for r in recs if r["event"] == "quality"]
    assert qs and qs[-1]["entropy"] == [None]  # no factor scores here
    assert qs[-1]["auroc"] is not None
    assert qs[-1]["mode"] == "host_readout"


# ---------------------------------------------------------------------------
# consumers: report / watch / regress / fleet
# ---------------------------------------------------------------------------

def test_report_renders_quality_section(quality_run):
    from redcliff_tpu.obs.report import build_report, render_text

    rep = build_report(quality_run["run_dir"])
    fits = rep["quality"]["fits"]
    assert len(fits) == 1
    q = fits[0]
    assert q["windows"] == 4
    assert q["lanes"] == 2
    assert q["final_auroc"] is not None
    assert q["final_stability"] is not None
    text = render_text(rep)
    assert "model quality" in text
    json.dumps(rep, allow_nan=False)


def test_watch_quality_headline(quality_run):
    from redcliff_tpu.obs.watch import build_snapshot, render_text

    snap = build_snapshot(quality_run["run_dir"])
    assert not schema.validate_record(snap)
    q = snap["quality"]
    assert q is not None and q["lanes"] == 2
    assert q["auroc"] is not None
    assert "quality:" in render_text(snap)


def _pre_quality_run_dir(tmp_path):
    """A PR-12-era run dir: metrics without quality events and a grid
    checkpoint whose dispatch_stats has NO 'quality' key."""
    from redcliff_tpu.obs.logging import MetricLogger
    from redcliff_tpu.runtime import checkpoint as durable_ckpt

    d = str(tmp_path / "old_run")
    old_stats = {"mode": "epoch", "epochs": 3, "train_dispatches": 3,
                 "val_dispatches": 3, "ckpt_stall_ms": 0.0,
                 "grid_width": 2, "lanes_live": 2,
                 "epoch_ms_by_width": {"2": 30.0},
                 "epochs_by_width": {"2": 3}}
    with MetricLogger(d) as log:
        log.log("fit_start", model="RedcliffGridRunner", grid_size=2,
                grid_width=2, shape={"num_chans": 4}, max_iter=3)
        for e in range(3):
            log.log("epoch", epoch=e, lanes_live=2, grid_width=2,
                    epoch_ms=10.0)
        log.log("fit_end", dispatch_stats=old_stats)
    durable_ckpt.write_checkpoint(
        os.path.join(d, "grid_checkpoint.pkl"),
        {"dispatch_stats": dict(old_stats), "meta": {"batch_size": 16}})
    return d


def test_pre_quality_run_dir_never_keyerrors(tmp_path):
    """Satellite fix: runs from pre-quality checkpoints (no 'quality' key
    anywhere) render in report AND watch with the section omitted."""
    from redcliff_tpu.obs.report import build_report, render_text
    from redcliff_tpu.obs.watch import build_snapshot
    from redcliff_tpu.obs.watch import render_text as watch_text

    d = _pre_quality_run_dir(tmp_path)
    rep = build_report(d)
    assert rep["quality"]["fits"] == []
    assert rep["quality"]["requests"] == {}
    assert "model quality" not in render_text(rep)
    snap = build_snapshot(d)
    assert snap["quality"] is None
    assert "quality:" not in watch_text(snap)
    assert not schema.validate_record(snap)


def test_regress_flags_injected_auroc_degradation():
    from redcliff_tpu.obs.regress import run_sentinel

    def payload(auroc, stability=0.95, overhead=0.1):
        return {"metric": "windows_per_sec_per_chip", "value": 100.0,
                "platform": "cpu", "grid_points": 16,
                "quality": {"final_auroc": auroc,
                            "edge_stability": stability,
                            "overhead_pct": overhead}}

    priors = [{"round": i, "path": f"r{i}", "payload": payload(0.72)}
              for i in (1, 2)]
    # healthy current: quiet on the quality families
    cur = payload(0.71)
    block = run_sentinel(cur, trajectory=priors
                         + [{"round": 3, "path": "r3", "payload": cur}])
    assert not [r for r in block["regressions"]
                if r["metric"].startswith("quality.")]
    # injected degradation: flags via the absolute floor (contract_min)
    bad = payload(0.30)
    block = run_sentinel(bad, trajectory=priors
                         + [{"round": 3, "path": "r3", "payload": bad}])
    hits = [r for r in block["regressions"]
            if r["metric"] == "quality.synthetic_auroc"]
    assert hits and hits[0].get("contract") is True
    # an overhead contract breach flags too
    slow = payload(0.72, overhead=3.5)
    block = run_sentinel(slow, trajectory=priors
                         + [{"round": 3, "path": "r3", "payload": slow}])
    assert [r for r in block["regressions"]
            if r["metric"] == "quality.overhead_pct"
            and r.get("contract")]
    # floor flags even with NO quality-bearing priors (fresh trajectory)
    block = run_sentinel(payload(0.30), trajectory=[])
    assert [r for r in block["regressions"]
            if r["metric"] == "quality.synthetic_auroc"]


def test_regress_real_trajectory_stays_quiet_on_quality_families():
    """The committed BENCH_r*.json rounds predate the quality probe: the
    scientific families must be skipped there, never noise."""
    from redcliff_tpu.obs.regress import load_trajectory, run_sentinel

    traj = load_trajectory()
    usable = [r for r in traj if r["payload"] is not None]
    if not usable:
        pytest.skip("no usable BENCH rounds in this checkout")
    block = run_sentinel(usable[-1]["payload"], trajectory=traj)
    assert not [r for r in block["regressions"]
                if r["metric"].startswith("quality.")]


def test_fleet_results_carry_per_request_quality_block(tmp_path):
    """run_batch stamps the final per-request quality slice into
    results/<id>.json, keyed by each request's own point range."""
    from redcliff_tpu.fleet.__main__ import TINY_SPEC
    from redcliff_tpu.fleet.run_batch import run_batch_file

    run_dir = str(tmp_path / "work")
    spec = json.loads(json.dumps(TINY_SPEC))
    batch = {
        "batch_id": "b-quality", "run_dir": run_dir,
        "checkpoint_every": 1,
        "requests": [
            {"request_id": "req-a", "tenant": "ta", "spec": spec,
             "points": [{"gen_lr": 1e-3}]},
            {"request_id": "req-b", "tenant": "tb", "spec": spec,
             "points": [{"gen_lr": 3e-3}, {"gen_lr": 5e-3}]},
        ],
    }
    bf = tmp_path / "batch.json"
    bf.write_text(json.dumps(batch))
    run_batch_file(str(bf))
    ra = json.load(open(os.path.join(run_dir, "results", "req-a.json")))
    rb = json.load(open(os.path.join(run_dir, "results", "req-b.json")))
    assert ra["quality"] is not None
    assert len(ra["quality"]["plateaued_at_epoch"]) == 1
    assert len(rb["quality"]["plateaued_at_epoch"]) == 2
    assert len(rb["quality"]["topk_hash"]) == 2
    # no ground truth on the fleet synthetic spec -> explicit None scores
    assert ra["quality"]["auroc"] is None

    # obs report on the batch run dir renders the per-request lines
    from redcliff_tpu.obs.report import build_report, render_text

    rep = build_report(run_dir)
    assert set(rep["quality"]["requests"]) == {"req-a", "req-b"}
    text = render_text(rep)
    assert "request req-a" in text and "quality" in text


def test_report_renders_na_for_requests_without_quality(tmp_path):
    """Requests whose results block has no quality events show n/a."""
    from redcliff_tpu.obs.logging import MetricLogger
    from redcliff_tpu.obs.report import build_report, render_text

    d = str(tmp_path / "batch")
    with MetricLogger(d) as log:
        log.log("fleet", kind="manifest", batch_id="b0",
                requests=[{"request_id": "req-x", "tenant": "t0",
                           "start": 0, "stop": 1}], tenants=["t0"],
                n_points=1)
        log.log("fit_start", model="RedcliffGridRunner", grid_size=1,
                shape={"num_chans": 4})
        log.log("fit_end")
    os.makedirs(os.path.join(d, "results"))
    with open(os.path.join(d, "results", "req-x.json"), "w") as f:
        json.dump({"request_id": "req-x", "quality": None}, f)
    rep = build_report(d)
    assert rep["quality"]["requests"]["req-x"] is None
    assert "request req-x: quality n/a" in render_text(rep)
