"""Smoke tests for the plotting helpers: every figure writes a nonempty PNG."""
import os

import numpy as np

from redcliff_tpu.utils.plotting import (
    make_scatter_and_std_err_of_mean_plot_overlay,
    plot_all_signal_channels,
    plot_cross_experiment_summary_grid,
    plot_gc_est_comparison,
    plot_gc_est_comparisons_by_factor,
    plot_heatmap,
    plot_metric_histories,
    plot_reconstruction_comparison,
    plot_state_score_traces,
    plot_x_wavelet_comparison,
)


def _written(path):
    return os.path.isfile(path) and os.path.getsize(path) > 0


def test_heatmap_and_gc_comparisons(tmp_path):
    rng = np.random.default_rng(0)
    A = rng.uniform(size=(5, 5))
    p1 = str(tmp_path / "hm.png")
    plot_heatmap(A, p1, title="t")
    assert _written(p1)

    true_gc = rng.uniform(size=(5, 5, 2))
    est_gc = rng.uniform(size=(5, 5, 2))
    p2 = str(tmp_path / "cmp.png")
    plot_gc_est_comparison(true_gc, est_gc, p2, include_lags=True)
    assert _written(p2)
    p3 = str(tmp_path / "cmp_nolag.png")
    plot_gc_est_comparison(true_gc, est_gc, p3, include_lags=False)
    assert _written(p3)

    p4 = str(tmp_path / "byfac.png")
    plot_gc_est_comparisons_by_factor([true_gc, true_gc], [est_gc, est_gc],
                                      p4)
    assert _written(p4)
    # curation-time usage: truth only, no estimates
    p5 = str(tmp_path / "truthonly.png")
    plot_gc_est_comparisons_by_factor([true_gc], None, p5, include_lags=True)
    assert _written(p5)


def test_scatter_sem_and_histories(tmp_path):
    results = {"algA": [0.8, 0.9, 0.85], "algB": [0.6, 0.7, None],
               "empty": []}
    p = str(tmp_path / "scatter.png")
    make_scatter_and_std_err_of_mean_plot_overlay(
        results, p, "title", "alg", "f1", alpha=0.5)
    assert _written(p)

    p2 = str(tmp_path / "hist.png")
    plot_metric_histories({"loss": [3.0, 2.0, 1.5], "val": [3.1, 2.4, 2.0]},
                          p2)
    assert _written(p2)


def test_signal_wavelet_state_recon(tmp_path):
    rng = np.random.default_rng(1)
    X = rng.normal(size=(50, 3))
    p1 = str(tmp_path / "sig.png")
    plot_all_signal_channels(X, p1, fs=100)
    assert _written(p1)

    p2 = str(tmp_path / "wav.png")
    plot_x_wavelet_comparison(X, rng.normal(size=(50, 3, 2)), p2)
    assert _written(p2)

    p3 = str(tmp_path / "scores.png")
    plot_state_score_traces(rng.uniform(size=(3, 40)), p3,
                            labels=["HC", "OF", "TS"])
    assert _written(p3)

    p4 = str(tmp_path / "recon.png")
    plot_reconstruction_comparison(X, X + 0.1, p4)
    assert _written(p4)


def test_extended_helper_family(tmp_path):
    """The long-tail helpers (ref plotting.py:14-256, 458-646) each write a
    nonempty figure."""
    from redcliff_tpu.utils import plotting as P

    rng = np.random.default_rng(3)
    p = lambda name: str(tmp_path / name)

    P.plot_cross_experiment_summary(
        p("xexp.png"), means=rng.uniform(size=6), sems=rng.uniform(size=6) * .1,
        alg_names=["A", "B", "C"], dataset_names=["numN10_numE20", "numN5_numE9"],
        title="t", xlabel="F1", x_domain_lim=(0, 1))
    assert _written(p("xexp.png"))

    P.plot_confidence_interval_summary(
        p("ci.png"), [1, 2, 3], [0.5, 1.5, 2.5], [1.5, 2.5, 3.5],
        center_label="median", title="t", criteria_name="loss",
        domain_name="epoch")
    assert _written(p("ci.png"))

    P.make_bar_and_whisker_plot_overlay(
        {"a": [1.0, 2.0, 3.0], "b": [2.0, 2.5]}, p("bw.png"), title="t")
    assert _written(p("bw.png"))

    P.plot_scattered_results([1, 2, 3], [4, 5, 6], p("sc.png"), x_eps=0.1,
                             y_eps=0.1)
    assert _written(p("sc.png"))

    P.plot_training_loss([3.0, 2.0, 1.0], p("tl.png"))
    assert _written(p("tl.png"))

    P.plot_x_simulation_comparison(rng.normal(size=(2, 30, 3)),
                                   rng.normal(size=(2, 30, 3)), p("sim.png"))
    assert _written(p("sim.png"))

    P.plot_scatter([1, 2], [3, 4], "t", "x", "y", p("s2.png"))
    assert _written(p("s2.png"))

    P.plot_curve([1, 2, 3], "t", "x", "y", p("c.png"), domain_start=5)
    assert _written(p("c.png"))

    P.plot_curve_comparison([[1, 2, 3], [2, 3, 4]], "t", "x", "y", p("cc.png"))
    assert _written(p("cc.png"))

    P.plot_curve_comparison_from_dict({"a": [1, 2], "b": [2, 3]}, "t", "x",
                                      "y", p("ccd.png"))
    assert _written(p("ccd.png"))

    P.plot_system_state_score_comparison(p("ssc.png"),
                                         rng.uniform(size=(3, 30)))
    assert _written(p("ssc.png"))

    P.plot_avg_system_state_score_comparison(
        p("avg.png"), [rng.uniform(size=(2, 20)) for _ in range(3)],
        [rng.uniform(size=(2, 20)) for _ in range(3)])
    assert _written(p("avg.png"))

    P.plot_estimated_vs_true_curve(p("evt.png"), [1, 2, 3], [1.1, 2.1, 2.9])
    assert _written(p("evt.png"))

    # zoom companions
    P.plot_all_signal_channels(rng.normal(size=(60, 2)), p("z.png"), zoom=10)
    assert _written(p("z.png"))
    assert _written(p("z_ZOOMED.png"))
    assert _written(p("z_partiallyZOOMED.png"))


def test_scatter_sem_diff_plots(tmp_path):
    """make_diff_plots writes per-group IMPROVEMENTS subfolders with pairwise
    difference figures (ref plotting.py:177-198)."""
    results = {"algA": [0.8, 0.9], "algB": [0.6, 0.7]}
    p = str(tmp_path / "main.png")
    make_scatter_and_std_err_of_mean_plot_overlay(
        results, p, "t", "alg", "f1", make_diff_plots=True)
    assert _written(p)
    assert _written(str(tmp_path / "algA_IMPROVEMENTS" / "main.png"))
    assert _written(str(tmp_path / "algB_IMPROVEMENTS" / "main.png"))


def test_cross_experiment_grid_and_aliases(tmp_path):
    summary = {"dsetA": {"algA": 0.9, "algB": 0.7},
               "dsetB": {"algA": 0.85}}
    p = str(tmp_path / "grid.png")
    plot_cross_experiment_summary_grid(summary, p, "optimal_f1")
    assert _written(p)

    # reference-spelling aliases resolve to the same callables
    from redcliff_tpu.utils import plotting as P

    assert P.plot_gc_est_comparisson is P.plot_gc_est_comparison
    assert P.make_scatter_and_stdErrOfMean_plot_overlay_vis is \
        P.make_scatter_and_std_err_of_mean_plot_overlay


def test_cross_alg_plot_integration(tmp_path):
    """run_cross_algorithm_comparison(plot=True) emits the per-paradigm
    scatter figures now that utils.plotting exists."""
    import pickle

    from redcliff_tpu.eval.cross_alg import run_cross_algorithm_comparison
    from redcliff_tpu.models.dynotears import DynotearsConfig

    rng = np.random.default_rng(2)
    true_g = (rng.uniform(size=(4, 4, 1)) > 0.5).astype(float)
    alg_root = tmp_path / "DYNOTEARS_Vanilla_models"
    run = alg_root / "dset_fold0_run"
    os.makedirs(run)
    with open(run / "final_best_model.bin", "wb") as f:
        pickle.dump({"model_class": "DynotearsVanillaModel",
                     "config": DynotearsConfig(lag_size=1),
                     "a_est": true_g[:, :, 0] + 0.01}, f)
    out = tmp_path / "out"
    run_cross_algorithm_comparison(
        [str(alg_root)], {"dset": {0: [true_g]}}, str(out), 1, plot=True)
    pngs = [x for x in os.listdir(out / "cv_dset") if x.endswith(".png")]
    assert pngs
