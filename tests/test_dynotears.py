"""DYNOTEARS: solver recovery on a known SVAR, warm-start wrapper behavior,
vanilla one-shot averaging, and the free objective/constraint functions."""
import numpy as np
import pytest

from redcliff_tpu.data.datasets import ArrayDataset
from redcliff_tpu.models.dynotears import (
    DynotearsConfig, DynotearsModel, DynotearsState, DynotearsVanillaModel,
    dynotears_h_constraint, dynotears_objective, dynotears_solve, reshape_wa,
)


def make_svar(n=400, d=5, p=1, seed=0, with_w=False, with_a=True):
    """X(I − W) = Xlags·A + E with a known strict-upper-triangular W.

    Intra (W) and lagged (A) structure are kept separable per test — when both
    are present the same fit can be explained through A alone (X's intra
    dependencies are deterministic functions of Xlags), so recovery of W is
    only identifiable from intra-only data."""
    rng = np.random.default_rng(seed)
    W = np.zeros((d, d))
    if with_w:
        W[0, 2] = 0.8
        W[1, 3] = -0.7
    A = np.zeros((p * d, d))
    if with_a:
        A[0, 1] = 0.9
        A[4, 0] = 0.8
    Xlags = rng.normal(size=(n, p * d))
    # unit-scale innovations: the ½/n‖·‖² gain from a true edge must beat the
    # λ·|w| cost for the edge to enter the model at all
    E = rng.normal(size=(n, d))
    X = (Xlags @ A + E) @ np.linalg.inv(np.eye(d) - W)
    return X, Xlags, W, A


def auc(scores, truth):
    from sklearn.metrics import roc_auc_score

    t = (np.abs(truth) > 0).astype(int).ravel()
    return roc_auc_score(t, np.abs(scores).ravel())


def test_solver_recovers_lagged_structure():
    X, Xlags, _, A = make_svar(with_a=True, with_w=False)
    res = dynotears_solve(X, Xlags, lambda_w=0.05, lambda_a=0.05)
    assert res.d_vars == 5 and res.p_orders == 1
    assert dynotears_h_constraint(res.state.wa_est, 5, 1) < 1e-6
    assert auc(res.a_mat, A) > 0.95
    assert abs(res.a_mat[0, 1]) > 0.5 and abs(res.a_mat[4, 0]) > 0.5


def test_solver_recovers_intra_structure():
    X, Xlags, W, _ = make_svar(with_a=False, with_w=True)
    res = dynotears_solve(X, Xlags, lambda_w=0.05, lambda_a=0.05)
    assert dynotears_h_constraint(res.state.wa_est, 5, 1) < 1e-6
    assert auc(res.w_mat, W) > 0.95
    assert abs(res.w_mat[0, 2]) > 0.3 and abs(res.w_mat[1, 3]) > 0.3


def test_solver_warm_start_reuses_state():
    X, Xlags, _, _ = make_svar(n=150)
    cold = dynotears_solve(X, Xlags)
    warm = dynotears_solve(X, Xlags, state=cold.state)
    # warm start from the converged point stays converged
    assert warm.state.h_value <= max(cold.state.h_value, 1e-8)
    assert auc(warm.a_mat, cold.a_mat > 0.1) > 0.9


def test_objective_and_constraint_free_functions():
    X, Xlags, _, _ = make_svar(n=50)
    d, p = 5, 1
    rng = np.random.default_rng(1)
    wa = np.abs(rng.normal(size=2 * (p + 1) * d * d)) * 0.1
    w_mat, a_mat = reshape_wa(wa, d, p)
    resid = X @ (np.eye(d) - w_mat) - Xlags @ a_mat
    h = dynotears_h_constraint(wa, d, p)
    expect = (0.5 / 50 * np.sum(resid**2) + 0.5 * 2.0 * h * h + 0.3 * h
              + 0.1 * wa[: 2 * d * d].sum() + 0.2 * wa[2 * d * d :].sum())
    got = dynotears_objective(X, Xlags, wa, rho=2.0, alpha=0.3, d_vars=d,
                              p_orders=p, lambda_a=0.2, lambda_w=0.1, n=50)
    np.testing.assert_allclose(got, expect, rtol=1e-12)
    assert h > 0  # random dense W is cyclic


def test_tabu_constraints_pin_entries_to_zero():
    X, Xlags, _, _ = make_svar(n=200)
    res = dynotears_solve(X, Xlags, tabu_edges=[(1, 0, 1)],
                          tabu_parent_nodes=[2])
    assert res.a_mat[0, 1] == 0.0          # banned lagged edge
    assert np.all(res.w_mat[2, :] == 0.0)  # banned parent row (intra)
    assert np.all(res.a_mat[2, :] == 0.0)  # banned parent row (lag 1)
    assert np.all(np.diag(res.w_mat) == 0.0)  # self-loops always banned


def test_stochastic_model_fit_and_gc(tmp_path):
    rng = np.random.default_rng(3)
    d, T, n_rec = 4, 60, 6
    A = np.zeros((d, d))
    A[0, 1] = 0.85
    A[2, 3] = 0.8
    recs = np.zeros((n_rec, T, d), dtype=np.float32)
    for r in range(n_rec):
        x = np.zeros((T, d))
        x[0] = rng.normal(size=d)
        for t in range(1, T):
            x[t] = x[t - 1] @ (A + 0.3 * np.eye(d)) + 0.3 * rng.normal(size=d)
        recs[r] = x
    ds = ArrayDataset(recs, None, normalize=True)
    model = DynotearsModel(DynotearsConfig(max_iter=20, reuse_rho=True,
                                           reuse_alpha=True))
    best, hist = model.fit(ds, ds, save_dir=str(tmp_path), max_data_iter=2,
                           batch_size=4)
    gc = model.gc()
    assert gc.shape == (d, d)
    assert np.isfinite(best) and len(hist) == 2
    assert (tmp_path / "final_best_model.bin").exists()
    assert (tmp_path / "training_meta_data_and_hyper_parameters.pkl").exists()


def test_vanilla_model_averages_samples():
    rng = np.random.default_rng(4)
    recs = rng.normal(size=(3, 40, 4)).astype(np.float32)
    model = DynotearsVanillaModel(DynotearsConfig(max_iter=5))
    a_est = model.fit(recs)
    assert a_est.shape == (4, 4)
    assert model.gc() is a_est
