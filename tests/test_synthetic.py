"""Tests for the synthetic sVAR generator: graph factory invariants, host-vs-device
rollout agreement, and basic statistical sanity of generated datasets."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from redcliff_tpu.data import synthetic as S


def _simple_system(D=4, L=2, seed=0):
    p = S.reference_curation_params(D)
    graphs, acts, _ = S.generate_lagged_adjacency_graphs_for_factor_model(
        num_nodes=D, num_lags=L, num_factors=2, make_factors_orthogonal=True,
        make_factors_singular_components=False, rand_seed=seed,
        off_diag_edge_strengths=p["off_diag_edge_strengths"],
        diag_receiving_node_forgetting_coeffs=p["diag_receiving_node_forgetting_coeffs"],
        diag_sending_node_forgetting_coeffs=p["diag_sending_node_forgetting_coeffs"],
    )
    return graphs, acts


def test_graph_factory_shapes_and_diagonal():
    graphs, acts, inds = S.generate_lagged_adjacency_graphs_for_factor_model(
        num_nodes=5, num_lags=2, num_factors=3, make_factors_orthogonal=True,
        make_factors_singular_components=False, rand_seed=1,
    )
    assert len(graphs) == 3 and sorted(inds) == [0, 1, 2]
    for A in graphs:
        assert A.shape == (5, 5, 2)
        # self-connections exist at every lag (identity base, possibly damped)
        for l in range(2):
            assert np.all(np.diag(A[:, :, l]) > 0)


def test_graph_factory_orthogonal_edges_disjoint():
    graphs, _, _ = S.generate_lagged_adjacency_graphs_for_factor_model(
        num_nodes=6, num_lags=2, num_factors=2, make_factors_orthogonal=True,
        make_factors_singular_components=False, rand_seed=2,
    )
    offdiag = []
    for A in graphs:
        mask = A.sum(axis=2) * (1 - np.eye(6)) > 0
        offdiag.append({(i, j) for i, j in zip(*np.where(mask))})
    assert offdiag[0].isdisjoint(offdiag[1])


def test_rollout_np_shape_and_burnin():
    graphs, acts = _simple_system()
    rng = np.random.default_rng(0)
    D = 4
    sig = S.rollout_np(graphs[0], acts[0], base_freqs=S.reference_curation_params(D)["base_freqs"],
                       noise_mu=np.zeros(D), noise_var=np.ones(D),
                       innovation_amp=0.5 * np.ones(D), recording_length=50,
                       burnin_period=10, rng=rng)
    assert sig.shape == (4, 50)
    assert np.all(np.isfinite(sig))


def test_rollout_scan_matches_np_dynamics_zero_noise():
    """With zero innovations the scan and numpy rollouts implement identical
    deterministic dynamics from the same initial state."""
    graphs, acts = _simple_system()
    D = 4
    A = graphs[0]
    M1, M2 = S._step_matrices(A, np.full(D, np.pi))
    codes = acts[0]
    x0 = np.linspace(-0.3, 0.4, D)
    # numpy trajectory
    innov = np.zeros(D)
    x1 = S.nvar_step_np(x0, x0, M1, M2, codes, innov, num_lags=1)
    traj = [x0, x1]
    for _ in range(20):
        traj.append(S.nvar_step_np(traj[-1], traj[-2], M1, M2, codes, innov))
    traj = np.stack(traj[2:], axis=0)  # (20, D)

    # scan trajectory with identical carry and zero noise
    def step(carry, _):
        x_tm1, x_tm2 = carry
        c1 = S._apply_act(jnp.asarray(M1) * x_tm1[None, :], jnp.asarray(codes)[:, :, 0]).sum(axis=1)
        c2 = S._apply_act(jnp.asarray(M2) * x_tm2[None, :], jnp.asarray(codes)[:, :, 1]).sum(axis=1)
        x_t = c1 + c2
        return (x_t, x_tm1), x_t

    _, xs = jax.lax.scan(step, (jnp.asarray(x1), jnp.asarray(x0)), None, length=20)
    np.testing.assert_allclose(np.asarray(xs), traj, rtol=1e-5, atol=1e-6)


def test_generate_synthetic_dataset_shapes_and_labels():
    graphs, acts = _simple_system()
    D = 4
    X, Y = S.generate_synthetic_dataset(
        jax.random.PRNGKey(0), graphs, acts, base_freqs=S.reference_curation_params(D)["base_freqs"],
        noise_mu=np.zeros(D), noise_var=np.ones(D), innovation_amp=0.5 * np.ones(D),
        num_samples=8, recording_length=30, burnin_period=5,
        num_labeled_sys_states=2, label_type="Oracle",
    )
    assert X.shape == (8, 30, 4)
    assert Y.shape == (8, 2, 30)
    assert np.all(np.isfinite(X))
    # oracle labels are activation ramps in [0, 1]
    assert Y.min() >= 0.0 and Y.max() <= 1.0 + 1e-6


def test_generate_synthetic_dataset_onehot():
    graphs, acts = _simple_system()
    D = 4
    X, Y = S.generate_synthetic_dataset(
        jax.random.PRNGKey(1), graphs, acts, base_freqs=S.reference_curation_params(D)["base_freqs"],
        noise_mu=np.zeros(D), noise_var=np.ones(D), innovation_amp=0.5 * np.ones(D),
        num_samples=4, recording_length=20, burnin_period=5,
        num_labeled_sys_states=2, label_type="OneHot",
    )
    np.testing.assert_allclose(Y.sum(axis=1), 1.0)
    assert set(np.unique(Y)) <= {0.0, 1.0}


def test_unsupervised_state_pooled_into_extra_label():
    graphs, acts, _ = S.generate_lagged_adjacency_graphs_for_factor_model(
        num_nodes=4, num_lags=2, num_factors=3, make_factors_orthogonal=False,
        make_factors_singular_components=False, rand_seed=3,
    )
    D = 4
    X, Y = S.generate_synthetic_dataset(
        jax.random.PRNGKey(2), graphs, acts, base_freqs=S.reference_curation_params(D)["base_freqs"],
        noise_mu=np.zeros(D), noise_var=np.ones(D), innovation_amp=0.5 * np.ones(D),
        num_samples=2, recording_length=10, burnin_period=2,
        num_labeled_sys_states=2, label_type="Oracle",
    )
    # 2 supervised + 1 pooled 'UNKNOWN' row (ref data_utils.py:141-175)
    assert Y.shape == (2, 3, 10)


def test_np_and_device_datasets_statistically_close():
    graphs, acts = _simple_system()
    D = 4
    common = dict(base_freqs=S.reference_curation_params(D)["base_freqs"], noise_mu=np.zeros(D),
                  noise_var=np.ones(D), innovation_amp=0.5 * np.ones(D),
                  num_samples=64, recording_length=40, burnin_period=5,
                  num_labeled_sys_states=2, label_type="Oracle")
    Xd, _ = S.generate_synthetic_dataset(jax.random.PRNGKey(3), graphs, acts, **common)
    Xn, _ = S.generate_synthetic_data_np(np.random.default_rng(3), graphs, acts, **common)
    # distributional agreement (same dynamics, different RNG streams)
    assert abs(Xd.mean() - Xn.mean()) < 0.15
    assert abs(Xd.std() - Xn.std()) / max(Xn.std(), 1e-6) < 0.5
