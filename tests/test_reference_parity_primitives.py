"""L1-primitive A/B against the actual reference implementations.

Imports the reference's general_utils modules from /root/reference and
compares our metric library, GC plumbing, directed-spectrum estimator
(both directly and through the feature pipeline), and signal-processing
helpers on identical random inputs.  (The model-level A/B lives in
test_reference_parity.py, whose ref() fixture carries the same
reference-import scaffolding plus the torcheeg stub that module needs.)
"""
import types

import numpy as np
import pytest

torch = pytest.importorskip("torch")


@pytest.fixture(scope="module")
def refgu():
    from conftest import add_reference_to_path

    add_reference_to_path()
    from general_utils import directed_spectrum as rds
    from general_utils import metrics as rm
    from general_utils import misc as rmisc
    from general_utils import time_series as rts

    return types.SimpleNamespace(metrics=rm, misc=rmisc, ts=rts, ds=rds)


@pytest.fixture
def rng():
    return np.random.default_rng(0)


# --------------------------------------------------------------------------
# metrics library
# --------------------------------------------------------------------------
def test_optimal_f1_and_fixed_f1_match_reference(refgu, rng):
    from redcliff_tpu.utils import metrics as M

    labels = (rng.uniform(size=60) > 0.6).astype(int)
    scores = rng.uniform(size=60)
    r_thresh, r_f1 = refgu.metrics.compute_optimal_f1(list(labels), scores)
    j_thresh, j_f1 = M.compute_optimal_f1(labels, scores)
    assert j_f1 == pytest.approx(r_f1)
    assert j_thresh == pytest.approx(r_thresh)
    for cutoff in (0.3, 0.5, 0.8):
        assert M.compute_f1(labels, scores, cutoff) == pytest.approx(
            refgu.metrics.compute_f1(list(labels), scores, cutoff))


def test_confusion_rate_family_matches_reference(refgu, rng):
    from redcliff_tpu.utils import metrics as M

    labels = (rng.uniform(size=40) > 0.5).astype(int)
    preds = rng.uniform(size=40)
    cutoff = 0.45
    r = refgu.metrics.compute_true_PosNeg_and_false_PosNeg_rates(
        labels, preds, pred_cutoff=cutoff)
    j = M.confusion_counts(labels, preds, cutoff)
    np.testing.assert_array_equal(j, r)  # (tp, tn, fp, fn) counts
    assert M.compute_sensitivity(labels, preds, cutoff) == pytest.approx(
        refgu.metrics.compute_sensitivity(labels, preds, pred_cutoff=cutoff))
    assert M.compute_specificity(labels, preds, cutoff) == pytest.approx(
        refgu.metrics.compute_specificity(labels, preds, pred_cutoff=cutoff))


def test_deltacon_family_matches_reference(refgu, rng):
    from redcliff_tpu.utils import metrics as M

    A = rng.uniform(size=(6, 6))
    B = (rng.uniform(size=(6, 6)) > 0.5).astype(float)
    eps = 0.1
    assert M.deltacon0(A, B, eps) == pytest.approx(
        float(refgu.metrics.deltacon0(A, B, eps)))
    assert M.deltacon0(A, B, eps, make_graphs_undirected=True) == pytest.approx(
        float(refgu.metrics.deltacon0(A, B, eps, make_graphs_undirected=True)))
    assert M.deltacon0_with_directed_degrees(A, B, eps, 1.0, 2.0) == \
        pytest.approx(float(refgu.metrics.deltacon0_with_directed_degrees(
            A, B, eps, in_degree_coeff=1.0, out_degree_coeff=2.0)))
    assert M.deltaffinity(A, B, eps) == pytest.approx(
        float(refgu.metrics.deltaffinity(A, B, eps)))
    assert M.deltaffinity(A, B, eps, max_path_length=3) == pytest.approx(
        float(refgu.metrics.deltaffinity(A, B, eps, max_path_length=3)))
    assert M.matsusita_distance(np.abs(A), np.abs(B)) == pytest.approx(
        float(refgu.metrics.matsusita_distance(np.abs(A), np.abs(B))))


def test_path_length_mse_matches_reference(refgu, rng):
    from redcliff_tpu.utils import metrics as M

    A = (rng.uniform(size=(5, 5)) > 0.6).astype(float)
    B = (rng.uniform(size=(5, 5)) > 0.6).astype(float)
    r_total, r_per_k = refgu.metrics.path_length_mse(A, B)
    j_total, j_per_k = M.path_length_mse(A, B)
    assert j_total == pytest.approx(float(r_total))
    np.testing.assert_allclose(j_per_k, [float(x) for x in r_per_k],
                               rtol=1e-10)


def test_hungarian_and_cosine_match_reference(refgu, rng):
    from redcliff_tpu.utils import metrics as M

    ests = [rng.uniform(size=(4, 4)) for _ in range(3)]
    trues = [rng.uniform(size=(4, 4)) for _ in range(3)]
    r_rows, r_cols = refgu.metrics.solve_linear_sum_assignment_between_graph_options(
        ests, trues)
    j_rows, j_cols = M.solve_linear_sum_assignment_between_graph_options(
        ests, trues)
    np.testing.assert_array_equal(j_rows, r_rows)
    np.testing.assert_array_equal(j_cols, r_cols)
    assert M.compute_cosine_similarity(ests[0], trues[0]) == pytest.approx(
        float(refgu.metrics.compute_cosine_similarity(ests[0], trues[0])))


def test_dagness_and_components_match_reference(refgu, rng):
    from redcliff_tpu.utils import metrics as M

    A = rng.uniform(size=(5, 5))
    ref_loss = refgu.metrics.DAGNessLoss()(torch.from_numpy(A))
    assert float(M.dagness_penalty(A)) == pytest.approx(float(ref_loss),
                                                        rel=1e-6)
    B = (rng.uniform(size=(6, 6)) > 0.7).astype(float)
    assert M.get_number_of_connected_components(B) == \
        refgu.metrics.get_number_of_connected_components(B)


def test_misc_plumbing_matches_reference(refgu, rng):
    from redcliff_tpu.utils import misc as misc

    A = rng.uniform(size=(4, 4))
    np.testing.assert_allclose(misc.normalize_array(A),
                               refgu.misc.normalize_numpy_array(A))
    np.testing.assert_allclose(
        misc.mask_diag_elements(A),
        refgu.misc.mask_diag_elements_of_square_numpy_array(A))
    vals = list(rng.uniform(size=7))
    np.testing.assert_allclose(
        misc.place_on_zero_to_one_scale(vals),
        refgu.misc.place_list_elements_on_zero_to_one_scale(vals))
    G = rng.uniform(size=(3, 4, 2))
    np.testing.assert_allclose(
        misc.flatten_gc_with_lags(G),
        refgu.misc.flatten_GC_estimate_with_lags(G))
    sqG = rng.uniform(size=(4, 4 * 2))
    np.testing.assert_allclose(
        misc.unflatten_gc_with_lags(sqG),
        refgu.misc.unflatten_GC_estimate_with_lags(sqG))
    sq = rng.uniform(size=(4, 4, 2))
    np.testing.assert_allclose(
        misc.flatten_directed_spectrum_features(sq),
        refgu.misc.flatten_directed_spectrum_features(sq))
    # the reference's unflatten doubles off-diagonal entries; our
    # accumulate_shared_entries=True reproduces it exactly
    flat = misc.flatten_directed_spectrum_features(sq)
    np.testing.assert_allclose(
        misc.unflatten_directed_spectrum_features(
            flat, accumulate_shared_entries=True),
        refgu.misc.unflatten_directed_spectrum_features(flat))


# --------------------------------------------------------------------------
# signal processing + directed spectrum
# --------------------------------------------------------------------------
def test_filters_and_outliers_match_reference(refgu, rng):
    from redcliff_tpu.utils import time_series as TS

    x = rng.normal(size=1000)
    fs = 500
    r = refgu.ts.filter_signal_via_lowpass(x, fs, cutoff=40.0)
    j = TS.filter_signal_via_lowpass(x, fs, cutoff=40.0)
    np.testing.assert_allclose(j, r, rtol=1e-8, atol=1e-10)
    r = refgu.ts.filter_signal_via_bandpass(x, fs, lowcut=5.0, highcut=50.0)
    j = TS.filter_signal_via_bandpass(x, fs, lowcut=5.0, highcut=50.0)
    np.testing.assert_allclose(j, r, rtol=1e-8, atol=1e-10)
    lfps = {"roi": x.copy()}
    lfps["roi"][100] = 50.0
    r_marked = refgu.ts.mark_outliers({k: v.copy() for k, v in lfps.items()},
                                      fs)
    j_marked = TS.mark_outliers({k: v.copy() for k, v in lfps.items()}, fs)
    np.testing.assert_array_equal(np.isnan(j_marked["roi"]),
                                  np.isnan(r_marked["roi"]))


def test_high_level_signal_features_match_reference(refgu, rng):
    """CSD power features + directed spectrum — the DCSFA input features
    (ref time_series.py:121-238, directed_spectrum.py:48-145)."""
    from redcliff_tpu.utils import time_series as TS

    x = rng.normal(size=(64, 3)).astype(np.float64)
    kwargs = dict(fs=1000, min_freq=0.0, max_freq=250.0,
                  directed_spectrum=True,
                  csd_params={"detrend": "constant", "window": "hann",
                              "nperseg": 32, "noverlap": 16, "nfft": None})
    r = refgu.ts.make_high_level_signal_features(x, **kwargs)
    j = TS.make_high_level_signal_features(x, **kwargs)
    assert set(j.keys()) >= {"power", "freq", "dir_spec"}
    np.testing.assert_allclose(np.asarray(j["freq"]), np.asarray(r["freq"]),
                               rtol=1e-10)
    np.testing.assert_allclose(np.asarray(j["power"]),
                               np.asarray(r["power"]), rtol=1e-6, atol=1e-9)
    np.testing.assert_allclose(np.asarray(j["dir_spec"]),
                               np.asarray(r["dir_spec"]),
                               rtol=1e-5, atol=1e-8)


def test_directed_spectrum_matches_reference(refgu, rng):
    """Direct A/B of the Wilson-factorization directed-spectrum estimator
    (ref directed_spectrum.py:48-145), pairwise and joint."""
    from redcliff_tpu.utils import directed_spectrum as DS

    x = rng.normal(size=(2, 3, 128))  # [n_window, n_roi, time]
    csd_params = {"detrend": "constant", "window": "hann", "nperseg": 64,
                  "noverlap": 32, "nfft": None}
    for pairwise in (True, False):
        r_f, r_ds = refgu.ds.get_directed_spectrum(
            x, 500, pairwise=pairwise, csd_params=csd_params)
        j_f, j_ds = DS.get_directed_spectrum(
            x, 500, pairwise=pairwise, csd_params=csd_params)
        np.testing.assert_allclose(np.asarray(j_f), np.asarray(r_f),
                                   rtol=1e-10)
        np.testing.assert_allclose(np.asarray(j_ds), np.asarray(r_ds),
                                   rtol=1e-4, atol=1e-8)


# --------------------------------------------------------------------------
# tidybench (pure-numpy reference algorithms)
# --------------------------------------------------------------------------
@pytest.fixture(scope="module")
def reftb(refgu):
    from tidybench import lasar as rlasar
    from tidybench import qrbs as rqrbs
    from tidybench import slarac as rslarac

    return types.SimpleNamespace(slarac=rslarac, qrbs=rqrbs, lasar=rlasar)


def _var_series(rng, T=120, N=4):
    x = np.zeros((T, N))
    A = 0.4 * (rng.uniform(size=(N, N)) > 0.7)
    for t in range(1, T):
        x[t] = x[t - 1] @ A + rng.normal(scale=0.5, size=N)
    return x


def test_slarac_deterministic_core_matches_reference(reftb, rng, monkeypatch):
    """n_subsamples=0 removes the random subsampling, leaving the full-data
    VAR coefficient scores (ref slarac.py:56-57).  maxlags=1 is fully
    deterministic; for maxlags=2 both sides' random effective-lag draw
    (ref :88) is pinned to the maximum so the regression math can be A/B'd."""
    from redcliff_tpu.tidybench.slarac import slarac

    data = _var_series(rng)
    r = reftb.slarac.slarac(data.copy(), maxlags=1, n_subsamples=0)
    j = slarac(data.copy(), maxlags=1, n_subsamples=0)
    np.testing.assert_allclose(np.asarray(j), np.asarray(r),
                               rtol=1e-8, atol=1e-10)

    def ref_choice(a, size=None):
        if size is not None:  # the subsample-size draw (empty here)
            return np.asarray(a)[:0]
        return np.asarray(a)[-1]  # the effective-lag draw -> max lag

    monkeypatch.setattr(reftb.slarac.np.random, "choice", ref_choice)

    class _MaxLag:
        def integers(self, low, high, size=None):
            return high - 1

        def choice(self, a, size=None):
            return np.asarray(a)[:0]  # n_subsamples == 0

    import importlib

    jsm = importlib.import_module("redcliff_tpu.tidybench.slarac")
    monkeypatch.setattr(jsm.np.random, "default_rng",
                        lambda rng=None: _MaxLag())
    r = reftb.slarac.slarac(data.copy(), maxlags=2, n_subsamples=0)
    j = slarac(data.copy(), maxlags=2, n_subsamples=0)
    np.testing.assert_allclose(np.asarray(j), np.asarray(r),
                               rtol=1e-8, atol=1e-10)


def test_lasar_deterministic_core_matches_reference(reftb, rng):
    """n_subsamples=0: the full-data LassoCV estimate only
    (ref lasar.py:58-60) — deterministic A/B."""
    from redcliff_tpu.tidybench.lasar import lasar

    data = _var_series(rng, T=150)
    r = reftb.lasar.lasar(data.copy(), maxlags=2, n_subsamples=0)
    j = lasar(data.copy(), maxlags=2, n_subsamples=0)
    np.testing.assert_allclose(np.asarray(j), np.asarray(r),
                               rtol=1e-6, atol=1e-8)


def test_qrbs_ridge_core_matches_reference(reftb, rng, monkeypatch):
    """The ridge + lag-aggregation + quantile math, with both sides'
    bootstrap forced to the same deterministic first-k rows (the reference
    resamples through sklearn's global RNG, ours through a Generator, so
    exact A/B of the random draws is impossible by construction)."""
    import importlib

    jqm = importlib.import_module("redcliff_tpu.tidybench.qrbs")

    data = _var_series(rng, T=140)
    monkeypatch.setattr(reftb.qrbs, "resample",
                        lambda X, y, n_samples: (X[:n_samples], y[:n_samples]))

    class _FirstK:
        def integers(self, low, high, size):
            return np.arange(size)

    monkeypatch.setattr(jqm.np.random, "default_rng",
                        lambda rng=None: _FirstK())
    r = reftb.qrbs.qrbs(data.copy(), lags=2, n_resamples=3)
    j = jqm.qrbs(data.copy(), lags=2, n_resamples=3)
    np.testing.assert_allclose(np.asarray(j), np.asarray(r),
                               rtol=1e-6, atol=1e-9)


# --------------------------------------------------------------------------
# synthetic sVAR dynamics (the test oracle's generator, ref data/data_utils.py)
# --------------------------------------------------------------------------
def test_nvar_step_matches_reference(refgu, rng):
    """One step of the 2-lag sinusoid-driven nonlinear VAR
    (ref data_utils.py:47-86) with the noise variance zeroed so the
    dynamics are deterministic: sinusoidal self-connections, per-edge
    min0/max0 nonlinearities, identity edges."""
    from data import data_utils as rdu

    from redcliff_tpu.data.synthetic import (ACT_MAX0, ACT_MIN0,
                                             _step_matrices, nvar_step_np)

    D, L = 4, 2
    A = rng.uniform(-0.6, 0.6, size=(D, D, L))
    f = rng.uniform(0.05, 0.45, size=(D, 1))
    hist = [rng.normal(size=(D, 1)) for _ in range(2)]  # [t-2, t-1]

    # per-edge nonlinearity assignment: identity / min0 / max0, mirrored in
    # both encodings (the reference takes callables, ours integer codes)
    # the reference applies per-edge nonlinearities to self terms too
    # (ref data_utils.py:71-78), so the diagonal participates as well
    acts = rng.integers(0, 3, size=(D, D, L))
    fn_map = {0: None,
              1: lambda x: np.min((x, 0)),
              2: lambda x: np.max((x, 0))}
    nonlin = [[[fn_map[int(acts[i, j, l])] for l in range(L)]
               for j in range(D)] for i in range(D)]
    r = rdu.multivariate_relational_nvar_sinusoid_with_gaussian_innovations(
        hist, A, f=f, mu=np.zeros((D, 1)), var=np.zeros((D, 1)),
        innovation_amp=np.ones((D, 1)), d=D, NUM_LAGS=L,
        nonlinear_functions_by_lagged_adjacency=nonlin)

    code_map = {0: 0, 1: ACT_MIN0, 2: ACT_MAX0}
    codes = np.vectorize(code_map.get)(acts)
    M1, M2 = _step_matrices(A, f[:, 0])
    j = nvar_step_np(hist[-1][:, 0], hist[-2][:, 0], M1, M2, codes,
                     innovation=np.zeros(D))
    np.testing.assert_allclose(j, r[:, 0], rtol=1e-10, atol=1e-12)
