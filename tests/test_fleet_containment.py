"""Fleet blast-radius containment tests (redcliff_tpu/fleet, ISSUE 11).

Queue containment units (deadletter/cancel/requeue/attempt-ledger/pinned
batches), planner suspect quarantine, lease-heartbeat renewal escalation,
worker settle discipline (retry budgets, missing-result routing, poison
attribution, blind bisection) against a stubbed supervisor, the fleet
chaos-harness primitives, and the end-to-end acceptance: a 6-request
merged batch with 1 injected poison request converges to exactly 1
dead-letter entry and 5 ``done`` records — bit-identical survivor results
vs an uninterrupted run — under both the attribution (quarantine-cause)
and blind (SIGKILL bisection) failure modes, plus the seeded multi-worker
chaos soak pinning the containment invariant: every request terminal in
exactly one of done/failed/deadletter/canceled, never lost, never
duplicated, healthy requests always complete.
"""
import json
import os
import random
import subprocess
import sys
import time

import pytest

from redcliff_tpu.fleet import chaos, planner
from redcliff_tpu.fleet import worker as worker_mod
from redcliff_tpu.fleet.queue import (FleetQueue, LeaseLost,
                                      TERMINAL_STATES)
from redcliff_tpu.fleet.worker import _LeaseHeartbeat, run_one_batch
from redcliff_tpu.fleet.__main__ import TINY_SPEC
from redcliff_tpu.obs import schema as obs_schema
from redcliff_tpu.obs.logging import MetricLogger, read_jsonl
from redcliff_tpu.runtime.supervisor import SuperviseOutcome

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _submit_tiny(q, tenant, epochs=2, points=None, **kw):
    spec = json.loads(json.dumps(TINY_SPEC))
    spec["epochs"] = epochs
    return q.submit(tenant, points or [{"gen_lr": 1e-3}], spec=spec, **kw)


def _clean_fault_env():
    env = dict(os.environ)
    env.pop("REDCLIFF_FAULT_INJECT", None)
    env.pop("REDCLIFF_FAULT_MARKER", None)
    return env


# ---------------------------------------------------------------------------
# queue containment units
# ---------------------------------------------------------------------------
def test_deadletter_is_terminal_with_dossier(tmp_path):
    q = FleetQueue(tmp_path)
    rid = _submit_tiny(q, "t")
    dossier = {"reason": "crash_loop", "attempts": 3, "tenant": "t"}
    assert q.deadletter(rid, dossier=dossier) is True
    assert q.terminal_state(rid) == "deadletter"
    assert q.pending() == []
    assert q.claim(rid, "w", lease_s=5.0) is None
    assert q.deadletter_record(rid)["dossier"] == dossier
    assert [r["request_id"] for r in q.deadletters()] == [rid]
    st = q.status()
    assert st["counts"]["deadletter"] == 1
    assert st["by_tenant"]["t"]["deadletter"] == 1


def test_terminal_states_mutually_exclusive(tmp_path):
    # every terminal write goes through one settle that defers to any
    # existing record in ANY terminal directory: exactly one state wins
    q = FleetQueue(tmp_path)
    rid = _submit_tiny(q, "t")
    assert q.deadletter(rid, dossier={}) is True
    assert q.complete(rid, result={"late": True}) is False
    assert q.fail(rid, "numerics_abort") is False
    assert q.cancel(rid) is False
    states = [s for s in TERMINAL_STATES
              if os.path.exists(os.path.join(str(tmp_path), s,
                                             f"{rid}.json"))]
    assert states == ["deadletter"]


def test_cancel_rides_tombstone_path(tmp_path):
    q = FleetQueue(tmp_path)
    rid = _submit_tiny(q, "t")
    # canceling a LEASED request drops the lease (never orphaned) and the
    # request is never re-planned
    lease = q.claim(rid, "w1", lease_s=60.0)
    assert q.cancel(rid, reason="operator") is True
    assert q.terminal_state(rid) == "canceled"
    assert not os.path.exists(lease.path)
    assert q.pending() == []
    assert q.claim(rid, "w2", lease_s=5.0) is None
    # first writer wins: a racing cancel (or the worker's settle) loses
    assert q.cancel(rid) is False
    # the standing owner's publish must lose to the cancel record
    assert q.complete(rid, result={"ok": True}) is False
    assert q.status()["counts"]["canceled"] == 1


def test_cancel_unknown_request_id_refused(tmp_path):
    q = FleetQueue(tmp_path)
    assert q.cancel("req-never-submitted") is False


def test_expired_lease_of_canceled_request_is_gcd(tmp_path):
    # a worker dies holding a lease, then the request is canceled out from
    # under it: the stale lease must not sit forever once it expires
    q = FleetQueue(tmp_path)
    rid = _submit_tiny(q, "t")
    lease = q.claim(rid, "w1", lease_s=60.0, batch_id="b1",
                    batch_request_ids=[rid])
    assert q.cancel(rid) is True          # settle already unlinked it...
    # ...so recreate the orphan: a dead claimant's lease file outliving
    # the cancel, expired
    with open(lease.path, "w") as f:
        json.dump(dict(lease.data, expires_at=0.0), f)
    assert q.expired_claims() == {}       # scan GCs it, no reclaim offered
    assert not os.path.exists(lease.path)


def test_requeue_resurrects_with_fresh_budget(tmp_path):
    q = FleetQueue(tmp_path)
    rid = _submit_tiny(q, "t")
    for _ in range(3):
        q.record_attempt(rid, "giving_up", batch_id="b")
    q.deadletter(rid, dossier={"reason": "crash_loop"})
    assert q.requeue(rid) is True
    # pending again with a zeroed budget — but still marked suspect, so
    # the planner keeps it solo; the dossier is archived (not a terminal
    # record anymore, but kept for audit)
    assert [r["request_id"] for r in q.pending()] == [rid]
    att = q.attempt_record(rid)
    assert att["attempts"] == 0 and att["suspect"] is True
    assert q.deadletters() == []
    archived = [n for n in os.listdir(tmp_path / "deadletter")
                if ".requeued." in n]
    assert len(archived) == 1
    # idempotence: nothing left to resurrect
    assert q.requeue(rid) is False
    assert q.requeue("req-unknown") is False


def test_settle_race_converges_to_priority_winner(tmp_path):
    # two racers aiming at DIFFERENT terminal states can both pass the
    # pre-write is_terminal check; the post-write re-scan must converge
    # every interleaving onto the fixed priority (done > canceled).
    # Simulate the stale check by forcing is_terminal to say "not yet".
    q = FleetQueue(tmp_path)
    rid = _submit_tiny(q, "t")
    assert q.complete(rid, result={"ok": True}) is True
    real = q.is_terminal
    q.is_terminal = lambda r: False          # the racing cancel's stale view
    try:
        assert q.cancel(rid) is False        # defers to the done record
    finally:
        q.is_terminal = real
    assert not os.path.exists(os.path.join(str(tmp_path), "canceled",
                                           f"{rid}.json"))
    assert q.terminal_state(rid) == "done"

    # the mirror interleaving: cancel landed first, the done writer's
    # check was stale — done outranks and the canceled record is removed
    rid2 = _submit_tiny(q, "t2")
    assert q.cancel(rid2) is True
    q.is_terminal = lambda r: False
    try:
        assert q.complete(rid2, result={"ok": True}) is True
    finally:
        q.is_terminal = real
    assert not os.path.exists(os.path.join(str(tmp_path), "canceled",
                                           f"{rid2}.json"))
    assert q.terminal_state(rid2) == "done"


def test_attempt_ledger_failure_vs_reclaim_and_bounded_history(tmp_path):
    q = FleetQueue(tmp_path)
    rid = _submit_tiny(q, "t")
    assert q.attempt_record(rid) is None
    rec = q.record_attempt(rid, "giving_up", batch_id="b1", run_dir="/r1")
    assert rec["attempts"] == 1 and rec["reclaims"] == 0
    # reclaims are dossier evidence, NOT budget (infra faults must not
    # spend a healthy tenant's budget)
    rec = q.record_attempt(rid, "lease_expired", kind="reclaim")
    assert rec["attempts"] == 1 and rec["reclaims"] == 1
    assert rec["last"]["classification"] == "lease_expired"
    for i in range(30):
        rec = q.record_attempt(rid, f"c{i}")
    assert rec["attempts"] == 31
    assert len(rec["history"]) == 20      # bounded
    assert [a["request_id"] for a in q.attempt_records()] == [rid]


def test_pinned_batch_roundtrip(tmp_path):
    q = FleetQueue(tmp_path)
    q.pin_batch("half-a", ["r1", "r2"], parent_batch_id="parent")
    pins = q.pinned_batches()
    assert [p["batch_id"] for p in pins] == ["half-a"]
    assert pins[0]["requests"] == ["r1", "r2"]
    assert pins[0]["parent_batch_id"] == "parent"
    q.unpin_batch("half-a")
    assert q.pinned_batches() == []
    q.unpin_batch("half-a")               # idempotent


# ---------------------------------------------------------------------------
# planner suspect quarantine (the containment circuit breaker)
# ---------------------------------------------------------------------------
def _req(i, n_points=1, per_lane=None, tenant="t"):
    shape = {"num_chans": 4, "num_factors": 2}
    return {"request_id": f"req-{i:03d}", "tenant": tenant,
            "submitted_at": float(i), "priority": 0, "deadline_s": None,
            "shape": shape,
            "points": [{"gen_lr": 1e-3 * (j + 1)} for j in range(n_points)],
            "epochs": 10, "per_lane_bytes": per_lane, "fixed_bytes": 0,
            "spec": {"model_config": shape, "epochs": 10}}


def test_suspects_planned_solo_never_merged():
    reqs = [_req(i) for i in range(4)]
    pl = planner.plan(reqs, n_devices=1, suspects={"req-001"})
    by_len = sorted(pl["batches"], key=lambda b: len(b["requests"]))
    assert [b["requests"] for b in by_len] == \
        [["req-001"], ["req-000", "req-002", "req-003"]]
    assert by_len[0]["suspect"] is True
    assert by_len[1]["suspect"] is False
    # without the suspect flag the same mix merges into one batch
    assert len(planner.plan(reqs, n_devices=1)["batches"]) == 1


def test_suspect_over_budget_is_unschedulable_not_admitted():
    r = _req(0, n_points=4, per_lane=4 << 30)  # 16 GiB at its solo bucket
    pl = planner.plan([r], n_devices=1, budget_bytes=8 << 30,
                      suspects={"req-000"})
    assert pl["batches"] == []
    assert pl["unschedulable"][0]["reason"] == "exceeds_headroom"


# ---------------------------------------------------------------------------
# lease-renewal heartbeat escalation (satellite: no silent fs hiccup)
# ---------------------------------------------------------------------------
class _StubLogger:
    def __init__(self):
        self.events = []

    def log(self, event, **kw):
        self.events.append(dict(kw, event=event))


class _FlakyLease:
    """renew() raises OSError for the first ``n_errors`` calls."""

    def __init__(self, n_errors):
        self.n_errors = n_errors
        self.calls = 0

    def renew(self, lease_s, now=None):
        self.calls += 1
        if self.calls <= self.n_errors:
            raise OSError("disk on fire")


def _wait_for(cond, timeout=8.0):
    deadline = time.time() + timeout
    while not cond():
        assert time.time() < deadline, "condition never held"
        time.sleep(0.02)


def test_renew_errors_escalate_to_lease_lost():
    log = _StubLogger()
    leases = {"r1": _FlakyLease(n_errors=10 ** 6)}
    with _LeaseHeartbeat(leases, lease_s=0.3, logger=log,
                         max_renew_misses=3) as hb:
        _wait_for(lambda: "r1" in hb.lost)
    errors = [e for e in log.events if e.get("kind") == "renew_error"]
    assert [e["consecutive"] for e in errors] == [1, 2, 3]
    assert "OSError" in errors[0]["error"]
    lost = [e for e in log.events if e.get("kind") == "lease_lost"]
    assert lost and lost[0]["error"] == "renewal misses exhausted"
    # escalated exactly once, then the lease left the renewal set
    assert hb.lost == ["r1"] and not leases


def test_renew_error_recovery_resets_consecutive_count():
    log = _StubLogger()
    lease = _FlakyLease(n_errors=2)       # recovers before the 3rd miss
    with _LeaseHeartbeat({"r1": lease}, lease_s=0.3, logger=log,
                         max_renew_misses=3) as hb:
        _wait_for(lambda: lease.calls >= 5)
        assert hb.lost == []
    errors = [e for e in log.events if e.get("kind") == "renew_error"]
    assert [e["consecutive"] for e in errors] == [1, 2]
    assert not any(e.get("kind") == "lease_lost" for e in log.events)


def test_lost_lease_stops_renewals():
    class _GoneLease:
        def __init__(self):
            self.calls = 0

        def renew(self, lease_s, now=None):
            self.calls += 1
            raise LeaseLost("reclaimed")

    log = _StubLogger()
    lease = _GoneLease()
    with _LeaseHeartbeat({"r1": lease}, lease_s=0.3, logger=log) as hb:
        _wait_for(lambda: "r1" in hb.lost)
    assert lease.calls == 1               # dropped from the set immediately
    assert any(e.get("kind") == "lease_lost" for e in log.events)


# ---------------------------------------------------------------------------
# worker settle discipline against a stubbed supervisor (no jax child)
# ---------------------------------------------------------------------------
def _stub_supervise(monkeypatch, classification, rc=1):
    def fake(cmd, ledger_path=None, policy=None, env=None, **kw):
        return SuperviseOutcome(classification=classification,
                                returncode=rc, attempts=[{"rc": rc}])

    monkeypatch.setattr(worker_mod, "supervise", fake)


def _claimed_batch(q, n, lease_s=60.0):
    members = [dict(r) for r in q.requests()][:n]
    batch = planner._batch_view(members, 1)
    leases = {}
    for m in members:
        lease = q.claim(m["request_id"], "w-test", lease_s,
                        batch_id=batch["batch_id"],
                        batch_request_ids=batch["requests"],
                        tenant=m["tenant"])
        assert lease is not None
        leases[m["request_id"]] = lease
    return batch, leases, members


def _write_result(q, batch_id, rid, n_points=1, failures=()):
    d = os.path.join(q.batch_dir(batch_id), "results")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, f"{rid}.json"), "w") as f:
        json.dump({"request_id": rid, "n_points": n_points,
                   "failures": list(failures),
                   "best_criteria": [0.5] * n_points}, f)


def test_missing_result_released_once_then_deadlettered(tmp_path,
                                                        monkeypatch):
    # a clean exit with NO per-request artifact is a durability bug, not a
    # verdict: budget-routed (released), never a stub done
    q = FleetQueue(tmp_path / "fleet")
    rid = _submit_tiny(q, "t")
    _stub_supervise(monkeypatch, "clean", rc=0)
    with MetricLogger(str(tmp_path / "fleet")) as logger:
        batch, leases, members = _claimed_batch(q, 1)
        run_one_batch(q, batch, leases, members, logger, "w-test",
                      max_attempts=2)
        assert q.terminal_state(rid) is None          # released, not done
        assert q.attempt_record(rid)["attempts"] == 1
        assert q.attempt_record(rid)["last"]["classification"] \
            == "missing_result"
        assert [r["request_id"] for r in q.pending()] == [rid]
        # second clean-but-empty run exhausts the budget -> dead-letter
        batch, leases, members = _claimed_batch(q, 1)
        run_one_batch(q, batch, leases, members, logger, "w-test",
                      max_attempts=2)
    assert q.terminal_state(rid) == "deadletter"
    doss = q.deadletter_record(rid)["dossier"]
    assert doss["reason"] == "missing_result" and doss["attempts"] == 2


def test_solo_deterministic_class_fails_outright(tmp_path, monkeypatch):
    q = FleetQueue(tmp_path / "fleet")
    rid = _submit_tiny(q, "t")
    _stub_supervise(monkeypatch, "numerics_abort", rc=18)
    with MetricLogger(str(tmp_path / "fleet")) as logger:
        run_one_batch(q, *_claimed_batch(q, 1), logger, "w-test")
    assert q.terminal_state(rid) == "failed"
    assert q.attempt_record(rid)["attempts"] == 1


def test_solo_crash_loop_burns_budget_then_deadletters(tmp_path,
                                                       monkeypatch):
    q = FleetQueue(tmp_path / "fleet")
    rid = _submit_tiny(q, "t")
    _stub_supervise(monkeypatch, "giving_up", rc=139)
    with MetricLogger(str(tmp_path / "fleet")) as logger:
        for expect_attempts in (1, 2):
            run_one_batch(q, *_claimed_batch(q, 1), logger, "w-test",
                          max_attempts=2)
            assert q.attempt_record(rid)["attempts"] == expect_attempts
    assert q.terminal_state(rid) == "deadletter"
    assert q.deadletter_record(rid)["dossier"]["reason"] == "crash_loop"
    recs = read_jsonl(str(tmp_path / "fleet"))
    assert any(r.get("kind") == "deadletter" for r in recs
               if r.get("event") == "fleet")


def test_merged_terminal_failure_bisects_into_pinned_halves(tmp_path,
                                                            monkeypatch):
    # a blind terminal failure of a MERGED batch never blames every member:
    # exact halves are pinned (the planner cannot re-merge them) and every
    # member is charged one attempt
    q = FleetQueue(tmp_path / "fleet")
    rids = [_submit_tiny(q, f"t{i}") for i in range(4)]
    _stub_supervise(monkeypatch, "giving_up", rc=137)
    root = str(tmp_path / "fleet")
    with MetricLogger(root) as logger:
        run_one_batch(q, *_claimed_batch(q, 4), logger, "w-test")
        for rid in rids:
            assert q.terminal_state(rid) is None      # nobody failed
            assert q.attempt_record(rid)["attempts"] == 1
        pins = q.pinned_batches()
        assert sorted(p["requests"] for p in pins) \
            == sorted([rids[:2], rids[2:]])
        assert {p["parent_batch_id"] for p in pins} == \
            {planner.batch_id_for(rids)}
        # the next claim cycle runs a pinned half EXACTLY as pinned, and
        # consumes the pin
        got = worker_mod._next_batch(q, "w2", 60.0, 1, None,
                                     planner.DEFAULT_MAX_BUCKET, logger)
        assert got is not None
        batch, leases, members = got
        assert batch["requests"] in (rids[:2], rids[2:])
        assert len(q.pinned_batches()) == 1
        for lease in leases.values():
            lease.release()
    recs = read_jsonl(root)
    bisects = [r for r in recs if r.get("event") == "fleet"
               and r.get("kind") == "bisect"]
    assert len(bisects) == 1
    assert [h["requests"] for h in bisects[0]["halves"]] \
        == [rids[:2], rids[2:]]
    assert obs_schema.validate_records(recs) == []


def test_clean_fully_quarantined_member_deadlettered_siblings_done(
        tmp_path, monkeypatch):
    # the attribution path: the grid engine named the culprit (every point
    # of one request quarantined) — no bisection, siblings complete
    q = FleetQueue(tmp_path / "fleet")
    rid_ok = _submit_tiny(q, "healthy")
    rid_bad = _submit_tiny(q, "poison")
    _stub_supervise(monkeypatch, "clean", rc=0)
    with MetricLogger(str(tmp_path / "fleet")) as logger:
        batch, leases, members = _claimed_batch(q, 2)
        _write_result(q, batch["batch_id"], rid_ok)
        _write_result(q, batch["batch_id"], rid_bad, failures=[
            {"point": 0, "cause": "nonfinite_grad"}])
        run_one_batch(q, batch, leases, members, logger, "w-test")
    assert q.terminal_state(rid_ok) == "done"
    assert q.terminal_state(rid_bad) == "deadletter"
    doss = q.deadletter_record(rid_bad)["dossier"]
    assert doss["reason"] == "poison_quarantine"
    assert doss["quarantine_causes"] == {"nonfinite_grad": 1}


def test_deadline_eviction_is_not_poison(tmp_path, monkeypatch):
    # a request whose every lane hit its wall-clock fit deadline is NOT a
    # deterministic poison: it completes done-with-failures, never
    # dead-lettered as poison_quarantine
    q = FleetQueue(tmp_path / "fleet")
    rid = _submit_tiny(q, "t")
    _stub_supervise(monkeypatch, "clean", rc=0)
    with MetricLogger(str(tmp_path / "fleet")) as logger:
        batch, leases, members = _claimed_batch(q, 1)
        _write_result(q, batch["batch_id"], rid, failures=[
            {"point": 0, "cause": "deadline"}])
        run_one_batch(q, batch, leases, members, logger, "w-test")
    assert q.terminal_state(rid) == "done"


def test_merged_batch_with_lost_leases_never_verdicts_survivor(
        tmp_path, monkeypatch):
    # a MERGED batch whose other leases were lost mid-run dies with a
    # deterministic class: the lone survivor may be a healthy co-tenant of
    # the real poison, so it is budget-routed (released), never terminally
    # failed with the batch's verdict
    q = FleetQueue(tmp_path / "fleet")
    rid_a = _submit_tiny(q, "healthy")
    rid_b = _submit_tiny(q, "other")

    def fake(cmd, ledger_path=None, policy=None, env=None, **kw):
        # another worker reclaims B's lease mid-run (a chaos expire race):
        # force expiry, steal it, and let the heartbeat notice LeaseLost.
        # Retried because the heartbeat may re-extend between our expiry
        # write and the claim.
        path = q._lease_path(rid_b)
        for _ in range(50):
            with open(path) as f:
                lease = json.load(f)
            lease["expires_at"] = 0.0
            with open(path, "w") as f:
                json.dump(lease, f)
            if q.claim(rid_b, "thief", lease_s=60.0) is not None:
                break
        else:
            raise AssertionError("never stole the lease")
        time.sleep(0.5)                      # > one heartbeat period
        return SuperviseOutcome(classification="numerics_abort",
                                returncode=18, attempts=[])

    monkeypatch.setattr(worker_mod, "supervise", fake)
    with MetricLogger(str(tmp_path / "fleet")) as logger:
        batch, leases, members = _claimed_batch(q, 2, lease_s=0.6)
        run_one_batch(q, batch, leases, members, logger, "w-test",
                      lease_s=0.6, max_attempts=3)
    # survivor: released with one budgeted attempt, NOT failed
    assert q.terminal_state(rid_a) is None
    assert q.attempt_record(rid_a)["attempts"] == 1
    # the stolen member was never settled by the losing worker
    assert q.terminal_state(rid_b) is None
    assert q.lease_of(rid_b)["worker"] == "thief"


def test_pinned_half_drops_terminal_members(tmp_path, monkeypatch):
    # a pinned member canceled between pin and claim must not ride back
    # into the fit: the half is re-keyed to the surviving composition
    q = FleetQueue(tmp_path / "fleet")
    rids = [_submit_tiny(q, f"t{i}") for i in range(3)]
    q.pin_batch(planner.batch_id_for(rids), rids, parent_batch_id="parent")
    q.cancel(rids[1])
    with MetricLogger(str(tmp_path / "fleet")) as logger:
        got = worker_mod._next_batch(q, "w", 60.0, 1, None,
                                     planner.DEFAULT_MAX_BUCKET, logger)
        assert got is not None
        batch, leases, members = got
        survivors = [rids[0], rids[2]]
        assert batch["requests"] == survivors
        assert batch["batch_id"] == planner.batch_id_for(survivors)
        assert [m["request_id"] for m in members] == survivors
        assert q.pinned_batches() == []      # old pin gone, new consumed
        for lease in leases.values():
            lease.release()


def test_requeued_deadletter_is_planned_solo(tmp_path):
    # the worker derives the planner's suspect set from the attempt
    # ledger: a requeued dead-letter (attempts back to 0) must still be
    # quarantined solo via its suspect marker
    q = FleetQueue(tmp_path / "fleet")
    bad = _submit_tiny(q, "bad")
    healthy = [_submit_tiny(q, f"h{i}") for i in range(2)]
    q.record_attempt(bad, "giving_up")
    q.deadletter(bad, dossier={"reason": "crash_loop"})
    assert q.requeue(bad) is True
    with MetricLogger(str(tmp_path / "fleet")) as logger:
        got = worker_mod._next_batch(q, "w", 60.0, 1, None,
                                     planner.DEFAULT_MAX_BUCKET, logger)
        assert got is not None
        batch, leases, members = got
        # whichever batch was claimed first, the suspect is never merged
        # with the healthy tenants
        assert batch["requests"] in ([bad], healthy)
        for lease in leases.values():
            lease.release()
    plan_ev = [r for r in read_jsonl(str(tmp_path / "fleet"))
               if r.get("event") == "fleet" and r.get("kind") == "plan"]
    assert plan_ev and plan_ev[-1]["suspects"] == [bad]


def test_partial_quarantine_is_normal_sweep_behavior(tmp_path, monkeypatch):
    q = FleetQueue(tmp_path / "fleet")
    rid = _submit_tiny(q, "t", points=[{"gen_lr": 1e-3}, {"gen_lr": 3e-3}])
    _stub_supervise(monkeypatch, "clean", rc=0)
    with MetricLogger(str(tmp_path / "fleet")) as logger:
        batch, leases, members = _claimed_batch(q, 1)
        _write_result(q, batch["batch_id"], rid, n_points=2, failures=[
            {"point": 1, "cause": "nonfinite_val"}])
        run_one_batch(q, batch, leases, members, logger, "w-test")
    assert q.terminal_state(rid) == "done"


def test_canceled_member_is_never_published(tmp_path, monkeypatch):
    # cancel lands while the batch is in flight: the worker's settle finds
    # the terminal record and its publish loses
    q = FleetQueue(tmp_path / "fleet")
    rid = _submit_tiny(q, "t")

    def fake(cmd, ledger_path=None, policy=None, env=None, **kw):
        q.cancel(rid, reason="mid-flight")
        return SuperviseOutcome(classification="clean", returncode=0,
                                attempts=[])

    monkeypatch.setattr(worker_mod, "supervise", fake)
    with MetricLogger(str(tmp_path / "fleet")) as logger:
        batch, leases, members = _claimed_batch(q, 1)
        _write_result(q, batch["batch_id"], rid)
        run_one_batch(q, batch, leases, members, logger, "w-test")
    assert q.terminal_state(rid) == "canceled"
    assert q.result(rid) is None


# ---------------------------------------------------------------------------
# chaos harness primitives
# ---------------------------------------------------------------------------
def test_poison_point_modes_and_strip():
    nan = chaos.poison_point("nan")
    assert chaos.CHAOS_KEY not in nan          # attributable: a real point
    assert nan["gen_lr"] > 1e19
    blind = chaos.poison_point("sigkill")
    assert blind[chaos.CHAOS_KEY] == "sigkill"
    sink = []
    stripped = chaos.strip_chaos(blind, sink)
    assert chaos.CHAOS_KEY not in stripped and sink == ["sigkill"]
    assert chaos.strip_chaos({"gen_lr": 1e-3}) == {"gen_lr": 1e-3}


def test_detonate_exit_specs():
    with pytest.raises(SystemExit) as e:
        chaos.detonate("exit:7")
    assert e.value.code == 7
    with pytest.raises(SystemExit) as e:
        chaos.detonate("hang:0.01")
    assert e.value.code == 19                  # watchdog EXIT_HANG
    with pytest.raises(SystemExit):
        chaos.detonate("wat")


def test_unarmed_sentinels_are_inert():
    from redcliff_tpu.runtime.faultinject import fleet_poison_armed

    assert not fleet_poison_armed()


def test_torn_spool_fault_skipped_and_healed(tmp_path):
    q = FleetQueue(tmp_path)
    a = _submit_tiny(q, "a")
    chaos.tear_spool_tail(tmp_path)
    b = _submit_tiny(q, "b")                   # heals the line boundary
    assert [r["request_id"] for r in q.requests()] == [a, b]
    assert q.status()["torn_spool_lines"] == 1


def test_corrupt_lease_fault_is_reclaimable(tmp_path):
    q = FleetQueue(tmp_path)
    rid = _submit_tiny(q, "t")
    q.claim(rid, "w1", lease_s=60.0)
    assert chaos.corrupt_random_lease(tmp_path, random.Random(0)) \
        == f"{rid}.json"
    # torn lease == expired: the request is reclaimable, never wedged
    lease = q.claim(rid, "w2", lease_s=30.0)
    assert lease is not None and lease.data["worker"] == "w2"


def test_expire_lease_race_old_owner_stands_down(tmp_path):
    q = FleetQueue(tmp_path)
    rid = _submit_tiny(q, "t")
    l1 = q.claim(rid, "w1", lease_s=600.0)
    assert chaos.expire_random_lease(tmp_path, random.Random(0)) == rid
    l2 = q.claim(rid, "w2", lease_s=30.0)
    assert l2 is not None
    with pytest.raises(LeaseLost):
        l1.renew(600.0)                        # exactly one live publisher


def test_random_fleet_fault_schedule_deterministic():
    a = chaos.random_fleet_fault_schedule(7, n_ops=12)
    assert a == chaos.random_fleet_fault_schedule(7, n_ops=12)
    assert a != chaos.random_fleet_fault_schedule(8, n_ops=12)
    assert set(a) <= set(chaos.FLEET_FAULT_KINDS)
    with pytest.raises(ValueError):
        chaos.apply_fault("wat", ".", random.Random(0))


# ---------------------------------------------------------------------------
# cancel / requeue CLI verbs
# ---------------------------------------------------------------------------
def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "redcliff_tpu.fleet", *args],
        capture_output=True, text=True, env=_clean_fault_env(),
        cwd=REPO_ROOT)


def test_cancel_requeue_cli_verbs(tmp_path):
    root = str(tmp_path / "fleet")
    q = FleetQueue(root)
    rid = _submit_tiny(q, "cli")
    out = _cli("cancel", rid, "--root", root, "--reason", "operator")
    assert out.returncode == 0, out.stderr
    assert q.terminal_state(rid) == "canceled"
    # a second cancel reports the existing terminal state and fails
    out = _cli("cancel", rid, "--root", root)
    assert out.returncode == 1 and "canceled" in out.stderr

    rid2 = _submit_tiny(q, "cli")
    q.record_attempt(rid2, "giving_up")
    q.deadletter(rid2, dossier={"reason": "crash_loop"})
    out = _cli("requeue", rid2, "--root", root)
    assert out.returncode == 0, out.stderr
    assert q.terminal_state(rid2) is None
    assert q.attempt_record(rid2)["attempts"] == 0
    assert q.attempt_record(rid2)["suspect"] is True
    out = _cli("requeue", rid2, "--root", root)
    assert out.returncode == 1
    # the verbs are audited as schema-registered fleet events
    kinds = {r.get("kind") for r in read_jsonl(root)
             if r.get("event") == "fleet"}
    assert {"cancel", "requeue"} <= kinds
    assert obs_schema.validate_records(read_jsonl(root)) == []


# ---------------------------------------------------------------------------
# resume-fingerprint compatibility across the lane_seeds upgrade
# ---------------------------------------------------------------------------
def test_resume_accepts_pre_lane_seeds_checkpoint(tmp_path):
    """A grid checkpoint written BEFORE per-lane content seeds joined the
    resume fingerprint must still resume under a lane_seeds-carrying spec:
    seeds are consulted only by init_grid and a resumed fit never
    re-initializes, so rejecting would crash-loop an upgraded fleet
    worker's reclaim of an old in-flight batch straight into the
    dead-letter queue. A checkpoint that RECORDED its derivation
    (``lane_seeds`` key present, even as None) still rejects a different
    one — that genuinely is a different fit."""
    import dataclasses

    import jax

    from redcliff_tpu.data.datasets import ArrayDataset
    from redcliff_tpu.fleet.run_batch import lane_seed
    from redcliff_tpu.parallel.grid import RedcliffGridRunner
    from redcliff_tpu.runtime import checkpoint as rck
    from redcliff_tpu.runtime.faultinject import _tiny_runner

    runner, X, Y = _tiny_runner(3)
    ds = ArrayDataset(X, Y)
    ck = str(tmp_path / "ck")
    runner.fit(jax.random.PRNGKey(2), ds, ds, max_iter=2,
               checkpoint_dir=ck, checkpoint_every=1)
    seeded = dataclasses.replace(
        runner.spec, lane_seeds=[lane_seed(p) for p in runner.spec.points])

    # control: the checkpoint RECORDED lane_seeds=None — resuming under a
    # content-seeded spec is a fingerprint mismatch, named
    with pytest.raises(ValueError, match="lane_seeds"):
        RedcliffGridRunner(runner.model, runner.tc, seeded).fit(
            jax.random.PRNGKey(2), ds, ds, checkpoint_dir=ck,
            checkpoint_every=1)

    # rewrite as a pre-containment checkpoint (no lane_seeds key at all):
    # the carve-out must resume it and finish the remaining epoch
    path = os.path.join(ck, "grid_checkpoint.pkl")
    blob = rck.read_checkpoint(path)
    del blob["meta"]["lane_seeds"]
    rck.write_checkpoint(path, blob)
    res = RedcliffGridRunner(runner.model, runner.tc, seeded).fit(
        jax.random.PRNGKey(2), ds, ds, checkpoint_dir=ck,
        checkpoint_every=1)
    assert res.val_history.shape[0] == 3  # resumed epoch 2, not rejected


# ---------------------------------------------------------------------------
# end-to-end acceptance (supervised jax children; warm suite compile cache)
# ---------------------------------------------------------------------------
def _drain(root, env=None, max_restarts=2, **kw):
    from redcliff_tpu.runtime.retry import RetryPolicy
    from redcliff_tpu.runtime.supervisor import SupervisorPolicy

    from redcliff_tpu.fleet.worker import work

    policy = SupervisorPolicy(
        max_restarts=max_restarts,
        backoff=RetryPolicy(max_attempts=100, base_delay_s=0.05,
                            multiplier=1.0, max_delay_s=0.05))
    return work(str(root), drain=True, poll_s=0.2, lease_s=20.0,
                supervisor_policy=policy, env=env or _clean_fault_env(),
                **kw)


def _submit_mix(q, poison=None, n_healthy=5, epochs=2):
    """n_healthy 1-point requests (tenant h<i>) + optionally one poison
    request (tenant 'poison'); returns ({tenant: rid}, poison_rid)."""
    rids = {}
    for i in range(n_healthy):
        rids[f"h{i}"] = _submit_tiny(q, f"h{i}", epochs=epochs,
                                     points=[{"gen_lr": 1e-3 * (i + 1)}])
    prid = None
    if poison is not None:
        prid = _submit_tiny(q, "poison", epochs=epochs, points=[poison])
    return rids, prid


def _payload(result):
    """A per-request result minus its identity fields (request id / batch
    id differ across legs by construction; the numeric payload — criteria,
    epochs, val history, active mask, failures — is the bit-identity
    surface)."""
    return {k: v for k, v in result.items()
            if k not in ("request_id", "batch_id")}


def _assert_invariant(q, rids):
    """Every request terminal in exactly ONE of done/failed/deadletter/
    canceled — never lost, never duplicated."""
    for rid in rids:
        states = [s for s in TERMINAL_STATES if os.path.exists(
            os.path.join(q.root, s, f"{rid}.json"))]
        assert len(states) == 1, f"{rid}: terminal in {states}"


def test_attribution_containment_6way_bit_identical(tmp_path):
    """The bisection-determinism contract, attribution mode: a 6-request
    merged batch with 1 nan-poison request converges to exactly 1
    dead-letter entry and 5 done records, survivors bit-identical to an
    uninterrupted (poison-free) run — the poison co-tenant costs its
    siblings nothing, not even an ulp (same G-bucket both legs)."""
    root_p, root_r = tmp_path / "poisoned", tmp_path / "ref"
    qp, qr = FleetQueue(root_p), FleetQueue(root_r)
    rids_p, prid = _submit_mix(qp, poison=chaos.poison_point("nan"))
    rids_r, _ = _submit_mix(qr)

    assert _drain(root_p, max_attempts=2) == 1   # ONE merged batch
    cp = qp.status()["counts"]
    assert cp["done"] == 5 and cp["deadletter"] == 1 and cp["failed"] == 0
    _assert_invariant(qp, list(rids_p.values()) + [prid])
    doss = qp.deadletter_record(prid)["dossier"]
    assert doss["reason"] == "poison_quarantine"
    assert set(doss["quarantine_causes"]) <= {"nonfinite_grad",
                                              "nonfinite_val"}
    assert doss["attempts"] == 1                 # never crash-looped

    assert _drain(root_r) == 1
    for tenant, rid in rids_p.items():
        res = _payload(qp.result(rid)["result"])
        ref = _payload(qr.result(rids_r[tenant])["result"])
        assert res == ref, f"{tenant} diverged beside the poison co-tenant"

    # observability: watch fleet mode renders dead-letter depth + attempt
    # budgets; report grows the containment section; all schema-valid
    from redcliff_tpu.obs.report import build_report, render_text
    from redcliff_tpu.obs.watch import build_snapshot

    snap = build_snapshot(str(root_p))
    assert obs_schema.validate_record(snap) == []
    assert snap["fleet"]["deadletter"]["depth"] == 1
    dl0 = snap["fleet"]["deadletter"]["requests"][0]
    assert dl0["tenant"] == "poison" and dl0["attempts"] == 1
    # terminal budgets live in the dossier headline; the live attempts
    # map only carries in-flight/queued requests (everything settled here)
    assert prid not in snap["fleet"]["attempts"]
    report = build_report(str(root_p))
    fc = report["fleet_containment"]
    assert fc["counts"]["deadletter"] == 1
    assert fc["deadletters"][0]["dossier"]["reason"] == "poison_quarantine"
    assert fc["events"].get("deadletter") == 1
    assert "dead-letter" in render_text(report)
    recs = read_jsonl(str(root_p))
    assert obs_schema.validate_records(recs) == []


@pytest.mark.slow
def test_blind_sigkill_poison_bisection_bit_identical(tmp_path):
    """The bisection-determinism contract, blind mode: the poison child
    SIGKILLs itself before any attribution exists, so the worker corners
    it by halving — 6 requests converge to 5 done + exactly 1 dead-letter,
    and the survivors (finishing in width-4 and width-2 halves) are
    bit-identical to the uninterrupted width-8 merged run on the
    width-exact legacy CPU runtime."""
    env = _clean_fault_env()
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_cpu_use_thunk_runtime=false").strip()
    armed = dict(env, REDCLIFF_FAULT_INJECT="fleet_poison")

    root_a, root_r = tmp_path / "armed", tmp_path / "ref"
    qa, qr = FleetQueue(root_a), FleetQueue(root_r)
    rids_a, prid = _submit_mix(qa, poison=chaos.poison_point("sigkill"))
    rids_r, prid_r = _submit_mix(qr, poison=chaos.poison_point("sigkill"))

    _drain(root_a, env=armed, max_restarts=0, max_attempts=3)
    ca = qa.status()["counts"]
    assert ca["done"] == 5 and ca["deadletter"] == 1 and ca["failed"] == 0
    _assert_invariant(qa, list(rids_a.values()) + [prid])
    doss = qa.deadletter_record(prid)["dossier"]
    assert doss["reason"] == "crash_loop"
    assert doss["attempts"] >= 3
    assert "giving_up" in doss["classifications"]
    recs = read_jsonl(str(root_a))
    bisects = [r for r in recs if r.get("event") == "fleet"
               and r.get("kind") == "bisect"]
    assert len(bisects) >= 2, "halving never cornered the poison"
    assert obs_schema.validate_records(recs) == []

    # reference: the SAME spool unarmed — sentinels stripped, all 6 fit in
    # one uninterrupted width-8 batch
    assert _drain(root_r, env=env) == 1
    assert qr.status()["counts"]["done"] == 6
    for tenant, rid in rids_a.items():
        res = _payload(qa.result(rid)["result"])
        ref = _payload(qr.result(rids_r[tenant])["result"])
        assert res == ref, f"{tenant} diverged across bisection widths"


@pytest.mark.slow
def test_chaos_soak_containment_invariant(tmp_path):
    """The seeded multi-worker chaos soak: real worker processes, a
    nan-poison co-tenant, SIGKILL storms, forced lease-expiry races, and
    torn/corrupt durable state — every request must end terminal in
    exactly one state, healthy requests all done with results
    bit-identical to a fault-free drain, the poison dead-lettered."""
    seed = 11
    env = _clean_fault_env()
    root, ref = tmp_path / "soak", tmp_path / "ref"
    q, qr = FleetQueue(root), FleetQueue(ref)
    rids, prid = _submit_mix(q, poison=chaos.poison_point("nan"),
                             n_healthy=4, epochs=3)
    rids_r, prid_r = _submit_mix(qr, poison=chaos.poison_point("nan"),
                                 n_healthy=4, epochs=3)
    all_rids = list(rids.values()) + [prid]

    rng = random.Random(seed)
    schedule = chaos.random_fleet_fault_schedule(seed, n_ops=5)
    ops = iter(schedule)
    applied = []
    with chaos.WorkerFleet(root, n_workers=2, lease_s=3.0, poll_s=0.2,
                           max_attempts=3, env=env) as fleet:
        deadline = time.time() + 600
        while time.time() < deadline:
            if all(q.is_terminal(r) for r in all_rids):
                break
            op = next(ops, None)
            if op is not None:
                applied.append(chaos.apply_fault(op, root, rng,
                                                 fleet=fleet))
            fleet.respawn()
            time.sleep(2.0)
        else:
            raise AssertionError(
                f"soak never settled; status={q.status()['counts']} "
                f"applied={applied}")

    _assert_invariant(q, all_rids)
    counts = q.status()["counts"]
    assert counts["done"] == 4, (counts, applied)
    assert counts["deadletter"] == 1 and counts["failed"] == 0
    assert q.terminal_state(prid) == "deadletter"
    # healthy requests bit-identical to a fault-free drain of the same mix
    assert _drain(ref, max_attempts=3) >= 1
    for tenant, rid in rids.items():
        assert _payload(q.result(rid)["result"]) \
            == _payload(qr.result(rids_r[tenant])["result"]), \
            f"{tenant} diverged under chaos (applied={applied})"
    assert qr.terminal_state(prid_r) == "deadletter"
