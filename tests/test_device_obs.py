"""Device memory & profile observatory acceptance suite (ISSUE 9):

* footprint-model goldens — the analytical HBM model's terms agree with the
  CONCRETE parameter trees' byte counts at off-ladder and heterogeneous
  G-buckets (on this CPU container the ±20% vs-measured-watermark contract
  is golden-valued: there is no ``memory_stats`` to measure against);
* capture windows — profiling on vs off is bit-identical, the window
  brackets exactly the requested epochs, and the legacy ``profile_dir``
  knob now captures ONE bounded window instead of the whole fit;
* trace export — spans + events + ledger attempts from a ROTATED metrics
  chain with a torn tail round-trip into valid Chrome trace-event JSON
  (process/thread lanes, lanes-live + HBM counter tracks), and the CLI
  exits 2 on missing/empty run dirs like its report/watch siblings;
* ``memory`` events ride a real grid fit, validate against the closed
  registry, and surface in ``obs report`` / ``obs watch`` with an explicit
  ``n/a (backend)`` degradation on this CPU container;
* the standalone lint entry (``python -m redcliff_tpu.obs.schema --check``)
  runs the AST source tripwires clean.
"""
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from redcliff_tpu.obs import build_report, schema
from redcliff_tpu.obs import memory as obsmem
from redcliff_tpu.obs import profiling
from redcliff_tpu.obs.logging import MetricLogger, jsonl_files, read_jsonl
from redcliff_tpu.obs.report import main as obs_main
from redcliff_tpu.obs.report import render_text
from redcliff_tpu.obs.trace_export import build_trace, validate_trace
from redcliff_tpu.obs.watch import build_snapshot
from redcliff_tpu.obs.watch import render_text as watch_render
from redcliff_tpu.parallel import compaction
from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig
from test_parallel_grid import _data, _model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# analytical footprint model (obs/memory.py)
# ---------------------------------------------------------------------------
def test_footprint_model_golden_off_ladder():
    """The abstract-shape model must agree EXACTLY with the concrete
    parameter trees' byte counts, at an off-ladder G (5 -> bucket 8)."""
    model = _model()
    params = model.init(jax.random.PRNGKey(0))
    per_point = obsmem.tree_bytes(params)
    emb = obsmem.tree_bytes(params["embedder"])
    fac = obsmem.tree_bytes(params["factors"])
    pb = obsmem.param_bytes(model)
    assert pb["embedder"] == emb and pb["factors"] == fac
    assert pb["total"] == per_point > 0

    g_exec = compaction.bucket_width(5)  # off-ladder grid pads 5 -> 8
    assert g_exec == 8
    fp = obsmem.grid_footprint(model, None, g_exec, stream_mode="per_batch")
    assert fp["params_bytes"] == per_point * 8
    assert fp["opt_bytes"] == 2 * (emb + fac) * 8  # Adam mu+nu per group
    assert fp["best_bytes"] == per_point * 8       # best copy, no freeze
    assert fp["dataset_bytes"] == 0 and fp["epoch_gather_bytes"] == 0
    assert fp["total_bytes"] == fp["per_lane_bytes"] * 8
    # the per-lane slope is exact: heterogeneous buckets differ by
    # exactly (width delta) x per_lane
    fp4 = obsmem.grid_footprint(model, None, 4, stream_mode="per_batch")
    assert fp["total_bytes"] - fp4["total_bytes"] == 4 * fp["per_lane_bytes"]


def test_footprint_epoch_mode_counts_dataset_and_gather():
    model = _model()
    ds = _data(model)
    x_bytes = ds.X.nbytes + ds.Y.nbytes
    fp = obsmem.grid_footprint(model, None, 4, train_ds=ds, val_ds=ds,
                               stream_mode="epoch")
    assert fp["dataset_bytes"] == 2 * x_bytes        # train + val resident
    assert fp["epoch_gather_bytes"] == x_bytes       # permuted train copy
    # device-batch datasets stay resident on the per-batch path too; only
    # the epoch scan pays the transient permuted copy
    off = obsmem.grid_footprint(model, None, 4, train_ds=ds, val_ds=ds,
                                stream_mode="per_batch")
    assert off["dataset_bytes"] == 2 * x_bytes
    assert off["epoch_gather_bytes"] == 0


def test_footprint_by_bucket_rides_the_ladder():
    model = _model()
    rungs = obsmem.footprint_by_bucket(model, None, g_real=5, n_devices=1)
    widths = [r["g_bucket"] for r in rungs]
    assert widths == compaction.ladder_widths(5, 1) == [8, 16, 32, 64]
    totals = [r["total_bytes"] for r in rungs]
    assert totals == sorted(totals) and totals[0] < totals[-1]


def test_ladder_widths_submesh_rungs():
    # widths below the mesh stay on divisors (sub-mesh rungs), above it on
    # multiples — the same ladder bucket_width walks
    assert compaction.ladder_widths(2, 8, max_width=16) == [2, 4, 8, 16]
    assert compaction.ladder_widths(9, 4, max_width=64) == [16, 32, 64]


def test_headroom_degrades_explicitly_on_cpu():
    """This container's CPU backend reports no memory_stats: the headroom
    verdict must be an explicit None (n/a), never a guess."""
    assert obsmem.device_memory_stats() is None
    assert obsmem.poll_watermark() is None
    hr = obsmem.check_headroom(1 << 30)
    assert hr["fits"] is None and hr["bytes_limit"] is None
    assert hr["budget_bytes"] is None
    assert hr["backend"] == "cpu"


def test_mem_poll_env_knob(monkeypatch):
    monkeypatch.setenv(obsmem.ENV_MEM_POLL, "0")
    assert not obsmem.polling_enabled()
    monkeypatch.setenv(obsmem.ENV_MEM_POLL, "1")
    assert obsmem.polling_enabled()


# ---------------------------------------------------------------------------
# capture windows (obs/profiling.py)
# ---------------------------------------------------------------------------
def test_parse_window_specs():
    assert profiling.parse_window(None) is None
    assert profiling.parse_window("off") is None
    assert profiling.parse_window("0") is None
    assert profiling.parse_window("epoch:3") == (3, 3)
    assert profiling.parse_window("epoch:2-4") == (2, 4)
    for bad in ("epoch", "step:3", "epoch:x", "epoch:4-2", "epoch:-1"):
        with pytest.raises(ValueError):
            profiling.parse_window(bad)


def test_window_for_profile_dir_alias_is_bounded(tmp_path):
    """profile_dir WITHOUT a window spec = one bounded steady-state window
    (epoch 1), never the whole fit."""
    class C:
        profile_dir = str(tmp_path / "prof")
        profile_window = None

    w = profiling.window_for(C(), max_iter=10)
    assert (w.first_epoch, w.last_epoch) == (1, 1)
    one = profiling.window_for(C(), max_iter=1)
    assert (one.first_epoch, one.last_epoch) == (0, 0)

    class Off:
        profile_dir = None
        profile_window = None

    assert profiling.window_for(Off(), run_dir=None) is profiling.NOOP


def test_explicit_off_beats_profile_dir_alias(tmp_path, monkeypatch):
    """The operator's off switch (profile_window='off' / REDCLIFF_PROFILE=0)
    disables profiling even when a committed config sets profile_dir."""
    class C:
        profile_dir = str(tmp_path / "prof")
        profile_window = "off"

    assert profiling.window_for(C(), max_iter=10) is profiling.NOOP

    class D:
        profile_dir = str(tmp_path / "prof")
        profile_window = None

    monkeypatch.setenv(profiling.ENV_PROFILE, "0")
    assert profiling.window_for(D(), max_iter=10) is profiling.NOOP
    monkeypatch.delenv(profiling.ENV_PROFILE)
    assert profiling.window_for(D(), max_iter=10).enabled


def test_truncated_window_reports_captured_range(tmp_path):
    """A fit dying inside an open window announces the epochs actually
    captured (started..last seen), marked truncated."""
    win = profiling.CaptureWindow(str(tmp_path / "prof"), 1, 10)
    with MetricLogger(str(tmp_path)) as log, win:
        for e in range(4):  # fit ends at epoch 3, inside the 1-10 window
            win.on_epoch_start(e)
            win.on_epoch_end(e, logger=log)
    profs = read_jsonl(str(tmp_path), event="profile")
    assert len(profs) == 1
    p = profs[0]
    assert p["truncated"] and (p["first_epoch"], p["last_epoch"]) == (1, 3)


@pytest.fixture(scope="module")
def profiled_pair(tmp_path_factory):
    """Two identical grid fits, one with a capture window armed: the
    bit-identity input and the memory/profile-event fixture."""
    model = _model()
    ds = _data(model)
    points = [{"gen_lr": 1e-3}, {"gen_lr": 5e-3}, {"gen_lr": 2e-3}]

    def run(profile_window, run_dir):
        tc = RedcliffTrainConfig(max_iter=4, batch_size=32, check_every=1,
                                 profile_window=profile_window)
        runner = RedcliffGridRunner(model, tc, GridSpec(points=list(points)))
        res = runner.fit(jax.random.PRNGKey(0), ds, ds, log_dir=run_dir)
        return runner, res

    off_dir = str(tmp_path_factory.mktemp("win_off"))
    on_dir = str(tmp_path_factory.mktemp("win_on"))
    # the OFF leg also disables watermark polling, so the identity compare
    # covers BOTH knobs at once: window+polling on vs window+polling off
    old = os.environ.get(obsmem.ENV_MEM_POLL)
    os.environ[obsmem.ENV_MEM_POLL] = "0"
    try:
        _, res_off = run(None, off_dir)
    finally:
        if old is None:
            os.environ.pop(obsmem.ENV_MEM_POLL, None)
        else:
            os.environ[obsmem.ENV_MEM_POLL] = old
    runner_on, res_on = run("epoch:1-2", on_dir)
    return res_off, res_on, on_dir, runner_on


def test_capture_window_on_off_bit_identical(profiled_pair):
    """Profiling and memory polling observe, never participate: the
    decision streams with a capture window + watermark polling armed are
    BIT-identical to the run with both off."""
    res_off, res_on, _run, _runner = profiled_pair
    np.testing.assert_array_equal(np.asarray(res_off.val_history),
                                  np.asarray(res_on.val_history))
    np.testing.assert_array_equal(np.asarray(res_off.best_criteria),
                                  np.asarray(res_on.best_criteria))
    np.testing.assert_array_equal(np.asarray(res_off.best_epoch),
                                  np.asarray(res_on.best_epoch))


def test_capture_window_brackets_requested_epochs(profiled_pair):
    _off, _on, run, _runner = profiled_pair
    profs = read_jsonl(run, event="profile")
    assert len(profs) == 1
    p = profs[0]
    assert (p["first_epoch"], p["last_epoch"]) == (1, 2)
    assert p["spec"] == "epoch:1-2" and not p["truncated"]
    assert not schema.validate_record(p)
    # the jax.profiler artifact tree exists under the announced path
    produced = [os.path.join(dp, f)
                for dp, _dn, fs in os.walk(p["path"]) for f in fs]
    assert produced, "capture window produced no profile artifact"


def test_memory_events_ride_the_fit_and_validate(profiled_pair):
    _off, _on, run, runner = profiled_pair
    recs = read_jsonl(run)
    assert not schema.validate_records(recs)
    mems = [r for r in recs if r["event"] == "memory"]
    kinds = {m["kind"] for m in mems}
    assert "predicted" in kinds
    pred = next(m for m in mems if m["kind"] == "predicted")
    assert pred["g_bucket"] == 4 and pred["predicted_bytes"] > 0
    assert pred["backend"] == "cpu" and pred["fits"] is None
    # dispatch_stats carries the same axis (-> every checkpoint)
    sm = runner.dispatch_stats["memory"]
    assert sm["predicted_bytes"] == pred["predicted_bytes"]
    assert sm["peak_bytes"] is None  # no memory_stats on this backend


def test_report_and_watch_surface_memory(profiled_pair):
    _off, _on, run, _runner = profiled_pair
    rep = build_report(run)
    mem = rep["memory"]
    assert mem["fits"] and mem["fits"][0]["predicted_bytes"] > 0
    assert not mem["measured_available"]
    assert mem["profiles"] and mem["profiles"][0]["spec"] == "epoch:1-2"
    text = render_text(rep)
    assert "n/a (cpu)" in text and "device memory" in text
    snap = build_snapshot(run)
    assert not schema.validate_record(snap)
    assert snap["memory"]["predicted_bytes"] > 0
    assert snap["memory"]["bytes_in_use"] is None
    assert "hbm: n/a (cpu)" in watch_render(snap)


# ---------------------------------------------------------------------------
# trace export (obs/trace_export.py)
# ---------------------------------------------------------------------------
def _write_trace_fixture(run, n=40, max_bytes=2000):
    """A rotation-forcing metrics chain + ledger: fit lifecycle, epochs
    (lanes_live counter source), spans, measured memory polls (hbm counter
    source), and a torn tail SIGKILL-style."""
    with MetricLogger(run, max_bytes=max_bytes) as log:
        log.log("fit_start", model="RedcliffGridRunner", grid_size=8,
                grid_width=8, shape={"num_chans": 4})
        for i in range(n):
            log.log("span", name="grid.dispatch", dur_ms=1.5, span_id=i + 1)
            if i % 4 == 0:
                log.log("epoch", epoch=i // 4, lanes_live=8 - i // 8,
                        grid_width=8, epoch_ms=2.0)
            if i % 8 == 0:
                log.log("memory", kind="measured", epoch=i // 4,
                        bytes_in_use=1000 + i, peak_bytes=2000 + i,
                        bytes_limit=10_000)
        log.log("compaction", epoch=n // 4, from_width=8, to_width=4)
        log.log("fit_end")
    with open(os.path.join(run, "metrics.jsonl"), "a") as f:
        f.write('{"event": "epoch", "wall_time": 99.0, "epo')  # torn tail
    with open(os.path.join(run, "run_ledger.jsonl"), "w") as f:
        f.write(json.dumps({
            "event": "attempt", "attempt": 0, "cmd": ["fit"], "rc": 0,
            "classification": "clean", "action": "stop",
            "started_at": 1.0, "duration_s": 2.0}) + "\n")


def test_trace_export_round_trip_rotated_torn(tmp_path):
    run = str(tmp_path)
    _write_trace_fixture(run)
    assert len(jsonl_files(os.path.join(run, "metrics.jsonl"))) > 1, \
        "fixture must exercise the rotation chain"
    trace = build_trace(run)
    # valid Chrome trace-event JSON, strict round trip
    blob = json.dumps(trace, allow_nan=False)
    assert validate_trace(json.loads(blob)) == []
    events = trace["traceEvents"]
    spans = [e for e in events if e["ph"] == "X" and e["cat"] == "span"]
    assert len(spans) == 40 and all(e["dur"] > 0 for e in spans)
    lanes = [e for e in events if e["ph"] == "C"
             and e["name"] == "lanes_live"]
    assert lanes and lanes[-1]["args"]["lanes_live"] == 4
    hbm = [e for e in events if e["ph"] == "C" and e["name"] == "hbm_bytes"]
    assert hbm and hbm[0]["args"]["peak_bytes"] == 2000
    attempts = [e for e in events if e.get("cat") == "attempt"]
    assert len(attempts) == 1 and attempts[0]["dur"] == 2e6
    # process/thread metadata names every lane
    meta = [e for e in events if e["ph"] == "M"]
    assert {m["name"] for m in meta} == {"process_name", "thread_name"}
    # the torn line was skipped and counted, not fatal
    assert trace["otherData"]["torn_lines"] == 1


def test_trace_cli_writes_and_exits_2_like_siblings(tmp_path, capsys):
    run = str(tmp_path / "run")
    os.makedirs(run)
    _write_trace_fixture(run, n=8, max_bytes=None)
    out = str(tmp_path / "trace.json")
    assert obs_main(["trace", run, "-o", out]) == 0
    with open(out) as f:
        assert validate_trace(json.load(f)) == []
    capsys.readouterr()
    # exit-2 contract shared with report/watch: one-line diagnosis
    assert obs_main(["trace", str(tmp_path / "missing")]) == 2
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    assert obs_main(["trace", empty]) == 2
    err = capsys.readouterr().err
    assert "obs trace:" in err and "no telemetry" in err


def test_trace_cli_module_entry(tmp_path):
    r = subprocess.run(
        [sys.executable, "-m", "redcliff_tpu.obs", "trace",
         str(tmp_path / "nope")],
        cwd=REPO, capture_output=True, text=True, timeout=240,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 2 and "obs trace:" in r.stderr


# ---------------------------------------------------------------------------
# standalone source-tripwire entry (CI lint job)
# ---------------------------------------------------------------------------
def test_schema_check_sources_clean_and_catches_drift(tmp_path):
    assert schema.check_sources() == []
    # an unregistered event literal in a scanned tree is a violation
    bad = tmp_path / "obs"
    bad.mkdir()
    (bad / "rogue.py").write_text(
        'def f(log):\n    log.log("mystery_event", x=1)\n')
    errs = schema.check_sources(str(tmp_path))
    assert errs and "mystery_event" in errs[0]
    # and so is a module-scope jax import in a lazy-jax module — including
    # one hidden inside a try: block (a tree.body-only walk would miss it)
    (bad / "rogue.py").unlink()
    (bad / "memory.py").write_text("import jax\n")
    errs = schema.check_sources(str(tmp_path))
    assert errs and "jax imported" in errs[0]
    (bad / "memory.py").write_text(
        "try:\n    import jax\nexcept ImportError:\n    jax = None\n")
    errs = schema.check_sources(str(tmp_path))
    assert errs and "jax imported" in errs[0]
    # a function-scoped (lazy) import is exactly what the discipline allows
    (bad / "memory.py").write_text("def f():\n    import jax\n    return jax\n")
    assert schema.check_sources(str(tmp_path)) == []


def test_schema_check_module_entry():
    r = subprocess.run(
        [sys.executable, "-m", "redcliff_tpu.obs.schema", "--check"],
        cwd=REPO, capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 violation(s)" in r.stdout
