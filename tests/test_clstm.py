"""cLSTM family: cell parity vs torch's nn.LSTM, GC/prox semantics, and an
end-to-end cLSTM_FM training slice (the reference's train/CLSTM_* capability)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from redcliff_tpu.data import synthetic as S
from redcliff_tpu.data.datasets import train_val_split
from redcliff_tpu.models import clstm as clstm_mod
from redcliff_tpu.models.clstm_fm import CLSTMFM, CLSTMFMConfig, arrange_input
from redcliff_tpu.train.trainer import TrainConfig, Trainer
from redcliff_tpu.utils.metrics import roc_auc


def test_clstm_forward_matches_torch_lstm():
    """The batched scan must reproduce torch's per-series LSTM + 1x1-conv head
    (the reference's building block, ref models/clstm.py:12-43) exactly."""
    torch = pytest.importorskip("torch")
    C, H, B, T = 3, 7, 2, 11
    key = jax.random.PRNGKey(0)
    params = clstm_mod.init_clstm_params(key, C, H)

    rng = np.random.default_rng(0)
    X = rng.normal(size=(B, T, C)).astype(np.float32)
    preds, (h, c) = clstm_mod.clstm_forward(params, jnp.asarray(X))

    for s in range(C):
        lstm = torch.nn.LSTM(C, H, batch_first=True)
        sd = lstm.state_dict()
        sd["weight_ih_l0"] = torch.tensor(np.asarray(params["w_ih"][s]))
        sd["weight_hh_l0"] = torch.tensor(np.asarray(params["w_hh"][s]))
        sd["bias_ih_l0"] = torch.tensor(np.asarray(params["b"][s]))
        sd["bias_hh_l0"] = torch.zeros(4 * H)  # merged bias convention
        lstm.load_state_dict(sd)
        with torch.no_grad():
            out, (ht, ct) = lstm(torch.tensor(X))
            y = out @ torch.tensor(np.asarray(params["head"]["w"][s])) + float(
                params["head"]["b"][s])
        np.testing.assert_allclose(np.asarray(preds[:, :, s]), y.numpy(),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(h[:, s]), ht[0].numpy(),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(c[:, s]), ct[0].numpy(),
                                   rtol=1e-5, atol=1e-5)


def test_clstm_hidden_carry_continues_sequence():
    C, H = 2, 5
    params = clstm_mod.init_clstm_params(jax.random.PRNGKey(1), C, H)
    X = jax.random.normal(jax.random.PRNGKey(2), (3, 10, C))
    full, _ = clstm_mod.clstm_forward(params, X)
    first, carry = clstm_mod.clstm_forward(params, X[:, :4, :])
    second, _ = clstm_mod.clstm_forward(params, X[:, 4:, :], hidden=carry)
    np.testing.assert_allclose(np.asarray(full),
                               np.asarray(jnp.concatenate([first, second], axis=1)),
                               rtol=1e-5, atol=1e-6)


def test_clstm_gc_shape_and_prox_zeroing():
    C, H = 4, 6
    params = clstm_mod.init_clstm_params(jax.random.PRNGKey(3), C, H)
    gc = clstm_mod.clstm_gc(params)
    assert gc.shape == (C, C)
    assert bool(jnp.all(gc > 0))
    # a huge lam*lr wipes every column group to exactly zero
    zeroed = clstm_mod.clstm_prox_update(params, lam=1e3, lr=1.0)
    assert bool(jnp.all(clstm_mod.clstm_gc(zeroed) == 0.0))
    # thresholded readout is binary ints
    thr = clstm_mod.clstm_gc(zeroed, threshold=True)
    assert thr.dtype == jnp.int32 and bool(jnp.all(thr == 0))


def test_arrange_input_matches_reference_semantics():
    """Window t of the input covers steps [t, t+ctx) and its target covers
    [t+1, t+ctx+1) (ref clstm_fm.py:95-112)."""
    B, T, C, ctx = 2, 9, 3, 4
    X = jnp.arange(B * T * C, dtype=jnp.float32).reshape(B, T, C)
    inp, tgt = arrange_input(X, ctx)
    assert inp.shape == (B * (T - ctx), ctx, C)
    np.testing.assert_array_equal(np.asarray(inp[0]), np.asarray(X[0, :ctx]))
    np.testing.assert_array_equal(np.asarray(tgt[0]), np.asarray(X[0, 1 : ctx + 1]))
    np.testing.assert_array_equal(np.asarray(inp[T - ctx]), np.asarray(X[1, :ctx]))


@pytest.mark.slow
def test_clstm_fm_end_to_end_recovers_structure():
    D = 5
    p = S.reference_curation_params(D)
    graphs, acts, _ = S.generate_lagged_adjacency_graphs_for_factor_model(
        num_nodes=D, num_lags=2, num_factors=1, make_factors_orthogonal=False,
        make_factors_singular_components=False, rand_seed=21,
        off_diag_edge_strengths=p["off_diag_edge_strengths"],
        diag_receiving_node_forgetting_coeffs=p["diag_receiving_node_forgetting_coeffs"],
        diag_sending_node_forgetting_coeffs=p["diag_sending_node_forgetting_coeffs"],
        num_edges_per_graph=6,
    )
    X, Y = S.generate_synthetic_dataset(
        jax.random.PRNGKey(6), graphs, acts, p["base_freqs"], p["noise_mu"],
        p["noise_var"], p["innovation_amp"], num_samples=192,
        recording_length=24, burnin_period=10, num_labeled_sys_states=1,
        noise_type="gaussian", noise_amp=0.0,
    )
    train_ds, val_ds = train_val_split(X, Y, val_fraction=0.2,
                                      rng=np.random.default_rng(0))
    # the L1 coefficient must dominate early weight growth: the early-stopping
    # criterion is the raw GC L1 (reference parity), which otherwise selects the
    # untrained epoch-0 model
    cfg = CLSTMFMConfig(num_chans=D, gen_hidden=10, context=8,
                        forecast_coeff=1.0, adj_l1_coeff=0.05)
    model = CLSTMFM(cfg)
    params = model.init(jax.random.PRNGKey(0))
    trainer = Trainer(model, TrainConfig(learning_rate=1e-2, max_iter=40,
                                         batch_size=64, check_every=10, lookback=10))
    res = trainer.fit(params, train_ds, val_ds)
    fl = res.histories["avg_forecasting_loss"]
    assert fl[-1] < fl[0]
    assert res.best_it > 0
    est = np.asarray(model.gc(res.params)[0])
    truth = (graphs[0].sum(axis=2) > 0).astype(int)
    auc = roc_auc(truth.ravel(), est.ravel())
    assert auc > 0.85, f"ROC-AUC {auc} too close to chance"
