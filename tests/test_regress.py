"""Regression sentinel (redcliff_tpu/obs/regress.py, ISSUE 8):

* QUIET on the real BENCH_r01-r05 trajectory — every round judged against
  its predecessors with the documented noise bands flags nothing (the
  container's measured ±25% dispatch noise and the 1-ulp width-rounding
  caveat are exactly why the bands are shaped the way they are);
* LOUD on an injected synthetic slowdown;
* platform / grid-size gating, min-prior-samples, dispersion widening,
  absolute timing floors, improvement reporting;
* the block is schema-valid and bench.py embeds it into every payload.

Host-side only (no jax backend) — milliseconds.
"""
import copy
import json
import os
import sys

from redcliff_tpu.obs import regress, schema

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cpu_payload(value, rnd=None, **over):
    p = {"metric": "m", "value": value, "unit": "w/s", "platform": "cpu",
         "grid_points": 16, "vs_baseline": 0.8}
    p.update(over)
    return p


def _traj(*payloads):
    return [{"round": i + 1, "path": f"r{i+1}", "payload": p}
            for i, p in enumerate(payloads)]


def test_real_trajectory_stays_quiet():
    """Each real BENCH round judged against its predecessors: clean.
    (r01/r05 have unrecoverable payloads — skipped, not fatal.)"""
    traj = regress.load_trajectory(REPO)
    assert [r["round"] for r in traj] == [1, 2, 3, 4, 5]
    usable = [r for r in traj if r["payload"] is not None]
    assert len(usable) >= 3  # r02-r04 parse today; more is fine
    for i, r in enumerate(traj):
        if r["payload"] is None:
            continue
        block = regress.run_sentinel(r["payload"], trajectory=traj[:i],
                                     bench_dir=REPO)
        assert block["regressions"] == [], (r["round"], block["regressions"])
        assert not schema.validate_record(block)
    # the full-history judgment of the newest usable round is quiet too
    # and actually judged something
    block = regress.run_sentinel(usable[-1]["payload"], trajectory=traj,
                                 bench_dir=REPO)
    assert block["regressions"] == []
    assert block["families_checked"] >= 2
    assert block["current_round"] == usable[-1]["round"]


def test_injected_slowdown_flags():
    base = [r["payload"] for r in regress.load_trajectory(REPO)
            if r["payload"] is not None]
    slow = copy.deepcopy(base[-1])
    slow["value"] = base[-1]["value"] * 0.4  # 60% headline collapse
    block = regress.run_sentinel(slow, trajectory=_traj(*base))
    flagged = {r["metric"] for r in block["regressions"]}
    assert "value" in flagged
    [v] = [r for r in block["regressions"] if r["metric"] == "value"]
    assert v["change_pct"] < -35 and v["direction"] == "higher"
    assert len(v["priors"]) >= 2


def test_min_prior_samples_and_platform_gating():
    cur = _cpu_payload(100.0)
    # one prior only -> skipped, not judged
    block = regress.run_sentinel(cur, trajectory=_traj(_cpu_payload(300.0)))
    assert block["regressions"] == [] and block["families_checked"] == 0
    assert any(s["metric"] == "value" for s in block["skipped"])
    # two priors on ANOTHER platform -> still skipped
    tpu = _cpu_payload(300.0, platform="tpu")
    block = regress.run_sentinel(cur, trajectory=_traj(tpu, tpu))
    assert block["families_checked"] == 0
    # two same-platform priors -> flagged
    block = regress.run_sentinel(
        cur, trajectory=_traj(_cpu_payload(300.0), _cpu_payload(310.0)))
    assert [r["metric"] for r in block["regressions"]] == ["value"]


def test_live_fallback_samples_join_the_trajectory():
    """A cached-TPU headline's CPU live_fallback leg keeps the CPU
    trajectory comparable."""
    cached = {"metric": "m", "value": 999.0, "platform": "tpu",
              "grid_points": 64, "cached": True,
              "live_fallback": _cpu_payload(300.0)}
    block = regress.run_sentinel(
        _cpu_payload(100.0),
        trajectory=_traj(cached, _cpu_payload(310.0)))
    assert [r["metric"] for r in block["regressions"]] == ["value"]


def test_current_live_fallback_leg_is_judged():
    """A cached-TPU headline must not shield the round's FRESH CPU
    measurement: the current live_fallback leg is judged against the CPU
    trajectory too."""
    cur = {"metric": "m", "value": 999.0, "platform": "tpu",
           "grid_points": 64, "cached": True,
           "live_fallback": _cpu_payload(100.0)}
    block = regress.run_sentinel(
        cur, trajectory=_traj(_cpu_payload(300.0), _cpu_payload(310.0)))
    [r] = block["regressions"]
    assert r["metric"] == "value" and r["sample"] == "live_fallback"
    # a healthy fallback leg stays quiet
    cur["live_fallback"] = _cpu_payload(305.0)
    assert regress.run_sentinel(
        cur, trajectory=_traj(_cpu_payload(300.0),
                              _cpu_payload(310.0)))["regressions"] == []


def test_dispersion_widens_band():
    """History noisier than the default band raises the bar: priors
    spanning 2x forgive a drop the default ±35% band would flag."""
    cur = _cpu_payload(95.0)
    block = regress.run_sentinel(
        cur, trajectory=_traj(_cpu_payload(100.0), _cpu_payload(200.0)))
    assert block["regressions"] == []


def test_lower_better_families_and_abs_floor():
    mk = lambda warm: _cpu_payload(
        100.0, compile_cache={"warm_compile_ms": warm})
    # regression: warm retrieval cost tripled, well above the 100ms floor
    block = regress.run_sentinel(
        mk(900.0), trajectory=_traj(mk(200.0), mk(210.0)))
    assert any(r["metric"] == "compile_cache.warm_compile_ms"
               for r in block["regressions"])
    # same ratio below the absolute floor: timing dust, quiet
    block = regress.run_sentinel(
        mk(9.0), trajectory=_traj(mk(2.0), mk(2.1)))
    assert block["regressions"] == []
    # obs_overhead_pct: the <=2% contract is the floor — 0.01 -> 0.2 is
    # quiet, a breach past 2% flags
    mo = lambda pct: _cpu_payload(100.0, obs_overhead_pct=pct)
    assert regress.run_sentinel(
        mo(0.2), trajectory=_traj(mo(0.01), mo(0.02)))["regressions"] == []
    block = regress.run_sentinel(
        mo(3.5), trajectory=_traj(mo(0.01), mo(0.02)))
    assert any(r["metric"] == "obs_overhead_pct"
               for r in block["regressions"])
    # the <=2% ceiling is ABSOLUTE: a breach flags even when the relative
    # change vs (already-high) priors sits inside the noise band — and
    # even with too few priors for a relative judgment
    block = regress.run_sentinel(
        mo(2.6), trajectory=_traj(mo(1.8), mo(1.9)))
    [r] = [r for r in block["regressions"]
           if r["metric"] == "obs_overhead_pct"]
    assert r.get("contract") and r["baseline_median"] == 2.0
    assert regress.run_sentinel(
        mo(2.6), trajectory=[])["regressions"]


def test_improvements_reported_not_fatal():
    cur = _cpu_payload(300.0)
    block = regress.run_sentinel(
        cur, trajectory=_traj(_cpu_payload(100.0), _cpu_payload(110.0)))
    assert block["regressions"] == []
    assert any(r["metric"] == "value" for r in block["improvements"])


def test_tpu_cache_provenance_surfaces():
    tc = regress.load_tpu_cache_provenance(REPO)
    assert tc is not None and tc["platform"] == "tpu"
    assert tc["measured_at"] and tc["value"]
    # the dated real-TPU pallas prox parity evidence rides along
    assert tc["pallas_prox_max_abs_err"] == 5e-07
    block = regress.run_sentinel(_cpu_payload(1.0), trajectory=[],
                                 bench_dir=REPO)
    assert block["tpu_cache"]["measured_at"] == tc["measured_at"]


def test_cli_and_module_entry(capsys):
    rc = regress.main(["--bench-dir", REPO, "--json"])
    assert rc == 0  # the real trajectory is clean
    block = json.loads(capsys.readouterr().out)
    assert block["event"] == "regression" and block["regressions"] == []
    assert not schema.validate_record(block)
    rc = regress.main(["--bench-dir", REPO])
    assert "clean" in capsys.readouterr().out
    assert rc == 0


def test_cli_current_without_recoverable_payload_exits_2(tmp_path, capsys):
    """A CI gate pointing --current at an unusable artifact must fail
    loudly (exit 2), not report 'clean' while judging nothing."""
    art = tmp_path / "busted.json"
    art.write_text(json.dumps({"n": 9, "rc": 1, "tail": "no json here"}))
    assert regress.main(["--bench-dir", REPO, "--current",
                         str(art)]) == 2
    assert "no bench payload recoverable" in capsys.readouterr().err
    assert regress.main(["--current", str(tmp_path / "missing.json")]) == 2


def test_bench_attaches_regressions_block():
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    payload = _cpu_payload(1.0, metric=bench.METRIC)
    out = bench._attach_regressions(payload)
    assert isinstance(out["regressions"], list)  # empty list = clean is
    #                                              the recorded contract
    assert "rounds_compared" in out["regression_sentinel"]
