"""Numerics sentinel suite: in-graph non-finite guards, divergence rollback,
input contracts, strict-JSON metrics, and the no-raw-pickle-checkpoint scan.

Acceptance battery for runtime/numerics.py and its wiring through the
trainers, the grid engine, and the data layer:

* a fault-injected NaN batch mid-fit is skipped in-graph and the final
  params are BIT-IDENTICAL to a clean run minus that batch (skip semantics);
* an injected gradient blowup triggers checkpoint rollback + learning-rate
  backoff, visible as a ``numerics`` event in metrics.jsonl;
* an all-NaN validation fit aborts with a recorded cause instead of burning
  max_iter;
* grid lane quarantine records its cause (nonfinite_grad vs nonfinite_val);
* datasets enforce shape/dtype/finite input contracts with quarantine counts;
* metrics.jsonl is strict JSON (non-finite floats -> null);
* no raw pickle.dump checkpoint write exists outside runtime/checkpoint.py.

All CPU — no accelerator needed.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from redcliff_tpu.data.datasets import ArrayDataset, InputContractError
from redcliff_tpu.runtime import numerics
from redcliff_tpu.runtime import checkpoint as rck
from redcliff_tpu.runtime.numerics import (DivergenceMonitor, NumericsPolicy,
                                           guarded_update,
                                           init_numerics_state,
                                           numerics_summary,
                                           scale_learning_rate)
from redcliff_tpu.utils.observability import read_jsonl

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# in-graph guard unit tests
# ---------------------------------------------------------------------------
def _apply_add_one(tree):
    return jax.tree.map(lambda x: x + 1.0, tree)


def test_guarded_update_applies_when_finite():
    ns = init_numerics_state()
    tree = {"w": jnp.zeros(3)}
    grads = {"w": jnp.ones(3)}
    new, ns, ok = jax.jit(
        lambda t, g, n: guarded_update(t, g, jnp.float32(1.0),
                                       _apply_add_one, n))(tree, grads, ns)
    assert bool(ok)
    np.testing.assert_array_equal(np.asarray(new["w"]), np.ones(3))
    s = numerics_summary(ns)
    assert s["skipped"] == 0 and s["consecutive"] == 0 and s["checked"] == 1
    assert s["grad_norm_last"] == pytest.approx(np.sqrt(3.0))


@pytest.mark.parametrize("loss,gradval", [
    (np.nan, 1.0),      # non-finite loss
    (1.0, np.nan),      # NaN gradient leaf
    (1.0, np.inf),      # inf gradient leaf
])
def test_guarded_update_skips_poisoned_step(loss, gradval):
    ns = init_numerics_state()
    tree = {"w": jnp.zeros(3)}
    grads = {"w": jnp.full(3, gradval)}
    new, ns, ok = guarded_update(tree, grads, jnp.float32(loss),
                                 _apply_add_one, ns)
    assert not bool(ok)
    # the update was skipped: params pass through bit-identical
    np.testing.assert_array_equal(np.asarray(new["w"]), np.zeros(3))
    s = numerics_summary(ns)
    assert s["skipped"] == 1 and s["consecutive"] == 1


def test_consecutive_counter_resets_on_good_step():
    ns = init_numerics_state()
    tree = {"w": jnp.zeros(1)}
    bad = {"w": jnp.full(1, np.nan)}
    good = {"w": jnp.ones(1)}
    tree, ns, _ = guarded_update(tree, bad, jnp.float32(1.0), _apply_add_one, ns)
    tree, ns, _ = guarded_update(tree, bad, jnp.float32(1.0), _apply_add_one, ns)
    assert numerics_summary(ns)["consecutive"] == 2
    tree, ns, _ = guarded_update(tree, good, jnp.float32(1.0), _apply_add_one, ns)
    s = numerics_summary(ns)
    assert s["consecutive"] == 0 and s["skipped"] == 2 and s["checked"] == 3


def test_scale_learning_rate_walks_injected_state():
    opt = optax.inject_hyperparams(optax.adam)(learning_rate=1e-3)
    state = opt.init({"w": jnp.zeros(3)})
    scaled = scale_learning_rate(state, 0.5)
    assert float(scaled.hyperparams["learning_rate"]) == pytest.approx(5e-4)
    # untouched trees pass through
    assert numerics.current_learning_rates(scaled) == [pytest.approx(5e-4)]
    plain = optax.adam(1e-3).init({"w": jnp.zeros(3)})
    assert numerics.current_learning_rates(
        scale_learning_rate(plain, 0.5)) == []


# ---------------------------------------------------------------------------
# DivergenceMonitor policy unit tests
# ---------------------------------------------------------------------------
def test_monitor_rolls_back_on_criteria_blowup():
    mon = DivergenceMonitor(NumericsPolicy(divergence_factor=10.0))
    clean = {"skipped": 0, "consecutive": 0}
    assert mon.check(0, clean, 1.0).kind == "ok"
    mon.note_good(0, {"w": jnp.ones(2)})
    assert mon.check(1, clean, 0.9).kind == "ok"
    mon.note_good(1, {"w": jnp.full(2, 2.0)})
    action = mon.check(2, clean, 1e6)
    assert action.kind == "rollback" and action.cause == "divergence"
    restored = mon.rollback()
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.full(2, 2.0))
    assert mon.lr_scale == pytest.approx(0.5)


def test_rollback_lr_backoff_compounds_without_new_snapshot():
    """Repeated rollbacks of the SAME snapshot must deepen the backoff
    (0.5x, 0.25x, ...), not reset to the snapshot's original rate; a new
    snapshot embedding an already-backed-off rate must not double-count."""
    opt = optax.inject_hyperparams(optax.adam)(learning_rate=1e-2)
    state = opt.init({"w": jnp.zeros(2)})
    mon = DivergenceMonitor(NumericsPolicy(max_rollbacks=5, lr_backoff=0.5))
    mon.note_good(0, {"opt": state})
    r1 = mon.rollback()
    assert numerics.current_learning_rates(r1) == [pytest.approx(5e-3)]
    r2 = mon.rollback()
    assert numerics.current_learning_rates(r2) == [pytest.approx(2.5e-3)]
    mon.note_good(1, r2)  # fresh snapshot at the backed-off rate
    r3 = mon.rollback()
    assert numerics.current_learning_rates(r3) == [pytest.approx(1.25e-3)]


def test_monitor_near_zero_best_tolerates_noise():
    """A well-converged fit (best ~ 0) must not turn routine noise into a
    spurious divergence: the threshold has an absolute floor."""
    mon = DivergenceMonitor(NumericsPolicy(divergence_factor=10.0,
                                           divergence_atol=1e-2))
    clean = {"skipped": 0, "consecutive": 0}
    mon.check(0, clean, 1e-6)
    mon.note_good(0, {"w": jnp.zeros(1)})
    # 5e-5 >> 10 x best, but far under the atol-floored threshold
    assert mon.check(1, clean, 5e-5).kind == "ok"
    # a genuine blow-up still trips it
    assert mon.check(2, clean, 1.0).kind == "rollback"


def test_monitor_rollback_budget_exhaustion_aborts():
    mon = DivergenceMonitor(NumericsPolicy(max_rollbacks=1))
    clean = {"skipped": 0, "consecutive": 0}
    mon.check(0, clean, 1.0)
    mon.note_good(0, {"w": jnp.zeros(1)})
    assert mon.check(1, clean, 1e9).kind == "rollback"
    mon.rollback()
    assert mon.check(2, clean, 1e9).kind == "abort"


def test_monitor_consecutive_skips_without_snapshot_aborts():
    mon = DivergenceMonitor(NumericsPolicy(max_consecutive_skips=3))
    action = mon.check(0, {"skipped": 3, "consecutive": 3}, np.nan)
    assert action.kind == "abort" and action.cause == "nonfinite_grad"


def test_monitor_all_nonfinite_validation_aborts():
    mon = DivergenceMonitor(NumericsPolicy(max_nonfinite_epochs=3))
    clean = {"skipped": 0, "consecutive": 0}
    assert mon.check(0, clean, np.nan).kind == "ok"
    assert mon.check(1, clean, np.nan).kind == "ok"
    action = mon.check(2, clean, np.nan)
    assert action.kind == "abort"
    assert action.cause == "all_nonfinite_validation"


# ---------------------------------------------------------------------------
# trainer integration: fault-injected NaN batch / gradient blowup
# ---------------------------------------------------------------------------
def _tiny_trainer(max_iter=4, **cfg_kw):
    from redcliff_tpu.models.cmlp_fm import CMLPFM, CMLPFMConfig
    from redcliff_tpu.train.trainer import TrainConfig, Trainer

    model = CMLPFM(CMLPFMConfig(num_chans=3, gen_lag=2, gen_hidden=(8,),
                                input_length=6, forecast_coeff=1.0,
                                adj_l1_coeff=1e-3))
    trainer = Trainer(model, TrainConfig(learning_rate=1e-2, max_iter=max_iter,
                                         batch_size=16, check_every=1,
                                         **cfg_kw))
    rng = np.random.default_rng(7)
    X = rng.normal(size=(48, 12, 3)).astype(np.float32)
    ds = ArrayDataset(X, None)  # 3 steps/epoch at batch_size=16
    params = model.init(jax.random.PRNGKey(0))
    return trainer, params, ds


def test_nan_batch_skip_semantics_bit_identical(tmp_path, monkeypatch):
    """A guarded fit with a NaN batch injected at step 4 must end bit-identical
    to a clean fit that skips exactly that update — the guard's skip IS the
    reference semantics, and the poison never touches params."""
    trainer, params, ds = _tiny_trainer()

    monkeypatch.setenv("REDCLIFF_FAULT_INJECT", "nan_batch:4")
    poisoned = trainer.fit(params, ds, ds, save_dir=str(tmp_path / "poisoned"))

    monkeypatch.setenv("REDCLIFF_FAULT_INJECT", "skip_update:4")
    reference = trainer.fit(params, ds, ds, save_dir=str(tmp_path / "ref"))

    for a, b in zip(jax.tree.leaves(poisoned.params),
                    jax.tree.leaves(reference.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.isfinite(np.asarray(jax.tree.leaves(poisoned.params)[0])).all()
    assert poisoned.aborted is None

    # the skip surfaced as an anomaly event with the step count
    anomalies = read_jsonl(str(tmp_path / "poisoned"), event="anomaly")
    assert len(anomalies) == 1
    assert anomalies[0]["cause"] == "nonfinite_grad"
    assert anomalies[0]["epoch_skipped_steps"] == 1
    assert not read_jsonl(str(tmp_path / "ref"), event="anomaly")


def test_grad_blowup_triggers_rollback_and_lr_backoff(tmp_path, monkeypatch):
    """An entire epoch of exploding gradients (steps 6-8 = epoch 2) trips the
    consecutive-skip threshold: the monitor restores the epoch-1 snapshot and
    halves the learning rate, all recorded as a ``numerics`` event."""
    trainer, params, ds = _tiny_trainer(
        max_iter=5, numerics=NumericsPolicy(max_consecutive_skips=3,
                                            lr_backoff=0.5))
    monkeypatch.setenv("REDCLIFF_FAULT_INJECT", "grad_blowup:6-8")
    res = trainer.fit(params, ds, ds, save_dir=str(tmp_path))

    assert res.aborted is None
    for leaf in jax.tree.leaves(res.params):
        assert np.isfinite(np.asarray(leaf)).all()

    events = read_jsonl(str(tmp_path), event="numerics")
    rollbacks = [e for e in events if e["kind"] == "rollback"]
    assert len(rollbacks) == 1
    rb = rollbacks[0]
    assert rb["cause"] == "nonfinite_grad"
    assert rb["epoch"] == 2 and rb["restored_epoch"] == 1
    assert rb["lr_scale"] == pytest.approx(0.5)
    assert rb["learning_rates"] == [pytest.approx(5e-3)]  # 1e-2 backed off
    assert rb["rollbacks"] == 1
    # the poisoned epoch also logged its skipped steps
    anomalies = read_jsonl(str(tmp_path), event="anomaly")
    assert anomalies and anomalies[0]["epoch_skipped_steps"] == 3


class _NaNCriteriaModel:
    """Finite loss, but a validation criteria that is always NaN — the
    all-NaN stall that used to burn max_iter (best_it never set)."""

    def __init__(self):
        from redcliff_tpu.models.cmlp_fm import CMLPFM, CMLPFMConfig

        self._inner = CMLPFM(CMLPFMConfig(num_chans=3, gen_lag=2,
                                          gen_hidden=(4,), input_length=6))
        self.config = self._inner.config

    def init(self, key):
        return self._inner.init(key)

    def loss(self, params, X, Y=None):
        return self._inner.loss(params, X)

    def gc(self, params, **kw):
        return self._inner.gc(params, **kw)

    def validation_criteria(self, params, val):
        return float("nan")


def test_all_nan_validation_aborts_with_recorded_cause(tmp_path):
    from redcliff_tpu.train.trainer import TrainConfig, Trainer

    model = _NaNCriteriaModel()
    trainer = Trainer(model, TrainConfig(
        learning_rate=1e-3, max_iter=50, batch_size=16, check_every=1,
        numerics=NumericsPolicy(max_nonfinite_epochs=3)))
    rng = np.random.default_rng(3)
    ds = ArrayDataset(rng.normal(size=(32, 12, 3)).astype(np.float32), None)
    params = model.init(jax.random.PRNGKey(1))
    res = trainer.fit(params, ds, ds, save_dir=str(tmp_path))

    assert res.aborted == "all_nonfinite_validation"
    # the fit stopped at the abort threshold, nowhere near max_iter
    epochs = read_jsonl(str(tmp_path), event="epoch")
    assert len(epochs) == 3
    aborts = read_jsonl(str(tmp_path), event="numerics")
    assert aborts[-1]["kind"] == "abort"
    assert aborts[-1]["cause"] == "all_nonfinite_validation"
    # strict JSON: the NaN criteria serialized as null
    assert all(e["criteria"] is None for e in epochs)


# ---------------------------------------------------------------------------
# grid lane quarantine cause
# ---------------------------------------------------------------------------
def test_grid_lane_quarantine_records_grad_cause():
    from redcliff_tpu.runtime.faultinject import tiny_grid_fit

    res = tiny_grid_fit(None, max_iter=3, bad_point=True)
    assert [f["point"] for f in res.failures] == [1]
    # the poisoned-lr lane exploded through its own gradients: the in-graph
    # guard observed the non-finite steps, so the cause is attributed to them
    assert res.failures[0]["cause"] == "nonfinite_grad"
    assert res.active[0] and not res.active[1]


# ---------------------------------------------------------------------------
# durable trainer checkpoints (the torn-write hole, both trainers)
# ---------------------------------------------------------------------------
def test_trainer_checkpoints_are_durable_format(tmp_path):
    trainer, params, ds = _tiny_trainer(max_iter=2)
    trainer.fit(params, ds, ds, save_dir=str(tmp_path))
    for name in ("final_best_model.bin", "trainer_checkpoint.pkl",
                 "training_meta_data_and_hyper_parameters.pkl"):
        with open(tmp_path / name, "rb") as f:
            assert f.read(4) == b"RTCK", f"{name} is not a durable checkpoint"


def test_trainer_resume_survives_torn_checkpoint(tmp_path):
    """Truncating the checkpoint head (torn write) must fall back to the
    .prev generation with a quarantine warning — not crash, not restart."""
    from redcliff_tpu.runtime.faultinject import corrupt_checkpoint

    trainer, params, ds = _tiny_trainer(max_iter=3)
    trainer.fit(params, ds, ds, save_dir=str(tmp_path))
    head = str(tmp_path / "trainer_checkpoint.pkl")
    corrupt_checkpoint(head, "truncate")
    with pytest.warns(RuntimeWarning, match="quarantined"):
        res = trainer.fit(params, ds, ds, save_dir=str(tmp_path), resume=True)
    assert os.path.exists(head + ".bad")
    assert res.aborted is None


def test_redcliff_trainer_checkpoints_are_durable_format(tmp_path):
    from redcliff_tpu.models.redcliff import (RedcliffSCMLP,
                                              RedcliffSCMLPConfig)
    from redcliff_tpu.train.redcliff_trainer import (RedcliffTrainConfig,
                                                     RedcliffTrainer)

    model = RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=4, gen_lag=2, gen_hidden=(8,), embed_lag=4,
        embed_hidden_sizes=(8,), num_factors=2, num_supervised_factors=2,
        factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive", num_sims=1,
        training_mode="combined"))
    tc = RedcliffTrainConfig(max_iter=2, batch_size=16, check_every=1)
    trainer = RedcliffTrainer(model, tc)
    rng = np.random.default_rng(0)
    cfg = model.config
    T = cfg.max_lag + cfg.num_sims
    X = rng.normal(size=(32, T, cfg.num_chans)).astype(np.float32)
    Y = rng.uniform(size=(32, 3, 1)).astype(np.float32)
    ds = ArrayDataset(X, Y)
    params = model.init(jax.random.PRNGKey(2))
    res = trainer.fit(params, ds, ds, save_dir=str(tmp_path))
    assert res.aborted is None
    for name in ("final_best_model.bin", "trainer_checkpoint.pkl",
                 "training_meta_data_and_hyper_parameters.pkl"):
        with open(tmp_path / name, "rb") as f:
            assert f.read(4) == b"RTCK", f"{name} is not a durable checkpoint"


def test_trainer_resumes_pre_inject_hyperparams_checkpoint(tmp_path):
    """A checkpoint written before the optimizer switched to
    inject_hyperparams holds a bare adam state; resume must wrap it (with
    the configured learning rate) instead of crashing in update()."""
    import pickle

    trainer, params, ds = _tiny_trainer(max_iter=2)
    trainer.fit(params, ds, ds, save_dir=str(tmp_path))
    ck = rck.read_checkpoint(str(tmp_path / "trainer_checkpoint.pkl"))
    # strip the inject wrapper AND the durable header: the legacy layout
    assert hasattr(ck["opt_state"], "inner_state")
    ck["opt_state"] = ck["opt_state"].inner_state
    with open(tmp_path / "trainer_checkpoint.pkl", "wb") as f:
        pickle.dump(ck, f)
    os.remove(tmp_path / "trainer_checkpoint.pkl.prev")

    trainer2, _, _ = _tiny_trainer(max_iter=4)
    res = trainer2.fit(params, ds, ds, save_dir=str(tmp_path), resume=True)
    assert res.aborted is None
    for leaf in jax.tree.leaves(res.params):
        assert np.isfinite(np.asarray(leaf)).all()


def test_grid_resume_rejects_changed_numerics_policy(tmp_path):
    """The numerics guard gates every grid update, so resuming under a
    different policy must be rejected by the fingerprint, not silently
    train different semantics."""
    import dataclasses

    from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig
    from redcliff_tpu.models.redcliff import (RedcliffSCMLP,
                                              RedcliffSCMLPConfig)

    model = RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=4, gen_lag=2, gen_hidden=(8,), embed_lag=4,
        embed_hidden_sizes=(8,), num_factors=2, num_supervised_factors=2,
        factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive", num_sims=1,
        training_mode="combined"))
    rng = np.random.default_rng(0)
    T = model.config.max_lag + model.config.num_sims
    ds = ArrayDataset(rng.normal(size=(32, T, 4)).astype(np.float32),
                      rng.uniform(size=(32, 3, 1)).astype(np.float32))
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 3e-3}])
    tc = RedcliffTrainConfig(max_iter=2, batch_size=16, check_every=1)
    ck = str(tmp_path / "ck")
    RedcliffGridRunner(model, tc, spec).fit(
        jax.random.PRNGKey(0), ds, ds, checkpoint_dir=ck, checkpoint_every=1)
    tc2 = dataclasses.replace(
        tc, numerics=NumericsPolicy(max_consecutive_skips=7))
    with pytest.raises(ValueError, match="numerics"):
        RedcliffGridRunner(model, tc2, spec).fit(
            jax.random.PRNGKey(0), ds, ds, checkpoint_dir=ck,
            checkpoint_every=1)


def test_grid_resume_accepts_pre_sentinel_checkpoint_under_default_policy(
        tmp_path):
    """A grid checkpoint written before the sentinel (no numerics
    fingerprint, no per-lane counters) must still resume under the DEFAULT
    policy — the guard doesn't change healthy-lane math — with the sentinel
    state backfilled."""
    import jax as _jax

    from redcliff_tpu.models.redcliff import (RedcliffSCMLP,
                                              RedcliffSCMLPConfig)
    from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig

    model = RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=4, gen_lag=2, gen_hidden=(8,), embed_lag=4,
        embed_hidden_sizes=(8,), num_factors=2, num_supervised_factors=2,
        factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive", num_sims=1,
        training_mode="combined"))
    rng = np.random.default_rng(0)
    T = model.config.max_lag + model.config.num_sims
    ds = ArrayDataset(rng.normal(size=(32, T, 4)).astype(np.float32),
                      rng.uniform(size=(32, 3, 1)).astype(np.float32))
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 3e-3}])
    tc = RedcliffTrainConfig(max_iter=3, batch_size=16, check_every=1)
    ck = str(tmp_path / "ck")
    RedcliffGridRunner(model, tc, spec).fit(
        _jax.random.PRNGKey(0), ds, ds, max_iter=2, checkpoint_dir=ck,
        checkpoint_every=1)
    # rewrite the checkpoint as a pre-sentinel one: drop the numerics
    # fingerprint and the per-lane sentinel state
    path = os.path.join(ck, "grid_checkpoint.pkl")
    blob = rck.read_checkpoint(path)
    del blob["meta"]["numerics"]
    del blob["nstate"]
    del blob["failed_cause"]
    rck.write_checkpoint(path, blob)
    res = RedcliffGridRunner(model, tc, spec).fit(
        _jax.random.PRNGKey(0), ds, ds, checkpoint_dir=ck,
        checkpoint_every=1)
    assert res.val_history.shape[0] == 3  # resumed epoch 2, not rejected


# ---------------------------------------------------------------------------
# data input contracts
# ---------------------------------------------------------------------------
def test_dataset_quarantines_nonfinite_samples():
    X = np.ones((6, 4, 2), dtype=np.float32)
    X[1, 0, 0] = np.nan
    X[4, 3, 1] = np.inf
    with pytest.warns(RuntimeWarning, match="quarantined 2/6"):
        ds = ArrayDataset(X, None)
    assert ds.quarantined_samples == 2
    assert len(ds) == 4
    # quarantine ran BEFORE normalization stats: clean samples stay finite
    assert np.isfinite(ds.X).all()


def test_dataset_quarantines_nonfinite_labels():
    X = np.ones((4, 3, 2), dtype=np.float32)
    Y = np.ones((4, 2), dtype=np.float32)
    Y[2, 1] = np.nan
    with pytest.warns(RuntimeWarning, match="quarantined 1/4"):
        ds = ArrayDataset(X, Y)
    assert ds.quarantined_samples == 1 and len(ds) == 3


def test_dataset_shape_contract():
    with pytest.raises(InputContractError, match="num_samples"):
        ArrayDataset(np.ones((4, 6), dtype=np.float32))


def test_dataset_ragged_input_contract():
    ragged = np.empty(2, dtype=object)
    ragged[0] = np.ones((3, 2))
    ragged[1] = np.ones((4, 2))
    with pytest.raises(InputContractError, match="object array"):
        ArrayDataset(ragged)


def test_dataset_label_length_contract():
    with pytest.raises(InputContractError, match="label length"):
        ArrayDataset(np.ones((4, 3, 2), dtype=np.float32),
                     np.ones((3, 2), dtype=np.float32))


def test_dataset_contract_escape_hatch():
    # contract=False restores permissive construction for exotic callers
    ds = ArrayDataset(np.ones((4, 6), dtype=np.float32), contract=False,
                      normalize=False)
    assert ds.X.shape == (4, 6)


def test_shard_loader_reports_quarantine(tmp_path):
    import pickle

    from redcliff_tpu.data.shards import load_shard_samples

    good = np.ones((5, 2), dtype=np.float32)
    bad = good.copy()
    bad[0, 0] = np.inf
    split = tmp_path / "train"
    os.makedirs(split)
    with open(split / "subset_0.pkl", "wb") as f:
        pickle.dump([[good, np.ones(1)], [bad, np.ones(1)],
                     [good, np.ones(1)]], f)
    report = {}
    with pytest.warns(RuntimeWarning, match="quarantined 1"):
        samples = load_shard_samples(str(split), report=report)
    assert len(samples) == 2
    assert report["quarantined"] == 1 and report["loaded"] == 2
    assert report["quarantined_by_file"] == {"subset_0.pkl": 1}


# ---------------------------------------------------------------------------
# strict-JSON metrics round trip
# ---------------------------------------------------------------------------
def test_jsonable_maps_nonfinite_to_null_strict_roundtrip(tmp_path):
    from redcliff_tpu.utils.observability import MetricLogger

    path = str(tmp_path / "metrics.jsonl")
    with MetricLogger(path) as logger:
        logger.log("epoch", epoch=0, criteria=float("nan"),
                   loss=np.float32(np.inf),
                   history=[1.0, float("-inf"), 2.0],
                   arr=np.asarray([np.nan, 3.0]),
                   nested={"v": np.float64("nan")})

    def _no_constants(name):
        raise AssertionError(f"non-strict JSON token {name!r} in metrics")

    with open(path) as f:
        for line in f:
            json.loads(line, parse_constant=_no_constants)

    [rec] = read_jsonl(path, event="epoch")
    assert rec["criteria"] is None
    assert rec["loss"] is None
    assert rec["history"] == [1.0, None, 2.0]
    assert rec["arr"] == [None, 3.0]
    assert rec["nested"]["v"] is None


# ---------------------------------------------------------------------------
# CI guard: no raw pickle checkpoint writes outside runtime/checkpoint.py
# ---------------------------------------------------------------------------
CHECKPOINT_ARTIFACT_NAMES = (
    "final_best_model",
    "training_meta_data_and_hyper_parameters",
    "trainer_checkpoint",
    "grid_checkpoint",
    "best_model_name",
    "dCSFA-NMF-best-model",
)
# modules allowed to contain pickle.dump in the checkpoint-owning layers:
# checkpoint.py OWNS the durable format; faultinject.py writes a
# test-harness result blob (not a resume artifact)
PICKLE_DUMP_ALLOWLIST = {
    os.path.join("runtime", "checkpoint.py"),
    os.path.join("runtime", "faultinject.py"),
}


def _package_sources():
    pkg = os.path.join(REPO, "redcliff_tpu")
    for dirpath, _dirs, files in os.walk(pkg):
        for name in files:
            if name.endswith(".py"):
                full = os.path.join(dirpath, name)
                yield os.path.relpath(full, pkg), open(full).read()


def test_no_raw_pickle_dump_in_checkpoint_layers():
    """train/, parallel/ and runtime/ own checkpoint-shaped state; any
    pickle.dump there (outside the durable writer itself) is a regression
    toward non-durable checkpoints."""
    offenders = []
    for rel, src in _package_sources():
        top = rel.split(os.sep)[0]
        if top not in ("train", "parallel", "runtime"):
            continue
        if rel in PICKLE_DUMP_ALLOWLIST:
            continue
        if "pickle.dump" in src:
            offenders.append(rel)
    assert not offenders, (
        f"raw pickle.dump in checkpoint-owning modules {offenders}; route "
        f"checkpoint writes through runtime.checkpoint.write_checkpoint "
        f"(atomic + CRC + .prev) instead")


def test_no_pickle_dump_near_checkpoint_artifact_names():
    """Package-wide: a pickle.dump within a few lines of a checkpoint
    artifact name is a non-durable checkpoint write sneaking back in."""
    offenders = []
    for rel, src in _package_sources():
        if rel in PICKLE_DUMP_ALLOWLIST:
            continue
        lines = src.splitlines()
        for i, line in enumerate(lines):
            if "pickle.dump" not in line:
                continue
            window = "\n".join(lines[max(0, i - 8): i + 1])
            hits = [n for n in CHECKPOINT_ARTIFACT_NAMES if n in window]
            if hits:
                offenders.append((rel, i + 1, hits))
    assert not offenders, (
        f"raw pickle.dump writing checkpoint artifacts at {offenders}; use "
        f"runtime.checkpoint.write_checkpoint")
