"""Tests for the supervised-discovery (Table-2) evaluation stack."""
import numpy as np
import pytest

from redcliff_tpu.eval.supervised_discovery import (
    prepare_data_for_modeling,
    run_discovery_algorithm,
    run_supervised_discovery_evaluation,
    score_discovery_predictions,
    standardized_off_diagonal_predictions,
)


def _two_regime_samples(rng, num_windows=8, T=120, noise=0.25):
    """Windows alternating between two linear VAR regimes:
    regime 0 drives 0 -> 1, regime 1 drives 1 -> 2 (3 nodes)."""
    samples = []
    for w in range(num_windows):
        regime = w % 2
        X = np.zeros((T, 3))
        for t in range(1, T):
            for c in range(3):
                X[t, c] = 0.4 * X[t - 1, c] + rng.normal(scale=noise)
            if regime == 0:
                X[t, 1] += 0.7 * X[t - 1, 0]
            else:
                X[t, 2] += 0.7 * X[t - 1, 1]
        y = np.zeros((2, T))
        y[regime, :] = 1.0
        samples.append((X, y))
    return samples


def _true_graphs():
    """Ground truth in the eval's columns-drive-rows convention (predictions
    are transposed into it, ref TRANSPOSE_PREDICTIONS_DURING_EVAL :224)."""
    g0 = np.zeros((3, 3, 1))
    g0[1, 0, 0] = 1.0  # entry (target=1, source=0): node 0 drives node 1
    g1 = np.zeros((3, 3, 1))
    g1[2, 1, 0] = 1.0
    return [g0, g1]


def test_prepare_data_for_modeling_masks():
    rng = np.random.default_rng(0)
    samples = _two_regime_samples(rng, num_windows=4, T=50)
    data, labels, masks, Tw, Tt, N, R = prepare_data_for_modeling(samples)
    assert data.shape == (200, 3) and labels.shape == (200, 2)
    assert Tw == 50 and Tt == 200 and N == 3 and R == 2
    # alternating windows: regime 0 owns windows 0 and 2
    assert masks[0][:50].all() and not masks[0][50:100].any()
    assert masks[1][50:100].all()
    # masks partition every step
    total = masks[0] + masks[1]
    np.testing.assert_array_equal(total, np.ones_like(total))


def test_standardized_off_diagonal_predictions():
    A = np.arange(18, dtype=float).reshape(3, 3, 2)
    out = standardized_off_diagonal_predictions(A)
    assert out.shape == (3, 3)
    assert np.all(np.diag(out) == 0)
    out_t = standardized_off_diagonal_predictions(A, transpose=True)
    np.testing.assert_array_equal(out_t, (np.abs(A).sum(2).T
                                          * (1 - np.eye(3))))


@pytest.mark.parametrize("alg", ["slarac", "qrbs", "lasar", "selvar",
                                 "PCMCI"])
def test_run_discovery_algorithm_shapes(alg):
    rng = np.random.default_rng(1)
    samples = _two_regime_samples(rng, num_windows=4, T=60)
    # maxlags=None keeps each algorithm's reference default
    # (tidybench 1, PCMCI tau_max=2)
    preds = run_discovery_algorithm(samples, alg)
    assert len(preds) == 2
    for p in preds:
        assert p.shape == (3, 3)
        assert np.all(np.diag(p) == 0)
        assert np.isfinite(p).all()


def test_score_discovery_predictions_keys():
    rng = np.random.default_rng(2)
    true_graphs = [np.asarray(g.sum(axis=2) > 0, dtype=int)
                   for g in _true_graphs()]
    # perfect predictions in the transposed (column-drives-row) convention
    preds = [g.T + 0.01 * rng.uniform(size=(3, 3)) for g in true_graphs]
    stats = score_discovery_predictions(preds, true_graphs,
                                        transpose_predictions=True)
    for rf in ("rf_0", "rf_1"):
        e = stats[rf]
        assert e["optF1_score"] == pytest.approx(1.0)
        assert e["roc_auc"] == pytest.approx(1.0)
        assert "optF1Thresh_ancestor_aid" in e
        assert "upper_optF1Thresh_shd" in e
        assert "lower_optF1Thresh_parent_aid" in e
        # near-perfect thresholded mask: the strict '>' threshold may drop
        # the single edge sitting exactly at the optimal threshold (the
        # reference shares this quirk, mask = rf_pred > thresh at :327)
        assert e["optF1Thresh_shd"][1] <= 1
        assert e["optF1Thresh_parent_aid"][1] <= 2


def test_end_to_end_discovery_recovers_regimes():
    rng = np.random.default_rng(3)
    samples = _two_regime_samples(rng, num_windows=10, T=150)
    results = run_supervised_discovery_evaluation(
        samples, _true_graphs(), algorithms=("slarac", "PCMCI"))
    for alg in ("slarac", "PCMCI"):
        s = results[alg]["stats"]
        # each regime's driving edge should be recovered well above chance
        assert s["rf_0"]["optF1_score"] > 0.6, (alg, s["rf_0"])
        assert s["rf_1"]["optF1_score"] > 0.6, (alg, s["rf_1"])


def test_end_to_end_pickles_summary(tmp_path):
    rng = np.random.default_rng(4)
    samples = _two_regime_samples(rng, num_windows=4, T=60)
    run_supervised_discovery_evaluation(
        samples, _true_graphs(), algorithms=("selvar",), maxlags=1,
        save_path=str(tmp_path))
    import os
    assert os.path.isfile(tmp_path / "supervised_discovery_summary.pkl")
