"""Streaming inference service tests (redcliff_tpu/serve, ISSUE 17).

Pins the serve plane's contracts: the slot-table engine's O(1) ring advance
against a host sliding-window reference, per-stream NaN/shape quarantine
with BYTE-identical co-resident outputs (the churn-isolation pin, engine vs
engine at the same table shape), the lease/heartbeat session state machine
(LIFO slot recycling, reap-on-expiry, snapshot round-trip), the shared
admission taxonomy (SlotsExhausted reject-with-ETA; BackpressureReject
re-exported from its fleet home), the degraded-QoS cadence ladder with
hysteresis, slow-consumer containment (bounded out-queues, per-stream
drops), drain/resume zero-loss durability (the interrupted run's record
stream byte-matches the uninterrupted one), serve SLO knobs, and
schema-valid serve/session telemetry. The slow-marked soak runs the full
seeded chaos storm (churn + NaN + abandoned leases + slow consumers)
through chaos.churn_isolation_report.
"""
import numpy as np
import pytest

from redcliff_tpu.models.redcliff import RedcliffSCMLP, RedcliffSCMLPConfig
from redcliff_tpu.obs import read_jsonl, schema
from redcliff_tpu.obs import slo as SLO
from redcliff_tpu.runtime.admission import (AdmissionReject,
                                            BackpressureReject,
                                            SlotsExhausted)
from redcliff_tpu.serve import chaos
from redcliff_tpu.serve.engine import StreamEngine
from redcliff_tpu.serve.service import QOS_CADENCE, ServeService
from redcliff_tpu.serve.session import (ACTIVE, CLOSED, EXPIRED, QUARANTINED,
                                        SessionRegistry)

C = 4          # channels
L = 4          # embed_lag == ring length


def _model():
    return RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=C, gen_lag=2, gen_hidden=(8,), embed_lag=L,
        embed_hidden_sizes=(8,), num_factors=2, num_supervised_factors=2,
        factor_weight_l1_coeff=0.01, adj_l1_reg_coeff=0.001,
        factor_cos_sim_coeff=0.01,
        factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive", num_sims=1,
        training_mode="combined"))


@pytest.fixture(scope="module")
def fitted():
    import jax
    model = _model()
    return model, model.init(jax.random.PRNGKey(0))


def _service(fitted, capacity=3, root=None, lease_s=30.0, resume=True):
    model, params = fitted
    return ServeService(model, params, root=root, capacity=capacity,
                        lease_s=lease_s, resume=resume)


def _feed(svc, sid, samples, now0=0.0, dt=0.01, poll=True):
    """Tick-per-sample drive of one already-connected stream."""
    recs, now = [], now0
    for x in samples:
        now += dt
        svc.ingest(sid, x, now=now)
        svc.pump(now=now)
        if poll:
            recs.extend(svc.poll(sid, now=now))
    return recs


# ---------------------------------------------------------------- engine
def test_engine_ring_matches_sliding_window(fitted):
    """The O(1) ring advance must reproduce the O(window) host path: after
    each accepted sample the engine's readout equals the embedder applied
    to the host-assembled last-L sliding window (same (S, L, C) program
    shape; tight tolerance covers fusion-order differences)."""
    import jax.numpy as jnp
    model, params = fitted
    eng = StreamEngine(model, params, capacity=3)
    xs = chaos.stream_samples(3, 10, C)
    arrive = np.array([True, False, False])
    for i in range(len(xs)):
        batch = np.zeros((3, C), np.float32)
        batch[0] = xs[i]
        out = eng.step(batch, arrive)
        if i < L - 1:
            assert not out["ready"][0]
            continue
        assert out["ready"][0]
        win = np.zeros((3, L, C), np.float32)
        win[0] = xs[i - L + 1: i + 1]
        ref, _ = model._embed(params, jnp.asarray(win))
        np.testing.assert_allclose(out["scores"][0], np.asarray(ref)[0],
                                   rtol=1e-5, atol=1e-6)
        # per-sample graph is the weighting-blended static per-factor GC
        graph_ref = np.einsum("k,kij->ij", out["scores"][0],
                              np.asarray(eng.static_gc))
        np.testing.assert_allclose(out["graph"][0], graph_ref,
                                   rtol=1e-5, atol=1e-6)
    assert not out["ready"][1] and not out["ready"][2]


def test_engine_poison_latches_and_spares_ring(fitted):
    """A non-finite sample never reaches ring state: the lane latches
    ``poisoned``, the sample is discarded, and later finite samples are
    refused — while a co-resident lane's outputs stay byte-identical to a
    run where the poisoner never existed."""
    model, params = fitted
    xs = chaos.stream_samples(7, 8, C)
    bad = xs.copy()

    def run(poison):
        eng = StreamEngine(model, params, capacity=2)
        outs = []
        for i in range(len(xs)):
            batch = np.zeros((2, C), np.float32)
            batch[0] = xs[i]
            batch[1] = bad[i]
            if poison and i == 5:
                batch[1, 0] = np.nan
            out = eng.step(batch, np.array([True, poison]))
            outs.append(out)
        return outs

    clean = run(False)
    stormy = run(True)
    hit = stormy[5]
    assert hit["poison_hit"][1] and hit["poisoned"][1]
    assert not hit["ready"][1]
    # latched: the finite sample at tick 6 is refused too
    assert stormy[6]["poisoned"][1] and not stormy[6]["ready"][1]
    # the victim lane's bytes are untouched by its neighbor's poisoning
    for a, b in zip(clean, stormy):
        assert a["scores"][0].tobytes() == b["scores"][0].tobytes()
        assert a["graph"][0].tobytes() == b["graph"][0].tobytes()


def test_engine_import_state_refuses_geometry_mismatch(fitted):
    model, params = fitted
    eng = StreamEngine(model, params, capacity=2)
    snap = eng.export_state()
    other = StreamEngine(model, params, capacity=3)
    with pytest.raises(ValueError, match="geometry mismatch"):
        other.import_state(snap)


# ---------------------------------------------------------------- sessions
def test_session_registry_lifecycle():
    reg = SessionRegistry(capacity=2, lease_s=10.0)
    a = reg.connect(sid="a", now=0.0)
    b = reg.connect(sid="b", now=0.0)
    assert {a.slot, b.slot} == {0, 1} and reg.free_slots() == 0
    assert a.trace_id.startswith("tr-") and len(a.trace_id) == 19
    with pytest.raises(ValueError):
        reg.connect(sid="a", now=0.0)
    with pytest.raises(SlotsExhausted) as ei:
        reg.connect(now=4.0)
    assert ei.value.eta_s == pytest.approx(6.0)
    # LIFO recycling: the most recently freed slot is re-leased first
    reg.disconnect("a")
    assert a.state == CLOSED
    c = reg.connect(sid="c", now=1.0)
    assert c.slot == a.slot
    # heartbeat renews; silence expires at the next reap
    reg.heartbeat("b", now=8.0)
    dead = reg.reap(now=12.0)
    assert [s.sid for s in dead] == ["c"] and c.state == EXPIRED
    assert reg.get("b").state == ACTIVE
    # double-disconnect is a no-op, not an error
    assert reg.disconnect("c") is None


def test_session_snapshot_roundtrip_renews_leases():
    reg = SessionRegistry(capacity=3, lease_s=10.0)
    reg.connect(sid="a", now=0.0)
    reg.quarantine("a", "poison")
    reg.connect(sid="b", now=5.0)
    snap = reg.snapshot()
    back = SessionRegistry.from_snapshot(snap, now=100.0)
    assert {s.sid for s in back.live()} == {"a", "b"}
    assert back.get("a").state == QUARANTINED
    assert back.get("a").trace_id == reg.get("a").trace_id
    assert back.get("a").slot == reg.get("a").slot
    # resumed leases restart at the resume clock, not the dead server's
    assert back.get("b").lease_expires_at == pytest.approx(110.0)
    assert back.free_slots() == 1


def test_admission_taxonomy_is_shared():
    """Both planes raise the same typed family; the fleet re-export stays
    byte-compatible with its original home."""
    from redcliff_tpu.fleet.queue import BackpressureReject as FleetBP
    assert FleetBP is BackpressureReject
    bp = BackpressureReject("t0", 12.0, 5.0, 3, 1)
    assert isinstance(bp, AdmissionReject)
    assert bp.eta_s == 12.0 and bp.tenant == "t0"
    assert "REDCLIFF_BACKPRESSURE=0" in str(bp)
    se = SlotsExhausted(8, eta_s=3.5)
    assert isinstance(se, AdmissionReject)
    assert se.capacity == 8 and se.eta_s == 3.5
    assert "REDCLIFF_SERVE_SLOTS" in str(se)


# ---------------------------------------------------------------- service
def test_nan_quarantine_spares_siblings(fitted, tmp_path):
    """The headline fault-isolation contract: a stream that turns NaN is
    quarantined with a structured error record while its co-resident
    siblings answer EVERY sample with finite scores."""
    svc = _service(fitted, capacity=3, root=str(tmp_path))
    n = L + 6
    good = chaos.stream_samples(1, n, C)
    bad = chaos.stream_samples(2, n, C)
    bad[L + 2, 1] = np.nan
    svc.connect(sid="good", now=0.0)
    svc.connect(sid="bad", now=0.0)
    now, recs = 0.0, {"good": [], "bad": []}
    for i in range(n):
        now += 0.01
        svc.ingest("good", good[i], now=now)
        svc.ingest("bad", bad[i], now=now)
        svc.pump(now=now)
        for sid in recs:
            recs[sid].extend(svc.poll(sid, now=now))
    assert len(recs["good"]) == n - L + 1
    assert all(np.isfinite(r["scores"]).all() for r in recs["good"])
    assert [r["seq"] for r in recs["good"]] == list(range(1, n - L + 2))
    errs = [r for r in recs["bad"] if "error" in r]
    assert errs and "non-finite" in errs[0]["error"]
    sess = svc.registry.get("bad")
    assert sess.state == QUARANTINED
    # ingest after quarantine: structured refusal, never an exception
    v = svc.ingest("bad", bad[0], now=now)
    assert not v["accepted"] and "quarantined" in v["reason"]
    svc.stop()
    recs_log = read_jsonl(str(tmp_path))
    assert not schema.validate_records(recs_log)
    assert any(r["event"] == "session" and r.get("kind") == "quarantine"
               for r in recs_log)


def test_shape_violation_quarantines_host_side(fitted):
    svc = _service(fitted, capacity=2)
    svc.connect(sid="a", now=0.0)
    svc.connect(sid="b", now=0.0)
    v = svc.ingest("a", np.zeros(C + 1, np.float32), now=0.1)
    assert not v["accepted"] and "quarantined" in v["reason"]
    assert svc.registry.get("a").state == QUARANTINED
    assert "shape violation" in svc.registry.get("a").quarantine_reason
    # the sibling is untouched and still serves
    recs = _feed(svc, "b", chaos.stream_samples(4, L + 1, C))
    assert len(recs) == 2
    svc.stop()


def test_slots_exhausted_reject_with_eta(fitted):
    svc = _service(fitted, capacity=2, lease_s=30.0)
    svc.connect(sid="a", now=0.0)
    svc.connect(sid="b", now=0.0)
    with pytest.raises(SlotsExhausted) as ei:
        svc.connect(sid="c", now=10.0)
    assert ei.value.eta_s == pytest.approx(20.0)
    assert svc.rejects == 1
    # a disconnect frees the slot; admission succeeds again
    svc.disconnect("b")
    got = svc.connect(sid="c", now=11.0)
    assert got["sid"] == "c"
    svc.stop()


def test_lease_expiry_reaps_silent_stream(fitted):
    """A subscriber that stops heartbeating is EXPIRED by the pump's reap
    sweep and its slot recycled — ingest and poll both renew."""
    svc = _service(fitted, capacity=2, lease_s=5.0)
    svc.connect(sid="live", now=0.0)
    svc.connect(sid="dead", now=0.0)
    xs = chaos.stream_samples(5, 12, C)
    now = 0.0
    for i in range(12):
        now += 1.0
        svc.ingest("live", xs[i], now=now)   # heartbeat
        svc.pump(now=now)
        svc.poll("live", now=now)
    assert svc.registry.get("dead") is None
    assert svc.registry.get("live").state == ACTIVE
    assert svc.registry.free_slots() == 1
    assert svc.connect(sid="next", now=now)["sid"] == "next"
    svc.stop()


def test_fast_churn_isolation_pin(fitted):
    """The tier-1 pin: victims' answered records are byte-identical with
    and without a seeded storm of connect/disconnect/NaN/abandoned
    neighbors in co-resident lanes."""
    report = chaos.churn_isolation_report(
        lambda: _service(fitted, capacity=4, lease_s=0.05, resume=False),
        chans=C, n_victims=2, n_samples=12, seed=0, extra_ticks=4)
    assert report["identical"], report["detail"]
    assert report["compared"] == 2 * (12 - L + 1)


def test_slow_consumer_drops_are_contained(fitted, monkeypatch):
    """A subscriber that never polls sheds ITS oldest records at the
    out-queue cap (counted); the polling sibling loses nothing."""
    monkeypatch.setenv("REDCLIFF_SERVE_OUT_CAP", "4")
    svc = _service(fitted, capacity=2)
    svc.connect(sid="slow", now=0.0)
    svc.connect(sid="fast", now=0.0)
    n = L + 11
    xs, ys = chaos.stream_samples(8, n, C), chaos.stream_samples(9, n, C)
    now, fast_recs = 0.0, []
    for i in range(n):
        now += 0.01
        svc.ingest("slow", xs[i], now=now)
        svc.ingest("fast", ys[i], now=now)
        svc.pump(now=now)
        fast_recs.extend(svc.poll("fast", now=now))
    answered = n - L + 1
    assert len(fast_recs) == answered
    assert len(svc.out["slow"]) == 4
    assert svc.drops["slow"] == answered - 4
    assert svc.drops["fast"] == 0
    # the survivors are the NEWEST records (oldest were shed)
    assert [r["seq"] for r in svc.poll("slow", now=now)] \
        == list(range(answered - 3, answered + 1))
    svc.stop()


def test_qos_ladder_demotes_and_restores(fitted, monkeypatch, tmp_path):
    """Backlog past the demote fraction thins the graph-readout cadence for
    THAT stream only; draining below the restore fraction recovers rung 0.
    Factor scores flow at full rate throughout."""
    monkeypatch.setenv("REDCLIFF_SERVE_INGEST_CAP", "8")
    svc = _service(fitted, capacity=2, root=str(tmp_path))
    svc.connect(sid="greedy", now=0.0)
    svc.connect(sid="calm", now=0.0)
    xs = chaos.stream_samples(10, 30, C)
    # burst 7 samples without pumping: backlog 7 >= demote_at (4)
    for i in range(7):
        svc.ingest("greedy", xs[i], now=0.1)
    svc.ingest("calm", xs[0], now=0.1)
    svc.pump(now=0.2)
    assert svc.registry.get("greedy").qos_rung == 1
    assert svc.registry.get("calm").qos_rung == 0
    # drain the backlog: backlog falls to <= restore_at (2) -> rung 0
    now = 0.2
    recs = []
    for _ in range(6):
        now += 0.01
        svc.pump(now=now)
        recs.extend(svc.poll("greedy", now=now))
    assert svc.registry.get("greedy").qos_rung == 0
    # every answered sample carried scores; graph thinned while demoted
    assert all("scores" in r for r in recs)
    assert any("graph" not in r for r in recs)
    kinds = [(r.get("reason"), r.get("rung"), r.get("sid"))
             for r in read_jsonl(str(tmp_path))
             if r["event"] == "serve" and r.get("kind") == "qos"]
    assert ("backlog", 1, "greedy") in kinds
    assert ("recovered", 0, "greedy") in kinds
    svc.stop()
    assert QOS_CADENCE[0] == 1  # rung 0 is always full-cadence


def test_backlog_cap_refuses_structurally(fitted, monkeypatch):
    monkeypatch.setenv("REDCLIFF_SERVE_INGEST_CAP", "3")
    svc = _service(fitted, capacity=1)
    svc.connect(sid="a", now=0.0)
    x = np.zeros(C, np.float32)
    for _ in range(3):
        assert svc.ingest("a", x, now=0.1)["accepted"]
    v = svc.ingest("a", x, now=0.1)
    assert not v["accepted"] and v["reason"] == "backlog full"
    assert v["backlog"] == 3
    svc.stop()


def test_drain_resume_matches_uninterrupted_run(fitted, tmp_path):
    """Zero-loss durability: drain mid-stream, restart from the checkpoint,
    finish the stream — undelivered records are handed back and the full
    record sequence byte-matches the uninterrupted run (same ring state,
    same trace_id, seq continues)."""
    n, cut = 12, 7
    xs = chaos.stream_samples(11, n, C)

    # reference: one uninterrupted service
    ref_svc = _service(fitted, capacity=2, resume=False)
    ref_svc.connect(sid="s", now=0.0)
    ref = _feed(ref_svc, "s", xs)
    ref_svc.stop()
    ref_trace = None

    # interrupted: feed `cut`, never poll, drain (checkpoint), resume
    root = str(tmp_path)
    svc1 = _service(fitted, capacity=2, root=root)
    tr1 = svc1.connect(sid="s", now=0.0)["trace_id"]
    _feed(svc1, "s", xs[:cut], poll=False)
    path = svc1.drain(now=1.0)
    assert path and path.endswith("serve_state.bin")

    svc2 = _service(fitted, capacity=2, root=root)
    sess = svc2.registry.get("s")
    assert sess is not None and sess.state == ACTIVE
    assert sess.trace_id == tr1
    # undelivered records from before the restart are handed back first
    got = list(svc2.poll("s", now=2.0))
    got += _feed(svc2, "s", xs[cut:], now0=2.0)
    svc2.stop()

    assert [r["seq"] for r in got] == [r["seq"] for r in ref]
    for a, b in zip(got, ref):
        assert a["scores"].tobytes() == b["scores"].tobytes()
        assert ("graph" in a) == ("graph" in b)
        if "graph" in a:
            assert a["graph"].tobytes() == b["graph"].tobytes()
        assert a["trace_id"] == tr1
        ref_trace = b["trace_id"]
    assert ref_trace != tr1  # distinct services mint distinct identities

    recs = read_jsonl(root)
    assert not schema.validate_records(recs)
    kinds = [r.get("kind") for r in recs if r["event"] == "serve"]
    assert "drain" in kinds and "resume" in kinds
    drain_ev = [r for r in recs
                if r["event"] == "serve" and r.get("kind") == "drain"][-1]
    assert drain_ev["undelivered"] == cut - L + 1
    assert drain_ev["checkpoint"] == path


def test_drain_answers_backlog_and_quarantine_errors(fitted, tmp_path):
    """drain() answers every in-flight sample — including converting a
    quarantined stream's stranded pending samples to error records."""
    svc = _service(fitted, capacity=2, root=str(tmp_path))
    svc.connect(sid="a", now=0.0)
    svc.connect(sid="q", now=0.0)
    xs = chaos.stream_samples(12, L + 4, C)
    for i in range(L + 4):
        svc.ingest("a", xs[i], now=0.1)      # backlog, no pump
    svc.ingest("q", xs[0], now=0.1)
    bad = xs[1].copy()
    bad[0] = np.inf
    svc.ingest("q", bad, now=0.1)
    svc.drain(now=1.0)
    a_recs = [r for r in svc.out["a"]]
    assert len(a_recs) == 5                  # L+4 samples, ring fills at L
    q_recs = [r for r in svc.out["q"]]
    assert q_recs and all("error" in r for r in q_recs)
    assert svc.registry.get("q").state == QUARANTINED


def test_serve_slo_knobs_and_breach(monkeypatch):
    """The serve latency SLO knobs arm threshold checks in the obs reader
    (no backend needed — pure record folding)."""
    monkeypatch.setenv(SLO.ENV_SERVE_P50_MS, "1.0")
    monkeypatch.setenv(SLO.ENV_SERVE_P99_MS, "5.0")
    thr = SLO.serve_thresholds_from_env()
    assert thr == {"serve_p50_ms": 1.0, "serve_p99_ms": 5.0}
    recs = [{"event": "serve", "kind": "start", "capacity": 4},
            {"event": "serve", "kind": "tick", "streams": 2,
             "samples_in": 40, "samples_out": 38, "rejects": 1,
             "dropped": 0, "p50_ms": 2.0, "p99_ms": 9.0, "n": 38}]
    out = SLO.compute_serve_slo(recs, thresholds=thr)
    assert out["latency"]["p99_ms"] == 9.0
    assert {b["slo"] for b in out["breaches"]} \
        == {"serve_p50_ms", "serve_p99_ms"}
    assert SLO.compute_serve_slo([{"event": "metric"}]) is None


def test_serve_smoke_entrypoint(tmp_path):
    """The CI smoke leg end to end: tiny artifact -> 3 streams (one goes
    NaN) -> quarantine + sibling completeness + drain checkpoint."""
    from redcliff_tpu.serve.__main__ import main
    assert main(["smoke", "--root", str(tmp_path)]) == 0


# ---------------------------------------------------------------- soak
@pytest.mark.slow
def test_churn_soak_isolation(fitted):
    """The full seeded storm at soak length: sustained connect/disconnect
    churn, NaN streams, abandoned leases reaped mid-run, slow consumers
    shedding — and every victim byte stays identical. Storm pressure must
    actually bite (admission rejects observed)."""
    report = chaos.churn_isolation_report(
        lambda: _service(fitted, capacity=4, lease_s=0.05, resume=False),
        chans=C, n_victims=2, n_samples=48, seed=7, extra_ticks=16)
    assert report["identical"], report["detail"]
    assert report["compared"] == 2 * (48 - L + 1)
    assert report["rejects"] > 0
