"""Elastic re-meshing & host-fault tolerance acceptance battery
(parallel/remesh.py + the host_lost supervisor/watchdog taxonomy).

The headline property: a "host" dying mid-grid (single-process sub-mesh
simulation — this container's CPU backend cannot run 2-process collectives,
see ROADMAP item 5) surfaces as a typed ``host_lost`` exit, the supervisor
degrades the mesh budget and restarts, and the resumed fit re-shards the
checkpointed lanes onto the survivors with per-lane decision streams
bit-identical to an uninterrupted run at the degraded width — results under
original point ids throughout. Plus: the resume fingerprint stays
mesh-agnostic (checkpoints cross device counts in both directions), the
``remesh`` event lands in metrics.jsonl / dispatch_stats / run_ledger.jsonl,
and ShardedBatchDataset's host-local shard assignment partitions uneven
shard counts exactly.
"""
import json
import os
import pickle
import subprocess
import sys
import time

import numpy as np
import pytest

from redcliff_tpu.parallel import remesh
from redcliff_tpu.runtime import watchdog as wdg
from redcliff_tpu.runtime.faultinject import (_result_blob,
                                              random_host_fault_schedule,
                                              tiny_grid_fit)
from redcliff_tpu.runtime.retry import RetryPolicy
from redcliff_tpu.runtime.supervisor import (MESH_DEVICES_ENV,
                                             SupervisorPolicy, supervise)
from redcliff_tpu.runtime.watchdog import (EXIT_HANG, EXIT_HOST_LOST,
                                           HeartbeatRegistry, Watchdog,
                                           WatchdogPolicy, classify_exit)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = [sys.executable, "-m", "redcliff_tpu.runtime.faultinject"]


# ---------------------------------------------------------------------------
# planner units
# ---------------------------------------------------------------------------
def test_classify_device_error_routes():
    cde = remesh.classify_device_error
    assert cde(RuntimeError(
        "INTERNAL: device lost: local device vanished")) == "device_lost"
    assert cde(RuntimeError("PJRT error: device disconnected")) \
        == "device_lost"
    assert cde(RuntimeError(
        "DEADLINE_EXCEEDED: coordinator heartbeat timed out")) \
        == "coordinator_loss"
    assert cde(RuntimeError(
        "distributed runtime service unavailable")) == "coordinator_loss"
    assert cde(RuntimeError(
        "collective all-reduce timed out after 60s")) == "collective_timeout"
    assert cde(RuntimeError("NCCL operation timeout")) == "collective_timeout"
    # not mesh-shaped: model/shape errors stay in their original class
    assert cde(ValueError("shapes (3, 4) and (4, 5) do not match")) is None
    assert cde(RuntimeError("loss went non-finite at step 7")) is None
    assert cde(None) is None


def test_choose_mesh_devices_prefers_wall_clock_then_width():
    # 9 lanes: all 6 survivors (width 18, 3 lanes/device) beat the pow2
    # 4-subset (width 16, 4 lanes/device)
    assert remesh.choose_mesh_devices(6, 9) == 6
    # ties go to MORE devices (filler burns joules, not seconds)
    assert remesh.choose_mesh_devices(6, 8) == 6
    assert remesh.choose_mesh_devices(8, 8) == 8
    assert remesh.choose_mesh_devices(1, 5) == 1


def test_plan_resharding_shrink_grow_and_compact_off():
    ids = np.arange(8, dtype=np.int32)
    live = np.ones(8, bool)
    # width 8 onto 6 devices: grows up the ladder with filler padding
    p = remesh.plan_resharding(live, ids, [], 6)
    assert p.new_width == 12
    np.testing.assert_array_equal(p.orig_ids[:8], ids)
    assert (p.orig_ids[8:] == -1).all()
    assert p.active[:8].all() and not p.active[8:].any()
    assert p.retire_rows.size == 0
    # compatible meshes need no plan (same-mesh resumes stay on the fast
    # path; pow2 shrink rides the sub-mesh rule)
    assert remesh.plan_resharding(live, ids, [], 8) is None
    assert remesh.plan_resharding(live, ids, [], 4) is None
    assert remesh.plan_resharding(live, ids, [], 1) is None
    # 3 live of 8 onto 4 devices, compacting: width 4, frozen lanes retire
    # (except those already in the retired store)
    some = np.array([1, 0, 1, 0, 0, 1, 0, 0], bool)
    p2 = remesh.plan_resharding(some, ids, [7], 4)
    assert p2.new_width == 4
    np.testing.assert_array_equal(p2.orig_ids, [0, 2, 5, -1])
    assert sorted(int(i) for i in p2.retire_ids) == [1, 3, 4, 6]
    # compact=False keeps every real lane at fixed-width semantics
    p3 = remesh.plan_resharding(some, ids, [], 6, compact=False)
    assert p3.new_width == 12 and p3.retire_rows.size == 0
    assert list(p3.active[:8]) == list(some)
    # no live lanes (resume-to-finish): keep all real rows, retire nothing
    p4 = remesh.plan_resharding(np.zeros(8, bool), ids, [], 6)
    assert p4.new_width == 12 and p4.retire_rows.size == 0
    assert not p4.active.any()
    # filler rows replicate a LIVE lane even in the keep-all branch — row 0
    # may be a quarantined lane holding non-finite params
    p5 = remesh.plan_resharding(
        np.array([0, 1, 0, 0, 0, 0, 0, 0], bool), ids, [], 6, compact=False)
    assert (p5.sel[8:] == 1).all()
    # filler-only input: nothing to plan
    assert remesh.plan_resharding(
        np.zeros(2, bool), np.full(2, -1, np.int32), [], 4) is None


def test_visible_devices_and_mesh_shape(monkeypatch):
    import jax

    monkeypatch.delenv(remesh.ENV_MESH_DEVICES, raising=False)
    monkeypatch.delenv(remesh.ENV_SIM_HOSTS, raising=False)
    assert len(remesh.visible_devices()) == jax.device_count()
    monkeypatch.setenv(remesh.ENV_MESH_DEVICES, "6")
    assert len(remesh.visible_devices()) == 6
    monkeypatch.setenv(remesh.ENV_MESH_DEVICES, "not-a-number")
    assert len(remesh.visible_devices()) == jax.device_count()
    monkeypatch.setenv(remesh.ENV_MESH_DEVICES, "6")
    monkeypatch.setenv(remesh.ENV_SIM_HOSTS, "3")
    shape = remesh.mesh_shape(remesh.visible_mesh())
    assert shape == {"n_hosts": 3, "n_devices": 6, "device_kind": "cpu"}
    # mesh=None describes the single-device default placement
    monkeypatch.delenv(remesh.ENV_SIM_HOSTS, raising=False)
    assert remesh.mesh_shape(None)["n_devices"] == 1


def test_mesh_shape_ignores_sim_hosts_on_real_multiprocess(monkeypatch):
    """REDCLIFF_SIM_HOSTS applies ONLY to genuinely single-process device
    sets: on a real multi-controller mesh the process_index spread is the
    truth, and the supervisor-exported sim value must not distort the
    audit trail."""
    class _Dev:
        def __init__(self, pi):
            self.process_index = pi
            self.device_kind = "tpu"

    monkeypatch.setenv(remesh.ENV_SIM_HOSTS, "4")
    real = [_Dev(0), _Dev(0), _Dev(1), _Dev(1)]
    assert remesh.mesh_shape(devices=real)["n_hosts"] == 2
    sim = [_Dev(0)] * 4
    assert remesh.mesh_shape(devices=sim)["n_hosts"] == 4


# ---------------------------------------------------------------------------
# watchdog: host-scoped staleness -> EXIT_HOST_LOST
# ---------------------------------------------------------------------------
def test_host_component_naming_and_taxonomy():
    assert wdg.host_component(3, "shard_loader") == "host3:shard_loader"
    assert wdg.host_of("host3:shard_loader") == 3
    assert wdg.host_of("shard_loader") is None
    assert wdg.host_of("hostile:thing") is None
    assert classify_exit(EXIT_HOST_LOST) == "host_lost"


class _GuardStub:
    preempted = False
    signum = None


class _Log:
    active = True

    def __init__(self, events):
        self._events = events

    def log(self, event, **kw):
        self._events.append((event, kw))

    def close(self):
        pass


def test_watchdog_host_scoped_staleness_exits_host_lost():
    """One host's heartbeats going stale while the process stays healthy is
    a HOST loss (exit 21, no preempt latch — nothing in-process needs
    saving and a final save could wedge on dead collectives), not a hang."""
    reg = HeartbeatRegistry(default_budget_s=0.05)
    reg.stamp(wdg.host_component(2, "stream"))
    reg.stamp("epoch_engine")
    guard = _GuardStub()
    exits, events = [], []
    wd = Watchdog(policy=WatchdogPolicy(poll_s=0.02, grace_s=0.1),
                  registry=reg, guard=guard, logger=_Log(events),
                  exit_fn=exits.append)
    with wd:
        deadline = time.monotonic() + 10.0
        while not exits and time.monotonic() < deadline:
            reg.stamp("epoch_engine")  # this process keeps beating
            time.sleep(0.01)
    assert exits == [EXIT_HOST_LOST]
    assert guard.preempted is False
    kinds = [e for e, _ in events]
    assert "host_lost" in kinds and "host_lost_exit" in kinds
    lost = dict(events)["host_lost"]
    assert lost["host"] == 2
    assert "host2:stream" in lost["components"]


def test_watchdog_host_loss_demotes_to_hang_without_proof_of_life():
    """A short-budget host beat going overdue while every other component
    is merely IN-BUDGET (but frozen) must not shrink the mesh: without a
    fresh stamp from some other component during the grace window, the
    incident demotes to the ordinary hang ladder (exit 19) — a wedged
    process gets a same-shape restart, never a misclassified re-mesh."""
    reg = HeartbeatRegistry(default_budget_s=10.0)  # epoch_engine in budget
    reg.budgets["host2:stream"] = 0.05
    reg.stamp("host2:stream")
    reg.stamp("epoch_engine")
    exits, events = [], []
    wd = Watchdog(policy=WatchdogPolicy(poll_s=0.02, grace_s=0.1),
                  registry=reg, logger=_Log(events), exit_fn=exits.append)
    with wd:
        deadline = time.monotonic() + 10.0
        while not exits and time.monotonic() < deadline:
            time.sleep(0.01)  # NOBODY stamps: the whole process is frozen
    assert exits == [EXIT_HANG]
    kinds = [e for e, _ in events]
    # the host-loss incident fired, failed its proof-of-life check, and
    # the hang ladder took over
    assert "host_lost" in kinds and "hang" in kinds
    assert "host_lost_exit" not in kinds


def test_watchdog_whole_process_stall_is_still_a_hang():
    """Host-scoped AND process-wide beats both stale = this process is
    wedged: the ordinary hang ladder (exit 19), not host_lost."""
    reg = HeartbeatRegistry(default_budget_s=0.05)
    reg.stamp(wdg.host_component(1, "stream"))
    reg.stamp("epoch_engine")
    exits = []
    wd = Watchdog(policy=WatchdogPolicy(poll_s=0.02, grace_s=0.05),
                  registry=reg, exit_fn=exits.append)
    with wd:
        deadline = time.monotonic() + 10.0
        while not exits and time.monotonic() < deadline:
            time.sleep(0.01)
    assert exits == [EXIT_HANG]
    # and a lone stale host beat with NOTHING else monitored is a hang too
    # (no evidence the rest of the process is alive)
    reg2 = HeartbeatRegistry(default_budget_s=0.05)
    reg2.stamp(wdg.host_component(1, "stream"))
    exits2 = []
    wd2 = Watchdog(policy=WatchdogPolicy(poll_s=0.02, grace_s=0.05),
                   registry=reg2, exit_fn=exits2.append)
    with wd2:
        deadline = time.monotonic() + 10.0
        while not exits2 and time.monotonic() < deadline:
            time.sleep(0.01)
    assert exits2 == [EXIT_HANG]


def test_host_scoped_heartbeat_inherits_base_budget_override():
    """budget.shard_loader must govern EVERY host's shard loader: the
    host-scoped beat falls back to the base component's override instead
    of silently reverting to the 600s default."""
    reg = HeartbeatRegistry(clock=lambda: reg_clock[0],
                            default_budget_s=100.0)
    reg_clock = [0.0]
    reg.budgets["shard_loader"] = 2.0
    reg.stamp("host1:shard_loader")
    reg.stamp("other")
    reg_clock[0] = 3.0
    assert [o[0] for o in reg.overdue()] == ["host1:shard_loader"]
    # an exact host-scoped override still wins over the base fallback
    reg.budgets["host1:shard_loader"] = 50.0
    reg.retire("host1:shard_loader")
    reg.stamp("host1:shard_loader")
    reg_clock[0] = 6.0
    assert reg.overdue() == []


def test_apply_reshard_backfills_presentinel_failed_cause():
    """A pre-sentinel checkpoint (no failed_cause) with frozen lanes must
    re-shard, not crash: the retire loop backfills causes from
    failed_epoch exactly like the grid resume path."""
    from redcliff_tpu.runtime import numerics

    ids = np.arange(4, dtype=np.int32)
    active = np.array([True, False, True, True])
    ckpt = {
        "params": np.arange(4.0).reshape(4, 1),
        "optA_state": np.arange(4.0), "optB_state": np.arange(4.0),
        "best_params": {"w": np.arange(8.0).reshape(4, 2)},
        "best_crit": np.array([1.0, 2.0, 3.0, 4.0]),
        "best_epoch": np.array([0, 1, 2, 3]),
        "active": active, "accepted": None,
        "failed_epoch": np.array([-1, 1, -1, -1]),  # lane 1 quarantined
        "orig_ids": ids,
    }
    retired = {}
    plan = remesh.plan_resharding(active, ids, retired.keys(), 6)
    assert plan is not None and list(plan.retire_ids) == [1]
    migrated = remesh.apply_reshard(ckpt, retired, plan)
    assert migrated == 3
    assert retired[1]["failed_cause"] == numerics.CAUSE_NONFINITE_VAL
    assert retired[1]["failed_epoch"] == 1
    np.testing.assert_array_equal(retired[1]["best_params"]["w"], [2.0, 3.0])
    assert ckpt["params"].shape[0] == plan.new_width


def test_watchdog_policy_host_loss_knob(monkeypatch):
    monkeypatch.setenv(wdg.ENV_WATCHDOG, "poll_s=0.5,host_loss=0")
    p = WatchdogPolicy.from_env()
    assert p.host_loss is False
    monkeypatch.setenv(wdg.ENV_WATCHDOG, "1")
    assert WatchdogPolicy.from_env().host_loss is True


# ---------------------------------------------------------------------------
# supervisor: host_lost -> re-mesh-then-restart, mesh audit in the ledger
# ---------------------------------------------------------------------------
class _FakeProc:
    def __init__(self, rc):
        self._rc = rc

    def wait(self):
        return self._rc


def _fake_popen(rcs, envs):
    def popen(cmd, env=None):
        envs.append(dict(env) if env is not None else None)
        return _FakeProc(rcs[len(envs) - 1])

    return popen


def _fast_policy(**kw):
    return SupervisorPolicy(
        backoff=RetryPolicy(max_attempts=10 ** 6, base_delay_s=0.0,
                            multiplier=2.0, max_delay_s=0.0), **kw)


def test_supervisor_remesh_restart_degrades_mesh(tmp_path):
    """Two host losses on a 4-host x 2-device mesh: each attempt's ledger
    line records the mesh it ran under, each host_lost triggers a
    remesh_restart that shrinks REDCLIFF_MESH_DEVICES by one host's worth,
    and the run finishes clean on the twice-degraded mesh."""
    envs = []
    ledger = str(tmp_path / "run_ledger.jsonl")
    out = supervise(
        ["driver"], ledger_path=ledger,
        policy=_fast_policy(mesh_devices=8, n_hosts=4, device_kind="cpu"),
        popen=_fake_popen([EXIT_HOST_LOST, EXIT_HOST_LOST, 0], envs),
        sleep=lambda s: None)
    assert out.classification == "clean"
    assert [e[MESH_DEVICES_ENV] for e in envs] == ["8", "6", "4"]
    assert [e["REDCLIFF_SIM_HOSTS"] for e in envs] == ["4", "3", "2"]
    recs = [json.loads(l) for l in open(ledger)]
    attempts = [r for r in recs if r["event"] == "attempt"]
    assert [a["classification"] for a in attempts] == \
        ["host_lost", "host_lost", "clean"]
    assert [a["action"] for a in attempts] == \
        ["remesh_restart", "remesh_restart", "stop"]
    assert [a["mesh"] for a in attempts] == [
        {"n_hosts": 4, "n_devices": 8, "device_kind": "cpu"},
        {"n_hosts": 3, "n_devices": 6, "device_kind": "cpu"},
        {"n_hosts": 2, "n_devices": 4, "device_kind": "cpu"}]
    remeshes = [r for r in recs if r["event"] == "remesh"]
    assert [(r["from_devices"], r["to_devices"]) for r in remeshes] == \
        [(8, 6), (6, 4)]


def test_supervisor_mesh_exhausted_stops(tmp_path):
    """A mesh that cannot degrade further (min_devices floor, or the last
    host) is terminal: there is nothing left to run on."""
    envs = []
    out = supervise(
        ["driver"],
        policy=_fast_policy(mesh_devices=8, n_hosts=4, min_devices=7),
        popen=_fake_popen([EXIT_HOST_LOST], envs), sleep=lambda s: None)
    assert out.classification == "mesh_exhausted"
    assert out.attempts[0]["action"] == "stop"
    # last-host case
    envs2 = []
    out2 = supervise(
        ["driver"], policy=_fast_policy(mesh_devices=2, n_hosts=1),
        popen=_fake_popen([EXIT_HOST_LOST], envs2), sleep=lambda s: None)
    assert out2.classification == "mesh_exhausted"


def test_supervisor_unknown_host_width_degrades_one_device(tmp_path):
    """--mesh-devices without n_hosts/devices-per-host: the host width is
    unknown, so each loss degrades by ONE device (conservative — extra
    restart rounds beat discarding healthy capacity for the whole sweep)."""
    envs = []
    out = supervise(
        ["driver"], policy=_fast_policy(mesh_devices=8),
        popen=_fake_popen([EXIT_HOST_LOST, EXIT_HOST_LOST, 0], envs),
        sleep=lambda s: None)
    assert out.classification == "clean"
    assert [e[MESH_DEVICES_ENV] for e in envs] == ["8", "7", "6"]


def test_supervisor_host_lost_without_mesh_is_plain_restart(tmp_path):
    """No declared mesh = no re-mesh knowledge: host_lost degrades to the
    ordinary restart class (same shape, and no mesh env is injected)."""
    envs = []
    out = supervise(
        ["driver"], policy=_fast_policy(),
        popen=_fake_popen([EXIT_HOST_LOST, 0], envs), sleep=lambda s: None)
    assert out.classification == "clean"
    assert out.attempts[0]["action"] == "restart"
    assert "mesh" not in out.attempts[0]
    assert envs == [None, None]  # caller env passed through untouched


# ---------------------------------------------------------------------------
# ShardedBatchDataset: host-local shard assignment
# ---------------------------------------------------------------------------
def _write_shards(split_dir, n_files, per_file=3, channels=2, T=4, seed=0):
    os.makedirs(split_dir)
    rng = np.random.default_rng(seed)
    for i in range(n_files):
        pairs = [[rng.normal(size=(T, channels)).astype(np.float32),
                  np.float32([i * per_file + j])]
                 for j in range(per_file)]
        with open(os.path.join(split_dir, f"subset_{i}.pkl"), "wb") as f:
            pickle.dump(pairs, f)


@pytest.mark.parametrize("n_files,n_hosts", [(5, 2), (7, 3), (4, 4)])
def test_host_local_assignment_partitions_unevenly(tmp_path, n_files,
                                                   n_hosts):
    """Host-local shard assignment is a PARTITION for any (files, hosts):
    no shard dropped, none owned twice — uneven counts included — and every
    sample streams from exactly one host."""
    from redcliff_tpu.data.shards import ShardedBatchDataset

    split = str(tmp_path / "train")
    _write_shards(split, n_files)
    parts = [ShardedBatchDataset(split, normalize=False, host_id=h,
                                 n_hosts=n_hosts) for h in range(n_hosts)]
    owned = [f for p in parts for f in p.files]
    assert sorted(owned) == sorted(
        f"subset_{i}.pkl" for i in range(n_files))  # complete
    assert len(owned) == len(set(owned))            # disjoint
    # sample-level: the union of host streams is exactly the dataset (the
    # label encodes (file, sample), so multiset equality pins no-dup/no-drop)
    labels = []
    for p in parts:
        for _, Y in p.batches(batch_size=2):
            labels.extend(float(y) for y in Y.ravel())
    assert sorted(labels) == list(range(n_files * 3))
    assert sum(len(p) for p in parts) == n_files * 3


def test_host_local_assignment_errors_and_heartbeat(tmp_path):
    from redcliff_tpu.data.shards import ShardedBatchDataset

    split = str(tmp_path / "train")
    _write_shards(split, 2)
    with pytest.raises(ValueError, match="together"):
        ShardedBatchDataset(split, host_id=0)
    with pytest.raises(ValueError, match="out of range"):
        ShardedBatchDataset(split, host_id=2, n_hosts=2)
    # more hosts than shards: the empty host fails loudly at construction
    with pytest.raises(FileNotFoundError, match="owns no shards"):
        ShardedBatchDataset(split, host_id=2, n_hosts=3)
    # host-scoped heartbeat: the per-host staleness detector's producer
    before = wdg.REGISTRY.counts().get("host1:shard_loader", 0)
    ds = ShardedBatchDataset(split, host_id=1, n_hosts=2)
    assert wdg.REGISTRY.counts()["host1:shard_loader"] > before
    assert "host1:shard_loader" not in wdg.REGISTRY.ages()  # retired when idle
    assert ds.files == ["subset_1.pkl"]


def test_run_coefficient_grid_rejects_unknown_mesh_string():
    """Only 'auto' is a valid mesh string (and it resolves before any model
    work); typos fail loudly instead of silently training unsharded."""
    from redcliff_tpu.train.driver import run_coefficient_grid

    with pytest.raises(ValueError, match="'auto'"):
        run_coefficient_grid(None, None, [{"gen_lr": 1e-3}], None, None,
                             mesh="bogus")


# ---------------------------------------------------------------------------
# tripwire: the resume fingerprint is mesh-agnostic (satellite 2)
# ---------------------------------------------------------------------------
def test_resume_fingerprint_is_mesh_agnostic(tmp_path, monkeypatch):
    """A checkpoint written on an 8-device mesh must be ACCEPTED on a
    4-device mesh (and vice versa) — the mesh is audit metadata in the
    payload, never part of the compatibility fingerprint. A rejection here
    means someone added a mesh-shaped field to _checkpoint_meta."""
    monkeypatch.delenv("REDCLIFF_FAULT_INJECT", raising=False)
    monkeypatch.delenv(remesh.ENV_MESH_DEVICES, raising=False)
    ck = str(tmp_path / "ck_8to4")
    blob_8 = _result_blob(tiny_grid_fit(ck, max_iter=2, use_mesh=True))
    monkeypatch.setenv(remesh.ENV_MESH_DEVICES, "4")
    # resume on 4 devices: must load (not reject as "different fit") and
    # reproduce the finished fit's results exactly from the checkpoint
    blob_4 = _result_blob(tiny_grid_fit(ck, max_iter=2, use_mesh=True))
    for k in ("val_history", "best_criteria", "best_epoch", "active"):
        np.testing.assert_array_equal(blob_4[k], blob_8[k])
    # and vice versa: written at 4, resumed at 8
    ck2 = str(tmp_path / "ck_4to8")
    blob_w4 = _result_blob(tiny_grid_fit(ck2, max_iter=2, use_mesh=True))
    monkeypatch.delenv(remesh.ENV_MESH_DEVICES, raising=False)
    blob_r8 = _result_blob(tiny_grid_fit(ck2, max_iter=2, use_mesh=True))
    np.testing.assert_array_equal(blob_r8["val_history"],
                                  blob_w4["val_history"])


# ---------------------------------------------------------------------------
# typed-error mapping: injected device/coordinator loss -> HostLostError
# ---------------------------------------------------------------------------
def test_injected_device_and_coordinator_loss_map_to_typed_error(
        tmp_path, monkeypatch):
    monkeypatch.delenv("REDCLIFF_FAULT_MARKER", raising=False)
    cases = [("device_lost:0", "device_lost", None),
             ("coordinator_loss:0", "coordinator_loss", None),
             ("host_drop:2:0", "host_drop", 2)]
    for spec, reason, host in cases:
        monkeypatch.setenv("REDCLIFF_FAULT_INJECT", spec)
        with pytest.raises(remesh.HostLostError) as ei:
            tiny_grid_fit(str(tmp_path / reason), max_iter=1)
        assert ei.value.reason == reason
        assert ei.value.host == host


# ---------------------------------------------------------------------------
# in-process degraded-mesh resume: remesh plan + event + stats + audit
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def degraded_reference(tmp_path_factory):
    """The uninterrupted run at the DEGRADED width: G=8 on the 6-device
    survivor mesh (execution width 12) for all 3 epochs — what every
    resumed leg must match bit-for-bit at the decision level. Computed once
    per module (three tests compare against it)."""
    prev = os.environ.get(remesh.ENV_MESH_DEVICES)
    prev_fi = os.environ.pop("REDCLIFF_FAULT_INJECT", None)
    os.environ[remesh.ENV_MESH_DEVICES] = "6"
    try:
        res = tiny_grid_fit(
            str(tmp_path_factory.mktemp("degraded_ref")), max_iter=3,
            grid_size=8, use_mesh=True)
        return _result_blob(res)
    finally:
        if prev is None:
            os.environ.pop(remesh.ENV_MESH_DEVICES, None)
        else:
            os.environ[remesh.ENV_MESH_DEVICES] = prev
        if prev_fi is not None:
            os.environ["REDCLIFF_FAULT_INJECT"] = prev_fi


def _assert_decisions_match(got, want):
    """Per-lane decision streams + GridResult under original point ids,
    BITWISE; params float-tight (a re-mesh changes the per-device shard
    width mid-history, which XLA codegen may round ~1 ulp — measured on
    the legacy runtime, exact on the thunk runtime for this shape; see the
    strict slow leg and ARCHITECTURE's caveat)."""
    np.testing.assert_array_equal(got["val_history"], want["val_history"])
    np.testing.assert_array_equal(got["best_criteria"],
                                  want["best_criteria"])
    np.testing.assert_array_equal(got["best_epoch"], want["best_epoch"])
    np.testing.assert_array_equal(got["active"], want["active"])
    assert got["failures"] == want["failures"]
    for a, b in zip(got["best_params_leaves"], want["best_params_leaves"]):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_degraded_mesh_resume_reshards_and_matches(tmp_path, monkeypatch,
                                                   degraded_reference):
    """Checkpoint at width 8 on the full 8-device mesh, resume with only 6
    devices visible: the engine re-shards to the width-12 bucket (all 8
    lanes migrate, 4 filler pads), logs the structured ``remesh`` event,
    surfaces it in dispatch_stats, stamps the new mesh into the checkpoint
    payload — and the finished fit matches the uninterrupted degraded-width
    run at the decision level, under original point ids."""
    import jax

    from redcliff_tpu.data.datasets import ArrayDataset
    from redcliff_tpu.runtime import checkpoint as rck
    from redcliff_tpu.runtime.faultinject import _tiny_runner
    from redcliff_tpu.utils.observability import read_jsonl

    monkeypatch.delenv("REDCLIFF_FAULT_INJECT", raising=False)
    monkeypatch.delenv(remesh.ENV_MESH_DEVICES, raising=False)
    ck = str(tmp_path / "ck")
    runner, X, Y = _tiny_runner(3, grid_size=8, use_mesh=True)
    assert runner.mesh.devices.size == 8
    ds = ArrayDataset(X, Y)
    runner.fit(jax.random.PRNGKey(2), ds, ds, max_iter=2,
               checkpoint_dir=ck, checkpoint_every=1)
    ckpt = rck.read_checkpoint(os.path.join(ck, "grid_checkpoint.pkl"))
    assert ckpt["mesh"] == {"n_hosts": 1, "n_devices": 8,
                            "device_kind": "cpu"}

    monkeypatch.setenv(remesh.ENV_MESH_DEVICES, "6")
    runner2, _, _ = _tiny_runner(3, grid_size=8, use_mesh=True)
    assert runner2.mesh.devices.size == 6
    res = runner2.fit(jax.random.PRNGKey(2), ds, ds,
                      checkpoint_dir=ck, checkpoint_every=1, log_dir=ck)
    stats = runner2.dispatch_stats
    assert stats["remeshes"] == 1 and stats["grid_width"] == 12
    assert stats["remesh"]["from_width"] == 8
    assert stats["remesh"]["to_width"] == 12
    assert stats["remesh"]["lanes_migrated"] == 8
    assert stats["remesh"]["plan_ms"] >= 0
    rem = [e for e in read_jsonl(ck) if e.get("event") == "remesh"]
    assert len(rem) == 1
    assert rem[0]["from_devices"] == 8 and rem[0]["to_devices"] == 6
    assert rem[0]["lanes_migrated"] == 8 and rem[0]["lanes_retired"] == []
    # the post-remesh checkpoint carries the NEW mesh (audit end to end)
    ckpt2 = rck.read_checkpoint(os.path.join(ck, "grid_checkpoint.pkl"))
    assert ckpt2["mesh"]["n_devices"] == 6
    assert len(ckpt2["orig_ids"]) == 12
    _assert_decisions_match(_result_blob(res), degraded_reference)


# ---------------------------------------------------------------------------
# THE acceptance: SIGKILL-grade host loss mid-grid, supervised end to end
# ---------------------------------------------------------------------------
def _run_supervised_mesh(tmp_path, ck, fault, result=None, max_iter=3,
                         timeout=420, extra_env=None):
    env = dict(os.environ,
               REDCLIFF_FAULT_MARKER=str(tmp_path / "fault.marker"))
    env.pop(remesh.ENV_MESH_DEVICES, None)
    env.pop("REDCLIFF_WATCHDOG", None)
    if fault:
        env["REDCLIFF_FAULT_INJECT"] = fault
    else:
        env.pop("REDCLIFF_FAULT_INJECT", None)
    env.update(extra_env or {})
    ledger = str(tmp_path / "run_ledger.jsonl")
    child = CHILD + ["--checkpoint-dir", str(ck), "--mesh",
                     "--grid-size", "8", "--max-iter", str(max_iter)]
    if result:
        child += ["--result", str(result)]
    cmd = [sys.executable, "-m", "redcliff_tpu.supervise",
           "--ledger", ledger, "--max-restarts", "3",
           "--base-delay-s", "0.05",
           "--mesh-devices", "8", "--n-hosts", "4", "--device-kind", "cpu",
           "--"] + child
    proc = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=timeout)
    recs = [json.loads(l) for l in open(ledger)]
    return proc, recs


def test_host_drop_supervised_remesh_acceptance(tmp_path,
                                                degraded_reference):
    """THE host-fault acceptance: a simulated host partition (host 3 of a
    4-host x 2-device mesh) dies at the end of epoch 1, mid-grid. The child
    exits with the host_lost taxonomy code, the supervisor classifies it,
    degrades the commanded mesh 8 -> 6 devices (ledger ``remesh`` event,
    per-attempt mesh shapes), and the restarted child re-shards the
    checkpointed lanes onto the survivors (metrics ``remesh`` event) and
    finishes — with per-lane decision streams and the final GridResult,
    under original point ids, bit-identical to an uninterrupted run at the
    degraded width."""
    ck = tmp_path / "ck"
    res_path = tmp_path / "res.pkl"
    proc, recs = _run_supervised_mesh(tmp_path, ck, "host_drop:3:1",
                                      result=res_path)
    assert proc.returncode == 0, proc.stderr[-3000:]
    attempts = [r for r in recs if r["event"] == "attempt"]
    assert attempts[0]["rc"] == EXIT_HOST_LOST
    assert attempts[0]["classification"] == "host_lost"
    assert attempts[0]["action"] == "remesh_restart"
    assert attempts[0]["mesh"] == {"n_hosts": 4, "n_devices": 8,
                                   "device_kind": "cpu"}
    assert attempts[-1]["classification"] == "clean"
    assert attempts[-1]["mesh"] == {"n_hosts": 3, "n_devices": 6,
                                    "device_kind": "cpu"}
    remeshes = [r for r in recs if r["event"] == "remesh"]
    assert [(r["from_devices"], r["to_devices"]) for r in remeshes] \
        == [(8, 6)]
    # the resumed child re-sharded 8 -> 12 and said so in metrics.jsonl
    events = [json.loads(l) for l in open(ck / "metrics.jsonl")]
    rem = [e for e in events if e.get("event") == "remesh"]
    assert rem and rem[0]["from_width"] == 8 and rem[0]["to_width"] == 12
    assert rem[0]["lanes_migrated"] == 8
    with open(res_path, "rb") as f:
        got = pickle.load(f)
    _assert_decisions_match(got, degraded_reference)


@pytest.mark.slow
@pytest.mark.parametrize("fault", ["device_lost:1", "coordinator_loss:1"])
def test_device_and_coordinator_loss_supervised(tmp_path, fault,
                                                degraded_reference):
    """The other two detection routes end-to-end: an XLA-shaped device-loss
    / coordinator-timeout error is mapped to the typed HostLostError by the
    grid engine, exits 21, and the supervised re-mesh resume completes
    identically. (Tier-1 covers the mapping in-process and the host_drop
    route through the supervisor; these ride the slow tier.)"""
    ck = tmp_path / "ck"
    res_path = tmp_path / "res.pkl"
    proc, recs = _run_supervised_mesh(tmp_path, ck, fault, result=res_path)
    assert proc.returncode == 0, proc.stderr[-3000:]
    attempts = [r for r in recs if r["event"] == "attempt"]
    assert attempts[0]["classification"] == "host_lost"
    assert attempts[0]["action"] == "remesh_restart"
    with open(res_path, "rb") as f:
        got = pickle.load(f)
    _assert_decisions_match(got, degraded_reference)


# ---------------------------------------------------------------------------
# slow tier: strict bitwise leg + host-fault chaos soak
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_host_drop_acceptance_strict_legacy_runtime(tmp_path):
    """The acceptance property on the OTHER CPU runtime (legacy, the
    width-stable one the PR-5 strict compaction leg uses): decision streams
    and the final GridResult stay BITWISE across the re-mesh. Params are
    float-tight, not bitwise: unlike a same-mesh compaction (where epochs
    before the width change ran on the identical device layout), a re-mesh
    changes the PER-DEVICE shard width mid-history (1 lane/device on the
    8-mesh epochs vs 2 on the 6-mesh), and measured on this container the
    legacy runtime rounds ~1 ulp across shard layouts (23/768 elements,
    <=1.5e-8 on the probe shape) — while the thunk runtime is exact on the
    same shape (tier-1 test above). Decision-level bitwise holds on BOTH."""
    env_extra = {"XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                               + " --xla_cpu_use_thunk_runtime=false").strip()}
    ck = tmp_path / "ck"
    res_path = tmp_path / "res.pkl"
    proc, recs = _run_supervised_mesh(tmp_path, ck, "host_drop:3:1",
                                      result=res_path, extra_env=env_extra)
    assert proc.returncode == 0, proc.stderr[-3000:]
    # uninterrupted degraded-width reference under the SAME runtime
    ref_path = tmp_path / "ref.pkl"
    env = dict(os.environ, **env_extra)
    env.pop("REDCLIFF_FAULT_INJECT", None)
    env[remesh.ENV_MESH_DEVICES] = "6"
    ref = subprocess.run(
        CHILD + ["--checkpoint-dir", str(tmp_path / "ck_ref"), "--mesh",
                 "--grid-size", "8", "--max-iter", "3",
                 "--result", str(ref_path)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=420)
    assert ref.returncode == 0, ref.stderr[-3000:]
    with open(res_path, "rb") as f:
        got = pickle.load(f)
    with open(ref_path, "rb") as f:
        want = pickle.load(f)
    np.testing.assert_array_equal(got["val_history"], want["val_history"])
    np.testing.assert_array_equal(got["best_criteria"],
                                  want["best_criteria"])
    np.testing.assert_array_equal(got["best_epoch"], want["best_epoch"])
    np.testing.assert_array_equal(got["active"], want["active"])
    assert got["failures"] == want["failures"]
    for a, b in zip(got["best_params_leaves"], want["best_params_leaves"]):
        # ~1 ulp across per-device shard layouts (see docstring)
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


@pytest.mark.slow
@pytest.mark.parametrize("seed", list(range(10)))
def test_host_fault_chaos_soak(tmp_path, seed):
    """The host-drop chaos soak: seeded schedules across the host-fault
    grammar (host_drop / device_lost / coordinator_loss, optionally over
    degraded storage) must all terminate clean under supervision with a
    complete ledger — every host_lost classified, every restart re-meshed,
    and the final durable checkpoint intact."""
    from redcliff_tpu.runtime import checkpoint as rck

    schedule = random_host_fault_schedule(seed)
    ck = tmp_path / "ck"
    proc, recs = _run_supervised_mesh(tmp_path, ck, schedule)
    assert proc.returncode == 0, (schedule, proc.stderr[-3000:])
    attempts = [r for r in recs if r["event"] == "attempt"]
    finals = [r for r in recs if r["event"] == "final"]
    assert len(finals) == 1 and finals[0]["classification"] == "clean"
    for a in attempts[:-1]:
        assert a["classification"] == "host_lost", (schedule, attempts)
        assert a["action"] == "remesh_restart"
    assert attempts[-1]["classification"] == "clean"
    ckpt, _ = rck.load_checkpoint(str(ck / "grid_checkpoint.pkl"))
    assert ckpt is not None and ckpt["epoch"] == 2
