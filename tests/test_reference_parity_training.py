"""Training-TRAJECTORY A/B against the reference optimizer loop.

The point-in-weight-space parity suite (test_reference_parity.py) proves
forward/loss/GC equality; this file closes the remaining face of SURVEY hard
part #1: N optimizer steps of the actual reference training choreography —
`REDCLIFF_S_CMLP.batch_update` with two torch Adams (coupled weight decay,
ref general_utils/model_utils.py:749-762) driven through the real phase
schedule (pretrain embedder -> acclimate factors -> combined,
ref models/redcliff_s_cmlp.py:689-885) — against the same number of steps of
the JAX RedcliffTrainer from identical weights and an identical batch stream,
asserting per-step probe-loss histories and final params/GC.

Also A/B'd here, against the importable torch originals:
* `cMLP.perform_prox_update_on_GC_weights` (ref models/cmlp.py:117-144) and
  `general_utils.model_utils.prox_update` (ref :231-257) for all three
  penalties (GL / GSGL / H — including GSGL's sequential two-stage threshold
  and H's in-place lag-prefix recursion) vs redcliff_tpu.ops.prox;
* `general_utils.model_utils.regularize` / `ridge_regularize` (ref :270-307)
  vs our in-loss penalty terms;
* a prox-mode trajectory: Adam + per-step GL prox on a cMLP (the GISTA-style
  update the reference exposes) stepped N times in both frameworks.

Tolerances: both sides run f32; divergence compounds through Adam's rsqrt, so
trajectory assertions use f32-scale tolerances (probe losses rtol 2e-3, final
params rtol 5e-3 atol 5e-4) — tight enough that any semantic drift (wrong
decay coupling, wrong bias correction, wrong phase gating) fails immediately,
as semantic errors produce O(1) divergence within a few steps.
"""
import sys
import types

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from test_reference_parity import (  # noqa: E402
    C, EMBED_HIDDEN, GEN_HIDDEN, GEN_LAG, EMBED_LAG, MAX_LAG, S,
    _copy_params, _np,
)


@pytest.fixture(scope="module")
def ref():
    from conftest import add_reference_to_path

    add_reference_to_path(extra_stubs=[
        ("torcheeg", {}),
        ("torcheeg.models", {"DGCNN": type("DGCNN", (), {})}),
    ])
    sys.modules["torcheeg"].models = sys.modules["torcheeg.models"]
    from general_utils import model_utils
    from models.cmlp import cMLP
    from models.redcliff_s_cmlp import REDCLIFF_S_CMLP

    return types.SimpleNamespace(REDCLIFF_S_CMLP=REDCLIFF_S_CMLP, cMLP=cMLP,
                                 model_utils=model_utils)


# reference-style hyperparameters (ref call_model_fit_method :749-762)
EMBED_LR, EMBED_EPS, EMBED_WD = 1e-3, 1e-4, 1e-4
GEN_LR, GEN_EPS, GEN_WD = 5e-4, 1e-4, 1e-4
K_TRAJ = 3          # factors (keep the trajectory test fast)
NUM_SIMS_TRAJ = 2
PRETRAIN, ACCLIM, EPOCHS, BATCHES = 3, 3, 13, 4   # 52 batch_update calls
COEFFS_TRAJ = dict(FORECAST_COEFF=1.0, FACTOR_SCORE_COEFF=2.0,
                   FACTOR_COS_SIM_COEFF=0.3, FACTOR_WEIGHT_L1_COEFF=0.05,
                   ADJ_L1_REG_COEFF=0.01, DAGNESS_REG_COEFF=0.0,
                   DAGNESS_LAG_COEFF=0.0, DAGNESS_NODE_COEFF=0.0)


def _build_pair(ref):
    """(ref_model, jax_model, params) with identical weights and the real
    3-phase schedule."""
    from redcliff_tpu.models.redcliff import RedcliffSCMLP, RedcliffSCMLPConfig

    torch.manual_seed(7)
    ECC = 10.0
    ref_model = ref.REDCLIFF_S_CMLP(
        num_chans=C, gen_lag=GEN_LAG, gen_hidden=list(GEN_HIDDEN),
        embed_lag=EMBED_LAG, embed_hidden_sizes=list(EMBED_HIDDEN),
        num_in_timesteps=MAX_LAG, num_out_timesteps=1, num_factors=K_TRAJ,
        num_supervised_factors=S, coeff_dict=dict(COEFFS_TRAJ),
        use_sigmoid_restriction=True,
        factor_score_embedder_type="cEmbedder",
        factor_score_embedder_args=[("sigmoid_eccentricity_coeff", ECC),
                                    ("embed_lag", EMBED_LAG),
                                    ("hidden", list(EMBED_HIDDEN))],
        primary_gc_est_mode="conditional_factor_fixed_embedder",
        forward_pass_mode="apply_factor_weights_at_each_sim_step",
        num_sims=NUM_SIMS_TRAJ,
        training_mode="pretrain_embedder_then_acclimate_factors_then_combined",
        num_pretrain_epochs=PRETRAIN, num_acclimation_epochs=ACCLIM,
    )
    jax_model = RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=C, gen_lag=GEN_LAG, gen_hidden=tuple(GEN_HIDDEN),
        embed_lag=EMBED_LAG, embed_hidden_sizes=tuple(EMBED_HIDDEN),
        num_factors=K_TRAJ, num_supervised_factors=S,
        forecast_coeff=COEFFS_TRAJ["FORECAST_COEFF"],
        factor_score_coeff=COEFFS_TRAJ["FACTOR_SCORE_COEFF"],
        factor_cos_sim_coeff=COEFFS_TRAJ["FACTOR_COS_SIM_COEFF"],
        factor_weight_l1_coeff=COEFFS_TRAJ["FACTOR_WEIGHT_L1_COEFF"],
        adj_l1_reg_coeff=COEFFS_TRAJ["ADJ_L1_REG_COEFF"],
        use_sigmoid_restriction=True, sigmoid_eccentricity_coeff=ECC,
        factor_score_embedder_type="cEmbedder",
        primary_gc_est_mode="conditional_factor_fixed_embedder",
        forward_pass_mode="apply_factor_weights_at_each_sim_step",
        num_sims=NUM_SIMS_TRAJ,
        training_mode="pretrain_embedder_then_acclimate_factors_then_combined",
        num_pretrain_epochs=PRETRAIN, num_acclimation_epochs=ACCLIM,
    ))
    params = _copy_params(ref_model, "cEmbedder")
    return ref_model, jax_model, params


def _batch_stream(num_epochs, num_batches, batch=6):
    """Deterministic batch stream shared verbatim by both frameworks."""
    rng = np.random.default_rng(42)
    T = MAX_LAG + NUM_SIMS_TRAJ + 1
    stream = []
    for _ in range(num_epochs):
        epoch = []
        for _ in range(num_batches):
            X = rng.normal(size=(batch, T, C)).astype(np.float32)
            Y = rng.uniform(size=(batch, S + 1, T)).astype(np.float32)
            epoch.append((X, Y))
        stream.append(epoch)
    return stream


def _ref_probe_loss(ref_model, X, Y):
    """Combined-phase loss on a probe batch (no grad, no update)."""
    with torch.no_grad():
        Xt, Yt = torch.from_numpy(X), torch.from_numpy(Y)
        W = max(ref_model.gen_lag, ref_model.embed_lag)
        x_sims, _, _, labels = ref_model.forward(Xt[:, :W, :])
        loss, _ = ref_model.compute_loss(
            Xt[:, : ref_model.embed_lag, :], x_sims,
            Xt[:, W: W + ref_model.num_sims * 1, :], labels, Yt,
            ref_model.primary_gc_est_mode, node_dag_scale=0.1,
            embedder_pretrain_loss=False, factor_pretrain_loss=False)
    return float(loss)


def test_training_trajectory_parity(ref):
    """~50 reference batch_update calls across the real phase schedule vs the
    JAX trainer: per-epoch probe-loss histories and final params/GC agree."""
    from redcliff_tpu.models.redcliff import phase_schedule
    from redcliff_tpu.train.redcliff_trainer import (RedcliffTrainConfig,
                                                     RedcliffTrainer)

    ref_model, jax_model, params = _build_pair(ref)
    trainer = RedcliffTrainer(jax_model, RedcliffTrainConfig(
        embed_lr=EMBED_LR, embed_eps=EMBED_EPS, embed_weight_decay=EMBED_WD,
        gen_lr=GEN_LR, gen_eps=GEN_EPS, gen_weight_decay=GEN_WD))
    optA = torch.optim.Adam(ref_model.gen_model[0].parameters(), lr=EMBED_LR,
                            betas=(0.9, 0.999), eps=EMBED_EPS,
                            weight_decay=EMBED_WD)
    optB = torch.optim.Adam(ref_model.gen_model[1].parameters(), lr=GEN_LR,
                            betas=(0.9, 0.999), eps=GEN_EPS,
                            weight_decay=GEN_WD)
    sA = trainer.optA.init(params["embedder"])
    sB = trainer.optB.init(params["factors"])

    stream = _batch_stream(EPOCHS, BATCHES)
    probe_X, probe_Y = _batch_stream(1, 1, batch=8)[0][0]

    from redcliff_tpu.runtime.numerics import init_numerics_state

    ns = init_numerics_state()
    ref_hist, jax_hist = [], []
    phases_seen = set()
    for epoch in range(EPOCHS):
        phases = phase_schedule(jax_model.config, epoch)
        phases_seen.add(phases)
        for X, Y in stream[epoch]:
            # reference: one batch_update call through its own phase gating
            ref_model.batch_update(epoch, 0, torch.from_numpy(X),
                                   torch.from_numpy(Y), optA, optB,
                                   output_length=1)
            # ours: the trainer's jit step(s) for the schedule's phase(s)
            for phase in phases:
                params, sA, sB, _, _, ns = trainer._steps[phase](
                    params, sA, sB, jnp.asarray(X), jnp.asarray(Y), ns)
        ref_hist.append(_ref_probe_loss(ref_model, probe_X, probe_Y))
        jax_hist.append(float(jax_model.loss_for_phase(
            params, jnp.asarray(probe_X), jnp.asarray(probe_Y),
            "combined")[0]))

    # the schedule actually exercised all three phases
    assert phases_seen == {("embedder_pretrain",), ("factor_pretrain",),
                           ("combined",)}
    # per-epoch probe-loss histories track each other
    np.testing.assert_allclose(jax_hist, ref_hist, rtol=2e-3, atol=2e-4)
    # both trajectories actually moved (this is a training test, not a no-op)
    assert abs(ref_hist[-1] - ref_hist[0]) > 1e-3

    # final params agree tensor-by-tensor
    final_ref = _copy_params(ref_model, "cEmbedder")
    flat_j, _ = jax.tree_util.tree_flatten(params)
    flat_r, _ = jax.tree_util.tree_flatten(final_ref)
    assert len(flat_j) == len(flat_r)
    for a, b in zip(flat_j, flat_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-4)

    # final GC readout agrees (the scientific output of the trajectory)
    with torch.no_grad():
        ref_gc = ref_model.GC(gc_est_mode="fixed_factor_exclusive",
                              threshold=False, ignore_lag=True)
    jax_gc = np.asarray(jax_model.gc(
        params, gc_est_mode="fixed_factor_exclusive", ignore_lag=True))[0]
    ref_gc_arr = np.stack([_np(g) for g in ref_gc[0]])
    if ref_gc_arr.ndim == 4:  # ref keeps a trailing singleton lag axis
        ref_gc_arr = ref_gc_arr[..., 0]
    np.testing.assert_allclose(jax_gc[..., 0], ref_gc_arr, rtol=5e-3,
                               atol=5e-4)


# ---------------------------------------------------------------------------
# prox-operator A/B vs the importable torch originals
# ---------------------------------------------------------------------------
def _ref_cmlp(ref, seed=0, Cn=4, lag=3, hidden=(6,)):
    torch.manual_seed(seed)
    return ref.cMLP(Cn, lag, list(hidden))


@pytest.mark.parametrize("penalty", ["GL", "GSGL", "H"])
def test_prox_parity_vs_reference_cmlp(ref, penalty):
    """cMLP.perform_prox_update_on_GC_weights (ref models/cmlp.py:117-144)
    vs ops.prox.prox_update on the stacked first-layer block."""
    from redcliff_tpu.ops.prox import prox_update

    model = _ref_cmlp(ref)
    lam, lr = 0.9, 0.35  # large enough to zero some groups
    W_before = np.stack([_np(net.layers[0].weight)
                         for net in model.networks])  # (C_out, H, C_in, L)
    ours = np.asarray(prox_update(jnp.asarray(W_before), lam, lr, penalty))
    model.perform_prox_update_on_GC_weights(lam, lr, penalty)
    theirs = np.stack([_np(net.layers[0].weight) for net in model.networks])
    np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-7)
    assert not np.allclose(theirs, W_before)  # the update actually thresholded


@pytest.mark.parametrize("penalty", ["GL", "GSGL", "H"])
def test_model_utils_prox_update_parity(ref, penalty):
    """general_utils.model_utils.prox_update (ref :231-257, the shared-op
    variant) vs ops.prox.prox_update on a single network block."""
    from redcliff_tpu.ops.prox import prox_update

    model = _ref_cmlp(ref, seed=3)
    net = model.networks[1]
    lam, lr = 1.1, 0.25
    W_before = _np(net.layers[0].weight)  # (H, C_in, L)
    ours = np.asarray(prox_update(jnp.asarray(W_before), lam, lr, penalty))
    ref.model_utils.prox_update(net, lam, lr, model_type="cMLP",
                                penalty=penalty)
    np.testing.assert_allclose(ours, _np(net.layers[0].weight),
                               rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("penalty", ["GL", "GSGL", "H"])
def test_regularize_parity(ref, penalty):
    """general_utils.model_utils.regularize (ref :270-292) vs our group-norm
    penalty terms."""
    from redcliff_tpu.ops.prox import group_lasso_penalty

    model = _ref_cmlp(ref, seed=5)
    net = model.networks[0]
    lam = 0.37
    theirs = float(ref.model_utils.regularize(net, lam, model_type="cMLP",
                                              penalty=penalty))
    W = jnp.asarray(_np(net.layers[0].weight))
    ours = float(group_lasso_penalty(W, lam, penalty))
    np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-7)


def test_ridge_regularize_parity(ref):
    """general_utils.model_utils.ridge_regularize (ref :294-307) vs our ridge
    penalty over the non-first layers."""
    from redcliff_tpu.ops.prox import ridge_penalty

    model = _ref_cmlp(ref, seed=6, hidden=(6, 5))
    net = model.networks[2]
    lam = 0.21
    theirs = float(ref.model_utils.ridge_regularize(net, lam,
                                                    model_type="cMLP"))
    layers = [jnp.asarray(_np(l.weight)) for l in net.layers[1:]]
    ours = float(ridge_penalty(layers, lam))
    np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-7)


def test_prox_mode_trajectory_parity(ref):
    """GISTA-style prox-mode training: N steps of (torch Adam + in-place GL
    prox) on the reference cMLP vs (optax adam + ops.prox) on the tensorized
    block, from identical weights and batches."""
    import optax

    from redcliff_tpu.models.cmlp import cmlp_forward
    from redcliff_tpu.ops.prox import prox_update

    Cn, lag, hidden = 4, 3, (6,)
    model = _ref_cmlp(ref, seed=11, Cn=Cn, lag=lag, hidden=hidden)
    # threshold (lam*lr_prox = 0.05/step) strong enough that groups with weak
    # gradient pull pin to exactly zero within the 30-step trajectory
    lam, lr_prox = 1.0, 5e-2
    opt = torch.optim.Adam(model.parameters(), lr=1e-2, betas=(0.9, 0.999),
                           eps=1e-8)

    # copy weights: networks[c].layers -> layer list of stacked blocks
    def copy_params():
        n_layers = len(model.networks[0].layers)
        layers = []
        for li in range(n_layers):
            w = np.stack([_np(net.layers[li].weight)
                          for net in model.networks])
            b = np.stack([_np(net.layers[li].bias) for net in model.networks])
            if li > 0:
                w = w[..., 0]
            layers.append({"w": jnp.asarray(w), "b": jnp.asarray(b)})
        return layers

    params = copy_params()
    jopt = optax.adam(1e-2, b1=0.9, b2=0.999, eps=1e-8)
    jstate = jopt.init(params)

    def jax_loss(p, X, Yt):
        pred = cmlp_forward(p, X)
        return jnp.mean((pred - Yt) ** 2)

    @jax.jit
    def jstep(p, state, X, Yt):
        grads = jax.grad(jax_loss)(p, X, Yt)
        upd, state = jopt.update(grads, state)
        p = optax.apply_updates(p, upd)
        p[0]["w"] = prox_update(p[0]["w"], lam, lr_prox, "GL")
        return p, state

    rng = np.random.default_rng(5)
    mse = torch.nn.MSELoss()
    for _ in range(30):
        X = rng.normal(size=(8, 12, Cn)).astype(np.float32)
        Yt = rng.normal(size=(8, 12 - lag + 1, Cn)).astype(np.float32)
        Xt = torch.from_numpy(X)

        opt.zero_grad()
        # reference forward: per-network conv stack, cat on the series axis
        outs = []
        for net in model.networks:
            h = Xt.transpose(2, 1)
            for i, layer in enumerate(net.layers):
                if i != 0:
                    h = torch.relu(h)
                h = layer(h)
            outs.append(h.transpose(2, 1))
        loss = mse(torch.cat(outs, dim=2), torch.from_numpy(Yt))
        loss.backward()
        opt.step()
        with torch.no_grad():
            model.perform_prox_update_on_GC_weights(lam, lr_prox, "GL")

        params, jstate = jstep(params, jstate, jnp.asarray(X),
                               jnp.asarray(Yt))

    final_ref = copy_params()
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(final_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=1e-5)
    # the prox actually produced exact zero groups on both sides
    W1 = np.asarray(params[0]["w"])
    group_norms = np.sqrt((W1 ** 2).sum(axis=(1, 3)))
    assert (group_norms == 0.0).any()


# ---------------------------------------------------------------------------
# Freeze-mode choreography A/B vs the reference accept/revert logic
# ---------------------------------------------------------------------------
def _build_freeze_pair(ref, mode_suffix, num_factors=4):
    """(ref_model, jax_model) in a Freeze training mode with identical
    weights (cEmbedder; fixed_factor GC drives the decision statistic)."""
    from redcliff_tpu.models.redcliff import RedcliffSCMLP, RedcliffSCMLPConfig

    mode = f"pretrain_embedder_then_post_train_factor_{mode_suffix}"
    torch.manual_seed(13)
    ECC = 10.0
    ref_model = ref.REDCLIFF_S_CMLP(
        num_chans=C, gen_lag=GEN_LAG, gen_hidden=list(GEN_HIDDEN),
        embed_lag=EMBED_LAG, embed_hidden_sizes=list(EMBED_HIDDEN),
        num_in_timesteps=MAX_LAG, num_out_timesteps=1,
        num_factors=num_factors, num_supervised_factors=S,
        coeff_dict=dict(COEFFS_TRAJ), use_sigmoid_restriction=True,
        factor_score_embedder_type="cEmbedder",
        factor_score_embedder_args=[("sigmoid_eccentricity_coeff", ECC),
                                    ("embed_lag", EMBED_LAG),
                                    ("hidden", list(EMBED_HIDDEN))],
        primary_gc_est_mode="fixed_factor_exclusive",
        forward_pass_mode="apply_factor_weights_at_each_sim_step",
        num_sims=NUM_SIMS_TRAJ, training_mode=mode,
        num_pretrain_epochs=2, num_acclimation_epochs=0,
    )
    jax_model = RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=C, gen_lag=GEN_LAG, gen_hidden=tuple(GEN_HIDDEN),
        embed_lag=EMBED_LAG, embed_hidden_sizes=tuple(EMBED_HIDDEN),
        num_factors=num_factors, num_supervised_factors=S,
        forecast_coeff=COEFFS_TRAJ["FORECAST_COEFF"],
        factor_score_coeff=COEFFS_TRAJ["FACTOR_SCORE_COEFF"],
        factor_cos_sim_coeff=COEFFS_TRAJ["FACTOR_COS_SIM_COEFF"],
        factor_weight_l1_coeff=COEFFS_TRAJ["FACTOR_WEIGHT_L1_COEFF"],
        adj_l1_reg_coeff=COEFFS_TRAJ["ADJ_L1_REG_COEFF"],
        use_sigmoid_restriction=True, sigmoid_eccentricity_coeff=10.0,
        factor_score_embedder_type="cEmbedder",
        primary_gc_est_mode="fixed_factor_exclusive",
        forward_pass_mode="apply_factor_weights_at_each_sim_step",
        num_sims=NUM_SIMS_TRAJ, training_mode=mode,
        num_pretrain_epochs=2, num_acclimation_epochs=0,
    ))
    return ref_model, jax_model


def _perturb_factors(ref_model, seed=2):
    """Scale/noise the current model's factor weights so some factors shrink
    (accepted) and some grow (reverted) relative to a cached copy."""
    rng = np.random.default_rng(seed)
    with torch.no_grad():
        for k, factor in enumerate(ref_model.factors):
            for net in factor.networks:
                w = net.layers[0].weight
                w.mul_(0.5 if k % 2 == 0 else 1.7)
                w.add_(torch.from_numpy(
                    rng.normal(0, 0.01, size=tuple(w.shape)).astype(
                        np.float32)))


def _ref_intent_need_updates(ref, ref_model, cached_model, mode):
    """The reference decision rule (ref :1116-1156) computed with the
    reference's OWN primitives on the squeezed 2-D estimates. The in-situ
    function cannot run: REDCLIFF GC hands it (C, C, 1) tensors and
    np.linalg.norm(x, ord=1) raises ValueError on 3-D input (pinned below),
    so the evident intent — the matrix 1-norm of the max-normalized 2-D
    estimate — is evaluated here directly."""
    from general_utils.metrics import compute_cosine_similarity

    def ests(m):
        return [np.squeeze(_np(x), axis=-1) for x in m.GC(
            "fixed_factor_exclusive", X=None, threshold=False,
            ignore_lag=True, combine_wavelet_representations=False,
            rank_wavelets=False)[0]]

    cached = ests(cached_model)
    curr = ests(ref_model)
    K = ref_model.num_factors_nK
    need = []
    for f in range(K):
        c_norm = cached[f] / np.max(cached[f])
        n_norm = curr[f] / np.max(curr[f])
        if "withComboCosSimL1" in mode:
            cos_c = cos_n = 0.0
            for o in range(K):
                if o == f:
                    continue
                cos_c += compute_cosine_similarity(
                    c_norm, cached[o] / np.max(cached[o]))
                cos_n += compute_cosine_similarity(
                    n_norm, curr[o] / np.max(curr[o]))
            cos_c /= K - 1.0
            cos_n /= K - 1.0
            need.append(bool(cos_n * np.linalg.norm(n_norm, ord=1)
                             < cos_c * np.linalg.norm(c_norm, ord=1)))
        else:
            need.append(bool(np.linalg.norm(n_norm, ord=1)
                             < np.linalg.norm(c_norm, ord=1)))
    return need


def test_reference_freeze_decision_crashes_as_published(ref):
    """The reference's determine_which_factors_need_updates raises ValueError
    as published: REDCLIFF GC returns (C, C, 1) estimates and
    np.linalg.norm(x, ord=1) rejects 3-D input (ref :1131,1149) — no shipped
    cached-args use a Freeze mode, so the path was never executed upstream.
    Pinned so any reference drift (or a fix) is noticed."""
    import copy as copy_mod

    ref_model, _ = _build_freeze_pair(ref, "withL1FreezeByBatch")
    best = copy_mod.deepcopy(ref_model)
    _perturb_factors(ref_model)
    with pytest.raises(ValueError, match="Improper number of dimensions"):
        ref_model.determine_which_factors_need_updates(
            best, [True] * ref_model.num_factors_nK)


@pytest.mark.parametrize("mode_suffix", ["withL1FreezeByBatch",
                                         "withComboCosSimL1FreezeByBatch"])
def test_freeze_decision_parity(ref, mode_suffix):
    """Our freeze_accept_vector vs the reference decision rule (matrix 1-norm
    of the max-normalized unlagged GC estimate, optionally weighted by the
    mean cross-factor cosine), evaluated with the reference's own primitives
    per _ref_intent_need_updates."""
    import copy as copy_mod

    from redcliff_tpu.train.freeze import (factor_decision_stats,
                                           freeze_accept_vector)

    ref_model, jax_model = _build_freeze_pair(ref, mode_suffix)
    best = copy_mod.deepcopy(ref_model)
    _perturb_factors(ref_model)
    need = _ref_intent_need_updates(ref, ref_model, best,
                                    jax_model.config.training_mode)

    cand = _copy_params(ref_model, "cEmbedder")
    acc = _copy_params(best, "cEmbedder")
    accept = freeze_accept_vector(
        jax_model.config.training_mode,
        factor_decision_stats(jax_model, cand),
        factor_decision_stats(jax_model, acc))
    assert [bool(a) for a in np.asarray(accept)] == need
    assert any(need) and not all(need)  # the fixture produces a real mix
