"""Edge-dynamics statistics: golden-value tests against hand-built reference
semantics (scipy per-edge loops mirroring /root/reference/evaluate/eval_utils.py
:43-654) on small random histories."""
import numpy as np
import pytest
from scipy.stats import linregress, rankdata, spearmanr

from redcliff_tpu.eval.edge_dynamics import (
    compute_edge_lock_performance_v3_stats,
    compute_edge_lock_performance_v4_stats,
    compute_edge_rank_performance_v1_stats,
    compute_edge_rank_performance_v2_stats,
    compute_key_correlation_stats_betw_two_score_histories,
    compute_key_covariance_stats_betw_two_score_histories,
    compute_key_edge_correlation_stats,
    compute_key_edge_covariance_stats,
    compute_key_spearman_correlation_stats_betw_two_score_histories,
    compute_key_stats_betw_two_gc_score_vecs,
    compute_smoothed_edge_cross_edge_rank_covariance_stats,
    compute_smoothed_edge_rank_covariance_stats,
    dense_rank_per_window,
    smooth_history,
    spearman_numerator_cov,
    vector_pearson,
    vector_spearman,
)


def _histories(T=12, C=4, seed=0):
    rng = np.random.default_rng(seed)
    true = rng.uniform(size=(T, C, C))
    true[:, C - 2, C - 1] = 0.0  # an edge with no true activation
    est = 0.6 * true + 0.4 * rng.uniform(size=(T, C, C))
    return est, true


def _ref_smooth(hist, w):
    # the reference's exact loop (eval_utils.py:68-78)
    T = len(hist)
    out = [np.zeros_like(hist[0]) for _ in range(T - w)]
    C = hist[0].shape[0]
    for i in range(C):
        for j in range(C):
            edge = [hist[t][i, j] for t in range(T)]
            sm = [np.mean(edge[t:t + w]) for t in range(T - w)]
            for t, v in enumerate(sm):
                out[t][i, j] = v
    return out


def test_smooth_history_matches_reference_convention():
    est, _ = _histories()
    for w in (1, 3):
        got = smooth_history(est, w)
        want = np.stack(_ref_smooth(list(est), w))
        assert got.shape[0] == est.shape[0] - w
        np.testing.assert_allclose(got, want, atol=1e-12)


def test_dense_rank_per_window_matches_rankdata():
    est, _ = _histories(T=5)
    got = dense_rank_per_window(est)
    for t in range(5):
        want = rankdata(est[t], method="dense").reshape(est[t].shape)
        np.testing.assert_array_equal(got[t], want)


def test_vector_pearson_matches_linregress():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(20, 7))
    y = rng.normal(size=(20, 7))
    r, p = vector_pearson(x, y)
    for e in range(7):
        lr = linregress(x[:, e], y[:, e])
        assert r[e] == pytest.approx(lr.rvalue, abs=1e-10)
        assert p[e] == pytest.approx(lr.pvalue, abs=1e-10)


def test_vector_spearman_matches_scipy():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(15, 5))
    y = 0.5 * x + rng.normal(size=(15, 5))
    r, p = vector_spearman(x, y)
    for e in range(5):
        sr, sp = spearmanr(x[:, e], y[:, e])
        assert r[e] == pytest.approx(sr, abs=1e-10)
        assert p[e] == pytest.approx(sp, abs=1e-10)


def test_edge_lock_v4_covers_all_edges_with_pearson():
    est, true = _histories()
    C = est.shape[1]
    stats = compute_edge_lock_performance_v4_stats(
        "PearsonCorrelation", est, true, smoothing_window_size=2)
    assert len(stats) == C * C
    s_est, s_true = _ref_smooth(list(est), 2), _ref_smooth(list(true), 2)
    i, j = 1, 2
    lr = linregress([A[i, j] for A in s_est], [A[i, j] for A in s_true])
    got = stats[f"{i}<-{j}"][
        "PearsonCorrelation_curr_paradigm_smooth_activ_hist_stat"]
    assert got["pearson_r"] == pytest.approx(lr.rvalue, abs=1e-10)
    assert got["pearson_p"] == pytest.approx(lr.pvalue, abs=1e-10)


def test_edge_lock_v3_filters_diagonal_and_inactive():
    est, true = _histories()
    C = est.shape[1]
    stats = compute_edge_lock_performance_v3_stats(
        "PearsonCorrelation", est, true, smoothing_window_size=1)
    # no self-edges
    assert all(k.split("<-")[0] != k.split("<-")[1] for k in stats)
    assert len(stats) <= C * C - C


def test_edge_lock_rejects_unknown_paradigm():
    est, true = _histories()
    with pytest.raises(NotImplementedError):
        compute_edge_lock_performance_v4_stats("Wavelet", est, true)


def test_edge_rank_v2_golden_values():
    est, true = _histories(T=10, C=3, seed=3)
    w = 2
    stats = compute_edge_rank_performance_v2_stats(
        "PearsonCorrelation", est, true, smoothing_window_size=w)
    s_est, s_true = _ref_smooth(list(est), w), _ref_smooth(list(true), w)
    r_est = [rankdata(A, method="dense").reshape(A.shape) for A in s_est]
    r_true = [rankdata(A, method="dense").reshape(A.shape) for A in s_true]
    for key, entry in stats.items():
        if not isinstance(key, str):
            continue
        i, j = (int(v) for v in key.split("<-"))
        er = np.array([A[i, j] for A in r_est])
        tr = np.array([A[i, j] for A in r_true])
        ea = np.array([A[i, j] for A in s_est])
        ta = np.array([A[i, j] for A in s_true])
        assert tr.mean() > 1.0 and i != j  # the reference's filter
        assert entry["smooth_rank_MSE_across_windows"] == pytest.approx(
            np.mean((er - tr) ** 2))
        assert entry["smooth_activ_MSE_across_windows"] == pytest.approx(
            np.mean((ea - ta) ** 2))
        lr = linregress(er, tr)
        got = entry["PearsonCorrelation_curr_paradigm_ranked_smooth_hist_stat"]
        assert got["pearson_r"] == pytest.approx(lr.rvalue, abs=1e-10)


def test_edge_rank_v2_aggregates_by_true_rank_key():
    est, true = _histories(T=10, C=3, seed=4)
    stats = compute_edge_rank_performance_v2_stats(
        "PearsonCorrelation", est, true)
    float_keys = [k for k in stats if not isinstance(k, str)]
    assert float_keys, "expected per-true-rank aggregation keys"
    total = sum(len(stats[k]["smooth_rank_MSE_across_windows"])
                for k in float_keys)
    n_edges = len([k for k in stats if isinstance(k, str)])
    assert total == n_edges


def test_edge_rank_v1_stats_and_paradigms():
    est, true = _histories(T=10, C=3, seed=5)
    for paradigm in ("PearsonCorrelation", "SpearmanCorrelation", "ROC_AUC"):
        stats = compute_edge_rank_performance_v1_stats(paradigm, est, true)
        str_keys = [k for k in stats if isinstance(k, str)]
        assert str_keys
        entry = stats[str_keys[0]]
        assert "avg_smooth_rank_diff" in entry
        assert "avg_of_smooth_activ_diffs_across_windows" in entry
        if paradigm == "ROC_AUC":
            # activation stat is always None under ROC_AUC (ref :377)
            assert entry[
                "ROC_AUC_curr_paradigm_smooth_activ_hist_stat"] is None


def test_edge_rank_v1_diff_golden():
    est, true = _histories(T=8, C=3, seed=6)
    stats = compute_edge_rank_performance_v1_stats(
        "PearsonCorrelation", est, true, smoothing_window_size=1)
    s_est, s_true = _ref_smooth(list(est), 1), _ref_smooth(list(true), 1)
    key = next(k for k in stats if isinstance(k, str))
    i, j = (int(v) for v in key.split("<-"))
    ea = np.array([A[i, j] for A in s_est])
    ta = np.array([A[i, j] for A in s_true])
    assert stats[key]["avg_smooth_activ_diff"] == pytest.approx(
        ea.mean() - ta.mean())
    assert stats[key]["avg_of_smooth_activ_diffs_across_windows"] == \
        pytest.approx((ea - ta).mean())


def test_spearman_numerator_cov_fixes_reference_bug():
    rng = np.random.default_rng(7)
    x = rng.normal(size=20)
    y = np.exp(x) + rng.normal(size=20) * 0.01  # monotone -> rank cov != cov
    fixed = spearman_numerator_cov(x, y)
    buggy = spearman_numerator_cov(x, y, match_reference_bug=True)
    assert buggy == pytest.approx(np.cov(x, y)[0, 1])
    want = np.cov(rankdata(x), rankdata(y))[0, 1]
    assert fixed == pytest.approx(want)
    assert fixed != pytest.approx(buggy)


def test_covariance_summaries():
    est, true = _histories(T=9, C=3, seed=8)
    out = compute_key_edge_covariance_stats(est, true)
    covs, rcovs = [], []
    for i in range(3):
        for j in range(3):
            covs.append(np.cov(est[:, i, j], true[:, i, j])[0, 1])
            rcovs.append(np.cov(rankdata(est[:, i, j]),
                                rankdata(true[:, i, j]))[0, 1])
    assert out["avg_edge_cov"] == pytest.approx(np.mean(covs))
    assert out["avg_edge_rank_cov"] == pytest.approx(np.mean(rcovs))


def test_smoothed_rank_covariance_windows():
    est, true = _histories(T=12, C=3, seed=9)
    out = compute_smoothed_edge_rank_covariance_stats(
        est, true, smoothing_window_sizes=(1, 3))
    assert set(out) == {"smoothWindow1_avg_edge_rank_cov",
                        "smoothWindow3_avg_edge_rank_cov"}
    out2 = compute_smoothed_edge_cross_edge_rank_covariance_stats(
        est, true, smoothing_window_sizes=(2,))
    assert set(out2) == {"smoothWindow2_avg_edge_rank_cov"}
    assert np.isfinite(out2["smoothWindow2_avg_edge_rank_cov"])


def test_score_history_stats():
    rng = np.random.default_rng(10)
    est_h = rng.normal(size=25)
    true_h = 0.7 * est_h + rng.normal(size=25) * 0.5
    cov_stats = compute_key_covariance_stats_betw_two_score_histories(
        est_h, true_h)
    assert cov_stats["cov"] == pytest.approx(np.cov(est_h, true_h)[0, 1])
    corr = compute_key_correlation_stats_betw_two_score_histories(est_h, true_h)
    lr = linregress(est_h, true_h)
    assert corr["r"] == pytest.approx(lr.rvalue, abs=1e-10)
    assert corr["p"] == pytest.approx(lr.pvalue, abs=1e-10)
    sp_stats = compute_key_spearman_correlation_stats_betw_two_score_histories(
        est_h, true_h)
    sr, sp = spearmanr(est_h, true_h)
    assert sp_stats["sr"] == pytest.approx(sr, abs=1e-10)
    assert sp_stats["sp"] == pytest.approx(sp, abs=1e-10)


def test_score_vec_stats():
    a = np.array([1.0, 2.0, 3.0])
    b = np.array([1.0, 2.0, 4.0])
    out = compute_key_stats_betw_two_gc_score_vecs(a, b)
    assert out["mse"] == pytest.approx(np.mean((a - b) ** 2))
    assert 0.9 < out["cosine_similarity"] <= 1.0


def test_edge_correlation_summary():
    est, true = _histories(T=10, C=3, seed=11)
    out = compute_key_edge_correlation_stats(est, true)
    rs = [linregress(est[:, i, j], true[:, i, j]).rvalue
          for i in range(3) for j in range(3)]
    # one constant true edge -> nan on both sides, like scipy
    assert out["avg_edge_pearson_r"] == pytest.approx(
        np.mean(rs), abs=1e-10, nan_ok=True)
    finite = [r for r in rs if np.isfinite(r)]
    pr, _ = vector_pearson(est.reshape(10, -1), true.reshape(10, -1))
    np.testing.assert_allclose(
        np.sort(pr[np.isfinite(pr)]), np.sort(finite), atol=1e-10)
