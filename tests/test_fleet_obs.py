"""Request-scoped distributed tracing + fleet SLO observatory (ISSUE 12).

The durable lifecycle ledger (fleet/history.py — append/read roundtrip,
torn-tail healing, best-effort writes), exact SLO math on synthetic
timings (obs/slo.py nearest-rank percentiles, deadline hit-rate, requeue
re-entry, REDCLIFF_SLO_* breach flags), cross-process trace-context
propagation (obs/spans.py set_trace_ctx / REDCLIFF_TRACE_CTX — span and
metrics stamping, zero-stamp when tracing is off), the full lifecycle
driven through the real worker loop against a stubbed supervisor
(submitted -> planned -> claimed -> attempt -> settled under one
trace_id, dead-letter + bisection linkage, worker_crash flight dump),
the fleet Perfetto export (obs/trace_export.py --fleet: per-request
tracks, queue counter curves, structural validity), the PR-8
rotation-boundary/SIGKILL-torn-tail pattern extended to the fleet root,
and one real supervised end-to-end drain pinning the acceptance: every
request's track spans submit -> settle across processes under its
submit-minted trace_id, and the child's records carry the same join keys.
"""
import json
import os
import subprocess
import sys
import time

import pytest

from redcliff_tpu.fleet import history as fleet_history
from redcliff_tpu.fleet import worker as worker_mod
from redcliff_tpu.fleet.queue import FleetQueue
from redcliff_tpu.fleet.__main__ import TINY_SPEC
from redcliff_tpu.obs import schema as obs_schema
from redcliff_tpu.obs import slo as obs_slo
from redcliff_tpu.obs import spans as obs_spans
from redcliff_tpu.obs.logging import MetricLogger, read_jsonl
from redcliff_tpu.obs.trace_export import build_fleet_trace, validate_trace
from redcliff_tpu.runtime.supervisor import SuperviseOutcome

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _submit_tiny(q, tenant, epochs=2, points=None, **kw):
    spec = json.loads(json.dumps(TINY_SPEC))
    spec["epochs"] = epochs
    return q.submit(tenant, points or [{"gen_lr": 1e-3}], spec=spec, **kw)


def _clean_fault_env():
    env = dict(os.environ)
    env.pop("REDCLIFF_FAULT_INJECT", None)
    env.pop("REDCLIFF_FAULT_MARKER", None)
    return env


# ---------------------------------------------------------------------------
# lifecycle ledger (fleet/history.py)
# ---------------------------------------------------------------------------
def test_history_append_read_roundtrip(tmp_path):
    root = str(tmp_path)
    fleet_history.append_event(root, "submitted", request_id="r1",
                               trace_id="tr-1", tenant="a",
                               submitted_at=10.0, now=10.0)
    fleet_history.append_event(root, "claimed", request_id="r1",
                               trace_id="tr-1", worker="w-1", now=12.0)
    stats = {}
    recs = fleet_history.read_history(root, stats=stats)
    assert [r["kind"] for r in recs] == ["submitted", "claimed"]
    assert all(r["event"] == "fleet_lifecycle" for r in recs)
    assert all(r["trace_id"] == "tr-1" for r in recs)
    assert stats["torn_lines"] == 0
    # registered in the closed schema registry
    assert obs_schema.validate_records(recs) == []
    # identity triple rides every transition (the ordering contract)
    for r in recs:
        assert r["seq"] > 0 and r["pid"] == os.getpid() and r["host"]


def test_history_torn_tail_healed_and_counted(tmp_path):
    root = str(tmp_path)
    fleet_history.append_event(root, "submitted", request_id="r1", now=1.0)
    # a writer SIGKILLed mid-append: unterminated torn garbage on disk
    with open(fleet_history.history_path(root), "a") as f:
        f.write('{"event": "fleet_lifecycle", "kind": "cla')
    # the next writer's healing newline keeps its record whole
    fleet_history.append_event(root, "settled", request_id="r1",
                               state="done", now=2.0)
    stats = {}
    recs = fleet_history.read_history(root, stats=stats)
    assert [r["kind"] for r in recs] == ["submitted", "settled"]
    assert stats["torn_lines"] == 1


def test_history_unwritable_root_never_raises(tmp_path):
    # best-effort durability: an unwritable ledger is an observability
    # loss, never a queue-protocol failure
    rec = fleet_history.append_event(str(tmp_path / "no" / "such" / "dir"),
                                     "submitted", request_id="r1")
    assert rec["kind"] == "submitted"  # returned, not raised
    assert fleet_history.read_history(str(tmp_path / "absent")) == []


def test_history_rotation_cap_windows_the_ledger(tmp_path, monkeypatch):
    # REDCLIFF_HISTORY_MAX_BYTES: the head rotates like the metrics spine,
    # the chain reads back oldest-first, and backups stay capped — a
    # week-long fleet's ledger (and the per-tick SLO re-parse) is bounded
    root = str(tmp_path)
    monkeypatch.setenv(fleet_history.ENV_MAX_BYTES, "2000")
    for i in range(100):
        fleet_history.append_event(root, "submitted", request_id=f"r{i:03d}",
                                   trace_id=f"tr-{i}", tenant="t",
                                   submitted_at=1000.0 + i, now=1000.0 + i)
    head = fleet_history.history_path(root)
    assert os.path.exists(f"{head}.1")  # rotated at least once
    assert os.path.getsize(head) <= 2000 + 300  # one record of slack
    recs = fleet_history.read_history(root)
    ids = [r["request_id"] for r in recs]
    assert ids == sorted(ids) and ids[-1] == "r099"  # chain order intact
    backups = [n for n in os.listdir(root)
               if n.startswith("history.jsonl.")
               and n.rsplit(".", 1)[-1].isdigit()]
    assert 1 <= len(backups) <= fleet_history.MAX_BACKUPS
    # unset (the default) never rotates
    monkeypatch.delenv(fleet_history.ENV_MAX_BYTES)
    other = str(tmp_path / "uncapped")
    os.makedirs(other)
    for i in range(50):
        fleet_history.append_event(other, "submitted", request_id=f"r{i}",
                                   now=2000.0 + i)
    assert not os.path.exists(fleet_history.history_path(other) + ".1")


def test_queue_transitions_append_lifecycle_events(tmp_path):
    q = FleetQueue(tmp_path / "fleet")
    rid = _submit_tiny(q, "alice", deadline_s=60.0)
    [rec] = q.requests()
    assert rec["trace_id"].startswith("tr-")
    lease = q.claim(rid, "w-1", 30.0, batch_id="b1",
                    batch_request_ids=[rid], tenant="alice",
                    trace_id=rec["trace_id"])
    assert lease is not None
    q.complete(rid, result={"n_points": 1}, trace_id=rec["trace_id"])
    recs = fleet_history.read_history(str(tmp_path / "fleet"))
    by_kind = {r["kind"]: r for r in recs}
    assert set(by_kind) == {"submitted", "claimed", "settled"}
    assert by_kind["submitted"]["deadline_s"] == 60.0
    assert by_kind["submitted"]["submitted_at"] == rec["submitted_at"]
    assert by_kind["claimed"]["worker"] == "w-1"
    assert by_kind["settled"]["state"] == "done"
    # ONE trace identity across every transition
    assert {r["trace_id"] for r in recs} == {rec["trace_id"]}


def test_lease_release_appends_released_event(tmp_path):
    # a released claim (budget-route, bisection, all-or-nothing rollback)
    # puts the request back in the queue: the ledger must say so, or the
    # SLO layer under-reports the wait and the trace counters stay "busy"
    q = FleetQueue(tmp_path / "fleet")
    rid = _submit_tiny(q, "alice")
    [rec] = q.requests()
    lease = q.claim(rid, "w-1", 30.0, batch_id="b1",
                    batch_request_ids=[rid], tenant="alice",
                    trace_id=rec["trace_id"])
    lease.release()
    recs = fleet_history.read_history(str(tmp_path / "fleet"))
    assert obs_schema.validate_records(recs) == []
    assert [r["kind"] for r in recs] == ["submitted", "claimed", "released"]
    released = recs[-1]
    assert released["trace_id"] == rec["trace_id"]
    assert released["tenant"] == "alice" and released["batch_id"] == "b1"
    assert released["worker"] == "w-1"
    # a stale handle (lease since reclaimed) releases nothing, writes
    # nothing — the new owner's claim is the last word
    assert q.claim(rid, "w-2", 30.0, tenant="alice",
                   trace_id=rec["trace_id"]) is not None
    lease.release()
    kinds = [r["kind"] for r in
             fleet_history.read_history(str(tmp_path / "fleet"))]
    assert kinds == ["submitted", "claimed", "released", "claimed"]


def test_cancel_and_requeue_ride_the_ledger(tmp_path):
    q = FleetQueue(tmp_path / "fleet")
    rid = _submit_tiny(q, "t")
    [rec] = q.requests()
    assert q.cancel(rid, reason="operator")
    kinds = [r["kind"] for r in
             fleet_history.read_history(str(tmp_path / "fleet"))]
    assert kinds == ["submitted", "settled"]
    # cancel looks the trace_id up from the spool itself
    settled = fleet_history.read_history(str(tmp_path / "fleet"))[-1]
    assert settled["state"] == "canceled" \
        and settled["trace_id"] == rec["trace_id"]


# ---------------------------------------------------------------------------
# SLO math on synthetic timings (obs/slo.py) — exact, no interpolation
# ---------------------------------------------------------------------------
def test_percentile_nearest_rank_exact():
    vals = list(range(1, 101))
    assert obs_slo.percentile(vals, 50.0) == 50
    assert obs_slo.percentile(vals, 99.0) == 99
    assert obs_slo.percentile(vals, 100.0) == 100
    assert obs_slo.percentile([7.0], 99.0) == 7.0
    assert obs_slo.percentile([], 50.0) is None


def _ev(kind, rid, wall, **fields):
    rec = {"event": "fleet_lifecycle", "kind": kind, "request_id": rid,
           "wall_time": wall, "seq": int(wall * 10), "pid": 1, "host": "h"}
    rec.update(fields)
    return rec


def _synthetic_history():
    """Known timings -> exactly predictable SLO numbers (the acceptance).

    queue waits [2, 4, 8, 1] / ttfa [3, 6, 10, 0.5]; deadlines: a1 hit
    (40 <= 50), a2 miss (20 > 10), b1 miss (failed); a3 dead-lettered."""
    t = 1000.0
    return [
        _ev("submitted", "a1", t, tenant="a", submitted_at=t,
            deadline_s=50.0, trace_id="tr-a1"),
        _ev("submitted", "a2", t, tenant="a", submitted_at=t,
            deadline_s=10.0, trace_id="tr-a2"),
        _ev("submitted", "a3", t, tenant="a", submitted_at=t,
            trace_id="tr-a3"),
        _ev("submitted", "b1", t, tenant="b", submitted_at=t,
            deadline_s=100.0, trace_id="tr-b1"),
        _ev("claimed", "a1", t + 2), _ev("claimed", "a2", t + 4),
        _ev("claimed", "a3", t + 8), _ev("claimed", "b1", t + 1),
        _ev("attempt", "a1", t + 5, started_at=t + 3, attempts=1),
        _ev("attempt", "a2", t + 9, started_at=t + 6, attempts=2),
        _ev("attempt", "a3", t + 12, started_at=t + 10, attempts=1),
        _ev("attempt", "b1", t + 2, started_at=t + 0.5, attempts=3),
        _ev("settled", "a1", t + 40, state="done"),
        _ev("settled", "a2", t + 20, state="done"),
        _ev("settled", "a3", t + 30, state="deadletter"),
        _ev("settled", "b1", t + 50, state="failed"),
    ]


def test_slo_exact_on_synthetic_timings():
    slo = obs_slo.compute_slo(_synthetic_history(), thresholds={})
    ov = slo["overall"]
    assert slo["requests"] == 4 and slo["settled"] == 4
    assert ov["states"] == {"done": 2, "failed": 1, "deadletter": 1,
                            "canceled": 0}
    # nearest-rank on [1, 2, 4, 8]: p50 = rank 2 -> 2, p99 = rank 4 -> 8
    assert ov["queue_wait_s"]["p50"] == 2.0
    assert ov["queue_wait_s"]["p99"] == 8.0
    assert ov["queue_wait_s"]["max"] == 8.0
    # ttfa [0.5, 3, 6, 10]
    assert ov["ttfa_s"]["p50"] == 3.0 and ov["ttfa_s"]["p99"] == 10.0
    assert ov["deadline"]["with_deadline"] == 3 and \
        ov["deadline"]["hits"] == 1
    assert abs(ov["deadline"]["hit_pct"] - 100.0 / 3) < 1e-9
    assert ov["attempts_per_request"] == pytest.approx(7 / 4)
    assert ov["deadletter_pct"] == 25.0
    # per-tenant split: a's waits [2, 4, 8] -> p50 rank 2 -> 4
    a = slo["tenants"]["a"]
    assert a["queue_wait_s"]["p50"] == 4.0
    assert a["queue_wait_s"]["p99"] == 8.0
    assert a["deadline"]["hit_pct"] == 50.0
    b = slo["tenants"]["b"]
    assert b["queue_wait_s"]["p50"] == 1.0 and b["requests"] == 1
    assert slo["breaches"] == []  # no thresholds -> nothing checked
    json.dumps(slo, allow_nan=False)


def test_slo_requeued_deadletter_rejoins_live_population():
    recs = [
        _ev("submitted", "r1", 100.0, tenant="t", submitted_at=100.0),
        _ev("claimed", "r1", 101.0),
        _ev("settled", "r1", 105.0, state="deadletter"),
        _ev("requeued", "r1", 110.0),
    ]
    slo = obs_slo.compute_slo(recs, thresholds={})
    assert slo["requests"] == 1 and slo["settled"] == 0
    assert slo["overall"]["deadletter_pct"] is None  # judged afresh
    # the eventual re-settle is judged normally
    recs.append(_ev("settled", "r1", 120.0, state="done"))
    slo = obs_slo.compute_slo(recs, thresholds={})
    assert slo["settled"] == 1 and slo["overall"]["states"]["done"] == 1


def test_slo_settle_race_converges_to_priority_winner():
    # racing settle writers: the queue's fixed priority (done > failed >
    # deadletter > canceled) decides what survives — mirror it
    recs = [
        _ev("submitted", "r1", 10.0, tenant="t", submitted_at=10.0),
        _ev("settled", "r1", 20.0, state="deadletter"),
        _ev("settled", "r1", 21.0, state="done"),
    ]
    ov = obs_slo.compute_slo(recs, thresholds={})["overall"]
    assert ov["states"]["done"] == 1 and ov["states"]["deadletter"] == 0


def test_slo_queue_wait_ignores_rolled_back_claim():
    # a claim released before any attempt never did work — the tenant is
    # still in line, so the wait ends at the claim that reached an attempt
    t = 1000.0
    recs = [
        _ev("submitted", "r1", t, tenant="t", submitted_at=t),
        _ev("claimed", "r1", t + 1),
        _ev("released", "r1", t + 2),
        _ev("claimed", "r1", t + 30),
        _ev("attempt", "r1", t + 31, started_at=t + 31, attempts=1),
        _ev("settled", "r1", t + 40, state="done"),
    ]
    ov = obs_slo.compute_slo(recs, thresholds={})["overall"]
    assert ov["queue_wait_s"]["p50"] == 30.0  # NOT 1.0
    # a claim that reached an attempt locks the wait: the release that
    # budget-routes it afterwards doesn't reopen it
    recs2 = [
        _ev("submitted", "r2", t, tenant="t", submitted_at=t),
        _ev("claimed", "r2", t + 3),
        _ev("attempt", "r2", t + 4, started_at=t + 4, attempts=1),
        _ev("released", "r2", t + 5),
        _ev("claimed", "r2", t + 60),
    ]
    ov2 = obs_slo.compute_slo(recs2, thresholds={})["overall"]
    assert ov2["queue_wait_s"]["p50"] == 3.0
    # a claim still live at ledger end DID end the wait (worker mid-batch)
    recs3 = [
        _ev("submitted", "r3", t, tenant="t", submitted_at=t),
        _ev("claimed", "r3", t + 5),
    ]
    ov3 = obs_slo.compute_slo(recs3, thresholds={})["overall"]
    assert ov3["queue_wait_s"]["p50"] == 5.0


def test_slo_deadline_excludes_canceled():
    # a voluntary tenant cancel is not a service miss: it leaves the
    # denominator entirely instead of dragging hit-rate into false breach
    t = 1000.0
    recs = [
        _ev("submitted", "c1", t, tenant="t", submitted_at=t,
            deadline_s=50.0),
        _ev("submitted", "c2", t, tenant="t", submitted_at=t,
            deadline_s=50.0),
        _ev("settled", "c1", t + 10, state="canceled"),
        _ev("claimed", "c2", t + 1),
        _ev("attempt", "c2", t + 2, started_at=t + 2, attempts=1),
        _ev("settled", "c2", t + 20, state="done"),
    ]
    ov = obs_slo.compute_slo(recs, thresholds={})["overall"]
    assert ov["deadline"] == {"with_deadline": 1, "hits": 1,
                              "hit_pct": 100.0}
    assert ov["states"]["canceled"] == 1  # still counted as settled


def test_slo_breach_flags_from_env_knobs(monkeypatch):
    monkeypatch.setenv(obs_slo.ENV_QUEUE_P99_S, "5.0")
    monkeypatch.setenv(obs_slo.ENV_DEADLINE_PCT, "90")
    monkeypatch.setenv(obs_slo.ENV_DEADLETTER_PCT, "10")
    monkeypatch.setenv(obs_slo.ENV_TTFA_P99_S, "")  # blank = unchecked
    slo = obs_slo.compute_slo(_synthetic_history())
    assert slo["thresholds"]["queue_p99_s"] == 5.0
    assert slo["thresholds"]["ttfa_p99_s"] is None
    got = {(b["scope"], b["slo"]) for b in slo["breaches"]}
    # overall queue p99 8 > 5; hit-rate 33% < 90; dead-letter 25% > 10
    assert ("overall", "queue_p99_s") in got
    assert ("overall", "deadline_hit_pct") in got
    assert ("overall", "deadletter_pct") in got
    assert ("a", "queue_p99_s") in got          # tenant a's p99 is 8 too
    assert ("b", "queue_p99_s") not in got      # b waited 1s: within SLO
    assert not any(b["slo"] == "ttfa_p99_s" for b in slo["breaches"])


def test_slo_for_root_none_without_ledger(tmp_path):
    assert obs_slo.slo_for_root(str(tmp_path)) is None


# ---------------------------------------------------------------------------
# trace context (obs/spans.py): in-process scoping + env propagation
# ---------------------------------------------------------------------------
def test_set_trace_ctx_scopes_and_restores():
    assert obs_spans.trace_ctx() is None
    prev = obs_spans.set_trace_ctx({"batch_id": "b1"})
    try:
        assert prev is None
        assert obs_spans.trace_ctx() == {"batch_id": "b1"}
        inner = obs_spans.set_trace_ctx({"batch_id": "b2"})
        assert inner == {"batch_id": "b1"}
        obs_spans.set_trace_ctx(inner)
        assert obs_spans.trace_ctx() == {"batch_id": "b1"}
    finally:
        obs_spans.set_trace_ctx(None)
    assert obs_spans.trace_ctx() is None
    # a non-dict / empty context never sticks
    obs_spans.set_trace_ctx("garbage")
    assert obs_spans.trace_ctx() is None


def test_spans_and_metrics_records_carry_trace_ctx(tmp_path):
    ctx = {"batch_id": "b-test", "trace_ids": {"r1": "tr-1"}}
    was = obs_spans.enabled()
    prev = obs_spans.set_trace_ctx(ctx)
    try:
        obs_spans.set_enabled(True)
        with MetricLogger(str(tmp_path)) as log:
            with obs_spans.span("fleet.batch", logger=log, emit=True):
                pass
            obs_spans.record_span("fleet.plan", 1.0, logger=log, emit=True)
            log.log("fleet", kind="plan", batches=1)
        recs = read_jsonl(str(tmp_path))
        assert obs_schema.validate_records(recs) == []
        assert len(recs) == 3
        for r in recs:
            assert r["trace"] == ctx, r
    finally:
        obs_spans.set_enabled(was)
        obs_spans.set_trace_ctx(prev)


def test_trace_off_drops_metrics_stamping(tmp_path):
    # the zero-cost contract: REDCLIFF_TRACE=0 -> the decision stream is
    # bit-identical to a context-free run (no trace field anywhere)
    ctx = {"batch_id": "b-test"}
    was = obs_spans.enabled()
    prev = obs_spans.set_trace_ctx(ctx)
    try:
        obs_spans.set_enabled(False)
        assert obs_spans.span("fleet.batch") is obs_spans.NOOP
        assert obs_spans.record_span("fleet.plan", 1.0) is None
        with MetricLogger(str(tmp_path)) as log:
            log.log("fleet", kind="plan", batches=1)
        [rec] = [r for r in read_jsonl(str(tmp_path))
                 if r.get("event") == "fleet"]
        assert "trace" not in rec
    finally:
        obs_spans.set_enabled(was)
        obs_spans.set_trace_ctx(prev)


def test_trace_ctx_env_parsed_in_child_process(tmp_path):
    ctx = {"batch_id": "b-env", "trace_ids": {"r1": "tr-env"}}
    child = ("from redcliff_tpu.obs import spans\n"
             "import json\n"
             "print(json.dumps(spans.trace_ctx()))\n")
    for raw, expect in ((json.dumps(ctx), ctx),
                        ("not json {", None),     # garbage never crashes
                        ("[1, 2]", None)):        # non-dict ignored
        env = dict(os.environ, **{obs_spans.ENV_TRACE_CTX: raw})
        r = subprocess.run([sys.executable, "-c", child], env=env,
                           capture_output=True, text=True, cwd=REPO_ROOT,
                           timeout=120)
        assert r.returncode == 0, r.stderr
        assert json.loads(r.stdout) == expect


# ---------------------------------------------------------------------------
# full lifecycle through the real worker loop (stubbed supervisor, no jax)
# ---------------------------------------------------------------------------
def _stub_drain(monkeypatch, classification="clean", rc=0, captured=None):
    """Patch worker.supervise with a fake that writes every member's
    result artifact (what a healthy run_batch child would have produced)
    and captures the env the child would have received."""
    def fake(cmd, ledger_path=None, policy=None, env=None, **kw):
        if captured is not None:
            captured.append(dict(env or {}))
        if rc == 0:
            with open(cmd[-1]) as f:
                batch = json.load(f)
            d = os.path.join(batch["run_dir"], "results")
            os.makedirs(d, exist_ok=True)
            for m in batch["requests"]:
                n = len(m.get("points") or ())
                with open(os.path.join(d, f"{m['request_id']}.json"),
                          "w") as f:
                    json.dump({"request_id": m["request_id"],
                               "n_points": n, "failures": [],
                               "best_criteria": [0.5] * n}, f)
        return SuperviseOutcome(classification=classification,
                                returncode=rc, attempts=[{"rc": rc}])

    monkeypatch.setattr(worker_mod, "supervise", fake)


def test_full_lifecycle_one_trace_id_per_request(tmp_path, monkeypatch):
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    rids = [_submit_tiny(q, t) for t in ("alice", "bob")]
    traces = {r["request_id"]: r["trace_id"] for r in q.requests()}
    captured = []
    _stub_drain(monkeypatch, captured=captured)
    assert worker_mod.work(str(root), drain=True, poll_s=0.1,
                           worker_id="w-test") == 1  # merged: ONE batch
    recs = fleet_history.read_history(str(root))
    assert obs_schema.validate_records(recs) == []
    kinds = [r["kind"] for r in recs]
    assert kinds.count("submitted") == 2 and kinds.count("claimed") == 2
    assert kinds.count("attempt") == 2 and kinds.count("settled") == 2
    [planned] = [r for r in recs if r["kind"] == "planned"]
    assert set(planned["requests"]) == set(rids)
    assert planned["trace_ids"] == traces
    for rid in rids:
        mine = [r for r in recs if r.get("request_id") == rid]
        # the whole lifecycle under the submit-minted identity
        assert {r["trace_id"] for r in mine} == {traces[rid]}
        [settled] = [r for r in mine if r["kind"] == "settled"]
        assert settled["state"] == "done"
        [att] = [r for r in mine if r["kind"] == "attempt"]
        assert att["classification"] == "clean" and att["batch_id"]
        assert att["started_at"] <= settled["wall_time"]
    # the child env carried the same join keys (REDCLIFF_TRACE_CTX)
    [env] = captured
    ctx = json.loads(env[obs_spans.ENV_TRACE_CTX])
    assert ctx["trace_ids"] == traces
    # worker's own fleet events carry the context while the batch ran
    stamped = [r for r in read_jsonl(str(root))
               if r.get("event") == "fleet"
               and r.get("kind") in ("batch_start", "batch_end")]
    assert stamped and all(
        r["trace"]["trace_ids"] == traces for r in stamped)
    # ... and the context never leaks past the batch
    assert obs_spans.trace_ctx() is None


def test_deadletter_settle_linked_to_trace(tmp_path, monkeypatch):
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    rid = _submit_tiny(q, "t")
    [rec] = q.requests()
    _stub_drain(monkeypatch, classification="giving_up", rc=139)
    worker_mod.work(str(root), drain=True, poll_s=0.1, max_attempts=1)
    assert q.terminal_state(rid) == "deadletter"
    recs = fleet_history.read_history(str(root))
    [settled] = [r for r in recs if r["kind"] == "settled"]
    assert settled["state"] == "deadletter"
    assert settled["trace_id"] == rec["trace_id"]


def test_bisected_round_links_member_traces(tmp_path, monkeypatch):
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    rids = [_submit_tiny(q, f"t{i}") for i in range(4)]
    traces = {r["request_id"]: r["trace_id"] for r in q.requests()}
    _stub_drain(monkeypatch, classification="giving_up", rc=137)
    worker_mod.work(str(root), once=True, poll_s=0.1)
    recs = fleet_history.read_history(str(root))
    [bis] = [r for r in recs if r["kind"] == "bisected"]
    assert set(bis["requests"]) == set(rids)
    assert bis["trace_ids"] == traces
    assert len(bis["halves"]) == 2


def test_worker_crash_emits_event_and_flight_record(tmp_path, monkeypatch):
    root = tmp_path / "fleet"
    FleetQueue(root)

    def boom(*a, **kw):
        raise RuntimeError("induced worker-loop crash")

    monkeypatch.setattr(worker_mod, "_next_batch", boom)
    with pytest.raises(RuntimeError, match="induced"):
        worker_mod.work(str(root), drain=True, poll_s=0.1)
    recs = read_jsonl(str(root))
    assert obs_schema.validate_records(recs) == []
    [crash] = [r for r in recs if r.get("kind") == "worker_crash"]
    assert "RuntimeError" in crash["error"]
    assert crash["flight_record"] and os.path.exists(crash["flight_record"])
    with open(crash["flight_record"]) as f:
        dump = json.load(f)
    assert dump["reason"] == "worker_crash"


# ---------------------------------------------------------------------------
# fleet trace export (obs trace --fleet)
# ---------------------------------------------------------------------------
def test_fleet_trace_joins_ledger_into_request_tracks(tmp_path,
                                                      monkeypatch):
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    rids = [_submit_tiny(q, t) for t in ("alice", "bob")]
    traces = {r["request_id"]: r["trace_id"] for r in q.requests()}
    _stub_drain(monkeypatch)
    worker_mod.work(str(root), drain=True, poll_s=0.1)
    trace = build_fleet_trace(str(root))
    assert validate_trace(trace) == [], validate_trace(trace)[:3]
    json.dumps(trace, allow_nan=False)
    ev = trace["traceEvents"]
    # one X track per request, spanning submit -> settle under its
    # submit-minted trace_id
    tracks = {e["args"]["request_id"]: e for e in ev
              if e.get("cat") == "request" and e["ph"] == "X"}
    assert set(tracks) == set(rids)
    for rid, tr in tracks.items():
        assert tr["args"]["trace_id"] == traces[rid]
        assert tr["args"]["state"] == "done"
        assert tr["dur"] > 0
    # lifecycle instants ride each request's thread
    insts = [e for e in ev if e.get("cat") == "fleet_lifecycle"
             and e["ph"] == "i"]
    assert {e["name"] for e in insts} >= {"submitted", "claimed",
                                          "attempt", "settled", "planned"}
    # queue counter curves replayed from the ledger
    counters = {e["name"] for e in ev if e["ph"] == "C"}
    assert {"queue_depth", "in_flight", "deadletter_depth"} <= counters
    depth = [e["args"]["queued"] for e in ev
             if e["ph"] == "C" and e["name"] == "queue_depth"]
    assert max(depth) == 2 and depth[-1] == 0  # drained
    od = trace["otherData"]
    assert od["history_records"] >= 7 and od["torn_lines"] == 0


def test_fleet_trace_counters_track_released_claims(tmp_path):
    # a released claim returns the request to the queue: the in-flight
    # curve must come back down and queue depth back up, or the counters
    # read "busy" through exactly the crash-loop incidents they diagnose
    root = tmp_path / "fleet"
    os.makedirs(root)
    t = 1000.0
    recs = [
        _ev("submitted", "r1", t, tenant="t", submitted_at=t,
            trace_id="tr-r1"),
        _ev("claimed", "r1", t + 1),
        _ev("released", "r1", t + 2),
        _ev("claimed", "r1", t + 3),
        _ev("settled", "r1", t + 4, state="done"),
    ]
    with open(os.path.join(root, "history.jsonl"), "w") as f:
        for rec in recs:
            f.write(json.dumps(rec) + "\n")
    trace = build_fleet_trace(str(root))
    assert validate_trace(trace) == [], validate_trace(trace)[:3]
    ev = trace["traceEvents"]
    queued = [e["args"]["queued"] for e in ev
              if e.get("ph") == "C" and e["name"] == "queue_depth"]
    inflight = [e["args"]["in_flight"] for e in ev
                if e.get("ph") == "C" and e["name"] == "in_flight"]
    assert queued == [1, 0, 1, 0, 0]
    assert inflight == [0, 1, 0, 1, 0]
    # the release rides the request's own track as an instant too
    assert "released" in {e["name"] for e in ev if e.get("ph") == "i"}


def test_fleet_trace_cli_flag_and_exit_codes(tmp_path, capsys):
    from redcliff_tpu.obs.trace_export import main as trace_main

    # a submit-only fleet root (no metrics chain yet) still exports
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    _submit_tiny(q, "t")
    out_path = str(tmp_path / "trace.json")
    assert trace_main([str(root), "--fleet", "-o", out_path]) == 0
    with open(out_path) as f:
        trace = json.load(f)
    assert validate_trace(trace) == []
    [track] = [e for e in trace["traceEvents"]
               if e.get("cat") == "request" and e["ph"] == "X"]
    assert track["args"]["state"] == "live"
    capsys.readouterr()
    # a non-fleet empty dir is refused with the exit-2 contract
    empty = tmp_path / "empty"
    empty.mkdir()
    assert trace_main([str(empty), "--fleet"]) == 2
    # the obs dispatcher passes --fleet through
    from redcliff_tpu.obs.report import main as obs_main

    assert obs_main(["trace", str(root), "--fleet", "-o", out_path]) == 0


def test_fleet_trace_tolerates_rotated_chain_with_sigkill_torn_tail(
        tmp_path):
    """Satellite: the PR-8 rotation-boundary pattern at the FLEET root — a
    worker writing fleet metrics through a small rotation cap dies by
    SIGKILL mid-append on both the metrics chain and the history ledger;
    watch fleet mode and the fleet trace export must see every whole
    record and count both torn tails."""
    root = tmp_path / "fleet"
    spec = json.dumps(TINY_SPEC)
    child = (
        "import os, signal, json\n"
        "from redcliff_tpu.obs.logging import MetricLogger\n"
        "from redcliff_tpu.fleet.queue import FleetQueue\n"
        "from redcliff_tpu.fleet import history\n"
        f"root = {str(root)!r}\n"
        "q = FleetQueue(root)\n"
        f"spec = json.loads({spec!r})\n"
        "for i in range(3):\n"
        "    q.submit('rot', [{'gen_lr': 1e-3}], spec=spec)\n"
        "log = MetricLogger(root, max_bytes=400, max_backups=20)\n"
        "for i in range(12):\n"
        "    log.log('fleet', kind='plan', batches=0, queue_depth=3,\n"
        "            unschedulable=0, plan_ms=0.1)\n"
        "log._fh.write('{\"event\": \"fleet\", \"kind\": \"plan\", \"qu')\n"
        "log._fh.flush()\n"
        "with open(history.history_path(root), 'a') as f:\n"
        "    f.write('{\"event\": \"fleet_lifecycle\", \"kind\": \"cl')\n"
        "    f.flush()\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n")
    r = subprocess.run([sys.executable, "-c", child], cwd=REPO_ROOT,
                       timeout=120, env=_clean_fault_env())
    assert r.returncode == -9
    assert "metrics.jsonl.1" in os.listdir(root), \
        "no rotation happened: cap too big"
    # watch fleet mode over the rotated+torn chain: every whole record
    from redcliff_tpu.obs.watch import build_snapshot, render_text

    snap = build_snapshot(str(root))
    assert obs_schema.validate_record(snap) == []
    assert snap["fleet"]["counts"]["queued"] == 3
    assert snap["fleet"]["last_plan"]["queue_depth"] == 3
    assert snap["read_audit"]["torn_lines"] == 1
    assert len(snap["read_audit"]["files"]) > 1
    # the SLO headline is live from the (torn) ledger: 3 submitted
    assert snap["fleet"]["slo"]["requests"] == 3
    assert snap["fleet"]["slo"]["settled"] == 0
    assert "slo:" in render_text(snap)
    # the fleet trace joins the same chain and counts BOTH torn tails
    trace = build_fleet_trace(str(root))
    assert validate_trace(trace) == []
    od = trace["otherData"]
    assert od["torn_lines"] == 2
    assert od["history_records"] == 3
    tracks = [e for e in trace["traceEvents"]
              if e.get("cat") == "request" and e["ph"] == "X"]
    assert len(tracks) == 3


# ---------------------------------------------------------------------------
# fleet status CLI: per-request queue/terminal ages (satellite)
# ---------------------------------------------------------------------------
def test_status_per_request_ages(tmp_path):
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    rid = _submit_tiny(q, "aged", now=time.time() - 30.0)
    done = _submit_tiny(q, "aged")
    q.cancel(done, now=time.time() - 5.0)
    st = q.status(include_requests=True)
    rows = {r["request_id"]: r for r in st["requests"]}
    assert rows[rid]["state"] == "queued"
    assert 29.0 <= rows[rid]["queue_age_s"] <= 120.0
    assert rows[rid]["terminal_age_s"] is None
    assert rows[rid]["trace_id"].startswith("tr-")
    assert rows[done]["state"] == "canceled"
    assert rows[done]["queue_age_s"] is None
    assert 4.0 <= rows[done]["terminal_age_s"] <= 120.0
    # off by default: follow-mode watchers must not pay the reads
    assert "requests" not in q.status()


def test_status_cli_renders_age_table(tmp_path):
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    rid = _submit_tiny(q, "cli")
    out = subprocess.run(
        [sys.executable, "-m", "redcliff_tpu.fleet", "status", "--root",
         str(root)], capture_output=True, text=True,
        env=_clean_fault_env(), cwd=REPO_ROOT)
    assert out.returncode == 0, out.stderr
    assert "queue age" in out.stdout and rid in out.stdout
    out = subprocess.run(
        [sys.executable, "-m", "redcliff_tpu.fleet", "status", "--root",
         str(root), "--json"], capture_output=True, text=True,
        env=_clean_fault_env(), cwd=REPO_ROOT)
    st = json.loads(out.stdout)
    [row] = st["requests"]
    assert row["request_id"] == rid and row["queue_age_s"] >= 0


# ---------------------------------------------------------------------------
# obs report: fleet-SLO section
# ---------------------------------------------------------------------------
def test_report_fleet_slo_section(tmp_path, monkeypatch):
    from redcliff_tpu.obs.report import build_report, render_text

    root = tmp_path / "fleet"
    q = FleetQueue(root)
    _submit_tiny(q, "alice")
    _submit_tiny(q, "bob")
    _stub_drain(monkeypatch)
    worker_mod.work(str(root), drain=True, poll_s=0.1)
    monkeypatch.setenv(obs_slo.ENV_QUEUE_P99_S, "0.000001")
    rep = build_report(str(root))
    slo = rep["fleet_slo"]
    assert slo["requests"] == 2 and slo["settled"] == 2
    assert set(slo["tenants"]) == {"alice", "bob"}
    assert slo["overall"]["states"]["done"] == 2
    assert slo["overall"]["queue_wait_s"]["n"] == 2
    # a sub-microsecond threshold must flag (real waits exceed it)
    assert any(b["slo"] == "queue_p99_s" for b in slo["breaches"])
    text = render_text(rep)
    assert "fleet SLOs" in text and "SLO BREACH" in text
    # a plain run dir has no SLO section
    monkeypatch.delenv(obs_slo.ENV_QUEUE_P99_S)
    plain = tmp_path / "plain"
    with MetricLogger(str(plain)) as log:
        log.log("fit_start", model="m", grid_size=1, grid_width=1)
        log.log("fit_end")
    assert build_report(str(plain))["fleet_slo"] is None


# ---------------------------------------------------------------------------
# end-to-end: one real supervised drain (jax child; warm compile cache)
# ---------------------------------------------------------------------------
def test_e2e_supervised_drain_trace_joins_across_processes(tmp_path):
    """ISSUE 12 acceptance: a real multi-tenant drain (supervised jax
    child) exports one Perfetto trace where each request's track spans
    submit -> settle under its submit-minted trace_id, the CHILD
    process's records carry the same join keys (the cross-process half),
    and the SLO section computes from the surviving ledger."""
    from redcliff_tpu.runtime.retry import RetryPolicy
    from redcliff_tpu.runtime.supervisor import SupervisorPolicy

    root = tmp_path / "fleet"
    q = FleetQueue(root)
    rids = [_submit_tiny(q, t) for t in ("alice", "bob")]
    traces = {r["request_id"]: r["trace_id"] for r in q.requests()}
    policy = SupervisorPolicy(
        max_restarts=2,
        backoff=RetryPolicy(max_attempts=100, base_delay_s=0.05,
                            multiplier=1.0, max_delay_s=0.05))
    n = worker_mod.work(str(root), drain=True, poll_s=0.2, lease_s=20.0,
                        supervisor_policy=policy, env=_clean_fault_env())
    assert n == 1
    assert q.status()["counts"]["done"] == 2
    # the supervised CHILD's own records carry the trace join keys: the
    # identity crossed the process boundary via REDCLIFF_TRACE_CTX
    [batch_dir] = [os.path.join(root, "work", d)
                   for d in os.listdir(root / "work")]
    child_recs = read_jsonl(batch_dir)
    own_pid = os.getpid()
    stamped = [r for r in child_recs
               if r.get("trace") and r.get("pid") != own_pid]
    assert stamped, "child wrote no trace-stamped records"
    for r in stamped:
        assert r["trace"]["trace_ids"] == traces
    # one joined timeline: request tracks + child process lanes together
    trace = build_fleet_trace(str(root))
    assert validate_trace(trace) == []
    ev = trace["traceEvents"]
    tracks = {e["args"]["request_id"]: e for e in ev
              if e.get("cat") == "request" and e["ph"] == "X"}
    assert set(tracks) == set(rids)
    for rid in rids:
        assert tracks[rid]["args"]["trace_id"] == traces[rid]
        assert tracks[rid]["args"]["state"] == "done"
    # >= 3 process lanes: worker control process, jax child, synthetic
    # fleet-requests/queue processes
    lanes = {e["args"]["name"] for e in ev
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert len(lanes) >= 4, lanes
    # SLO view computes from the ledger the drain left behind
    slo = obs_slo.slo_for_root(str(root))
    assert slo["settled"] == 2
    assert slo["overall"]["deadline"] is None       # none requested
    assert slo["overall"]["queue_wait_s"]["p99"] >= 0
    assert slo["overall"]["attempts_per_request"] == 1.0
