"""Direct numerical A/B against the actual torch reference implementation.

Loads /root/reference/models/redcliff_s_cmlp.py (and the withStateSmoothing
variant), copies ONE set of torch weights into the JAX pytree, and asserts on
identical inputs:

* forward outputs (x_sims, per-factor preds, factor weightings, state labels)
  under BOTH forward_pass modes (ref :249-319, :322-381),
* every loss term (forecasting, factor, cosine, fw-L1, adj-L1 — ref :620-686 —
  plus the Smooth variant's fw_smoothing term, ref Smooth :667-692) under all
  three phase gatings and all three label-shape conventions,
* all 9 GC readout modes, lagged and unlagged (ref :411-617),

to float32 tolerance. Covered embedders: Vanilla (MLPClassifierForMultiple/
SingleObjectives) and cEmbedder — both pure torch in the reference. The DGCNN
embedder depends on the external torcheeg package, which is not installed, so
the reference's own DGCNN path cannot execute here (stubbing it with our
reimplementation would make the A/B circular); it is exercised by the native
tests in test_dgcnn.py instead.

The reference is imported from its own directory with stub torcheeg/pywt
modules (import-time dependencies only; no stubbed code runs in these tests).

Also A/B'd against the actual reference code here: the DYNOTEARS
augmented-Lagrangian solver (scipy vs scipy, incl. the warm-started refit
chain), NAVAR (forward, contributions, std-over-windows causal matrix),
cLSTM (stacked-LSTM forward + input-norm GC), DCSFA-NMF (eval-mode
transform, class predictions, reconstruction, W_nmf GC readout incl. the
reference's off-diagonal-doubling unflatten), and the mvts TS transformer
(BatchNorm encoder + classiregressor head) — six model families total.
"""
import sys
import types

import numpy as np
import pytest

torch = pytest.importorskip("torch")


# --------------------------------------------------------------------------
# reference import scaffolding
# --------------------------------------------------------------------------
@pytest.fixture(scope="module")
def ref():
    from conftest import add_reference_to_path

    add_reference_to_path(extra_stubs=[
        ("torcheeg", {}),
        ("torcheeg.models", {"DGCNN": type("DGCNN", (), {})}),
    ])
    sys.modules["torcheeg"].models = sys.modules["torcheeg.models"]
    from models.redcliff_s_cmlp import REDCLIFF_S_CMLP
    from models.redcliff_s_cmlp_withStateSmoothing import (
        REDCLIFF_S_CMLP_withStateSmoothing,
    )

    ns = types.SimpleNamespace(
        REDCLIFF_S_CMLP=REDCLIFF_S_CMLP,
        Smooth=REDCLIFF_S_CMLP_withStateSmoothing,
    )
    return ns


# shared shape/coefficient configuration (multi-layer factors, K > S so both
# supervised and unsupervised factors exist, num_sims > 2 so the 3-point
# smoothing branch runs)
C, GEN_LAG, EMBED_LAG = 5, 3, 6
GEN_HIDDEN = [8, 6]
EMBED_HIDDEN = [12]
K, S, NUM_SIMS = 4, 2, 3
ECC = 10.0
COEFFS = dict(FORECAST_COEFF=1.0, FACTOR_SCORE_COEFF=2.0,
              FACTOR_COS_SIM_COEFF=0.3, FACTOR_WEIGHT_L1_COEFF=0.05,
              ADJ_L1_REG_COEFF=0.01, DAGNESS_REG_COEFF=0.0,
              DAGNESS_LAG_COEFF=0.0, DAGNESS_NODE_COEFF=0.0)
MAX_LAG = max(GEN_LAG, EMBED_LAG)


def _build_ref_model(ref, embedder_type, forward_mode, gc_mode,
                     smooth=False, num_sims=NUM_SIMS):
    coeffs = dict(COEFFS)
    if smooth:
        coeffs["FACTOR_WEIGHT_SMOOTHING_PENALTY_COEFF"] = 0.7
    embedder_args = []
    if embedder_type == "cEmbedder":
        # ctor appends these positionally after (num_chans, S, K, sigmoid):
        # sigmoid_eccentricity_coeff, embed_lag, hidden (ref :109-116)
        embedder_args = [("sigmoid_eccentricity_coeff", ECC),
                         ("embed_lag", EMBED_LAG),
                         ("hidden", list(EMBED_HIDDEN))]
    cls = ref.Smooth if smooth else ref.REDCLIFF_S_CMLP
    torch.manual_seed(0)
    return cls(
        num_chans=C, gen_lag=GEN_LAG, gen_hidden=list(GEN_HIDDEN),
        embed_lag=EMBED_LAG, embed_hidden_sizes=list(EMBED_HIDDEN),
        num_in_timesteps=MAX_LAG, num_out_timesteps=1, num_factors=K,
        num_supervised_factors=S, coeff_dict=coeffs,
        use_sigmoid_restriction=True, factor_score_embedder_type=embedder_type,
        factor_score_embedder_args=embedder_args,
        primary_gc_est_mode=gc_mode, forward_pass_mode=forward_mode,
        num_sims=num_sims, training_mode="combined",
    )


def _build_jax_model(embedder_type, forward_mode, gc_mode, smooth=False,
                     num_sims=NUM_SIMS):
    from redcliff_tpu.models.redcliff import RedcliffSCMLP, RedcliffSCMLPConfig

    return RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=C, gen_lag=GEN_LAG, gen_hidden=tuple(GEN_HIDDEN),
        embed_lag=EMBED_LAG, embed_hidden_sizes=tuple(EMBED_HIDDEN),
        num_factors=K, num_supervised_factors=S,
        forecast_coeff=COEFFS["FORECAST_COEFF"],
        factor_score_coeff=COEFFS["FACTOR_SCORE_COEFF"],
        factor_cos_sim_coeff=COEFFS["FACTOR_COS_SIM_COEFF"],
        factor_weight_l1_coeff=COEFFS["FACTOR_WEIGHT_L1_COEFF"],
        adj_l1_reg_coeff=COEFFS["ADJ_L1_REG_COEFF"],
        factor_weight_smoothing_penalty_coeff=0.7 if smooth else 0.0,
        use_sigmoid_restriction=True, sigmoid_eccentricity_coeff=ECC,
        factor_score_embedder_type=("Vanilla_Embedder"
                                    if embedder_type == "Vanilla_Embedder"
                                    else embedder_type),
        primary_gc_est_mode=gc_mode, forward_pass_mode=forward_mode,
        num_sims=num_sims, training_mode="combined",
    ))


# --------------------------------------------------------------------------
# torch -> JAX weight copying
# --------------------------------------------------------------------------
def _np(t):
    return t.detach().cpu().numpy()


def _copy_factors(ref_model):
    """cMLP factor stack: ref factors[k].networks[c].layers[li] Conv1d weights
    -> our layer list of {w (K, C, h, C, L) | (K, C, d_out, d_in), b}."""
    n_layers = len(ref_model.factors[0].networks[0].layers)
    layers = []
    for li in range(n_layers):
        w_k, b_k = [], []
        for factor in ref_model.factors:
            w_c = np.stack([_np(net.layers[li].weight) for net in factor.networks])
            b_c = np.stack([_np(net.layers[li].bias) for net in factor.networks])
            if li > 0:  # 1x1 conv: (d_out, d_in, 1) -> (d_out, d_in)
                w_c = w_c[..., 0]
            w_k.append(w_c)
            b_k.append(b_c)
        layers.append({"w": np.stack(w_k), "b": np.stack(b_k)})
    return layers


def _copy_vanilla_multi_embedder(ref_model):
    e = ref_model.factor_score_embedder
    p = {"trunk": {
        "conv1": _np(e.series_embedding_layers[0].weight)[:, 0],
        "conv2": _np(e.series_embedding_layers[2].weight)[:, :, 0],
    }}
    if e.unsup_factor_weighting_layer is not None:
        p["unsup_head"] = _np(e.unsup_factor_weighting_layer.weight).T
    return p


def _copy_cembedder(ref_model):
    e = ref_model.factor_score_embedder
    n_layers = len(e.networks[0].layers)
    nets = []
    for li in range(n_layers):
        w = np.stack([_np(net.layers[li].weight) for net in e.networks])
        b = np.stack([_np(net.layers[li].bias) for net in e.networks])
        if li > 0:
            w = w[..., 0]
        nets.append({"w": w, "b": b})
    return {"nets": nets}


def _copy_params(ref_model, embedder_type):
    import jax.numpy as jnp

    if embedder_type == "Vanilla_Embedder":
        emb = _copy_vanilla_multi_embedder(ref_model)
    elif embedder_type == "cEmbedder":
        emb = _copy_cembedder(ref_model)
    else:
        raise NotImplementedError(embedder_type)
    params = {"embedder": emb, "factors": _copy_factors(ref_model)}
    import jax

    return jax.tree.map(jnp.asarray, params)


def _data(rng, batch=7, label_shape="trace"):
    T = MAX_LAG + NUM_SIMS + 2
    X = rng.normal(size=(batch, T, C)).astype(np.float32)
    if label_shape == "trace":
        Y = rng.uniform(size=(batch, S + 1, T)).astype(np.float32)
    elif label_shape == "static3":
        Y = rng.uniform(size=(batch, S + 1, 1)).astype(np.float32)
    else:  # 2-D (orig DREAM4)
        Y = rng.uniform(size=(batch, S + 1)).astype(np.float32)
    return X, Y


# --------------------------------------------------------------------------
# forward parity
# --------------------------------------------------------------------------
@pytest.mark.parametrize("embedder_type", ["Vanilla_Embedder", "cEmbedder"])
@pytest.mark.parametrize("forward_mode", [
    "apply_factor_weights_at_each_sim_step",
    "apply_factor_weights_after_sim_completion",
])
def test_forward_parity(ref, embedder_type, forward_mode):
    gc_mode = "fixed_factor_exclusive"
    ref_model = _build_ref_model(ref, embedder_type, forward_mode, gc_mode)
    jax_model = _build_jax_model(embedder_type, forward_mode, gc_mode)
    params = _copy_params(ref_model, embedder_type)
    X, _ = _data(np.random.default_rng(0))
    Xw = X[:, :MAX_LAG, :]

    with torch.no_grad():
        r_sims, r_fp, r_fw, r_lab = ref_model.forward(torch.from_numpy(Xw))
    j_sims, j_fp, j_fw, j_lab = jax_model.forward(params, Xw)

    np.testing.assert_allclose(np.asarray(j_sims), _np(r_sims),
                               rtol=1e-5, atol=1e-5)
    assert len(j_fw) == len(r_fw)
    for jw, rw in zip(j_fw, r_fw):
        np.testing.assert_allclose(np.asarray(jw), _np(rw), rtol=1e-5, atol=1e-6)
    assert len(j_lab) == len(r_lab)
    for jl, rl in zip(j_lab, r_lab):
        np.testing.assert_allclose(np.asarray(jl), _np(rl), rtol=1e-5, atol=1e-6)

    if forward_mode == "apply_factor_weights_at_each_sim_step":
        # ref: list (sims) of list (K) of (B, 1, C); ours: list (sims) of (K, B, 1, C)
        for jp, rp in zip(j_fp, r_fp):
            np.testing.assert_allclose(np.asarray(jp),
                                       np.stack([_np(t) for t in rp]),
                                       rtol=1e-5, atol=1e-5)
    else:
        # ref: list (K) of (B, S, C); ours: list (sims) of (K, B, 1, C)
        ours = np.concatenate([np.asarray(p) for p in j_fp], axis=2)  # (K, B, S, C)
        theirs = np.stack([_np(t) for t in r_fp])  # (K, B, S, C)
        np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-5)


# --------------------------------------------------------------------------
# loss-term parity
# --------------------------------------------------------------------------
@pytest.mark.parametrize("label_shape", ["trace", "static3", "static2"])
@pytest.mark.parametrize("phase,flags", [
    ("combined", dict(embedder_pretrain_loss=False, factor_pretrain_loss=False)),
    ("embedder_pretrain", dict(embedder_pretrain_loss=True, factor_pretrain_loss=False)),
    ("factor_pretrain", dict(embedder_pretrain_loss=False, factor_pretrain_loss=True)),
])
def test_loss_term_parity(ref, label_shape, phase, flags):
    embedder_type = "Vanilla_Embedder"
    forward_mode = "apply_factor_weights_at_each_sim_step"
    gc_mode = "conditional_factor_fixed_embedder"
    # embedder GC modes need a causal embedder
    gc_mode_for = "fixed_factor_exclusive"
    ref_model = _build_ref_model(ref, embedder_type, forward_mode, gc_mode_for)
    jax_model = _build_jax_model(embedder_type, forward_mode, gc_mode_for)
    params = _copy_params(ref_model, embedder_type)
    X, Y = _data(np.random.default_rng(1), label_shape=label_shape)
    Xw = X[:, :MAX_LAG, :]
    targets = X[:, MAX_LAG : MAX_LAG + NUM_SIMS, :]

    with torch.no_grad():
        r_sims, _, _, r_lab = ref_model.forward(torch.from_numpy(Xw))
        r_combo, r_terms = ref_model.compute_loss(
            torch.from_numpy(X[:, :EMBED_LAG, :]), r_sims,
            torch.from_numpy(targets), r_lab, torch.from_numpy(Y),
            gc_mode_for, **flags)
    r_forecast, r_factor, r_cos, r_l1, r_adj, _ = r_terms

    j_combo, j_parts = jax_model.loss_for_phase(params, X, Y, phase)
    np.testing.assert_allclose(float(j_parts["forecasting_loss"]),
                               float(r_forecast), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(j_parts["factor_loss"]),
                               float(r_factor), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(j_parts["fw_l1_penalty"]),
                               float(r_l1), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(j_parts["adj_l1_penalty"]),
                               float(r_adj), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(j_parts["factor_cos_sim_penalty"]),
                               float(r_cos), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(j_combo), float(r_combo),
                               rtol=1e-5, atol=1e-5)


def test_loss_parity_with_conditional_gc_mode_in_loss(ref):
    """The canonical experiment configuration scores GC in-loss with the
    conditional_factor_fixed_embedder mode, which requires a causal embedder
    (cEmbedder here; D4IC uses DGCNN)."""
    embedder_type = "cEmbedder"
    forward_mode = "apply_factor_weights_at_each_sim_step"
    gc_mode = "conditional_factor_fixed_embedder"
    ref_model = _build_ref_model(ref, embedder_type, forward_mode, gc_mode)
    jax_model = _build_jax_model(embedder_type, forward_mode, gc_mode)
    params = _copy_params(ref_model, embedder_type)
    X, Y = _data(np.random.default_rng(2), label_shape="trace")
    Xw = X[:, :MAX_LAG, :]
    targets = X[:, MAX_LAG : MAX_LAG + NUM_SIMS, :]

    with torch.no_grad():
        r_sims, _, _, r_lab = ref_model.forward(torch.from_numpy(Xw))
        r_combo, r_terms = ref_model.compute_loss(
            torch.from_numpy(X[:, :EMBED_LAG, :]), r_sims,
            torch.from_numpy(targets), r_lab, torch.from_numpy(Y), gc_mode)
    j_combo, j_parts = jax_model.loss_for_phase(params, X, Y, "combined")
    np.testing.assert_allclose(float(j_parts["factor_cos_sim_penalty"]),
                               float(r_terms[2]), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(j_parts["adj_l1_penalty"]),
                               float(r_terms[4]), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(j_combo), float(r_combo),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("num_sims", [2, 3])
def test_smoothing_term_parity(ref, num_sims):
    """Smooth variant: the epsilon-masked (num_sims == 2) and 3-point
    monotonicity (num_sims > 2) smoothing penalties (ref Smooth :667-692)."""
    embedder_type = "Vanilla_Embedder"
    forward_mode = "apply_factor_weights_at_each_sim_step"
    gc_mode = "fixed_factor_exclusive"
    ref_model = _build_ref_model(ref, embedder_type, forward_mode, gc_mode,
                                 smooth=True, num_sims=num_sims)
    jax_model = _build_jax_model(embedder_type, forward_mode, gc_mode,
                                 smooth=True, num_sims=num_sims)
    assert float(ref_model.STATE_SCORE_SMOOTHING_EPSILON) == pytest.approx(
        jax_model.config.state_score_smoothing_epsilon)
    params = _copy_params(ref_model, embedder_type)
    X, Y = _data(np.random.default_rng(3), label_shape="trace")
    Xw = X[:, :MAX_LAG, :]
    targets = X[:, MAX_LAG : MAX_LAG + num_sims, :]

    with torch.no_grad():
        r_sims, _, _, r_lab = ref_model.forward(torch.from_numpy(Xw))
        r_combo, r_terms = ref_model.compute_loss(
            torch.from_numpy(X[:, :EMBED_LAG, :]), r_sims,
            torch.from_numpy(targets), r_lab, torch.from_numpy(Y), gc_mode)
    # Smooth variant term order: [forecast, factor, cos, fw_l1, SMOOTH, adj, dag]
    r_smooth = r_terms[4]
    j_combo, j_parts = jax_model.loss_for_phase(params, X, Y, "combined")
    np.testing.assert_allclose(float(j_parts["fw_smoothing_penalty"]),
                               float(r_smooth), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(j_combo), float(r_combo),
                               rtol=1e-5, atol=1e-5)


# --------------------------------------------------------------------------
# GC readout parity — all 9 modes
# --------------------------------------------------------------------------
FACTOR_ONLY_MODES = ["fixed_factor_exclusive", "conditional_factor_exclusive"]
ALL_MODES = [
    "fixed_factor_exclusive", "raw_embedder", "conditional_factor_exclusive",
    "fixed_embedder_exclusive", "conditional_embedder_exclusive",
    "fixed_factor_fixed_embedder", "conditional_factor_fixed_embedder",
    "fixed_factor_conditional_embedder",
    "conditional_factor_conditional_embedder",
]


def _assert_gc_match(jax_model, params, ref_model, mode, X, ignore_lag):
    with torch.no_grad():
        r = ref_model.GC(mode, X=None if "conditional" not in mode
                         else torch.from_numpy(X),
                         threshold=False, ignore_lag=ignore_lag)
    j = jax_model.gc_as_lists(params, mode,
                              X=None if "conditional" not in mode else X,
                              threshold=False, ignore_lag=ignore_lag)
    assert len(j) == len(r), (mode, len(j), len(r))
    for s, (js, rs) in enumerate(zip(j, r)):
        assert len(js) == len(rs), (mode, s, len(js), len(rs))
        for jf, rf in zip(js, rs):
            rf = _np(rf)
            if rf.ndim == 2:
                rf = rf[:, :, None]
            np.testing.assert_allclose(np.asarray(jf), rf, rtol=1e-5,
                                       atol=1e-6, err_msg=f"{mode} il={ignore_lag}")


@pytest.mark.parametrize("ignore_lag", [True, False])
@pytest.mark.parametrize("mode", ALL_MODES)
def test_gc_readout_parity_cembedder(ref, mode, ignore_lag):
    embedder_type = "cEmbedder"
    ref_model = _build_ref_model(
        ref, embedder_type, "apply_factor_weights_at_each_sim_step", mode)
    jax_model = _build_jax_model(
        embedder_type, "apply_factor_weights_at_each_sim_step", mode)
    params = _copy_params(ref_model, embedder_type)
    X = np.random.default_rng(4).normal(size=(6, MAX_LAG, C)).astype(np.float32)
    _assert_gc_match(jax_model, params, ref_model, mode, X, ignore_lag)


@pytest.mark.parametrize("ignore_lag", [True, False])
@pytest.mark.parametrize("mode", FACTOR_ONLY_MODES)
def test_gc_readout_parity_vanilla(ref, mode, ignore_lag):
    embedder_type = "Vanilla_Embedder"
    ref_model = _build_ref_model(
        ref, embedder_type, "apply_factor_weights_at_each_sim_step", mode)
    jax_model = _build_jax_model(
        embedder_type, "apply_factor_weights_at_each_sim_step", mode)
    params = _copy_params(ref_model, embedder_type)
    X = np.random.default_rng(5).normal(size=(6, MAX_LAG, C)).astype(np.float32)
    _assert_gc_match(jax_model, params, ref_model, mode, X, ignore_lag)


# --------------------------------------------------------------------------
# DYNOTEARS solver parity (no torch involved: scipy vs scipy)
# --------------------------------------------------------------------------
@pytest.fixture(scope="module")
def ref_dynotears():
    """Import the reference's vendored causalnex solver with the external
    causalnex package stubbed (only its StructureModel wrapper is imported;
    the core _learn_dynamic_structure never touches it)."""
    from conftest import add_reference_to_path

    add_reference_to_path(extra_stubs=[
        ("causalnex", {}),
        ("causalnex.structure", {"StructureModel": type("SM", (), {})}),
        ("causalnex.structure.transformers",
         {"DynamicDataTransformer": type("DDT", (), {})}),
    ])
    sys.modules["causalnex"].structure = sys.modules["causalnex.structure"]
    sys.modules["causalnex.structure"].transformers = sys.modules[
        "causalnex.structure.transformers"]
    from models import causalnex_dynotears

    return causalnex_dynotears


def _var_data(rng, d=4, p=2, n=80):
    series = np.zeros((n + p, d))
    A1 = 0.4 * (rng.uniform(size=(d, d)) > 0.7)
    for t in range(p, n + p):
        series[t] = series[t - 1] @ A1 + rng.normal(scale=0.5, size=d)
    X = series[p:]
    Xlags = np.concatenate(
        [series[p - k : n + p - k] for k in range(1, p + 1)], axis=1)
    return X, Xlags


def _ref_bounds(d, p):
    bnds_w = 2 * [(0, 0) if i == j else (0, None)
                  for i in range(d) for j in range(d)]
    bnds_a = []
    for _ in range(1, p + 1):
        bnds_a.extend(2 * [(0, None) for _ in range(d * d)])
    return bnds_w + bnds_a


def test_dynotears_solver_parity(ref_dynotears):
    """Our augmented-Lagrangian DYNOTEARS solve reproduces the reference's
    _learn_dynamic_structure (ref causalnex_dynotears.py:333-510) W and A
    matrices on identical data."""
    from redcliff_tpu.models.dynotears import dynotears_solve

    rng = np.random.default_rng(11)
    d, p = 4, 2
    X, Xlags = _var_data(rng, d=d, p=p)
    w_ref, a_ref = ref_dynotears._learn_dynamic_structure(
        X, Xlags, _ref_bounds(d, p), 0.1, 0.1, 100, 1e-8)[:2]
    res = dynotears_solve(X, Xlags, lambda_w=0.1, lambda_a=0.1,
                          max_iter=100, h_tol=1e-8)
    np.testing.assert_allclose(res.w_mat, w_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(res.a_mat, a_ref, rtol=1e-5, atol=1e-6)


def test_dynotears_warm_start_parity(ref_dynotears):
    """The stochastic variant's warm-started refit chain: threading
    (wa, rho, alpha, h) through a second call matches the reference's
    keyword-threaded state handling (ref :162-173,478-509)."""
    from redcliff_tpu.models.dynotears import DynotearsState, dynotears_solve

    rng = np.random.default_rng(13)
    d, p = 3, 1
    X1, Xl1 = _var_data(rng, d=d, p=p, n=50)
    X2, Xl2 = _var_data(rng, d=d, p=p, n=50)
    bnds = _ref_bounds(d, p)

    r1 = ref_dynotears._learn_dynamic_structure(
        X1, Xl1, bnds, 0.1, 0.1, 50, 1e-8)
    _, _, wa_ref, rho_ref, alpha_ref, h_ref, h_new_ref, wa_new_ref = r1[:8]
    r2 = ref_dynotears._learn_dynamic_structure(
        X2, Xl2, bnds, 0.1, 0.1, 50, 1e-8, wa_est=wa_ref.copy(),
        rho=rho_ref, alpha=alpha_ref, h_value=h_ref, h_new=h_new_ref,
        wa_new=wa_new_ref.copy())

    o1 = dynotears_solve(X1, Xl1, lambda_w=0.1, lambda_a=0.1, max_iter=50,
                         h_tol=1e-8)
    o2 = dynotears_solve(X2, Xl2, lambda_w=0.1, lambda_a=0.1, max_iter=50,
                         h_tol=1e-8, state=o1.state)
    np.testing.assert_allclose(o1.w_mat, r1[0], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(o2.w_mat, r2[0], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(o2.a_mat, r2[1], rtol=1e-5, atol=1e-6)


# --------------------------------------------------------------------------
# NAVAR parity (vendored torch module, ref models/navar.py:9-127)
# --------------------------------------------------------------------------
def test_navar_forward_and_causal_matrix_parity(ref):
    """Copy the reference NAVAR's grouped-conv weights into our per-node
    einsum pytree and assert predictions, contributions, and the
    std-over-windows causal matrix match (ref navar.py:41-51,119-122)."""
    from models.navar import NAVAR as RefNAVAR

    from redcliff_tpu.models.navar import NAVAR, NAVARConfig

    N, H, L, HL = 5, 8, 4, 2
    torch.manual_seed(1)
    ref_model = RefNAVAR(num_nodes=N, num_hidden=H, maxlags=L,
                         hidden_layers=HL, dropout=0)
    ours = NAVAR(NAVARConfig(num_nodes=N, num_hidden=H, maxlags=L,
                             hidden_layers=HL, dropout=0.0))

    params = {
        "w1": _np(ref_model.first_hidden_layer.weight).reshape(N, H, L),
        "b1": _np(ref_model.first_hidden_layer.bias).reshape(N, H),
        "hidden": [
            {"w": _np(layer.weight).reshape(N, H, H),
             "b": _np(layer.bias).reshape(N, H)}
            for layer in ref_model.hidden_layer_list
        ],
        "wc": _np(ref_model.contributions.weight).reshape(N, N, H),
        "bc": _np(ref_model.contributions.bias).reshape(N, N),
        "bias": _np(ref_model.biases)[0],
    }

    rng = np.random.default_rng(2)
    B = 6
    Xw = rng.normal(size=(B, L, N)).astype(np.float32)
    with torch.no_grad():
        # torch input layout: (batch, nodes, time)
        r_pred, r_contrib = ref_model(
            torch.from_numpy(np.swapaxes(Xw, 1, 2)))
    j_pred, j_contrib = ours.forward(params, Xw)
    np.testing.assert_allclose(np.asarray(j_pred), _np(r_pred),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(j_contrib).reshape(B, N * N),
        _np(r_contrib)[:, :, 0], rtol=1e-5, atol=1e-6)

    # causal matrix: std of each contribution stream over windows
    # (ref fit loop :119-122 computes torch.std over the training epoch)
    j_cm = np.asarray(j_contrib).reshape(B, N * N).std(axis=0, ddof=1)
    r_cm = torch.std(r_contrib[:, :, 0], dim=0)
    np.testing.assert_allclose(j_cm, _np(r_cm), rtol=1e-4, atol=1e-6)


# --------------------------------------------------------------------------
# cLSTM parity (vendored torch module, ref models/clstm.py:12-160)
# --------------------------------------------------------------------------
def test_clstm_forward_and_gc_parity(ref):
    """Copy the reference cLSTM's per-series nn.LSTM + Conv1d-head weights
    into our scanned stacked block and assert per-step predictions and the
    input-weight-norm GC readout match (ref clstm.py:100-112,126-156)."""
    from models.clstm import cLSTM as RefCLSTM

    from redcliff_tpu.models.clstm import clstm_forward, clstm_gc

    C, H, T, B = 4, 6, 12, 5
    torch.manual_seed(3)
    ref_model = RefCLSTM(num_chans=C, hidden=H)

    params = {
        "w_ih": np.stack([_np(n.lstm.weight_ih_l0)
                          for n in ref_model.networks]),
        "w_hh": np.stack([_np(n.lstm.weight_hh_l0)
                          for n in ref_model.networks]),
        "b": np.stack([_np(n.lstm.bias_ih_l0) + _np(n.lstm.bias_hh_l0)
                       for n in ref_model.networks]),
        "head": {
            "w": np.stack([_np(n.linear.weight)[0, :, 0]
                           for n in ref_model.networks]),
            "b": np.stack([_np(n.linear.bias)[0]
                           for n in ref_model.networks]),
        },
    }

    rng = np.random.default_rng(4)
    X = rng.normal(size=(B, T, C)).astype(np.float32)
    with torch.no_grad():
        r_pred, _ = ref_model(torch.from_numpy(X))
    j_pred, _ = clstm_forward(params, X)
    np.testing.assert_allclose(np.asarray(j_pred), _np(r_pred),
                               rtol=1e-5, atol=1e-5)

    with torch.no_grad():
        r_gc = ref_model.GC(threshold=False)
    j_gc = clstm_gc(params, threshold=False)
    np.testing.assert_allclose(np.asarray(j_gc), _np(r_gc),
                               rtol=1e-5, atol=1e-6)


# --------------------------------------------------------------------------
# DCSFA-NMF parity (vendored torch module, ref models/dcsfa_nmf.py)
# --------------------------------------------------------------------------
def test_dcsfa_transform_and_gc_parity(ref):
    """Copy a reference FullDCSFAModel's encoder/NMF/logistic weights into
    our param pytree and assert eval-mode transform outputs (recon, class
    probabilities, scores) and the per-factor GC readout match
    (ref dcsfa_nmf.py transform :796-860, get_factor_GC :1299-1315)."""
    from models.dcsfa_nmf import FullDCSFAModel as RefFull

    from redcliff_tpu.models.dcsfa_nmf import (DcsfaNmfConfig,
                                               FullDCSFAModel)

    N_NODES, HLF, NC, NS, H = 4, 3, 3, 2, 16
    node_factor_len = HLF * (2 * N_NODES - 1)
    dim_in = N_NODES * node_factor_len
    torch.manual_seed(5)
    ref_model = RefFull(num_nodes=N_NODES, num_high_level_node_features=HLF,
                        n_components=NC, n_sup_networks=NS, h=H,
                        device="cpu")
    ref_model._initialize(dim_in)
    ref_model.eval()

    ours = FullDCSFAModel(
        num_nodes=N_NODES, num_high_level_node_features=HLF,
        gc_feature_layout="dirspec",
        config=DcsfaNmfConfig(n_components=NC, n_sup_networks=NS, h=H))

    enc = ref_model.encoder
    params = {
        "W_nmf": _np(ref_model.W_nmf),
        "enc1": {"w": _np(enc[0].weight).T, "b": _np(enc[0].bias)},
        "bn_scale": _np(enc[1].weight), "bn_shift": _np(enc[1].bias),
        "enc2": {"w": _np(enc[3].weight).T, "b": _np(enc[3].bias)},
        "phi": np.array([_np(p)[0] for p in ref_model.phi_list]),
        "beta": np.stack([_np(b)[:, 0] for b in ref_model.beta_list]),
    }
    state = {"bn_mean": _np(enc[1].running_mean),
             "bn_var": _np(enc[1].running_var)}

    rng = np.random.default_rng(6)
    X = np.abs(rng.normal(size=(9, dim_in))).astype(np.float32)
    with torch.no_grad():
        r_recon, r_pred, r_s = ref_model.transform(
            torch.from_numpy(X), avg_intercept=True, return_npy=True)
    j_s, _ = ours.encode(params, state, X, train=False)
    j_s = np.asarray(j_s)
    j_recon = j_s @ np.asarray(ours.get_w_nmf(params))
    j_pred = np.asarray(ours.class_predictions(params, j_s,
                                               avg_intercept=True))
    np.testing.assert_allclose(j_s, r_s, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(j_recon, r_recon, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(j_pred, r_pred, rtol=1e-4, atol=1e-5)

    # GC readout from the copied W_nmf (threshold=False path is pure numpy
    # in the reference, so it runs without torch state)
    r_gc = ref_model.GC(threshold=False, ignore_features=True)
    j_gc = ours.gc(params, threshold=False)
    assert len(j_gc) >= NS
    for k in range(len(r_gc)):
        np.testing.assert_allclose(np.asarray(j_gc[k]), np.asarray(r_gc[k]),
                                   rtol=1e-4, atol=1e-6)


# --------------------------------------------------------------------------
# TS transformer parity (vendored mvts module, ref models/ts_transformer.py)
# --------------------------------------------------------------------------
def _shim_encoder_layers(ref_model):
    """torch>=2.2's nn.TransformerEncoder passes is_causal= to each layer;
    the reference's custom TransformerBatchNormEncoderLayer predates that
    kwarg.  Drop it — version compatibility only, no math changes."""
    for layer in ref_model.transformer_encoder.layers:
        orig = layer.forward

        def fwd(src, *a, _o=orig, **kw):
            kw.pop("is_causal", None)
            return _o(src, *a, **kw)

        layer.forward = fwd


def _copy_ts_transformer(ref_model, num_layers, learnable_pos=False):
    d = ref_model.d_model
    params = {"project_inp": {"w": _np(ref_model.project_inp.weight).T,
                              "b": _np(ref_model.project_inp.bias)}}
    if learnable_pos:
        params["pos"] = _np(ref_model.pos_enc.pe)[:, 0, :]
    layers = []
    for li in range(num_layers):
        rl = ref_model.transformer_encoder.layers[li]
        in_proj = _np(rl.self_attn.in_proj_weight)
        layers.append({
            "wq": in_proj[:d].T, "wk": in_proj[d:2 * d].T,
            "wv": in_proj[2 * d:].T,
            "wo": _np(rl.self_attn.out_proj.weight).T,
            "ff1": {"w": _np(rl.linear1.weight).T, "b": _np(rl.linear1.bias)},
            "ff2": {"w": _np(rl.linear2.weight).T, "b": _np(rl.linear2.bias)},
            "norm1_scale": _np(rl.norm1.weight),
            "norm1_shift": _np(rl.norm1.bias),
            "norm2_scale": _np(rl.norm2.weight),
            "norm2_shift": _np(rl.norm2.bias),
        })
    params["layers"] = layers
    params["output"] = {"w": _np(ref_model.output_layer.weight).T,
                        "b": _np(ref_model.output_layer.bias)}
    return params


@pytest.mark.parametrize("partial_mask", [False, True])
def test_ts_transformer_encoder_parity(ref, partial_mask):
    """Copy the reference TSTransformerEncoder's weights (BatchNorm variant,
    the mvts default) and assert the denoising-head forward matches in
    batch-statistics mode (ref :145-190).  dropout=0 so train() only
    switches BatchNorm to the batch statistics our stateless norm uses."""
    from models.ts_transformer import TSTransformerEncoder as RefTST

    from redcliff_tpu.models.ts_transformer import (TSTransformerConfig,
                                                    TSTransformerEncoder)

    F_DIM, T, D, H, L, FF = 5, 12, 8, 2, 2, 16
    torch.manual_seed(7)
    ref_model = RefTST(feat_dim=F_DIM, max_len=T, d_model=D, n_heads=H,
                       num_layers=L, dim_feedforward=FF, dropout=0.0,
                       pos_encoding="fixed", activation="gelu",
                       norm="BatchNorm")
    ref_model.train()  # batch-statistics BatchNorm; dropout=0 stays inert
    _shim_encoder_layers(ref_model)

    cfg = TSTransformerConfig(feat_dim=F_DIM, max_len=T, d_model=D,
                              n_heads=H, num_layers=L, dim_feedforward=FF,
                              pos_encoding="fixed", activation="gelu",
                              norm="BatchNorm")
    ours = TSTransformerEncoder(cfg)
    params = _copy_ts_transformer(ref_model, L)

    rng = np.random.default_rng(8)
    X = rng.normal(size=(6, T, F_DIM)).astype(np.float32)
    mask = np.ones((6, T), dtype=bool)
    if partial_mask:
        mask[:, -3:] = False
    with torch.no_grad():
        r_out = ref_model(torch.from_numpy(X), torch.from_numpy(mask))
    j_out = ours.forward(params, X, padding_masks=mask)
    np.testing.assert_allclose(np.asarray(j_out), _np(r_out),
                               rtol=1e-4, atol=1e-5)


def test_ts_transformer_classiregressor_parity(ref):
    """The classification head: padded embeddings zeroed, flattened linear
    (ref TSTransformerEncoderClassiregressor :192-250)."""
    from models.ts_transformer import (
        TSTransformerEncoderClassiregressor as RefClf,
    )

    from redcliff_tpu.models.ts_transformer import (
        TSTransformerConfig,
        TSTransformerEncoderClassiregressor,
    )

    F_DIM, T, D, H, L, FF, NCLS = 4, 10, 8, 2, 1, 12, 3
    torch.manual_seed(9)
    ref_model = RefClf(feat_dim=F_DIM, max_len=T, d_model=D, n_heads=H,
                       num_layers=L, dim_feedforward=FF, num_classes=NCLS,
                       dropout=0.0, pos_encoding="fixed", activation="gelu",
                       norm="BatchNorm")
    ref_model.train()
    _shim_encoder_layers(ref_model)

    cfg = TSTransformerConfig(feat_dim=F_DIM, max_len=T, d_model=D,
                              n_heads=H, num_layers=L, dim_feedforward=FF,
                              num_classes=NCLS, pos_encoding="fixed",
                              activation="gelu", norm="BatchNorm")
    ours = TSTransformerEncoderClassiregressor(cfg)
    params = _copy_ts_transformer(ref_model, L)

    rng = np.random.default_rng(10)
    X = rng.normal(size=(5, T, F_DIM)).astype(np.float32)
    mask = np.ones((5, T), dtype=bool)
    mask[:, -2:] = False
    with torch.no_grad():
        r_out = ref_model(torch.from_numpy(X), torch.from_numpy(mask))
    j_out = ours.forward(params, X, padding_masks=mask)
    np.testing.assert_allclose(np.asarray(j_out), _np(r_out),
                               rtol=1e-4, atol=1e-5)


# --------------------------------------------------------------------------
# cMLP_FM parity (vendored torch module, ref models/cmlp_fm.py:58-148)
# --------------------------------------------------------------------------
def test_cmlp_fm_multisim_forward_parity(ref):
    """The single-factor baseline's autoregressive multi-sim forecast: each
    sim emits T' = input_length - lag + 1 steps and the window slides by T'
    (ref cmlp_fm.py:96-148) — covers the T' > 1 window slide that the
    REDCLIFF forward A/B (T' == 1) does not."""
    from models.cmlp_fm import cMLP_FM as RefFM

    from redcliff_tpu.models.cmlp_fm import CMLPFM, CMLPFMConfig

    C, LAG, IN_LEN, SIMS = 4, 3, 8, 3
    torch.manual_seed(11)
    ref_model = RefFM(
        num_chans=C, gen_lag=LAG, gen_hidden=[8, 6],
        embed_hidden_sizes=[8], num_in_timesteps=IN_LEN,
        num_out_timesteps=1, num_sims=SIMS,
        coeff_dict={"FORECAST_COEFF": 1.0,
                    "ADJ_L1_REG_COEFF": 0.0, "DAGNESS_REG_COEFF": 0.0,
                    "DAGNESS_LAG_COEFF": 0.0, "DAGNESS_NODE_COEFF": 0.0},
        wavelet_level=None, save_path=None)

    ours = CMLPFM(CMLPFMConfig(num_chans=C, gen_lag=LAG, gen_hidden=(8, 6),
                               num_sims=SIMS, input_length=IN_LEN))
    # _copy_factors over the 1-factor ModuleList, K axis stripped
    params = {"factor": [{k: v[0] for k, v in layer.items()}
                         for layer in _copy_factors(ref_model)]}

    rng = np.random.default_rng(12)
    X = rng.normal(size=(5, IN_LEN, C)).astype(np.float32)
    with torch.no_grad():
        r_out = ref_model(torch.from_numpy(X))
    if isinstance(r_out, tuple):
        r_out = r_out[0]
    j_out = ours.forward(params, X)
    np.testing.assert_allclose(np.asarray(j_out), _np(r_out),
                               rtol=1e-5, atol=1e-5)

    r_gc = ref_model.factors[0].GC(threshold=False, ignore_lag=True)
    j_gc = ours.gc(params, threshold=False, ignore_lag=True)[0]
    np.testing.assert_allclose(np.asarray(j_gc), _np(r_gc),
                               rtol=1e-5, atol=1e-6)

