"""Golden tests for the metric library against sklearn and hand-computed graphs.

The reference scores everything with sklearn (precision_recall_curve, roc_auc_score,
f1_score) — these tests pin our numpy implementations to sklearn outputs on random
data, and pin the DeltaCon0 family to hand-checkable small graphs.
"""
import numpy as np
import pytest
from sklearn.metrics import f1_score as sk_f1
from sklearn.metrics import precision_recall_curve as sk_prc
from sklearn.metrics import roc_auc_score as sk_auc

from redcliff_tpu.utils import metrics as M
from redcliff_tpu.utils import misc as misc


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def test_precision_recall_curve_matches_sklearn(rng):
    for _ in range(20):
        n = int(rng.integers(5, 200))
        labels = rng.integers(0, 2, n)
        if labels.sum() == 0:
            labels[0] = 1
        scores = np.round(rng.normal(size=n), 2)  # rounding forces ties
        p, r, t = M.precision_recall_curve(labels, scores)
        sp, sr, st = sk_prc(labels, scores)
        np.testing.assert_allclose(p, sp, atol=1e-12)
        np.testing.assert_allclose(r, sr, atol=1e-12)
        np.testing.assert_allclose(t, st, atol=1e-12)


def test_compute_optimal_f1_matches_reference_formula(rng):
    for _ in range(10):
        n = int(rng.integers(10, 100))
        labels = rng.integers(0, 2, n)
        if labels.sum() == 0:
            labels[0] = 1
        scores = rng.normal(size=n)
        thr, f1 = M.compute_optimal_f1(labels, scores)
        # reference semantics: positive iff score >= threshold on the PR curve
        preds = (scores >= thr).astype(int)
        assert f1 == pytest.approx(sk_f1(labels, preds), abs=1e-9)


def test_roc_auc_matches_sklearn(rng):
    for _ in range(20):
        n = int(rng.integers(5, 300))
        labels = rng.integers(0, 2, n)
        if labels.sum() == 0:
            labels[0] = 1
        if labels.sum() == n:
            labels[0] = 0
        scores = np.round(rng.normal(size=n), 1)
        assert M.roc_auc(labels, scores) == pytest.approx(sk_auc(labels, scores), abs=1e-12)


def test_compute_f1_fixed_cutoff(rng):
    labels = rng.integers(0, 2, 50)
    labels[0] = 1
    scores = rng.normal(size=50)
    f1 = M.compute_f1(labels, scores, 0.0)
    assert f1 == pytest.approx(sk_f1(labels, (scores > 0.0).astype(int)), abs=1e-12)


def test_deltacon0_identical_graphs_is_one():
    A = np.array([[0.0, 1.0, 0.0], [0.0, 0.0, 1.0], [1.0, 0.0, 0.0]])
    assert M.deltacon0(A, A, eps=0.1) == pytest.approx(1.0)
    assert M.deltacon0_with_directed_degrees(A, A, eps=0.1) == pytest.approx(1.0)
    assert M.deltaffinity(A, A, eps=0.1) == pytest.approx(1.0)


def test_deltacon0_decreases_with_perturbation():
    A = np.array([[0.0, 1.0, 0.0], [0.0, 0.0, 1.0], [1.0, 0.0, 0.0]])
    B = A.copy()
    B[0, 1] = 0.0
    C = np.zeros_like(A)
    s_small = M.deltacon0(A, B, eps=0.1)
    s_large = M.deltacon0(A, C, eps=0.1)
    assert 0 < s_large < s_small < 1


def test_deltacon0_finite_on_signed_graphs():
    """Signed (negative-valued) estimates yield negative affinity entries;
    the reference NaNs there — we clamp at zero so the whole DeltaCon0
    family stays finite (documented deviation in matsusita_distance)."""
    rng = np.random.default_rng(5)
    A = rng.normal(size=(6, 6))  # signed entries
    B = (rng.uniform(size=(6, 6)) > 0.5).astype(float)
    with np.errstate(invalid="raise"):
        d = M.matsusita_distance(A - 0.5, B - 0.5)
        s = M.deltacon0(A, B, eps=0.1)
        sdd = M.deltacon0_with_directed_degrees(A, B, eps=0.1)
        daf = M.deltaffinity(A, B, eps=0.1)
    assert np.isfinite([d, s, sdd, daf]).all()


def test_deltacon0_hand_computed_two_node():
    # two nodes, single directed edge vs empty graph, eps=0.5
    A = np.array([[0.0, 1.0], [0.0, 0.0]])
    B = np.zeros((2, 2))
    eps = 0.5
    S_A = np.linalg.inv(np.eye(2) + eps**2 * np.diag(A.sum(0)) - eps * A)
    S_B = np.eye(2)
    d = np.sqrt(np.sum((np.sqrt(S_A) - np.sqrt(S_B)) ** 2))
    assert M.deltacon0(A, B, eps) == pytest.approx(1.0 / (1.0 + d))


def test_path_length_mse():
    A = np.array([[0.0, 1.0], [0.0, 0.0]])
    B = np.zeros((2, 2))
    total, per_k = M.path_length_mse(A, B)
    # default max_path_length = n-1 = 1: A^1 differs by one entry (mse 1/4)
    assert per_k == pytest.approx([0.25])
    assert total == pytest.approx(0.25)
    total2, per_k2 = M.path_length_mse(A, B, max_path_length=2)
    # A^2 == 0 == B^2
    assert per_k2 == pytest.approx([0.25, 0.0])
    assert total2 == pytest.approx(0.25)


def test_get_f1_score_positive_entries():
    A_true = np.array([[0.0, 1.0], [0.0, 0.0]])
    assert M.get_f1_score(A_true, A_true) == pytest.approx(1.0)
    assert M.get_f1_score(np.zeros((2, 2)), A_true) == 0.0


def test_hungarian_matching_recovers_permutation(rng):
    truths = [rng.normal(size=(4, 4)) for _ in range(3)]
    perm = [2, 0, 1]
    ests = [truths[p] + 0.01 * rng.normal(size=(4, 4)) for p in perm]
    # cost is cosine similarity and scipy minimizes => matched pairs are the
    # MOST DISSIMILAR assignment (reference behavior, metrics.py:274-301)
    rows, cols = M.solve_linear_sum_assignment_between_graph_options(ests, truths)
    assert sorted(rows.tolist()) == [0, 1, 2]
    assert sorted(cols.tolist()) == [0, 1, 2]


def test_sort_unsupervised_estimates_roundtrip(rng):
    truths = [rng.normal(size=(3, 3)) for _ in range(3)]
    sorted_ests = misc.sort_unsupervised_estimates(list(truths), truths)
    assert len(sorted_ests) == 3


def test_dagness_penalty_zero_diag():
    W = np.array([[0.0, 2.0], [3.0, 0.0]])
    # elementwise exp: trace(exp(W*W)) = exp(0)+exp(0) = 2 = N
    assert M.dagness_penalty(W) == pytest.approx(0.0)
    W2 = np.array([[1.0, 0.0], [0.0, 0.0]])
    assert M.dagness_penalty(W2) == pytest.approx((np.exp(1.0) - 1.0) ** 2)


def test_flatten_unflatten_gc_roundtrip(rng):
    GC = rng.normal(size=(5, 5, 3))
    flat = misc.flatten_gc_with_lags(GC)
    assert flat.shape == (5, 15)
    np.testing.assert_allclose(misc.unflatten_gc_with_lags(flat), GC)
    # lag-major block layout: block l holds GC[:, :, l]
    np.testing.assert_allclose(flat[:, 5:10], GC[:, :, 1])


def test_flatten_unflatten_dirspec_roundtrip(rng):
    x = rng.normal(size=(4, 4, 3))
    flat = misc.flatten_directed_spectrum_features(x)
    assert flat.shape == (4, 3 * 7)
    back = misc.unflatten_directed_spectrum_features(flat)
    np.testing.assert_allclose(back, x)


def test_top_k_filter():
    A = np.array([[5.0, 1.0], [3.0, 2.0]])
    out = misc.apply_top_k_filter_to_edges(A, k=2)
    np.testing.assert_allclose(out, [[5.0, 0.0], [3.0, 0.0]])


def test_connected_components():
    A = np.zeros((4, 4))
    A[0, 1] = 1.0
    A[2, 3] = 1.0
    assert M.get_number_of_connected_components(A) == 2


def test_kfolds_cv_splits():
    data = list(range(10))
    labels = [i * 10 for i in range(10)]
    folds = misc.make_kfolds_cv_splits(data, labels, num_folds=3)
    assert set(folds) == {0, 1, 2}
    sizes = [len(folds[i]["validation"]) for i in range(3)]
    assert sum(sizes) >= 10 // 3 * 3
    for i in range(3):
        assert len(folds[i]["train"]) + len(folds[i]["validation"]) == 10
