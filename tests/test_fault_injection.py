"""Fault-injection suite: preemption resilience of the grid engine.

Acceptance battery for the runtime layer (redcliff_tpu/runtime/): a grid fit
SIGKILLed mid-run in a subprocess resumes BIT-IDENTICALLY; truncated/corrupted
checkpoints are quarantined to *.bad and the fit restarts cleanly; resuming
against a changed batch stream or dataset is explicitly rejected; SIGTERM
triggers one final checkpoint; injected probe failures follow the retry
policy's backoff schedule exactly. All CPU — no accelerator needed.
"""
import os
import pickle
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from redcliff_tpu.runtime import checkpoint as rck
from redcliff_tpu.runtime.faultinject import (PREEMPTED_EXIT_CODE,
                                              corrupt_checkpoint, flaky,
                                              tiny_grid_fit)
from redcliff_tpu.runtime.preempt import PreemptionGuard
from redcliff_tpu.runtime.retry import (GiveUp, RetryPolicy, retry)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = [sys.executable, "-m", "redcliff_tpu.runtime.faultinject"]
CKPT_NAME = "grid_checkpoint.pkl"


def _run_child(checkpoint_dir, *extra, fault=None, marker=None, timeout=240):
    env = dict(os.environ)
    env.pop("REDCLIFF_FAULT_INJECT", None)
    env.pop("REDCLIFF_FAULT_MARKER", None)
    if fault:
        env["REDCLIFF_FAULT_INJECT"] = fault
    if marker:
        env["REDCLIFF_FAULT_MARKER"] = marker
    return subprocess.run(
        CHILD + ["--checkpoint-dir", str(checkpoint_dir)] + list(extra),
        env=env, cwd=REPO, capture_output=True, text=True, timeout=timeout)


# ---------------------------------------------------------------------------
# durable checkpoint format
# ---------------------------------------------------------------------------
def test_checkpoint_roundtrip_and_prev_generation(tmp_path):
    path = str(tmp_path / "ck.pkl")
    rck.write_checkpoint(path, {"gen": 1})
    rck.write_checkpoint(path, {"gen": 2})
    assert rck.read_checkpoint(path) == {"gen": 2}
    assert rck.read_checkpoint(path + ".prev") == {"gen": 1}


def test_truncated_head_falls_back_to_prev(tmp_path):
    path = str(tmp_path / "ck.pkl")
    rck.write_checkpoint(path, {"gen": 1})
    rck.write_checkpoint(path, {"gen": 2})
    corrupt_checkpoint(path, "truncate")
    with pytest.warns(RuntimeWarning, match="quarantined"):
        obj, src = rck.load_checkpoint(path)
    assert obj == {"gen": 1} and src == path + ".prev"
    # the corrupt head was preserved as evidence, not deleted
    assert os.path.exists(path + ".bad") and not os.path.exists(path)


def test_both_generations_corrupt_degrades_to_fresh_start(tmp_path):
    path = str(tmp_path / "ck.pkl")
    rck.write_checkpoint(path, {"gen": 1})
    rck.write_checkpoint(path, {"gen": 2})
    corrupt_checkpoint(path, "truncate")
    corrupt_checkpoint(path + ".prev", "zero_header")
    with pytest.warns(RuntimeWarning):
        obj, src = rck.load_checkpoint(path)
    assert obj is None and src is None
    assert os.path.exists(path + ".bad")
    assert os.path.exists(path + ".prev.bad")


def test_crc_catches_silent_bit_flip(tmp_path):
    path = str(tmp_path / "ck.pkl")
    rck.write_checkpoint(path, {"weights": list(range(100))})
    corrupt_checkpoint(path, "flip_payload")
    with pytest.raises(rck.CheckpointCorruptError, match="CRC"):
        rck.read_checkpoint(path)


def test_legacy_headerless_pickle_still_reads(tmp_path):
    path = str(tmp_path / "legacy.pkl")
    with open(path, "wb") as f:
        pickle.dump({"old": True}, f)
    assert rck.read_checkpoint(path) == {"old": True}


# ---------------------------------------------------------------------------
# (a) SIGKILL mid-fit -> bit-identical resume
# ---------------------------------------------------------------------------
def test_sigkill_mid_fit_resume_bit_identical(tmp_path):
    """A grid fit SIGKILLed right after its epoch-1 checkpoint (no grace, the
    preemption-without-warning case) resumes to results bit-identical to an
    uninterrupted run — params, best criteria/epochs, lane masks, history."""
    ck = tmp_path / "ck"
    killed = _run_child(ck, "--max-iter", "4",
                        fault="sigkill_after_checkpoint:1")
    assert killed.returncode == -signal.SIGKILL, killed.stderr[-2000:]
    ckpt = rck.read_checkpoint(str(ck / CKPT_NAME))
    assert ckpt["epoch"] == 1  # died mid-fit, after the epoch-1 save

    res_path = tmp_path / "resumed.pkl"
    resumed = _run_child(ck, "--max-iter", "4", "--result", str(res_path))
    assert resumed.returncode == 0, resumed.stderr[-2000:]

    full_path = tmp_path / "full.pkl"
    uninterrupted = _run_child(tmp_path / "ck_full", "--max-iter", "4",
                               "--result", str(full_path))
    assert uninterrupted.returncode == 0, uninterrupted.stderr[-2000:]

    with open(res_path, "rb") as f:
        got = pickle.load(f)
    with open(full_path, "rb") as f:
        want = pickle.load(f)
    np.testing.assert_array_equal(got["val_history"], want["val_history"])
    np.testing.assert_array_equal(got["best_criteria"],
                                  want["best_criteria"])
    np.testing.assert_array_equal(got["best_epoch"], want["best_epoch"])
    np.testing.assert_array_equal(got["active"], want["active"])
    for a, b in zip(got["best_params_leaves"], want["best_params_leaves"]):
        np.testing.assert_array_equal(a, b)


def test_sigkill_during_async_ckpt_write_prev_fallback_resumes(tmp_path):
    """Async-checkpointing crash safety: the fit is SIGKILLed while the
    BACKGROUND writer sits inside the durable writer's crash window (head
    already rotated to .prev, new generation not yet promoted — the
    fault hook holds the window open and writes a marker). Resume must fall
    back to the .prev generation and still finish bit-identical to an
    uninterrupted run."""
    ck = tmp_path / "ck"
    marker = str(tmp_path / "in_window.marker")
    env = dict(os.environ,
               REDCLIFF_FAULT_INJECT="hang_between_ckpt_replaces:60",
               REDCLIFF_FAULT_MARKER=marker)
    proc = subprocess.Popen(
        CHILD + ["--checkpoint-dir", str(ck), "--max-iter", "4"],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    try:
        deadline = time.monotonic() + 180
        while not os.path.exists(marker):
            assert proc.poll() is None, proc.communicate()[1][-2000:]
            assert time.monotonic() < deadline, \
                "child never reached the checkpoint crash window"
            time.sleep(0.05)
        proc.kill()
        proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    head = str(ck / CKPT_NAME)
    # killed inside the window: the head generation is gone, .prev intact
    assert not os.path.exists(head)
    obj, src = rck.load_checkpoint(head)
    assert obj is not None and src == head + ".prev"

    res_path = tmp_path / "resumed.pkl"
    resumed = _run_child(ck, "--max-iter", "4", "--result", str(res_path))
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    full_path = tmp_path / "full.pkl"
    uninterrupted = _run_child(tmp_path / "ck_full", "--max-iter", "4",
                               "--result", str(full_path))
    assert uninterrupted.returncode == 0, uninterrupted.stderr[-2000:]
    with open(res_path, "rb") as f:
        got = pickle.load(f)
    with open(full_path, "rb") as f:
        want = pickle.load(f)
    np.testing.assert_array_equal(got["val_history"], want["val_history"])
    for a, b in zip(got["best_params_leaves"], want["best_params_leaves"]):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# (b) corrupt checkpoint -> quarantine, clean restart
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("mode", ["truncate", "flip_payload"])
def test_corrupt_checkpoint_quarantined_fit_restarts(tmp_path, mode):
    """A fit pointed at a corrupt checkpoint (no usable .prev) quarantines it
    to *.bad and restarts from scratch — no crash, results identical to a
    fresh run."""
    ck = str(tmp_path / "ck")
    fresh = tiny_grid_fit(None, max_iter=2)
    tiny_grid_fit(ck, max_iter=2)
    head = os.path.join(ck, CKPT_NAME)
    corrupt_checkpoint(head, mode)
    os.remove(head + ".prev")
    with pytest.warns(RuntimeWarning, match="quarantined"):
        restarted = tiny_grid_fit(ck, max_iter=2)
    assert os.path.exists(head + ".bad")
    np.testing.assert_array_equal(restarted.val_history, fresh.val_history)


# ---------------------------------------------------------------------------
# (c) changed config/data -> explicit rejection
# ---------------------------------------------------------------------------
def _mismatch_fit(ck, **tc_overrides):
    import dataclasses

    from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig

    from test_parallel_grid import _data, _model

    model = _model()
    tc = dataclasses.replace(
        RedcliffTrainConfig(max_iter=2, batch_size=32, check_every=1),
        **tc_overrides)
    spec = GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 3e-3}])
    runner = RedcliffGridRunner(model, tc, spec)
    ds = _data(model)
    return runner, ds


def test_resume_rejects_changed_batch_size(tmp_path):
    """Regression for the old silent-wrong-resume: the restored rng state
    would replay a DIFFERENT batch stream under a new batch_size, so the
    fingerprint now rejects it with the mismatching field named."""
    import jax

    ck = str(tmp_path / "ck")
    runner, ds = _mismatch_fit(ck)
    runner.fit(jax.random.PRNGKey(0), ds, ds, checkpoint_dir=ck,
               checkpoint_every=1)
    runner2, ds2 = _mismatch_fit(ck, batch_size=16)
    with pytest.raises(ValueError, match="batch_size"):
        runner2.fit(jax.random.PRNGKey(0), ds2, ds2, checkpoint_dir=ck,
                    checkpoint_every=1)


def test_resume_rejects_predurability_checkpoint_with_clear_message(tmp_path):
    """A checkpoint written by the pre-durability code (bare pickle, old
    {points, seed, training_mode} meta) is rejected as a format upgrade, not
    misreported as 'a different fit'."""
    import jax

    ck = str(tmp_path / "ck")
    runner, ds = _mismatch_fit(ck)
    os.makedirs(ck)
    legacy = {"meta": {"points": list(runner.spec.points), "seed": 0,
                       "training_mode": "combined"}}
    with open(os.path.join(ck, CKPT_NAME), "wb") as f:
        pickle.dump(legacy, f)
    with pytest.raises(ValueError, match="predates the durable"):
        runner.fit(jax.random.PRNGKey(0), ds, ds, checkpoint_dir=ck,
                   checkpoint_every=1)


def test_resume_rejects_changed_dataset_shape(tmp_path):
    import jax

    from test_parallel_grid import _data

    ck = str(tmp_path / "ck")
    runner, ds = _mismatch_fit(ck)
    runner.fit(jax.random.PRNGKey(0), ds, ds, checkpoint_dir=ck,
               checkpoint_every=1)
    runner2, _ = _mismatch_fit(ck)
    ds_small = _data(runner2.model, n=32)
    with pytest.raises(ValueError, match="train_data"):
        runner2.fit(jax.random.PRNGKey(0), ds_small, ds_small,
                    checkpoint_dir=ck, checkpoint_every=1)


def test_resume_rejects_changed_matmul_precision(tmp_path):
    """Fingerprint audit (ADVICE r5): matmul precision changes every step's
    update math, so it now rides the resume fingerprint; the same checkpoint
    still resumes under the unchanged config (backfill covers pre-precision
    checkpoints separately)."""
    import jax

    ck = str(tmp_path / "ck")
    runner, ds = _mismatch_fit(ck)
    runner.fit(jax.random.PRNGKey(0), ds, ds, checkpoint_dir=ck,
               checkpoint_every=1)
    runner2, ds2 = _mismatch_fit(ck, matmul_precision="bfloat16")
    with pytest.raises(ValueError, match="matmul_precision"):
        runner2.fit(jax.random.PRNGKey(0), ds2, ds2, checkpoint_dir=ck,
                    checkpoint_every=1)
    # a pre-precision checkpoint (field absent) resumes under the default
    ckpt = rck.read_checkpoint(os.path.join(ck, CKPT_NAME))
    ckpt["meta"].pop("matmul_precision")
    rck.write_checkpoint(os.path.join(ck, CKPT_NAME), ckpt)
    runner3, ds3 = _mismatch_fit(ck)
    runner3.fit(jax.random.PRNGKey(0), ds3, ds3, checkpoint_dir=ck,
                checkpoint_every=1)


# ---------------------------------------------------------------------------
# disk-full / IO-error hardening of the durable writer
# ---------------------------------------------------------------------------
def test_ckpt_write_enospc_maps_to_typed_error_and_cleans_tmp(
        tmp_path, monkeypatch):
    import errno
    import glob

    monkeypatch.setenv("REDCLIFF_FAULT_INJECT", "io_error:ckpt_write:ENOSPC")
    monkeypatch.delenv("REDCLIFF_FAULT_MARKER", raising=False)
    path = str(tmp_path / "ck.pkl")
    with pytest.raises(rck.CheckpointWriteError, match="disk full") as ei:
        rck.write_checkpoint(path, {"x": 1})
    assert ei.value.errno == errno.ENOSPC
    # the failed write left NO debris: no head, no orphan tmp file
    assert not os.path.exists(path)
    assert glob.glob(path + ".tmp*") == []
    # existing generations survive a later failed write untouched
    monkeypatch.delenv("REDCLIFF_FAULT_INJECT")
    rck.write_checkpoint(path, {"gen": 1})
    monkeypatch.setenv("REDCLIFF_FAULT_INJECT", "io_error:ckpt_write:EIO")
    with pytest.raises(rck.CheckpointWriteError):
        rck.write_checkpoint(path, {"gen": 2})
    assert rck.read_checkpoint(path) == {"gen": 1}


def test_async_writer_surfaces_enospc_at_next_submit_barrier(
        tmp_path, monkeypatch):
    """The background writer must not die silently on a full disk: the
    typed failure re-raises at the next submit (the barrier), and the
    writer is reusable after the operator frees space."""
    monkeypatch.setenv("REDCLIFF_FAULT_INJECT", "io_error:ckpt_write:ENOSPC")
    monkeypatch.delenv("REDCLIFF_FAULT_MARKER", raising=False)
    path = str(tmp_path / "ck.pkl")
    w = rck.AsyncCheckpointWriter()
    w.submit(lambda: rck.write_checkpoint(path, {"x": 1}))
    with pytest.raises(rck.CheckpointWriteError, match="disk full"):
        w.submit(lambda: rck.write_checkpoint(path, {"x": 2}))
    # disk freed: the writer keeps working and wait() is clean
    monkeypatch.delenv("REDCLIFF_FAULT_INJECT")
    w.submit(lambda: rck.write_checkpoint(path, {"x": 3}))
    w.wait()
    assert rck.read_checkpoint(path) == {"x": 3}


# ---------------------------------------------------------------------------
# SIGTERM -> one final checkpoint (the SLURM/TPU-VM preemption notice)
# ---------------------------------------------------------------------------
def test_sigterm_triggers_final_checkpoint(tmp_path):
    """checkpoint_every is set far beyond the run, so the ONLY way a
    checkpoint can appear is the preemption path: marker file says epoch 1
    finished, parent sends SIGTERM, child saves at the next epoch boundary
    and exits with the preempted code."""
    ck = tmp_path / "ck"
    marker = str(tmp_path / "epoch1.marker")
    env = dict(os.environ,
               REDCLIFF_FAULT_INJECT="marker_after_epoch:1",
               REDCLIFF_FAULT_MARKER=marker)
    proc = subprocess.Popen(
        CHILD + ["--checkpoint-dir", str(ck), "--max-iter", "500",
                 "--checkpoint-every", "100000"],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    try:
        deadline = time.monotonic() + 180
        while not os.path.exists(marker):
            assert proc.poll() is None, proc.communicate()[1][-2000:]
            assert time.monotonic() < deadline, "child never reached epoch 1"
            time.sleep(0.05)
        proc.send_signal(signal.SIGTERM)
        _, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == PREEMPTED_EXIT_CODE, err[-2000:]
    assert os.path.exists(ck / "preempted.json")
    ckpt = rck.read_checkpoint(str(ck / CKPT_NAME))
    assert ckpt["epoch"] >= 1
    assert ckpt["meta"]["batch_size"] == 16  # full fingerprint rode along


def test_preemption_guard_latches_and_restores():
    before = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as g:
        os.kill(os.getpid(), signal.SIGTERM)
        for _ in range(100):
            if g.preempted:
                break
            time.sleep(0.01)
        assert g.preempted and g.signum == signal.SIGTERM
    assert signal.getsignal(signal.SIGTERM) is before


# ---------------------------------------------------------------------------
# graceful degradation: non-finite points quarantined, grid keeps training
# ---------------------------------------------------------------------------
def test_nonfinite_point_quarantined_rest_of_grid_trains():
    res = tiny_grid_fit(None, max_iter=3, bad_point=True)
    assert [f["point"] for f in res.failures] == [1]
    assert res.failures[0]["epoch"] >= 0
    assert res.failures[0]["hparams"]["gen_lr"] == 1e20
    assert not res.active[1]
    # the healthy point trained through all epochs, unaffected
    assert res.active[0]
    assert np.isfinite(res.val_history[:, 0]).all()
    assert np.isfinite(res.best_criteria[0])
    # the quarantined lane froze: its val loss stops changing after failure
    e = res.failures[0]["epoch"]
    if e + 2 < res.val_history.shape[0]:
        np.testing.assert_array_equal(res.val_history[e + 1, 1],
                                      res.val_history[e + 2, 1])


def test_driver_writes_failures_json(tmp_path):
    import json

    import jax

    from redcliff_tpu.train.driver import run_coefficient_grid
    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig
    from test_parallel_grid import _data, _model

    model = _model()
    ds = _data(model, n=32)
    run_dir = str(tmp_path / "run")
    res = run_coefficient_grid(
        model, RedcliffTrainConfig(max_iter=2, batch_size=16, check_every=1),
        [{"gen_lr": 1e-3}, {"gen_lr": 1e20, "embed_lr": 1e20}],
        ds, ds, key=jax.random.PRNGKey(0), run_dir=run_dir)
    assert res.failures
    with open(os.path.join(run_dir, "failures.json")) as f:
        blob = json.load(f)
    assert blob["grid_size"] == 2
    assert blob["failures"][0]["point"] == 1
    # the quarantine cause rides into failures.json (numerics sentinel)
    assert blob["failures"][0]["cause"] in ("nonfinite_grad", "nonfinite_val")


# ---------------------------------------------------------------------------
# resume onto a different (smaller) device mesh
# ---------------------------------------------------------------------------
def test_resume_on_smaller_mesh(tmp_path):
    """Checkpoints hold gathered host state, so a fit that lost half its
    devices resumes on a smaller mesh — and still matches the uninterrupted
    big-mesh run (per-point compute is mesh-placement-invariant)."""
    import jax

    from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
    from redcliff_tpu.parallel.mesh import grid_mesh
    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig
    from test_parallel_grid import _data, _model

    model = _model()
    spec = GridSpec(points=[{"gen_lr": 1e-3 * (i + 1)} for i in range(8)])
    tc = RedcliffTrainConfig(max_iter=4, batch_size=32, check_every=1)
    ds = _data(model, n=32)

    full = RedcliffGridRunner(model, tc, spec, mesh=grid_mesh(8)).fit(
        jax.random.PRNGKey(3), ds, ds)

    ck = str(tmp_path / "ck")
    RedcliffGridRunner(model, tc, spec, mesh=grid_mesh(8)).fit(
        jax.random.PRNGKey(3), ds, ds, max_iter=2, checkpoint_dir=ck,
        checkpoint_every=1)
    resumed = RedcliffGridRunner(model, tc, spec, mesh=grid_mesh(4)).fit(
        jax.random.PRNGKey(3), ds, ds, checkpoint_dir=ck,
        checkpoint_every=1)
    np.testing.assert_allclose(resumed.val_history, full.val_history,
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_array_equal(resumed.best_epoch, full.best_epoch)


# ---------------------------------------------------------------------------
# injected probe failures follow the policy's backoff schedule
# ---------------------------------------------------------------------------
def test_injected_probe_failures_follow_backoff_schedule():
    policy = RetryPolicy(max_attempts=5, base_delay_s=3.0, multiplier=2.0,
                         max_delay_s=10.0)
    slept = []
    out = retry(flaky(3), policy, is_success=lambda r: r[0],
                info_of=lambda r: r[1], sleep=slept.append)
    # exact exponential schedule, capped: 3, 6, 10 (not 12)
    assert slept == [3.0, 6.0, 10.0]
    assert out.ok and out.value == (True, "ok")
    log = out.log()
    assert [a["backoff_s"] for a in log["attempts"]] == [0.0, 3.0, 6.0, 10.0]
    assert [a["ok"] for a in log["attempts"]] == [False, False, False, True]
    assert log["deadline_hit"] is False
    assert log["policy"]["max_attempts"] == 5


def test_retry_deadline_cuts_schedule():
    clock = {"t": 0.0}

    def fake_sleep(s):
        clock["t"] += s

    policy = RetryPolicy(max_attempts=10, base_delay_s=10.0, multiplier=1.0,
                         max_delay_s=10.0, deadline_s=25.0)
    out = retry(flaky(100), policy, is_success=lambda r: r[0],
                sleep=fake_sleep, monotonic=lambda: clock["t"])
    # attempts at t=0, 10, 20; the t=30 attempt would cross the deadline
    assert len(out.attempts) == 3
    assert out.deadline_hit and not out.ok


def test_retry_giveup_aborts_immediately():
    calls = []

    def fn(attempt):
        calls.append(attempt)
        raise GiveUp("budget exhausted")

    out = retry(fn, RetryPolicy(max_attempts=5, base_delay_s=0.0),
                sleep=lambda s: None)
    assert calls == [0]
    assert not out.ok and "budget exhausted" in out.error


def test_retry_exception_classification():
    # non-retryable exceptions surface immediately
    def boom(attempt):
        raise KeyError("nope")

    with pytest.raises(KeyError):
        retry(boom, RetryPolicy(max_attempts=3, base_delay_s=0.0),
              retryable=lambda e: isinstance(e, OSError),
              sleep=lambda s: None)

    # retryable exceptions burn attempts, then the last one re-raises
    probe = flaky(100, exc=OSError("bind failed"))
    with pytest.raises(OSError):
        retry(probe, RetryPolicy(max_attempts=3, base_delay_s=0.0),
              retryable=lambda e: isinstance(e, OSError),
              sleep=lambda s: None)
    assert probe.calls() == 3

    # a retryable failure followed by success recovers
    probe2 = flaky(2, exc=OSError("bind failed"))
    out = retry(probe2, RetryPolicy(max_attempts=5, base_delay_s=0.0),
                is_success=lambda r: r[0],
                retryable=lambda e: isinstance(e, OSError),
                sleep=lambda s: None)
    assert out.ok and probe2.calls() == 3
