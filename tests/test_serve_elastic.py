"""Elastic serve data plane tests (redcliff_tpu/serve, ISSUE 20).

Pins the elasticity contracts on top of the ISSUE-17 serve plane: the pow2
occupancy-rung helper, the ladder policy's priced shrink verdicts (growth
mandatory, hysteresis, empty-evidence always-max fallback), BYTE identity
of served records across every elastic axis — ladder on/off, grow ->
shrink -> grow under a seeded sawtooth churn storm, micro-batched tick
fusion on/off at equal sample counts, and the f32-vs-mixed demotion path —
plus drain/resume re-packing lanes across rung geometries (and the
both-geometries error when the checkpoint cannot fit), the poisoned-lane
storm auto-demotion sentinel with its persisted checkpoint bit, the
graph-mix kernel's interpret-mode bitwise parity, and schema-valid
serve_ladder/serve_fuse/precision telemetry. The slow-marked soak rides a
long sawtooth with NaN poisoning through the forced ladder.
"""
import os

import numpy as np
import pytest

from redcliff_tpu.models.redcliff import RedcliffSCMLP, RedcliffSCMLPConfig
from redcliff_tpu.obs import read_jsonl, schema
from redcliff_tpu.parallel.compaction import serve_rung
from redcliff_tpu.serve import chaos
from redcliff_tpu.serve.engine import StreamEngine
from redcliff_tpu.serve.service import (MIN_RUNG, ServeLadder, ServeService,
                                        STATE_BASENAME)

C = 4          # channels
L = 4          # embed_lag == ring length


def _model():
    return RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=C, gen_lag=2, gen_hidden=(8,), embed_lag=L,
        embed_hidden_sizes=(8,), num_factors=2, num_supervised_factors=2,
        factor_weight_l1_coeff=0.01, adj_l1_reg_coeff=0.001,
        factor_cos_sim_coeff=0.01,
        factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive", num_sims=1,
        training_mode="combined"))


@pytest.fixture(scope="module")
def fitted():
    import jax
    model = _model()
    return model, model.init(jax.random.PRNGKey(0))


def _service(fitted, **kw):
    model, params = fitted
    kw.setdefault("lease_s", 30.0)
    kw.setdefault("resume", False)
    return ServeService(model, params, **kw)


def _events(root, name):
    return [r for r in read_jsonl(root) if r.get("event") == name]


# ------------------------------------------------------------- rung helper
def test_serve_rung_pow2_and_clamps():
    """The rung is the smallest pow2 >= max(live, min_rung), clamped to
    capacity (non-pow2 capacities clamp, never round up past the table)."""
    assert serve_rung(0, 8) == 1
    assert serve_rung(1, 8) == 1
    assert serve_rung(3, 8) == 4
    assert serve_rung(4, 8) == 4
    assert serve_rung(5, 8) == 8
    assert serve_rung(9, 8) == 8          # clamp: never above capacity
    assert serve_rung(17, 32) == 32
    assert serve_rung(0, 8, min_rung=4) == 4
    assert serve_rung(2, 8, min_rung=4) == 4
    assert serve_rung(3, 3) == 3          # non-pow2 capacity clamps
    assert serve_rung(1, 3) == 1


# ------------------------------------------------------------ ladder policy
def test_ladder_growth_mandatory_shrink_hysteresis():
    """Growth fires immediately (a leased slot beyond the rung would never
    be dispatched); shrink waits out ``hold`` consecutive under-rung ticks
    even in force mode."""
    lad = ServeLadder(16, mode="force", hold=2)
    w, ev = lad.decide(2, 16, lambda w: True)
    assert (w, ev) == (16, None)          # first under-rung tick: hold
    w, ev = lad.decide(2, 16, lambda w: True)
    assert w == 4 and ev["kind"] == "shrink" and ev["reason"] == "forced"
    w, ev = lad.decide(10, 4, lambda w: True)
    assert w == 16 and ev["kind"] == "grow" and ev["live"] == 10


def test_ladder_off_always_capacity():
    lad = ServeLadder(16, mode="off", hold=1)
    assert lad.decide(2, 16, lambda w: False) == (16, None)
    assert lad.target(2) == 16


def test_ladder_auto_no_evidence_holds_max(tmp_path, monkeypatch):
    """Empty persistent store + no local timings: auto mode must hold the
    current (maximum) rung — the bit-identical fallback — and say so once
    per hysteresis episode, not per tick."""
    monkeypatch.setenv("REDCLIFF_COST_MODEL_DIR", str(tmp_path))
    lad = ServeLadder(16, mode="auto", hold=1)
    w, ev = lad.decide(2, 16, lambda w: True)
    assert w == 16 and ev["kind"] == "fallback" \
        and ev["reason"] == "no_evidence"
    w, ev = lad.decide(2, 16, lambda w: True)
    assert (w, ev) == (16, None)          # episode already reported


def test_ladder_auto_prices_shrink_vs_compile(tmp_path, monkeypatch):
    """The auto verdict is the PR-15 pricing shape: predicted dead-lane
    saving over the horizon vs the target rung's compile cost when cold."""
    monkeypatch.setenv("REDCLIFF_COST_MODEL_DIR", str(tmp_path))
    lad = ServeLadder(16, mode="auto", hold=1, horizon=100)
    for _ in range(4):
        lad.observe(16, 10.0, cold=False)
    # warm target: zero compile cost, per-lane prior says 4 lanes cost
    # 2.5ms -> saving 7.5ms * 100 ticks, shrink approved
    w, ev = lad.decide(2, 16, lambda w: False)
    assert w == 4 and ev["kind"] == "shrink"
    assert ev["saving_ms"] == pytest.approx(750.0)
    # cold target with NO compile evidence anywhere: unpriceable, hold
    lad2 = ServeLadder(16, mode="auto", hold=1, horizon=100)
    for _ in range(4):
        lad2.observe(16, 10.0, cold=False)
    w, ev = lad2.decide(2, 16, lambda w: True)
    assert w == 16 and ev["reason"] == "compile_unpriceable"
    # compile evidence says the cold program costs MORE than the saving:
    # hold with the priced verdict on the record
    lad2.observe(4, 5000.0, cold=True)
    lad2._below = 0
    w, ev = lad2.decide(2, 16, lambda w: True)
    assert w == 16 and ev["kind"] == "hold" \
        and ev["reason"] == "not_worth_compile"
    # a longer horizon flips the same evidence to a shrink
    lad3 = ServeLadder(16, mode="auto", hold=1, horizon=1000)
    for _ in range(4):
        lad3.observe(16, 10.0, cold=False)
    lad3.observe(4, 5000.0, cold=True)
    w, ev = lad3.decide(2, 16, lambda w: True)
    assert w == 4 and ev["kind"] == "shrink" and ev["cold"] is True


def test_ladder_rows_feed_cost_store():
    lad = ServeLadder(8, mode="auto", hold=1, shape_key="serve|x",
                      precision="f32")
    lad.observe(8, 10.0, cold=False)
    lad.observe(8, 12.0, cold=False)
    lad.observe(4, 100.0, cold=True)
    rows = lad.rows()
    by_w = {r["g_bucket"]: r for r in rows}
    assert by_w[8]["epochs"] == 2 and by_w[8]["epoch_ms"] == 22.0
    assert by_w[4]["compiles"] == 1 and by_w[4]["compile_ms"] == 100.0
    assert all(r["shape"] == "serve|x" for r in rows)


# ---------------------------------------------------------------- engine
def test_engine_fused_scan_bitwise_equals_sequential(fitted):
    """One fused lax.scan over F backlogged samples is BITWISE equal to F
    sequential single-sample dispatches — the fusion identity at the
    engine level, before any service plumbing."""
    model, params = fitted
    rng = np.random.default_rng(0)
    W, F = 3, 5
    samples = rng.normal(size=(W, F, C)).astype(np.float32)
    arrive = np.ones((W, F), dtype=bool)
    arrive[2, 3] = False                  # a ragged hole in the backlog

    eng_a = StreamEngine(model, params, capacity=W)
    seq = [eng_a.step(samples[:, f], arrive[:, f]) for f in range(F)]
    eng_b = StreamEngine(model, params, capacity=W)
    fused = eng_b.step_fused(samples, arrive)

    for f in range(F):
        for k in seq[f]:
            a = np.asarray(seq[f][k])
            b = np.asarray(fused[k][f])
            assert a.tobytes() == b.tobytes(), (k, f)
    sa, sb = eng_a.export_state(), eng_b.export_state()
    for k in sa:
        assert np.asarray(sa[k]).tobytes() == np.asarray(sb[k]).tobytes()


def test_engine_resize_preserves_lane_bytes(fitted):
    """Grow -> shrink -> grow at the engine level: occupied lanes are
    byte-identical to a fixed-width run at every step (shrink slices,
    grow zero-pads; lane math is row-independent)."""
    model, params = fitted
    rng = np.random.default_rng(1)
    xs = rng.normal(size=(8, 2, C)).astype(np.float32)

    fixed = StreamEngine(model, params, capacity=8)
    elastic = StreamEngine(model, params, capacity=8)
    elastic.resize(4)
    for t in range(8):
        s = np.zeros((8, C), np.float32)
        s[:2] = xs[t]
        arr = np.zeros(8, bool)
        arr[:2] = True
        a = fixed.step(s, arr)
        if t == 3:
            elastic.resize(8)
        if t == 5:
            elastic.resize(4)
        w = elastic.width
        b = elastic.step(s[:w], arr[:w])
        for k in a:
            assert np.asarray(a[k])[:2].tobytes() \
                == np.asarray(b[k])[:2].tobytes(), (k, t)
    with pytest.raises(ValueError):
        elastic.resize(16)                # beyond capacity
    with pytest.raises(ValueError):
        elastic.resize(0)


def test_graph_mix_interpret_bitwise_parity():
    """The serve-path graph mix (weightings x static factor graphs through
    the PR-14 factor-mix kernel) is bitwise equal to the reference einsum
    in interpret mode — the exact-jnp parity anchor for the mixed path's
    TPU routing."""
    import jax.numpy as jnp

    from redcliff_tpu.ops.factor_mix import (factor_mix_reference, graph_mix,
                                             graph_mix_reference)
    rng = np.random.default_rng(2)
    for S, K, D in ((7, 3, 5), (1, 2, 4), (16, 5, 3)):
        w = jnp.asarray(rng.random((S, K)).astype(np.float32))
        g = jnp.asarray(rng.normal(size=(K, D, D)).astype(np.float32))
        got = graph_mix(w, g, interpret=True)
        # bitwise vs the kernel's exact-jnp anchor (the broadcast
        # factor-mix reference — same contraction the kernel runs)
        preds = jnp.broadcast_to(g[:, None], (K, S, D, D))
        want = factor_mix_reference(w, preds)
        assert np.asarray(got).tobytes() == np.asarray(want).tobytes(), \
            (S, K, D)
        # and numerically the same blend the non-TPU engine path serves
        np.testing.assert_allclose(np.asarray(got),
                                   np.asarray(graph_mix_reference(w, g)),
                                   rtol=1e-6, atol=1e-7)


# ------------------------------------------------- service-level identity
def test_ladder_identity_under_sawtooth_churn(fitted, tmp_path,
                                              monkeypatch):
    """THE elasticity pin: a forced-ladder service riding grow -> shrink ->
    grow under a seeded sawtooth churn storm answers its victims
    byte-identically to a ladder-off (always-max) run, and the ladder's
    decisions are schema-valid."""
    monkeypatch.setenv("REDCLIFF_SERVE_LADDER_HOLD", "2")
    victims = {f"v{i}": chaos.stream_samples(50 + i, 16, C)
               for i in range(2)}

    def run(mode, root):
        svc = _service(fitted, capacity=16, root=str(root), ladder=mode)
        for sid in victims:
            svc.connect(sid=sid, now=0.0)
        storm = chaos.make_sawtooth_storm(9, C, lo=0, hi=6, period=5)
        res = chaos.drive(svc, victims, ticks=24, chaos_fn=storm)
        svc.stop()
        return res

    root_on = tmp_path / "on"
    res_on = run("force", root_on)
    res_off = run("off", tmp_path / "off")
    identical, compared, detail = chaos.outputs_identical(res_on, res_off)
    assert identical and compared > 0, detail

    trans = [(e["kind"], e["to_width"])
             for e in _events(str(root_on), "serve_ladder")
             if e.get("kind") in ("grow", "shrink")]
    kinds = [k for k, _ in trans]
    assert "grow" in kinds and "shrink" in kinds and len(trans) >= 3
    assert all(w in (4, 8, 16) for _, w in trans)
    records = read_jsonl(str(root_on))
    assert schema.validate_records(records) == []


def test_fusion_identity_equal_sample_counts(fitted, tmp_path):
    """Backlogged streams drained through the fused scan answer the exact
    bytes of an unfused pump-per-sample run, and the serve_fuse stats
    event reports the depth histogram."""
    xs = chaos.stream_samples(5, 24, C)

    def run(fuse, burst, root):
        svc = _service(fitted, capacity=4, root=str(root), ladder="off",
                       fuse=fuse)
        svc.connect(sid="s", now=0.0)
        now, recs = 0.0, []
        for i in range(24):
            now += 0.01
            svc.ingest("s", xs[i], now=now)
            if (i + 1) % burst == 0:
                svc.pump(now=now)
                recs.extend(svc.poll("s", now=now))
        # trailing pumps both drain stragglers and cross the _TICK_EVERY
        # cadence so the serve_fuse stats event lands
        for _ in range(20):
            now += 0.01
            svc.pump(now=now)
            recs.extend(svc.poll("s", now=now))
        svc.stop()
        return {"s": recs}, svc

    plain, _ = run(1, 1, tmp_path / "plain")
    fused, svc_f = run(4, 4, tmp_path / "fused")
    identical, compared, detail = chaos.outputs_identical(plain, fused)
    assert identical and compared == 24 - L + 1, detail
    assert svc_f._fused_samples > 0
    stats = [e for e in _events(str(tmp_path / "fused"), "serve_fuse")
             if e.get("kind") == "stats"]
    assert stats and stats[-1]["fused_samples"] == svc_f._fused_samples
    assert "4" in stats[-1]["hist"] or 4 in stats[-1]["hist"]


def test_mixed_precision_parity_and_finiteness(fitted):
    """The mixed serve path answers close to f32 (bf16 contraction
    tolerance) with every score finite — the path-alive pin on every
    backend; bitwise equality only holds where the backend's matmul
    ignores the bf16 hint."""
    xs = {f"v{i}": chaos.stream_samples(70 + i, 12, C) for i in range(2)}

    def run(pm):
        svc = _service(fitted, capacity=4, ladder="off", precision_mode=pm)
        for sid in xs:
            svc.connect(sid=sid, now=0.0)
        res = chaos.drive(svc, xs, ticks=16)
        svc.stop()
        return res

    a, b = run("f32"), run("mixed")
    for sid in xs:
        assert len(a[sid]) == len(b[sid]) > 0
        for ra, rb in zip(a[sid], b[sid]):
            sa, sb = np.asarray(ra["scores"]), np.asarray(rb["scores"])
            assert np.all(np.isfinite(sb))
            np.testing.assert_allclose(sa, sb, rtol=2e-2, atol=1e-3)
    with pytest.raises(ValueError):
        _service(fitted, capacity=2, precision_mode="tf32-ish")


def test_poison_storm_demotes_and_resume_honors_it(fitted, tmp_path,
                                                   monkeypatch):
    """The demotion sentinel: a poisoned-lane storm inside the window
    demotes the mixed table to f32 (precision event, engine latch), the
    drain checkpoint persists the bit, a restarted mixed server comes up
    demoted, and post-demotion victim records are BYTE-identical to a
    pure-f32 run (the demoted program carries no precision context and
    the ring holds raw f32 samples)."""
    monkeypatch.setenv("REDCLIFF_SERVE_DEMOTE_STORM", "2")
    root = tmp_path / "mix"
    victims = {"v0": chaos.stream_samples(80, 20, C)}

    def storm(svc, t, now):
        if t == 2:
            for i in range(3):
                svc.connect(sid=f"p{i}", now=now)
        if 2 <= t <= 6:
            for i in range(3):
                x = np.full(C, np.nan, np.float32)
                svc.ingest(f"p{i}", x, now=now)

    svc = _service(fitted, capacity=8, root=str(root),
                   precision_mode="mixed")
    svc.connect(sid="v0", now=0.0)
    res_mixed = chaos.drive(svc, victims, ticks=24, chaos_fn=storm)
    assert svc.engine.demoted
    ck = svc.drain(now=5.0)
    assert ck and os.path.basename(ck) == STATE_BASENAME

    prec = [e for e in _events(str(root), "precision")
            if e.get("scope") == "serve"]
    assert any(e["kind"] == "demote"
               and e["cause"] == "poisoned-lane storm" for e in prec)

    # f32 control run: same victims, same storm shape (quarantined lanes
    # never perturb co-residents either way)
    svc_f = _service(fitted, capacity=8, ladder="off")
    svc_f.connect(sid="v0", now=0.0)
    res_f32 = chaos.drive(svc_f, victims, ticks=24, chaos_fn=storm)
    svc_f.stop()
    # records produced AFTER the demotion tick must byte-match f32
    demote_tick = next(e["ticks"] for e in prec if e["kind"] == "demote")
    post_m = [r for r in res_mixed["v0"]
              if r.get("seq", 0) > demote_tick + L]
    post_f = res_f32["v0"][-len(post_m):] if post_m else []
    assert post_m, "storm must land before the victim stream ends"
    ok, n, detail = chaos.outputs_identical({"v0": post_m}, {"v0": post_f})
    assert ok and n == len(post_m), detail

    # restart: the checkpoint's demotion bit must win over the requested
    # mixed mode, with the resume_demoted event on the record
    svc2 = _service(fitted, capacity=8, root=str(root),
                    precision_mode="mixed", resume=True)
    assert svc2.engine.demoted
    svc2.stop()
    prec2 = [e for e in _events(str(root), "precision")
             if e.get("scope") == "serve"]
    assert any(e["kind"] == "resume_demoted" for e in prec2)


# --------------------------------------------------------- drain / resume
def test_resume_repacks_lanes_across_rung_geometries(fitted, tmp_path,
                                                     monkeypatch):
    """Drain at one capacity, resume at another: live lanes re-pack into
    the new table at the rung their count wants, the repack is on the
    serve_ladder record, and the resumed stream's records byte-match an
    uninterrupted run."""
    monkeypatch.setenv("REDCLIFF_SERVE_LADDER_HOLD", "2")
    root = tmp_path / "rp"
    xs = {f"r{i}": chaos.stream_samples(90 + i, 14, C) for i in range(3)}

    ref = _service(fitted, capacity=4, ladder="off")
    for sid in xs:
        ref.connect(sid=sid, now=0.0)
    res_ref = chaos.drive(ref, xs, ticks=18)
    ref.stop()

    svc = _service(fitted, capacity=4, root=str(root), ladder="off")
    for sid in xs:
        svc.connect(sid=sid, now=0.0)
    first = {sid: arr[:7] for sid, arr in xs.items()}
    res_a = chaos.drive(svc, first, ticks=7)
    svc.drain(now=1.0)

    svc2 = _service(fitted, capacity=16, root=str(root), ladder="auto",
                    resume=True)
    assert sorted(svc2.registry.sessions) == sorted(xs)
    assert svc2.engine.capacity == 16
    assert svc2.engine.width == serve_rung(3, 16, MIN_RUNG)
    rest = {sid: arr[7:] for sid, arr in xs.items()}
    res_b = chaos.drive(svc2, rest, ticks=11, now0=2.0)
    svc2.stop()

    joined = {sid: res_a[sid] + res_b[sid] for sid in xs}
    identical, compared, detail = chaos.outputs_identical(joined, res_ref)
    assert identical and compared > 0, detail
    assert any(e.get("kind") == "repack"
               for e in _events(str(root), "serve_ladder"))


def test_resume_too_small_capacity_names_both_geometries(fitted, tmp_path):
    root = tmp_path / "small"
    svc = _service(fitted, capacity=4, root=str(root))
    for i in range(3):
        svc.connect(sid=f"s{i}", now=0.0)
    svc.drain(now=1.0)
    with pytest.raises(ValueError) as ei:
        _service(fitted, capacity=2, root=str(root), resume=True)
    msg = str(ei.value)
    assert "geometry mismatch" in msg
    assert "capacity 4" in msg and "capacity 2" in msg


# ----------------------------------------------------------- chaos harness
def test_sawtooth_storm_deterministic():
    """Same seed -> same triangle wave and same sample bytes (the
    reproduce-exactly contract every chaos actor carries)."""
    s1 = chaos.make_sawtooth_storm(3, C, lo=1, hi=5, period=4)
    s2 = chaos.make_sawtooth_storm(3, C, lo=1, hi=5, period=4)
    assert [s1.target(t) for t in range(10)] \
        == [s2.target(t) for t in range(10)] \
        == [1, 2, 3, 4, 5, 4, 3, 2, 1, 2]

    class _Rec:
        def __init__(self):
            self.log = []

        def connect(self, sid=None, now=None):
            self.log.append(("c", sid))

        def disconnect(self, sid):
            self.log.append(("d", sid))

        def ingest(self, sid, x, now=None):
            self.log.append(("i", sid, x.tobytes()))

    a, b = _Rec(), _Rec()
    for t in range(10):
        s1(a, t, 0.0)
        s2(b, t, 0.0)
    assert a.log == b.log


@pytest.mark.slow
def test_sawtooth_soak_identity(fitted, tmp_path, monkeypatch):
    """Long sawtooth with NaN poisoning through the forced ladder on a
    capacity-16 table: victims stay byte-identical to the always-max run
    across every rung the storm drags the table through."""
    monkeypatch.setenv("REDCLIFF_SERVE_LADDER_HOLD", "2")
    victims = {f"v{i}": chaos.stream_samples(60 + i, 40, C)
               for i in range(2)}

    def run(mode):
        svc = _service(fitted, capacity=16, ladder=mode, fuse=2)
        for sid in victims:
            svc.connect(sid=sid, now=0.0)
        storm = chaos.make_sawtooth_storm(11, C, lo=0, hi=10, period=8,
                                          nan_p=0.05)
        res = chaos.drive(svc, victims, ticks=56, chaos_fn=storm)
        svc.stop()
        return res

    res_on, res_off = run("force"), run("off")
    identical, compared, detail = chaos.outputs_identical(res_on, res_off)
    assert identical and compared > 0, detail
