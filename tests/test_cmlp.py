"""Parity tests: the tensorized cMLP must match an independently-written
torch Conv1d per-series model (the reference architecture) given identical
weights, and the prox/GC ops must match hand computations."""
import numpy as np
import pytest
import torch
import torch.nn as nn

import jax
import jax.numpy as jnp

from redcliff_tpu.models import cmlp as C
from redcliff_tpu.ops import prox as P


class TorchPerSeriesMLP(nn.Module):
    """Reference-architecture check model: one Conv1d(num_series->h, lag) + 1x1
    convs per output series, outputs concatenated (written fresh for testing)."""

    def __init__(self, num_series, lag, hidden):
        super().__init__()
        dims = list(hidden) + [1]
        self.nets = nn.ModuleList()
        for _ in range(num_series):
            layers = [nn.Conv1d(num_series, dims[0], lag)]
            for d_in, d_out in zip(dims[:-1], dims[1:]):
                layers.append(nn.Conv1d(d_in, d_out, 1))
            self.nets.append(nn.ModuleList(layers))

    def forward(self, X):  # X: (B, T, C)
        outs = []
        for net in self.nets:
            h = X.transpose(2, 1)
            for i, conv in enumerate(net):
                if i != 0:
                    h = torch.relu(h)
                h = conv(h)
            outs.append(h.transpose(2, 1))
        return torch.cat(outs, dim=2)


def _copy_torch_into_jax(tmodel, num_series, lag, hidden):
    dims = list(hidden) + [1]
    layers = []
    w0 = np.stack([net[0].weight.detach().numpy() for net in tmodel.nets])  # (C, H, C, L)
    b0 = np.stack([net[0].bias.detach().numpy() for net in tmodel.nets])
    layers.append({"w": jnp.asarray(w0), "b": jnp.asarray(b0)})
    for li in range(1, len(dims)):
        w = np.stack([net[li].weight.detach().numpy()[:, :, 0] for net in tmodel.nets])
        b = np.stack([net[li].bias.detach().numpy() for net in tmodel.nets])
        layers.append({"w": jnp.asarray(w), "b": jnp.asarray(b)})
    return layers


@pytest.mark.parametrize("hidden", [[8], [8, 6]])
def test_cmlp_forward_matches_torch_reference_arch(hidden):
    torch.manual_seed(0)
    B, T, Cn, lag = 3, 12, 5, 4
    tmodel = TorchPerSeriesMLP(Cn, lag, hidden)
    params = _copy_torch_into_jax(tmodel, Cn, lag, hidden)
    X = np.random.default_rng(0).normal(size=(B, T, Cn)).astype(np.float32)
    with torch.no_grad():
        t_out = tmodel(torch.from_numpy(X)).numpy()
    j_out = np.asarray(C.cmlp_forward(params, jnp.asarray(X)))
    assert j_out.shape == (B, T - lag + 1, Cn)
    np.testing.assert_allclose(j_out, t_out, rtol=1e-4, atol=1e-5)


def test_cmlp_gc_matches_torch_norms():
    torch.manual_seed(1)
    Cn, lag, hidden = 4, 3, [6]
    tmodel = TorchPerSeriesMLP(Cn, lag, hidden)
    params = _copy_torch_into_jax(tmodel, Cn, lag, hidden)
    # torch: GC[i, j] = || net_i.layers[0].weight[:, j, :] || over (hidden, lag)
    expected = np.stack([
        torch.norm(net[0].weight, dim=(0, 2)).detach().numpy() for net in tmodel.nets
    ])
    got = np.asarray(C.cmlp_gc(params, ignore_lag=True))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)
    got_lag = np.asarray(C.cmlp_gc(params, ignore_lag=False))
    expected_lag = np.stack([
        torch.norm(net[0].weight, dim=0).detach().numpy() for net in tmodel.nets
    ])
    np.testing.assert_allclose(got_lag, expected_lag, rtol=1e-5, atol=1e-6)


def test_prox_gl_matches_manual_soft_threshold():
    rng = np.random.default_rng(0)
    W = jnp.asarray(rng.normal(size=(4, 6, 4, 3)))  # (C_out, H, C_in, L)
    lam, lr = 0.7, 0.1
    out = P.prox_update(W, lam, lr, penalty="GL")
    W_np = np.asarray(W)
    norm = np.sqrt((W_np**2).sum(axis=(1, 3), keepdims=True))
    expected = (W_np / np.maximum(norm, lr * lam)) * np.maximum(norm - lr * lam, 0.0)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6, atol=1e-7)


def test_prox_gl_zeroes_small_groups_keeps_large():
    W = np.zeros((2, 3, 2, 2), dtype=np.float32)
    W[0, :, 0, :] = 5.0   # large group survives
    W[0, :, 1, :] = 0.01  # small group is zeroed
    out = np.asarray(P.prox_update(jnp.asarray(W), lam=1.0, lr=0.1))
    assert np.all(out[0, :, 1, :] == 0.0)
    assert np.all(np.abs(out[0, :, 0, :]) > 0.0)
    # shrinkage direction preserved
    assert np.all(out[0, :, 0, :] < 5.0)


def test_prox_h_hierarchical_prefix_structure():
    rng = np.random.default_rng(1)
    W = jnp.asarray(rng.normal(size=(2, 4, 2, 3)))
    out = P.prox_update(W, lam=0.5, lr=0.2, penalty="H")
    assert out.shape == W.shape
    # H with large threshold kills the most-lagged entries first (lag index 0)
    out_strong = np.asarray(P.prox_update(W, lam=20.0, lr=0.2, penalty="H"))
    assert np.abs(out_strong[..., 0]).sum() <= np.abs(out_strong[..., -1]).sum() + 1e-6


def test_prox_gsgl_composes():
    rng = np.random.default_rng(2)
    W = jnp.asarray(rng.normal(size=(2, 4, 2, 3)))
    out = P.prox_update(W, lam=0.5, lr=0.2, penalty="GSGL")
    W_np = np.asarray(W)
    n1 = np.sqrt((W_np**2).sum(axis=1, keepdims=True))
    step1 = (W_np / np.maximum(n1, 0.1)) * np.maximum(n1 - 0.1, 0.0)
    n2 = np.sqrt((step1**2).sum(axis=(1, 3), keepdims=True))
    expected = (step1 / np.maximum(n2, 0.1)) * np.maximum(n2 - 0.1, 0.0)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6, atol=1e-7)


def test_vmap_over_factor_axis():
    """The K-factor extension is literally a vmap of the single-factor model."""
    key = jax.random.PRNGKey(0)
    K, Cn, lag, hidden = 3, 4, 2, [5]
    keys = jax.random.split(key, K)
    params = jax.vmap(lambda k: C.init_cmlp_params(k, Cn, lag, hidden))(keys)
    X = jax.random.normal(jax.random.PRNGKey(1), (2, 6, Cn))
    out = jax.vmap(lambda p: C.cmlp_forward(p, X))(params)
    assert out.shape == (K, 2, 5, Cn)
    gc = jax.vmap(lambda p: C.cmlp_gc(p))(params)
    assert gc.shape == (K, Cn, Cn)


def test_wavelet_mask_values():
    mask = np.asarray(C.build_wavelet_ranking_mask(8))
    # mask[i, j] = 1.3^(2(1 - i%4)) * 1.3^(2(1 - j%4))
    assert mask[0, 0] == pytest.approx(1.3**2 * 1.3**2)
    assert mask[1, 1] == pytest.approx(1.0)
    assert mask[3, 3] == pytest.approx(1.3**-4 * 1.3**-4)
    assert mask[4, 0] == pytest.approx(mask[0, 0])  # periodic across channels


def test_condense_wavelet_gc_blocks():
    ns, nc = 8, 2
    GC = jnp.asarray(np.arange(ns * ns, dtype=np.float32).reshape(ns, ns))
    cond = np.asarray(C.condense_wavelet_gc(GC, nc))
    assert cond.shape == (2, 2)
    manual = np.asarray(GC).reshape(2, 4, 2, 4).sum(axis=(1, 3))
    np.testing.assert_allclose(cond, manual)
