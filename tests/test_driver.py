"""Tests for the L5 experiment driver layer."""
import json
import os

import numpy as np
import pytest

from redcliff_tpu.data.curation import curate_synthetic_fold
from redcliff_tpu.train.driver import (
    kick_off_model_training_experiment,
    rescale_dataset_dependent_coefficients,
    run_coefficient_grid,
    run_folder_name,
    set_up_and_run_experiments,
)


def test_run_folder_name_encoding():
    args = {"model_type": "REDCLIFF_S_CMLP", "data_set_name": "d4IC_HSNR",
            "coeff_dict": {"FORECAST_COEFF": 10.0,
                           "FACTOR_SCORE_COEFF": 100.0,
                           "FACTOR_COS_SIM_COEFF": 0.123456789,
                           "FACTOR_WEIGHT_L1_COEFF": 0.001,
                           "ADJ_L1_REG_COEFF": 1.0}}
    name = run_folder_name(args)
    assert name.startswith("REDCLIFF_S_CMLP_d4IC_HSNR_fc10-0")
    assert "fsc100-0" in name
    assert "fcsc0-123456"[:8] in name  # clipped to 8 chars
    assert "." not in name


def test_coefficient_rescaling():
    args = {"num_factors": 5, "num_channels": 10,
            "coeff_dict": {"FORECAST_COEFF": 10.0,
                           "FACTOR_SCORE_COEFF": 100.0,
                           "FACTOR_COS_SIM_COEFF": 1.0,
                           "ADJ_L1_REG_COEFF": 1.0}}
    rescale_dataset_dependent_coefficients(args)
    cd = args["coeff_dict"]
    assert cd["FACTOR_COS_SIM_COEFF"] == pytest.approx(1.0 / 10.0)  # sum 1..4
    assert cd["ADJ_L1_REG_COEFF"] == pytest.approx(
        (1.0 / 5.0) / np.sqrt(99.0))
    assert args["stopping_criteria_forecast_coeff"] == 10.0
    assert args["stopping_criteria_factor_coeff"] == 100.0
    assert args["stopping_criteria_cosSim_coeff"] == cd[
        "FACTOR_COS_SIM_COEFF"]


def _write_cmlp_model_args(path):
    model_args = {
        "num_sims": "1", "embed_hidden_sizes": "[8]", "batch_size": "4",
        "gen_eps": "0.0001", "gen_weight_decay": "0.0", "max_iter": "2",
        "lookback": "2", "check_every": "2", "verbose": "0",
        "output_length": "1", "wavelet_level": "None", "gen_hidden": "[8]",
        "gen_lr": "0.01", "gen_lag_and_input_len": "3",
        "FORECAST_COEFF": "1.0", "ADJ_L1_REG_COEFF": "0.01",
        "DAGNESS_REG_COEFF": "0.0", "DAGNESS_LAG_COEFF": "0.0",
        "DAGNESS_NODE_COEFF": "0.0",
    }
    with open(path, "w") as f:
        json.dump(model_args, f)


def test_set_up_and_run_experiments_array_task(tmp_path):
    fold_dir, _ = curate_synthetic_fold(
        str(tmp_path / "data"), fold_id=0, num_nodes=5, num_factors=2,
        num_samples_in_train_set=6, num_samples_in_val_set=3,
        sample_recording_len=30, folder_name="toySys")
    margs = tmp_path / "cMLP_toy_cached_args.txt"
    _write_cmlp_model_args(str(margs))
    data_args_file = os.path.join(fold_dir, "data_fold0_cached_args.txt")

    save_root = tmp_path / "runs"
    os.makedirs(save_root)
    args = {"save_root_path": str(save_root)}
    task_id = set_up_and_run_experiments(
        args, [str(margs)], [data_args_file],
        possible_model_types=["cMLP"],
        possible_data_sets=["data_fold0"], task_id=1)
    assert task_id == 1
    runs = os.listdir(save_root)
    assert len(runs) == 1 and runs[0].startswith("cMLP_data_fold0")
    run_dir = save_root / runs[0]
    assert (run_dir / "final_best_model.bin").exists()

    # rerun with existing artifacts flips into resume mode without error
    set_up_and_run_experiments(
        args, [str(margs)], [data_args_file],
        possible_model_types=["cMLP"],
        possible_data_sets=["data_fold0"], task_id=1)


def test_run_coefficient_grid_over_mesh(tmp_path):
    """TPU-first grid execution: several coefficient variants trained at once
    over the virtual 8-device CPU mesh."""
    import jax

    from redcliff_tpu.models.redcliff import (
        RedcliffSCMLP,
        RedcliffSCMLPConfig,
    )
    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig
    from redcliff_tpu.data.datasets import ArrayDataset

    rng = np.random.default_rng(0)
    X = rng.normal(size=(16, 20, 4)).astype(np.float32)
    Y = rng.uniform(size=(16, 2, 20)).astype(np.float32)
    train = ArrayDataset(X[:12], Y[:12])
    val = ArrayDataset(X[12:], Y[12:], stats=train.stats)

    cfg = RedcliffSCMLPConfig(
        num_chans=4, gen_lag=2, gen_hidden=(6,), embed_lag=4,
        embed_hidden_sizes=(6,), num_factors=2, num_supervised_factors=2,
        factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive",
        training_mode="combined", num_pretrain_epochs=0)
    model = RedcliffSCMLP(cfg)
    tc = RedcliffTrainConfig(max_iter=2, batch_size=4, check_every=2)
    points = [{"gen_lr": 1e-3 * (i + 1)} for i in range(4)]
    result = run_coefficient_grid(model, tc, points, train, val)
    assert len(result.best_criteria) == 4
    assert np.isfinite(np.asarray(result.best_criteria)).all()
