"""Tests for the dCSFA-NMF family (ref models/dcsfa_nmf.py,
dcsfa_nmf_vanillaDirSpec.py)."""
import numpy as np
import jax
import pytest

from redcliff_tpu.models.dcsfa_nmf import (
    DcsfaNmf,
    DcsfaNmfConfig,
    FullDCSFAModel,
    mann_whitney_auc,
    nmf_fit,
    nndsvd_init,
)
from redcliff_tpu.utils.misc import flatten_directed_spectrum_features


def _lowrank_nonneg(rng, n=80, d=30, k=3):
    W = rng.uniform(0.0, 1.0, size=(n, k))
    H = rng.uniform(0.0, 1.0, size=(k, d))
    return (W @ H).astype(np.float32)


def test_nndsvd_nonnegative():
    rng = np.random.default_rng(0)
    X = _lowrank_nonneg(rng)
    W, H = nndsvd_init(X, 3)
    assert (W >= 0).all() and (H >= 0).all()
    assert W.shape == (80, 3) and H.shape == (3, 30)


def test_nmf_fit_reduces_error():
    rng = np.random.default_rng(1)
    X = _lowrank_nonneg(rng) + 0.01
    S0, H0 = nmf_fit(X, 3, max_iter=0)
    S, H = nmf_fit(X, 3, max_iter=200)
    err0 = np.mean((X - S0 @ H0) ** 2)
    err = np.mean((X - S @ H) ** 2)
    assert (S >= 0).all() and (H >= 0).all()
    assert err < err0
    assert err < 1e-2 * np.mean(X**2)


def test_nmf_fit_is_loss_runs():
    rng = np.random.default_rng(2)
    X = _lowrank_nonneg(rng) + 0.05
    S, H = nmf_fit(X, 3, max_iter=50, loss="IS")
    assert np.isfinite(S).all() and np.isfinite(H).all()
    assert (S >= 0).all() and (H >= 0).all()


def test_mann_whitney_auc_matches_scipy():
    from scipy.stats import mannwhitneyu

    rng = np.random.default_rng(3)
    pos = rng.normal(1.0, 1.0, size=40)
    neg = rng.normal(0.0, 1.0, size=55)
    U, _ = mannwhitneyu(pos, neg)
    expected = U / (len(pos) * len(neg))
    assert mann_whitney_auc(pos, neg) == pytest.approx(expected)


def test_mann_whitney_auc_separable():
    assert mann_whitney_auc([3.0, 4.0], [1.0, 2.0]) == 1.0
    assert mann_whitney_auc([1.0, 2.0], [3.0, 4.0]) == 0.0


def _toy_supervised(rng, n=120, d=24, n_sup=2):
    """Two supervised latent factors, each driving a disjoint feature block
    and a binary label."""
    y = (rng.uniform(size=(n, n_sup)) > 0.5).astype(np.float32)
    scores = y * rng.uniform(1.0, 2.0, size=(n, n_sup)) + 0.05
    basis = np.zeros((n_sup, d), dtype=np.float32)
    basis[0, : d // 2] = rng.uniform(0.5, 1.0, size=d // 2)
    basis[1, d // 2 :] = rng.uniform(0.5, 1.0, size=d - d // 2)
    X = scores @ basis + 0.01 * rng.uniform(size=(n, d)).astype(np.float32)
    return X.astype(np.float32), y


def test_dcsfa_fit_learns_labels():
    rng = np.random.default_rng(4)
    X, y = _toy_supervised(rng)
    cfg = DcsfaNmfConfig(n_components=4, n_sup_networks=2, h=16,
                         use_deep_encoder=True, lr=1e-2)
    model = DcsfaNmf(cfg)
    params, state, hist = model.fit(
        jax.random.PRNGKey(0), X, y, n_epochs=40, n_pre_epochs=10,
        nmf_max_iter=50, batch_size=32)
    aucs = model.score(params, state, X, y)
    assert aucs.shape == (2,)
    assert np.mean(aucs) > 0.8
    assert len(hist["training"]) == 40
    # training loss should drop
    assert hist["training"][-1] < hist["training"][0]


def test_dcsfa_validation_checkpointing():
    rng = np.random.default_rng(5)
    X, y = _toy_supervised(rng)
    cfg = DcsfaNmfConfig(n_components=3, n_sup_networks=2, h=8)
    model = DcsfaNmf(cfg)
    params, state, hist = model.fit(
        jax.random.PRNGKey(1), X[:90], y[:90], X_val=X[90:], y_val=y[90:],
        n_epochs=8, n_pre_epochs=2, nmf_max_iter=20, batch_size=32)
    assert "best_epoch" in hist and 0 <= hist["best_epoch"] < 8
    assert len(hist["val_recon"]) == 8


def test_dcsfa_linear_encoder_and_transform_shapes():
    rng = np.random.default_rng(6)
    X, y = _toy_supervised(rng, n=60)
    cfg = DcsfaNmfConfig(n_components=3, n_sup_networks=2,
                         use_deep_encoder=False)
    model = DcsfaNmf(cfg)
    params, state, _ = model.fit(jax.random.PRNGKey(2), X, y, n_epochs=3,
                                 n_pre_epochs=1, nmf_max_iter=10,
                                 batch_size=16)
    X_recon, y_pred, s = model.transform(params, state, X)
    assert X_recon.shape == X.shape
    assert y_pred.shape == (60, 2)
    assert s.shape == (60, 3)
    assert (s >= 0).all()
    preds = model.predict(params, state, X)
    assert preds.dtype == bool and preds.shape == (60, 2)


def test_fixed_corr_constraints():
    cfg = DcsfaNmfConfig(n_components=3, n_sup_networks=2,
                         fixed_corr=("positive", "negative"))
    model = DcsfaNmf(cfg)
    params, _ = model.init(jax.random.PRNGKey(0), 10)
    phi = np.asarray(model.get_phi(params))
    assert phi[0] > 0 and phi[1] < 0
    with pytest.raises(ValueError):
        DcsfaNmfConfig(n_sup_networks=1, fixed_corr="bogus")


def test_full_dcsfa_gc_dirspec_layout():
    n_nodes, F = 3, 4
    model = FullDCSFAModel(num_nodes=n_nodes, num_high_level_node_features=F,
                           n_components=2, n_sup_networks=1, h=8)
    params, state = model.init(jax.random.PRNGKey(0), model.dim_in)
    graphs = model.gc(params, threshold=False)
    assert len(graphs) == 2
    assert graphs[0].shape == (n_nodes, n_nodes)
    assert (graphs[0] >= 0).all()
    binary = model.gc(params, threshold=True)
    assert set(np.unique(binary[0])).issubset({0, 1})


def test_full_dcsfa_gc_recovers_planted_tensor():
    """A W_nmf row built by flattening a known dirspec tensor unflattens with
    the REFERENCE's accumulate semantics: off-diagonal entries (present in
    two nodes' flattened rows) come back doubled, so the squared-and-summed
    GC carries a 4x off-diagonal factor (ref dcsfa_nmf.py:1305 via
    misc.py:178-195)."""
    n_nodes, F = 3, 2
    rng = np.random.default_rng(7)
    planted = rng.uniform(0.1, 1.0, size=(n_nodes, n_nodes, F))
    flat = flatten_directed_spectrum_features(planted)  # (n, F*(2n-1))
    model = FullDCSFAModel(num_nodes=n_nodes, num_high_level_node_features=F,
                           n_components=1, n_sup_networks=1, h=8)
    gc = model.get_factor_gc(flat.reshape(1, -1), threshold=False,
                             ignore_features=True)
    scale = 4.0 - 3.0 * np.eye(n_nodes)  # 1x diag, 4x off-diag
    np.testing.assert_allclose(gc, scale * (planted**2).sum(axis=2),
                               rtol=1e-6)


def test_full_dcsfa_vanilla_layout():
    n_nodes, F = 4, 3
    model = FullDCSFAModel(num_nodes=n_nodes, num_high_level_node_features=F,
                           gc_feature_layout="vanilla", n_components=2,
                           n_sup_networks=1, h=8)
    assert model.dim_in == n_nodes * n_nodes * F
    vec = np.arange(model.dim_in, dtype=np.float32)
    gc = model.get_factor_gc(vec, threshold=False, ignore_features=True)
    expected = (vec.reshape(n_nodes, n_nodes, F) ** 2).sum(axis=2)
    np.testing.assert_allclose(gc, expected, rtol=1e-6)


def test_full_dcsfa_evaluate_summary():
    n_nodes, F = 3, 2
    rng = np.random.default_rng(8)
    model = FullDCSFAModel(num_nodes=n_nodes, num_high_level_node_features=F,
                           n_components=2, n_sup_networks=1, h=8)
    params, state = model.init(jax.random.PRNGKey(1), model.dim_in)
    X = rng.uniform(size=(20, model.dim_in)).astype(np.float32)
    y = (rng.uniform(size=(20, 1)) > 0.5).astype(np.float32)
    GC_true = [rng.uniform(size=(n_nodes, n_nodes))]
    summary = model.evaluate(params, state, X, y, GC_true)
    assert {"gc_mse", "recon_mse", "score_mse"} <= set(summary)
    assert np.isfinite(summary["recon_mse"])
