"""Predictive scheduling + deadline-aware preemption (ISSUE 15).

Policy units (parallel/policy.py PredictiveSchedulingPolicy): the
bit-identical empty-store fallback contract, compact-vs-hold pricing,
warm-rung initial-width reuse, cold-compile ordering. Planner satellite:
the deterministic unknown-ETA tie-break under two different cost-model
stores. Worker preemption: the monitor's pricing decision, the settle
path's zero-charge reclaim accounting (PR 11 budgets untouched), the
``after_request`` pin deferral, and — slow-marked — the end-to-end
acceptance: checkpoint-and-preempt mid-fit, the higher-priority tenant
meets its deadline, and the preempted batch resumes with bit-identical
decision streams. Engine wiring: a REDCLIFF_PREDICTIVE fit emits
schema-valid ``policy`` events and stays bit-identical to the heuristic
when the store holds no steering prior.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

from redcliff_tpu.fleet import planner
from redcliff_tpu.fleet import worker as fleet_worker
from redcliff_tpu.fleet.queue import FleetQueue
from redcliff_tpu.fleet.__main__ import TINY_POINTS, TINY_SPEC
from redcliff_tpu.obs import costmodel
from redcliff_tpu.obs import schema as obs_schema
from redcliff_tpu.obs.logging import MetricLogger, read_jsonl
from redcliff_tpu.parallel.policy import (GridSchedulingPolicy,
                                          PredictiveSchedulingPolicy,
                                          predictive_enabled)

SHAPE = "num_chans=4"


def _model_with(rows, platform="cpu"):
    store = costmodel._empty_store()
    costmodel._merge_rows(store, rows, platform, now=1.0)
    return costmodel.CostModel(store)


def _row(shape, width, epoch_ms=None, epochs=10, compiles=0,
         compile_ms=0.0):
    return {"shape": shape, "g_bucket": width, "epochs": epochs,
            "epoch_ms": (epoch_ms or 0.0) * epochs, "compiles": compiles,
            "compile_ms": compile_ms}


# ---------------------------------------------------------------------------
# policy units
# ---------------------------------------------------------------------------
def test_predictive_enabled_gate(monkeypatch):
    monkeypatch.delenv("REDCLIFF_PREDICTIVE", raising=False)
    assert not predictive_enabled()
    for off in ("0", "", "false", "off"):
        assert not predictive_enabled(env=off)
    assert predictive_enabled(env="1")
    monkeypatch.setenv("REDCLIFF_PREDICTIVE", "1")
    assert predictive_enabled()


def test_empty_store_decisions_bit_identical_to_heuristic():
    """The fallback contract: no usable prior -> exactly the PR-5 ladder,
    across widths, meshes, and compaction scenarios."""
    h = GridSchedulingPolicy()
    for cm in (None, costmodel.CostModel(costmodel._empty_store())):
        p = PredictiveSchedulingPolicy(cost_model=cm, shape_key=SHAPE,
                                       platform="cpu", epochs=50)
        for g, n_dev in ((1, 1), (3, 1), (5, 8), (9, 6), (2, 8)):
            assert p.initial_width(g, n_dev) == h.initial_width(g, n_dev)
        for live, width, n_dev in ((1, 8, 1), (3, 8, 1), (5, 16, 8),
                                   (2, 4, 1)):
            act = np.zeros((width,), bool)
            act[:live] = True
            ids = np.arange(width, dtype=np.int32)
            ph = h.compaction_plan(act, ids, (), n_dev)
            pp = p.compaction_plan(act, ids, (), n_dev,
                                   epochs_remaining=100)
            assert (ph is None) == (pp is None)
            if ph is not None:
                np.testing.assert_array_equal(ph.sel, pp.sel)
                np.testing.assert_array_equal(ph.orig_ids, pp.orig_ids)


def test_compaction_priced_hold_vs_compact():
    cm = _model_with([
        _row(SHAPE, 8, epoch_ms=100.0, compiles=1, compile_ms=5000.0),
        _row(SHAPE, 4, epoch_ms=60.0),
    ])
    pol = PredictiveSchedulingPolicy(cost_model=cm, shape_key=SHAPE,
                                     platform="cpu", epochs=50)
    act = np.zeros((8,), bool)
    act[:3] = True
    ids = np.arange(8, dtype=np.int32)
    # target rung 4 is COLD (no compile evidence): saving (100-60)*rem must
    # beat predicted compile 5000 + gather 250
    plan = pol.compaction_plan(act, ids, (), 1, epochs_remaining=10)
    dec = pol.take_decision()
    assert plan is None and dec["action"] == "hold" and not dec["fallback"]
    assert dec["saving_ms"] == pytest.approx(400.0)
    plan = pol.compaction_plan(act, ids, (), 1, epochs_remaining=500)
    dec = pol.take_decision()
    assert plan is not None and plan.new_width == 4
    assert dec["action"] == "compact" and not dec["fallback"]
    # a WARM target rung only needs to beat the gather cost
    cm2 = _model_with([
        _row(SHAPE, 8, epoch_ms=100.0, compiles=1, compile_ms=5000.0),
        _row(SHAPE, 4, epoch_ms=60.0, compiles=1, compile_ms=5000.0),
    ])
    pol2 = PredictiveSchedulingPolicy(cost_model=cm2, shape_key=SHAPE,
                                      platform="cpu", epochs=50)
    plan = pol2.compaction_plan(act, ids, (), 1, epochs_remaining=10)
    dec = pol2.take_decision()
    assert plan is not None and dec["action"] == "compact"
    assert dec["compile_ms"] == pytest.approx(0.0)
    # unpriceable target width epoch cost -> bit-identical heuristic
    # fallback, recorded as such
    cm3 = _model_with([_row(SHAPE, 8, epoch_ms=100.0)])
    pol3 = PredictiveSchedulingPolicy(cost_model=cm3, shape_key=SHAPE,
                                      platform="cpu", epochs=50)
    act2 = np.zeros((32,), bool)
    act2[:2] = True  # 32 -> 2 is beyond the adjacent-rung clamp
    ids2 = np.arange(32, dtype=np.int32)
    plan = pol3.compaction_plan(act2, ids2, (), 1, epochs_remaining=10)
    dec = pol3.take_decision()
    assert plan is not None and dec["fallback"] and dec["action"] == "compact"


def test_initial_width_warm_rung_reuse():
    # base rung 8 is cold; rung 16 is warm with evidence: short fits widen
    # to reuse the cached program, long fits keep the ladder
    cm = _model_with([
        _row(SHAPE, 8, epoch_ms=100.0),
        _row(SHAPE, 16, epoch_ms=180.0, compiles=1, compile_ms=60000.0),
    ])
    short = PredictiveSchedulingPolicy(cost_model=cm, shape_key=SHAPE,
                                       platform="cpu", epochs=10)
    w = short.initial_width(5, 1)
    dec = short.take_decision()
    # 10 epochs: 10*100 + 60000 cold = 61000 at rung 8 vs 10*180 warm =
    # 1800 at rung 16
    assert w == 16 and dec["action"] == "widen"
    assert dec["heuristic_width"] == 8 and dec["saving_ms"] > 0
    long = PredictiveSchedulingPolicy(cost_model=cm, shape_key=SHAPE,
                                      platform="cpu", epochs=5000)
    assert long.initial_width(5, 1) == 8
    assert long.take_decision()["action"] == "keep"
    # base rung unpriceable -> heuristic fallback recorded
    cm2 = _model_with([_row(SHAPE, 256, epoch_ms=1000.0)])
    pol = PredictiveSchedulingPolicy(cost_model=cm2, shape_key=SHAPE,
                                     platform="cpu", epochs=10)
    assert pol.initial_width(5, 1) == 8
    assert pol.take_decision()["action"] == "fallback"
    # admission ceiling (REDCLIFF_POLICY_MAX_WIDTH): a warm-rung widening
    # must never outgrow the width the fleet's HBM gate/max_bucket priced
    capped = PredictiveSchedulingPolicy(cost_model=cm, shape_key=SHAPE,
                                        platform="cpu", epochs=10,
                                        max_width=8)
    assert capped.initial_width(5, 1) == 8  # 16 would win, but is capped
    assert capped.take_decision()["action"] == "keep"


def test_compile_order_longest_cold_first():
    cm = _model_with([
        _row("a=1", 8, epoch_ms=1.0, compiles=1, compile_ms=1000.0),
        _row("b=1", 8, epoch_ms=1.0, compiles=1, compile_ms=9000.0),
        _row("c=1", 8, epoch_ms=1.0, compiles=1, compile_ms=4000.0),
    ])
    progs = [{"shape_key": "a=1", "g_bucket": 16},   # cold, pred 1000
             {"shape_key": "b=1", "g_bucket": 16},   # cold, pred 9000
             {"shape_key": "b=1", "g_bucket": 8},    # warm (exact evidence)
             {"shape_key": "d=1", "g_bucket": 8},    # unpriceable
             {"shape_key": "c=1", "g_bucket": 16}]   # cold, pred 4000
    order = PredictiveSchedulingPolicy.compile_order(progs, cm)
    # longest predicted cold compile first; warm/unpriceable keep position
    assert order == [1, 4, 0, 2, 3]
    # no cost model: given order untouched
    assert PredictiveSchedulingPolicy.compile_order(progs, None) \
        == [0, 1, 2, 3, 4]
    # pre-priced descriptors (the planner's batch-view cold_compile_ms —
    # one source of truth) are used as-is: 0.0 means warm, None unpriceable
    priced = [{"cold_compile_ms": 100.0}, {"cold_compile_ms": 7000.0},
              {"cold_compile_ms": 0.0}, {"cold_compile_ms": None},
              {"cold_compile_ms": 900.0}]
    assert PredictiveSchedulingPolicy.compile_order(priced) \
        == [1, 4, 0, 2, 3]


def test_worker_cold_compile_order_respects_urgency_classes(tmp_path):
    """The worker's claim reordering moves the longest predicted COLD
    compile first — consuming the batch views' plan-time
    ``cold_compile_ms`` — but only WITHIN the leading urgency class; a
    higher-priority head batch is never displaced."""
    def view(bid, cold_ms, priority=0):
        return {"batch_id": bid, "priority": priority, "deadline_s": None,
                "cold_compile_ms": cold_ms, "requests": [bid]}

    a = view("b-a", 2000.0)
    b = view("b-b", 9000.0)
    hi = view("b-hi", 9000.0, priority=9)
    with MetricLogger(str(tmp_path)) as logger:
        out = fleet_worker._cold_compile_order([a, b], logger, "w")
        assert [x["batch_id"] for x in out] == ["b-b", "b-a"]
        # a higher-priority head forms its own class: untouched
        out = fleet_worker._cold_compile_order([hi, a, b], logger, "w")
        assert [x["batch_id"] for x in out] == ["b-hi", "b-a", "b-b"]
    recs = read_jsonl(str(tmp_path))
    assert not obs_schema.validate_records(recs)
    assert any(r["event"] == "policy" and r["kind"] == "compile_order"
               for r in recs)


# ---------------------------------------------------------------------------
# planner satellite: deterministic unknown-ETA tie-break (two-store test)
# ---------------------------------------------------------------------------
def _plan_req(rid, shape, submitted_at):
    return {"request_id": rid, "tenant": "t", "submitted_at": submitted_at,
            "priority": 0, "deadline_s": None, "shape": shape,
            "points": [{"gen_lr": 1e-3}, {"gen_lr": 2e-3}], "epochs": 50,
            "spec": {"model_config": shape, "epochs": 50}}


def test_planner_unknown_eta_order_is_submission_order_across_stores():
    """Two planners with DIFFERENT cost-model stores (each prices a shape
    the other has never seen) must agree on the relative order of batches
    neither can price: submission order, not content-hash order."""
    sa, sb, sc = ({"num_chans": 4}, {"num_chans": 8}, {"num_chans": 16})
    reqs = [_plan_req("req-zz", sc, 0.0), _plan_req("req-aa", sb, 1.0),
            _plan_req("req-mm", sa, 2.0)]
    ka, kb = obs_schema.shape_key(sa), obs_schema.shape_key(sb)
    store_a = _model_with([_row(ka, 2, epoch_ms=10.0)], platform="any")
    store_b = _model_with([_row(kb, 2, epoch_ms=10.0)], platform="any")

    def order(cm):
        pl = planner.plan(reqs, n_devices=1, cost_model=cm)
        return [b["requests"][0] for b in pl["batches"]]

    o_a = order(store_a)
    o_b = order(store_b)
    # the priced shape drains first; the unknown pair rides submission
    # order in BOTH plans (zz submitted before aa/mm)
    assert o_a == ["req-mm", "req-zz", "req-aa"]
    assert o_b == ["req-aa", "req-zz", "req-mm"]
    # and with no store at all, pure submission order
    assert order(None) == ["req-zz", "req-aa", "req-mm"]
    # batch views carry the tie-break + ordering provenance fields
    b = planner.plan(reqs, n_devices=1, cost_model=store_a)["batches"][0]
    assert b["submitted_at"] == 2.0 and "cold_compile_ms" in b


# ---------------------------------------------------------------------------
# worker preemption: monitor decision + settle accounting
# ---------------------------------------------------------------------------
def _submit_tiny(q, tenant, epochs=2, points=None, **kw):
    spec = json.loads(json.dumps(TINY_SPEC))
    spec["epochs"] = epochs
    return q.submit(tenant, points or list(TINY_POINTS), spec=spec, **kw)


def _prime_store(path, shape, width, epoch_ms, compile_ms=500.0,
                 platform="cpu"):
    costmodel.update_store(str(path), [
        _row(obs_schema.shape_key(shape), width, epoch_ms=epoch_ms,
             epochs=50, compiles=1, compile_ms=compile_ms)], platform)


class _FakeProc:
    def __init__(self):
        self.terminated = False

    def poll(self):
        return None

    def terminate(self):
        self.terminated = True


def test_preempt_monitor_prices_and_signals(tmp_path, monkeypatch):
    root = tmp_path / "fleet"
    store = tmp_path / "store"
    monkeypatch.setenv("REDCLIFF_COST_MODEL_DIR", str(store))
    q = FleetQueue(root)
    low = _submit_tiny(q, "long", epochs=300)
    low_rec = next(r for r in q.requests() if r["request_id"] == low)
    _prime_store(store, low_rec["shape"], 2, epoch_ms=2000.0,
                 platform="any")

    members = [low_rec]
    batch = planner._batch_view(members, 1,
                                cost_model=costmodel.load(str(store)))
    run_dir = q.batch_dir(batch["batch_id"])
    os.makedirs(run_dir, exist_ok=True)
    lease = q.claim(low, "w1", 60.0, batch_id=batch["batch_id"])
    assert lease is not None

    with MetricLogger(str(root)) as logger:
        mon = fleet_worker._PreemptMonitor(q, batch, members, run_dir,
                                           logger, "w1", n_devices=1,
                                           grace_s=2.0, poll_s=0.05)
        proc = _FakeProc()
        mon.on_spawn(proc)
        # no higher-priority deadline tenant queued: hold
        mon._check(time.time())
        assert not mon.requested and not proc.terminated

        urgent = _submit_tiny(q, "urgent", epochs=2, priority=5,
                              deadline_s=30.0)
        # decision gated on the first durable checkpoint
        mon._check(time.time())
        assert not mon.requested
        open(os.path.join(run_dir, "grid_checkpoint.pkl"), "wb").close()
        mon._check(time.time())
        assert mon.requested and proc.terminated
        assert mon.decision["beneficiary"] == urgent
    recs = read_jsonl(str(root))
    assert not obs_schema.validate_records(recs)
    kinds = [(r["event"], r.get("kind"), r.get("action")) for r in recs
             if r["event"] in ("policy", "preempt")]
    assert ("policy", "preempt_price", "preempt") in kinds
    assert ("preempt", "signal", None) in kinds


def test_preempt_monitor_never_fires_without_predictions(tmp_path,
                                                         monkeypatch):
    """No usable cost-model prior -> hold, never a preemption on a guess."""
    root = tmp_path / "fleet"
    monkeypatch.setenv("REDCLIFF_COST_MODEL_DIR",
                       str(tmp_path / "empty_store"))
    q = FleetQueue(root)
    low = _submit_tiny(q, "long", epochs=300)
    low_rec = next(iter(q.requests()))
    batch = planner._batch_view([low_rec], 1)
    run_dir = q.batch_dir(batch["batch_id"])
    os.makedirs(run_dir, exist_ok=True)
    open(os.path.join(run_dir, "grid_checkpoint.pkl"), "wb").close()
    q.claim(low, "w1", 60.0, batch_id=batch["batch_id"])
    _submit_tiny(q, "urgent", epochs=2, priority=5, deadline_s=30.0)
    with MetricLogger(str(root)) as logger:
        mon = fleet_worker._PreemptMonitor(q, batch, [low_rec], run_dir,
                                           logger, "w1")
        proc = _FakeProc()
        mon.on_spawn(proc)
        mon._check(time.time())
    assert not mon.requested and not proc.terminated


class _FakeMonitor:
    """A pre-decided monitor for exercising the settle path without a
    supervised child."""

    def __init__(self, beneficiary):
        self.requested = True
        self.decision = {"beneficiary": beneficiary}

    def on_spawn(self, proc):
        pass

    def should_stop(self):
        return True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass


def test_preemption_settle_is_zero_charge_reclaim(tmp_path, monkeypatch):
    """Settle of a preempted batch: requests charged ZERO failure attempts
    (PR 11 budget untouched), leases released cleanly (re-claimable), the
    exact composition pinned with the beneficiary, preempt events +
    lifecycle transition recorded."""
    from redcliff_tpu.runtime.supervisor import SuperviseOutcome

    root = tmp_path / "fleet"
    q = FleetQueue(root)
    low = _submit_tiny(q, "long", epochs=4)
    urgent = _submit_tiny(q, "urgent", epochs=2, priority=5,
                          deadline_s=60.0)
    by_id = {r["request_id"]: r for r in q.requests()}
    members = [by_id[low]]
    batch = planner._batch_view(members, 1)
    leases = {low: q.claim(low, "w1", 60.0, batch_id=batch["batch_id"])}

    def fake_supervise(cmd, ledger_path=None, policy=None, env=None,
                       on_spawn=None, should_stop=None, **kw):
        return SuperviseOutcome(classification="preempted", returncode=17,
                                attempts=[{"classification": "preempted"}])

    monkeypatch.setattr(fleet_worker, "supervise", fake_supervise)
    with MetricLogger(str(root)) as logger:
        out = fleet_worker.run_one_batch(
            q, batch, leases, members, logger, "w1",
            preempt_monitor=_FakeMonitor(urgent))
    assert out.classification == "preempted"

    # zero-charge: reclaims counted, failure attempts NOT
    att = q.attempt_record(low)
    assert att["attempts"] == 0 and att["reclaims"] == 1
    assert att["last"]["classification"] == "preempted"
    # lease released cleanly — the request is claimable again (by the pin)
    assert q.lease_of(low) is None
    [pin] = q.pinned_batches()
    assert pin["batch_id"] == batch["batch_id"]
    assert pin["requests"] == [low]
    assert pin["after_request"] == urgent

    recs = read_jsonl(str(root))
    assert not obs_schema.validate_records(recs)
    pre = [r for r in recs if r["event"] == "preempt"]
    assert pre and pre[-1]["kind"] == "preempted" \
        and pre[-1]["beneficiary"] == urgent
    hist = [json.loads(l) for l in
            open(os.path.join(root, "history.jsonl"))]
    assert any(h.get("kind") == "preempted" and h.get("requests") == [low]
               for h in hist)

    # the pin defers to the beneficiary: the next claim cycle serves the
    # urgent tenant FIRST, then the preempted composition becomes claimable
    with MetricLogger(str(root)) as logger:
        got = fleet_worker._next_batch(q, "w2", 60.0, 1, None, 256, logger)
        assert got is not None
        b2, leases2, _ = got
        assert b2["requests"] == [urgent]
        q.complete(urgent, result={"ok": True})
        for l in leases2.values():
            l.release()
        got = fleet_worker._next_batch(q, "w2", 60.0, 1, None, 256, logger)
        assert got is not None
        b3, leases3, _ = got
        assert b3["batch_id"] == batch["batch_id"]  # same run dir: resume
        assert b3["requests"] == [low]
        for l in leases3.values():
            l.release()
    assert q.pinned_batches() == []  # the pin was consumed at claim


# ---------------------------------------------------------------------------
# engine wiring: REDCLIFF_PREDICTIVE fit emits policy events, stays
# bit-identical without steering priors
# ---------------------------------------------------------------------------
def test_grid_engine_predictive_policy_events(tmp_path, monkeypatch):
    import jax

    from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig
    from test_parallel_grid import _data, _model

    model = _model()
    ds = _data(model)
    spec = lambda: GridSpec(points=[{"gen_lr": 1e-3}, {"gen_lr": 5e-3},
                                    {"gen_lr": 2e-3}])
    tc = RedcliffTrainConfig(max_iter=3, batch_size=32, check_every=1)

    # heuristic reference leg (gate off)
    monkeypatch.delenv("REDCLIFF_PREDICTIVE", raising=False)
    ref = RedcliffGridRunner(model, tc, spec()).fit(
        jax.random.PRNGKey(0), ds, ds)

    # predictive leg: store primed with epoch evidence at the base rung
    # only — every pricing keeps the heuristic choice, so the decision
    # stream (and the results) must be bit-identical, with the decisions
    # RECORDED as schema-valid `policy` events
    store = tmp_path / "store"
    shape_key = obs_schema.shape_key(obs_schema.shape_desc(model.config))
    costmodel.update_store(str(store), [
        _row(shape_key, 4, epoch_ms=50.0, epochs=10)],
        jax.default_backend())
    monkeypatch.setenv("REDCLIFF_PREDICTIVE", "1")
    monkeypatch.setenv("REDCLIFF_COST_MODEL_DIR", str(store))
    log_dir = str(tmp_path / "run")
    runner = RedcliffGridRunner(model, tc, spec())
    assert isinstance(runner.policy, PredictiveSchedulingPolicy)
    res = runner.fit(jax.random.PRNGKey(0), ds, ds, log_dir=log_dir)
    np.testing.assert_array_equal(res.val_history, ref.val_history)

    recs = read_jsonl(log_dir)
    assert not obs_schema.validate_records(recs)
    pols = [r for r in recs if r["event"] == "policy"]
    assert pols and pols[0]["kind"] == "initial_width"
    assert pols[0]["chosen_width"] == 4


# ---------------------------------------------------------------------------
# obs surfaces: watch headlines + report decision table
# ---------------------------------------------------------------------------
def test_watch_and_report_surface_policy_decisions(tmp_path):
    from redcliff_tpu.obs import report as obs_report
    from redcliff_tpu.obs import watch as obs_watch

    run = str(tmp_path / "run")
    with MetricLogger(run) as log:
        log.log("fit_start", model="probe", grid_size=8, grid_width=8,
                shape={"num_chans": 4})
        log.log("policy", kind="initial_width", epoch=-1, action="keep",
                fallback=False, chosen_width=8, heuristic_width=8,
                total_ms=100.0, heuristic_ms=100.0, saving_ms=0.0)
        for e in (2, 4):
            log.log("epoch", epoch=e, grid_width=8, epoch_ms=100.0)
        log.log("policy", kind="compaction", epoch=4, action="hold",
                fallback=False, from_width=8, to_width=4,
                saving_ms=120.0, compile_ms=5000.0, gather_ms=250.0,
                epochs_remaining=3)
        log.log("preempt", kind="preempted", batch_id="batch-x",
                requests=["req-1"], beneficiary="req-9", worker="w1")
    snap = obs_watch.build_snapshot(run)
    assert not obs_schema.validate_record(snap)
    assert snap["policy"]["kind"] == "compaction"
    assert snap["policy"]["action"] == "hold"
    assert snap["preempt"]["beneficiary"] == "req-9"
    text = obs_watch.render_text(snap)
    assert "policy: hold 8->4" in text
    assert "preempt: preempted batch batch-x -> req-9" in text

    rep = obs_report.build_report(run)
    pd = rep["policy_decisions"]
    assert pd["decisions"] == 2 and pd["fallbacks"] == 0
    assert pd["by_action"] == {"compaction:hold": 1,
                               "initial_width:keep": 1}
    assert pd["preempts"] == 1
    rtext = obs_report.render_text(rep)
    assert "predictive policy decisions" in rtext


# ---------------------------------------------------------------------------
# end-to-end acceptance (slow): preempt mid-fit, beneficiary meets its
# deadline, preempted batch resumes bit-identically
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_preemption_acceptance_end_to_end(tmp_path, monkeypatch):
    from redcliff_tpu.runtime.retry import RetryPolicy
    from redcliff_tpu.runtime.supervisor import SupervisorPolicy

    monkeypatch.setenv("REDCLIFF_PREDICTIVE", "1")
    # fast re-pricing so the preemption lands right after the first
    # durable checkpoint instead of half a second later
    monkeypatch.setenv("REDCLIFF_PREEMPT_POLL_S", "0.05")
    store = tmp_path / "store"
    monkeypatch.setenv("REDCLIFF_COST_MODEL_DIR", str(store))
    for var in ("REDCLIFF_FAULT_INJECT", "REDCLIFF_FAULT_MARKER"):
        monkeypatch.delenv(var, raising=False)
    sup = SupervisorPolicy(
        max_restarts=2,
        backoff=RetryPolicy(max_attempts=10, base_delay_s=0.05,
                            multiplier=1.0, max_delay_s=0.05))

    def _submit_long(q_, tenant):
        # 400 epochs with a LATE scoring cadence (check_every=100): until
        # epoch 100 the running fit emits no cost_model events, so the
        # monitor prices its remaining work from the primed store
        # (~2 s/epoch — a predicted miss for any 45 s deadline) while the
        # real fit stays short enough to keep the test fast
        spec = json.loads(json.dumps(TINY_SPEC))
        spec["epochs"] = 400
        spec["train_config"]["check_every"] = 100
        return q_.submit(tenant, [{"gen_lr": 1e-3}], spec=spec)

    root = tmp_path / "fleet"
    q = FleetQueue(root)
    long_rid = _submit_long(q, "long")
    long_rec = next(iter(q.requests()))
    # prime the store: the long fit predicts ~2s/epoch (so its remaining
    # ETA dwarfs the deadline), the urgent 2-epoch fit predicts seconds
    _prime_store(store, long_rec["shape"], 1, epoch_ms=2000.0,
                 platform="any")

    worker_err = []

    def run_worker():
        try:
            fleet_worker.work(str(root), drain=True, poll_s=0.1,
                              lease_s=60.0, supervisor_policy=sup,
                              max_attempts=2, predictive=True)
        except Exception as e:  # pragma: no cover - surfaced below
            worker_err.append(e)

    t = threading.Thread(target=run_worker)
    t.start()
    try:
        # wait for the long batch to be claimed, then submit the urgent
        # deadline tenant
        deadline = time.time() + 120
        while not q.live_leases():
            assert time.time() < deadline, "long batch never claimed"
            assert t.is_alive(), worker_err
            time.sleep(0.05)
        urgent_rid = _submit_tiny(q, "urgent", epochs=2, priority=5,
                                  deadline_s=45.0)
        urgent_submitted = next(
            r for r in q.requests()
            if r["request_id"] == urgent_rid)["submitted_at"]
        t.join(timeout=420)
        assert not t.is_alive(), "worker never drained"
    finally:
        if t.is_alive():  # pragma: no cover - diagnostics only
            q.cancel(long_rid)
            q.cancel(urgent_rid)
            t.join(timeout=60)
    assert not worker_err, worker_err

    # both settled done; the preemption was recorded
    counts = q.status()["counts"]
    assert counts["done"] == 2 and counts["failed"] == 0 \
        and counts["deadletter"] == 0, counts
    recs = read_jsonl(str(root))
    assert not obs_schema.validate_records(recs)
    pre_kinds = {r.get("kind") for r in recs if r["event"] == "preempt"}
    assert {"signal", "preempted"} <= pre_kinds, pre_kinds

    # the beneficiary met its deadline and finished BEFORE the long fit
    urgent_done = q.result(urgent_rid)
    long_done = q.result(long_rid)
    assert urgent_done["completed_at"] - urgent_submitted <= 45.0
    assert urgent_done["completed_at"] < long_done["completed_at"]

    # zero-charge accounting: the preempted request burned no failure
    # attempts (PR 11 budget intact), only reclaims
    att = q.attempt_record(long_rid)
    assert att["attempts"] == 0 and att["reclaims"] >= 1, att

    # bit-identical resumed streams: an uninterrupted control run of the
    # identical request (content-derived lane seeds) matches field-for-field
    ref_root = tmp_path / "fleet_ref"
    qr = FleetQueue(ref_root)
    ref_rid = _submit_long(qr, "long")
    fleet_worker.work(str(ref_root), drain=True, poll_s=0.1, lease_s=60.0,
                      supervisor_policy=sup, max_attempts=2,
                      predictive=True)
    res = long_done["result"]
    ref = qr.result(ref_rid)["result"]
    for key in ("best_criteria", "best_epoch", "val_history", "active",
                "failures"):
        assert res[key] == ref[key], f"{key} diverged after preemption"
