"""Liveness watchdog suite: heartbeat registry, escalation ladder, deadline
eviction, and the dead-heartbeat tripwire.

The process-level half of the story (a wedged child hard-exits EXIT_HANG and
the supervisor restarts it bit-identically) lives in tests/test_supervisor.py;
this file pins the in-process mechanics on fake clocks and tiny fits.
"""
import os
import threading
import time

import numpy as np
import pytest

from redcliff_tpu.runtime import watchdog as wdg
from redcliff_tpu.runtime.preempt import DeadlineExceeded
from redcliff_tpu.runtime.watchdog import (CORE_COMPONENTS, EXIT_DEADLINE,
                                           EXIT_HANG, EXIT_NUMERICS_ABORT,
                                           EXIT_PREEMPTED, HeartbeatRegistry,
                                           Watchdog, WatchdogPolicy,
                                           classify_exit)


# ---------------------------------------------------------------------------
# heartbeat registry (fake clock: no sleeping)
# ---------------------------------------------------------------------------
class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_registry_overdue_and_retire():
    clock = _Clock()
    reg = HeartbeatRegistry(clock=clock, default_budget_s=5.0)
    reg.stamp("a")          # auto-registers with the default budget
    reg.register("b", budget_s=1.0)
    clock.t = 2.0
    assert [o[0] for o in reg.overdue()] == ["b"]  # a: 2s < 5s budget
    reg.stamp("b")          # b recovers
    assert reg.overdue() == []
    clock.t = 20.0          # both overdue now
    assert {o[0] for o in reg.overdue()} == {"a", "b"}
    reg.retire("a")         # retired components are not liveness-monitored
    assert [o[0] for o in reg.overdue()] == ["b"]
    # ...but their cumulative stamp counts persist (the tripwire reads these)
    assert reg.counts()["a"] == 1


def test_registry_refresh_grants_fresh_budget():
    clock = _Clock()
    reg = HeartbeatRegistry(clock=clock, default_budget_s=1.0)
    reg.stamp("stale")
    clock.t = 100.0
    assert reg.overdue()
    reg.refresh()           # what Watchdog.start() does
    assert reg.overdue() == []
    assert reg.counts()["stale"] == 1  # refresh is not a stamp


def test_registry_budget_overrides():
    reg = HeartbeatRegistry(clock=_Clock(), default_budget_s=100.0)
    reg.budgets["fast"] = 2.0
    reg.stamp("fast")
    reg.stamp("slow")
    ages = reg.ages()
    assert set(ages) == {"fast", "slow"}
    reg.clock.t = 3.0
    assert [o[0] for o in reg.overdue()] == ["fast"]


# ---------------------------------------------------------------------------
# exit-code taxonomy
# ---------------------------------------------------------------------------
def test_classify_exit_taxonomy():
    assert classify_exit(0) == "clean"
    assert classify_exit(EXIT_PREEMPTED) == "preempted"
    assert classify_exit(EXIT_NUMERICS_ABORT) == "numerics_abort"
    assert classify_exit(EXIT_HANG) == "hang"
    assert classify_exit(EXIT_DEADLINE) == "deadline"
    assert classify_exit(-9) == "signal:SIGKILL"
    assert classify_exit(-15) == "signal:SIGTERM"
    assert classify_exit(1) == "crash"
    assert classify_exit(77) == "crash"


def test_policy_from_env(monkeypatch):
    monkeypatch.delenv(wdg.ENV_WATCHDOG, raising=False)
    assert WatchdogPolicy.from_env() is None
    monkeypatch.setenv(wdg.ENV_WATCHDOG, "0")
    assert WatchdogPolicy.from_env() is None
    monkeypatch.setenv(wdg.ENV_WATCHDOG, "1")
    assert WatchdogPolicy.from_env() is not None
    monkeypatch.setenv(wdg.ENV_WATCHDOG,
                       "poll_s=0.5,grace_s=2,budget_s=9,budget.prefetch=3")
    p = WatchdogPolicy.from_env()
    assert p.poll_s == 0.5 and p.grace_s == 2.0
    assert p.default_budget_s == 9.0 and p.budgets == {"prefetch": 3.0}


# ---------------------------------------------------------------------------
# escalation ladder: log -> preempt latch -> hard exit
# ---------------------------------------------------------------------------
class _GuardStub:
    preempted = False
    signum = None


def test_watchdog_escalates_latch_then_exit():
    reg = HeartbeatRegistry(default_budget_s=0.05)
    reg.stamp("wedged")
    guard = _GuardStub()
    exits = []
    events = []

    class _Log:
        active = True

        def log(self, event, **kw):
            events.append((event, kw))

        def close(self):
            pass

    wd = Watchdog(policy=WatchdogPolicy(poll_s=0.02, grace_s=0.1),
                  registry=reg, guard=guard, logger=_Log(),
                  exit_fn=exits.append)
    with wd:
        assert wd._thread.daemon  # pytest teardown can never hang on this
        deadline = time.monotonic() + 10.0
        while not exits and time.monotonic() < deadline:
            time.sleep(0.01)
    # rung 2 fired before rung 3: the guard was latched so an alive loop
    # could still have checkpointed and exited EXIT_PREEMPTED on its own
    assert guard.preempted is True
    assert exits == [EXIT_HANG]
    assert wd.incidents == 1  # one incident, not one per poll
    kinds = [e for e, _ in events]
    assert "hang" in kinds and "hang_exit" in kinds
    hang = dict(events)["hang"]
    assert "wedged" in hang["components"]
    assert hang["components"]["wedged"]["age_s"] > 0.05
    # the forensic stack dump names this (main) thread
    assert "MainThread" in hang["stacks"]


def test_watchdog_compile_scope_excuses_stalled_siblings():
    """While an op-scoped ``compile`` heartbeat is live and within its own
    budget, the watchdog must NOT escalate other overdue components — a cold
    XLA compile legitimately blocks the main thread (epoch_engine cannot
    stamp mid-compile). Once the compile scope retires, the stalled sibling
    escalates normally; a compile overdue past its OWN budget escalates
    too (a wedged XLA compile is a hang)."""
    reg = HeartbeatRegistry(default_budget_s=0.05)
    reg.budgets["compile"] = 10.0  # generous, like the production default
    reg.stamp("epoch_engine")
    reg.stamp("compile")  # cold compile in progress
    exits = []
    wd = Watchdog(policy=WatchdogPolicy(poll_s=0.02, grace_s=0.05),
                  registry=reg, exit_fn=exits.append)
    with wd:
        time.sleep(0.3)  # epoch_engine is long overdue, but excused
        assert wd.incidents == 0 and exits == []
        reg.retire("compile")  # compile finished; the stall is now real
        deadline = time.monotonic() + 10.0
        while not exits and time.monotonic() < deadline:
            time.sleep(0.01)
    assert exits == [EXIT_HANG]

    # a compile past its own budget is NOT excused
    reg2 = HeartbeatRegistry(default_budget_s=0.05)
    reg2.budgets["compile"] = 0.05
    reg2.stamp("compile")
    exits2 = []
    wd2 = Watchdog(policy=WatchdogPolicy(poll_s=0.02, grace_s=0.05),
                   registry=reg2, exit_fn=exits2.append)
    with wd2:
        deadline = time.monotonic() + 10.0
        while not exits2 and time.monotonic() < deadline:
            time.sleep(0.01)
    assert exits2 == [EXIT_HANG]


def test_compile_op_scope_stamps_and_retires():
    """parallel/grid.py wraps first-dispatch-per-program in
    watchdog.op_scope('compile'): stamp on entry, retire on exit, count
    preserved for the dead-heartbeat tripwire."""
    reg = wdg.REGISTRY
    before = reg.counts().get("compile", 0)
    # a sibling that last stamped long ago: its age includes any compile
    # window it was blocked behind
    reg.stamp("stale_sibling")
    with reg._lock:
        reg._beats["stale_sibling"][0] -= 1000.0
    try:
        with wdg.op_scope(wdg.COMPILE_COMPONENT):
            assert "compile" in reg.ages()
        assert "compile" not in reg.ages()
        assert reg.counts()["compile"] == before + 1
        # the closing compile scope refreshed live components, so the
        # sibling gets a fresh budget instead of an instant false hang
        assert reg.ages()["stale_sibling"] < 100.0
    finally:
        reg.retire("stale_sibling")
    # the generous default budget ships in the global registry
    assert reg.budgets.get("compile", 0) >= 600.0


def test_watchdog_recovery_rearms_without_exit():
    reg = HeartbeatRegistry(default_budget_s=0.08)
    reg.stamp("slow")
    exits = []
    wd = Watchdog(policy=WatchdogPolicy(poll_s=0.02, grace_s=5.0),
                  registry=reg, exit_fn=exits.append)
    with wd:
        deadline = time.monotonic() + 10.0
        while wd.incidents == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        reg.stamp("slow")  # the component recovers inside the grace window
        time.sleep(0.1)
    assert wd.incidents >= 1 and exits == []


def test_maybe_start_is_inert_without_env(monkeypatch):
    monkeypatch.delenv(wdg.ENV_WATCHDOG, raising=False)
    with wdg.maybe_start() as live:
        assert live is None


# ---------------------------------------------------------------------------
# the tier-1 tripwire: a short supervised-shaped fit stamps EVERY component
# in the heartbeat map (no silent dead heartbeats), and no liveness/pipeline
# thread outlives the fit
# ---------------------------------------------------------------------------
def test_every_core_component_stamps_in_sharded_fit(tmp_path):
    from redcliff_tpu.runtime.faultinject import tiny_sharded_fit

    wdg.REGISTRY.clear()
    res = tiny_sharded_fit(str(tmp_path), max_iter=1)
    assert np.all(np.isfinite(res.val_history))
    counts = wdg.REGISTRY.counts()
    dead = [c for c in CORE_COMPONENTS if counts.get(c, 0) == 0]
    assert not dead, f"dead heartbeats (registered but never stamped): {dead}"
    # op-scoped heartbeats retired on the way out: nothing left to monitor
    # spuriously, and no daemon worker outlives the fit
    assert wdg.REGISTRY.ages() == {}
    alive = [t.name for t in threading.enumerate()
             if t.name in ("runtime-watchdog", "batch-prefetch",
                           "ckpt-writer") and t.is_alive()]
    assert not alive, f"liveness/pipeline threads leaked: {alive}"


# ---------------------------------------------------------------------------
# wall-clock deadlines (acceptance: deadline eviction + whole-grid drain)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def ref_fit3():
    """The shared no-deadline reference run both deadline tests compare
    against (one compile + fit instead of two)."""
    from redcliff_tpu.runtime.faultinject import tiny_grid_fit

    return tiny_grid_fit(None, max_iter=3)


def test_lane_deadline_evicts_slow_lane_siblings_unchanged(tmp_path,
                                                           ref_fit3):
    """A grid with one lane over its wall-clock budget finishes; the lane
    lands in GridResult.failures with cause 'deadline' plus a valid durable
    checkpoint, and the sibling lane's results are bit-identical to a
    no-deadline run."""
    import jax

    from redcliff_tpu.runtime import checkpoint as rck
    from redcliff_tpu.runtime.faultinject import tiny_grid_fit

    ck = str(tmp_path / "ck")
    # lane 1's budget is sub-epoch: "artificially slow" relative to it
    res = tiny_grid_fit(ck, max_iter=3,
                        fit_deadline_s=[float("inf"), 1e-4])
    assert [f["point"] for f in res.failures] == [1]
    assert res.failures[0]["cause"] == "deadline"
    assert not res.active[1] and res.active[0]
    # the evicted lane's state was checkpointed durably (forced save).
    # Checkpoints store EXECUTION-width state (elastic compaction may have
    # dropped the evicted lane's row by the final save), so decode through
    # the lane->point map / retired store rather than original indices
    ckpt = rck.read_checkpoint(os.path.join(ck, "grid_checkpoint.pkl"))
    ids = np.asarray(ckpt["orig_ids"])
    if 1 in ids:
        row = int(np.flatnonzero(ids == 1)[0])
        failed_at = int(np.asarray(ckpt["failed_epoch"])[row])
    else:
        failed_at = ckpt["retired"][1]["failed_epoch"]
    assert failed_at == res.failures[0]["epoch"]

    ref = ref_fit3
    np.testing.assert_array_equal(res.val_history[:, 0],
                                  ref.val_history[:, 0])
    for a, b in zip(jax.tree.leaves(res.best_params),
                    jax.tree.leaves(ref.best_params)):
        np.testing.assert_array_equal(np.asarray(a)[0], np.asarray(b)[0])
    # frozen after eviction: the evicted lane's val loss stops changing
    e = res.failures[0]["epoch"]
    if e + 2 < res.val_history.shape[0]:
        np.testing.assert_array_equal(res.val_history[e + 1, 1],
                                      res.val_history[e + 2, 1])


def test_grid_deadline_exits_resumable(tmp_path, ref_fit3):
    """The whole-grid deadline drains the epoch, writes a final checkpoint,
    and raises DeadlineExceeded; resuming WITHOUT the deadline completes to
    results bit-identical to an uninterrupted run."""
    from redcliff_tpu.runtime.faultinject import tiny_grid_fit

    ck = str(tmp_path / "ck")
    with pytest.raises(DeadlineExceeded, match="resume"):
        tiny_grid_fit(ck, max_iter=3, grid_deadline_s=1e-4)
    assert os.path.exists(os.path.join(ck, "grid_checkpoint.pkl"))
    resumed = tiny_grid_fit(ck, max_iter=3)
    np.testing.assert_array_equal(resumed.val_history, ref_fit3.val_history)
    np.testing.assert_array_equal(resumed.best_epoch, ref_fit3.best_epoch)


def test_gridspec_deadline_validation():
    from redcliff_tpu.parallel.grid import GridSpec

    with pytest.raises(ValueError, match="positive"):
        GridSpec(points=[{}], grid_deadline_s=0.0)
    with pytest.raises(ValueError, match="entries"):
        GridSpec(points=[{}, {}], fit_deadline_s=[1.0])
    spec = GridSpec(points=[{}, {}], fit_deadline_s=30.0)
    np.testing.assert_array_equal(spec.lane_deadlines(), [30.0, 30.0])
    assert GridSpec(points=[{}]).lane_deadlines() is None
