"""The wavelet-decomposed training flow, end to end at unit scale.

The reference stores each sample's stationary-wavelet decomposition at
curation time (sample entry X_WAV_DECOMP_IND, ref
data/synthetic_datasets.py:28,102-103) and trains on it when signal_format is
"wavelet_decomp"; the models' GC readouts then rank wavelet bands
(ref models/cmlp.py:62-82) and condense band blocks back to channel
granularity (:169-199). This build decomposes at load
(data/shards.py:decompose_windows) instead of tripling stored samples; these
tests pin the layout contract and drive the full driver path on wavelet
inputs.
"""
import json
import os

import numpy as np
import pytest

from redcliff_tpu.data.curation import curate_synthetic_fold
from redcliff_tpu.data.shards import (decompose_windows,
                                      load_normalized_split_datasets)
from redcliff_tpu.utils.time_series import perform_wavelet_decomposition


def test_decompose_windows_matches_reference_layout():
    """Batched decomposition == the reference-shaped per-sample helper:
    channel blocks contiguous, [cA, cD_level, ..., cD_1] order."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(3, 32, 4)).astype(np.float32)
    level = 2
    got = decompose_windows(X, level)
    assert got.shape == (3, 32, 4 * (level + 1))
    for i in range(3):
        want = perform_wavelet_decomposition(X[i][None], "db1", level)[0]
        np.testing.assert_allclose(got[i], want, rtol=1e-5, atol=1e-6)


def test_decompose_windows_rejects_indivisible_length():
    with pytest.raises(AssertionError, match="divisible"):
        decompose_windows(np.zeros((2, 30, 4), np.float32), 2)


def test_loader_decomposes_before_normalization(tmp_path):
    """wavelet_decomp loading: decomposed width, and the z-scoring applies to
    the DECOMPOSED series (each of the C*(level+1) series ~N(0,1)) — the
    reference's curation-then-normalize order."""
    fold_dir, _ = curate_synthetic_fold(
        str(tmp_path), fold_id=0, num_nodes=4, num_lags=2, num_factors=2,
        num_supervised_factors=2, num_edges_per_graph=2,
        num_samples_in_train_set=24, num_samples_in_val_set=8,
        sample_recording_len=32, burnin_period=10,
        label_type_setting="OneHot", noise_type="gaussian", noise_level=1.0,
        folder_name="wavSys")
    level = 2
    train, val = load_normalized_split_datasets(
        fold_dir, signal_format="wavelet_decomp", wavelet_level=level,
        grid_search=False)
    assert train.X.shape[2] == 4 * (level + 1)
    flat = train.X.reshape(-1, train.X.shape[2])
    np.testing.assert_allclose(flat.mean(axis=0), 0.0, atol=1e-4)
    np.testing.assert_allclose(flat.std(axis=0), 1.0, atol=1e-2)


def test_wavelet_redcliff_trains_and_condenses_through_driver(tmp_path):
    """A REDCLIFF-S run with wavelet_level >= 1 through the REAL array-task
    driver: the model trains on (T, C*(level+1)) inputs, and the
    system-level GC readout condenses back to (C, C[, L])."""
    import jax

    from redcliff_tpu.eval.cross_alg import evaluate_algorithm_on_fold
    from redcliff_tpu.train.driver import set_up_and_run_experiments
    from redcliff_tpu.utils.config import load_true_gc_factors

    # level 3 = the reference's 4-wavelets-per-channel configuration (its
    # ranking mask is only defined there, ref cmlp.py:65)
    C, level = 4, 3
    fold_dir, _ = curate_synthetic_fold(
        str(tmp_path / "data"), fold_id=0, num_nodes=C, num_lags=2,
        num_factors=2, num_supervised_factors=2, num_edges_per_graph=2,
        num_samples_in_train_set=24, num_samples_in_val_set=8,
        sample_recording_len=32, burnin_period=10,
        label_type_setting="OneHot", noise_type="gaussian", noise_level=1.0,
        folder_name="wavSys")
    dargs = os.path.join(fold_dir, "data_fold0_cached_args.txt")
    margs = {
        "output_length": "1", "batch_size": "16", "max_iter": "5",
        "lookback": "1", "check_every": "1", "verbose": "0", "num_sims": "1",
        "num_factors": "2", "num_supervised_factors": "2",
        "wavelet_level": str(level), "gen_hidden": "[8]",
        "gen_lr": "0.001", "gen_eps": "0.0001", "gen_weight_decay": "0.0",
        "gen_lag_and_input_len": "2", "FORECAST_COEFF": "1.0",
        "FACTOR_SCORE_COEFF": "1.0", "FACTOR_COS_SIM_COEFF": "0.1",
        "FACTOR_WEIGHT_L1_COEFF": "0.001", "ADJ_L1_REG_COEFF": "0.01",
        "DAGNESS_REG_COEFF": "0.0", "DAGNESS_LAG_COEFF": "0.0",
        "DAGNESS_NODE_COEFF": "0.0",
        "primary_gc_est_mode": "fixed_factor_exclusive",
        "forward_pass_mode": "apply_factor_weights_after_sim_completion",
        "training_mode": "combined",
        "num_pretrain_epochs": "0", "num_acclimation_epochs": "0",
        "factor_score_embedder_type": "Vanilla_Embedder",
        "embed_hidden_sizes": "[8]", "embed_num_hidden_nodes": "8",
        "embed_num_graph_conv_layers": "1", "embed_lr": "0.001",
        "embed_eps": "0.0001", "embed_weight_decay": "0.0",
        "embed_lag": "4", "use_sigmoid_restriction": "0",
        "sigmoid_eccentricity_coeff": "10.0", "prior_factors_path": "None",
        "cost_criteria": "CosineSimilarity", "unsupervised_start_index": "0",
        "max_factor_prior_batches": "2",
        "stopping_criteria_forecast_coeff": "1.",
        "stopping_criteria_factor_coeff": "1.",
        "stopping_criteria_cosSim_coeff": "1.", "deltaConEps": "0.1",
        "in_degree_coeff": "1.", "out_degree_coeff": "1.",
    }
    margs_file = str(tmp_path / "REDCLIFF_S_CMLP_wav_cached_args.txt")
    with open(margs_file, "w") as f:
        json.dump(margs, f)
    save_root = str(tmp_path / "runs")
    os.makedirs(save_root, exist_ok=True)
    set_up_and_run_experiments(
        {"save_root_path": save_root}, [margs_file], [dargs],
        possible_model_types=["REDCLIFF_S_CMLP"],
        possible_data_sets=["data_fold0"], task_id=1)

    run_dir = os.path.join(save_root, os.listdir(save_root)[0])
    true_gcs = load_true_gc_factors(dargs)
    stats = evaluate_algorithm_on_fold(run_dir, "REDCLIFF_S_CMLP", true_gcs)
    off = stats["key_stats_estGC_normOffDiag_vs_trueGC_normOffDiag"]
    assert np.isfinite(off["f1_mean_across_factors"])

    # the trained model's readout condenses band blocks to channel shape,
    # and the wavelet-ranked variant applies the ranking mask finitely
    from redcliff_tpu.eval.model_io import load_model_for_eval
    model, params = load_model_for_eval(run_dir)[:2]
    # gc keeps a trailing lag axis (L=1 under ignore_lag)
    est = np.asarray(model.gc(params, "fixed_factor_exclusive",
                              threshold=False, ignore_lag=True,
                              combine_wavelet_representations=True))[..., 0]
    assert est.shape[-2:] == (C, C)
    ranked = np.asarray(model.gc(params, "fixed_factor_exclusive",
                                 threshold=False, ignore_lag=True,
                                 combine_wavelet_representations=True,
                                 rank_wavelets=True))[..., 0]
    assert ranked.shape[-2:] == (C, C)
    assert np.all(np.isfinite(ranked))
