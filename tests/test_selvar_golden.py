"""SELVAR golden-output pins on a frozen deterministic system.

The native C++ core (tidybench/native/selvar.cpp) and its numpy twin are
A/B'd against each other elsewhere (tests/test_tidybench.py), but a bug
present in BOTH would pass that suite — and the Fortran original
(/root/reference/tidybench/selvarF.f) cannot be compiled here (no gfortran).
These tests therefore pin the algorithm to externally-derived ground:

1. the PRESS statistic (GTPRSS, selvarF.f:139-215) is checked against an
   INDEPENDENT oracle written from the published leave-one-out identity
   sum_t (e_t / (1 - h_t))^2 with the hat matrix H = D (D'D)^+ D' computed
   via pinv/lstsq — a different linear-algebra route than either backend
   (both use Cholesky normal equations);
2. the full hill-climb output (selected lag matrix A and GTCOEF "ABS" score
   matrix B, selvarF.f:80-135,217-290) is frozen as golden constants for a
   fixed 4-node VAR(2) system, audited once by hand: every generating edge
   (0->1 lag 1 coeff 0.8, 1->2 lag 1 coeff 0.5, 2->3 lag 2 coeff -0.7, and
   the AR diagonals) is recovered at its true lag with |coefficient| close
   to the generating value. A regression in either backend — or in both at
   once — now fails against these constants.
"""
import numpy as np
import pytest

from redcliff_tpu.tidybench.selvar import _press_np, gtcoef, slvar


def _frozen_system():
    """Deterministic 4-node VAR(2); see docstring for the edge inventory."""
    rng = np.random.default_rng(1234)
    T, N = 120, 4
    X = np.zeros((T, N))
    eps = rng.normal(0, 0.3, (T, N))
    for t in range(2, T):
        X[t, 0] = 0.5 * X[t - 1, 0] + eps[t, 0]
        X[t, 1] = 0.8 * X[t - 1, 0] + 0.2 * X[t - 1, 1] + eps[t, 1]
        X[t, 2] = 0.5 * X[t - 1, 1] + 0.3 * X[t - 2, 2] + eps[t, 2]
        X[t, 3] = -0.7 * X[t - 2, 2] + 0.2 * X[t - 1, 3] + eps[t, 3]
    return X


# golden outputs of slvar(X, batchsize=-1, maxlags=2, mxitr=-1), recorded
# 2026-07-30 after the manual audit described in the module docstring; both
# backends produced these exact values
GOLDEN_A = np.array([
    [1, 1, 0, 0],
    [1, 1, 1, 0],
    [0, 0, 2, 2],
    [2, 0, 0, 1],
], dtype=np.int32)
GOLDEN_B = np.array([
    [0.5017283672, 0.8028507665, 0.0,          0.0],
    [0.102864931,  0.1573950678, 0.4283354456, 0.0],
    [0.0,          0.0,          0.4045307867, 0.7485374549],
    [0.0890778551, 0.0,          0.0,          0.2342364085],
])
GOLDEN_PRESS = {0: 15.4645662128, 1: 12.4435429276,
                2: 13.2721968402, 3: 11.7076757561}
_FIXED_A = np.zeros((4, 4), dtype=np.int32)
_FIXED_A[0, 1] = 1
_FIXED_A[1, 2] = 1
_FIXED_A[2, 3] = 2


def _press_oracle(X, ml, bs, A, j):
    """Independent leave-one-out PRESS: hat matrix via pinv, fit via lstsq
    (a different route than the Cholesky used by both backends)."""
    T, N = X.shape
    nf = (T - ml) // bs
    src = [i for i in range(N) if A[i, j] > 0]
    lags = [A[i, j] for i in src]
    s = 0.0
    for k in range(nf):
        t0 = ml + k * bs + np.arange(bs)
        D = np.column_stack([np.ones(bs)]
                            + [X[t0 - l, i] for i, l in zip(src, lags)])
        y = X[t0, j]
        H = D @ np.linalg.pinv(D.T @ D) @ D.T
        beta, *_ = np.linalg.lstsq(D, y, rcond=None)
        e = y - D @ beta
        s += float(np.sum((e / (1 - np.diag(H))) ** 2))
    return s


def test_press_matches_independent_oracle():
    X = _frozen_system()
    T = X.shape[0]
    ml, bs = 2, T - 2
    for j in range(4):
        ours = _press_np(X, ml, [bs], _FIXED_A, j)
        oracle = _press_oracle(X, ml, bs, _FIXED_A, j)
        np.testing.assert_allclose(ours, oracle, rtol=1e-10)


def test_press_golden_values():
    X = _frozen_system()
    for j, want in GOLDEN_PRESS.items():
        got = _press_np(X, 2, [X.shape[0] - 2], _FIXED_A, j)
        np.testing.assert_allclose(got, want, rtol=1e-9)


@pytest.mark.parametrize("backend", ["native", "numpy"])
def test_slvar_golden_structure_and_scores(backend):
    X = _frozen_system()
    try:
        B, A, _ = slvar(X, batchsize=-1, maxlags=2, mxitr=-1, backend=backend)
    except RuntimeError as e:
        pytest.skip(str(e))  # native toolchain unavailable
    np.testing.assert_array_equal(np.asarray(A), GOLDEN_A)
    np.testing.assert_allclose(np.asarray(B), GOLDEN_B, rtol=1e-8, atol=1e-10)


def test_golden_structure_contains_every_generating_edge():
    """The pinned A is not arbitrary: each generating edge sits at its true
    lag, and the pinned B carries |coefficient| near the generating value."""
    gen_edges = {(0, 1, 1, 0.8), (1, 2, 1, 0.5), (2, 3, 2, 0.7),
                 (0, 0, 1, 0.5), (1, 1, 1, 0.2), (2, 2, 2, 0.3),
                 (3, 3, 1, 0.2)}
    for i, j, lag, coeff in gen_edges:
        assert GOLDEN_A[i, j] == lag, (i, j)
        assert abs(GOLDEN_B[i, j] - coeff) < 0.15, (i, j)


def test_gtcoef_raw_job_signs():
    """GTCOEF with the raw job reproduces the generating SIGNS (the ABS job
    in the goldens discards them): the 2->3 edge is negative."""
    X = _frozen_system()
    A = np.array(GOLDEN_A)
    B = gtcoef(X, A, maxlags=2, batchsize=-1, job="RAW")
    assert B[2, 3] < -0.5
    assert B[0, 1] > 0.5
