"""Multi-host (DCN) execution path: 2 cooperating processes over the loopback
coordinator train one sharded grid (SURVEY §2.8 "multi-slice sweeps partition
the grid over hosts").

Each worker process owns 2 virtual CPU devices; jax.distributed joins them into
a 4-device global mesh. The grid runner's G axis shards across both processes,
so this exercises the genuine multi-controller code path (non-addressable
shards, allgather result collection) that single-process mesh tests cannot."""
import os
import pickle
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_grid_over_loopback_dcn(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers set their own device count
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(port), str(pid), "2", str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            text=True)
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"worker {pid}: OK" in out

    with open(tmp_path / "result_0.pkl", "rb") as f:
        r0 = pickle.load(f)
    with open(tmp_path / "result_1.pkl", "rb") as f:
        r1 = pickle.load(f)
    # every host sees the same full-grid result after the DCN allgather
    np.testing.assert_allclose(r0["val_history"], r1["val_history"],
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(r0["best_leaf"], r1["best_leaf"],
                               rtol=1e-6, atol=1e-7)
    assert np.all(np.isfinite(r0["best_criteria"]))
