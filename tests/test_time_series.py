"""Signal-processing layer: SWT tight-frame properties, triangular packing,
CSD/directed-spectrum features, Wilson factorization, filters, outliers."""
import numpy as np
import pytest

from redcliff_tpu.utils import time_series as TS
from redcliff_tpu.utils.directed_spectrum import get_directed_spectrum, wilson_factorize


# --------------------------------------------------------------- wavelets

def test_swt_is_tight_frame_and_invertible():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(3, 64))
    for wavelet in ("db1", "db2", "db4"):
        bands = TS.swt(x, wavelet, level=3)
        assert len(bands) == 4
        energy = sum(np.sum(b ** 2) for b in bands)
        np.testing.assert_allclose(energy, np.sum(x ** 2), rtol=1e-10)
        np.testing.assert_allclose(TS.iswt(bands, wavelet), x, atol=1e-10)


def test_swt_haar_additive_reconstruction_exact():
    """For haar the band sum reconstructs the signal exactly — the property the
    reference's 'additive' approximation relies on (ref time_series.py:29-43)."""
    rng = np.random.default_rng(1)
    x = rng.normal(size=(2, 32))
    bands = TS.swt(x, "haar", level=2)
    np.testing.assert_allclose(sum(bands), x, atol=1e-12)


def test_swt_shift_invariance():
    """Stationarity: decomposing a circularly shifted signal equals shifting the
    decomposition (the property DWT lacks and SWT provides)."""
    rng = np.random.default_rng(2)
    x = rng.normal(size=(16,))
    b1 = TS.swt(np.roll(x, 5), "db2", level=2)
    b2 = [np.roll(b, 5) for b in TS.swt(x, "db2", level=2)]
    for a, b in zip(b1, b2):
        np.testing.assert_allclose(a, b, atol=1e-10)


def test_perform_wavelet_decomposition_layout_and_approx():
    rng = np.random.default_rng(3)
    sig = rng.normal(size=(1, 64, 3))
    level = 2
    out = TS.perform_wavelet_decomposition(sig, "haar", level, "swt")
    assert out.shape == (1, 64, 3 * (level + 1))
    # channel c's bands occupy columns [c*(level+1), (c+1)*(level+1))
    approx = TS.construct_signal_approx_from_wavelet_coeffs(out, level)
    np.testing.assert_allclose(approx, sig[0], atol=1e-10)
    with pytest.raises(NotImplementedError):
        TS.perform_wavelet_decomposition(sig, "haar", level, "wavedec")


# ---------------------------------------------------- triangular packing

def test_triangular_squeeze_unsqueeze_roundtrip():
    rng = np.random.default_rng(4)
    n = 5
    sym = rng.normal(size=(2, n, n, 7))
    sym = sym + np.swapaxes(sym, 1, 2)
    packed = TS.squeeze_triangular_array(sym, dims=(1, 2))
    assert packed.shape == (2, n * (n + 1) // 2, 7)
    # condensed layout: entry (i, j<=i) at i(i+1)/2 + j
    np.testing.assert_allclose(packed[:, 0], sym[:, 0, 0])
    np.testing.assert_allclose(packed[:, 2], sym[:, 1, 1])
    np.testing.assert_allclose(packed[:, 4], sym[:, 2, 1])
    restored = TS.unsqueeze_triangular_array(packed, dim=1)
    np.testing.assert_allclose(restored, sym)


# ------------------------------------------------------ spectral features

def _coupled_ar_windows(rng, W=4, T=2048, coupling=0.9):
    """2-channel AR process where channel 0 drives channel 1."""
    X = np.zeros((W, 2, T))
    for w in range(W):
        e = rng.normal(size=(2, T))
        for t in range(2, T):
            X[w, 0, t] = 0.55 * X[w, 0, t - 1] - 0.8 * X[w, 0, t - 2] + e[0, t]
            X[w, 1, t] = coupling * X[w, 0, t - 1] + 0.2 * X[w, 1, t - 1] + e[1, t]
    return X


def test_wilson_factorization_reconstructs_cpsd():
    from scipy.signal import csd

    rng = np.random.default_rng(5)
    X = _coupled_ar_windows(rng, W=2, T=4096)
    params = dict(TS.DEFAULT_CSD_PARAMS, nperseg=256, noverlap=128)
    f, cpsd = csd(X[:, np.newaxis], X[:, :, np.newaxis], fs=1000,
                  return_onesided=False, **params)
    cpsd = np.moveaxis(cpsd, 3, 1)
    H, Sigma = wilson_factorize(cpsd, max_iter=1000, tol=1e-7)
    recon = H @ Sigma[:, None] @ H.conj().swapaxes(-1, -2)
    err = np.abs(recon - cpsd).max() / np.abs(cpsd).max()
    assert err < 1e-4, f"factorization residual {err}"


def test_directed_spectrum_identifies_direction():
    rng = np.random.default_rng(6)
    X = _coupled_ar_windows(rng, W=3, T=4096)
    f, ds = get_directed_spectrum(X, fs=1000,
                                  csd_params={"nperseg": 256, "noverlap": 128})
    assert ds.shape[2:] == (2, 2)
    # channel 0 drives channel 1: ds[0 -> 1] must dominate ds[1 -> 0]
    fwd = ds[:, :, 0, 1].sum()
    bwd = ds[:, :, 1, 0].sum()
    assert fwd > 3.0 * bwd, f"forward {fwd} not >> backward {bwd}"


def test_make_high_level_signal_features_shapes_and_nan():
    rng = np.random.default_rng(7)
    T, C = 1024, 3
    X = rng.normal(size=(T, C))
    res = TS.make_high_level_signal_features(X, fs=1000, max_freq=55.0,
                                             directed_spectrum=True)
    Fn = len(res["freq"])
    assert res["power"].shape == (1, C * (C + 1) // 2, Fn)
    assert res["dir_spec"].shape == (1, C, C, Fn)
    assert np.all(np.isfinite(res["power"]))
    assert np.all(res["freq"] < 55.0)
    # a NaN anywhere marks the whole window's features NaN (ref :177-190)
    Xn = X.copy()
    Xn[5, 0] = np.nan
    res_n = TS.make_high_level_signal_features(Xn, fs=1000,
                                               rng=np.random.default_rng(0))
    assert np.all(np.isnan(res_n["power"]))


# ----------------------------------------------------------------- filters

def test_bandpass_filter_attenuates_out_of_band():
    fs = 1000.0
    t = np.arange(4096) / fs
    in_band = np.sin(2 * np.pi * 40.0 * t)
    out_band = np.sin(2 * np.pi * 5.0 * t)
    y_in = TS.filter_signal(in_band, fs, filter_type="bandpass",
                            apply_notch_filters=False)
    y_out = TS.filter_signal(out_band, fs, filter_type="bandpass",
                             apply_notch_filters=False)
    assert np.std(y_in[500:]) > 10 * np.std(y_out[500:])


def test_notch_filter_removes_line_noise():
    fs = 1000.0
    t = np.arange(8192) / fs
    line = np.sin(2 * np.pi * 60.0 * t)
    y = TS.filter_signal(line, fs, filter_type="lowpass", cutoff=100.0,
                         apply_notch_filters=True)
    assert np.std(y[2000:]) < 0.25 * np.std(line)


def test_filters_preserve_nan_mask():
    fs = 1000.0
    x = np.sin(np.arange(2048) / 10.0)
    x[100:110] = np.nan
    y = TS.filter_signal(x, fs, filter_type="lowpass")
    assert np.all(np.isnan(y[100:110]))
    assert np.isfinite(y[:100]).all()


def test_mark_outliers_flags_artifacts():
    rng = np.random.default_rng(8)
    fs = 1000.0
    t = np.arange(8192) / fs
    clean = np.sin(2 * np.pi * 40.0 * t) + 0.1 * rng.normal(size=t.size)
    sig = clean.copy()
    sig[4000:4010] += 50.0  # artifact inside the passband
    marked = TS.mark_outliers({"roi": sig}, fs)["roi"]
    # the causal Butterworth's group delay shifts the flagged region a few
    # tens of samples past the artifact (same with the reference's lfilter)
    assert np.isnan(marked[4000:4060]).any()
    assert np.isfinite(marked[:3000]).all()


# ------------------------------------------------------------ window draws

def test_draw_timesteps_avoids_nans():
    rng = np.random.default_rng(9)
    nan_locs = [50, 51, 52]
    starts = TS.draw_timesteps_to_sample_from(
        0, 200, window_size=10, num_samples=20, nan_locations=nan_locs, rng=rng)
    for s in starts:
        assert not any(s <= loc <= s + 10 for loc in nan_locs)


def test_draw_timesteps_with_label_reference():
    rng = np.random.default_rng(10)
    labels = np.zeros(300, dtype=int)
    labels[100:200] = 1
    starts = TS.draw_timesteps_to_sample_from_using_label_reference(
        labels, window_size=20, num_samples=10, nan_locations=[], rng=rng)
    for s in starts:
        assert labels[s: s + 20].sum() == 20
