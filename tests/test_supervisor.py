"""Crash-loop supervisor suite + the chaos soak harness.

Process-level acceptance for the supervised run lifecycle: a fit wedged by an
injected hang is detected by the watchdog, hard-exits with the hang taxonomy
code, is restarted by the supervisor, and finishes bit-identical to an
unfaulted run; seeded random fault schedules (kill / nan / hang / torn write /
slow IO / disk error) always terminate with correct final artifacts and a
complete run_ledger.jsonl. All CPU — no accelerator needed.
"""
import json
import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

from redcliff_tpu.runtime.supervisor import (SupervisorPolicy, supervise)
from redcliff_tpu.runtime.faultinject import random_fault_schedule
from redcliff_tpu.runtime.retry import RetryPolicy
from redcliff_tpu.runtime.watchdog import (EXIT_DEADLINE, EXIT_HANG,
                                           EXIT_NUMERICS_ABORT,
                                           EXIT_PREEMPTED)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the watchdog knobs every supervised child in this file runs under: fast
# polling, component budgets small enough to catch an injected hang in
# seconds, the default budget generous enough to cover jit compiles
WATCHDOG_ENV = ("poll_s=0.25,grace_s=1,budget_s=120,"
                "budget.prefetch=3,budget.shard_loader=3,budget.ckpt_writer=6")


def _counter_cmd(tmp_path, fail_times, fail_rc=1):
    """A child that exits ``fail_rc`` its first ``fail_times`` runs, then 0
    (state in a counter file — restarts are separate processes)."""
    counter = str(tmp_path / "count.txt")
    src = (
        "import os,sys\n"
        f"p={counter!r}\n"
        "n=int(open(p).read()) if os.path.exists(p) else 0\n"
        "open(p,'w').write(str(n+1))\n"
        f"sys.exit({fail_rc} if n < {fail_times} else 0)\n"
    )
    return [sys.executable, "-c", src]


def _fast_policy(max_restarts=5):
    return SupervisorPolicy(
        max_restarts=max_restarts,
        backoff=RetryPolicy(max_attempts=10 ** 6, base_delay_s=0.5,
                            multiplier=2.0, max_delay_s=4.0))


def test_supervisor_restarts_crash_then_clean(tmp_path):
    ledger = str(tmp_path / "run_ledger.jsonl")
    slept = []
    out = supervise(_counter_cmd(tmp_path, fail_times=2), ledger_path=ledger,
                    policy=_fast_policy(), sleep=slept.append)
    assert out.classification == "clean" and out.returncode == 0
    assert [a["classification"] for a in out.attempts] == \
        ["crash", "crash", "clean"]
    assert [a["action"] for a in out.attempts] == \
        ["restart", "restart", "stop"]
    # restarts follow the shared retry backoff schedule (slept in short
    # slices so a stop signal interrupts the wait)
    assert sum(slept) == pytest.approx(1.5)
    assert [a["backoff_s"] for a in out.attempts] == [0.5, 1.0, 0.0]
    recs = [json.loads(l) for l in open(ledger)]
    assert [r["event"] for r in recs] == ["attempt"] * 3 + ["final"]
    assert recs[-1]["classification"] == "clean"
    assert recs[0]["rc"] == 1 and recs[0]["backoff_s"] == 0.5


def test_supervisor_gives_up_on_crash_loop(tmp_path):
    out = supervise(_counter_cmd(tmp_path, fail_times=99),
                    ledger_path=str(tmp_path / "l.jsonl"),
                    policy=_fast_policy(max_restarts=2),
                    sleep=lambda s: None)
    assert out.classification == "giving_up"
    assert out.returncode == 1
    assert len(out.attempts) == 3  # 1 run + 2 restarts
    assert out.attempts[-1]["action"] == "give_up"


@pytest.mark.parametrize("code,name", [
    (EXIT_NUMERICS_ABORT, "numerics_abort"), (EXIT_DEADLINE, "deadline")])
def test_supervisor_stops_on_terminal_classes(tmp_path, code, name):
    """Deterministic failures are NOT restarted: a numerics abort replays
    identically, a deadline's budget is already spent."""
    cmd = [sys.executable, "-c", f"import sys; sys.exit({code})"]
    out = supervise(cmd, policy=_fast_policy(), sleep=lambda s: None)
    assert out.classification == name
    assert out.returncode == code
    assert len(out.attempts) == 1 and out.attempts[0]["action"] == "stop"


def test_supervisor_restarts_on_signal_and_preemption(tmp_path):
    # SIGKILL (rc -9) is a restartable class: first run kills itself,
    # the restart exits clean
    counter = str(tmp_path / "sig_count.txt")
    src = (
        "import os, signal\n"
        f"p={counter!r}\n"
        "n = int(open(p).read()) if os.path.exists(p) else 0\n"
        "open(p, 'w').write(str(n + 1))\n"
        "if n < 1:\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n"
    )
    out = supervise([sys.executable, "-c", src], policy=_fast_policy(),
                    sleep=lambda s: None)
    assert out.attempts[0]["classification"] == "signal:SIGKILL"
    assert out.classification == "clean"
    # an externally-stopped supervisor does not restart a preempted child
    cmd2 = [sys.executable, "-c", f"import sys; sys.exit({EXIT_PREEMPTED})"]
    out2 = supervise(cmd2, policy=_fast_policy(), sleep=lambda s: None,
                     should_stop=lambda: True)
    assert out2.classification == "preempted"
    assert len(out2.attempts) == 1


def test_supervisor_stop_during_backoff_prevents_respawn(tmp_path):
    """A SIGTERM landing BETWEEN attempts (no live child to relay it to)
    stops the loop during the backoff wait instead of spawning a fresh
    child that never saw the preemption notice."""
    calls = {"n": 0}

    def stop_after_exit_check():
        # False at the post-exit check, True from the backoff wait onward
        calls["n"] += 1
        return calls["n"] > 1

    out = supervise([sys.executable, "-c", "import sys; sys.exit(1)"],
                    policy=_fast_policy(), sleep=lambda s: None,
                    should_stop=stop_after_exit_check)
    assert out.classification == "stopped"
    assert len(out.attempts) == 1  # the crash was never respawned


# ---------------------------------------------------------------------------
# acceptance: injected hang -> watchdog exit -> supervised restart ->
# bit-identical completion
# ---------------------------------------------------------------------------
def _supervised_child(ck, result=None, max_iter=3, extra=()):
    cmd = [sys.executable, "-m", "redcliff_tpu.runtime.faultinject",
           "--checkpoint-dir", str(ck), "--sharded",
           "--max-iter", str(max_iter)] + list(extra)
    if result:
        cmd += ["--result", str(result)]
    return cmd


def _run_supervised(tmp_path, ck, fault, result=None, max_iter=3,
                    max_restarts=3, timeout=300):
    env = dict(os.environ,
               REDCLIFF_FAULT_MARKER=str(tmp_path / "fault.marker"),
               REDCLIFF_WATCHDOG=WATCHDOG_ENV)
    if fault:
        env["REDCLIFF_FAULT_INJECT"] = fault
    else:
        env.pop("REDCLIFF_FAULT_INJECT", None)
    ledger = str(tmp_path / "run_ledger.jsonl")
    cmd = [sys.executable, "-m", "redcliff_tpu.supervise",
           "--ledger", ledger, "--max-restarts", str(max_restarts),
           "--base-delay-s", "0.05", "--"] \
        + _supervised_child(ck, result=result, max_iter=max_iter)
    proc = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=timeout)
    recs = [json.loads(l) for l in open(ledger)]
    return proc, recs


def test_hang_detected_restarted_bit_identical(tmp_path, monkeypatch):
    """THE liveness acceptance test: a fit wedged by ``hang_in:prefetch`` is
    detected by the watchdog (structured ``hang`` event in metrics.jsonl),
    hard-exits with the hang taxonomy code, is restarted by the supervisor,
    and the completed run's params are bit-identical to an unfaulted run."""
    ck = tmp_path / "ck"
    res_path = tmp_path / "res.pkl"
    proc, recs = _run_supervised(tmp_path, ck, "hang_in:prefetch:600",
                                 result=res_path, max_iter=2)
    assert proc.returncode == 0, proc.stderr[-2000:]
    attempts = [r for r in recs if r["event"] == "attempt"]
    assert attempts[0]["rc"] == EXIT_HANG
    assert attempts[0]["classification"] == "hang"
    assert attempts[0]["action"] == "restart"
    assert attempts[-1]["classification"] == "clean"
    # the hang incident is a structured event with component ages + stacks
    events = [json.loads(l) for l in open(ck / "metrics.jsonl")]
    hangs = [e for e in events if e["event"] == "hang"]
    assert hangs and "prefetch" in hangs[0]["components"]
    assert hangs[0]["components"]["prefetch"]["age_s"] >= 3.0  # its budget
    assert any(e["event"] == "hang_exit" for e in events)

    # unfaulted reference (in-process; the child fit is the same function)
    from redcliff_tpu.runtime.faultinject import (_result_blob,
                                                  tiny_sharded_fit)

    monkeypatch.delenv("REDCLIFF_FAULT_INJECT", raising=False)
    monkeypatch.delenv("REDCLIFF_WATCHDOG", raising=False)
    want = _result_blob(tiny_sharded_fit(str(tmp_path / "ck_ref"),
                                         max_iter=2))
    with open(res_path, "rb") as f:
        got = pickle.load(f)
    np.testing.assert_array_equal(got["val_history"], want["val_history"])
    np.testing.assert_array_equal(got["best_criteria"],
                                  want["best_criteria"])
    for a, b in zip(got["best_params_leaves"], want["best_params_leaves"]):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# seeded chaos soak: every random fault schedule terminates with correct
# final artifacts and a complete ledger. Fast tier-1 subset here; the full
# >=20-schedule soak is slow-marked below.
# ---------------------------------------------------------------------------
def _soak_one(tmp_path, seed):
    from redcliff_tpu.runtime import checkpoint as rck

    schedule = random_fault_schedule(seed)
    ck = tmp_path / f"ck_{seed}"
    proc, recs = _run_supervised(tmp_path / f"s{seed}", ck, schedule,
                                 max_iter=2, timeout=280)
    attempts = [r for r in recs if r["event"] == "attempt"]
    finals = [r for r in recs if r["event"] == "final"]
    # the ledger is complete: every attempt classified, one final verdict
    assert len(finals) == 1, (schedule, recs)
    assert all(r["classification"] for r in attempts)
    assert finals[0]["attempts"] == len(attempts)
    # the supervised run TERMINATED in a taxonomy state; for every schedule
    # in the grammar that is a clean finish within the restart budget
    assert proc.returncode == 0, (schedule, proc.stderr[-2000:])
    # correct final artifacts: the durable checkpoint loads and holds the
    # final epoch, metrics.jsonl is strict JSON
    ckpt, src = rck.load_checkpoint(str(ck / "grid_checkpoint.pkl"))
    assert ckpt is not None and ckpt["epoch"] == 1
    for line in open(ck / "metrics.jsonl"):
        json.loads(line)
    return schedule, len(attempts)


@pytest.mark.parametrize("seed", [0])
def test_chaos_soak_fast_subset(tmp_path, seed):
    """Tier-1 subset of the chaos soak: seed 0 composes a torn-write hang
    inside the checkpoint writer's crash window with a mid-fit SIGKILL —
    the richest schedule in the fuzzer's first draw. The full >=20-seed
    soak below is slow-marked."""
    _soak_one(tmp_path, seed)


@pytest.mark.slow
@pytest.mark.parametrize("seed", list(range(20)))
def test_chaos_soak_full(tmp_path, seed):
    """The full soak: >=20 seeded schedules spanning the whole grammar
    (kill / nan / hang / torn write / slow IO / disk error) all terminate
    within their deadline with valid artifacts and a complete ledger."""
    _soak_one(tmp_path, seed)
