"""Tests for the dynamic-readout evaluation (state tracking + conditional-GC
dynamics) — the scoring layer behind the paper's separating claim."""
import numpy as np
import pytest

from redcliff_tpu.eval.dynamic_readout import (
    lag_normed_graph,
    score_dynamic_graph_tracking,
    score_state_tracking,
    static_graph_history,
    true_dynamic_graph_history,
)


def _two_state_truth(T=60, C=4):
    """Oracle trace switching state 0 -> 1 at T/2, with disjoint graphs."""
    Y = np.zeros((2, T))
    Y[0, : T // 2] = 1.0
    Y[1, T // 2:] = 1.0
    G0 = np.zeros((C, C, 2))
    G0[0, 1, 0] = 1.0
    G0[2, 3, 1] = 0.5
    G1 = np.zeros((C, C, 2))
    G1[1, 0, 0] = 1.0
    G1[3, 2, 1] = 0.5
    return Y, [G0, G1]


def test_lag_normed_graph_reduces_and_scales():
    G = np.zeros((3, 3, 2))
    G[0, 1] = [3.0, 4.0]  # L2 = 5
    G[1, 2] = [0.0, 2.5]
    out = lag_normed_graph(G)
    assert out.shape == (3, 3)
    assert out[0, 1] == pytest.approx(1.0)
    assert out[1, 2] == pytest.approx(0.5)
    # 2-D input passes through (scaled)
    out2 = lag_normed_graph(np.array([[0.0, 2.0], [1.0, 0.0]]))
    assert out2[0, 1] == pytest.approx(1.0)


def test_true_dynamic_graph_history_follows_dominant_state():
    Y, graphs = _two_state_truth()
    hist, dom, valid = true_dynamic_graph_history(Y, graphs, history=10)
    assert hist.shape == (50, 4, 4)
    assert valid.all()
    # first window is scored at step 9 (state 0), last at step 58 (state 1)
    assert dom[0] == 0 and dom[-1] == 1
    assert hist[0][0, 1] == pytest.approx(1.0)
    assert hist[0][1, 0] == pytest.approx(0.0)
    assert hist[-1][1, 0] == pytest.approx(1.0)


def test_pooled_unsupervised_label_row_marks_windows_invalid():
    """A dominant label row with no truth graph (the pooled unsupervised row)
    must invalidate the window, not silently score an arbitrary graph."""
    Y, graphs = _two_state_truth()
    Y = np.vstack([Y, np.zeros((1, Y.shape[1]))])
    Y[2, 20:30] = 5.0  # pooled row dominates steps 20..29
    _, dom, valid = true_dynamic_graph_history(Y, graphs, history=10)
    assert (~valid).sum() == 10
    assert (dom[~valid] == 2).all()
    assert valid[:11].all() and valid[-10:].all()


def test_score_state_tracking_perfect_and_constant():
    Y, _ = _two_state_truth()
    history = 10
    num = Y.shape[1] - history
    # a perfect tracker: weightings equal the oracle slice
    w = Y[:, history - 1: history - 1 + num]
    st = score_state_tracking(w, Y, history)
    assert st["state_score_r"] == pytest.approx(1.0)
    assert st["dominant_state_acc"] == pytest.approx(1.0)
    # a constant readout cannot track a varying oracle
    st0 = score_state_tracking(np.full((2, num), 0.5), Y, history)
    assert st0["state_score_r"] == pytest.approx(0.0)
    # a constant ORACLE defines no tracking target: skipped, not scored
    Yc = np.zeros_like(Y)
    Yc[0] = 1.0  # state 0 dominant for the whole recording
    stc = score_state_tracking(w, Yc, history)
    assert stc["state_score_r"] is None
    assert 0.0 <= stc["dominant_state_acc"] <= 1.0


def test_dynamic_graph_tracking_separates_conditional_from_static():
    Y, graphs = _two_state_truth()
    true_hist, _, _ = true_dynamic_graph_history(Y, graphs, history=10)
    # a conditional estimator that switches with the truth
    cond = score_dynamic_graph_tracking(true_hist + 1e-3, true_hist)
    assert cond["dynamic_optimal_f1"] == pytest.approx(1.0)
    assert cond["edge_tracking_r"] == pytest.approx(1.0)
    # the best any static graph can do: the union of both states' graphs
    union = np.maximum(lag_normed_graph(graphs[0]),
                       lag_normed_graph(graphs[1]))
    static = score_dynamic_graph_tracking(
        static_graph_history(union, true_hist.shape[0]), true_hist)
    assert static["edge_tracking_r"] == pytest.approx(0.0)  # no tracking
    # disjoint graphs: the union predicts both states' edges every window,
    # so per-window precision (and F1) is strictly below the tracker's
    assert static["dynamic_optimal_f1"] < cond["dynamic_optimal_f1"] - 0.2
    assert static["num_tracked_edges"] == cond["num_tracked_edges"] == 4


def test_degenerate_windows_are_skipped_not_crashed():
    C = 3
    true_hist = np.zeros((5, C, C))  # no off-diag truth at any window
    est = np.random.default_rng(0).uniform(size=(5, C, C))
    out = score_dynamic_graph_tracking(est, true_hist)
    assert out["dynamic_optimal_f1"] is None
    assert out["edge_tracking_r"] is None
    assert out["num_tracked_edges"] == 0


def test_label_align_conventions():
    """Window label anchors: "last" = trailing step, "center" = middle step,
    "majority" = per-window vote — on a trace with one hard state switch the
    three conventions disagree exactly around the transition."""
    from redcliff_tpu.eval.dynamic_readout import _dominant_trace

    T, history = 20, 8
    Y = np.zeros((2, T))
    Y[0, :10] = 1.0  # state 0 dominates steps 0..9
    Y[1, 10:] = 1.0  # state 1 dominates steps 10..19
    num = T - history  # 12 scoreable windows

    last = _dominant_trace(Y, history, "last")      # anchor i+7
    center = _dominant_trace(Y, history, "center")  # anchor i+4
    maj = _dominant_trace(Y, history, "majority")
    assert last.shape == center.shape == maj.shape == (num,)
    # window i's last-step anchor flips at i+7 >= 10 -> i >= 3
    np.testing.assert_array_equal(last, (np.arange(num) + 7 >= 10))
    # center anchor flips at i+4 >= 10 -> i >= 6
    np.testing.assert_array_equal(center, (np.arange(num) + 4 >= 10))
    # majority flips when MORE than half the window's steps are state 1
    # (argmax ties go to the lower index): window [i, i+8) has i-2 state-1
    # steps for i >= 2; i-2 > 4 -> flip at i >= 7
    np.testing.assert_array_equal(maj, (np.arange(num) >= 7))


def test_state_tracking_majority_dominance():
    """majority alignment votes dominance over the window, not a single
    anchor step."""
    T, history = 20, 8
    Y = np.zeros((2, T))
    Y[0, :10] = 1.0
    Y[1, 10:] = 1.0
    num = T - history
    # a perfect majority-voting predictor (ties at the lower index)
    w = np.zeros((2, num))
    flip = np.arange(num) >= 7
    w[0, ~flip] = 1.0
    w[1, flip] = 1.0
    st = score_state_tracking(w, Y, history, label_align="majority")
    assert st["dominant_state_acc"] == pytest.approx(1.0)


def test_edge_tracking_bounded_by_weighting_sharpness():
    """The High-band mechanism note (round 5): per-edge tracking r of a
    conditional mixture readout is governed by the SHARPNESS of the factor
    weightings, not by the quality of the per-factor graphs. With perfect
    graphs and sharp (one-hot) weightings the mixture tracks the switching
    truth nearly perfectly; even FAINT weightings track well as long as
    their ordering is right (Pearson is scale-invariant) — but weightings
    that are UNINFORMATIVE about the active state (what the trained embedder
    produces on 4+-factor High-band systems, where dominant-state accuracy
    sits near 1/K chance) collapse r toward 0. Static baselines remain at
    the structural 0."""
    rng = np.random.default_rng(3)
    C, K, T = 6, 4, 80
    # K disjoint-ish random graphs
    graphs = (rng.uniform(size=(K, C, C)) < 0.15).astype(np.float64)
    for g in graphs:
        np.fill_diagonal(g, 0.0)
    # hard-switching truth: state t//20 dominates
    dom = (np.arange(T) // (T // K)).clip(max=K - 1)
    true_hist = graphs[dom]

    def mixture_history(sharpness):
        # weightings: softmax of sharpness * one-hot(dom) + noise
        logits = sharpness * np.eye(K)[dom] + rng.normal(scale=0.1,
                                                         size=(T, K))
        w = np.exp(logits)
        w /= w.sum(axis=1, keepdims=True)
        return np.einsum("tk,kij->tij", w, graphs)

    sharp = score_dynamic_graph_tracking(mixture_history(8.0), true_hist)
    faint = score_dynamic_graph_tracking(mixture_history(0.1), true_hist)
    uninformative = score_dynamic_graph_tracking(mixture_history(0.0),
                                                 true_hist)
    assert sharp["edge_tracking_r"] > 0.8
    # faint-but-correctly-ordered modulation still tracks (scale-invariance)
    assert faint["edge_tracking_r"] > 0.3
    # state-uninformative weightings are what kill tracking
    assert abs(uninformative["edge_tracking_r"]) < 0.2
    assert sharp["edge_tracking_r"] > uninformative["edge_tracking_r"] + 0.6
