"""Tests for factor-score sweeps and cross-experiment summaries."""
import os
import pickle

import jax
import numpy as np
import pytest

from redcliff_tpu.data.datasets import ArrayDataset
from redcliff_tpu.eval.factor_scoring import (
    average_factor_scoring_by_state,
    evaluate_avg_factor_scoring_across_recordings,
    factor_score_sweep,
)
from redcliff_tpu.eval.summaries import (
    extract_metric_table,
    load_full_comparison_summary,
    summarize_off_diag_f1,
    write_cross_experiment_report,
)
from redcliff_tpu.models.redcliff import RedcliffSCMLP, RedcliffSCMLPConfig


def _tiny_model():
    cfg = RedcliffSCMLPConfig(
        num_chans=3, gen_lag=2, gen_hidden=(4,), embed_lag=4,
        embed_hidden_sizes=(4,), num_factors=2, num_supervised_factors=2,
        factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive",
        training_mode="combined", num_pretrain_epochs=0)
    model = RedcliffSCMLP(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def test_factor_score_sweep_shape():
    model, params = _tiny_model()
    rng = np.random.default_rng(0)
    rec = rng.normal(size=(20, 3)).astype(np.float32)
    trace = factor_score_sweep(model, params, rec, 2,
                               num_timesteps_to_score=10,
                               num_timesteps_in_input_history=4)
    assert trace.shape == (2, 10)
    assert np.isfinite(trace).all()
    # batched sweep must equal the per-step loop the reference uses
    per_step = np.stack([
        np.asarray(model._embed(params, rec[None, i - 4 : i, :])[0])[0, :2]
        for i in range(4, 14)], axis=1)
    np.testing.assert_allclose(trace, per_step, rtol=1e-5)


def test_average_factor_scoring_by_state():
    model, params = _tiny_model()
    rng = np.random.default_rng(1)
    X = rng.normal(size=(6, 20, 3)).astype(np.float32)
    # one-hot window labels: first three recordings state 0, rest state 1
    Y = np.zeros((6, 2), dtype=np.float32)
    Y[:3, 0] = 1.0
    Y[3:, 1] = 1.0
    ds = ArrayDataset(X, Y, normalize=False)
    out = average_factor_scoring_by_state(model, params, ds, 2,
                                          num_timesteps_to_score=8,
                                          num_timesteps_in_input_history=4)
    assert out[0]["count"] == 3 and out[1]["count"] == 3
    assert out[0]["weightings"].shape == (2, 8)


def test_evaluate_avg_factor_scoring_plots(tmp_path):
    model, params = _tiny_model()
    rng = np.random.default_rng(2)
    X = rng.normal(size=(4, 16, 3)).astype(np.float32)
    # (S, T) Oracle label traces
    Y = np.zeros((4, 2, 16), dtype=np.float32)
    Y[:2, 0, :] = 1.0
    Y[2:, 1, :] = 1.0
    ds = ArrayDataset(X, Y, normalize=False)
    summary = evaluate_avg_factor_scoring_across_recordings(
        model, params, ds, 2, num_timesteps_to_score=6,
        num_timesteps_in_input_history=4, save_root_path=str(tmp_path),
        labels=["A", "B"])
    assert summary[0]["count"] == 2
    pngs = [x for x in os.listdir(tmp_path) if x.endswith(".png")]
    assert len(pngs) == 2


def _fake_full_summary():
    paradigm = "key_stats_estGC_normOffDiag_vs_trueGC_normOffDiag"
    return {
        "dsetA": {paradigm: {
            "algX": {"f1_mean_across_factors": 0.9,
                     "f1_median_across_factors": 0.92,
                     "f1_mean_std_err_across_factors": 0.01},
            "algY": {"f1_mean_across_factors": 0.7,
                     "f1_median_across_factors": 0.68,
                     "f1_mean_std_err_across_factors": 0.02},
        }},
        "dsetB": {paradigm: {
            "algX": {"f1_mean_across_factors": 0.85,
                     "f1_median_across_factors": 0.86,
                     "f1_mean_std_err_across_factors": 0.015},
        }},
    }


def test_extract_and_summarize(tmp_path):
    summary = _fake_full_summary()
    table = extract_metric_table(summary)
    assert table["dsetA"]["algX"] == pytest.approx(0.9)
    assert table["dsetB"].get("algY") is None
    condensed = summarize_off_diag_f1(summary)
    assert condensed["median"]["dsetA"]["algY"] == pytest.approx(0.68)

    p = tmp_path / "full_comparrisson_summary.pkl"
    with open(p, "wb") as f:
        pickle.dump(summary, f)
    loaded = load_full_comparison_summary(str(tmp_path))
    assert loaded.keys() == summary.keys()


def test_write_cross_experiment_report(tmp_path):
    table = write_cross_experiment_report(_fake_full_summary(),
                                          str(tmp_path))
    files = os.listdir(tmp_path)
    assert any(f.endswith(".csv") for f in files)
    assert any(f.endswith(".png") for f in files)
    csv = [f for f in files if f.endswith(".csv")][0]
    content = open(tmp_path / csv).read()
    assert "algX" in content and "0.9" in content


def test_old_artifact_config_migration(tmp_path):
    """Artifacts pickled before a config field existed must still load and
    run (unpickling bypasses dataclass defaults)."""
    from redcliff_tpu.eval.model_io import load_model_for_eval
    from redcliff_tpu.train.trainer import save_model

    model, params = _tiny_model()
    save_model(str(tmp_path), model, params)
    # simulate an old artifact: strip the newest config field's instance
    # value (fields with plain defaults still resolve via the class
    # attribute; _migrate_config covers default_factory fields too) and
    # rewrite it as a legacy raw pickle — loaders must read both formats
    from redcliff_tpu.runtime.checkpoint import read_checkpoint

    payload = read_checkpoint(str(tmp_path / "final_best_model.bin"))
    object.__delattr__(payload["config"], "factor_network_type")
    assert "factor_network_type" not in payload["config"].__dict__
    with open(tmp_path / "final_best_model.bin", "wb") as f:
        pickle.dump(payload, f)

    loaded_model, loaded_params = load_model_for_eval(str(tmp_path))
    assert loaded_model.config.factor_network_type == "cMLP"
    X = np.random.default_rng(0).normal(size=(2, 10, 3)).astype(np.float32)
    x_sims, _, _, _ = loaded_model.forward(loaded_params, jax.numpy.asarray(X))
    assert np.isfinite(np.asarray(x_sims)).all()
    G = loaded_model.factor_gc(loaded_params)
    assert np.asarray(G).shape[0] == 2
