"""Tests for the DREAM4/D4IC + LFP data layer."""
import os
import pickle

import numpy as np
import pytest
import scipy.io as scio

from redcliff_tpu.data.dream4 import (
    D4IC_SNR_TIERS,
    make_d4ic_fold,
    make_dream4_individual_dataset,
    make_dream4_single_dominant_superpositional_dataset,
    parse_dream4_timeseries,
)
from redcliff_tpu.data.lfp import (
    determine_keys_of_interest,
    extract_epoch_windows,
    load_lfp_data_matrix,
    preprocess_tst_raw_lfps_for_windowed_training,
)
from redcliff_tpu.data.shards import (
    apply_signal_format,
    load_normalized_split_datasets,
    load_shard_samples,
    samples_to_arrays,
    save_cv_split,
)


# ----------------------------------------------------------- DREAM4 TSV

def _write_dream4_tsv(path, num_recordings=5, num_channels=10, rng=None):
    rng = rng or np.random.default_rng(0)
    lines = ["\t".join(['"Time"'] + [f'"G{i+1}"' for i in range(num_channels)])]
    values = []
    for r in range(num_recordings):
        rec = rng.uniform(size=(21, num_channels))
        values.append(rec)
        for t in range(21):
            row = [str(t * 50)] + [f"{v:.6f}" for v in rec[t]]
            lines.append("\t".join(row))
        lines.append("")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return values


def test_parse_dream4_whole_recordings(tmp_path):
    p = str(tmp_path / "insilico_size10_1_timeseries.tsv")
    vals = _write_dream4_tsv(p)
    ts, labels, meta = parse_dream4_timeseries(p, apply_state_perspective=False)
    assert len(ts) == 5 and len(labels) == 5
    assert meta["num_channels"] == 10
    assert meta["num_time_points"] == 21
    np.testing.assert_allclose(ts[0], vals[0], atol=1e-6)
    np.testing.assert_array_equal(labels[0], [1, 0])


def test_parse_dream4_state_perspective(tmp_path):
    p = str(tmp_path / "insilico_size10_1_timeseries.tsv")
    vals = _write_dream4_tsv(p)
    ts, labels, meta = parse_dream4_timeseries(p, apply_state_perspective=True)
    assert len(ts) == 10
    # halves: first 11 steps (perturbed), last 10 (relaxed)
    assert ts[0].shape == (11, 10)
    assert ts[1].shape == (10, 10)
    np.testing.assert_array_equal(labels[0], [1, 0])
    np.testing.assert_array_equal(labels[1], [0, 1])
    np.testing.assert_allclose(np.vstack([ts[0], ts[1]]), vals[0], atol=1e-6)


def test_individual_dataset_folds(tmp_path):
    p = str(tmp_path / "ts.tsv")
    _write_dream4_tsv(p)
    save = str(tmp_path / "size10_out")
    os.makedirs(save)
    make_dream4_individual_dataset(p, save, state_label_setting=False)
    assert sorted(os.listdir(save)) == [f"fold_{i}" for i in range(5)]
    train = load_shard_samples(os.path.join(save, "fold_0", "train"))
    val = load_shard_samples(os.path.join(save, "fold_0", "validation"))
    assert len(train) == 4 and len(val) == 1


def _build_network_dirs(tmp_path, num_nets=3, rng=None):
    rng = rng or np.random.default_rng(1)
    orig = tmp_path / "orig"
    for n in range(num_nets):
        d = orig / f"insilico_size10_{n+1}"
        os.makedirs(d)
        _write_dream4_tsv(str(d / f"insilico_size10_{n+1}_timeseries.tsv"),
                          rng=rng)
    return str(orig)


def test_superpositional_dataset(tmp_path):
    orig = _build_network_dirs(tmp_path)
    save = str(tmp_path / "size10_super")
    os.makedirs(save)
    make_dream4_single_dominant_superpositional_dataset(
        orig, save, state_label_setting=False,
        dominant_net_coeff=5.0, background_net_coeff=0.1)
    nets = sorted(x for x in os.listdir(save) if x != "meta_data.pkl")
    assert len(nets) == 3
    # verify the mix: dominant*5 + 0.1*others, fold-aligned (kfolds are
    # unshuffled so train sample i maps to recording i+1 for fold_0)
    per_net_recs = []
    for net in nets:
        ts, _, _ = parse_dream4_timeseries(
            os.path.join(orig, net, f"{net}_timeseries.tsv"))
        per_net_recs.append(ts)
    t0 = load_shard_samples(os.path.join(save, nets[0], "fold_0", "train"))
    expected = (5.0 * per_net_recs[0][1] + 0.1 * per_net_recs[1][1]
                + 0.1 * per_net_recs[2][1])
    np.testing.assert_allclose(t0[0][0], expected, atol=1e-5)


def test_d4ic_fold_mixing_and_labels(tmp_path):
    orig = _build_network_dirs(tmp_path)
    pre = str(tmp_path / "size10_pre")
    os.makedirs(pre)
    make_dream4_single_dominant_superpositional_dataset(
        orig, pre, state_label_setting=False,
        dominant_net_coeff=1.0, background_net_coeff=0.0)
    d4ic = str(tmp_path / "d4ic_HSNR_fold0")
    combined = make_d4ic_fold(pre, d4ic, fold_id=0, num_factors=3,
                              snr_tier="HSNR")
    train = load_shard_samples(os.path.join(d4ic, "train"))
    # 3 factors x 4 train samples each
    assert len(train) == 12
    x, y = train[0]
    assert x.shape == (21, 10)
    assert y.shape == (3, 1)
    dom, bg = D4IC_SNR_TIERS["HSNR"]
    assert set(np.unique(y)) <= {dom, bg}
    assert np.sum(y == dom) == 1


def test_d4ic_label_coefficients_msnr(tmp_path):
    orig = _build_network_dirs(tmp_path)
    pre = str(tmp_path / "size10_pre")
    os.makedirs(pre)
    make_dream4_single_dominant_superpositional_dataset(
        orig, pre, state_label_setting=False,
        dominant_net_coeff=1.0, background_net_coeff=0.0)
    d4ic = str(tmp_path / "d4ic_MSNR_fold1")
    make_d4ic_fold(pre, d4ic, fold_id=1, num_factors=3, snr_tier="MSNR")
    val = load_shard_samples(os.path.join(d4ic, "validation"))
    _, y = val[0]
    assert sorted(np.unique(y)) == [0.1, 10.0]


# ----------------------------------------------------------- shards

def test_shard_roundtrip_and_arrays(tmp_path):
    rng = np.random.default_rng(3)
    data = [[rng.uniform(size=(8, 4)).astype(np.float32),
             np.array([1.0, 0.0])] for _ in range(6)]
    save_cv_split(data[:5], data[5:], 0, str(tmp_path))
    train = load_shard_samples(str(tmp_path / "fold_0" / "train"))
    X, Y = samples_to_arrays(train)
    assert X.shape == (5, 8, 4)
    assert Y.shape == (5, 2)


def test_load_shard_skips_nan(tmp_path):
    good = [np.ones((4, 2), np.float32), np.array([1.0])]
    bad = [np.full((4, 2), np.nan, np.float32), np.array([0.0])]
    os.makedirs(tmp_path / "split")
    with open(tmp_path / "split" / "subset_0.pkl", "wb") as f:
        pickle.dump([good, bad], f)
    samples = load_shard_samples(str(tmp_path / "split"))
    assert len(samples) == 1


def test_normalized_split_datasets(tmp_path):
    rng = np.random.default_rng(4)
    data = [[rng.uniform(1.0, 3.0, size=(10, 3)).astype(np.float32),
             np.array([1.0, 0.0])] for _ in range(8)]
    save_cv_split(data[:6], data[6:], 0, str(tmp_path))
    train, val = load_normalized_split_datasets(
        str(tmp_path / "fold_0"), grid_search=False)
    assert train.X.shape == (6, 10, 3)
    # z-scored per channel
    assert np.abs(train.X.mean(axis=(0, 1))).max() < 1e-5
    assert val.X.shape == (2, 10, 3)


def test_load_normalized_samples_matches_training_normalization(tmp_path):
    """The eval-side recording loader must hand trained models EXACTLY the
    z-scoring the training loaders applied (regression: raw-amplitude
    recordings fed the dynamic-readout sweep out-of-distribution inputs)."""
    from redcliff_tpu.data.shards import load_normalized_samples

    rng = np.random.default_rng(11)
    data = [[rng.uniform(1.0, 3.0, size=(10, 3)).astype(np.float32),
             np.array([1.0, 0.0])] for _ in range(8)]
    save_cv_split(data[:6], data[6:], 0, str(tmp_path))
    _, val = load_normalized_split_datasets(
        str(tmp_path / "fold_0"), shuffle=False, grid_search=False)
    ds = load_normalized_samples(str(tmp_path / "fold_0" / "validation"))
    np.testing.assert_allclose(np.asarray(ds.X), np.asarray(val.X),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(ds.Y), np.asarray(val.Y))


def test_apply_signal_format_flattened_and_vanilla_dirspec():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(3, 64, 4)).astype(np.float32)
    flat = apply_signal_format(X, "flattened", max_num_features_per_series=32)
    assert flat.shape == (3, 32 * 4)
    ds_params = {"fs": 100, "min_freq": 0.0, "max_freq": 40.0,
                 "directed_spectrum": True,
                 "csd_params": {"detrend": "constant", "window": "hann",
                                "nperseg": 32, "noverlap": 16, "nfft": None}}
    feats = apply_signal_format(X, "directed_spectrum_vanilla",
                                dirspec_params=ds_params)
    assert feats.shape[0] == 3 and feats.ndim == 2
    feats2 = apply_signal_format(X, "directed_spectrum",
                                 dirspec_params=ds_params)
    # dirspec row layout: n*(2n-1)*F features vs vanilla n*n*F
    n = 4
    assert feats2.shape[1] * n == feats.shape[1] * (2 * n - 1)


def test_region_map_averaging(tmp_path):
    rng = np.random.default_rng(6)
    data = [[rng.uniform(size=(10, 4)).astype(np.float32),
             np.array([1.0])] for _ in range(4)]
    save_cv_split(data[:3], data[3:], 0, str(tmp_path))
    region_map = {"A": [0, 1], "B": [2, 3]}
    train, _ = load_normalized_split_datasets(
        str(tmp_path / "fold_0"), grid_search=False, shuffle=False,
        average_region_map=region_map)
    assert train.X.shape == (3, 10, 2)


# ----------------------------------------------------------- LFP curation

def _write_lfp_mat(path, channels, T, rng, spike_at=None):
    data = {}
    for c in channels:
        sig = rng.normal(0.0, 1.0, size=T)
        if spike_at is not None:
            sig[spike_at] = 500.0  # extreme outlier for MAD masking
        data[c] = sig.reshape(1, -1)
    scio.savemat(path, data)


def test_load_lfp_data_matrix_and_keys(tmp_path):
    rng = np.random.default_rng(7)
    chans = ["Amy_01", "Cortex_01", "Hipp_01"]
    _write_lfp_mat(str(tmp_path / "m1_d1_LFP.mat"), chans, 4000, rng)
    _write_lfp_mat(str(tmp_path / "m2_d1_LFP.mat"), chans + ["Extra"], 4000,
                   rng)
    keys = determine_keys_of_interest(["m1_d1_LFP.mat", "m2_d1_LFP.mat"],
                                      str(tmp_path))
    assert keys == sorted(chans)  # Extra not shared
    mat = load_lfp_data_matrix(str(tmp_path), "m1_d1_LFP.mat", keys, 3,
                               sample_freq=1000)
    assert mat.shape == (3, 4000)
    assert np.isfinite(mat[~np.isnan(mat)]).all()


def test_extract_epoch_windows_shapes():
    rng = np.random.default_rng(8)
    raw = rng.normal(size=(3, 5000))
    epochs = [(0, 2000, [1.0, 0.0]), (2000, 5000, [0.0, 1.0])]
    wins = extract_epoch_windows(raw, epochs, window_size=500,
                                 num_samples_per_label_type=3,
                                 downsampling_step_size=10,
                                 rng=np.random.default_rng(0))
    assert len(wins[0]) == 3 and len(wins[1]) == 3
    w, lab = wins[0][0]
    assert w.shape == (50, 3)
    np.testing.assert_array_equal(lab, [1.0, 0.0])


def test_tst_preprocessing_end_to_end(tmp_path):
    rng = np.random.default_rng(9)
    lfp_dir = tmp_path / "lfp"
    lab_dir = tmp_path / "labels"
    out_dir = tmp_path / "out"
    os.makedirs(lfp_dir)
    os.makedirs(lab_dir)
    chans = ["Amy_01", "Cortex_01"]
    T = 700 * 1000  # 700 s at 1 kHz
    # 23-char aligned prefixes for LFP/TIME pairing
    name = "MouseA_2020_01_01_run01"
    _write_lfp_mat(str(lfp_dir / f"{name}_LFP.mat"), chans, T, rng)
    scio.savemat(str(lab_dir / f"{name}_TIME.mat"),
                 {"INT_TIME": np.array([[320, 120, 500, 120]])})
    preprocess_tst_raw_lfps_for_windowed_training(
        str(lfp_dir), str(lab_dir), str(out_dir),
        post_processing_sample_freq=100, num_processed_samples=18,
        sample_temp_window_size=1000, sample_freq=1000,
        rng=np.random.default_rng(0))
    files = sorted(os.listdir(out_dir))
    assert any("homeCage" in f for f in files)
    assert any("openField" in f for f in files)
    assert any("tailSuspension" in f for f in files)
    with open(out_dir / files[0], "rb") as f:
        samples = pickle.load(f)
    x, y = samples[0]
    assert x.shape == (100, 2)  # 1000-step window decimated 10x
    assert y.shape == (3,)


def test_socpref_windows_aligned_with_start_time(tmp_path):
    """Signal is a ramp equal to the absolute timestep index, so window
    contents reveal which absolute steps were sampled; behavior is active
    only in a known absolute interval after StartTime."""
    from redcliff_tpu.data.lfp import (
        preprocess_socpref_raw_lfps_for_windowed_training,
    )

    lfp_dir = tmp_path / "lfp"
    lab_dir = tmp_path / "labels"
    out_dir = tmp_path / "out"
    os.makedirs(lfp_dir)
    os.makedirs(lab_dir)
    T, fs = 20000, 1000
    name = "MouseB_2020_02_02_run01"
    ramp = np.arange(T, dtype=float)
    scio.savemat(str(lfp_dir / f"{name}_LFP.mat"),
                 {"Amy_01": ramp.reshape(1, -1),
                  "Ctx_01": ramp.reshape(1, -1)})
    start_time_sec = 5
    s_class = np.zeros(T)
    s_class[6000:9000] = 1.0  # absolute steps; relative [1000, 4000)
    o_class = np.zeros(T)
    o_class[11000:14000] = 1.0
    scio.savemat(str(lab_dir / f"{name}_Class.mat"),
                 {"StartTime": np.array([[start_time_sec]]),
                  "S_Class": s_class.reshape(1, -1),
                  "O_Class": o_class.reshape(1, -1)})
    preprocess_socpref_raw_lfps_for_windowed_training(
        str(lfp_dir), str(lab_dir), str(out_dir),
        post_processing_sample_freq=100, num_processed_samples=8,
        sample_temp_window_size=500, sample_freq=fs,
        rng=np.random.default_rng(0), recording_duration_sec=15)
    files = sorted(os.listdir(out_dir))
    soc_files = [f for f in files if "social" in f]
    assert soc_files
    with open(out_dir / soc_files[0], "rb") as f:
        samples = pickle.load(f)
    for win, label in samples:
        np.testing.assert_array_equal(label, [1.0, 0.0])
        # window values are ~absolute timestep indices; they must sit inside
        # the labeled interval [6000, 9000) (filter edge effects aside)
        mean_abs_step = float(win[:, 0].mean())
        assert 5800 < mean_abs_step < 9200, mean_abs_step


def test_array_dataset_device_batches_match_host():
    """device=True yields the same batch contents as host numpy batches (same
    shuffle), but as device-resident jax arrays gathered from one HBM copy."""
    import jax

    from redcliff_tpu.data.datasets import ArrayDataset

    rng = np.random.default_rng(0)
    X = rng.normal(size=(13, 6, 3)).astype(np.float32)
    Y = rng.uniform(size=(13, 2)).astype(np.float32)
    ds = ArrayDataset(X, Y)
    host = list(ds.batches(4, rng=np.random.default_rng(7)))
    dev = list(ds.batches(4, rng=np.random.default_rng(7), device=True))
    assert len(host) == len(dev)
    for (hx, hy), (dx, dy) in zip(host, dev):
        assert isinstance(dx, jax.Array)
        np.testing.assert_array_equal(hx, np.asarray(dx))
        np.testing.assert_array_equal(hy, np.asarray(dy))
    # the device cache is built once per sharding and reused across epochs
    assert ds._dev is not None
    first = ds._dev[None]
    list(ds.batches(4, device=True))
    assert ds._dev[None] is first
    # a mesh-sharded caller gets its own correctly-placed copy instead of
    # silently reusing the unsharded cache (regression)
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("grid",))
    repl = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    shard_batches = list(ds.batches(4, rng=np.random.default_rng(7),
                                    device=True, sharding=repl))
    assert ds._dev[repl][0].sharding.is_equivalent_to(repl, X.ndim)
    assert ds._dev[None] is first  # unsharded cache untouched
    for (hx, hy), (sx, sy) in zip(host, shard_batches):
        np.testing.assert_array_equal(hx, np.asarray(sx))
