"""REDCLIFF-S core tests: forward modes, GC readout modes, loss terms, training
phases, freeze choreography, and an end-to-end multi-factor recovery run."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from redcliff_tpu.data import synthetic as S
from redcliff_tpu.data.datasets import train_val_split
from redcliff_tpu.models.redcliff import (GC_EST_MODES, RedcliffSCMLP,
                                          RedcliffSCMLPConfig)
from redcliff_tpu.train.redcliff_trainer import (RedcliffTrainConfig,
                                                 RedcliffTrainer)


def _cfg(**kw):
    base = dict(
        num_chans=4, gen_lag=2, gen_hidden=(8,), embed_lag=4,
        embed_hidden_sizes=(12,), num_factors=3, num_supervised_factors=2,
        forecast_coeff=1.0, factor_score_coeff=1.0, factor_cos_sim_coeff=0.1,
        factor_weight_l1_coeff=0.01, adj_l1_reg_coeff=0.01,
        use_sigmoid_restriction=True,
        primary_gc_est_mode="conditional_factor_fixed_embedder",
        forward_pass_mode="apply_factor_weights_at_each_sim_step",
        num_sims=2, training_mode="combined",
        factor_score_embedder_type="cEmbedder",
    )
    base.update(kw)
    return RedcliffSCMLPConfig(**base)


@pytest.fixture(scope="module")
def model_and_params():
    cfg = _cfg()
    model = RedcliffSCMLP(cfg)
    params = model.init(jax.random.PRNGKey(0))
    return model, params


def test_forward_stepwise_shapes(model_and_params):
    model, params = model_and_params
    cfg = model.config
    B = 3
    X = jax.random.normal(jax.random.PRNGKey(1), (B, cfg.max_lag, cfg.num_chans))
    x_sims, factor_preds, fw, labels = model.forward(params, X)
    assert x_sims.shape == (B, cfg.num_sims, cfg.num_chans)
    assert len(factor_preds) == cfg.num_sims
    assert factor_preds[0].shape == (cfg.num_factors, B, 1, cfg.num_chans)
    assert fw[0].shape == (B, cfg.num_factors)
    assert len(labels) == cfg.num_sims


def test_forward_post_weighted_shapes():
    cfg = _cfg(forward_pass_mode="apply_factor_weights_after_sim_completion")
    model = RedcliffSCMLP(cfg)
    params = model.init(jax.random.PRNGKey(0))
    B = 3
    X = jax.random.normal(jax.random.PRNGKey(1), (B, cfg.max_lag, cfg.num_chans))
    x_sims, _, fw, labels = model.forward(params, X)
    assert x_sims.shape == (B, cfg.num_sims, cfg.num_chans)
    assert len(fw) == 1 and fw[0].shape == (B, cfg.num_factors)
    # post-weighted mode replicates the single logit set across sims
    assert len(labels) == cfg.num_sims


def test_forward_mixture_is_weighted_sum(model_and_params):
    """combined prediction must equal sum_k w_k * factor_k prediction."""
    model, params = model_and_params
    cfg = model.config
    X = jax.random.normal(jax.random.PRNGKey(2), (2, cfg.max_lag, cfg.num_chans))
    x_sims, factor_preds, fw, _ = model.forward(params, X)
    manual = np.einsum("bk,kbtc->btc", np.asarray(fw[0]), np.asarray(factor_preds[0]))
    np.testing.assert_allclose(np.asarray(x_sims[:, :1, :]), manual, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("mode", [m for m in GC_EST_MODES])
def test_all_gc_modes_shapes(mode):
    cfg = _cfg()
    model = RedcliffSCMLP(cfg)
    params = model.init(jax.random.PRNGKey(0))
    B, K, C = 2, cfg.num_factors, cfg.num_chans
    X = jax.random.normal(jax.random.PRNGKey(3), (B, cfg.max_lag, cfg.num_chans))
    G = model.gc(params, mode, X=X, ignore_lag=False)
    G = np.asarray(G)
    if mode == "fixed_factor_exclusive":
        assert G.shape == (1, K, C, C, cfg.gen_lag)
    elif mode == "raw_embedder":
        assert G.shape[:2] == (1, 1) and G.shape[2] == K
    elif mode == "fixed_embedder_exclusive":
        assert G.shape[:4] == (1, 1, C, C)
    elif "conditional" in mode:
        assert G.shape[0] == B
    else:
        assert G.shape[0] == 1
    assert np.all(np.isfinite(G))


def test_gc_lag_clipping_in_combo_modes():
    cfg = _cfg()
    model = RedcliffSCMLP(cfg)
    params = model.init(jax.random.PRNGKey(0))
    X = jax.random.normal(jax.random.PRNGKey(3), (2, cfg.max_lag, cfg.num_chans))
    G = model.gc(params, "conditional_factor_fixed_embedder", X=X, ignore_lag=False)
    # lag axis clipped to min(gen_lag, embed_lag) (ref redcliff_s_cmlp.py:558,575)
    assert G.shape[-1] == min(cfg.gen_lag, cfg.embed_lag)


def test_loss_parts_and_phases(model_and_params):
    model, params = model_and_params
    cfg = model.config
    B, T = 4, cfg.max_lag + cfg.num_sims
    X = jax.random.normal(jax.random.PRNGKey(4), (B, T, cfg.num_chans))
    Y = jax.random.uniform(jax.random.PRNGKey(5), (B, cfg.num_supervised_factors + 1, 1))
    combo, parts = model.loss_for_phase(params, X, Y, "combined")
    assert jnp.isfinite(combo)
    for key in ("forecasting_loss", "factor_loss", "factor_cos_sim_penalty",
                "fw_l1_penalty", "adj_l1_penalty"):
        assert jnp.isfinite(parts[key]), key
    # embedder-pretrain loss excludes forecasting
    combo_e, parts_e = model.loss_for_phase(params, X, Y, "embedder_pretrain")
    np.testing.assert_allclose(
        np.asarray(combo_e),
        np.asarray(parts_e["factor_loss"] + parts_e["fw_l1_penalty"]
                   + parts_e["fw_smoothing_penalty"]), rtol=1e-6)
    # factor-pretrain loss excludes the supervised factor term
    combo_f, parts_f = model.loss_for_phase(params, X, Y, "factor_pretrain")
    np.testing.assert_allclose(
        np.asarray(combo_f),
        np.asarray(parts_f["forecasting_loss"] + parts_f["fw_l1_penalty"]
                   + parts_f["fw_smoothing_penalty"] + parts_f["adj_l1_penalty"]
                   + parts_f["factor_cos_sim_penalty"]), rtol=1e-6)


def test_label_shape_dispatch(model_and_params):
    model, params = model_and_params
    cfg = model.config
    B, T = 4, cfg.max_lag + cfg.num_sims
    X = jax.random.normal(jax.random.PRNGKey(6), (B, T, cfg.num_chans))
    S_ = cfg.num_supervised_factors
    # (B, S, T_long) oracle traces
    Y3 = jax.random.uniform(jax.random.PRNGKey(7), (B, S_ + 1, cfg.max_lag + 5))
    c3, _ = model.loss_for_phase(params, X, Y3, "combined")
    # (B, S, 1) static labels
    Y1 = jax.random.uniform(jax.random.PRNGKey(8), (B, S_ + 1, 1))
    c1, _ = model.loss_for_phase(params, X, Y1, "combined")
    # (B, S) DREAM4-orig labels
    Y2 = jax.random.uniform(jax.random.PRNGKey(9), (B, S_ + 1))
    c2, _ = model.loss_for_phase(params, X, Y2, "combined")
    assert all(jnp.isfinite(v) for v in (c3, c1, c2))


def test_smoothing_penalty_active_only_in_smooth_variant():
    X = jax.random.normal(jax.random.PRNGKey(10), (4, 8, 4))
    Y = jax.random.uniform(jax.random.PRNGKey(11), (4, 3, 1))
    base = RedcliffSCMLP(_cfg(num_sims=3))
    p = base.init(jax.random.PRNGKey(0))
    _, parts = base.loss_for_phase(p, X, Y, "combined")
    assert float(parts["fw_smoothing_penalty"]) == 0.0
    smooth = RedcliffSCMLP(_cfg(num_sims=3, factor_weight_smoothing_penalty_coeff=0.5))
    _, parts_s = smooth.loss_for_phase(p, X, Y, "combined")
    assert float(parts_s["fw_smoothing_penalty"]) >= 0.0


def test_phase_schedule():
    cfg = _cfg(training_mode="pretrain_embedder_and_pretrain_factor_then_combined",
               num_pretrain_epochs=2)
    trainer = RedcliffTrainer(RedcliffSCMLP(cfg), RedcliffTrainConfig(max_iter=5))
    assert trainer.phase_for_epoch(0) == ("embedder_pretrain", "factor_pretrain")
    assert trainer.phase_for_epoch(1) == ("embedder_pretrain", "factor_pretrain")
    assert trainer.phase_for_epoch(2) == ("combined",)
    cfg2 = _cfg(training_mode="pretrain_embedder_then_acclimate_factors_then_combined",
                num_pretrain_epochs=1, num_acclimation_epochs=2)
    t2 = RedcliffTrainer(RedcliffSCMLP(cfg2), RedcliffTrainConfig(max_iter=5))
    assert t2.phase_for_epoch(0) == ("embedder_pretrain",)
    assert t2.phase_for_epoch(1) == ("factor_pretrain",)
    assert t2.phase_for_epoch(2) == ("factor_pretrain",)
    assert t2.phase_for_epoch(3) == ("combined",)
    cfg3 = _cfg(training_mode="pretrain_embedder_then_post_train_factor",
                num_pretrain_epochs=1)
    t3 = RedcliffTrainer(RedcliffSCMLP(cfg3), RedcliffTrainConfig(max_iter=5))
    assert t3.phase_for_epoch(1) == ("post_train",)


def test_permute_factors_roundtrip(model_and_params):
    model, params = model_and_params
    g_before = np.asarray(model.factor_gc(params))
    permuted = model.permute_factors(params, [2, 0, 1])
    g_after = np.asarray(model.factor_gc(permuted))
    np.testing.assert_allclose(g_after[0], g_before[2])
    np.testing.assert_allclose(g_after[1], g_before[0])


def test_freeze_swap_accept_and_revert():
    cfg = _cfg(training_mode="pretrain_embedder_then_post_train_factor_withL1FreezeByEpoch",
               num_pretrain_epochs=1)
    model = RedcliffSCMLP(cfg)
    trainer = RedcliffTrainer(model, RedcliffTrainConfig())
    accepted = model.init(jax.random.PRNGKey(0))
    # the decision compares the MATRIX 1-norm (max column sum, ref
    # np.linalg.norm(x, ord=1)) of max-normalized GC estimates: concentrate
    # factor 0 on a single edge (normalized matrix norm collapses to 1, the
    # minimum -> accept) and flatten factor 1 to all-equal weights (every
    # normalized entry 1, matrix norm = C, the maximum -> revert)
    candidate = jax.tree.map(lambda x: x, accepted)
    w = candidate["factors"][0]["w"]  # (K, C_out, H, C_in, L)
    keep = w[0, 0, :, 0, :]
    w = w.at[0].set(0.0)
    w = w.at[0, 0, :, 0, :].set(jnp.where(jnp.abs(keep) > 0, keep, 1.0))
    w = w.at[1].set(jnp.ones_like(w[1]))
    candidate["factors"][0] = dict(candidate["factors"][0], w=w)
    new_cand, new_acc = trainer._apply_freeze(candidate, accepted)
    # factor 0: candidate kept (accepted updated to candidate's shrunk weights)
    np.testing.assert_allclose(np.asarray(new_acc["factors"][0]["w"][0]),
                               np.asarray(candidate["factors"][0]["w"][0]))
    # factor 1: candidate reverted to accepted
    np.testing.assert_allclose(np.asarray(new_cand["factors"][0]["w"][1]),
                               np.asarray(accepted["factors"][0]["w"][1]))


@pytest.fixture(scope="module")
def two_state_data():
    D = 4
    p = S.reference_curation_params(D)
    graphs, acts, _ = S.generate_lagged_adjacency_graphs_for_factor_model(
        num_nodes=D, num_lags=2, num_factors=2, make_factors_orthogonal=True,
        make_factors_singular_components=False, rand_seed=21,
        off_diag_edge_strengths=p["off_diag_edge_strengths"],
        diag_receiving_node_forgetting_coeffs=p["diag_receiving_node_forgetting_coeffs"],
        diag_sending_node_forgetting_coeffs=p["diag_sending_node_forgetting_coeffs"],
        num_edges_per_graph=4,
    )
    X, Y = S.generate_synthetic_dataset(
        jax.random.PRNGKey(42), graphs, acts, p["base_freqs"], p["noise_mu"],
        p["noise_var"], p["innovation_amp"], num_samples=192,
        recording_length=30, burnin_period=10, num_labeled_sys_states=2,
        label_type="Oracle", noise_type="gaussian", noise_amp=0.0,
    )
    return graphs, X, Y


def test_redcliff_end_to_end_training(two_state_data, tmp_path):
    graphs, X, Y = two_state_data
    D = X.shape[2]
    train_ds, val_ds = train_val_split(X, Y, val_fraction=0.2,
                                       rng=np.random.default_rng(0))
    cfg = RedcliffSCMLPConfig(
        num_chans=D, gen_lag=2, gen_hidden=(12,), embed_lag=4,
        embed_hidden_sizes=(16,), num_factors=2, num_supervised_factors=2,
        forecast_coeff=1.0, factor_score_coeff=2.0, factor_cos_sim_coeff=0.05,
        factor_weight_l1_coeff=0.01, adj_l1_reg_coeff=0.001,
        use_sigmoid_restriction=True, factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive",
        forward_pass_mode="apply_factor_weights_at_each_sim_step", num_sims=1,
        training_mode="pretrain_embedder_and_pretrain_factor_then_combined",
        num_pretrain_epochs=2,
    )
    model = RedcliffSCMLP(cfg)
    params = model.init(jax.random.PRNGKey(0))
    trainer = RedcliffTrainer(model, RedcliffTrainConfig(
        embed_lr=2e-3, gen_lr=5e-3, max_iter=15, batch_size=64, check_every=5,
        seed=0))
    res = trainer.fit(params, train_ds, val_ds, true_GC=graphs,
                      save_dir=str(tmp_path / "redcliff_run"))
    fl = res.histories["avg_forecasting_loss"]
    assert fl[-1] < fl[0] * 1.05
    assert np.isfinite(res.final_val_loss)
    assert len(res.tracker.f1score_histories[0.0][0]) == len(fl)
    # confusion-matrix histories populated in combined epochs
    assert len(res.histories["factor_score_val_acc_history"]) > 0
    assert (tmp_path / "redcliff_run" / "final_best_model.bin").exists()


def test_redcliff_clstm_factor_variant():
    """REDCLIFF_S_CLSTM: cLSTM factor networks inside the shared core (the
    variant the reference declares but never shipped)."""
    import numpy as np
    from redcliff_tpu.models.redcliff import RedcliffSCMLP, RedcliffSCMLPConfig

    cfg = RedcliffSCMLPConfig(
        num_chans=4, gen_lag=3, gen_hidden=(8,), embed_lag=5,
        embed_hidden_sizes=(6,), num_factors=2, num_supervised_factors=2,
        factor_network_type="cLSTM",
        factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive",
        training_mode="combined", num_pretrain_epochs=0, num_sims=2)
    model = RedcliffSCMLP(cfg)
    params = model.init(jax.random.PRNGKey(0))
    X = jax.random.normal(jax.random.PRNGKey(1), (3, 10, 4))
    x_sims, factor_preds, fw_preds, label_preds = model.forward(params, X)
    assert x_sims.shape == (3, 2, 4)
    # GC: per-factor (C, C) from LSTM input weights, no lag axis
    G = model.factor_gc(params)
    assert G.shape == (2, 4, 4)
    assert np.isfinite(np.asarray(G)).all()
    G_lag = model.factor_gc(params, ignore_lag=False)
    assert G_lag.shape == (2, 4, 4, 1)
    # loss computes through both phases
    loss, terms = model.loss_for_phase(params, X,
                                       jnp.ones((3, 2, 10)), "combined")
    assert np.isfinite(float(loss))


def test_redcliff_clstm_post_weighted_mode():
    from redcliff_tpu.models.redcliff import RedcliffSCMLP, RedcliffSCMLPConfig

    cfg = RedcliffSCMLPConfig(
        num_chans=3, gen_lag=4, gen_hidden=(6,), embed_lag=4,
        embed_hidden_sizes=(6,), num_factors=2, num_supervised_factors=2,
        factor_network_type="cLSTM",
        factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive",
        forward_pass_mode="apply_factor_weights_after_sim_completion",
        training_mode="combined", num_pretrain_epochs=0, num_sims=3)
    model = RedcliffSCMLP(cfg)
    params = model.init(jax.random.PRNGKey(0))
    X = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 3))
    x_sims, _, _, _ = model.forward(params, X)
    assert x_sims.shape == (2, 3, 3)


def test_redcliff_clstm_factory_dispatch():
    from redcliff_tpu.train.orchestration import create_model_instance

    args = {
        "model_type": "REDCLIFF_S_CLSTM", "num_channels": 4,
        "context": 3, "gen_hidden": 8, "num_in_timesteps": 5,
        "embed_hidden_sizes": [6], "num_factors": 2,
        "num_supervised_factors": 2,
        "coeff_dict": {"FORECAST_COEFF": 1.0, "FACTOR_SCORE_COEFF": 1.0,
                       "FACTOR_COS_SIM_COEFF": 0.0,
                       "FACTOR_WEIGHT_L1_COEFF": 0.0,
                       "ADJ_L1_REG_COEFF": 0.0},
        "use_sigmoid_restriction": True,
        "factor_score_embedder_type": "Vanilla_Embedder",
        "factor_score_embedder_args": [],
        "primary_gc_est_mode": "fixed_factor_exclusive",
        "forward_pass_mode": "apply_factor_weights_at_each_sim_step",
        "num_sims": 1, "wavelet_level": None, "training_mode": "combined",
        "num_pretrain_epochs": 0,
    }
    model = create_model_instance(args)
    assert model.config.factor_network_type == "cLSTM"
    assert model.config.gen_lag == 3
    assert model.config.gen_hidden == (8,)


def test_gc_tracker_zero_estimate_cosine_warning_free():
    """An all-zero float32 GC estimate must not trip a divide-by-zero in the
    cosine tracking (regression: the reference's 1e-300 max floor underflows
    to zero in float32, ref model_utils.py:191-209)."""
    import warnings

    from redcliff_tpu.train.tracking import GCProgressTracker

    t = GCProgressTracker(2, 4, num_factors=2)
    rng = np.random.default_rng(0)
    truth = (rng.uniform(size=(4, 4)) > 0.5).astype(np.float64)
    zero = np.zeros((4, 4), dtype=np.float32)
    est = rng.uniform(size=(4, 4)).astype(np.float32)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        t.update(true_GC=[truth, truth], est_by_sample=[[zero, est]],
                 est_by_sample_lagsummed=[[zero, est]])
    assert t.gc_factor_cosine_sim_histories["0and1"] == [0.0]


def test_gc_tracker_all_negative_estimate_cosine_finite():
    """An all-non-positive estimate (possible for conditional GC modes with
    sign-free embedder weightings) must yield a FINITE cosine: the
    reference's max(max, 1e-300) floor scales such estimates by ~1e300 and
    the dot product overflows to +-inf, which then poisons the stopping
    criterion and auto-wins model selection (regression from the grid-science
    parity experiment)."""
    import warnings

    from redcliff_tpu.train.tracking import GCProgressTracker

    t = GCProgressTracker(2, 4, num_factors=2)
    rng = np.random.default_rng(1)
    truth = (rng.uniform(size=(4, 4)) > 0.5).astype(np.float64)
    neg = -rng.uniform(1.0, 2.0, size=(4, 4)).astype(np.float32)
    est = rng.uniform(size=(4, 4)).astype(np.float32)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        t.update(true_GC=[truth, truth], est_by_sample=[[neg, est]],
                 est_by_sample_lagsummed=[[neg, est]])
    val = t.gc_factor_cosine_sim_histories["0and1"][0]
    assert np.isfinite(val)
    assert -1.0 <= val <= 1.0
