"""Mixed-precision production path + autotuned Pallas kernels (ISSUE 14).

Covers the four contracts of the precision/kernel layer:

* kernel parity — the fused factor-mix kernel is BITWISE equal to the jnp
  reference in f32 interpret mode (including through the custom VJP), and
  the GL-prox kernel matches the jnp prox on off-tile row counts;
* precision_mode="f32" decision streams are bit-identical to a config that
  never heard of the knob (the pre-PR behavior);
* precision_mode="mixed" + a numerics-sentinel storm auto-demotes to f32
  (schema-registered `precision` event), the demotion persists in the
  checkpoint, and an f32 resume is bit-identical to an always-f32 fit from
  the demotion point;
* the autotune store searches once, persists beside the compile cache, and
  a second resolve performs zero search steps (corrupt stores degrade to
  defaults).
"""
import os
import shutil

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from redcliff_tpu.data.datasets import ArrayDataset
from redcliff_tpu.models.redcliff import RedcliffSCMLP, RedcliffSCMLPConfig
from redcliff_tpu.obs import read_jsonl, schema
from redcliff_tpu.obs import costmodel
from redcliff_tpu.ops import autotune
from redcliff_tpu.ops.factor_mix import (factor_mix_pallas,
                                         factor_mix_reference)
from redcliff_tpu.ops.pallas_prox import gl_prox_pallas
from redcliff_tpu.ops.prox import prox_update
from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
from redcliff_tpu.train.redcliff_trainer import (RedcliffTrainConfig,
                                                 RedcliffTrainer)
from redcliff_tpu.utils.precision import (precision_label,
                                          resolve_matmul_precision)


def _model():
    return RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=4, gen_lag=2, gen_hidden=(8,), embed_lag=4,
        embed_hidden_sizes=(8,), num_factors=2, num_supervised_factors=2,
        factor_weight_l1_coeff=0.01, adj_l1_reg_coeff=0.001,
        factor_cos_sim_coeff=0.01,
        factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive", num_sims=1,
        training_mode="combined"))


def _data(model, n=48):
    cfg = model.config
    rng = np.random.default_rng(0)
    T = cfg.max_lag + cfg.num_sims
    X = rng.normal(size=(n, T, cfg.num_chans)).astype(np.float32)
    Y = rng.uniform(size=(n, 3, 1)).astype(np.float32)
    return ArrayDataset(X, Y)


_POINTS = [{"gen_lr": 1e-3}, {"gen_lr": 3e-3}]


def _tc(**kw):
    kw.setdefault("max_iter", 3)
    return RedcliffTrainConfig(batch_size=16, check_every=1,
                               stream_mode="per_batch", **kw)


# ---------------------------------------------------------------------------
# precision resolution
# ---------------------------------------------------------------------------
def test_precision_mode_resolution():
    assert resolve_matmul_precision("f32") is None
    assert resolve_matmul_precision("mixed") == "bfloat16"
    # the legacy expert knob wins
    assert resolve_matmul_precision("f32", "tensorfloat32") == "tensorfloat32"
    assert precision_label("f32") == "f32"
    assert precision_label("mixed") == "mixed"
    assert precision_label("f32", "bfloat16") == "mixed"
    with pytest.raises(ValueError, match="precision_mode"):
        RedcliffTrainConfig(precision_mode="bf16")
    with pytest.raises(ValueError, match="precision_mode"):
        GridSpec(points=_POINTS, precision_mode="fp8")


# ---------------------------------------------------------------------------
# kernel parity
# ---------------------------------------------------------------------------
def test_factor_mix_bitwise_parity_interpret_f32():
    """The fused factor-mix kernel is BITWISE equal to the reference einsum
    in f32 interpret mode — including odd batch sizes that exercise the
    block padding/mask path."""
    rng = np.random.default_rng(0)
    for B, K, T, C in ((17, 5, 1, 10), (32, 2, 2, 4), (3, 4, 1, 7)):
        w = jnp.asarray(rng.random((B, K)).astype(np.float32))
        p = jnp.asarray(rng.normal(size=(K, B, T, C)).astype(np.float32))
        got = factor_mix_pallas(w, p, block_b=8, interpret=True)
        want = factor_mix_reference(w, p)
        assert bool(jnp.all(got == want)), (B, K, T, C)


def test_factor_mix_custom_vjp_matches_reference_grads():
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.random((6, 3)).astype(np.float32))
    p = jnp.asarray(rng.normal(size=(3, 6, 1, 4)).astype(np.float32))
    f_pl = lambda w, p: jnp.sum(jnp.sin(
        factor_mix_pallas(w, p, block_b=4, interpret=True)))
    f_rf = lambda w, p: jnp.sum(jnp.sin(factor_mix_reference(w, p)))
    g_pl = jax.grad(f_pl, argnums=(0, 1))(w, p)
    g_rf = jax.grad(f_rf, argnums=(0, 1))(w, p)
    for a, b in zip(g_pl, g_rf):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("shape,block_rows", [
    ((3, 7, 5, 7, 3), 2),     # rows 21, off-tile at block 2
    ((1, 1, 3, 1, 1), 16),    # rows 1 < block (clamp path)
    ((5, 12, 32, 12, 4), 7),  # rows 60, odd tile
])
def test_pallas_gl_prox_nondivisible_shapes(shape, block_rows):
    """Off-tile first-layer shapes ride the pad/mask path and still match
    the jnp reference (the tiling-robustness satellite)."""
    rng = np.random.default_rng(2)
    W = jnp.asarray(rng.normal(size=shape).astype(np.float32))
    got = gl_prox_pallas(W, 0.013, 0.002, block_rows=block_rows)
    want = prox_update(W, 0.013, 0.002, "GL")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_apply_prox_routes_first_layer_only():
    model = _model()
    params = model.init(jax.random.PRNGKey(0))
    out = model.apply_prox(params, lam=0.1, lr=0.01, penalty="GL")
    want_w = prox_update(params["factors"][0]["w"], 0.1, 0.01, "GL")
    np.testing.assert_array_equal(np.asarray(out["factors"][0]["w"]),
                                  np.asarray(want_w))
    # every other leaf untouched (bias + later layers + embedder)
    np.testing.assert_array_equal(np.asarray(out["factors"][0]["b"]),
                                  np.asarray(params["factors"][0]["b"]))
    for got_l, want_l in zip(jax.tree.leaves(out["factors"][1:]),
                             jax.tree.leaves(params["factors"][1:])):
        np.testing.assert_array_equal(np.asarray(got_l), np.asarray(want_l))


def test_grid_prox_enabled_fit_stays_finite_and_shrinks():
    """A prox-enabled grid fit runs end to end, and the GL prox actually
    shrinks first-layer group norms vs the no-prox fit."""
    model = _model()
    ds = _data(model)
    res_off = RedcliffGridRunner(model, _tc(), GridSpec(points=_POINTS)).fit(
        jax.random.PRNGKey(0), ds, ds)
    res_on = RedcliffGridRunner(
        model, _tc(prox_penalty="GL", prox_lam=0.05),
        GridSpec(points=_POINTS)).fit(jax.random.PRNGKey(0), ds, ds)
    assert np.all(np.isfinite(res_on.val_history))
    w_off = np.asarray(res_off.best_params["factors"][0]["w"])
    w_on = np.asarray(res_on.best_params["factors"][0]["w"])
    norm = lambda w: np.sqrt((w ** 2).sum(axis=(-3, -1)))
    assert norm(w_on).sum() < norm(w_off).sum()


# ---------------------------------------------------------------------------
# precision_mode="f32" bit-identity (the pre-PR decision streams)
# ---------------------------------------------------------------------------
def test_f32_mode_decision_stream_bit_identity():
    model = _model()
    ds = _data(model)
    res_default = RedcliffGridRunner(
        model, _tc(), GridSpec(points=_POINTS)).fit(
        jax.random.PRNGKey(0), ds, ds)
    res_f32 = RedcliffGridRunner(
        model, _tc(precision_mode="f32"), GridSpec(points=_POINTS)).fit(
        jax.random.PRNGKey(0), ds, ds)
    np.testing.assert_array_equal(np.asarray(res_default.val_history),
                                  np.asarray(res_f32.val_history))
    for a, b in zip(jax.tree.leaves(res_default.best_params),
                    jax.tree.leaves(res_f32.best_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# mixed-mode auto-demotion (grid + trainer) + resume semantics
# ---------------------------------------------------------------------------
def test_grid_mixed_demotes_on_skip_storm_and_resume_stays_f32(tmp_path):
    model = _model()
    ds = _data(model)
    ck = str(tmp_path / "ck")
    log1 = str(tmp_path / "log1")
    os.environ["REDCLIFF_FAULT_INJECT"] = "nan_batch:0-2"
    try:
        runner = RedcliffGridRunner(model, _tc(max_iter=5,
                                               precision_mode="mixed"),
                                    GridSpec(points=_POINTS))
        res = runner.fit(jax.random.PRNGKey(0), ds, ds, max_iter=3,
                         log_dir=log1, checkpoint_dir=ck,
                         checkpoint_every=1)
    finally:
        del os.environ["REDCLIFF_FAULT_INJECT"]
    recs = read_jsonl(log1)
    assert not schema.validate_records(recs)
    pev = [r for r in recs if r["event"] == "precision"]
    assert pev and pev[0]["kind"] == "demote" \
        and pev[0]["cause"] == "precision_cliff"
    assert runner._demoted
    # demotion gave the lanes an f32 epoch instead of quarantining them
    assert res.failures == []

    # resume under the SAME mixed config honors the checkpointed demotion
    log2 = str(tmp_path / "log2")
    runner2 = RedcliffGridRunner(model, _tc(max_iter=5,
                                            precision_mode="mixed"),
                                 GridSpec(points=_POINTS))
    runner2.fit(jax.random.PRNGKey(0), ds, ds, log_dir=log2,
                checkpoint_dir=ck, checkpoint_every=1)
    assert runner2._demoted
    recs2 = read_jsonl(log2)
    assert any(r["event"] == "precision" and r["kind"] == "resume_demoted"
               for r in recs2)

    # a DIFFERENT precision_mode is a different fit: resume rejects
    runner3 = RedcliffGridRunner(model, _tc(max_iter=5),
                                 GridSpec(points=_POINTS))
    with pytest.raises(ValueError, match="precision_mode"):
        runner3.fit(jax.random.PRNGKey(0), ds, ds, checkpoint_dir=ck)


def test_trainer_mixed_demotes_and_f32_resume_bit_identical(tmp_path):
    """Faultinject a bf16-cliff-shaped storm (non-finite grads -> sentinel
    skips -> rollback): the mixed trainer demotes, logs the `precision`
    event, and continuing the fit from the demotion point is BIT-IDENTICAL
    whether the resuming config says "mixed" (honoring the persisted
    demotion) or "f32" outright."""
    model = _model()
    ds = _data(model)
    d = str(tmp_path / "run")
    params = model.init(jax.random.PRNGKey(1))
    os.environ["REDCLIFF_FAULT_INJECT"] = "nan_batch:3-5"  # epoch 1's batches
    try:
        tr = RedcliffTrainer(model, _tc(precision_mode="mixed"))
        tr.fit(params, ds, ds, save_dir=d)
    finally:
        del os.environ["REDCLIFF_FAULT_INJECT"]
    assert tr._demoted
    recs = read_jsonl(d)
    assert not schema.validate_records(recs)
    pev = [r for r in recs if r["event"] == "precision"]
    assert pev and pev[0]["kind"] == "demote"
    # the anomaly trail shows the sentinel skipped (the storm evidence)
    assert any(r["event"] == "anomaly" for r in recs)

    d_mixed = str(tmp_path / "resume_mixed")
    d_f32 = str(tmp_path / "resume_f32")
    shutil.copytree(d, d_mixed)
    shutil.copytree(d, d_f32)
    res_a = RedcliffTrainer(model, _tc(max_iter=6, precision_mode="mixed")
                            ).fit(params, ds, ds, save_dir=d_mixed)
    res_b = RedcliffTrainer(model, _tc(max_iter=6, precision_mode="f32")
                            ).fit(params, ds, ds, save_dir=d_f32)
    for a, b in zip(jax.tree.leaves(res_a.params),
                    jax.tree.leaves(res_b.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# autotune store
# ---------------------------------------------------------------------------
def test_autotune_searches_once_then_zero_search_steps(tmp_path):
    d = str(tmp_path / "store")
    autotune.clear_memo()
    br, rec = autotune.tune_gl_prox(64, 16, base_dir=d, interpret=True,
                                    reps=1)
    assert rec["searched"] and rec["search_steps"] > 0
    assert rec["search_ms"] is not None
    assert rec["speedup_vs_default"] is not None
    assert os.path.exists(os.path.join(d, autotune.STORE_NAME))
    # drop the in-process memo: the second resolve must come from DISK
    autotune.clear_memo()
    br2, rec2 = autotune.tune_gl_prox(64, 16, base_dir=d, interpret=True)
    assert br2 == br
    assert rec2["search_steps"] == 0 and not rec2["searched"]
    # the drained records feed schema-registered `autotune` events
    kinds = [r["kind"] for r in autotune.drain_records()]
    assert kinds == ["search", "reuse"]
    autotune.clear_memo()


def test_autotune_corrupt_store_degrades_to_defaults(tmp_path):
    d = str(tmp_path / "store")
    os.makedirs(d)
    with open(os.path.join(d, autotune.STORE_NAME), "w") as f:
        f.write("{not json")
    autotune.clear_memo()
    assert autotune.winner("gl_prox", "cols16", 64, base_dir=d) is None
    # a search over a corrupt store restarts it fresh
    br, rec = autotune.tune_gl_prox(64, 16, base_dir=d, interpret=True,
                                    reps=1)
    assert rec["searched"]
    autotune.clear_memo()
    assert autotune.winner("gl_prox", "cols16", 64,
                           base_dir=d)["tile"]["block_rows"] == br
    autotune.clear_memo()


def test_autotuned_block_rows_reaches_gl_prox(tmp_path, monkeypatch):
    """gl_prox_pallas(block_rows=None) resolves the persisted winner from
    the configured store — and still matches the jnp reference at that
    tile. The store is pointed at tmp via REDCLIFF_AUTOTUNE_DIR because
    the hot-path lookup resolves the SAME store the winner was recorded
    to (memo keys include the resolved path)."""
    monkeypatch.setenv(autotune.ENV_STORE_DIR, str(tmp_path / "store"))
    autotune.clear_memo()
    rng = np.random.default_rng(3)
    W = jnp.asarray(rng.normal(size=(4, 8, 8, 8, 2)).astype(np.float32))
    # rows = 4*8*8 = 256 -> bucket 256; record the winner at the right key
    autotune.record_winner("gl_prox", "cols16", 256, {"block_rows": 2})
    got = gl_prox_pallas(W, 0.01, 0.002)  # winner lookup path
    want = prox_update(W, 0.01, 0.002, "GL")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
    autotune.clear_memo()


# ---------------------------------------------------------------------------
# cost-model precision axis
# ---------------------------------------------------------------------------
def test_costmodel_precision_splits_buckets(tmp_path):
    base = str(tmp_path)
    shape = "num_chans=4"
    rows_f32 = [{"shape": shape, "g_bucket": 8, "epochs": 4,
                 "epoch_ms": 400.0, "precision": "f32"}]
    rows_mixed = [{"shape": shape, "g_bucket": 8, "epochs": 4,
                   "epoch_ms": 100.0, "precision": "mixed"}]
    costmodel.update_store(base, rows_f32, platform="cpu")
    costmodel.update_store(base, rows_mixed, platform="cpu")
    model = costmodel.load(base)
    assert model.predict_epoch_ms(shape, 8, platform="cpu",
                                  precision="f32") == 100.0
    assert model.predict_epoch_ms(shape, 8, platform="cpu",
                                  precision="mixed") == 25.0
    # the two buckets never predict each other
    assert model.predict_epoch_ms(shape, 8, platform="cpu",
                                  precision="tf32") is None
    keys = set(model.buckets)
    assert costmodel.bucket_key("cpu", shape, 8, "f32") in keys
    assert costmodel.bucket_key("cpu", shape, 8, "mixed") in keys


def test_costmodel_legacy_store_backfills_f32(tmp_path):
    """A pre-precision store (3-segment keys, no precision field) reads as
    f32 buckets — existing evidence keeps predicting f32 fits."""
    import json

    base = str(tmp_path)
    path = costmodel.store_path(base)
    legacy = {
        "version": costmodel.STORE_VERSION, "updated_at": 1.0, "runs": 1,
        "buckets": {"cpu|num_chans=4|g8": {
            "platform": "cpu", "shape": "num_chans=4", "g_bucket": 8,
            "epochs": 2, "epoch_ms_total": 50.0, "compiles": 0,
            "compile_ms_total": 0.0, "cache_hits": 0, "cache_misses": 0,
            "runs": 1}}}
    with open(path, "w") as f:
        json.dump(legacy, f)
    model = costmodel.load(base)
    assert model.predict_epoch_ms("num_chans=4", 8, platform="cpu",
                                  precision="f32") == 25.0
    assert model.predict_epoch_ms("num_chans=4", 8, platform="cpu",
                                  precision="mixed") is None
    rows = model.accuracy_rows()
    assert rows[0]["precision"] == "f32"
    # a write-back normalizes the key
    costmodel.update_store(base, [{"shape": "num_chans=4", "g_bucket": 8,
                                   "epochs": 2, "epoch_ms": 50.0}],
                          platform="cpu")
    model2 = costmodel.load(base)
    assert costmodel.bucket_key("cpu", "num_chans=4", 8, "f32") \
        in model2.buckets
    assert model2.predict_epoch_ms("num_chans=4", 8, platform="cpu",
                                   precision="f32") == 25.0
