"""Spatial multi-tenant mesh packing tests (ISSUE 18).

Slot-table units (power-of-two alignment, reserve/free lifecycle,
occupancy), packed-vs-serial pricing (the empty-cost-store bit-identity
contract), per-tenant fair-share quota deferral with structured reasons,
and the gang-scheduling end-to-end ACCEPTANCE: two heterogeneous batches
drain CO-RESIDENT on disjoint sub-mesh slots of a simulated 4-device pool
with zero headroom violations; a poisoned co-tenant sharing the pool costs
the healthy batch nothing (bit-identical to a solo run); a canceled
co-tenant frees its slot at the next check window without perturbing the
survivor; a SIGKILLed worker's packed batches are reclaimed into their
ORIGINAL slots and resume from checkpoint.
"""
import json
import os
import signal
import subprocess
import sys
import time

from redcliff_tpu.fleet import chaos, planner
from redcliff_tpu.fleet.queue import FleetQueue
from redcliff_tpu.fleet.__main__ import TINY_SPEC
from redcliff_tpu.obs import schema as obs_schema
from redcliff_tpu.obs.logging import read_jsonl
from redcliff_tpu.parallel import packing

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# slot-table units
# ---------------------------------------------------------------------------
def test_slot_table_alloc_is_aligned_first_fit():
    st = packing.SlotTable(4)
    a = st.alloc(2)
    b = st.alloc(2)
    assert a == {"lo": 0, "width": 2} and b == {"lo": 2, "width": 2}
    assert st.alloc(1) is None and st.free_widths() == []
    st.free(a)
    assert st.free_widths() == [2, 1]
    # alignment: a width-2 slot only starts at multiples of 2
    c = st.alloc(2)
    assert c == {"lo": 0, "width": 2}


def test_slot_table_non_pow2_pool_uses_pow2_prefix():
    st = packing.SlotTable(6)  # pool = largest power of two <= 6
    assert st.pool == 4
    occ = st.occupancy()
    assert occ["n_devices"] == 6 and occ["pool"] == 4


def test_slot_table_reserve_and_idempotent_free():
    st = packing.SlotTable(8)
    # reserve re-occupies an exact recorded slot (the reclaim path)
    assert st.reserve({"lo": 2, "width": 2}) is True
    assert st.alloc(8) is None
    assert st.reserve({"lo": 2, "width": 2}) is False   # overlap
    assert st.reserve({"lo": 5, "width": 2}) is False   # misaligned
    assert st.reserve({"lo": 6, "width": 4}) is False   # out of range
    st.free({"lo": 2, "width": 2})
    st.free({"lo": 2, "width": 2})          # idempotent
    assert st.free_widths()[0] == 8


def test_slot_table_occupancy_utilization():
    st = packing.SlotTable(4)
    st.alloc(2)
    st.alloc(1)
    occ = st.occupancy()
    assert occ["busy_devices"] == 3 and occ["free_devices"] == 1
    assert occ["utilization_pct"] == 75.0
    assert {(s["lo"], s["width"]) for s in occ["slots"]} == {(0, 2), (2, 1)}


def test_packing_mode_env_parsing():
    assert packing.packing_mode(env="") == "off"
    assert packing.packing_mode(env="0") == "off"
    assert packing.packing_mode(env="force") == "force"
    assert packing.packing_mode(env="auto") == "auto"
    assert packing.packing_mode(env="1") == "auto"
    assert packing.devices_for(3, 8) == 3 or packing.devices_for(3, 8) >= 1


# ---------------------------------------------------------------------------
# packed-vs-serial pricing
# ---------------------------------------------------------------------------
def test_price_packing_unpriced_falls_back_serial():
    """The empty-cost-store contract: any batch without a priced eta keeps
    the decision 'serial' — the packed worker then claims one batch at a
    time, bit-identical to the serial heuristic."""
    batches = [{"batch_id": "a", "g_bucket": 1},
               {"batch_id": "b", "g_bucket": 1}]
    out = packing.price_packing(batches, 4, None)
    assert out["decision"] == "serial" and out["reason"] == "unpriced"
    assert out["headroom_violations"] == 0
    # deterministic: the same inputs price identically (no wall-clock,
    # no randomness inside the pricer)
    assert out == packing.price_packing(
        [dict(b) for b in batches], 4, None)


def test_price_packing_priced_packs_and_respects_budget():
    batches = [{"batch_id": "a", "g_bucket": 1, "eta_s": 10.0,
                "predicted_bytes": 600},
               {"batch_id": "b", "g_bucket": 1, "eta_s": 10.0,
                "predicted_bytes": 600}]
    packed = packing.price_packing(batches, 4, None)
    assert packed["decision"] == "packed"
    assert packed["makespan_ratio"] < 1.0
    assert packed["headroom_violations"] == 0
    # a budget that cannot hold both resident at once forces serial
    tight = packing.price_packing(batches, 4, 1000)
    assert tight["decision"] == "serial"
    assert tight["headroom_violations"] == 0
    starts = [a["start_s"] for a in tight["assignments"]]
    assert len(set(starts)) == 2, "resident-bytes gate must serialize"


def test_planner_plan_carries_packing_and_is_deterministic(tmp_path):
    q = FleetQueue(tmp_path)
    for t in ("a", "b"):
        spec = json.loads(json.dumps(TINY_SPEC))
        spec["data"]["seed"] = ord(t)
        q.submit(t, [{"gen_lr": 1e-3}], spec=spec)
    reqs = q.pending()
    p1 = planner.plan(reqs, n_devices=4)
    p2 = planner.plan(reqs, n_devices=4)
    assert len(p1["batches"]) == 2
    # empty cost store: unpriced -> serial, and the admitted batch list is
    # byte-for-byte the serial heuristic's (packing is an annotation, not
    # a perturbation)
    assert p1["packing"]["decision"] == "serial"
    assert p1["packing"]["reason"] == "unpriced"
    strip = lambda p: [{k: v for k, v in b.items()} for b in p["batches"]]
    assert strip(p1) == strip(p2)


# ---------------------------------------------------------------------------
# per-tenant fair-share quotas
# ---------------------------------------------------------------------------
def test_tenant_slot_quota_parser():
    assert planner.tenant_slot_quota(env=None) is None
    assert planner.tenant_slot_quota(env="") is None
    assert planner.tenant_slot_quota(env="2") == {"*": 2}
    assert planner.tenant_slot_quota(env="a=1,b=4") == {"a": 1, "b": 4}
    assert planner.tenant_slot_quota(env="2,a=1") == {"*": 2, "a": 1}
    assert planner.tenant_slot_quota(env="garbage=") is None


def test_plan_defers_over_quota_tenant_with_structured_reason(tmp_path):
    q = FleetQueue(tmp_path)
    for i, t in enumerate(("greedy", "greedy", "modest")):
        spec = json.loads(json.dumps(TINY_SPEC))
        spec["data"]["seed"] = i  # distinct merge keys -> three batches
        q.submit(t, [{"gen_lr": 1e-3}], spec=spec)
    pl = planner.plan(q.pending(), n_devices=4,
                      tenant_slots={"*": 1})
    admitted = {b["tenants"][0] for b in pl["batches"]}
    assert admitted == {"greedy", "modest"}
    assert len(pl["quota_deferred"]) == 1
    d = pl["quota_deferred"][0]
    assert d["tenant"] == "greedy"
    assert d["reason"] == "tenant quota"
    assert d["max_inflight_slots"] == 1 and d["inflight"] == 1
    assert "REDCLIFF_FLEET_TENANT_SLOTS" in d["detail"]
    # already-running slots count against the quota too
    pl2 = planner.plan(q.pending(), n_devices=4, tenant_slots={"*": 1},
                       inflight_slots={"modest": 1})
    assert {b["tenants"][0] for b in pl2["batches"]} == {"greedy"}
    assert {d["tenant"] for d in pl2["quota_deferred"]} \
        == {"greedy", "modest"}
    # deferred is NOT unschedulable: nothing lands in the dead-end list
    assert pl2["unschedulable"] == []


# ---------------------------------------------------------------------------
# gang-scheduling end-to-end
# ---------------------------------------------------------------------------
def _clean_fault_env():
    env = dict(os.environ)
    env.pop("REDCLIFF_FAULT_INJECT", None)
    env.pop("REDCLIFF_FAULT_MARKER", None)
    env.pop("REDCLIFF_FLEET_PACKING", None)
    env.pop("REDCLIFF_FLEET_TENANT_SLOTS", None)
    return env


def _drain(root, packing_mode="force", **kw):
    from redcliff_tpu.fleet.worker import work
    from redcliff_tpu.runtime.retry import RetryPolicy
    from redcliff_tpu.runtime.supervisor import SupervisorPolicy

    kw.setdefault("env", _clean_fault_env())
    kw.setdefault("max_attempts", 3)
    policy = SupervisorPolicy(
        max_restarts=kw.pop("max_restarts", 2),
        backoff=RetryPolicy(max_attempts=100, base_delay_s=0.05,
                            multiplier=1.0, max_delay_s=0.05))
    return work(str(root), drain=True, poll_s=0.2, lease_s=30.0,
                n_devices=4, supervisor_policy=policy,
                packing=packing_mode, **kw)


def _submit_two(q, epochs=1, points=None):
    rids = []
    for i in range(2):
        spec = json.loads(json.dumps(TINY_SPEC))
        spec["epochs"] = epochs
        spec["mesh"] = "auto"
        spec["data"]["seed"] = i  # distinct merge keys -> two batches
        rids.append(q.submit(
            f"tenant{i}", (points[i] if points else [{"gen_lr":
                                                      1e-3 * (i + 1)}]),
            spec=spec))
    return rids


def _payload(result):
    return {k: v for k, v in result.items()
            if k not in ("request_id", "batch_id")}


def _claim_spans(root):
    """{batch_id: (claim_wall, free_wall, slot)} from the packing events."""
    claims, frees = {}, {}
    for r in read_jsonl(str(root)):
        if r.get("event") != "packing":
            continue
        if r.get("kind") == "slot_claim":
            claims[r["batch_id"]] = r
        elif r.get("kind") == "slot_free":
            frees[r["batch_id"]] = r
    return {bid: (claims[bid]["wall_time"],
                  frees[bid]["wall_time"] if bid in frees else None,
                  claims[bid]["slot"])
            for bid in claims}


def test_packed_drain_two_batches_concurrently(tmp_path):
    """The tentpole acceptance: two heterogeneous batches co-resident on
    disjoint sub-mesh slots, gang-scheduled at check-window boundaries,
    zero headroom violations, full telemetry schema-valid."""
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    rids = _submit_two(q)
    n = _drain(root)
    assert n == 2
    st = q.status()["counts"]
    assert st["done"] == 2 and st["failed"] == 0

    spans = _claim_spans(root)
    assert len(spans) == 2
    (a0, a1, sa), (b0, b1, sb) = spans.values()
    # disjoint slots...
    assert not (sa["lo"] < sb["lo"] + sb["width"]
                and sb["lo"] < sa["lo"] + sa["width"])
    # ...resident at the same time (the whole point)
    assert a0 < b1 and b0 < a1, "batches never overlapped in time"

    recs = read_jsonl(str(root))
    assert obs_schema.validate_records(recs) == []
    plans = [r for r in recs if r.get("event") == "packing"
             and r.get("kind") == "plan"]
    assert plans and all(
        (r.get("headroom_violations") or 0) == 0 for r in plans)

    # the slot is durable in batch.json (the reclaim anchor)
    for bid, (_, _, slot) in spans.items():
        with open(os.path.join(q.batch_dir(bid), "batch.json"),
                  encoding="utf-8") as fh:
            assert json.load(fh)["slot"] == slot

    # per-point partial results streamed under each run dir, final rows
    # covering every point
    for rid in rids:
        paths = [os.path.join(q.batch_dir(bid), "results",
                              f"{rid}.partial.jsonl")
                 for bid in spans]
        path = next(p for p in paths if os.path.exists(p))
        rows = [json.loads(l) for l in open(path, encoding="utf-8")]
        assert rows and rows[-1]["final"] is True
        assert rows[-1]["request_id"] == rid

    # surfacing: watch packing section + fleet status --json packing key +
    # report fleet_packing section
    from redcliff_tpu.obs.watch import build_snapshot, render_text

    snap = build_snapshot(str(root))
    assert obs_schema.validate_record(snap) == []
    assert snap["packing"]["slot_claims"] == 2
    assert snap["packing"]["slot_frees"] == 2
    assert snap["packing"]["partial_points"] >= 2
    assert "packing:" in render_text(snap)

    out = subprocess.run(
        [sys.executable, "-m", "redcliff_tpu.fleet", "status", "--root",
         str(root), "--json"], capture_output=True, text=True,
        env=_clean_fault_env(), cwd=REPO_ROOT)
    assert out.returncode == 0, out.stderr
    cli = json.loads(out.stdout)
    assert "packing" in cli
    assert cli["packing"]["partial_results"]

    from redcliff_tpu.obs.report import build_report
    report = build_report(str(root))
    fp = report["fleet_packing"]
    assert fp["events"]["slot_claim"] == 2
    assert fp["last_plan"]["headroom_violations"] == 0


def test_auto_mode_empty_cost_store_stays_serial(tmp_path):
    """Bit-identity fallback: auto mode over an unpriced queue never
    co-schedules — claims are strictly sequential, exactly the serial
    heuristic's behavior."""
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    _submit_two(q)
    assert _drain(root, packing_mode="auto") == 2
    assert q.status()["counts"]["done"] == 2
    spans = _claim_spans(root)
    assert len(spans) == 2
    (a0, a1, _), (b0, b1, _) = spans.values()
    assert a1 <= b0 or b1 <= a0, "auto+unpriced must serialize claims"
    plans = [r for r in read_jsonl(str(root))
             if r.get("event") == "packing" and r.get("kind") == "plan"]
    assert plans and all(r["decision"] == "serial" for r in plans)
    assert {r["reason"] for r in plans} <= {"unpriced", "single_batch"}
    assert any(r["reason"] == "unpriced" for r in plans)


def test_tenant_quota_keeps_over_quota_batch_queued(tmp_path, monkeypatch):
    """Fair-share end-to-end: with a 1-slot quota, a two-batch tenant
    drains one batch at a time (the deferral is a delay, not a loss) and
    the structured reason rides the plan telemetry."""
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    for i in range(2):
        spec = json.loads(json.dumps(TINY_SPEC))
        spec["epochs"] = 1
        spec["mesh"] = "auto"
        spec["data"]["seed"] = i
        q.submit("hog", [{"gen_lr": 1e-3 * (i + 1)}], spec=spec)
    env = _clean_fault_env()
    env["REDCLIFF_FLEET_TENANT_SLOTS"] = "1"
    monkeypatch.setenv("REDCLIFF_FLEET_TENANT_SLOTS", "1")
    assert _drain(root, env=env) == 2
    assert q.status()["counts"]["done"] == 2
    spans = _claim_spans(root)
    (a0, a1, _), (b0, b1, _) = spans.values()
    assert a1 <= b0 or b1 <= a0, "quota=1 must never co-schedule a tenant"
    deferred = [r for r in read_jsonl(str(root))
                if r.get("event") == "fleet" and r.get("kind") == "plan"
                and r.get("quota_deferred")]
    assert deferred, "the deferral never hit the plan telemetry"
    d = deferred[0]["quota_deferred"][0]
    assert d["tenant"] == "hog" and d["reason"] == "tenant quota"


def test_poisoned_cotenant_does_not_perturb_healthy_batch(tmp_path):
    """Fault-isolation acceptance: a crash-looping (fleet_poison SIGKILL)
    co-tenant shares the pool with a healthy batch; the healthy batch's
    results are bit-identical to a solo run and the poison dead-letters
    on its own slot."""
    root_mix = tmp_path / "mix"
    root_solo = tmp_path / "solo"
    qm, qs = FleetQueue(root_mix), FleetQueue(root_solo)

    def submit_healthy(q):
        spec = json.loads(json.dumps(TINY_SPEC))
        spec["epochs"] = 2
        spec["mesh"] = "auto"
        return q.submit("healthy", [{"gen_lr": 1e-3}], spec=spec)

    rid_h = submit_healthy(qm)
    spec_p = json.loads(json.dumps(TINY_SPEC))
    spec_p["epochs"] = 2
    spec_p["mesh"] = "auto"
    spec_p["data"]["seed"] = 7
    rid_p = qm.submit("poison", [chaos.poison_point("sigkill")],
                      spec=spec_p)
    rid_solo = submit_healthy(qs)

    armed = _clean_fault_env()
    armed["REDCLIFF_FAULT_INJECT"] = "fleet_poison"
    _drain(root_mix, env=armed, max_restarts=0, max_attempts=3)
    cm = qm.status()["counts"]
    assert cm["done"] == 1 and cm["deadletter"] == 1 and cm["failed"] == 0
    assert qm.deadletter_record(rid_p) is not None

    assert _drain(root_solo) == 1
    res = _payload(qm.result(rid_h)["result"])
    ref = _payload(qs.result(rid_solo)["result"])
    assert res == ref, "healthy batch diverged beside the poison co-tenant"
    recs = read_jsonl(str(root_mix))
    assert obs_schema.validate_records(recs) == []


def test_cancel_frees_slot_without_perturbing_survivor(tmp_path):
    """Cancel/requeue satellite: canceling every member of one co-resident
    batch SIGTERMs only that batch; its slot frees at the next check
    window (slot_canceled, no requeue) and the surviving co-tenant
    completes bit-identically to a solo run."""
    import threading

    root = tmp_path / "fleet"
    root_solo = tmp_path / "solo"
    q, qs = FleetQueue(root), FleetQueue(root_solo)
    spec_s = json.loads(json.dumps(TINY_SPEC))
    spec_s["epochs"] = 2
    spec_s["mesh"] = "auto"
    rid_live = q.submit("live", [{"gen_lr": 1e-3}], spec=spec_s)
    rid_solo = qs.submit("live", [{"gen_lr": 1e-3}], spec=spec_s)
    spec_v = json.loads(json.dumps(TINY_SPEC))
    spec_v["epochs"] = 60       # long enough to still be running
    spec_v["mesh"] = "auto"
    spec_v["data"]["seed"] = 5
    rid_victim = q.submit("victim", [{"gen_lr": 2e-3}], spec=spec_v)

    def cancel_when_running():
        deadline = time.time() + 240
        while time.time() < deadline:
            lease = q.lease_of(rid_victim)
            run_dir = (q.batch_dir(lease["batch_id"])
                       if lease and lease.get("batch_id") else None)
            if run_dir and os.path.exists(
                    os.path.join(run_dir, "grid_checkpoint.pkl")):
                q.cancel(rid_victim, reason="operator")
                return
            time.sleep(0.1)

    t = threading.Thread(target=cancel_when_running, daemon=True)
    t.start()
    _drain(root)
    t.join(timeout=5)
    st = q.status()["counts"]
    assert st["done"] == 1 and st["canceled"] == 1 and st["failed"] == 0
    recs = read_jsonl(str(root))
    assert obs_schema.validate_records(recs) == []
    kinds = {r["kind"] for r in recs if r.get("event") == "packing"}
    assert "slot_canceled" in kinds and "cancel_stop" in kinds
    # the survivor never noticed
    assert _drain(root_solo) == 1
    res = _payload(q.result(rid_live)["result"])
    ref = _payload(qs.result(rid_solo)["result"])
    assert res == ref, "survivor diverged when its co-tenant was canceled"


def test_sigkill_mid_packing_reclaims_original_slots(tmp_path):
    """Crash-safety acceptance under packing: SIGKILL a worker while two
    batches are co-resident -> leases expire -> a second packed worker
    reclaims BOTH batches into their originally recorded slots and resumes
    from checkpoint; nothing lost, nothing run twice."""
    root = tmp_path / "fleet"
    q = FleetQueue(root)
    rids = _submit_two(q, epochs=4)

    env = _clean_fault_env()
    w1 = subprocess.Popen(
        [sys.executable, "-m", "redcliff_tpu.fleet", "work", "--root",
         str(root), "--max-batches", "2", "--lease-s", "2",
         "--poll-s", "0.2", "--n-devices", "4", "--packing", "force"],
        env=env, start_new_session=True, cwd=REPO_ROOT,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    slots_before = {}
    try:
        deadline = time.time() + 240
        while len(slots_before) < 2 and time.time() < deadline:
            assert w1.poll() is None, "worker died before packing"
            for rid in rids:
                lease = q.lease_of(rid)
                bid = (lease or {}).get("batch_id")
                if not bid or bid in slots_before:
                    continue
                bj = os.path.join(q.batch_dir(bid), "batch.json")
                ck = os.path.join(q.batch_dir(bid), "grid_checkpoint.pkl")
                if os.path.exists(bj) and os.path.exists(ck):
                    with open(bj, encoding="utf-8") as fh:
                        slots_before[bid] = json.load(fh)["slot"]
            time.sleep(0.1)
        assert len(slots_before) == 2, "both batches never got resident"
        os.killpg(w1.pid, signal.SIGKILL)
    finally:
        if w1.poll() is None:
            os.killpg(w1.pid, signal.SIGKILL)
        w1.wait()

    for rid in rids:
        lease = q.lease_of(rid)
        while lease is not None and time.time() < float(
                lease["expires_at"]):
            time.sleep(0.05)

    assert _drain(root) == 2
    assert q.status()["counts"]["done"] == 2
    # reclaimed into the ORIGINAL slots, resumed (not re-run)
    for bid, slot in slots_before.items():
        with open(os.path.join(q.batch_dir(bid), "batch.json"),
                  encoding="utf-8") as fh:
            assert json.load(fh)["slot"] == slot, f"{bid} moved slots"
        starts = [r for r in read_jsonl(q.batch_dir(bid))
                  if r.get("event") == "fit_start"]
        assert any(r.get("resumed_from_epoch") is not None
                   for r in starts), f"{bid} restarted from scratch"
    froot = read_jsonl(str(root))
    assert any(r.get("event") == "fleet" and r.get("kind") == "reclaim"
               for r in froot)
    assert obs_schema.validate_records(froot) == []


# ---------------------------------------------------------------------------
# autoscale slot-awareness lives in tests/test_autoscale.py
# (test_predicted_drain_is_slot_aware); packing state durability unit here
# ---------------------------------------------------------------------------
def test_publish_and_load_state_roundtrip(tmp_path):
    st = packing.SlotTable(4)
    st.alloc(2)
    packing.publish_state(str(tmp_path), st.occupancy(),
                          concurrent_batches=1)
    out = packing.load_state(str(tmp_path))
    assert out["busy_devices"] == 2 and out["concurrent_batches"] == 1
    # staleness gate: an old publication is ignored
    packing.publish_state(str(tmp_path), st.occupancy(),
                          concurrent_batches=1,
                          now=time.time() - 10 * packing.STATE_FRESH_S)
    assert packing.load_state(str(tmp_path)) is None
    # corrupt file -> None, never a crash
    with open(os.path.join(str(tmp_path), packing.STATE_FILE), "w",
              encoding="utf-8") as fh:
        fh.write("{torn")
    assert packing.load_state(str(tmp_path)) is None
