"""Tests for the evaluation layer (stats, dispatch, cross-alg driver,
grid-search selection)."""
import os
import pickle

import numpy as np
import pytest

from redcliff_tpu.eval.cross_alg import (
    evaluate_algorithm_on_fold,
    find_run_directory,
    run_cross_algorithm_comparison,
    select_algorithm_root,
)
from redcliff_tpu.eval.gc_estimates import (
    get_model_gc_estimates,
    get_model_gc_score_estimates,
)
from redcliff_tpu.eval.grid_selection import (
    average_factor_histories,
    filter_incomplete_runs,
    rank_runs,
    select_best_models,
)
from redcliff_tpu.eval.model_io import load_model_for_eval
from redcliff_tpu.eval.stats import (
    compute_fixed_f1_stats,
    compute_key_stats,
    compute_optimal_f1_stats,
    summarize_values,
    three_view_optimal_f1_stats,
)


# ------------------------------------------------------------------- stats

def test_optimal_f1_stats_perfect_estimate():
    true = np.array([[0, 1], [1, 0]], dtype=float)
    est = np.array([[0.1, 0.9], [0.8, 0.2]])
    out = compute_optimal_f1_stats(est, true)
    assert out["f1"] == pytest.approx(1.0)
    assert 0.2 < out["decision_threshold"] <= 0.8


def test_optimal_f1_stats_gates_degenerate_inputs(capsys):
    true = np.array([[0, 1], [1, 0]], dtype=float)
    assert compute_optimal_f1_stats(np.ones((2, 2)), true) == {}
    assert compute_optimal_f1_stats(np.full((2, 2), np.nan), true) == {}
    assert compute_optimal_f1_stats(np.array([[0.1, 0.9], [0.8, 0.2]]),
                                    np.ones((2, 2))) == {}


def test_fixed_f1_and_key_stats_keys():
    rng = np.random.default_rng(0)
    true = (rng.uniform(size=(4, 4)) > 0.5).astype(float)
    est = true * 0.9 + rng.uniform(0, 0.05, size=(4, 4))
    f1s = compute_fixed_f1_stats(est, true)
    assert "f1_pc0.5" in f1s and f1s["f1_pc0.5"] == pytest.approx(1.0)
    ks = compute_key_stats(est, true)
    assert ks["roc_auc"] == pytest.approx(1.0)
    assert "sensitivity_pc0.5" in ks and "NLR_pc0.9" in ks


def test_three_view_stats_paradigm_keys():
    rng = np.random.default_rng(1)
    true = (rng.uniform(size=(5, 5, 2)) > 0.6).astype(float)
    est = true + 0.1 * rng.uniform(size=true.shape)
    out = three_view_optimal_f1_stats(est, true)
    assert set(out) == {
        "key_stats_estGC_norm_vs_trueGC_norm",
        "key_stats_estGC_normOffDiag_vs_trueGC_normOffDiag",
        "key_stats_estGC_normOffDiagTransposed_vs_trueGC_normOffDiag",
    }
    assert out["key_stats_estGC_norm_vs_trueGC_norm"]["f1"] > 0.9


def test_summarize_values():
    s = summarize_values([1.0, 2.0, 3.0, None])
    assert s["mean"] == pytest.approx(2.0)
    assert s["median"] == pytest.approx(2.0)
    assert s["mean_std_err"] == pytest.approx(np.std([1, 2, 3]) / np.sqrt(3))
    assert summarize_values([None])["mean"] is None


# ------------------------------------------------------- gc dispatch

class _FakeGraphModel:
    """Duck-typed single-graph baseline (cMLP/cLSTM/DGCNN signature)."""

    def __init__(self, g):
        self._g = g

    def gc(self, params, threshold=False, ignore_lag=True,
           combine_wavelet_representations=False, rank_wavelets=False,
           combine_node_feature_edges=False):
        return [self._g]


class _FakeDynotears:
    def __init__(self, g):
        self._g = g

    def gc(self):
        return self._g


def test_gc_dispatch_replicates_single_graph():
    g = np.arange(9.0).reshape(3, 3)
    ests = get_model_gc_estimates(_FakeGraphModel(g), None, "CMLP", 4)
    assert len(ests) == 4
    np.testing.assert_array_equal(ests[0], g)
    ests[0][0, 0] = 99.0  # copies, not views
    assert ests[1][0, 0] == 0.0


def test_gc_dispatch_dynotears_and_scores():
    g = np.eye(3)
    ests = get_model_gc_estimates(_FakeDynotears(g), None,
                                  "DYNOTEARS_Vanilla", 2)
    assert len(ests) == 2
    scores = get_model_gc_score_estimates(_FakeDynotears(g), None,
                                          "DYNOTEARS_Vanilla", 2)
    np.testing.assert_array_equal(scores, np.ones(2))


def test_gc_dispatch_unknown_raises():
    with pytest.raises(NotImplementedError):
        get_model_gc_estimates(None, None, "MYSTERY_ALG", 2)


# ------------------------------------------- cross-alg driver end-to-end

def _make_dynotears_artifact(run_dir, a_est):
    from redcliff_tpu.models.dynotears import DynotearsConfig
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, "final_best_model.bin"), "wb") as f:
        pickle.dump({"model_class": "DynotearsVanillaModel",
                     "config": DynotearsConfig(lag_size=1),
                     "a_est": a_est}, f)


def test_cross_algorithm_comparison_end_to_end(tmp_path):
    rng = np.random.default_rng(2)
    true_g = (rng.uniform(size=(4, 4, 1)) > 0.5).astype(float)
    dset = "toy_dset"
    num_folds = 2
    alg_root = tmp_path / "DYNOTEARS_Vanilla_models"
    for fold in range(num_folds):
        run = alg_root / f"{dset}_fold{fold}_run"
        # estimate = truth + small noise so optimal F1 is 1.0
        est = true_g[:, :, 0] + 0.05 * rng.uniform(size=(4, 4))
        _make_dynotears_artifact(str(run), est)
    true_graphs = {dset: {f: [true_g, true_g] for f in range(num_folds)}}
    out_root = tmp_path / "eval_out"
    summary = run_cross_algorithm_comparison(
        [str(alg_root)], true_graphs, str(out_root), num_folds)
    assert (out_root / "full_comparrisson_summary.pkl").exists()
    cv = summary[dset]
    para = cv["key_stats_estGC_norm_vs_trueGC_norm"]["DYNOTEARS_Vanilla"]
    assert para["f1_mean_across_factors"] == pytest.approx(1.0)
    # 2 factors x 2 folds accumulated
    assert len(para["f1_vals_across_factors"]) == 4


def test_find_run_directory_requires_unique(tmp_path):
    root = tmp_path / "alg"
    os.makedirs(root / "dsetA_fold0_x")
    os.makedirs(root / "dsetA_fold0_y")
    with pytest.raises(ValueError):
        find_run_directory(str(root), "dsetA", 0)


def test_select_algorithm_root_alias_rules():
    roots = ["/runs/REDCLIFF_S_CMLP_models", "/runs/CMLP_models",
             "/runs/NAVAR_CMLP_models"]
    assert select_algorithm_root("CMLP", roots) == "/runs/CMLP_models"
    assert select_algorithm_root("REDCLIFF_S_CMLP", roots) == \
        "/runs/REDCLIFF_S_CMLP_models"
    assert select_algorithm_root("NAVAR_CMLP", roots) == \
        "/runs/NAVAR_CMLP_models"


# ------------------------------------------------- grid selection

def _write_meta(root, name, meta):
    d = os.path.join(root, name)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "training_meta_data_and_hyper_parameters.pkl"),
              "wb") as f:
        pickle.dump(meta, f)


def _toy_meta(forecast, factor, cos):
    n = len(forecast)
    return {
        "avg_forecasting_loss": forecast,
        "avg_factor_loss": factor,
        "gc_factor_cosine_sim_histories": {"01": cos, "10": cos},
        "roc_auc_histories": {0.0: [[0.5, 0.6]] * n},
        "roc_auc_OffDiag_histories": {0.0: [[0.5, 0.6]] * n},
        "avg_fw_l1_penalty": [0.1] * n,
        "gc_factor_l1_loss_histories": [[1.0, 2.0]] * n,
        "deltacon0_histories": [[0.9, 0.8]] * n,
        "deltacon0_with_directed_degrees_histories": [[0.9, 0.8]] * n,
        "deltaffinity_histories": [[0.9, 0.8]] * n,
        "path_length_mse_histories": {1: [[0.2, 0.3]] * n},
    }


def test_grid_selection_ranks_and_combines(tmp_path):
    root = str(tmp_path)
    _write_meta(root, "runA", _toy_meta([3.0, 2.0, 1.0], [0.5, 0.4, 0.3],
                                        [0.2, 0.2, 0.2]))
    _write_meta(root, "runB", _toy_meta([2.0, 1.5, 0.2], [0.9, 0.8, 0.7],
                                        [0.3, 0.3, 0.3]))
    _write_meta(root, "runC_incomplete", {"avg_forecasting_loss": []})
    res = select_best_models(root)
    assert res["forecasting_loss"]["best_run"] == "runB"
    assert res["forecasting_loss"]["best_epoch"] == 2
    assert res["factor_loss"]["best_run"] == "runA"
    combo = res["forecasting_loss_and_factor_loss_and_gc_cosine_sim_history"]
    # runA combo: 1.0+0.3+0.2=1.5 ; runB combo: 0.2+0.7+0.3=1.2
    assert combo["best_run"] == "runB"
    # incomplete run dropped everywhere
    for crit in res.values():
        assert all(r[0] != "runC_incomplete" for r in crit["ranking"])


def test_average_factor_histories_shapes():
    # histories are factor-major (outer list = factor, inner = epoch), as in
    # the reference tracker and train.tracking
    meta = _toy_meta([1.0, 2.0], [0.1, 0.2], [0.5, 0.6])
    out = average_factor_histories(meta)
    # two factors with per-epoch values [0.5, 0.6] each -> per-epoch means
    assert out["avg_roc_auc_score_history"] == [
        pytest.approx(0.5), pytest.approx(0.6)]
    assert out["avg_gc_factor_cos_sim_history"] == [
        pytest.approx(0.5), pytest.approx(0.6)]
    assert out["avg_gc_factor_l1_history"] == [
        pytest.approx(1.0), pytest.approx(2.0)]


def test_rank_runs_max_direction(tmp_path):
    s = {
        "a": {"avg_roc_auc_score_history": [0.5, 0.9]},
        "b": {"avg_roc_auc_score_history": [0.7, 0.6]},
    }
    rows = rank_runs(s, "roc_auc")
    assert rows[0] == ("a", pytest.approx(0.9), 1)


def test_dcsfa_artifact_roundtrip(tmp_path):
    import jax
    from redcliff_tpu.models.dcsfa_nmf import FullDCSFAModel

    model = FullDCSFAModel(num_nodes=3, num_high_level_node_features=2,
                           n_components=2, n_sup_networks=1, h=8)
    params, state = model.init(jax.random.PRNGKey(0), model.dim_in)
    run = tmp_path / "DCSFA_run"
    os.makedirs(run)
    with open(run / "dCSFA-NMF-best-model.pkl", "wb") as f:
        pickle.dump(model._artifact_payload(params, state), f)
    loaded_model, loaded_params, loaded_state = load_model_for_eval(str(run))
    assert type(loaded_model).__name__ == "FullDCSFAModel"
    assert loaded_model.num_nodes == 3
    ests = get_model_gc_estimates(loaded_model, loaded_params, "DCSFA", 2)
    assert len(ests) == 2 and ests[0].shape == (3, 3)


def test_fixed_corr_string_replicates():
    from redcliff_tpu.models.dcsfa_nmf import DcsfaNmfConfig

    cfg = DcsfaNmfConfig(n_sup_networks=3, fixed_corr="positive")
    assert cfg.fixed_corr == ("positive", "positive", "positive")
