"""tidybench suite: recovery on a known VAR system + native/numpy SELVAR parity.

The reference has no tests (SURVEY.md §4); synthetic linear VAR data with a
known sparse adjacency is the oracle, per the reference's own correctness
strategy of scoring against generated ground truth.
"""
import numpy as np
import pytest

from redcliff_tpu.tidybench import gtcoef, gtstat, lasar, qrbs, selvar, slarac, slvar
from redcliff_tpu.tidybench.selvar import _gtcoef_np, _slvar_np
from redcliff_tpu.tidybench.native import load_native


def make_var1(T=400, N=4, seed=0):
    """Stable VAR(1) with known sparse structure; returns (data, adjacency)
    where adjacency[i, j] = 1 iff X_i → X_j."""
    rng = np.random.default_rng(seed)
    A = np.zeros((N, N))
    A[0, 1] = 0.8
    # all-positive cross coefficients: LASAR's published variable-selection
    # step keeps only positive lasso coefficients (kept quirk)
    A[1, 2] = 0.7
    A[3, 0] = 0.75
    for i in range(N):
        A[i, i] = 0.4
    X = np.zeros((T, N))
    X[0] = rng.normal(size=N)
    for t in range(1, T):
        X[t] = X[t - 1] @ A + 0.3 * rng.normal(size=N)
    truth = (np.abs(A) > 0).astype(float)
    return X, truth


def offdiag_auc(scores, truth):
    """ROC-AUC over off-diagonal entries."""
    from sklearn.metrics import roc_auc_score

    N = truth.shape[0]
    mask = ~np.eye(N, dtype=bool)
    return roc_auc_score(truth[mask], np.asarray(scores)[mask])


@pytest.fixture(scope="module")
def var_data():
    return make_var1()


def test_slarac_recovers_var_structure(var_data):
    X, truth = var_data
    scores = slarac(X, maxlags=2, n_subsamples=40, rng=0)
    assert scores.shape == truth.shape
    assert offdiag_auc(scores, truth) > 0.9


def test_qrbs_recovers_var_structure(var_data):
    X, truth = var_data
    scores = qrbs(X, lags=2, n_resamples=60, rng=0)
    assert offdiag_auc(scores, truth) > 0.9


def test_lasar_recovers_var_structure(var_data):
    X, truth = var_data
    scores = lasar(X, maxlags=1, n_subsamples=3, cv=3, rng=0)
    assert offdiag_auc(scores, truth) > 0.9


def test_selvar_recovers_var_structure(var_data):
    X, truth = var_data
    scores = selvar(X, maxlags=1)
    assert offdiag_auc(scores, truth) > 0.9


def test_selvar_native_matches_numpy(var_data):
    if load_native() is None:
        pytest.skip("native toolchain unavailable")
    X, _ = var_data
    X = X[:120]
    for ml, bs, mxitr in [(1, -1, -1), (2, -2, -1), (-1, -1, 3)]:
        Bn, An, _ = slvar(X, batchsize=bs, maxlags=ml, mxitr=mxitr,
                          backend="native")
        Bp, Ap, _ = _slvar_np(np.asarray(X, dtype=np.float64), bs, ml, mxitr)
        np.testing.assert_array_equal(An, Ap)
        np.testing.assert_allclose(Bn, Bp, rtol=1e-8, atol=1e-10)


def test_selvar_adaptive_long_lag_parity():
    """Regression: adaptive-mode SLVAR where one target selects a lag larger
    than the final target's converged max-lag. The reference's Fortran GTCOEF
    read out of bounds here; both backends must now raise the coefficient
    stage's lag ceiling from the selected lag matrix and agree exactly."""
    rng = np.random.default_rng(5)
    T, N = 80, 3
    X = np.zeros((T, N))
    X[:6] = rng.normal(size=(6, N))
    for t in range(6, T):
        X[t, 1] = 0.5 * X[t - 1, 1] + 0.3 * rng.normal()
        X[t, 2] = 0.5 * X[t - 1, 2] + 0.3 * rng.normal()
        X[t, 0] = 0.9 * X[t - 6, 1] + 0.2 * rng.normal()
    Bp, Ap, _ = _slvar_np(X, -1, -1, -1)
    assert np.isfinite(Bp).all()
    if load_native() is not None:
        Bn, An, _ = slvar(X, batchsize=-1, maxlags=-1, mxitr=-1,
                          backend="native")
        np.testing.assert_array_equal(An, Ap)
        np.testing.assert_allclose(Bn, Bp, rtol=1e-8, atol=1e-10)
    # gtcoef's default lag ceiling must come from A, not a clamp to 1
    A = np.zeros((N, N), dtype=np.int32)
    A[1, 0] = 6
    B_def = gtcoef(X, A, backend="numpy")
    B_exp = gtcoef(X, A, maxlags=6, backend="numpy")
    np.testing.assert_allclose(B_def, B_exp)


def test_gtcoef_native_matches_numpy(var_data):
    if load_native() is None:
        pytest.skip("native toolchain unavailable")
    X, _ = var_data
    X = X[:100]
    N = X.shape[1]
    rng = np.random.default_rng(1)
    A = rng.integers(0, 3, size=(N, N)).astype(np.int32)
    for job in ("ABS", "SQR", "RAW"):
        Bn = gtcoef(X, A, maxlags=2, batchsize=-2, job=job, backend="native")
        Bp = _gtcoef_np(np.asarray(X, dtype=np.float64), 2, -2, A, job=job)
        np.testing.assert_allclose(Bn, Bp, rtol=1e-8, atol=1e-10)
    Bn = gtcoef(X, A, maxlags=2, batchsize=-2, nrm=1, backend="native")
    Bp = _gtcoef_np(np.asarray(X, dtype=np.float64), 2, -2, A, nrm=1)
    np.testing.assert_allclose(Bn, Bp, rtol=1e-8, atol=1e-10)


def test_gtstat_statistics_flag_true_edges(var_data):
    X, truth = var_data
    _, A, _ = slvar(X, maxlags=1)
    stats, df = gtstat(X, A, maxlags=1, job="LR")
    # removing a true edge must raise RSS → positive LR statistic
    assert stats[0, 1] > 0 and stats[1, 2] > 0
    assert df.shape == (X.shape[1], 2)
    if load_native() is not None:
        Bp, DFp = gtstat(X, A, maxlags=1, job="LR", backend="numpy")
        np.testing.assert_allclose(stats, Bp, rtol=1e-8, atol=1e-10)
        np.testing.assert_array_equal(df, DFp)


def test_pre_post_processing_switches(var_data):
    X, truth = var_data
    raw = slarac(X, maxlags=1, n_subsamples=10, rng=0)
    z = slarac(X, maxlags=1, n_subsamples=10, rng=0, post_zeroonescaling=True)
    assert z.min() == 0.0 and z.max() == 1.0
    e = slarac(X, maxlags=1, n_subsamples=10, rng=0, post_edgeprior=True)
    np.testing.assert_allclose(e.mean(), 1.0)
    s = slarac(X, maxlags=1, n_subsamples=10, rng=0, post_standardise=True)
    np.testing.assert_allclose(s.mean(), 0.0, atol=1e-12)
    # order-preserving transforms
    assert np.array_equal(np.argsort(raw, axis=None), np.argsort(z, axis=None))
