"""Test configuration: force an 8-device virtual CPU mesh before jax initializes.

The reference has no test suite (SURVEY.md §4); this build creates one. Multi-device
sharding paths are exercised on a virtual CPU mesh per jax's
xla_force_host_platform_device_count escape hatch, so no TPU is needed to run tests.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", False)
